"""The communication observatory (ISSUE 14 tentpole).

The paper's distributed core IS communication — the pivot-row
broadcast (main.cpp:1097), the cross-worker row exchange
(main.cpp:1093-1131), and the ring-shifted verification GEMM
(main.cpp:534-641) — yet until this module the observability stack
(spans, journeys, numerics, hwcost, capacity) was blind to the
collective layer.  arXiv:2112.09017's achieved-vs-peak accounting
discipline, already applied to FLOPs in ``obs/hwcost.py``, applies
equally to interconnect bytes.  Three parts:

1. **Analytical collective accounting** — for every distributed engine
   configuration, the per-superstep collective inventory (kind, mesh
   axis, operand shape, dtype) is derived EXACTLY from the layout math
   (``parallel/layout.py`` shard geometry × dtype width): the pivot
   reduction and H broadcast, the pivot-row psum, the row-exchange
   psum (or the swap-free engines' deferred bucketed-ppermute rounds),
   the 2D panel broadcast / swap fix-up / unscramble psums, the
   ring-GEMM / SUMMA residual collectives, and the implicit XLA gather.
   Attached to every distributed execute span, exported as
   ``tpu_jordan_comm_{bytes,messages}_total{phase=,collective=}``, and
   returned on ``SolveResult.comm``.

2. **Collective instrumentation** — ``parallel/compat.py``'s
   psum/pmin/pmax/ppermute shims note every collective the engines
   issue at TRACE time (off = one list-truthiness check per traced
   collective, zero warm-path cost, zero-compile pins intact).  With
   :func:`recording` active, the driver captures the observed multiset
   during each AOT compile and pins ``observed == analytical`` — the
   reconciliation invariant (an engine issuing a collective the model
   does not predict, or vice versa, is a typed mismatch, never a
   silent drift of the accounting from the code).

3. **Measured-vs-projected drift** — distributed execute spans gain
   achieved interconnect GB/s (modeled wire bytes over the measured
   non-compute residue) and a ``comm_vs_projected`` ratio against
   ``benchmarks/comm_model.py``'s comm term for the same topology
   point.  A ratio outside the model's stated accuracy band is a
   ``comm_drift`` flight-recorder event plus a
   ``tpu_jordan_comm_drift_total`` count — judged only where the
   projection claims to describe the hardware (a real TPU backend, or
   an explicit ``set_drift_policy(judge="always")``; on CPU meshes the
   v5e constants are a RANKING stand-in, per tuning/registry.py, and
   the honest ratio is recorded unjudged).  Judged measurements also
   feed the optional registry cost-hook calibration
   (:func:`cost_comm_scale` — ROADMAP item 5's self-pricing loop).

Byte conventions (both derived, both labeled):

  * ``payload_bytes`` — the collective operand's exact size (shape ×
    dtype width): the reconciliation unit, layout-exact.
  * ``wire_bytes`` — the modeled on-link traffic: ring all-reduce of S
    payload bytes over an axis of a devices moves S·(a−1)/a per
    direction (benchmarks/comm_model.py's convention); a single-hop
    ppermute ships its whole buffer once.  The GB/s headline unit.

Operator guide: docs/OBSERVABILITY.md (comm taxonomy + metric table +
drift post-mortem howto).  Gate: ``make comm-demo`` →
``tools/check_comm.py`` (exit 2 = an unaccounted collective or a
silent drift).
"""

from __future__ import annotations

import contextlib
import math
import threading
from collections import Counter
from dataclasses import dataclass, field

from . import metrics as _metrics
from . import recorder as _recorder

#: Phase vocabulary (docs/OBSERVABILITY.md): where in the superstep the
#: bytes move.  ``pivot`` = the scalar pivot reduction + the H
#: broadcast; ``row_bcast`` = the pivot-row psum (the grouped engines'
#: stacked psum — both rows + U rows + the eager t-block fused into one
#: collective — lands here too); ``row_exchange`` = the swap engines'
#: row-t broadcast and the 2D swap fix-up; ``panel_bcast`` = the 2D
#: t-chunk broadcast along "pc" (candidates AND eliminate multipliers —
#: one psum serves both); ``permute`` = the swap-free engines' deferred
#: bucketed-ppermute rounds; ``unscramble`` = the 2D column-swap replay
#: psums; ``residual`` = the ring-GEMM / SUMMA verification;
#: ``gather`` = the XLA-implicit all-gather of a gathered inverse
#: (modeled — not visible to the shims; ``implicit=True``).
PHASES = ("pivot", "row_bcast", "row_exchange", "panel_bcast",
          "permute", "unscramble", "residual", "gather")

_M_BYTES = _metrics.counter(
    "tpu_jordan_comm_bytes_total",
    "analytical per-solve collective payload bytes, by superstep phase "
    "and collective kind (layout-derived; docs/OBSERVABILITY.md)")
_M_MSGS = _metrics.counter(
    "tpu_jordan_comm_messages_total",
    "analytical per-solve collective launches, by superstep phase and "
    "collective kind")
_M_DRIFT = _metrics.counter(
    "tpu_jordan_comm_drift_total",
    "distributed solves whose measured non-compute residue fell "
    "outside the comm model's projected band (judged backends only)")
_M_GBPS = _metrics.gauge(
    "tpu_jordan_comm_achieved_gbps",
    "achieved interconnect GB/s of the last distributed solve per "
    "engine (modeled wire bytes / measured non-compute residue)")


def _itemsize(dtype: str) -> int:
    import numpy as np

    return np.dtype(dtype).itemsize


def _nelems(shape: tuple) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out


@dataclass(frozen=True)
class CollectiveSig:
    """One collective signature of an engine configuration: the exact
    (kind, mesh axis, operand shape, dtype) a traced program issues,
    how many times it appears in ONE trace (``traced``) and how many
    times it launches per solve (``executed`` — fori_loop bodies trace
    once but run Nr times)."""

    phase: str
    kind: str           # psum | pmin | pmax | ppermute | all_gather
    axis: str           # "p" | "pr" | "pc" | "pr,pc"
    axis_size: int      # devices participating
    shape: tuple
    dtype: str
    traced: int
    executed: int
    section: str = "engine"     # engine | residual | gather
    implicit: bool = False      # XLA-inserted, invisible to the shims

    @property
    def payload_bytes(self) -> int:
        """Operand bytes per launch (exact: shape × dtype width)."""
        return _nelems(self.shape) * _itemsize(self.dtype)

    @property
    def wire_bytes(self) -> float:
        """Modeled on-link bytes per launch (module docstring)."""
        s = float(self.payload_bytes)
        a = self.axis_size
        if self.kind == "ppermute":
            return s
        return 0.0 if a <= 1 else s * (a - 1) / a

    def key(self) -> tuple:
        return (self.kind, self.axis, self.shape, self.dtype)

    def to_json(self) -> dict:
        return {
            "phase": self.phase, "kind": self.kind, "axis": self.axis,
            "axis_size": self.axis_size, "shape": list(self.shape),
            "dtype": self.dtype, "traced": self.traced,
            "executed": self.executed, "section": self.section,
            "implicit": self.implicit,
            "payload_bytes": self.payload_bytes,
            "wire_bytes": round(self.wire_bytes, 1),
        }


# ---------------------------------------------------------------------
# Observed side: the trace-time recorder behind the compat shims.
# ---------------------------------------------------------------------


class CollectiveRecorder:
    """Sink for ``parallel/compat.py``'s shims: one (kind, axis, shape,
    dtype) record per collective issued at trace time while the
    recorder is registered."""

    def __init__(self):
        self._lock = threading.Lock()
        self.records: list[tuple] = []

    def note(self, kind: str, axis: str, shape: tuple,
             dtype: str) -> None:
        with self._lock:
            self.records.append((kind, axis, tuple(shape), str(dtype)))

    def counts(self) -> Counter:
        with self._lock:
            return Counter(self.records)

    def __len__(self) -> int:
        with self._lock:
            return len(self.records)


@contextlib.contextmanager
def record_collectives():
    """Register a fresh :class:`CollectiveRecorder` with the compat
    shims for the duration of the block; yields the recorder."""
    from ..parallel import compat as _compat

    rec = CollectiveRecorder()
    _compat.add_collective_recorder(rec)
    try:
        yield rec
    finally:
        _compat.remove_collective_recorder(rec)


_STATE = threading.local()


@contextlib.contextmanager
def recording():
    """Enable driver-integrated observed-count capture for solves
    inside the block: each distributed compile (and the first trace of
    its residual executable) runs under a :class:`CollectiveRecorder`,
    and ``SolveResult.comm`` carries the observed-vs-analytical
    reconciliation.  Off (the default), solves still get the full
    ANALYTICAL report — only the trace-time observation is skipped."""
    prev = getattr(_STATE, "on", False)
    _STATE.on = True
    try:
        yield
    finally:
        _STATE.on = prev


def recording_active() -> bool:
    return bool(getattr(_STATE, "on", False))


# ---------------------------------------------------------------------
# Analytical side: the layout-derived collective inventories.
# ---------------------------------------------------------------------


def _index_dtype() -> str:
    """The dtype jax gives ``jnp.arange``-derived index scalars (the
    pivot reduction's g_piv payloads): int64 under x64, else int32."""
    import jax
    import numpy as np

    return str(jax.dtypes.canonicalize_dtype(np.int64))


class _Builder:
    def __init__(self):
        self.sigs: list[CollectiveSig] = []

    def add(self, phase, kind, axis, axis_size, shape, dtype,
            traced, executed, section="engine", implicit=False):
        self.sigs.append(CollectiveSig(
            phase=phase, kind=kind, axis=axis, axis_size=int(axis_size),
            shape=tuple(int(s) for s in shape), dtype=str(dtype),
            traced=int(traced), executed=int(executed), section=section,
            implicit=implicit))

    def merged(self) -> list[CollectiveSig]:
        """Collapse identical signatures, summing traced/executed."""
        agg: dict[tuple, list] = {}
        order: list[tuple] = []
        for s in self.sigs:
            k = (s.phase, s.kind, s.axis, s.axis_size, s.shape, s.dtype,
                 s.section, s.implicit)
            if k not in agg:
                agg[k] = [0, 0]
                order.append(k)
            agg[k][0] += s.traced
            agg[k][1] += s.executed
        return [CollectiveSig(phase=k[0], kind=k[1], axis=k[2],
                              axis_size=k[3], shape=k[4], dtype=k[5],
                              traced=agg[k][0], executed=agg[k][1],
                              section=k[6], implicit=k[7])
                for k in order]


def _group_schedule(Nr: int, group: int, unroll: bool):
    """(kg, traced_steps, executed_steps) tuples for the grouped
    engines: the unrolled flavor traces every group; the fori flavor
    traces one full-size group body plus the unrolled tail."""
    kgrp = max(1, min(group, Nr))
    if unroll:
        out = []
        t0 = 0
        while t0 < Nr:
            kg = min(kgrp, Nr - t0)
            out.append((kg, kg, kg))
            t0 += kgrp
        return out
    G, tail = divmod(Nr, kgrp)
    out = [(kgrp, kgrp, G * kgrp)] if G else []
    if tail:
        out.append((tail, tail, tail))
    return out


def _sigs_1d(b: _Builder, lay, dtype: str, engine: str, group: int,
             unroll: bool) -> None:
    """The 1D row-cyclic engines (parallel/sharded_inplace.py /
    sharded_jordan.py) — collective inventory per superstep, exactly as
    the step functions issue them (``_step`` / ``_step_fori`` /
    ``_step_swapfree`` / ``_gstep`` / ``_local_step``)."""
    m, N, Nr, p = lay.m, lay.N, lay.Nr, lay.p
    bpw = lay.blocks_per_worker
    i_dt = _index_dtype()
    ax = ("p", p)

    if engine == "swapfree":
        # _step_swapfree (fori-only): 2 pmin + 3 psum per step; the
        # win_pos tie-break key rides the int32 ``pos`` carry.
        b.add("pivot", "pmin", *ax, (), dtype, 1, Nr)
        b.add("pivot", "pmin", *ax, (), "int32", 1, Nr)
        b.add("pivot", "psum", *ax, (), i_dt, 1, Nr)
        b.add("pivot", "psum", *ax, (m, m), dtype, 1, Nr)
        b.add("row_bcast", "psum", *ax, (m, N), dtype, 1, Nr)
        # The deferred permutation: p−1 single-hop ppermute rounds of
        # one padded shard-size bucket (parallel/permute.py).
        if p > 1:
            b.add("permute", "ppermute", *ax, (bpw, m, N), dtype,
                  p - 1, p - 1)
        return
    if engine == "augmented":
        # _local_step (fori-only), (m, 2N) augmented rows.
        b.add("pivot", "pmin", *ax, (), dtype, 1, Nr)
        b.add("pivot", "pmin", *ax, (), i_dt, 1, Nr)
        b.add("pivot", "psum", *ax, (), i_dt, 1, Nr)
        b.add("pivot", "psum", *ax, (m, m), dtype, 1, Nr)
        b.add("row_bcast", "psum", *ax, (m, 2 * N), dtype, 1, Nr)
        b.add("row_exchange", "psum", *ax, (m, 2 * N), dtype, 1, Nr)
        return
    if group > 1:
        # _gstep: the two row psums + H fuse into ONE stacked
        # (2m, N + kg·m + m) psum; tail groups stack narrower.
        for kg, traced, executed in _group_schedule(Nr, group, unroll):
            tr, ex = traced, executed
            b.add("pivot", "pmin", *ax, (), dtype, tr, ex)
            b.add("pivot", "pmin", *ax, (), i_dt, tr, ex)
            b.add("pivot", "psum", *ax, (), i_dt, tr, ex)
            b.add("pivot", "psum", *ax, (m, m), dtype, tr, ex)
            b.add("row_bcast", "psum", *ax,
                  (2 * m, N + kg * m + m), dtype, tr, ex)
        return
    # Plain in-place: _step (unrolled) / _step_fori.
    tr = Nr if unroll else 1
    b.add("pivot", "pmin", *ax, (), dtype, tr, Nr)
    b.add("pivot", "pmin", *ax, (), i_dt, tr, Nr)
    b.add("pivot", "psum", *ax, (), i_dt, tr, Nr)
    b.add("pivot", "psum", *ax, (m, m), dtype, tr, Nr)
    b.add("row_bcast", "psum", *ax, (m, N), dtype, tr, Nr)
    b.add("row_exchange", "psum", *ax, (m, N), dtype, tr, Nr)


def _sigs_2d(b: _Builder, lay, dtype: str, engine: str, group: int,
             unroll: bool) -> None:
    """The 2D block-cyclic engines (parallel/jordan2d_inplace.py /
    jordan2d.py) — per-superstep inventory of ``_step2d`` /
    ``_step2d_fori`` / ``_step2d_swapfree`` / ``_gstep2d`` /
    ``_local_step2d`` plus the column-swap unscramble replay."""
    m, N, Nr = lay.m, lay.N, lay.Nr
    pr, pc, bpr, bc1 = lay.pr, lay.pc, lay.bpr, lay.bc1
    Wc = N // pc
    i_dt = _index_dtype()
    axR = ("pr", pr)
    axC = ("pc", pc)
    axB = ("pr,pc", pr * pc)

    def pivot(tr, ex):
        b.add("pivot", "pmin", *axB, (), dtype, tr, ex)
        b.add("pivot", "pmin", *axB, (),
              "int32" if engine == "swapfree" else i_dt, tr, ex)
        b.add("pivot", "psum", *axB, (), i_dt, tr, ex)
        b.add("pivot", "psum", *axB, (m, m), dtype, tr, ex)

    if engine == "swapfree":
        b.add("panel_bcast", "psum", *axC, (bpr, m, m), dtype, 1, Nr)
        pivot(1, Nr)
        b.add("row_bcast", "psum", *axR, (m, Wc), dtype, 1, Nr)
        # Deferred repairs: column chunks along "pc" alone, rows along
        # "pr" alone (data moves only along the axis that shards it).
        if pc > 1:
            b.add("permute", "ppermute", *axC, (bc1, bpr, m, m), dtype,
                  pc - 1, pc - 1)
        if pr > 1:
            b.add("permute", "ppermute", *axR, (bpr, m, Wc), dtype,
                  pr - 1, pr - 1)
        return
    if engine == "augmented":
        Wc2 = 2 * N // pc
        b.add("panel_bcast", "psum", *axC, (bpr, m, m), dtype, 1, Nr)
        pivot(1, Nr)
        b.add("row_bcast", "psum", *axR, (m, Wc2), dtype, 1, Nr)
        b.add("row_exchange", "psum", *axR, (m, Wc2), dtype, 1, Nr)
        b.add("row_exchange", "psum", *axC, (m, m), dtype, 1, Nr)
        return
    if group > 1:
        for kg, traced, executed in _group_schedule(Nr, group, unroll):
            tr, ex = traced, executed
            b.add("panel_bcast", "psum", *axC, (bpr, m, m), dtype,
                  tr, ex)
            pivot(tr, ex)
            b.add("row_bcast", "psum", *axR,
                  (2 * m, Wc + kg * m + m), dtype, tr, ex)
        # Unscramble replay: 2 one-hot (bpr, m, m) psums along "pc"
        # per step (unrolled traces all Nr; the fori twin traces one).
        utr = Nr if unroll else 1
        b.add("unscramble", "psum", *axC, (bpr, m, m), dtype,
              2 * utr, 2 * Nr)
        return
    tr = Nr if unroll else 1
    b.add("panel_bcast", "psum", *axC, (bpr, m, m), dtype, tr, Nr)
    pivot(tr, Nr)
    b.add("row_bcast", "psum", *axR, (m, Wc), dtype, tr, Nr)
    b.add("row_exchange", "psum", *axR, (m, Wc), dtype, tr, Nr)
    b.add("row_exchange", "psum", *axC, (m, m), dtype, tr, Nr)
    b.add("unscramble", "psum", *axC, (bpr, m, m), dtype,
          2 * tr, 2 * Nr)


def _sigs_solve_1d(b: _Builder, lay, dtype: str, nrhs: int,
                   unroll: bool) -> None:
    """The 1D distributed SOLVE engine (ISSUE 15,
    parallel/sharded_inplace.py::_solve_step): per superstep, the
    pivot reduction + TWO stacked [A_live | X] row psums — the pivot
    row (``row_bcast``) and the swap's row t (``row_exchange``).  The
    unrolled flavor's row shapes SHRINK with t (the statically
    shrinking live-column window — each step traces its own shape);
    the fori flavor broadcasts full width once-traced."""
    m, N, Nr, p = lay.m, lay.N, lay.Nr, lay.p
    i_dt = _index_dtype()
    ax = ("p", p)
    tr = Nr if unroll else 1
    b.add("pivot", "pmin", *ax, (), dtype, tr, Nr)
    b.add("pivot", "pmin", *ax, (), i_dt, tr, Nr)
    b.add("pivot", "psum", *ax, (), i_dt, tr, Nr)
    b.add("pivot", "psum", *ax, (m, m), dtype, tr, Nr)
    if unroll:
        for t in range(Nr):
            shape = (m, N - t * m + nrhs)
            b.add("row_bcast", "psum", *ax, shape, dtype, 1, 1)
            b.add("row_exchange", "psum", *ax, shape, dtype, 1, 1)
    else:
        shape = (m, N + nrhs)
        b.add("row_bcast", "psum", *ax, shape, dtype, 1, Nr)
        b.add("row_exchange", "psum", *ax, shape, dtype, 1, Nr)


def _sigs_solve_2d(b: _Builder, lay, dtype: str, nrhs: int,
                   unroll: bool) -> None:
    """The 2D distributed SOLVE engine
    (parallel/jordan2d_inplace.py::_solve_step_2d): the t-chunk panel
    psum along "pc", the whole-mesh pivot reduction, two stacked
    [A_live | X] row psums along "pr" (live width shrinking statically
    in the unrolled flavor), and the (m, m) swap fix-up psum along
    "pc".  No unscramble — the solve never replays column swaps (A is
    discarded; X alone is the product)."""
    m, N, Nr = lay.m, lay.N, lay.Nr
    pr, pc, bpr, bc1 = lay.pr, lay.pc, lay.bpr, lay.bc1
    Wc = N // pc
    i_dt = _index_dtype()
    axR = ("pr", pr)
    axC = ("pc", pc)
    axB = ("pr,pc", pr * pc)
    tr = Nr if unroll else 1
    b.add("panel_bcast", "psum", *axC, (bpr, m, m), dtype, tr, Nr)
    b.add("pivot", "pmin", *axB, (), dtype, tr, Nr)
    b.add("pivot", "pmin", *axB, (), i_dt, tr, Nr)
    b.add("pivot", "psum", *axB, (), i_dt, tr, Nr)
    b.add("pivot", "psum", *axB, (m, m), dtype, tr, Nr)
    if unroll:
        for t in range(Nr):
            lw = (bc1 - t // pc) * m
            shape = (m, lw + nrhs)
            b.add("row_bcast", "psum", *axR, shape, dtype, 1, 1)
            b.add("row_exchange", "psum", *axR, shape, dtype, 1, 1)
    else:
        shape = (m, Wc + nrhs)
        b.add("row_bcast", "psum", *axR, shape, dtype, 1, Nr)
        b.add("row_exchange", "psum", *axR, shape, dtype, 1, Nr)
    b.add("row_exchange", "psum", *axC, (m, m), dtype, tr, Nr)


def _sigs_gather_solve(b: _Builder, lay, dtype: str, nrhs: int) -> None:
    """The XLA-implicit all-gather assembling X's row blocks: (N, k) —
    present in EITHER gather mode (X is O(n·k); it is assembled for
    the dense verification regardless — linalg/api.py)."""
    N = lay.N
    if hasattr(lay, "pc"):
        axis, a = "pr,pc", lay.pr * lay.pc
    else:
        axis, a = "p", lay.p
    b.add("gather", "all_gather", axis, a, (N, nrhs), dtype, 0, 1,
          section="gather", implicit=True)


#: Engines with a registered collective inventory — the registry lint
#: (tests/test_comm.py) pins every DISTRIBUTED-legal registry config's
#: engine to this set, and :func:`engine_report` refuses unknown names:
#: a new distributed engine without analytical accounting fails loudly
#: at its first report, never silently reconciling against the wrong
#: (or an empty) inventory.
INVENTORY_ENGINES = frozenset(
    {"inplace", "grouped", "swapfree", "augmented", "solve_sharded",
     "lookahead", "solve_lookahead"})


def _sigs_residual(b: _Builder, lay, dtype: str) -> None:
    """The independent verification pass: the 1D systolic ring GEMM
    (parallel/ring_gemm.py, main.cpp:534-641) or the 2D SUMMA
    (parallel/jordan2d.py::_summa_residual_worker)."""
    m, N, Nr = lay.m, lay.N, lay.Nr
    if hasattr(lay, "pc"):
        pr, pc, bpr = lay.pr, lay.pc, lay.bpr
        Wc = N // pc
        b.add("residual", "psum", "pc", pc, (bpr, m, m), dtype, 1, Nr,
              section="residual")
        b.add("residual", "psum", "pr", pr, (m, Wc), dtype, 1, Nr,
              section="residual")
        b.add("residual", "psum", "pc", pc, (bpr, m), dtype, 1, 1,
              section="residual")
        b.add("residual", "pmax", "pr,pc", pr * pc, (), dtype, 1, 1,
              section="residual")
        return
    p = lay.p
    bpw = lay.blocks_per_worker
    # One ppermute in the fori body (traced once, rotated p times) +
    # the scalar pmax that carries the verdict off the mesh.
    b.add("residual", "ppermute", "p", p, (bpw, m, N), dtype, 1, p,
          section="residual")
    b.add("residual", "pmax", "p", p, (), dtype, 1, 1,
          section="residual")


def _sigs_gather(b: _Builder, lay, dtype: str) -> None:
    """The XLA-implicit all-gather behind ``gather=True`` (jnp.take on
    the sharded blocks outside shard_map): modeled, never shim-visible
    (``implicit=True`` keeps it out of the reconciliation multiset)."""
    N = lay.N
    if hasattr(lay, "pc"):
        axis, a = "pr,pc", lay.pr * lay.pc
    else:
        axis, a = "p", lay.p
    b.add("gather", "all_gather", axis, a, (N, N), dtype, 0, 1,
          section="gather", implicit=True)


def engine_report(*, engine: str, lay, dtype, gather: bool = True,
                  refine: int = 0, group: int = 0,
                  unroll: bool | None = None,
                  rhs: int = 0) -> "CommReport":
    """Build the analytical :class:`CommReport` for one distributed
    engine configuration.  ``lay`` is the solve's ``CyclicLayout`` /
    ``CyclicLayout2D``; ``dtype`` the WORKING dtype (the distributed
    core computes in fp32 for sub-fp32 storage); ``unroll=None``
    resolves exactly like the compile front ends (Nr ≤ MAX_UNROLL_NR
    for the in-place/grouped/solve engines; the swap-free and
    augmented engines are fori-only).

    ``refine > 0`` skips the residual section (the refine branch
    verifies on the gathered full matrices — no ring/SUMMA pass), and
    ``gather=True`` adds the implicit all-gather phase.

    ``rhs`` (ISSUE 15) is the solve workload's RHS column count — the
    k riding the stacked row broadcasts of ``engine="solve_sharded"``.
    Solve reports have NO residual section (the verification is dense
    against the caller's own A and B — linalg/api.py) and model the
    implicit X assembly in either gather mode.

    An engine name outside :data:`INVENTORY_ENGINES` is a hard
    ``ValueError``: accounting is part of shipping an engine."""
    import jax.numpy as jnp

    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    if engine not in INVENTORY_ENGINES:
        raise ValueError(
            f"no collective inventory registered for engine "
            f"{engine!r} (obs/comm.INVENTORY_ENGINES); a distributed "
            f"engine ships WITH its analytical accounting — add its "
            f"_sigs_* builder before wiring it anywhere")
    dt = str(jnp.dtype(dtype))
    if engine in ("swapfree", "augmented"):
        unroll = False
    elif unroll is None:
        unroll = lay.Nr <= MAX_UNROLL_NR
    solve = engine in ("solve_sharded", "solve_lookahead")
    b = _Builder()
    two_d = hasattr(lay, "pc")
    if two_d:
        if solve:
            _sigs_solve_2d(b, lay, dt, int(rhs), unroll)
        else:
            _sigs_2d(b, lay, dt, engine, group, unroll)
        mesh = f"{lay.pr}x{lay.pc}"
        workers: object = (lay.pr, lay.pc)
    else:
        if solve:
            _sigs_solve_1d(b, lay, dt, int(rhs), unroll)
        else:
            _sigs_1d(b, lay, dt, engine, group, unroll)
        mesh = f"1D p={lay.p}"
        workers = lay.p
    if solve:
        _sigs_gather_solve(b, lay, dt, int(rhs))
    else:
        if not refine:
            _sigs_residual(b, lay, dt)
        if gather:
            _sigs_gather(b, lay, dt)
    return CommReport(engine=engine, mesh=mesh, workers=workers,
                      n=lay.n, block_size=lay.m, dtype=dt,
                      gather=bool(gather), group=int(group),
                      rhs=int(rhs), sigs=b.merged())


# ---------------------------------------------------------------------
# The report: totals, reconciliation, metrics, span attrs.
# ---------------------------------------------------------------------


@dataclass
class CommReport:
    """One distributed solve's communication record
    (``SolveResult.comm``)."""

    engine: str
    mesh: str
    workers: object
    n: int
    block_size: int
    dtype: str
    gather: bool
    group: int
    rhs: int = 0            # solve-workload RHS columns (0 = invert)
    sigs: list = field(default_factory=list)
    #: observed trace-time records per section ("engine"/"residual"),
    #: None = not captured (recording off, or the executable's trace
    #: was cache-hit — nothing re-traced, nothing to compare).
    observed: dict = field(default_factory=dict)
    #: per-section verdicts: True/False per captured section; overall
    #: ``reconciled`` is False iff any captured section mismatches,
    #: None iff nothing was captured.
    reconciled: bool | None = None
    mismatches: list = field(default_factory=list)
    drift: dict | None = None

    # ---- totals ------------------------------------------------------

    def total_bytes(self, implicit: bool = True) -> int:
        return sum(s.payload_bytes * s.executed for s in self.sigs
                   if implicit or not s.implicit)

    def total_wire_bytes(self, section: str | None = None) -> float:
        return sum(s.wire_bytes * s.executed for s in self.sigs
                   if section is None or s.section == section)

    def total_messages(self) -> int:
        return sum(s.executed for s in self.sigs if not s.implicit)

    def phase_totals(self) -> dict:
        """{(phase, kind): {"bytes": payload, "messages": launches}} —
        the metric export unit."""
        out: dict[tuple, dict] = {}
        for s in self.sigs:
            k = (s.phase, s.kind)
            d = out.setdefault(k, {"bytes": 0, "messages": 0,
                                   "wire_bytes": 0.0})
            d["bytes"] += s.payload_bytes * s.executed
            d["messages"] += 0 if s.implicit else s.executed
            d["wire_bytes"] += s.wire_bytes * s.executed
        return out

    # ---- reconciliation ---------------------------------------------

    def expected_traced(self, section: str) -> Counter:
        """The multiset of (kind, axis, shape, dtype) one fresh trace
        of ``section`` must issue through the compat shims."""
        c: Counter = Counter()
        for s in self.sigs:
            if s.section == section and not s.implicit and s.traced:
                c[s.key()] += s.traced
        return c

    def attach_observed(self, section: str, records) -> None:
        """Record one section's trace-time observations (a list of
        (kind, axis, shape, dtype) tuples from a
        :class:`CollectiveRecorder`); None or an empty capture of a
        section that predicts collectives means the trace was cache-hit
        and the section stays un-judged."""
        if records is None:
            self.observed[section] = None
            return
        recs = [tuple(r) for r in records]
        if not recs and self.expected_traced(section):
            self.observed[section] = None
            return
        self.observed[section] = recs
        self._reconcile()

    def _reconcile(self) -> None:
        self.mismatches = []
        judged = False
        ok = True
        for section, recs in self.observed.items():
            if recs is None:
                continue
            judged = True
            want = self.expected_traced(section)
            got = Counter((str(k), str(a), tuple(sh), str(dt))
                          for k, a, sh, dt in recs)
            for key in sorted(set(want) | set(got)):
                w, g = want.get(key, 0), got.get(key, 0)
                if w != g:
                    ok = False
                    kind, axis, shape, dt = key
                    self.mismatches.append(
                        f"{section}: {kind}@{axis} {list(shape)} {dt}: "
                        f"analytical {w} vs observed {g}")
        self.reconciled = ok if judged else None

    # ---- export ------------------------------------------------------

    def observe_metrics(self, sections: tuple | None = None) -> None:
        """Increment the per-solve comm counters (analytical totals —
        exact layout math, recorded whether or not observation ran).

        ``sections`` restricts the export to the report sections that
        actually ran: the driver's distributed core counts everything
        (its solve always verifies), while ``JordanSolver`` counts
        engine+gather per ``invert`` launch and the residual section
        only when ``residual()`` really runs the ring/SUMMA pass — the
        counters must never report verification traffic that did not
        move."""
        for s in self.sigs:
            if sections is not None and s.section not in sections:
                continue
            nb = s.payload_bytes * s.executed
            if nb:
                _M_BYTES.inc(nb, phase=s.phase, collective=s.kind)
            if s.executed and not s.implicit:
                _M_MSGS.inc(s.executed, phase=s.phase,
                            collective=s.kind)

    def attach_span(self, span) -> None:
        """Comm attrs on a distributed ``execute`` span: total payload
        and modeled wire bytes of the ELIMINATION section (what the
        span's wall actually brackets), plus message count."""
        span.attrs["comm_payload_bytes"] = int(sum(
            s.payload_bytes * s.executed for s in self.sigs
            if s.section == "engine"))
        span.attrs["comm_wire_bytes"] = round(
            self.total_wire_bytes("engine"), 1)
        span.attrs["comm_messages"] = int(sum(
            s.executed for s in self.sigs
            if s.section == "engine" and not s.implicit))

    def to_json(self) -> dict:
        obs = {}
        for section, recs in self.observed.items():
            if recs is None:
                obs[section] = None
                continue
            got = Counter((str(k), str(a), tuple(sh), str(dt))
                          for k, a, sh, dt in recs)
            obs[section] = [
                {"kind": k, "axis": a, "shape": list(sh), "dtype": dt,
                 "count": c}
                for (k, a, sh, dt), c in sorted(got.items())]
        return {
            "engine": self.engine, "mesh": self.mesh,
            "workers": (list(self.workers)
                        if isinstance(self.workers, tuple)
                        else self.workers),
            "n": self.n, "block_size": self.block_size,
            "dtype": self.dtype, "gather": self.gather,
            "group": self.group, "rhs": self.rhs,
            "sigs": [s.to_json() for s in self.sigs],
            "totals": {
                "payload_bytes": self.total_bytes(),
                "explicit_payload_bytes": self.total_bytes(False),
                "wire_bytes": round(self.total_wire_bytes(), 1),
                "messages": self.total_messages(),
            },
            "observed": obs,
            "reconciled": self.reconciled,
            "mismatches": list(self.mismatches),
            "drift": self.drift,
        }


#: The last distributed solve's report (the ``--comm-report`` CLI
#: snapshot source; process-level, like hwcost.WATERMARK).
_LAST_LOCK = threading.Lock()
LAST_REPORT: CommReport | None = None


def set_last_report(report: CommReport) -> None:
    """Record the most recent distributed solve's report (the
    ``--comm-report`` snapshot source; called by the driver)."""
    global LAST_REPORT
    with _LAST_LOCK:
        LAST_REPORT = report


# ---------------------------------------------------------------------
# Measured-vs-projected drift.
# ---------------------------------------------------------------------


@dataclass
class DriftPolicy:
    """When a measured/projected comm ratio becomes a ``comm_drift``
    event.  ``tolerance`` is the model's stated accuracy band (the
    projections are 'WHERE the collectives dominate, not 3-digit
    accuracy' — benchmarks/comm_model.py; a measured TPU calibration
    round can tighten it).  ``judge``:

      * "auto" — judge only where the projection claims to describe
        the hardware (jax backend is a real TPU); elsewhere the ratio
        is recorded as an attr, unjudged (the v5e constants off-chip
        are a cost-RANKING stand-in, tuning/registry.py).
      * "always" / "never" — force (the demo's drift leg uses
        "always" to exercise the event path on a CPU mesh)."""

    tolerance: float = 4.0
    judge: str = "auto"


_DRIFT_LOCK = threading.Lock()
_DRIFT = DriftPolicy()


def drift_policy() -> DriftPolicy:
    with _DRIFT_LOCK:
        return _DRIFT


@contextlib.contextmanager
def set_drift_policy(tolerance: float | None = None,
                     judge: str | None = None):
    """Scoped drift-policy override (context manager)."""
    global _DRIFT
    if judge is not None and judge not in ("auto", "always", "never"):
        raise ValueError(f"judge {judge!r}: auto/always/never")
    with _DRIFT_LOCK:
        prev = _DRIFT
        _DRIFT = DriftPolicy(
            tolerance=(prev.tolerance if tolerance is None
                       else float(tolerance)),
            judge=prev.judge if judge is None else judge)
    try:
        yield
    finally:
        with _DRIFT_LOCK:
            _DRIFT = prev


def _projection(n: int, m: int, workers, engine: str, group: int):
    """comm_model's phase projection for this topology point, with the
    chip the registry's cost hooks would rank it on."""
    import jax

    from ..tuning.registry import comm_model

    _cm = comm_model()
    params = _cm.topology_params()
    backend = jax.default_backend()
    chip_name = params["backend_chip"].get(backend, "v5e")
    chip = params["chips"][chip_name]
    pr, pc = (workers if isinstance(workers, (tuple, list))
              else (workers, 1))
    kw = {}
    if engine == "swapfree":
        kw["swapfree"] = True
    elif group > 1:
        kw["group"] = group
    r = _cm.predict(n, m, int(pr), int(pc), chip, **kw)
    scale = 2.0 if engine == "augmented" else 1.0  # [A|B] doubles bytes
    return {
        "chip": chip_name, "backend": backend,
        "comm_s": scale * r["comm"],
        "compute_s": r["elim"] + r["probe"] + r["glue"],
        "total_s": r["total"],
    }


def observe_drift(report: CommReport, elapsed: float,
                  span=None) -> dict:
    """Compare the measured non-compute residue of one distributed
    execute against the comm model's projected comm term; record the
    achieved GB/s gauge, the span attrs, and — on a judged backend
    with a ratio outside the band — a ``comm_drift`` flight-recorder
    event + counter.  Judged measurements also feed the cost-hook
    calibration (:func:`cost_comm_scale`)."""
    pol = drift_policy()
    proj = _projection(report.n, report.block_size, report.workers,
                       report.engine, report.group)
    residue = max(float(elapsed) - proj["compute_s"], 0.0)
    wire = report.total_wire_bytes("engine")
    gbps = (wire / residue / 1e9) if residue > 0 else None
    ratio = (residue / proj["comm_s"]) if proj["comm_s"] > 0 else None
    judged = (pol.judge == "always"
              or (pol.judge == "auto" and proj["backend"] == "tpu"))
    band = [1.0 / pol.tolerance, pol.tolerance]
    out_of_band = (judged and ratio is not None
                   and not (band[0] <= ratio <= band[1]))
    drift = {
        "elapsed_s": float(elapsed),
        "projected_comm_s": proj["comm_s"],
        "projected_compute_s": proj["compute_s"],
        "residue_s": residue,
        "comm_vs_projected": ratio,
        "band": band,
        "chip": proj["chip"],
        "backend": proj["backend"],
        "judged": judged,
        "out_of_band": out_of_band,
        "achieved_gbps": gbps,
        "wire_bytes": round(wire, 1),
        "event_recorded": False,
    }
    if gbps is not None:
        _M_GBPS.set(gbps, engine=report.engine)
    if span is not None:
        if ratio is not None:
            span.attrs["comm_vs_projected"] = float(f"{ratio:.4g}")
        if gbps is not None:
            span.attrs["comm_achieved_gbps"] = float(f"{gbps:.4g}")
        span.attrs["comm_projection_chip"] = proj["chip"]
        span.attrs["comm_drift_judged"] = judged
    if out_of_band:
        _M_DRIFT.inc(engine=report.engine)
        _recorder.record(
            "comm_drift", engine=report.engine, mesh=report.mesh,
            n=report.n, ratio=float(ratio), band=band,
            chip=proj["chip"], residue_s=residue,
            projected_comm_s=proj["comm_s"])
        drift["event_recorded"] = True
    if judged and ratio is not None and math.isfinite(ratio):
        _record_calibration(ratio)
    report.drift = drift
    return drift


# ---------------------------------------------------------------------
# Cost-hook feedback (ROADMAP item 5: the measured roofline turned
# from a report into a control signal — opt-in, default inert).
# ---------------------------------------------------------------------

_CAL_LOCK = threading.Lock()
_CAL = {"enabled": False, "ratio": None, "samples": 0}
_CAL_ALPHA = 0.25          # EWMA weight of the newest judged solve
_CAL_CLAMP = (0.25, 16.0)  # a calibration can re-price, not erase


def _record_calibration(ratio: float) -> None:
    with _CAL_LOCK:
        r = min(max(float(ratio), _CAL_CLAMP[0]), _CAL_CLAMP[1])
        if _CAL["ratio"] is None:
            _CAL["ratio"] = r
        else:
            _CAL["ratio"] = ((1 - _CAL_ALPHA) * _CAL["ratio"]
                             + _CAL_ALPHA * r)
        _CAL["samples"] += 1


def set_cost_feedback(enabled: bool) -> None:
    """Let judged measured/projected comm ratios scale the registry
    cost hooks' comm term (``tuning/registry.projected_seconds``).
    Default OFF: with it off — or with no judged measurement recorded —
    :func:`cost_comm_scale` is exactly 1.0 and every cost ranking is
    byte-identical to the pre-ISSUE-14 behavior."""
    with _CAL_LOCK:
        _CAL["enabled"] = bool(enabled)


def cost_comm_scale() -> float:
    """The comm-term multiplier for the registry cost hooks: the EWMA
    of judged measured/projected ratios when feedback is enabled, 1.0
    otherwise (see :func:`set_cost_feedback`)."""
    with _CAL_LOCK:
        if not _CAL["enabled"] or _CAL["ratio"] is None:
            return 1.0
        return float(_CAL["ratio"])


def calibration_state() -> dict:
    with _CAL_LOCK:
        return dict(_CAL)


def reset_calibration() -> None:
    """Drop the measured comm calibration and disable feedback (TESTS
    ONLY — production calibration is meant to accumulate)."""
    with _CAL_LOCK:
        _CAL.update({"enabled": False, "ratio": None, "samples": 0})


# ---------------------------------------------------------------------
# The --comm-report snapshot.
# ---------------------------------------------------------------------


def snapshot() -> dict:
    """The process-wide comm snapshot (``--comm-report``): the last
    distributed solve's full report plus the comm counter families."""
    reg = _metrics.REGISTRY.snapshot()
    with _LAST_LOCK:
        last = LAST_REPORT
    return {
        "metric": "comm_report",
        "last_solve": None if last is None else last.to_json(),
        "counters": {name: reg[name] for name in (
            "tpu_jordan_comm_bytes_total",
            "tpu_jordan_comm_messages_total",
            "tpu_jordan_comm_drift_total") if name in reg},
        "calibration": calibration_state(),
    }


def write_report(path: str) -> None:
    import json

    with open(path, "w") as f:
        json.dump(snapshot(), f)


# ---------------------------------------------------------------------
# The acceptance demo (`make comm-demo`, CLI --comm-demo).
# ---------------------------------------------------------------------


def _cpu_env(n_devices: int) -> dict:
    """Force an n-device virtual CPU platform from interpreter start
    (the __graft_entry__/conftest recipe) and make the repo importable
    from the child."""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    repo = _repo_root()
    env["PYTHONPATH"] = (repo + (os.pathsep + env["PYTHONPATH"]
                                 if env.get("PYTHONPATH") else ""))
    return env


def _repo_root() -> str:
    import os

    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _demo_leg(name: str, *, n: int, m: int, workers, engine: str,
              gather: bool, group: int = 0, dtype=None,
              generator: str = "absdiff") -> dict:
    import jax.numpy as jnp

    from ..driver import solve

    with recording():
        res = solve(n, m, workers=workers, engine=engine, group=group,
                    gather=gather, generator=generator,
                    dtype=dtype if dtype is not None else jnp.float32)
    rep = res.comm
    leg = {"name": name, "n": n, "block_size": m,
           "elapsed_s": res.elapsed,
           "rel_residual": res.rel_residual,
           "comm": rep.to_json()}
    return leg


def _solve_demo_leg(name: str, *, n: int, m: int, workers, gather: bool,
                    k: int, dtype, generator: str,
                    engine: str = "solve_sharded") -> dict:
    """One distributed-SOLVE reconciliation leg (ISSUE 15): the sharded
    [A | B] elimination under collective recording — the PR 13 safety
    net extended to the solve engine flavors.  Pinned by engine name
    (never "auto"): the checker's coverage gate names the flavors, and
    an autotuner re-ranking must not silently swap which inventory the
    demo reconciles."""
    import jax.numpy as jnp

    from ..linalg import solve_system
    from ..ops import generate

    dt = jnp.dtype(dtype if dtype is not None else jnp.float32)
    a = generate(generator, (n, n), dt)
    bmat = generate("rand", (n, k), dt, row_offset=n)
    with recording():
        res = solve_system(a, bmat, block_size=m, workers=workers,
                           gather=gather, engine=engine)
    return {"name": name, "n": n, "block_size": m,
            "elapsed_s": res.elapsed,
            "rel_residual": res.rel_residual,
            "comm": res.comm.to_json()}


def comm_demo(n: int = 48, block_size: int = 8, seed: int = 0,
              dtype=None, generator: str = "absdiff") -> dict:
    """The ISSUE 14 acceptance run: four tiny distributed solves —
    1D and 2D meshes, both gather modes, a grouped engine, and a
    RAGGED problem size (n not a multiple of the block size, so the
    identity-padded tail is part of every reconciled inventory) — each
    with collective recording on, reconciling the observed trace-time
    multiset against the layout-derived analytical inventory; then one
    deliberate drift leg (``judge="always"`` with a tight band on this
    CPU-mesh host, where the measured residue is nowhere near a v5e
    ICI projection) proving an out-of-band ratio is a RECORDED
    ``comm_drift`` event, never a silent number.

    Returns the one-line-JSON report ``tools/check_comm.py`` validates
    (exit 2 = an unaccounted collective or a silent drift).  Needs an
    8-device mesh: re-execs itself on a forced virtual CPU platform
    when the current process cannot host one (the dryrun recipe)."""
    import json
    import subprocess
    import sys

    import jax
    import jax.numpy as jnp

    del seed  # the demo fixtures are deterministic generators
    dt = jnp.dtype(dtype if dtype is not None else jnp.float32)
    if dt.kind == "c":
        from ..driver import UsageError

        raise UsageError(
            "--comm-demo reconciles the DISTRIBUTED engines and "
            "complex dtypes run single-device (driver.solve's "
            "contract); use a real dtype")
    try:
        can_inline = len(jax.devices()) >= 8
    except RuntimeError:
        can_inline = False
    if not can_inline:
        x64 = ("jax.config.update('jax_enable_x64', True)\n"
               if dt.itemsize == 8 else "")
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            + x64 +
            "import json\n"
            "from tpu_jordan.obs.comm import comm_demo\n"
            f"print(json.dumps(comm_demo(n={int(n)}, "
            f"block_size={int(block_size)}, dtype={dt.name!r}, "
            f"generator={generator!r})))\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], env=_cpu_env(8),
            cwd=_repo_root(), capture_output=True, text=True,
            timeout=900)
        if proc.returncode != 0:
            raise RuntimeError(
                f"comm_demo subprocess failed (rc={proc.returncode}): "
                f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    m = block_size
    # A ragged point: n chosen so n % m != 0 (the padded identity tail
    # rides through every inventory below).
    n_rag = n - m // 2 if n % m == 0 else n
    mark = _recorder.RECORDER.total
    kw = {"dtype": dt, "generator": generator}
    legs = [
        _demo_leg("1d_p4_inplace_gathered", n=n_rag, m=m, workers=4,
                  engine="inplace", gather=True, **kw),
        _demo_leg("1d_p4_grouped2_gathered", n=n_rag, m=m, workers=4,
                  engine="grouped", gather=True, group=2, **kw),
        _demo_leg("1d_p4_swapfree_sharded", n=n_rag, m=m, workers=4,
                  engine="swapfree", gather=False, **kw),
        # The probe-ahead leg (ISSUE 16): same analytical multiset as
        # the plain 1D engine — the lookahead schedule moves step
        # t+1's condition probe earlier, it never adds or drops a
        # collective — reconciled on the reordered observed trace.
        _demo_leg("1d_p4_lookahead_sharded", n=n_rag, m=m, workers=4,
                  engine="lookahead", gather=False, **kw),
        _demo_leg("2d_2x2_inplace_gathered", n=n_rag, m=m,
                  workers=(2, 2), engine="inplace", gather=True, **kw),
        _demo_leg("2d_2x2_swapfree_sharded", n=n_rag, m=m,
                  workers=(2, 2), engine="swapfree", gather=False,
                  **kw),
        # The distributed-solve legs (ISSUE 15): the [A | B]
        # elimination's own inventory — shrinking stacked-row psums,
        # no residual section — reconciled on both mesh shapes.
        _solve_demo_leg("1d_p4_solve_gathered", n=n_rag, m=m,
                        workers=4, gather=True, k=3, dtype=dt,
                        generator=generator),
        _solve_demo_leg("2d_2x2_solve_sharded", n=n_rag, m=m,
                        workers=(2, 2), gather=False, k=2, dtype=dt,
                        generator=generator),
        # The probe-ahead SOLVE leg (ISSUE 16): same multiset identity
        # as the plain distributed solve — 1 prologue probe + Nr−1
        # carried probes = the base engine's Nr in-loop probes.
        _solve_demo_leg("1d_p4_solve_lookahead_sharded", n=n_rag, m=m,
                        workers=4, gather=False, k=2, dtype=dt,
                        generator=generator, engine="solve_lookahead"),
    ]
    # The deliberate drift leg: judged with a tight band — on this
    # host the measured residue is host-dispatch wall time, orders of
    # magnitude beyond a v5e ICI projection, so the event MUST fire.
    with set_drift_policy(tolerance=1.5, judge="always"):
        drift_leg = _demo_leg("1d_p4_inplace_drift", n=n_rag, m=m,
                              workers=4, engine="inplace", gather=True,
                              **kw)
    blackbox = _recorder.RECORDER.dump(
        events=_recorder.RECORDER.since(mark))
    drift_events = [e for e in blackbox["events"]
                    if e["kind"] == "comm_drift"]
    # The reconciliation legs must judge strictly True (each is a
    # fresh configuration, so its compile traces fresh).  The drift leg
    # repeats leg 1's configuration — its lowering is jax-cache-hit, so
    # its comm sections are legitimately un-judged (None); it must only
    # never judge False.
    unreconciled = [leg["name"] for leg in legs
                    if leg["comm"]["reconciled"] is not True]
    if drift_leg["comm"]["reconciled"] is False:
        unreconciled.append(drift_leg["name"])
    mismatches = [msg for leg in legs + [drift_leg]
                  for msg in leg["comm"]["mismatches"]]
    dr = drift_leg["comm"]["drift"] or {}
    silent_drift = bool(dr.get("judged") and dr.get("out_of_band")
                        and not drift_events)
    reg = _metrics.REGISTRY.snapshot()
    return {
        "metric": "comm_demo",
        "n": n_rag, "block_size": m,
        "dtype": dt.name, "generator": generator,
        "ragged": n_rag % m != 0,
        "legs": legs,
        "drift_leg": drift_leg,
        "drift_events": len(drift_events),
        "comm_drift_total": sum(
            s.get("value", 0) for s in reg.get(
                "tpu_jordan_comm_drift_total", {}).get("series", [])),
        "unreconciled": unreconciled,
        "mismatches": mismatches,
        "silent_comm": bool(unreconciled or mismatches or silent_drift),
        "blackbox": blackbox,
    }
