"""Process-wide capacity ledger — fleet memory accounting (ISSUE 13
tentpole).

The paper's MPI program accounts for every byte it holds: each rank's
block buffers are sized up front from the row-cyclic decomposition
(main.cpp:95-123).  Our serving stack grew the opposite habit — every
``invert(resident=True)`` handle pins 2n² HBM-class bytes with eviction
left to the caller (ROADMAP item 2a), every AOT lane has an XLA
``memory_analysis`` footprint recorded per executable but never rolled
up, and the PR 9 device live-bytes watermark was probed exactly once.
The observability triad (PRs 4, 8, 9) covers time, requests, and
numerics; this module adds the missing axis: WHAT IS RESIDENT, per
byte class, with the same ledger-plus-checker discipline
(arXiv:2112.09017's explicit per-core footprint accounting,
arXiv:2412.14374's placement-aware resource budgeting).

Two kinds of byte class:

  * **metered** — residency with explicit create/evict lifecycles
    registers and releases through :data:`LEDGER`:
    ``handles`` (2n²·dtype per resident :class:`~..serve.handles.
    HandleState`, metered at create/evict/re-create), ``executor_lanes``
    (arg/out/temp HBM from the ``hwcost.executable_cost`` read at
    compile — or the arg+out projection where the backend exposes no
    ``memory_analysis``, labeled ``source=projected``, never silently
    modeled as the real thing), and ``plan_cache`` (the serialized plan
    document).  The reconciliation invariant ``bytes_created ==
    bytes_live + bytes_evicted`` holds PER CLASS by construction —
    ``tools/check_capacity.py`` exits 2 when a report breaks it
    (unmetered residency).
  * **sampled** — residency that churns too fast to meter per event is
    probed at snapshot time: the flight-recorder ring and the device
    allocator's live/peak watermark (re-probed at EVERY capacity/metrics
    snapshot on backends that report it — the ISSUE 13 satellite fixing
    the PR 9 one-shot; a backend reporting no allocator stats stays
    ``available=False`` forever, never zeroed, never modeled).

Accounting becomes actuation through :class:`CapacityBudget`: attached
to a :class:`~..serve.handles.HandleStore` it enforces a resident-bytes
ceiling with a pluggable eviction policy (:func:`lru_policy` over
``last_served``, pinned handles exempt).  Evictions emit journey hops
and flight-recorder events; an admission the budget cannot make room
for is the typed :class:`~..resilience.policy.CapacityExceededError`
at submit — never an OOM mid-launch.

Exported as ``tpu_jordan_capacity_*`` gauges/counters with per-component
labels and high-water marks; ``JordanFleet.stats()`` carries the fleet
rollup, CLI ``--capacity-report PATH`` writes :func:`snapshot`, and
``make capacity-demo`` + ``tools/check_capacity.py`` are the demo gate.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field

from . import metrics as _metrics

_M_LIVE = _metrics.gauge(
    "tpu_jordan_capacity_bytes",
    "live resident bytes per capacity component (handles, "
    "executor_lanes, plan_cache; sampled components export at probe "
    "time)")
_M_HIGH = _metrics.gauge(
    "tpu_jordan_capacity_high_water_bytes",
    "high-water mark of live resident bytes per capacity component")
_M_CREATED = _metrics.counter(
    "tpu_jordan_capacity_bytes_created_total",
    "resident bytes registered per capacity component (the ledger's "
    "create side; created == live + evicted is the reconciliation "
    "invariant check_capacity validates)")
_M_EVICTED = _metrics.counter(
    "tpu_jordan_capacity_bytes_evicted_total",
    "resident bytes released per capacity component (the ledger's "
    "evict side)")
_M_EVICTIONS = _metrics.counter(
    "tpu_jordan_capacity_evictions_total",
    "resident-handle evictions, labeled by cause (budget = the "
    "CapacityBudget's LRU evictor made room; caller = an explicit "
    "lifecycle evict)")
_M_REFUSED = _metrics.counter(
    "tpu_jordan_capacity_exceeded_total",
    "typed CapacityExceededError admission refusals — an over-budget "
    "resident invert the evictor could not make room for (everything "
    "evictable pinned), refused at submit instead of OOMing mid-launch")
_M_PROJECTED = _metrics.gauge(
    "tpu_jordan_capacity_projected_lane_bytes",
    "projected arg+out bytes of a serve lane's AOT signature, recorded "
    "BEFORE compiling (warmup/project_capacity) so operators see what "
    "a bucket costs to open; temps are compiler-known only and appear "
    "in the executor_lanes ledger after compile")


class _Component:
    """One metered byte class: {key: (bytes, detail)} entries plus the
    running created/evicted/high-water counters.  All mutation under
    the owning ledger's lock."""

    def __init__(self):
        self.entries: dict[object, tuple[int, str | None]] = {}
        self.live = 0
        self.created = 0
        self.evicted = 0
        self.high_water = 0


class CapacityLedger:
    """The thread-safe process-wide capacity ledger.  ``register`` /
    ``release`` meter explicit-lifecycle residency; ``register_probe``
    attaches a sampled class (probed at :meth:`snapshot`).  Re-register
    of a live key REPLACES it — the old bytes count as evicted, so the
    reconciliation invariant survives re-creates (a re-inverted handle,
    a re-saved plan cache)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._components: dict[str, _Component] = {}
        self._probes: dict[str, object] = {}

    # ---- metered classes --------------------------------------------

    def register(self, component: str, key, nbytes: int,
                 detail: str | None = None) -> None:
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("nbytes must be >= 0")
        with self._lock:
            comp = self._components.setdefault(component, _Component())
            old = comp.entries.pop(key, None)
            if old is not None:                 # replacement: old bytes
                comp.live -= old[0]             # are evicted, not lost
                comp.evicted += old[0]
            comp.entries[key] = (nbytes, detail)
            comp.live += nbytes
            comp.created += nbytes
            comp.high_water = max(comp.high_water, comp.live)
            live, high = comp.live, comp.high_water
            evicted_delta = old[0] if old is not None else 0
        _M_CREATED.inc(nbytes, component=component)
        if evicted_delta:
            _M_EVICTED.inc(evicted_delta, component=component)
        _M_LIVE.set(live, component=component)
        _M_HIGH.set(high, component=component)

    def release(self, component: str, key) -> int:
        """Release one entry; returns its bytes (0 when unknown — a
        double release is a no-op, never a negative ledger)."""
        with self._lock:
            comp = self._components.get(component)
            if comp is None:
                return 0
            old = comp.entries.pop(key, None)
            if old is None:
                return 0
            comp.live -= old[0]
            comp.evicted += old[0]
            live = comp.live
        _M_EVICTED.inc(old[0], component=component)
        _M_LIVE.set(live, component=component)
        return old[0]

    def live_bytes(self, component: str | None = None) -> int:
        with self._lock:
            if component is not None:
                comp = self._components.get(component)
                return comp.live if comp is not None else 0
            return sum(c.live for c in self._components.values())

    # ---- sampled classes --------------------------------------------

    def register_probe(self, component: str, probe) -> None:
        """Attach a sampled byte class: ``probe()`` returns a dict with
        at least ``{"bytes": int}`` (plus any extras), or None when the
        source reports nothing — reported ``available=False``, never
        zeroed, never modeled."""
        with self._lock:
            self._probes[component] = probe

    # ---- the snapshot ------------------------------------------------

    def snapshot(self) -> dict:
        """The per-component capacity document: metered classes carry
        the full created/live/evicted/high-water reconciliation plus a
        per-detail breakdown; sampled classes are probed NOW (the
        ISSUE 13 satellite: the device watermark re-probes at every
        snapshot on backends that support it)."""
        with self._lock:
            metered = {
                name: {
                    "kind": "metered",
                    "entries": len(c.entries),
                    "bytes_live": c.live,
                    "bytes_created": c.created,
                    "bytes_evicted": c.evicted,
                    "high_water_bytes": c.high_water,
                    "breakdown": _breakdown(c.entries),
                }
                for name, c in sorted(self._components.items())
            }
            probes = dict(self._probes)
        for name, probe in sorted(probes.items()):
            try:
                sampled = probe()
            except Exception:                        # noqa: BLE001
                sampled = None                       # telemetry never raises
            doc = {"kind": "sampled",
                   "available": sampled is not None}
            if sampled is not None:
                doc["bytes_live"] = int(sampled.get("bytes", 0))
                doc.update({k: v for k, v in sampled.items()
                            if k != "bytes"})
                _M_LIVE.set(doc["bytes_live"], component=name)
            metered[name] = doc
        return {
            "components": metered,
            "metered_bytes_live": sum(
                d["bytes_live"] for d in metered.values()
                if d["kind"] == "metered"),
        }

    def reset(self) -> None:
        """Drop every entry and probe (TESTS ONLY — production ledgers
        are monotone for the process's life, like the registry)."""
        with self._lock:
            self._components.clear()
            self._probes.clear()


def _breakdown(entries: dict) -> dict:
    out: dict[str, int] = {}
    for nbytes, detail in entries.values():
        label = detail if detail is not None else "unlabeled"
        out[label] = out.get(label, 0) + nbytes
    return dict(sorted(out.items()))


# ---- the eviction budget (accounting -> actuation) ------------------


def lru_policy(candidates):
    """The default eviction order: least-recently-served first
    (``HandleState.last_served``, stamped at create and on every
    committed update txn)."""
    return sorted(candidates, key=lambda st: st.last_served)


@dataclass
class CapacityBudget:
    """A resident-bytes ceiling for a :class:`~..serve.handles.
    HandleStore` (ISSUE 13): admission of a new resident handle evicts
    least-recently-served unpinned handles until the new state fits;
    when nothing evictable remains, admission is refused with the typed
    :class:`~..resilience.policy.CapacityExceededError` — at submit,
    never an OOM mid-launch.  ``policy`` is pluggable: any callable
    mapping candidate states to an eviction order (default
    :func:`lru_policy`)."""

    max_bytes: int
    policy: object = field(default=lru_policy)

    def __post_init__(self):
        self.max_bytes = int(self.max_bytes)
        if self.max_bytes < 1:
            raise ValueError("CapacityBudget.max_bytes must be >= 1")

    def victims(self, candidates):
        return list(self.policy(candidates))


def record_eviction(handle_id: str, nbytes: int, cause: str,
                    live_bytes: int,
                    budget_bytes: int | None = None) -> None:
    """One eviction's observability fan-out: the cause-labeled counter
    plus a flight-recorder ``capacity_eviction`` event (the budget
    event ``check_capacity`` pairs every budget eviction with —
    a budget eviction without one is the silent-evict class)."""
    from . import recorder as _recorder

    _M_EVICTIONS.inc(cause=cause)
    ev = {"handle_id": handle_id, "nbytes": int(nbytes),
          "cause": cause, "live_bytes": int(live_bytes)}
    if budget_bytes is not None:
        ev["budget_bytes"] = int(budget_bytes)
    _recorder.record("capacity_eviction", **ev)


def record_refusal(requested: int, live_bytes: int, budget_bytes: int,
                   pinned: int) -> None:
    """A typed admission refusal's observability fan-out (counter +
    flight-recorder event) — refusals are answers, and answers leave
    evidence."""
    from . import recorder as _recorder

    _M_REFUSED.inc()
    _recorder.record("capacity_refused", requested=int(requested),
                     live_bytes=int(live_bytes),
                     budget_bytes=int(budget_bytes), pinned=int(pinned))


def record_projection(lane: str, nbytes: int) -> None:
    """One lane's projected arg+out bytes, recorded BEFORE its compile
    (``JordanService.project_capacity`` / ``warmup``)."""
    _M_PROJECTED.set(int(nbytes), lane=str(lane))


# ---- THE process-wide ledger ----------------------------------------

LEDGER = CapacityLedger()


def register(component: str, key, nbytes: int,
             detail: str | None = None) -> None:
    LEDGER.register(component, key, nbytes, detail=detail)


def release(component: str, key) -> int:
    return LEDGER.release(component, key)


def live_bytes(component: str | None = None) -> int:
    return LEDGER.live_bytes(component)


def _recorder_probe() -> dict:
    """The flight-recorder ring's retained bytes (sampled — the ring
    churns per event; serializing it is a snapshot-time cost only)."""
    from . import recorder as _recorder

    evs = _recorder.RECORDER.events()
    return {
        "bytes": sum(len(json.dumps(e, default=str)) for e in evs),
        "events_retained": len(evs),
        "ring_capacity": _recorder.RECORDER.capacity,
    }


def _device_probe() -> dict | None:
    """The device allocator's live/peak watermark through the sticky
    hwcost probe (ISSUE 13 satellite: re-probed at every snapshot on
    backends that report allocator stats; a backend that reported none
    on the FIRST probe stays unavailable forever — absent, not zero)."""
    from . import hwcost as _hwcost

    stats = _hwcost.WATERMARK.sample()
    if stats is None:
        return None
    out = {"bytes": int(stats.get("bytes_in_use", 0))}
    if stats.get("peak_bytes_in_use") is not None:
        out["peak_bytes_in_use"] = int(stats["peak_bytes_in_use"])
    return out


LEDGER.register_probe("flight_recorder", _recorder_probe)
LEDGER.register_probe("device", _device_probe)


def snapshot() -> dict:
    """The process-wide capacity document (CLI ``--capacity-report``,
    ``JordanFleet.stats()['capacity']``)."""
    return LEDGER.snapshot()


def write_report(path: str) -> None:
    """Write :func:`snapshot` as one JSON document."""
    with open(path, "w") as f:
        json.dump(snapshot(), f)


# ---- the acceptance demo --------------------------------------------


def capacity_demo(n: int = 96, block_size: int | None = None,
                  seed: int = 0, dtype=None,
                  budget_handles: int = 2) -> dict:
    """The ``--capacity-demo`` CLI mode's engine (ISSUE 13 acceptance):
    one warmed service under a :class:`CapacityBudget` sized for
    ``budget_handles`` resident handles proves the whole
    accounting-to-actuation chain:

      1. lane bytes are PROJECTED before any compile
         (``project_capacity``), then metered for real at compile;
      2. resident creates fill the budget; an update touches the LRU
         order; the next create evicts the least-recently-served
         handle — the eviction emits a journey hop AND a
         ``capacity_eviction`` budget event;
      3. with every survivor pinned, one more resident invert is the
         typed ``CapacityExceededError`` at submit (zero compiles, the
         invert never launched) — never an OOM mid-launch;
      4. an update against the evicted handle is the typed
         ``UnknownHandleError`` — an eviction is always observable,
         never a silently stale serve;
      5. the ledger reconciles: bytes_created == bytes_live +
         bytes_evicted per metered class, zero compiles and zero
         plan-cache measurements on the whole capacity path after
         warmup (metering is on by default and costs the warm path
         nothing).

    Returns the one-line JSON report ``tools/check_capacity.py``
    validates (exit 2 = unmetered residency / silent eviction)."""
    import time

    import jax.numpy as jnp
    import numpy as np

    from ..resilience.policy import CapacityExceededError
    from ..serve.executors import bucket_for
    from ..serve.handles import (HandleStore, UnknownHandleError,
                                 resident_handle_bytes)
    from ..serve.service import JordanService
    from .metrics import REGISTRY
    from .recorder import RECORDER

    t0 = time.perf_counter()
    dtype = jnp.dtype(jnp.float32 if dtype is None else dtype)
    if budget_handles < 2:
        raise ValueError("capacity_demo needs budget_handles >= 2 "
                         "(the LRU order needs two candidates)")
    bucket = bucket_for(n)
    per = resident_handle_bytes(bucket, dtype)
    budget_bytes = budget_handles * per + per // 2
    store = HandleStore(budget=CapacityBudget(max_bytes=budget_bytes))
    rank = 8
    rng = np.random.default_rng(seed)
    mats = [rng.standard_normal((n, n)).astype(dtype)
            for _ in range(budget_handles + 2)]
    scale = 1.0 / np.sqrt(float(n) * rank)
    u = rng.standard_normal((n, rank)).astype(dtype) * scale
    v = rng.standard_normal((n, rank)).astype(dtype) * scale

    def counters():
        c = REGISTRY.counter
        return {
            "compiles": c("tpu_jordan_compiles_total").total(),
            "measurements":
                c("tpu_jordan_tuner_measurements_total").total(),
            "budget_evictions": _M_EVICTIONS.value(cause="budget"),
            "refusals": _M_REFUSED.total(),
        }

    mark = RECORDER.total
    with JordanService(engine="auto", dtype=dtype, batch_cap=1,
                       max_wait_ms=0.5, block_size=block_size,
                       shared_handles=store) as svc:
        projected = svc.project_capacity(update_shapes=[(n, rank)])
        svc.warmup(update_shapes=[(n, rank)])
        after_warm = counters()
        refs = {}
        for i in range(budget_handles):
            hid = f"h{i + 1}"
            refs[hid] = svc.invert(mats[i], resident=True,
                                   handle_id=hid, timeout=600)
        # Touch h1's LRU stamp: h2 (the other resident) becomes the
        # least-recently-served candidate the next admission evicts.
        svc.update(refs["h1"], u, v, timeout=600)
        over_id = f"h{budget_handles + 1}"
        refs[over_id] = svc.invert(mats[budget_handles], resident=True,
                                   handle_id=over_id, timeout=600)
        alive = store.ids()
        for hid in alive:
            store.pin(hid)
        typed_overflow = None
        try:
            svc.invert(mats[budget_handles + 1], resident=True,
                       handle_id=f"h{budget_handles + 2}", timeout=600)
        except CapacityExceededError as e:
            typed_overflow = type(e).__name__
        update_after_evict = None
        try:
            svc.update(refs["h2"], u, v, timeout=600)
        except UnknownHandleError as e:
            update_after_evict = type(e).__name__
        end = counters()
        budget_snap = store.budget_snapshot()
        handles_snap = store.snapshot()
    blackbox = RECORDER.dump(events=RECORDER.since(mark))
    ledger = snapshot()

    eviction_events = [e for e in blackbox["events"]
                       if e["kind"] == "capacity_eviction"]
    budget_events = [e for e in eviction_events
                     if e.get("cause") == "budget"]
    journey_evicts = [e for e in blackbox["events"]
                      if e["kind"] == "journey"
                      and e.get("event") == "capacity_evict"]
    budget_evictions = int(end["budget_evictions"]
                           - after_warm["budget_evictions"])
    unmetered = [name for name, doc in ledger["components"].items()
                 if doc["kind"] == "metered"
                 and doc["bytes_created"] != (doc["bytes_live"]
                                              + doc["bytes_evicted"])]
    silent_eviction = (budget_evictions != len(budget_events)
                       or len(journey_evicts) < len(budget_events))
    compiles_on_path = int(end["compiles"] - after_warm["compiles"])
    silent_capacity = (
        bool(unmetered) or silent_eviction
        or typed_overflow != "CapacityExceededError"
        or update_after_evict != "UnknownHandleError"
        or "h2" in alive or compiles_on_path != 0)
    return {
        "metric": "capacity_demo",
        "n": n, "bucket_n": bucket, "dtype": dtype.name, "seed": seed,
        "handle_bytes": per,
        "budget_bytes": budget_bytes,
        "budget_handles": budget_handles,
        "projected_lanes": projected,
        "ledger": ledger,
        "budget": budget_snap,
        "handles_alive": alive,
        "handles": handles_snap,
        "evictions": eviction_events,
        "journey_evict_hops": len(journey_evicts),
        "budget_evictions": budget_evictions,
        "typed_overflow": {
            "raised": typed_overflow == "CapacityExceededError",
            "error": typed_overflow,
            "refusals": int(end["refusals"] - after_warm["refusals"]),
        },
        "update_after_evict_typed": update_after_evict,
        "compiles_on_capacity_path": compiles_on_path,
        "measurements": int(end["measurements"]
                            - after_warm["measurements"]),
        "unmetered_components": unmetered,
        "silent_capacity": bool(silent_capacity),
        "blackbox": blackbox,
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
