"""Process-wide metrics registry (ISSUE 4 tentpole part 2).

Before this layer, three subsystems kept private, incompatible counter
state: ``utils/profiling.Scoreboard`` (wall seconds), the tuner's
``measurements`` attribute (test-pinned but not scrapeable), and
``serve/stats.ServeStats`` (per-instance bucket dicts).  Nobody could
answer "how many executables has this process compiled" without knowing
which object to interrogate.  Here: ONE named registry every subsystem
registers into — counters, gauges, and reservoir-backed histograms
(p50/p95/p99 via the bounded most-recent-samples window prototyped in
``serve/stats.py``, now shared) — queryable as a dict (``snapshot``),
as Prometheus text, or inside the one-line JSON report
(``obs/export.py``).

Naming contract: every metric name must match ``NAME_RE``
(``^tpu_jordan_[a-z0-9_]+$``) so the Prometheus namespace stays
consistent; registration raises on violations and a conftest lint
re-checks the live registry after the whole suite ran.  Counters end in
``_total``, timings in ``_seconds`` (convention, not enforced).

Label support is deliberately minimal: pass keyword labels at mutation
time (``inc(1, bucket="512")``); each distinct label set is one series.
``registry.counter(...)`` is idempotent per name (the same object comes
back), so call sites fetch-at-use without import-order coupling; a kind
conflict (counter vs gauge under one name) raises.
"""

from __future__ import annotations

import re
import threading

NAME_RE = re.compile(r"^tpu_jordan_[a-z0-9_]+$")

#: Bounded most-recent-sample window per histogram series (the
#: serve/stats prototype: beyond this the OLDEST samples drop — a
#: long-lived process must not grow without bound; 4096 recent samples
#: keep p99 meaningful at any realistic scale).
MAX_RESERVOIR_SAMPLES = 4096

_PCTS = (50.0, 95.0, 99.0)


def percentiles(samples) -> dict:
    """p50/p95/p99 by the nearest-rank method on a sorted copy — no
    numpy interpolation surprises for tiny k.  Values in the samples'
    own units; missing data reports None (folded here from
    ``serve/stats.py``, which now delegates)."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None}
    s = sorted(samples)
    out = {}
    for p in _PCTS:
        rank = max(0, min(len(s) - 1, int(round(p / 100.0 * len(s))) - 1))
        out[f"p{p:.0f}"] = s[rank]
    return out


class Reservoir:
    """The bounded recent-sample window behind histogram percentiles.
    NOT thread-safe on its own — the owning metric (or ServeStats) holds
    the lock, exactly like ``serve/stats._BucketStats``."""

    def __init__(self, maxlen: int = MAX_RESERVOIR_SAMPLES):
        self.maxlen = int(maxlen)
        self._samples: list[float] = []
        self.count = 0          # lifetime observations (never windowed)
        self.total = 0.0        # lifetime sum (the Prometheus _sum line)

    def add(self, value: float) -> None:
        self._samples.append(float(value))
        del self._samples[:-self.maxlen]
        self.count += 1
        self.total += float(value)

    def extend(self, values) -> None:
        for v in values:
            self.add(v)

    @property
    def samples(self) -> list[float]:
        return list(self._samples)

    def percentiles(self) -> dict:
        return percentiles(self._samples)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Metric:
    """Base: one named metric, many label series.  All mutation under
    the metric's own lock (writers include the serve dispatcher
    thread)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} violates the namespace contract "
                f"{NAME_RE.pattern} (docs/OBSERVABILITY.md)")
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}
        #: last exemplar per series (ISSUE 8): a ``request_id`` sample
        #: attached at mutation time, so an operator going from "the
        #: shed counter moved" to "show me ONE affected request" has a
        #: journey id to pull from the flight recorder.
        self._exemplars: dict[tuple, str] = {}

    def series(self) -> dict:
        """{label_key_tuple: value-or-reservoir} snapshot."""
        with self._lock:
            return dict(self._series)

    def exemplar(self, **labels) -> str | None:
        """The most recent exemplar recorded for the series, or None."""
        with self._lock:
            return self._exemplars.get(_label_key(labels))

    def exemplars(self) -> dict:
        with self._lock:
            return dict(self._exemplars)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, *, exemplar: str | None = None,
            **labels) -> None:
        """``exemplar`` (keyword-only, never a label) attaches a
        request-id sample to the series — the journey layer's
        shed/reroute/retry counters pass the affected request's id so
        a counter movement is traceable to one concrete journey."""
        if value < 0:
            raise ValueError("counters only go up; use a gauge")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(value)
            if exemplar is not None:
                self._exemplars[key] = str(exemplar)

    def total(self) -> float:
        """Sum over every label series (the headline scalar)."""
        with self._lock:
            return float(sum(self._series.values()))


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)


class Histogram(Metric):
    """Reservoir-backed summary: per-series bounded recent samples with
    nearest-rank p50/p95/p99 plus lifetime count/sum — exported in
    Prometheus summary form (quantile-labeled lines + _count/_sum)."""

    kind = "histogram"

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            res = self._series.get(key)
            if res is None:
                res = self._series[key] = Reservoir()
            res.add(value)

    def value(self, **labels) -> float:
        """Lifetime sum of observations for the series (the Prometheus
        ``_sum`` line) — the base implementation would float() the
        Reservoir; use ``percentiles()`` for the distribution."""
        with self._lock:
            res = self._series.get(_label_key(labels))
        return 0.0 if res is None else res.total

    def percentiles(self, **labels) -> dict:
        with self._lock:
            res = self._series.get(_label_key(labels))
        return res.percentiles() if res is not None else percentiles(())


class MetricsRegistry:
    """Named metric store.  ``counter``/``gauge``/``histogram`` are
    idempotent per name — the process-wide instance (``REGISTRY``) is
    what solve, the tuner, and the serving layer all register into, and
    what the exporters scrape."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}

    def _get(self, cls, name: str, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def collect(self) -> list[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """Plain-JSON view: {name: {type, help, series: [{labels, ...}]}}
        — the payload behind the one-line JSON exporter."""
        out = {}
        for m in self.collect():
            series = []
            exemplars = m.exemplars()
            for key, val in m.series().items():
                entry: dict = {"labels": dict(key)}
                if isinstance(val, Reservoir):
                    entry["count"] = val.count
                    entry["sum"] = val.total
                    entry.update(val.percentiles())
                else:
                    entry["value"] = val
                if key in exemplars:
                    entry["exemplar"] = exemplars[key]
                series.append(entry)
            out[m.name] = {"type": m.kind, "help": m.help,
                           "series": series}
        return out

    def reset(self) -> None:
        """Drop every registered metric (TESTS ONLY — a process's
        counters are meant to be monotone for its whole life)."""
        with self._lock:
            self._metrics.clear()


#: THE process-wide registry (ISSUE 4: one queryable surface instead of
#: three private scoreboards).  Library code mutates through this;
#: exporters and the conftest namespace lint read it.
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "") -> Histogram:
    return REGISTRY.histogram(name, help)
