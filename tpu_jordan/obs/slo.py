"""Declarative SLOs with multi-window burn-rate evaluation (ISSUE 8
tentpole part 3).

A fleet that is "correct" (PR 7: zero silent errors) can still be
*failing its users* — shedding 5% of one bucket's traffic, or serving
a p99 that drifted past the objective.  This module makes that a
first-class, checkable artifact:

  * :class:`SLOSpec` — one declarative objective per bucket (or
    fleet-wide): an **availability** target over the journey-derived
    ``tpu_jordan_request_outcome_total`` series, and an optional
    **p99 latency** bound over ``tpu_jordan_request_latency_seconds``
    (submit→terminal: queue + execute + any reroute hops, the number a
    caller actually experiences).
  * :class:`SLOMonitor` — samples timestamped
    :class:`~.metrics.MetricsRegistry` snapshots (counter deltas, never
    absolute values — a long-lived process's lifetime totals are not a
    window) and evaluates **multi-window burn rates**: for an error
    budget ``1 - availability``, the burn rate over a window is
    ``error_rate / budget`` (burn 1.0 = spending exactly the budget).
    An objective *pages* only when BOTH a long and a short window
    exceed the threshold — the standard SRE multi-window AND: the long
    window proves the problem is material, the short window proves it
    is still happening (not a resolved blip).

Windows are configurable because the demo's lifetime is seconds, not
weeks: ``fleet_demo --slo-report`` runs demo-scaled windows; a real
deployment passes production pairs (docs/OBSERVABILITY.md has the
standard table).  ``tools/check_slo.py`` validates a written report
both ways (accept + doctored-reject, the repo's checker discipline).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field

from . import metrics as _metrics

#: Production default: the two classic pairs from the SRE workbook —
#: (long_window_s, short_window_s, burn_threshold).  Page when both
#: windows of a pair burn above the threshold.
DEFAULT_WINDOWS = (
    (3600.0, 300.0, 14.4),     # 1h/5m at 14.4x: 2% of a 30d budget/hour
    (21600.0, 1800.0, 6.0),    # 6h/30m at 6x: 5% of a 30d budget/6h
)


@dataclass(frozen=True)
class SLOSpec:
    """One objective.  ``bucket`` None = fleet-wide (all buckets
    summed); availability in (0, 1); ``p99_latency_ms`` None = no
    latency objective."""

    name: str
    bucket: str | None = None
    availability: float = 0.999
    p99_latency_ms: float | None = None

    def __post_init__(self):
        if not (0.0 < self.availability < 1.0):
            raise ValueError("availability must be in (0, 1) — an SLO "
                             "of 1.0 has zero error budget and every "
                             "burn rate is infinite")

    @property
    def budget(self) -> float:
        return 1.0 - self.availability


def _outcome_counts(snapshot: dict, bucket: str | None) -> tuple[int, int]:
    """(ok, error) from a registry snapshot's request-outcome series,
    summed fleet-wide or filtered to one bucket."""
    ok = err = 0.0
    series = snapshot.get("tpu_jordan_request_outcome_total", {})
    for entry in series.get("series", []):
        labels = entry.get("labels", {})
        if bucket is not None and labels.get("bucket") != bucket:
            continue
        if labels.get("outcome") == "ok":
            ok += entry.get("value", 0.0)
        elif labels.get("outcome") == "error":
            err += entry.get("value", 0.0)
    return int(ok), int(err)


def _latency_p99_ms(snapshot: dict, bucket: str | None) -> float | None:
    """Worst per-bucket p99 (ms) from the request-latency histogram
    (fleet-wide = the max across buckets: an SLO is only as good as
    its worst-served bucket)."""
    series = snapshot.get("tpu_jordan_request_latency_seconds", {})
    worst = None
    for entry in series.get("series", []):
        labels = entry.get("labels", {})
        if bucket is not None and labels.get("bucket") != bucket:
            continue
        p99 = entry.get("p99")
        if p99 is not None:
            p99_ms = float(p99) * 1e3
            worst = p99_ms if worst is None else max(worst, p99_ms)
    return worst


class SLOMonitor:
    """Timestamped snapshot sampler + burn-rate evaluator.

    ``windows`` is a tuple of ``(long_s, short_s, threshold)`` pairs;
    ``clock`` is the obs injectable monotonic callable.  ``sample()``
    appends one (t, snapshot) observation; ``evaluate()`` computes, per
    spec and per window pair, the burn rate of each window (delta
    errors / delta total, over the budget) and the page decision."""

    def __init__(self, specs, registry=None, clock=None,
                 windows=DEFAULT_WINDOWS, max_samples: int = 512):
        self.specs = tuple(specs)
        if not self.specs:
            raise ValueError("at least one SLOSpec is required")
        self.registry = (registry if registry is not None
                         else _metrics.REGISTRY)
        self.clock = clock if clock is not None else time.monotonic
        self.windows = tuple((float(a), float(b), float(c))
                             for a, b, c in windows)
        for long_s, short_s, thr in self.windows:
            if not (long_s > short_s > 0) or thr <= 0:
                raise ValueError(
                    f"bad window ({long_s}, {short_s}, {thr}): need "
                    f"long > short > 0 and threshold > 0")
        self.max_samples = int(max_samples)
        self._samples: list[tuple[float, dict]] = []

    def sample(self) -> float:
        """Take one timestamped registry snapshot; returns its t."""
        t = self.clock()
        self._samples.append((t, self.registry.snapshot()))
        del self._samples[:-self.max_samples]
        return t

    def _window_burn(self, spec: SLOSpec, window_s: float) -> dict:
        """Burn rate over the trailing window: the delta between the
        newest sample and the oldest sample inside (or nearest outside)
        the window.  A window with no traffic burns 0 (no requests =
        no budget spent); a truncated window says so."""
        t_now, snap_now = self._samples[-1]
        t_edge = t_now - window_s
        older = [s for s in self._samples[:-1] if s[0] <= t_edge]
        truncated = not older
        t_then, snap_then = (older[-1] if older else self._samples[0])
        ok0, err0 = _outcome_counts(snap_then, spec.bucket)
        ok1, err1 = _outcome_counts(snap_now, spec.bucket)
        d_ok, d_err = max(0, ok1 - ok0), max(0, err1 - err0)
        total = d_ok + d_err
        error_rate = (d_err / total) if total else 0.0
        burn = error_rate / spec.budget
        return {
            "window_s": window_s,
            "span_s": round(t_now - t_then, 6),
            "truncated": truncated,
            "requests": total,
            "errors": d_err,
            "error_rate": round(error_rate, 6),
            "burn_rate": round(burn, 4),
        }

    def evaluate(self) -> dict:
        """The SLO report (the ``--slo-report`` document): per spec,
        every window pair's burn rates + page decision, the latest p99
        vs the objective, and the overall ``healthy`` verdict."""
        if len(self._samples) < 2:
            self.sample()
        if len(self._samples) < 2:          # pragma: no cover
            raise RuntimeError("need >= 2 samples to evaluate")
        results = []
        for spec in self.specs:
            pairs = []
            paging = False
            for long_s, short_s, thr in self.windows:
                long_b = self._window_burn(spec, long_s)
                short_b = self._window_burn(spec, short_s)
                page = (long_b["burn_rate"] > thr
                        and short_b["burn_rate"] > thr)
                paging = paging or page
                pairs.append({"threshold": thr, "long": long_b,
                              "short": short_b, "page": page})
            p99 = _latency_p99_ms(self._samples[-1][1], spec.bucket)
            p99_ok = (spec.p99_latency_ms is None or p99 is None
                      or (math.isfinite(p99)
                          and p99 <= spec.p99_latency_ms))
            results.append({
                "name": spec.name,
                "bucket": spec.bucket,
                "availability_target": spec.availability,
                "error_budget": round(spec.budget, 6),
                "windows": pairs,
                "p99_ms": None if p99 is None else round(p99, 3),
                "p99_target_ms": spec.p99_latency_ms,
                "p99_ok": p99_ok,
                "paging": paging,
                "healthy": (not paging) and p99_ok,
            })
        return {
            "metric": "slo_report",
            "samples": len(self._samples),
            "window_pairs": [list(w) for w in self.windows],
            "objectives": results,
            "healthy": all(r["healthy"] for r in results),
        }


def bucket_specs(buckets, availability: float = 0.9,
                 p99_latency_ms: float | None = None) -> list[SLOSpec]:
    """One spec per bucket plus the fleet-wide rollup — the fleet
    demo's default objective set."""
    specs = [SLOSpec(name="fleet", bucket=None,
                     availability=availability,
                     p99_latency_ms=p99_latency_ms)]
    specs += [SLOSpec(name=f"bucket_{b}", bucket=str(b),
                      availability=availability,
                      p99_latency_ms=p99_latency_ms)
              for b in sorted(int(b) for b in buckets)]
    return specs
