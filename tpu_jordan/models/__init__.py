from .jordan_solver import JordanSolver

__all__ = ["JordanSolver"]
