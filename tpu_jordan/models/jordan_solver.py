"""JordanSolver: the framework's flagship model — a configured inversion
pipeline (layout + pivoting + verification) reusable across many matrices
of the same shape.

The reference re-runs its whole program per matrix (main.cpp:65-93); here
the compiled executables (single-device or sharded) are cached on the
solver so repeated solves pay zero retrace/compile cost — the "model" is
the compiled computation, the "inference" is one inversion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from ..config import default_block_size
from ..ops import residual_inf_norm


@dataclass
class JordanSolver:
    """Configured blocked Gauss–Jordan inversion.

    Args:
      n: matrix dimension.
      block_size: pivot block size m (default: MXU-friendly for n).
      dtype: working dtype (fp32 on TPU, fp64 on CPU).
      refine: Newton–Schulz steps applied to every solve.
      workers: >1 distributes over a 1D mesh (``parallel.make_mesh``).
      precision: "highest" | "high" | "default" | "mixed" (driver.solve).
    """

    n: int
    block_size: int | None = None
    dtype: Any = jnp.float32
    refine: int = 0
    workers: int = 1
    precision: str = "highest"
    _run: Any = field(default=None, repr=False)
    _lay: Any = field(default=None, repr=False)
    _mesh: Any = field(default=None, repr=False)

    def __post_init__(self):
        from ..ops.refine import PRECISIONS, resolve_precision

        if self.block_size is None:
            self.block_size = default_block_size(self.n)
        # Resolve the precision policy once: "mixed" implies HIGH sweeps
        # and bumps refine to the policy minimum.
        self._sweep_prec, self.refine = resolve_precision(
            PRECISIONS[self.precision], self.refine
        )

    def _compile(self, a):
        if self.workers > 1:
            from ..parallel.sharded_jordan import prepare_sharded_invert

            _, self._lay, self._run = prepare_sharded_invert(
                a, self._get_mesh(), self.block_size,
                precision=self._sweep_prec,
            )
        else:
            from ..driver import single_device_invert

            self._run = single_device_invert(self.n, self.block_size).lower(
                a, block_size=self.block_size, refine=self.refine,
                precision=self._sweep_prec,
            ).compile()

    def _get_mesh(self):
        if self._mesh is None:
            from ..parallel import make_mesh

            self._mesh = make_mesh(self.workers)
        return self._mesh

    def invert(self, a: jnp.ndarray):
        """Invert one (n, n) matrix; returns (inverse, singular)."""
        a = jnp.asarray(a, self.dtype)
        if a.shape != (self.n, self.n):
            raise ValueError(f"expected ({self.n}, {self.n}), got {a.shape}")
        if self._run is None:
            self._compile(a)
        if self.workers > 1:
            from ..ops import newton_schulz
            from ..parallel.sharded_jordan import (
                gather_inverse,
                scatter_augmented,
            )

            blocks = scatter_augmented(a, self._lay, self._mesh)
            out, singular = self._run(blocks)
            inv = gather_inverse(out, self._lay, self.n)
            return newton_schulz(a, inv, self.refine), singular.any()
        return self._run(a)

    def residual(self, a, inv) -> float:
        """Independent ‖A·A⁻¹ − I‖∞ verification."""
        if self.workers > 1:
            from ..parallel import distributed_residual

            return float(distributed_residual(
                jnp.asarray(a, self.dtype), inv, self._get_mesh(),
                min(self.block_size, self.n),
            ))
        return float(residual_inf_norm(jnp.asarray(a, self.dtype), inv))
