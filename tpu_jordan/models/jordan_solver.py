"""JordanSolver: the framework's flagship model — a configured inversion
pipeline (layout + pivoting + verification) reusable across many matrices
of the same shape.

The reference re-runs its whole program per matrix (main.cpp:65-93); here
the compiled executables (single-device or sharded) are cached on the
solver so repeated solves pay zero retrace/compile cost — the "model" is
the compiled computation, the "inference" is one inversion.

Distribution mirrors ``driver.solve`` exactly (same backend adapters):
``workers=p`` runs the 1D row-block-cyclic layout over p devices,
``workers=(pr, pc)`` the 2D block-cyclic layout over a (pr, pc) mesh, and
``gather=False`` keeps the inverse as sharded cyclic blocks (the
memory-scaling mode: nothing n×n ever materializes per device).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from ..config import default_block_size
from ..ops import residual_inf_norm


@dataclass
class JordanSolver:
    """Configured blocked Gauss–Jordan inversion.

    Args:
      n: matrix dimension.
      block_size: pivot block size m (default: MXU-friendly for n).
      dtype: storage dtype; sub-fp32 dtypes compute in fp32 and round once
        at the end (the measured-safe policy, ops/jordan.py).
      refine: Newton–Schulz steps applied to every solve (requires
        ``gather=True`` on distributed meshes — refinement runs on the
        gathered inverse).
      workers: 1 = single device; int p > 1 = 1D row-cyclic mesh
        (``parallel.make_mesh``); tuple (pr, pc) = 2D block-cyclic mesh
        (``parallel.make_mesh_2d``).
      precision: "highest" | "high" | "default" | "mixed" (driver.solve).
      gather: distributed only — False returns the inverse as sharded
        cyclic blocks instead of one gathered n×n array.
      engine/group: elimination engine selection (driver.resolve_engine:
        "auto" | "inplace" | "grouped" | "augmented" | "swapfree"; its
        docstring carries the measured dispatch policy — grouped m=128
        k=2 wins for well-conditioned matrices at n >= 8192; swapfree
        is the distributed pod-scale comm design, legal with either
        gather mode — its deferred row permutation runs as bucketed
        ppermute rounds with per-worker residency capped at one shard).
      tune/plan_cache: ``engine="auto"`` only — the autotuner ladder
        (tuning/tuner.py): consult the ``plan_cache`` JSON (a warm hit
        performs zero measurements), else rank by the registry's cost
        model, and with ``tune=True`` measure the cost-pruned survivors
        and persist the winner.  The resolved pick lands on
        ``self.engine``/``self.group``/``self.plan``.
      telemetry: optional ``obs.spans.Telemetry`` — the select/compile
        steps and every ``invert`` record distinct compile/execute
        spans (repeat solves on the cached executable show zero-compile
        traces).  NOTE: with telemetry attached, ``invert`` adds a
        ``block_until_ready`` so the execute span is an honest wall
        bracket; without it the lazy-return behavior is unchanged.
      policy: optional ``resilience.ResiliencePolicy`` — transient
        compile/execute failures are retried per ``policy.retry``
        (``tpu_jordan_retries_total``); the compile/execute fault
        points (``resilience/faults.py``) fire either way, so chaos
        plans reach the solver model too.  The residual-gate ladder is
        a ``driver.solve``/serve concern (the solver returns raw
        ``(inverse, singular)`` without a residual pass).
    """

    n: int
    block_size: int | None = None
    dtype: Any = jnp.float32
    refine: int = 0
    workers: Any = 1
    precision: str = "highest"
    gather: bool = True
    engine: str = "auto"
    group: int = 0
    tune: bool = False
    plan_cache: str | None = None
    telemetry: Any = None
    policy: Any = None
    plan: Any = field(default=None, repr=False)
    cost: Any = field(default=None, repr=False)  # hwcost.ExecutableCost
    comm: Any = field(default=None, repr=False)  # obs.comm.CommReport
    #   (distributed solvers only, built at compile; ISSUE 14)
    work: Any = field(default=None, repr=False)  # obs.work.WorkReport
    #   (distributed solvers only, built at compile; ISSUE 19):
    #   per-worker useful-FLOP shares, skew, ragged-tail penalty
    _run: Any = field(default=None, repr=False)
    _be: Any = field(default=None, repr=False)

    def __post_init__(self):
        from ..driver import UsageError, resolve_engine
        from ..ops.refine import PRECISIONS, resolve_precision

        if self.block_size is None:
            self.block_size = default_block_size(self.n)
        self.engine, self.group = resolve_engine(self.engine, self.group)
        if (self.tune or self.plan_cache) and self.engine != "auto":
            raise UsageError("tune/plan_cache apply to engine='auto' only "
                             "(an explicit engine leaves nothing to tune)")
        if not self._distributed and not self.gather:
            raise UsageError("gather=False requires a distributed mesh")
        if self._distributed:
            # Shared with driver.solve (flag contract + layout policy
            # can't drift): validate flags BEFORE resolve_precision bumps
            # refine, exactly like solve does.
            from ..driver import check_gather_flags

            check_gather_flags(self.gather, self.refine, self.precision,
                               self.engine)
        if self.engine == "auto":
            # The same autotuner ladder as driver.solve: plan cache ->
            # registry cost ranking -> (tune=True) measured survivors.
            # The resolved pick is pinned on self.engine/group/plan, so
            # the cached executable and the reported configuration can
            # never disagree.
            from ..tuning.tuner import auto_select

            self.engine, self.group, self.plan = auto_select(
                self.n, self.block_size, self.dtype, self.workers,
                self.gather, tune=self.tune, plan_cache=self.plan_cache,
                telemetry=self.telemetry)
        if not self._distributed and self.engine == "swapfree":
            raise UsageError("engine='swapfree' is a distributed engine "
                             "(its win is collective bytes); use workers=p")
        from ..tuning.registry import PALLAS_ENGINES

        if self._distributed and self.engine in PALLAS_ENGINES:
            raise UsageError(
                f"engine={self.engine!r} is a single-device fused-kernel "
                "engine (no sharded variant yet); use engine='grouped' "
                "on distributed meshes")
        if self._distributed:
            from ..driver import make_distributed_backend

            self._be = make_distributed_backend(
                self.workers, self.n, self.block_size, self.engine,
                self.group)
        # Resolve the precision policy once: "mixed" implies HIGH sweeps
        # and bumps refine to the policy minimum.
        self._sweep_prec, self.refine = resolve_precision(
            PRECISIONS[self.precision], self.refine
        )
        self._in_dtype = jnp.dtype(self.dtype)
        # Sub-fp32 storage computes in fp32, rounds once at the end
        # (same policy as driver._solve_distributed_core).
        self._work_dtype = (jnp.float32 if self._in_dtype.itemsize < 4
                            else self._in_dtype)

    @property
    def _distributed(self) -> bool:
        return isinstance(self.workers, tuple) or self.workers > 1

    @property
    def _tel(self):
        from ..obs.spans import NULL

        return self.telemetry if self.telemetry is not None else NULL

    def _compile(self, sample):
        from ..driver import _record_compile
        from ..resilience import faults as _faults

        if self._distributed:
            # The communication observatory (ISSUE 14): the analytical
            # per-phase collective accounting for the cached
            # executable, with observed-vs-analytical reconciliation
            # when obs.comm.recording() wraps the compile — the same
            # record driver solves carry on SolveResult.comm.
            from ..obs import comm as _comm

            self.comm = _comm.engine_report(
                engine=self.engine, lay=self._be.lay,
                dtype=self._work_dtype, gather=self.gather,
                refine=self.refine, group=self.group)
            # The work observatory (ISSUE 19): the per-worker share
            # inventory for the cached executable — built once at
            # compile (host math); launches only stamp span attrs.
            from ..obs import work as _obswork

            self.work = _obswork.engine_report(
                engine=self.engine, lay=self._be.lay,
                dtype=self._work_dtype, group=self.group)

        with self._tel.span("compile", engine=self.engine, n=self.n) as csp:
            def compile_once():
                _faults.fire("compile")
                if self._distributed:
                    from ..obs import comm as _comm

                    if _comm.recording_active():
                        with _comm.record_collectives() as rec:
                            run = self._be.compile(sample,
                                                   self._sweep_prec)
                        self.comm.attach_observed("engine", rec.records)
                        return run
                    return self._be.compile(sample, self._sweep_prec)
                from ..driver import single_device_invert

                return single_device_invert(
                    self.n, self.block_size, self.engine, self.group,
                ).lower(
                    sample, block_size=self.block_size, refine=self.refine,
                    precision=self._sweep_prec,
                ).compile()

            self._run = (self.policy.retry.call(compile_once,
                                                component="solver.compile")
                         if self.policy is not None else compile_once())
        _record_compile(csp, "solver")
        # XLA's own accounting (ISSUE 10 hwcost), read once per
        # compile: ``self.cost`` (an ``obs.hwcost.ExecutableCost``)
        # carries flops/bytes/HBM of the cached executable; execute
        # spans get achieved-vs-analytical attrs off it.
        from ..obs import hwcost as _hwcost

        self.cost = _hwcost.executable_cost(self._run)
        if self.work is not None:
            # The hwcost pin (ISSUE 19): devices × per-device
            # cost_analysis judged against the padded executed model,
            # once per compile.
            self.work.attach_xla(self.cost)

    def _execute(self, arg):
        """One executable launch: with telemetry, an honest blocking
        execute span (obs.spans.timed_blocking); without, the original
        lazy return.  The solver's executables never donate their
        input, so a policy retry re-runs on the same buffer."""
        from ..resilience import faults as _faults

        def run_once():
            _faults.fire("execute")
            if self.telemetry is None:
                return self._run(arg)
            from ..obs import hwcost as _hwcost
            from ..obs.spans import timed_blocking

            out, esp = timed_blocking(self._run, arg,
                                      telemetry=self.telemetry,
                                      name="execute", engine=self.engine)
            _hwcost.attach_execute_cost(
                esp, self.cost if self.cost is not None
                else _hwcost.UNAVAILABLE,
                analytical_flops=2.0 * float(self.n) ** 3)
            if self.comm is not None:
                from ..obs import comm as _comm

                # Per-launch comm accounting + drift, same as the
                # driver's distributed core (ISSUE 14).  The residual
                # section is NOT counted here: the solver's invert()
                # never runs the ring/SUMMA pass — residual() counts
                # it when (and only when) it really executes.
                self.comm.observe_metrics(sections=("engine", "gather"))
                self.comm.attach_span(esp)
                _comm.observe_drift(self.comm, esp.duration, esp)
            if self.work is not None:
                # Per-launch work attrs + gauges (ISSUE 19) — host
                # math only, the zero-compile warm pins stay intact.
                self.work.observe_metrics()
                self.work.attach_span(esp)
            return out

        return (self.policy.retry.call(run_once, component="solver.execute")
                if self.policy is not None else run_once())

    def invert(self, a: jnp.ndarray):
        """Invert one (n, n) matrix; returns (inverse, singular).

        With ``gather=False`` the first element is the *sharded cyclic
        block* representation instead (layout on ``self.layout``).
        """
        a = jnp.asarray(a, self._work_dtype)
        if a.shape != (self.n, self.n):
            raise ValueError(f"expected ({self.n}, {self.n}), got {a.shape}")
        if not self._distributed:
            if self._run is None:
                self._compile(a)
            inv, singular = self._execute(a)
            return inv.astype(self._in_dtype), singular

        W = self._be.scatter_W(a)
        if self._run is None:
            self._compile(W)
        out, singular = self._execute(W)
        singular = singular.any()
        if not self.gather:
            return self._be.inv_blocks(out).astype(self._in_dtype), singular
        inv = self._be.gather(out, self.n)
        if self.refine:
            from ..ops import newton_schulz

            inv = newton_schulz(a, inv, self.refine)
        return inv.astype(self._in_dtype), singular

    def invert_batch(self, stack):
        """Invert a (..., n, n) stack in one vmapped computation
        (ops/batched.py; the north-star batch capability).  Single-device:
        for distributed batches, shard the batch axis over a mesh instead.
        Returns (inverses, singular_flags) shaped like the batch."""
        if self._distributed:
            from ..driver import UsageError

            raise UsageError(
                "invert_batch is single-device; for distributed batches "
                "shard the batch axis over the mesh")
        from ..ops import batched_jordan_invert

        a = jnp.asarray(stack, self._work_dtype)
        if a.shape[-2:] != (self.n, self.n):
            raise ValueError(
                f"expected (..., {self.n}, {self.n}), got {a.shape}")
        inv, sing = batched_jordan_invert(
            a, block_size=self.block_size, precision=self._sweep_prec,
            refine=self.refine,
        )
        return inv.astype(self._in_dtype), sing

    @property
    def layout(self):
        """The cyclic layout of ``gather=False`` inverse blocks."""
        return None if self._be is None else self._be.lay

    def residual(self, a, inv) -> float:
        """Independent ‖A·A⁻¹ − I‖∞ verification.

        ``inv`` is whatever ``invert`` returned: an n×n array
        (``gather=True``, verified with the distributed ring/SUMMA GEMM on
        distributed meshes) or sharded cyclic blocks (``gather=False``,
        verified without materializing anything n×n per device).
        """
        a = jnp.asarray(a, self._work_dtype)
        if not self._distributed:
            return float(residual_inf_norm(a, jnp.asarray(inv, a.dtype)))
        a_blocks = self._be.scatter_a_blocks(a)
        if self.gather:
            inv_blocks = self._be.scatter_a_blocks(
                jnp.asarray(inv, self._work_dtype))
        else:
            inv_blocks = jnp.asarray(inv, self._work_dtype)
        out = float(self._be.residual(a_blocks, inv_blocks))
        if self.comm is not None:
            # The ring/SUMMA verification really ran: count ITS
            # section now (invert() deliberately does not — ISSUE 14).
            self.comm.observe_metrics(sections=("residual",))
        return out
