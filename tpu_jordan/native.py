"""ctypes bindings to the native (C++) helpers in ``native/``.

Importing this module raises ImportError when the shared library has not
been built (``make native``); callers (io.py) fall back to pure Python.
No pybind11 in this image — plain C ABI + ctypes.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_CANDIDATES = [
    os.path.join(_HERE, "_native.so"),
    os.path.join(os.path.dirname(_HERE), "native", "_native.so"),
]

_lib = None
for _path in _CANDIDATES:
    if os.path.exists(_path):
        _lib = ctypes.CDLL(_path)
        break
if _lib is None:
    raise ImportError(
        "native library not built (run `make native`); using Python fallback"
    )

_lib.tj_parse_matrix_text.restype = ctypes.c_long
_lib.tj_parse_matrix_text.argtypes = [
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_double),
    ctypes.c_long,
]
_lib.tj_write_matrix_text.restype = ctypes.c_long
_lib.tj_write_matrix_text.argtypes = [
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_double),
    ctypes.c_long,
    ctypes.c_long,
]
_lib.tj_stream_open.restype = ctypes.c_void_p
_lib.tj_stream_open.argtypes = [ctypes.c_char_p]
_lib.tj_stream_read.restype = ctypes.c_long
_lib.tj_stream_read.argtypes = [
    ctypes.c_void_p,
    ctypes.POINTER(ctypes.c_double),
    ctypes.c_long,
]
_lib.tj_stream_close.restype = None
_lib.tj_stream_close.argtypes = [ctypes.c_void_p]


class MatrixStream:
    """Handle-based streaming parser (tj_stream_*): pull ``count`` doubles
    at a time with O(chunk) native memory — the scatter path's analog of
    the reference's per-block-row fscanf loop (main.cpp:242-276)."""

    def __init__(self, path: str):
        self._h = _lib.tj_stream_open(path.encode())
        if not self._h:
            raise FileNotFoundError(f"cannot open {path}")

    def read(self, count: int) -> np.ndarray:
        out = np.empty(count, dtype=np.float64)
        got = _lib.tj_stream_read(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            count,
        )
        return out[:max(got, 0)]

    def close(self):
        if self._h:
            _lib.tj_stream_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


def parse_matrix_text(path: str, count: int) -> np.ndarray:
    """Parse up to ``count`` doubles from ``path``.

    Raises FileNotFoundError if the file cannot be opened; returns however
    many numbers were parseable (io.py turns a short read into the
    reference's "cannot read" error).
    """
    out = np.empty(count, dtype=np.float64)
    got = _lib.tj_parse_matrix_text(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        count,
    )
    if got < 0:
        raise FileNotFoundError(f"cannot open {path}")
    return out[:got]


def write_matrix_text(path: str, a: np.ndarray) -> None:
    a = np.ascontiguousarray(a, dtype=np.float64)
    rows, cols = a.shape
    got = _lib.tj_write_matrix_text(
        path.encode(), a.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        rows, cols,
    )
    if got < 0:
        raise OSError(f"cannot write {path}")
