"""Augmented-system Gauss–Jordan: X = A⁻¹B with no inverse ever formed
(ISSUE 11 tentpole core).

Every pre-existing path in the repo materializes an explicit A⁻¹ — yet
the paper's own verification pass (the residual ‖A·A⁻¹ − I‖∞,
main.cpp:490-513) is the only consumer that actually *needs* the
inverse.  Most real traffic wants X = A⁻¹B or argmin‖Ax − b‖, which the
augmented working set [A | B] (main.cpp:366-370 with B = I specialized
away) computes directly: run the same condition-pivoted block
elimination until the A half is the identity, and the B half IS the
solution — ~n³·(1 + k/n) FLOPs for k right-hand sides against the
in-place inversion's 2n³, and a better-conditioned answer (the gate
judges the κ-free normwise backward error ‖AX − B‖, never an eps·n·κ∞
inverse bound — resilience/degrade.solve_gate_threshold).

Design, relative to ``ops/jordan.py``:

  * **Unrolled supersteps with a statically shrinking live window.**
    The elimination update at superstep ``t`` only touches columns
    >= t·m of the A half (the normalized pivot row is exactly zero in
    every already-eliminated column), so a Python-level loop slices the
    live columns statically — this is where the half-the-FLOPs saving
    physically lives; a fori_loop with full-width updates would compute
    (and throw away) the dead half.  Unrolled-only, capped at the same
    ``MAX_UNROLL_NR`` as the other unrolled engines.
  * **Pivot-free SPD fast path** (``spd=True``): the caller's
    assume="spd" promise means every diagonal block of every Schur
    complement is invertible (principal submatrices of an SPD matrix
    are PD), so the condition-based probe over all Nr−t candidates —
    the paper's most expensive non-GEMM phase (main.cpp:1026-1074) —
    collapses to ONE diagonal-block inverse per superstep and the row
    exchange disappears.  The probe arithmetic for that one block is
    the same ``batched_block_inverse`` element the pivoting path runs,
    so on inputs where the condition criterion would pick the diagonal
    anyway (e.g. the diagonally dominant ``kms`` fixture) the two paths
    are bit-identical — pinned by tests/test_linalg.py.
  * **Complex dtypes are first-class**: every magnitude comparison
    (probe thresholds, pivot keys) already runs in the real dtype of
    ``|z|`` (ops/block_inverse.py), and the sweeps are dtype-generic —
    complex64/complex128 flow through unchanged.  Sub-fp32 storage
    computes at fp32 and rounds once at the end, the engines' shared
    policy.

Padding follows ops/padding.py: A embeds into [[A, 0], [0, I]] and B's
rows pad with zeros, so X_pad = [[X], [0]] exactly and the returned
``X[:n]`` is bit-independent of the padding.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..config import default_block_size, eps_for
from ..ops.block_inverse import batched_block_inverse
from ..ops.norms import block_inf_norms
from ..ops.padding import pad_with_identity


def _is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


@partial(jax.jit, static_argnames=("block_size", "eps", "precision",
                                   "spd", "collect_stats"))
def block_jordan_solve(
    a: jnp.ndarray,
    b: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    spd: bool = False,
    collect_stats: bool = False,
):
    """Solve A·X = B by blocked Gauss–Jordan on [A | B].

    Args:
      a: (n, n) matrix (real or complex; sub-fp32 storage upcasts).
      b: (n, k) right-hand sides (promoted to ``a.dtype``).
      block_size: pivot block size ``m`` (the reference's argv[2] knob).
      eps: relative singularity threshold; defaults to the dtype's
        (``config.eps_for`` — complex dtypes use their component
        dtype's threshold on |z|).
      precision: matmul precision for the sweeps (HIGHEST default, the
        engines' measured requirement on badly scaled fixtures).
      spd: the caller PROMISES A is symmetric/Hermitian positive
        definite: the condition-based pivot probe and the row exchange
        are skipped (diagonal pivots are always invertible).  On a
        non-SPD matrix this promise is unsound — the per-block
        singularity threshold still catches hard zeros, but a
        badly-pivoted solve can pass it; the residual gate
        (linalg/api.py + a policy) is the safety net.
      collect_stats: the ISSUE 10 instrumented trace, extended to the
        solve engine (ROADMAP 1b remainder): returns
        ``(x, singular, stats)`` with the per-superstep health arrays
        (``ops.jordan_inplace._StepStats`` — chosen pivot block, its
        inverse ∞-norm, candidate spread, singular-candidate count,
        element-growth watermark over [A | X]) stacked into the SAME
        executable; X is bit-identical to the uninstrumented call and
        the pivot sequence equals the invert engine's on a shared
        fixture (tests/test_linalg.py).  The pivoting path only: the
        SPD fast path probes exactly one candidate — no selection to
        trace — and is a typed refusal at the API layer.

    Returns:
      (x, singular): X = A⁻¹B (garbage if singular) and the bool flag —
      the same contract as ``ops.jordan.block_jordan_invert``.
    """
    if collect_stats and spd:
        raise ValueError(
            "collect_stats traces the condition-based pivot probe; the "
            "spd fast path has no probe to trace (linalg/api.py types "
            "this refusal for callers)")
    n = a.shape[-1]
    k = b.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        # Sub-fp32 storage: fp32 compute, ONE final rounding (carrying
        # bf16 elimination state compounds a rounding injection per
        # superstep — measured divergent on the invert engines; the
        # same physics applies here).
        out = block_jordan_solve(
            a.astype(jnp.float32), b.astype(jnp.float32), block_size,
            eps, precision, spd, collect_stats)
        if collect_stats:
            x, singular, stats = out
            return x.astype(in_dtype), singular, stats
        x, singular = out
        return x.astype(in_dtype), singular
    dtype = a.dtype
    b = b.astype(dtype)
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)

    Nr = -(-n // m)
    from ..parallel.sharded_inplace import MAX_UNROLL_NR
    if Nr > MAX_UNROLL_NR:
        # Typed (ISSUE 15): large-Nr solves are LEGAL now — through the
        # fori engine below (solve_system routes engine="auto" there) —
        # so the unrolled engine's refusal must name the remedy instead
        # of reading like a hard ceiling on the workload.
        from ..driver import UsageError

        raise UsageError(
            f"block_jordan_solve is the UNROLLED engine (the live-column "
            f"window shrinks statically — the FLOP-cheap flavor) and "
            f"Nr={Nr} exceeds MAX_UNROLL_NR={MAX_UNROLL_NR}; use "
            f"block_jordan_solve_fori (engine='solve_fori', compile "
            f"cost flat in Nr), a larger block_size, or a distributed "
            f"mesh (solve_system(workers=...))")
    N = Nr * m
    A = pad_with_identity(a, N)
    X = jnp.zeros((N, k), dtype).at[:n].set(b)
    singular = jnp.asarray(False)
    row_blocks = jnp.arange(N) // m
    if collect_stats:
        from ..ops.jordan_inplace import _StepStats

        stats = _StepStats()
    else:
        stats = None

    for t in range(Nr):
        lo = t * m
        # --- PIVOT: probe the live candidates of column block t (all
        # of them for the general path; exactly the diagonal one under
        # the SPD promise — the same batched element either way, which
        # is what makes the two paths bit-comparable when the
        # condition criterion would pick the diagonal anyway).
        cands = A[lo:, lo:lo + m].reshape(Nr - t, m, m)
        if spd:
            invs, sing = batched_block_inverse(cands[:1], None, eps)
            singular = singular | sing[0]
            H = invs[0]
            rows_p_A = A[lo:lo + m, lo:]                  # (m, N - lo)
            rows_p_X = X[lo:lo + m]
        else:
            invs, sing = batched_block_inverse(cands, None, eps)
            inv_norms = block_inf_norms(invs)             # real dtype
            valid = ~sing
            key = jnp.where(valid, inv_norms,
                            jnp.asarray(jnp.inf, inv_norms.dtype))
            rel = jnp.argmin(key)                         # window-local
            singular = singular | ~jnp.any(valid)
            H = jnp.take(invs, rel, axis=0).astype(dtype)
            if stats is not None:
                # The same probe evidence the instrumented INVERT
                # engine records (ops/jordan_inplace._StepStats):
                # chosen block id (absolute), the criterion value, the
                # candidate spread, the probe's singular count — the
                # pivot sequence is pinned equal to the invert
                # engine's on shared fixtures (tests/test_linalg.py).
                stats.probe(t + rel, key, sing)
            piv_row = lo + rel * m                        # dynamic
            # Swap-by-copy (main.cpp:1093-1131): lift slot t, write it
            # into the pivot slot; slot t is rewritten from the
            # normalized copy below.  Columns < lo of A are unit and
            # identical across live rows' history — only live columns
            # (and X) need the exchange.
            rows_t_A = A[lo:lo + m, lo:]
            rows_t_X = X[lo:lo + m]
            rows_p_A = lax.dynamic_slice(A, (piv_row, lo), (m, N - lo))
            rows_p_X = lax.dynamic_slice(X, (piv_row, 0), (m, k))
            A = lax.dynamic_update_slice(A, rows_t_A, (piv_row, lo))
            X = lax.dynamic_update_slice(X, rows_t_X, (piv_row, 0))

        # --- NORMALIZE the pivot row: prow = H @ row, live columns +
        # the RHS block only (main.cpp:1133-1159).
        prow_A = jnp.matmul(H, rows_p_A, precision=precision)
        prow_X = jnp.matmul(H, rows_p_X, precision=precision)

        # --- ELIMINATE: one (N, m) x (m, live + k) MXU matmul pair
        # (main.cpp:1165-1193) over the statically-live columns — the
        # already-eliminated columns are provably untouched (prow is
        # zero there), so they are simply not computed.
        E = A[:, lo:lo + m]
        E = jnp.where((row_blocks == t)[:, None],
                      jnp.asarray(0, dtype), E)
        A = A.at[:, lo:].add(-jnp.matmul(E, prow_A, precision=precision))
        X = X - jnp.matmul(E, prow_X, precision=precision)
        A = A.at[lo:lo + m, lo:].set(prow_A)
        X = X.at[lo:lo + m].set(prow_X)
        if stats is not None:
            # Element growth over the LIVE working set [A_live | X] —
            # the augmented analogue of the invert trace's max|V|
            # watermark (eliminated A columns are dead by
            # construction: they are simply not computed).
            stats.sample_growth(A[:, lo:], X)

    if stats is not None:
        return X[:n], singular, stats.stacked()
    return X[:n], singular


@partial(jax.jit, static_argnames=("block_size", "eps", "precision",
                                   "spd"))
def block_jordan_solve_fori(
    a: jnp.ndarray,
    b: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    spd: bool = False,
):
    """The fori-compiled solve engine (ISSUE 15): ``lax.fori_loop``
    supersteps with traced offsets, so compile cost is flat in Nr and
    ``Nr > MAX_UNROLL_NR`` becomes legal — the window shrink moves from
    Python unrolling to masked/dynamic-slice indexing, the same trick
    the invert fori engines use.

    The price is honest and documented: with a traced ``t`` the
    elimination cannot slice a shrinking static width, so updates run
    full-width (~2n³ + 2n²k FLOPs vs the unrolled engine's
    n³(1 + 2k/n)) — the dead columns receive EXACT zeros (the pivot
    row is exactly zero there), which is also why X is BIT-IDENTICAL
    to the unrolled engine on nonsingular inputs (pinned by
    tests/test_linalg.py).  The probe masks dead candidates instead of
    slicing them away (``batched_block_inverse`` is per-candidate
    independent, so probing a dead block never changes a live one's
    arithmetic) — dtype-generic, so complex64/complex128 flow through
    exactly like the unrolled engine.  ``spd=True`` probes only the
    diagonal block, same promise semantics as the unrolled path.

    Same ``(x, singular)`` contract as :func:`block_jordan_solve`; no
    ``collect_stats`` twin (the per-superstep trace instruments the
    unrolled engines only — linalg/api.py types that refusal)."""
    n = a.shape[-1]
    k = b.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        x, singular = block_jordan_solve_fori(
            a.astype(jnp.float32), b.astype(jnp.float32), block_size,
            eps, precision, spd)
        return x.astype(in_dtype), singular
    dtype = a.dtype
    b = b.astype(dtype)
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)

    Nr = -(-n // m)
    N = Nr * m
    A0 = pad_with_identity(a, N)
    X0 = jnp.zeros((N, k), dtype).at[:n].set(b)

    def body(t, carry):
        A, X, singular = carry
        return _solve_fori_step(t, A, X, singular, Nr=Nr, m=m, k=k,
                                eps=eps, precision=precision, spd=spd)

    _, X, singular = lax.fori_loop(0, Nr, body,
                                   (A0, X0, jnp.asarray(False)))
    return X[:n], singular


def _solve_fori_step(t, A, X, singular, *, Nr: int, m: int, k: int,
                     eps, precision, spd: bool):
    """One traced-``t`` solve super-step on the full (N, N) + (N, k)
    working set — the fori_loop body of :func:`block_jordan_solve_fori`,
    factored to module level VERBATIM (same ops, same bits) so the
    checkpointed segment runner (ISSUE 20, resilience/checkpoint.py)
    re-enters the SAME arithmetic at an arbitrary step."""
    N = Nr * m
    dtype = A.dtype
    row_blocks = jnp.arange(N) // m
    blk = jnp.arange(Nr)
    tt = jnp.asarray(t, jnp.int32)
    z = jnp.int32(0)
    lo = tt * m
    if spd:
        C = lax.dynamic_slice(A, (lo, lo), (m, m))
        invs, sing = batched_block_inverse(C[None], None, eps)
        singular = singular | sing[0]
        H = invs[0]
        rows_p_A = lax.dynamic_slice(A, (lo, z), (m, N))
        rows_p_X = lax.dynamic_slice(X, (lo, z), (m, k))
    else:
        cands = lax.dynamic_slice(A, (z, lo), (N, m)).reshape(
            Nr, m, m)
        invs, sing = batched_block_inverse(cands, None, eps)
        inv_norms = block_inf_norms(invs)
        valid = (blk >= tt) & ~sing
        key = jnp.where(valid, inv_norms,
                        jnp.asarray(jnp.inf, inv_norms.dtype))
        rel = jnp.asarray(jnp.argmin(key), jnp.int32)  # ABSOLUTE
        singular = singular | ~jnp.any(valid)
        H = jnp.take(invs, rel, axis=0).astype(dtype)
        piv_row = rel * m
        rows_t_A = lax.dynamic_slice(A, (lo, z), (m, N))
        rows_t_X = lax.dynamic_slice(X, (lo, z), (m, k))
        rows_p_A = lax.dynamic_slice(A, (piv_row, z), (m, N))
        rows_p_X = lax.dynamic_slice(X, (piv_row, z), (m, k))
        A = lax.dynamic_update_slice(A, rows_t_A, (piv_row, z))
        X = lax.dynamic_update_slice(X, rows_t_X, (piv_row, z))

    prow_A = jnp.matmul(H, rows_p_A, precision=precision)
    prow_X = jnp.matmul(H, rows_p_X, precision=precision)

    E = lax.dynamic_slice(A, (z, lo), (N, m))
    E = jnp.where((row_blocks == tt)[:, None],
                  jnp.asarray(0, dtype), E)
    A = A - jnp.matmul(E, prow_A, precision=precision)
    X = X - jnp.matmul(E, prow_X, precision=precision)
    A = lax.dynamic_update_slice(A, prow_A, (lo, z))
    X = lax.dynamic_update_slice(X, prow_X, (lo, z))
    return A, X, singular


# ---------------------------------------------------------------------
# Checkpointed segment executables (ISSUE 20).  A checkpointed solve
# runs supersteps [t0, t1) as ONE jitted executable per segment, with
# the (A, X, singular) working set round-tripped to host between
# segments (byte-exact — np.asarray of f32/f64 is lossless).  Each
# segment runs the SAME per-step arithmetic as the monolithic engines
# above, so the concatenation of segments bit-matches the
# uninterrupted run (pinned by tests/test_checkpoint.py) — the
# reordered-arithmetic discipline of the ISSUE 16 lookahead pin.
# ---------------------------------------------------------------------


@partial(jax.jit, static_argnames=("t0", "t1", "Nr", "m", "k", "eps",
                                   "precision"))
def solve_segment(A, X, singular, *, t0: int, t1: int, Nr: int, m: int,
                  k: int, eps, precision=lax.Precision.HIGHEST):
    """Supersteps [t0, t1) of the UNROLLED solve on the identity-padded
    (N, N) + zero-padded (N, k) working set: the exact loop body of
    :func:`block_jordan_solve` (static shrinking live-column window),
    restricted to a static step range.  Pivoting path only — the SPD
    fast path is a typed checkpoint refusal (resilience/checkpoint.py:
    no probe means no pivot record to snapshot, and the promise-based
    contract has no singularity evidence to carry across a resume)."""
    N = Nr * m
    dtype = A.dtype
    row_blocks = jnp.arange(N) // m
    for t in range(t0, t1):
        lo = t * m
        cands = A[lo:, lo:lo + m].reshape(Nr - t, m, m)
        invs, sing = batched_block_inverse(cands, None, eps)
        inv_norms = block_inf_norms(invs)
        valid = ~sing
        key = jnp.where(valid, inv_norms,
                        jnp.asarray(jnp.inf, inv_norms.dtype))
        rel = jnp.argmin(key)
        singular = singular | ~jnp.any(valid)
        H = jnp.take(invs, rel, axis=0).astype(dtype)
        piv_row = lo + rel * m
        rows_t_A = A[lo:lo + m, lo:]
        rows_t_X = X[lo:lo + m]
        rows_p_A = lax.dynamic_slice(A, (piv_row, lo), (m, N - lo))
        rows_p_X = lax.dynamic_slice(X, (piv_row, 0), (m, k))
        A = lax.dynamic_update_slice(A, rows_t_A, (piv_row, lo))
        X = lax.dynamic_update_slice(X, rows_t_X, (piv_row, 0))
        prow_A = jnp.matmul(H, rows_p_A, precision=precision)
        prow_X = jnp.matmul(H, rows_p_X, precision=precision)
        E = A[:, lo:lo + m]
        E = jnp.where((row_blocks == t)[:, None],
                      jnp.asarray(0, dtype), E)
        A = A.at[:, lo:].add(-jnp.matmul(E, prow_A, precision=precision))
        X = X - jnp.matmul(E, prow_X, precision=precision)
        A = A.at[lo:lo + m, lo:].set(prow_A)
        X = X.at[lo:lo + m].set(prow_X)
    return A, X, singular


@partial(jax.jit, static_argnames=("t0", "t1", "Nr", "m", "k", "eps",
                                   "precision"))
def solve_segment_fori(A, X, singular, *, t0: int, t1: int, Nr: int,
                       m: int, k: int, eps,
                       precision=lax.Precision.HIGHEST):
    """Supersteps [t0, t1) of the fori solve engine: a ``fori_loop``
    over the shared :func:`_solve_fori_step` body — the same executable
    shape for every segment length, the same bits as the monolithic
    fori engine's steps."""
    def body(t, carry):
        A, X, singular = carry
        return _solve_fori_step(t, A, X, singular, Nr=Nr, m=m, k=k,
                                eps=eps, precision=precision, spd=False)

    return lax.fori_loop(t0, t1, body, (A, X, singular))


def solve_batch_metrics(a, x, b, n_real=None,
                        precision=lax.Precision.HIGHEST):
    """Per-element accuracy assembly for BATCHED solves — the solve
    twin of ``driver.batch_metrics`` (ISSUE 11): one shared
    implementation for the serve executors, the bench rows, and tests.

    ``a`` (B, N, N), ``x``/``b`` (B, N, K) stacks; returns (B,) arrays:
    ``residual`` = ‖A·X − B‖∞, the backing norms, the κ-free normwise
    backward error ``rel_residual`` = residual / (‖A‖∞‖X‖∞ + ‖B‖∞)
    (resilience/degrade.solve_gate_threshold is its gate), and
    ``kappa_est`` = ‖A‖∞‖X‖∞/‖B‖∞ — a LOWER-BOUND estimate of κ∞(A)
    (‖X‖ <= ‖A⁻¹‖‖B‖), the conditioning context without ever forming
    A⁻¹.

    ``n_real`` masks to each element's real rows under identity
    padding; pad rows of A·X − B are exactly zero (X and B pad rows are
    zero and A's pad block is [[0],[I]]), so the residual needs no mask
    — the norms do (pad rows of A abs-sum to 1)."""
    r = jnp.matmul(a, x, precision=precision) - b
    r_sums = jnp.sum(jnp.abs(r), axis=-1)
    a_sums = jnp.sum(jnp.abs(a), axis=-1)
    x_sums = jnp.sum(jnp.abs(x), axis=-1)
    b_sums = jnp.sum(jnp.abs(b), axis=-1)
    if n_real is not None:
        N = a.shape[-1]
        mask = (jnp.arange(N)[None, :]
                < jnp.asarray(n_real, jnp.int32)[:, None])
        zero = jnp.asarray(0, r_sums.dtype)
        r_sums = jnp.where(mask, r_sums, zero)
        a_sums = jnp.where(mask, a_sums, zero)
        x_sums = jnp.where(mask, x_sums, zero)
        b_sums = jnp.where(mask, b_sums, zero)
    residual = jnp.max(r_sums, axis=-1)
    norm_a = jnp.max(a_sums, axis=-1)
    norm_x = jnp.max(x_sums, axis=-1)
    norm_b = jnp.max(b_sums, axis=-1)
    denom = norm_a * norm_x + norm_b
    one = jnp.asarray(1, denom.dtype)
    return {
        "residual": residual,
        "norm_a": norm_a,
        "norm_x": norm_x,
        "norm_b": norm_b,
        # Guarded divisions: an all-masked filler element (n_real=0)
        # must report 0, never NaN.
        "rel_residual": jnp.where(denom > 0,
                                  residual / jnp.where(denom > 0, denom,
                                                       one),
                                  residual),
        "kappa_est": jnp.where(norm_b > 0,
                               norm_a * norm_x
                               / jnp.where(norm_b > 0, norm_b, one),
                               norm_a * norm_x),
    }
