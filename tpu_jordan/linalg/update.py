"""Sherman–Morrison–Woodbury rank-k inverse updates (ISSUE 12
tentpole core).

Every path in the repo so far pays the full O(n³) elimination for every
matrix — even when the caller's A differs from one it just inverted by
a handful of rows, exactly the shape of MPAX-style LP/QP inner loops
(arXiv:2412.09734) that re-factorize lightly-perturbed systems
thousands of times.  This module is the O(n²k) alternative: given a
resident A⁻¹, a rank-k mutation A ← A + U·Vᵀ updates the inverse by
the Sherman–Morrison–Woodbury identity

    (A + U·Vᵀ)⁻¹ = A⁻¹ − A⁻¹U · (I + VᵀA⁻¹U)⁻¹ · VᵀA⁻¹

at ~4n²k + O(nk²) FLOPs (``obs/hwcost.baseline_workload_flops``'s
``update`` convention) instead of a fresh ~(8/3)n³ elimination.  The
k×k *capacitance* system I + VᵀA⁻¹U is solved through the repo's own
``block_jordan_solve`` — its singular flag IS the mutated matrix's
singularity signal (det(A+UVᵀ) = det(A)·det(I+VᵀA⁻¹U)), typed out,
never garbage.  Complex dtypes use the PLAIN transpose throughout (the
identity as written — a Hermitian update is the caller's U = conj(V)
choice, not this module's).

Verification discipline (the PR 5 gate, re-applied to updates): the
serve-shaped kernel :func:`smw_update_with_metrics` mutates A, updates
the inverse, AND re-verifies ‖A_new·X_new − I‖∞ against the *mutated*
matrix in the SAME launch — the one consumer of the O(n³) residual
matmul, which keeps the whole executable's ``cost_analysis`` FLOPs
strictly below a same-n fresh-invert executable's for k ≤ n/8 (pinned
by tests/test_update.py) while the gate stays exactly as honest as the
invert path's.  Per-update residuals ACCUMULATE into a drift budget
(:func:`drift_budget`): m small updates each individually inside the
gate can still sum past ``DRIFT_BUDGET_FACTOR`` gate-widths, at which
point the "re_invert" degradation rung fires — a fresh elimination of
the mutated matrix, drift reset to zero — typed, never a silently
stale inverse (docs/WORKLOADS.md).

Zero-pad bucketing is exact, like every serve lane: zero columns of
U/V contribute nothing to U·Vᵀ, make the capacitance block-diagonal
[[S, 0], [0, I]], and drop out of the correction product — the
bucketed update returns bit-identically the top-left n×n of the padded
result.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import hwcost as _hwcost
from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from ..obs.spans import NULL as _NULL_TEL
from ..obs.spans import timed_blocking
from ..resilience import faults as _faults
from .engine import block_jordan_solve

#: How many gate-widths of ACCUMULATED per-update drift a resident
#: inverse may carry before the "re_invert" rung fires even though the
#: latest update individually passed the gate (docs/WORKLOADS.md: the
#: documented drift budget is ``DRIFT_BUDGET_FACTOR ×
#: gate_threshold``).  Each SMW application composes its own rounding
#: error onto the resident state; the budget bounds the composition,
#: not just the last step.
DRIFT_BUDGET_FACTOR = 4.0


def drift_budget(threshold: float, factor: float | None = None) -> float:
    """The accumulated-drift ceiling for one resident handle:
    ``DRIFT_BUDGET_FACTOR`` × the per-update residual-gate threshold
    (``resilience/degrade.gate_threshold`` — the same eps·n·κ∞ model,
    same 0.5 non-vacuousness cap, which also caps the budget at
    ``DRIFT_BUDGET_FACTOR/2``).  ``factor`` overrides the documented
    default (the serve knob ``update_drift_budget_factor`` — the
    update demo passes 0.0 to force the re_invert rung on every
    update, the deterministic ladder demonstration)."""
    return (DRIFT_BUDGET_FACTOR if factor is None
            else float(factor)) * threshold


def drift_exceeded(drift: float, budget: float) -> bool:
    """NaN-hostile budget check (the ``gate_passes`` discipline): a
    corrupt drift accumulator or budget always exceeds."""
    import math

    return not (drift <= budget) or not math.isfinite(drift)


def as_update_factors(u, v, n: int, dtype, error=ValueError):
    """The ONE u/v normalization every update entry point shares
    (``solve_update``, ``JordanService.submit_update``, the fleet
    router): cast to ``dtype``, lift 1-D vectors to (n, 1) columns,
    and validate the matching-(n, k≥1) shape — raising ``error`` (the
    caller's exception class: ``UsageError`` on the library surface,
    ``ValueError`` on the serve/fleet surfaces, matching each layer's
    historical contract).  Returns ``(u, v, k)``."""
    import numpy as np

    u = np.asarray(u, dtype)
    v = np.asarray(v, dtype)
    if u.ndim == 1:
        u = u[:, None]
    if v.ndim == 1:
        v = v[:, None]
    if (u.ndim != 2 or v.ndim != 2 or u.shape != v.shape
            or u.shape[0] != n or u.shape[1] < 1):
        raise error(
            f"u/v must be matching (n, k>=1) factors with n={n} rows, "
            f"got {tuple(u.shape)} / {tuple(v.shape)}")
    return u, v, int(u.shape[1])


@partial(jax.jit, static_argnames=("precision",))
def smw_update(inv, u, v, precision=lax.Precision.HIGHEST):
    """(A + U·Vᵀ)⁻¹ from A⁻¹ — the bare identity, no verification.

    Args:
      inv: (n, n) resident A⁻¹ (real or complex; sub-fp32 storage
        computes at fp32 and rounds once, the engines' shared policy).
      u, v: (n, k) update factors (zero-padded columns are exact).
      precision: matmul precision (HIGHEST default, like the engines).

    Returns:
      (inv_new, singular): the updated inverse (garbage if singular)
      and the capacitance system's singular flag — True exactly when
      the MUTATED matrix is numerically singular (det identity above).
    """
    in_dtype = inv.dtype
    if jnp.dtype(in_dtype).itemsize < 4 and jnp.dtype(in_dtype).kind != "c":
        inv_new, singular = smw_update(
            inv.astype(jnp.float32), u.astype(jnp.float32),
            v.astype(jnp.float32), precision)
        return inv_new.astype(in_dtype), singular
    dtype = inv.dtype
    u = u.astype(dtype)
    v = v.astype(dtype)
    k = u.shape[-1]
    w = jnp.matmul(inv, u, precision=precision)             # A⁻¹U (n,k)
    z = jnp.matmul(v.T, inv, precision=precision)           # VᵀA⁻¹ (k,n)
    s = (jnp.eye(k, dtype=dtype)
         + jnp.matmul(v.T, w, precision=precision))         # capacitance
    # The k×k capacitance solve rides the repo's own pivoted
    # elimination: its singular flag is the typed signal that the
    # mutated matrix lost rank — never NaN-laden garbage.
    y, singular = block_jordan_solve(s, z, precision=precision)
    return inv - jnp.matmul(w, y, precision=precision), singular


@partial(jax.jit, static_argnames=("precision",))
def smw_update_with_metrics(a, inv, u, v, n_real=None,
                            precision=lax.Precision.HIGHEST):
    """The serve-shaped one-launch update kernel: mutate A, update the
    inverse by SMW, and re-verify against the MUTATED matrix — all in
    one compiled program (what the serve ``update`` lane AOT-compiles
    per (bucket_n, k_bucket, dtype)).

    Returns ``(a_new, inv_new, singular, kappa, rel_residual)`` with
    the invert lanes' metric conventions (``driver.batch_metrics``,
    row-masked to ``n_real`` under identity padding): ``kappa`` =
    ‖A_new‖∞·‖X_new‖∞ and ``rel_residual`` = ‖A_new·X_new − I‖∞ /
    ‖A_new‖∞ — the number the PR 5 residual gate judges.  The
    verification matmul is the deliberate O(n³) term: it keeps the
    update exactly as honest as a fresh invert while the executable's
    total FLOPs stay strictly below one (tests/test_update.py pins
    it via ``cost_analysis``)."""
    from ..driver import batch_metrics

    a_new = a + jnp.matmul(u, v.T, precision=precision)
    inv_new, singular = smw_update(inv, u, v, precision=precision)
    nr = (jnp.asarray([a.shape[-1]], jnp.int32) if n_real is None
          else jnp.asarray(n_real, jnp.int32).reshape(1))
    met = batch_metrics(a_new[None], inv_new[None], nr,
                        precision=precision)
    return (a_new, inv_new, singular, met["kappa"][0],
            met["rel_residual"][0])


_M_WORKLOAD = None


def _count_update() -> None:
    """Direct-API traffic accounting (the linalg/api.py counter — one
    series, labeled by workload)."""
    global _M_WORKLOAD
    if _M_WORKLOAD is None:
        _M_WORKLOAD = _obs_metrics.counter(
            "tpu_jordan_workload_requests_total",
            "direct-API workload executions (solve_system / lstsq), "
            "labeled by workload")
    _M_WORKLOAD.inc(workload="update")


@dataclass
class UpdateResult:
    """One :func:`solve_update` outcome — the update twin of
    ``driver.SolveResult``.  ``inverse`` is (A+UVᵀ)⁻¹; ``a_new`` the
    mutated matrix (callers chaining updates feed both back in);
    ``drift`` the NEW accumulated drift (reset to 0 by a re_invert
    rung); ``recovery`` the ladder record when a policy gated the
    update."""

    inverse: jax.Array | None
    a_new: jax.Array | None
    n: int
    k: int
    elapsed: float
    rel_residual: float
    kappa: float
    drift: float
    gflops: float                 # 4n²k + O(nk²) convention (hwcost)
    engine: str = "smw_update"
    workload: str = "update"
    singular: bool = False
    recovery: tuple = ()
    numerics: object | None = None


def solve_update(
    a,
    inv,
    u,
    v,
    dtype=None,
    drift: float = 0.0,
    policy=None,
    telemetry=None,
    numerics: str = "off",
    check: bool = True,
    verbose: bool = False,
) -> UpdateResult:
    """Apply one rank-k SMW update as a product call (the library twin
    of ``JordanService.update``; docs/WORKLOADS.md is the guide).

    ``a``/``inv`` are the caller's current matrix and its resident
    inverse; ``u``/``v`` the (n, k) mutation factors; ``drift`` the
    accumulated drift carried over from previous updates of the same
    resident inverse (thread ``result.drift`` back in).  The driver
    discipline applies end to end: AOT compile with the
    compile/execute split, ``timed_blocking`` wall brackets, XLA
    ``cost_analysis`` on the executable, the workload traffic counter,
    and — with a ``policy`` attached — the PR 5 residual gate against
    the MUTATED matrix plus the accumulated-drift budget
    (:func:`drift_budget`); a failing gate fires the "re_invert" rung
    (a fresh elimination of A_new through the in-place engine, drift
    reset to zero) and an exhausted ladder raises the typed
    ``ResidualGateError`` — never a silently stale inverse.

    ``check=False`` reports a singular mutated matrix on
    ``result.singular``/``inverse=None`` instead of raising."""
    from ..driver import SingularMatrixError, UsageError

    tel = telemetry if telemetry is not None else _NULL_TEL
    a = jnp.asarray(a) if dtype is None else jnp.asarray(a, dtype)
    dtype = a.dtype
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise UsageError(f"expected a square (n, n) matrix, got shape "
                         f"{tuple(a.shape)}")
    n = int(a.shape[0])
    inv = jnp.asarray(inv, dtype)
    if inv.shape != a.shape:
        raise UsageError(f"inv must match a's shape {tuple(a.shape)}, "
                         f"got {tuple(inv.shape)}")
    u, v, k = as_update_factors(u, v, n, dtype, error=UsageError)
    u = jnp.asarray(u)
    v = jnp.asarray(v)

    from ..obs.numerics import resolve_mode
    numerics = resolve_mode(numerics)
    if numerics == "trace":
        raise UsageError(
            "numerics='trace' instruments the unrolled elimination "
            "engines; the SMW update is three matmuls and a k×k solve "
            "— use numerics='summary'")
    _count_update()

    with tel.span("solve_update", n=n, k=k, workload="update"):
        result = _solve_update_impl(a, inv, u, v, n, k, dtype,
                                    float(drift), tel, policy, numerics,
                                    check, verbose)
    if result.singular and check:
        raise SingularMatrixError("singular matrix (rank-k update made "
                                  "the matrix singular)")
    return result


def _solve_update_impl(a, inv, u, v, n, k, dtype, drift, tel, policy,
                       numerics, check, verbose):
    from ..driver import _record_compile

    with tel.span("compile", engine="smw_update", n=n, k=k) as csp:
        def _compile():
            _faults.fire("compile")
            return jax.jit(
                lambda aa, ii, uu, vv: smw_update_with_metrics(
                    aa, ii, uu, vv)
            ).lower(a, inv, u, v).compile()
        compiled = (policy.retry.call(_compile,
                                      component="solve_update.compile")
                    if policy is not None else _compile())
    _record_compile(csp, "solve_update")
    exe_cost = _hwcost.executable_cost(compiled)

    def _execute():
        _faults.fire("execute")
        return timed_blocking(compiled, a, inv, u, v, telemetry=tel,
                              name="execute", engine="smw_update",
                              workload="update")

    out, esp = (policy.retry.call(_execute,
                                  component="solve_update.execute")
                if policy is not None else _execute())
    a_new, inv_new, singular, kappa, rel = out
    elapsed = esp.duration
    flops = _hwcost.baseline_workload_flops(n, "update", k=k)
    _hwcost.attach_execute_cost(esp, exe_cost, analytical_flops=flops)
    rel = float(rel)
    kappa = float(kappa)
    if _faults.corrupt("result_corrupt_nan"):
        rel = float("nan")

    if bool(singular):
        _obs_metrics.counter("tpu_jordan_singular_total",
                             "solves/requests flagged singular"
                             ).inc(component="solve_update")
        return UpdateResult(
            inverse=None, a_new=a_new, n=n, k=k, elapsed=elapsed,
            rel_residual=float("inf"), kappa=float("inf"), drift=drift,
            gflops=0.0, singular=True)

    nreport = None
    if numerics == "summary":
        from ..obs import numerics as _numerics

        nreport = _numerics.summary_report(
            n=n, block_size=n, engine="smw_update", rel_residual=rel,
            kappa=kappa, norm_a=0.0, dtype=dtype, workload="update")
        _numerics.observe(nreport)
        thresholds = None
        if policy is not None:
            from ..resilience.degrade import gate_threshold

            gd = (policy.gate_dtype if policy.gate_dtype is not None
                  else dtype)
            thresholds = _numerics.SpikeThresholds(
                residual=gate_threshold(policy, n, kappa, gd))
        _numerics.record_spikes(nreport, thresholds)

    recovery = ()
    new_drift = drift + max(rel, 0.0) if rel == rel else float("nan")
    if policy is not None:
        inv_new, rel, kappa, new_drift, recovery = _update_recover(
            policy, tel, a_new=a_new, inv_new=inv_new, rel=rel,
            kappa=kappa, drift=drift, n=n, dtype=dtype,
            numerics=numerics)

    if verbose:
        print(f"glob_time: {elapsed:.2f}")
        print(f"rel_residual: {rel:e}")

    return UpdateResult(
        inverse=inv_new, a_new=a_new, n=n, k=k, elapsed=elapsed,
        rel_residual=rel, kappa=kappa, drift=new_drift,
        gflops=(flops / elapsed / 1e9) if elapsed > 0 else 0.0,
        recovery=recovery, numerics=nreport)


def reinvert_fresh(a_new, block_size: int | None = None):
    """The "re_invert" rung's fresh elimination: the in-place engine on
    the MUTATED matrix, metrics assembled in the same launch (the
    serve path reuses its warm invert-lane executable instead — this
    is the library/one-shot form).  Returns
    (inv, singular, kappa, rel_residual)."""
    from ..driver import batch_metrics
    from ..ops.jordan_inplace import block_jordan_invert_inplace

    def fn(aa):
        x, sing = block_jordan_invert_inplace(aa, block_size=block_size)
        met = batch_metrics(aa[None], x[None])
        return x, sing, met["kappa"][0], met["rel_residual"][0]

    x, sing, kappa, rel = jax.jit(fn)(a_new)
    return x, bool(sing), float(kappa), float(rel)


def _update_recover(policy, tel, *, a_new, inv_new, rel, kappa, drift,
                    n, dtype, numerics="off"):
    """Gate + drift budget + the re_invert rung (the degrade.py
    discipline on the resident-update path).  Returns
    ``(inv, rel, kappa, new_drift, recovery)``."""
    from ..resilience.degrade import (_M_GATE_FAIL, _M_RUNGS,
                                      gate_passes, gate_threshold)
    from ..resilience.policy import ResidualGateError

    gate_dtype = (policy.gate_dtype if policy.gate_dtype is not None
                  else dtype)
    threshold = gate_threshold(policy, n, kappa, gate_dtype)
    budget = drift_budget(threshold)
    new_drift = drift + max(rel, 0.0) if rel == rel else float("nan")
    if gate_passes(rel, threshold) and not drift_exceeded(new_drift,
                                                          budget):
        return inv_new, rel, kappa, new_drift, ()

    _M_GATE_FAIL.inc()
    cause = ("drift_budget" if gate_passes(rel, threshold)
             else "residual_gate")
    if numerics == "summary" and cause == "drift_budget":
        # The residual spike (recorded before this ladder) cannot
        # explain a drift-caused rung — the budget exceedance records
        # its own causal breadcrumb (the ISSUE 10 discipline).
        from ..obs.numerics import record_drift_spike

        record_drift_spike(n=n, engine="smw_update", value=new_drift,
                           threshold=budget)
    _recorder.record("residual_gate_failure", n=n, workload="update",
                     rel_residual=float(rel), threshold=float(threshold),
                     drift=float(new_drift), budget=float(budget),
                     cause=cause)
    recovery = []
    with tel.span("recover", n=n, workload="update", cause=cause,
                  rel_residual=float(rel), drift=float(new_drift)) as rsp:
        with tel.span("re_invert") as sp:
            inv2, sing2, kap2, rel2 = reinvert_fresh(a_new)
            thr2 = gate_threshold(policy, n, kap2, gate_dtype)
            passed = gate_passes(rel2, thr2) and not sing2
            sp.attrs.update(rel_residual=float(rel2), passed=passed)
        recovery.append({
            "rung": "re_invert", "cause": cause,
            "rel_residual_before": float(rel),
            "rel_residual_after": float(rel2),
            "drift_before": float(new_drift), "passed": passed,
        })
        _M_RUNGS.inc(rung="re_invert",
                     outcome="passed" if passed else "failed")
        _recorder.record("recovery_rung", rung="re_invert",
                         workload="update",
                         outcome="passed" if passed else "failed",
                         rel_residual=float(rel2))
        if passed:
            rsp.attrs["recovered_by"] = "re_invert"
            return inv2, float(rel2), float(kap2), 0.0, tuple(recovery)

    raise ResidualGateError(
        f"update residual gate failed ({cause}: rel {rel:.3e}, drift "
        f"{new_drift:.3e} vs threshold {threshold:.3e} / budget "
        f"{budget:.3e}) and the re_invert rung did not recover",
        recovery=tuple(recovery))
