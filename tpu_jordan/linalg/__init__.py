"""tpu_jordan.linalg — the solve workloads as first-class products
(ISSUE 11): ``solve_system`` (X = A⁻¹B by Gauss–Jordan on [A | B], no
inverse ever formed), ``lstsq`` (normal equations through the SPD fast
path), the pivot-free ``assume="spd"`` route, and complex dtypes —
wired through the tuning registry (workload-scoped engine="auto"), the
plan cache (``|wsolve`` key segments; invert keys byte-identical), the
serve buckets (``JordanService.submit(a, b)``), the ‖A·X − B‖ residual
gate, and the numerics observatory.  ISSUE 15 adds the distributed
solve (``solve_system(workers=p | (pr, pc))`` — the [A | B]
elimination sharded over the 1D/2D meshes, comm-reconciled) and the
fori engine (``block_jordan_solve_fori``) that lifts MAX_UNROLL_NR.
docs/WORKLOADS.md is the guide.
"""

from .api import (LstsqResult, SolveSystemResult, lstsq,
                  resolve_solve_engine, solve_system)
from .engine import (block_jordan_solve, block_jordan_solve_fori,
                     solve_batch_metrics)
from .update import (DRIFT_BUDGET_FACTOR, UpdateResult, drift_budget,
                     drift_exceeded, smw_update, smw_update_with_metrics,
                     solve_update)

__all__ = [
    "DRIFT_BUDGET_FACTOR", "LstsqResult", "SolveSystemResult",
    "UpdateResult", "block_jordan_solve", "block_jordan_solve_fori",
    "drift_budget",
    "drift_exceeded", "lstsq", "resolve_solve_engine", "smw_update",
    "smw_update_with_metrics", "solve_batch_metrics", "solve_system",
    "solve_update",
]
