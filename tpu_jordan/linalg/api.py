"""``solve_system`` / ``lstsq`` — the solve workloads as products
(ISSUE 11 tentpole).

The driver discipline, re-applied to the new workloads end to end:
AOT compile with the compile/execute split (warm telemetry shows zero
compile spans), ``timed_blocking`` wall brackets, XLA ``cost_analysis``
accounting on every executable, engine="auto" through the PR 2 tuner
ladder at a WORKLOAD-scoped tuning point (plan-cache keys grow a
``|wsolve`` segment; invert keys stay byte-identical), the κ-free
‖A·X − B‖ residual gate with a recovery ladder when a policy is
attached, and numerics="summary" observability — typed results
(:class:`SolveSystemResult` / :class:`LstsqResult`), never bare arrays.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..config import default_block_size
from ..obs import hwcost as _hwcost
from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from ..obs.spans import NULL as _NULL_TEL
from ..obs.spans import timed_blocking
from ..ops.norms import inf_norm
from ..resilience import faults as _faults
from .engine import block_jordan_solve

ASSUME = ("general", "spd")

_M_WORKLOAD = None


def _count_workload(workload: str) -> None:
    """Per-workload traffic accounting for the direct (non-serve) API
    (ISSUE 11): one counter, labeled by workload — the serve path's
    per-lane stats are the other half of the story."""
    global _M_WORKLOAD
    if _M_WORKLOAD is None:
        _M_WORKLOAD = _obs_metrics.counter(
            "tpu_jordan_workload_requests_total",
            "direct-API workload executions (solve_system / lstsq), "
            "labeled by workload")
    _M_WORKLOAD.inc(workload=workload)


@dataclass
class SolveSystemResult:
    """One ``solve_system`` outcome — the solve twin of
    ``driver.SolveResult``.  ``residual`` is ‖A·X − B‖∞ (the right
    verification for a solve: cheaper and tighter than inverting
    first); ``rel_residual`` the κ-free normwise backward error it
    gates on; ``kappa_est`` a lower-bound κ∞(A) estimate
    (‖A‖∞‖X‖∞/‖B‖∞ — conditioning context with no A⁻¹ formed)."""

    x: jax.Array | None
    elapsed: float
    residual: float               # ‖A·X − B‖∞
    n: int
    k: int
    block_size: int
    gflops: float                 # n³(1+k/n) convention (hwcost)
    engine: str | None = None
    workload: str = "solve"
    singular: bool = False
    plan: object | None = None    # tuning.Plan when engine="auto"
    kappa_est: float | None = None
    recovery: tuple = ()          # ladder rungs (policy= solves only)
    numerics: object | None = None
    trace: object | None = None
    workers: object = 1           # the mesh the solve ran on (ISSUE 15)
    x_blocks: jax.Array | None = None  # sharded X row blocks
    #   (gather=False distributed solves; cyclic row storage order)
    layout: object | None = None  # CyclicLayout/CyclicLayout2D of
    #   x_blocks
    comm: object | None = None    # obs.comm.CommReport on every
    #   DISTRIBUTED solve (the ISSUE 14 accounting, extended to the
    #   solve engines): per-phase collective bytes/messages, the
    #   observed == analytical reconciliation under
    #   obs.comm.recording(), and the drift record.  None single-device.
    work: object | None = None    # obs.work.WorkReport on every
    #   DISTRIBUTED solve (ISSUE 19): per-worker useful-FLOP shares of
    #   the shrinking [A|B] window summing EXACTLY to n³+n²k, skew and
    #   ragged-tail penalty, and the cost_analysis reconciliation.
    #   None single-device.
    _norm_a: float | None = None
    _norm_x: float | None = None
    _norm_b: float | None = None

    @property
    def rel_residual(self) -> float | None:
        """‖A·X−B‖∞ / (‖A‖∞‖X‖∞ + ‖B‖∞) — the normwise backward
        error (Higham ch. 7); ``solve_gate_threshold`` is its gate."""
        if self._norm_a is None:
            return None
        denom = self._norm_a * (self._norm_x or 0.0) + (self._norm_b
                                                        or 0.0)
        return self.residual / denom if denom else self.residual


@dataclass
class LstsqResult:
    """One ``lstsq`` outcome.  ``x`` minimizes ‖A·x − b‖ via the
    normal equations (AᴴA)x = Aᴴb routed through ``solve_system`` —
    the Gram matrix is Hermitian PD for a full-column-rank A, so the
    route IS the SPD fast path.  ``rank_deficient`` surfaces a
    singular Gram system (the rank-deficiency signal) instead of
    returning garbage; ``kappa_est`` is the Gram system's conditioning
    estimate (≈ κ(A)², the known normal-equations squaring)."""

    x: jax.Array | None
    residual: float               # ‖A·x − b‖∞, the LS objective's norm
    normal_residual: float        # ‖(AᴴA)x − Aᴴb‖∞ off the inner solve
    rows: int
    n: int
    k: int
    rank_deficient: bool
    kappa_est: float | None
    elapsed: float
    engine: str | None = None
    workload: str = "lstsq"
    plan: object | None = None
    inner: SolveSystemResult | None = None


def resolve_solve_engine(engine: str, assume: str):
    """Shared engine/assume flag contract for the solve workloads.

    Returns ``(engine, workload)``: "auto" stays "auto" and is resolved
    through the tuner ladder at the workload-scoped point ("solve", or
    "solve_spd" under the assume="spd" promise — where cost ranking
    picks the pivot-free engine, with the pivoting engine registered as
    the legal fallback).  An explicit engine must belong to the SOLVE
    vocabulary — the invert zoo is not addressable from here."""
    from ..driver import UsageError
    from ..tuning.registry import SOLVE_ENGINES

    if assume not in ASSUME:
        raise UsageError(f"unknown assume {assume!r}; choose from "
                         f"{'/'.join(ASSUME)}")
    workload = "solve_spd" if assume == "spd" else "solve"
    if engine not in SOLVE_ENGINES:
        raise UsageError(
            f"unknown solve engine {engine!r}; choose from "
            f"{'/'.join(SOLVE_ENGINES)} (the invert engines are not "
            f"solve engines — use driver.solve for inverses)")
    if engine == "solve_spd" and assume != "spd":
        raise UsageError(
            "engine='solve_spd' is the pivot-free path and requires "
            "the assume='spd' promise (skipping pivoting on a general "
            "matrix is unsound)")
    if engine == "solve_lookahead" and assume == "spd":
        # ISSUE 16: the probe-ahead schedule overlaps the CONDITION
        # PROBE with the trailing eliminate; the pivot-free flavor has
        # no probe to move — a typed refusal, never a silent fallback.
        raise UsageError(
            "engine='solve_lookahead' overlaps the pivot-condition "
            "probe with the trailing eliminate; the assume='spd' "
            "pivot-free path has nothing to probe ahead — legal "
            "lookahead engines are engine='solve_lookahead' "
            "(assume='general', workers>1) and driver.solve "
            "engine='lookahead'; under spd use engine='solve_spd' or "
            "'auto'")
    return engine, workload


def _as_2d_rhs(b, dtype, n: int, what: str):
    from ..driver import UsageError

    b = jnp.asarray(b, dtype)
    squeezed = b.ndim == 1
    if squeezed:
        b = b[:, None]
    if b.ndim != 2 or b.shape[0] != n or b.shape[1] < 1:
        raise UsageError(
            f"{what} must be (n,) or (n, k>=1) with n={n} rows, got "
            f"shape {tuple(b.shape)}")
    return b, squeezed


def solve_system(
    a,
    b,
    block_size: int | None = None,
    dtype=None,
    assume: str = "general",
    engine: str = "auto",
    workers=1,
    gather: bool = True,
    tune: bool = False,
    plan_cache: str | None = None,
    telemetry=None,
    policy=None,
    numerics: str = "off",
    check: bool = True,
    verbose: bool = False,
) -> SolveSystemResult:
    """Solve A·X = B — Gauss–Jordan on [A | B], no inverse ever formed.

    ``workers`` (ISSUE 15) routes the solve exactly like
    ``driver.solve``: 1 = single device; ``p`` = the 1D row-block-cyclic
    mesh; a ``(pr, pc)`` tuple = the 2D block-cyclic mesh.  Distributed
    points resolve ``engine="auto"`` through the workload-scoped tuner
    to the sharded [A | B] elimination (``solve_sharded`` —
    parallel/sharded_inplace.py and its 2D twin): the k RHS columns
    ride the pivot-probe / row-broadcast / eliminate supersteps, the
    live-column window still shrinks statically per shard (per-device
    ``cost_analysis`` FLOPs land ~1/p of the single-device solve's),
    and X bit-matches the single-device engine on block-aligned
    fixtures.  ``SolveSystemResult.comm`` carries the full ISSUE 14
    collective accounting (reconciled observed == analytical under
    ``obs.comm.recording()``).  ``gather=False`` (distributed only)
    additionally returns the sharded X row blocks
    (``result.x_blocks`` + ``result.layout``); unlike the invert
    engines X is O(n·k), so the dense ``result.x`` is assembled — and
    verified — in either mode (A itself never gathers on any
    distributed path).  Distributed solves are real-dtype (complex
    stays single-device, like invert), general-pivoting only
    (``assume="spd"`` is the single-device fast path), and support
    ``numerics="summary"`` (``"trace"`` is a typed refusal — the
    per-superstep stats are host-visible on the single-device unrolled
    engines only).

    The solve twin of ``driver.solve`` (docs/WORKLOADS.md is the
    product guide): ``engine="auto"`` resolves through the tuner ladder
    at a ``workload="solve"`` (or ``"solve_spd"`` under
    ``assume="spd"``) tuning point — plan-cache hit (zero
    measurements), registry cost ranking, or ``tune=True`` measured
    tuning; the resolved choice is on ``result.engine``/``plan``.
    ``assume="spd"`` is the pivot-free fast path (the caller's
    symmetric/Hermitian-positive-definite promise skips the
    condition-based pivot probe).  Complex dtypes are first-class:
    complex64/complex128 A and B flow through the engine, the residual
    machinery (all norms are |z|-based), and the gate.

    ``policy`` attaches the resilience layer: the κ-free backward-error
    gate ``rel_residual <= gate_tol·eps·n``
    (resilience/degrade.solve_gate_threshold) guards the result; a
    failing gate walks the solve recovery ladder — one iterative-
    refinement pass through the SAME compiled executable (X += A⁻¹R at
    working precision), then, under assume="spd", a re-solve on the
    pivoting engine, then (sub-fp32 storage) an fp32 re-solve — and an
    exhausted ladder raises ``ResidualGateError``, never a silently
    wrong X.  ``numerics="summary"`` records the NumericsReport
    (workload-tagged) with spikes BEFORE any recovery rung;
    ``numerics="trace"`` (ISSUE 12 satellite — the ROADMAP 1b
    remainder) additionally stacks the per-superstep pivot/growth
    health arrays into the same executable (pivot sequence pinned ==
    the invert engine's on shared fixtures); trace on the
    ``assume="spd"`` fast path stays a typed refusal (no probe to
    trace).

    ``check=False`` reports a singular system on
    ``result.singular``/``x=None`` instead of raising — the lstsq
    route uses it to surface rank deficiency as data."""
    from ..driver import UsageError

    tel = telemetry if telemetry is not None else _NULL_TEL
    a = jnp.asarray(a) if dtype is None else jnp.asarray(a, dtype)
    dtype = a.dtype
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise UsageError(f"expected a square (n, n) matrix, got shape "
                         f"{tuple(a.shape)}")
    n = int(a.shape[0])
    b2, squeezed = _as_2d_rhs(b, dtype, n, "b")
    k = int(b2.shape[1])
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)

    distributed = isinstance(workers, tuple) or workers > 1
    if not distributed and not gather:
        raise UsageError(
            "gather=False is only meaningful on distributed solves "
            "(workers > 1 or a (pr, pc) tuple)")
    if distributed:
        if jnp.issubdtype(dtype, jnp.complexfloating):
            raise UsageError(
                "complex dtypes run single-device (the distributed "
                "scatter/collective paths are real-dtype, the invert "
                "engines' contract); workers must be 1")
        if assume == "spd":
            raise UsageError(
                "assume='spd' is the single-device pivot-free fast "
                "path; the distributed [A | B] elimination pivots "
                "(workers must be 1, or drop the spd promise)")

    from ..obs.numerics import resolve_mode
    numerics = resolve_mode(numerics)
    if numerics == "trace" and distributed:
        raise UsageError(
            "numerics='trace' instruments the single-device unrolled "
            "engines (the per-superstep stats are host-visible there); "
            "distributed solves support numerics='summary'")
    if numerics == "trace" and assume == "spd":
        # The trace instruments the condition-based pivot PROBE; the
        # pivot-free fast path probes exactly one candidate per
        # superstep — there is no selection to trace, and silently
        # recording a one-candidate "spread" would be a different
        # record than the mode promises (the PR 4 honesty discipline,
        # same shape as the fused-engine refusals on the invert side).
        raise UsageError(
            "numerics='trace' traces the condition-based pivot probe; "
            "the assume='spd' fast path has no probe (one diagonal "
            "candidate per superstep) — use numerics='summary', or "
            "assume='general'")

    engine, workload = resolve_solve_engine(engine, assume)
    if engine == "solve_sharded" and not distributed:
        raise UsageError(
            "engine='solve_sharded' is the distributed [A | B] "
            "elimination (its win is the mesh); pass workers=p or "
            "workers=(pr, pc)")
    if engine == "solve_lookahead" and not distributed:
        # ISSUE 16: lookahead is NOT wired on the single-device
        # augmented [A | B] engine (solve_aug's fused sweep has no
        # separable panel to reorder) — typed, naming the legal homes.
        raise UsageError(
            "engine='solve_lookahead' is the probe-ahead distributed "
            "[A | B] elimination; it is not wired on the single-device "
            "augmented engine — pass workers=p or workers=(pr, pc), "
            "or use engine='solve_aug'/'auto' single-device (for "
            "inverses, driver.solve engine='lookahead')")
    if distributed and engine not in ("auto", "solve_sharded",
                                      "solve_lookahead"):
        raise UsageError(
            f"engine={engine!r} is a single-device solve engine; "
            f"distributed points run engine='solve_sharded' or "
            f"'solve_lookahead' (or 'auto', which resolves there)")
    if (tune or plan_cache is not None) and engine != "auto":
        raise UsageError("tune/plan_cache apply to engine='auto' only "
                         "(an explicit engine leaves nothing to tune)")
    plan = None
    if engine == "auto":
        from ..tuning.tuner import auto_select

        engine, _, plan = auto_select(n, m, dtype, workers, gather,
                                      tune=tune,
                                      plan_cache=plan_cache,
                                      telemetry=tel, workload=workload)
    if numerics == "trace" and engine == "solve_fori":
        raise UsageError(
            "numerics='trace' instruments the UNROLLED solve engine "
            "only (the fori engine's traced supersteps have no "
            "host-visible stats twin); use a larger block_size so "
            "Nr <= MAX_UNROLL_NR, or numerics='summary'")
    spd = engine == "solve_spd"
    _count_workload(workload)

    with tel.span("solve_system", n=n, k=k, workload=workload) as root:
        if engine in ("solve_sharded", "solve_lookahead"):
            result = _solve_system_dist_impl(
                a, b2, n, k, m, dtype, workers, gather, workload, plan,
                tel, policy, numerics, check, verbose, engine=engine)
        else:
            result = _solve_system_impl(
                a, b2, n, k, m, dtype, engine, spd, workload, plan, tel,
                policy, numerics, check, verbose)
    if telemetry is not None:
        result.trace = root
    if squeezed and result.x is not None:
        result.x = result.x[:, 0]
    return result


def _residual_stats(a, x, b):
    """(residual, norm_a, norm_x, norm_b) — eager |z|-based norms, the
    verification pass (‖A·X − B‖∞ against the CALLER's A and B — the
    solve analog of the reference's reload semantics: never algorithm
    state)."""
    from jax import lax as _lax

    r = jnp.matmul(a, x, precision=_lax.Precision.HIGHEST) - b
    residual = float(jnp.max(jnp.sum(jnp.abs(r), axis=-1)))
    return (residual, float(inf_norm(a)), float(inf_norm(x)),
            float(inf_norm(b)))


def _rel(residual: float, norm_a: float, norm_x: float,
         norm_b: float) -> float:
    denom = norm_a * norm_x + norm_b
    return residual / denom if denom else residual


def _solve_system_impl(a, b2, n, k, m, dtype, engine, spd, workload,
                       plan, tel, policy, numerics, check, verbose):
    from ..driver import SingularMatrixError, _record_compile

    # ISSUE 10 remainder (ROADMAP 1b): the instrumented per-superstep
    # trace twin, stacked into the SAME compiled executable — X bits
    # untouched, pivot sequence pinned equal to the invert engine's.
    collect = numerics == "trace"
    if engine == "solve_fori":
        from .engine import block_jordan_solve_fori

        def _solve_fn(aa, bb):
            # The fori-compiled engine (ISSUE 15): traced supersteps,
            # compile cost flat in Nr — Nr > MAX_UNROLL_NR is legal
            # here; X bit-matches the unrolled engine.
            return block_jordan_solve_fori(aa, bb, block_size=m,
                                           spd=spd)
    else:
        def _solve_fn(aa, bb):
            return block_jordan_solve(aa, bb, block_size=m, spd=spd,
                                      collect_stats=collect)
    with tel.span("compile", engine=engine, n=n, k=k) as csp:
        def _compile():
            _faults.fire("compile")
            return jax.jit(_solve_fn).lower(a, b2).compile()
        compiled = (policy.retry.call(_compile,
                                      component="solve_system.compile")
                    if policy is not None else _compile())
    _record_compile(csp, "solve_system")
    exe_cost = _hwcost.executable_cost(compiled)
    # The recovery ladder refines through the same executable and
    # expects the (x, singular) pair whichever mode compiled.
    run_compiled = ((lambda aa, bb: compiled(aa, bb)[:2]) if collect
                    else compiled)

    def _execute():
        _faults.fire("execute")
        return timed_blocking(compiled, a, b2, telemetry=tel,
                              name="execute", engine=engine,
                              workload=workload)

    out, esp = (
        policy.retry.call(_execute, component="solve_system.execute")
        if policy is not None else _execute())
    if collect:
        x, singular, nstats = out
    else:
        (x, singular), nstats = out, None
    elapsed = esp.duration
    flops = _hwcost.baseline_workload_flops(n, workload, k=k)
    if elapsed > 0:
        esp.attrs["gflops"] = round(flops / elapsed / 1e9, 3)
    _hwcost.attach_execute_cost(esp, exe_cost, analytical_flops=flops)
    _obs_metrics.histogram(
        "tpu_jordan_solve_seconds",
        "timed elimination wall seconds (the glob_time analog)",
    ).observe(elapsed, workload=workload)
    if _faults.corrupt("result_corrupt_nan"):
        x = x.at[0, 0].set(float("nan"))

    singular = bool(singular)
    if singular:
        _obs_metrics.counter("tpu_jordan_singular_total",
                             "solves/requests flagged singular"
                             ).inc(component="solve_system")
        if check:
            raise SingularMatrixError("singular matrix")
        return SolveSystemResult(
            x=None, elapsed=elapsed, residual=float("inf"), n=n, k=k,
            block_size=m, gflops=0.0, engine=engine, workload=workload,
            singular=True, plan=plan)

    with tel.span("residual"):
        residual, norm_a, norm_x, norm_b = _residual_stats(a, x, b2)
    rel = _rel(residual, norm_a, norm_x, norm_b)
    kappa_est = (norm_a * norm_x / norm_b) if norm_b else None

    nreport = None
    if numerics != "off":
        # Recorded (and spiked) BEFORE the recovery ladder — a rung
        # event must be causally preceded by its numerics evidence
        # (the ISSUE 10 discipline, extended to the solve workloads).
        nreport = _solve_numerics(n, m, engine, workload, rel,
                                  kappa_est, norm_a, dtype, policy,
                                  stats=nstats)

    recovery = ()
    if policy is not None:
        x, residual, norm_a, norm_x, norm_b, recovery = _solve_recover(
            policy, tel, a=a, b=b2, x=x, compiled=run_compiled,
            residual=residual, norm_a=norm_a, norm_x=norm_x,
            norm_b=norm_b, n=n, k=k, m=m, dtype=dtype, spd=spd,
            workload=workload)

    if verbose:
        print(f"glob_time: {elapsed:.2f}")
        print(f"residual: {residual:e}")

    return SolveSystemResult(
        x=x, elapsed=elapsed, residual=residual, n=n, k=k,
        block_size=m,
        gflops=(flops / elapsed / 1e9) if elapsed > 0 else 0.0,
        engine=engine, workload=workload, singular=False, plan=plan,
        kappa_est=kappa_est, recovery=recovery, numerics=nreport,
        _norm_a=norm_a, _norm_x=norm_x, _norm_b=norm_b)


def _fresh_solve_fn(n, m, spd):
    """The legal single-device solve engine for a FRESH re-solve at
    this (n, m): the unrolled engine inside its MAX_UNROLL_NR reach,
    the fori engine beyond — so the recovery ladder's repivot/resolve
    rungs never trip the unrolled engine's typed refusal on a
    large-Nr solve."""
    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    from .engine import block_jordan_solve_fori

    if -(-n // m) > MAX_UNROLL_NR:
        return lambda aa, bb: block_jordan_solve_fori(
            aa, bb, block_size=m, spd=spd)
    return lambda aa, bb: block_jordan_solve(aa, bb, block_size=m,
                                             spd=spd)


def solve_mesh_backend(workers, n: int, m: int):
    """ONE mesh-shape dispatch for the distributed solve (ISSUE 15):
    ``(mesh, lay, scatter_a, scatter_b, compile_fn, gather_x)`` for a
    workers spec (int p -> 1D row-cyclic, (pr, pc) -> 2D block-cyclic)
    — shared by :func:`solve_system`, the tuner's ``measure_config``,
    and bench's sharded row, so the measured/benched executable can
    never silently diverge from the one solve_system ships."""
    if isinstance(workers, tuple):
        from ..parallel import make_mesh_2d
        from ..parallel.jordan2d import scatter_matrix_2d
        from ..parallel.jordan2d_inplace import (
            compile_sharded_jordan_solve_2d, gather_solution_2d,
            scatter_rhs_2d)
        from ..parallel.layout import CyclicLayout2D

        pr, pc = workers
        return (make_mesh_2d(pr, pc),
                CyclicLayout2D.create(n, m, pr, pc),
                scatter_matrix_2d, scatter_rhs_2d,
                compile_sharded_jordan_solve_2d, gather_solution_2d)
    from ..parallel import make_mesh
    from ..parallel.layout import CyclicLayout
    from ..parallel.ring_gemm import _to_identity_padded_blocks
    from ..parallel.sharded_inplace import (
        compile_sharded_jordan_solve, gather_solution_1d,
        scatter_rhs_1d)

    return (make_mesh(workers), CyclicLayout.create(n, m, workers),
            _to_identity_padded_blocks, scatter_rhs_1d,
            compile_sharded_jordan_solve, gather_solution_1d)


def _solve_system_dist_impl(a, b2, n, k, m, dtype, workers, gather,
                            workload, plan, tel, policy, numerics,
                            check, verbose, engine="solve_sharded"):
    """The distributed solve skeleton (ISSUE 15): scatter [A | B] over
    the 1D/2D mesh, run the sharded elimination (unrolled below
    MAX_UNROLL_NR, fori beyond), reconcile the collective inventory
    (obs/comm.py), assemble X (O(n·k) — cheap in either gather mode),
    and verify ‖A·X − B‖ densely against the CALLER's A and B (they
    are in hand — solve_system takes arrays, so the verification
    needs no mesh collectives and the comm inventory has no residual
    section, unlike the invert driver's ring-GEMM pass).

    ``engine="solve_lookahead"`` (ISSUE 16) compiles the probe-ahead
    twin: same scatter/gather/verify skeleton, same analytical
    collective multiset (the schedule reorders, never adds), X bits
    pinned identical — only the compile flag and the report labels
    change."""
    from ..driver import (SingularMatrixError, _attach_overlap_evidence,
                          _record_compile)
    from ..obs import comm as _comm
    from ..obs import work as _obswork
    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    in_dtype = jnp.dtype(dtype)
    work = jnp.float32 if in_dtype.itemsize < 4 else in_dtype
    (mesh, lay, scatter_a, scatter_b, compile_fn,
     gather_x) = solve_mesh_backend(workers, n, m)

    with tel.span("load"):
        W = scatter_a(jnp.asarray(a, work), lay, mesh)
        Xb = scatter_b(jnp.asarray(b2, work), lay, mesh)

    # The layout-derived analytical collective inventory (ISSUE 14,
    # extended with the solve flavors) — built for every distributed
    # solve; observed counts captured only under obs.comm.recording().
    unroll = lay.Nr <= MAX_UNROLL_NR
    la = engine == "solve_lookahead"
    comm_rep = _comm.engine_report(
        engine=engine, lay=lay, dtype=work, gather=gather,
        unroll=unroll, rhs=k)
    # The work observatory (ISSUE 19): per-worker shares of the
    # shrinking [A|B] live window, integer-exact against n³+n²k.
    work_rep = _obswork.engine_report(engine=engine, lay=lay,
                                      dtype=work, k=k, unroll=unroll)

    with tel.span("compile", engine=engine, n=n, k=k) as csp:
        def _compile():
            _faults.fire("compile")
            if _comm.recording_active():
                with _comm.record_collectives() as rec:
                    run = compile_fn(W, Xb, mesh, lay, lookahead=la)
                comm_rep.attach_observed("engine", rec.records)
                return run
            return compile_fn(W, Xb, mesh, lay, lookahead=la)
        run = (policy.retry.call(_compile,
                                 component="solve_system.compile")
               if policy is not None else _compile())
    _record_compile(csp, "solve_system")
    exe_cost = _hwcost.executable_cost(run)

    # Distributed execute is NOT retried (the driver's contract: the
    # sharded working state may alias into the engine) — a mid-flight
    # failure propagates typed, never silently.
    _faults.fire("execute")
    (xb, singular), esp = timed_blocking(run, W, Xb, telemetry=tel,
                                         name="execute",
                                         engine=engine,
                                         workload=workload)
    elapsed = esp.duration
    flops = _hwcost.baseline_workload_flops(n, workload, k=k)
    if elapsed > 0:
        esp.attrs["gflops"] = round(flops / elapsed / 1e9, 3)
    _hwcost.attach_execute_cost(esp, exe_cost, analytical_flops=flops)
    if la:
        _attach_overlap_evidence(esp, n, m, workers)
    comm_rep.observe_metrics()
    comm_rep.attach_span(esp)
    _comm.observe_drift(comm_rep, elapsed, esp)
    _comm.set_last_report(comm_rep)
    work_rep.attach_xla(exe_cost, span=esp)
    work_rep.observe_metrics()
    work_rep.attach_span(esp)
    _obswork.set_last_report(work_rep)
    _obs_metrics.histogram(
        "tpu_jordan_solve_seconds",
        "timed elimination wall seconds (the glob_time analog)",
    ).observe(elapsed, workload=workload)

    singular = bool(singular.any())
    if singular:
        _obs_metrics.counter("tpu_jordan_singular_total",
                             "solves/requests flagged singular"
                             ).inc(component="solve_system")
        if check:
            raise SingularMatrixError("singular matrix")
        return SolveSystemResult(
            x=None, elapsed=elapsed, residual=float("inf"), n=n, k=k,
            block_size=m, gflops=0.0, engine=engine,
            workload=workload, singular=True, plan=plan,
            workers=workers, comm=comm_rep, work=work_rep)

    with tel.span("gather", gathered=gather):
        # X is O(n·k): assembled in EITHER mode (the verification needs
        # it; the memory contract is about A, which never gathers).
        x = gather_x(xb, lay, n)
        if in_dtype != work:
            x = x.astype(in_dtype)
            xb = xb.astype(in_dtype)

    with tel.span("residual"):
        residual, norm_a, norm_x, norm_b = _residual_stats(a, x, b2)
    rel = _rel(residual, norm_a, norm_x, norm_b)
    kappa_est = (norm_a * norm_x / norm_b) if norm_b else None

    nreport = None
    if numerics != "off":
        nreport = _solve_numerics(n, m, engine, workload, rel,
                                  kappa_est, norm_a, dtype, policy)

    recovery = ()
    if policy is not None:
        # The refine rung re-runs THE SAME sharded executable on a
        # re-scattered residual RHS (zero recompiles — W is still
        # resident); deeper rungs fall back to a fresh single-device
        # re-solve (_solve_recover's ladder).
        def _rerun(aa, rr):
            del aa
            Xr = scatter_b(jnp.asarray(rr, work), lay, mesh)
            ob, s = run(W, Xr)
            return gather_x(ob, lay, n), s.any()

        x, residual, norm_a, norm_x, norm_b, recovery = _solve_recover(
            policy, tel, a=a, b=b2, x=x, compiled=_rerun,
            residual=residual, norm_a=norm_a, norm_x=norm_x,
            norm_b=norm_b, n=n, k=k, m=m, dtype=dtype, spd=False,
            workload=workload)
        if recovery and not gather:
            # A rung replaced X: re-scatter the RECOVERED solution so
            # x_blocks can never silently hand out the gate-failing
            # pre-recovery answer next to a recovered x/residual.
            xb = scatter_b(jnp.asarray(x), lay, mesh)

    if verbose:
        print(f"glob_time: {elapsed:.2f}")
        print(f"residual: {residual:e}")

    return SolveSystemResult(
        x=x, elapsed=elapsed, residual=residual, n=n, k=k,
        block_size=m,
        gflops=(flops / elapsed / 1e9) if elapsed > 0 else 0.0,
        engine=engine, workload=workload, singular=False,
        plan=plan, kappa_est=kappa_est, recovery=recovery,
        numerics=nreport, workers=workers,
        x_blocks=None if gather else xb,
        layout=None if gather else lay, comm=comm_rep,
        work=work_rep,
        _norm_a=norm_a, _norm_x=norm_x, _norm_b=norm_b)


def _solve_numerics(n, m, engine, workload, rel, kappa_est, norm_a,
                    dtype, policy, stats=None):
    from ..obs import numerics as _numerics

    if stats is not None:
        # The full per-superstep record (ISSUE 10 trace, solve twin):
        # pivot selection evidence + element growth off the SAME
        # executable, residual semantics the κ-free backward error.
        report = _numerics.trace_report(
            stats, n=n, block_size=m, engine=engine,
            trace_engine=engine, rel_residual=rel,
            kappa=(kappa_est if kappa_est is not None else 1.0),
            norm_a=norm_a, dtype=dtype, workload=workload)
    else:
        report = _numerics.summary_report(
            n=n, block_size=m, engine=engine, rel_residual=rel,
            kappa=(kappa_est if kappa_est is not None else 1.0),
            norm_a=norm_a, dtype=dtype, workload=workload)
    _numerics.observe(report)
    thresholds = None
    if policy is not None:
        from ..resilience.degrade import solve_gate_threshold

        gd = policy.gate_dtype if policy.gate_dtype is not None else dtype
        thresholds = _numerics.SpikeThresholds(
            residual=solve_gate_threshold(policy, n, gd))
    _numerics.record_spikes(report, thresholds)
    return report


def _solve_recover(policy, tel, *, a, b, x, compiled, residual, norm_a,
                   norm_x, norm_b, n, k, m, dtype, spd, workload):
    """The solve recovery ladder (the degrade.py discipline on the
    ‖A·X − B‖ gate): refine through the SAME compiled executable
    (X += A⁻¹R — one extra launch, no recompile), then under the SPD
    promise a re-solve on the pivoting engine (a broken promise is the
    one failure class refinement cannot fix), then an fp32 re-solve for
    sub-fp32 storage.  Exhausted = typed ResidualGateError."""
    from ..resilience.degrade import (_M_GATE_FAIL, _M_RUNGS,
                                      gate_passes, solve_gate_threshold)
    from ..resilience.policy import ResidualGateError

    in_dtype = jnp.dtype(dtype)
    gate_dtype = policy.gate_dtype if policy.gate_dtype is not None \
        else in_dtype
    threshold = solve_gate_threshold(policy, n, gate_dtype)
    rel = _rel(residual, norm_a, norm_x, norm_b)
    if gate_passes(rel, threshold):
        return x, residual, norm_a, norm_x, norm_b, ()

    _M_GATE_FAIL.inc()
    _recorder.record("residual_gate_failure", n=n, workload=workload,
                     rel_residual=float(rel), threshold=float(threshold))
    recovery = []

    def _judge(x2, span, rung: str, **extra):
        res2, na2, nx2, nb2 = _residual_stats(a, x2, b)
        rel2 = _rel(res2, na2, nx2, nb2)
        # A refined/re-solved X may be at a higher working precision
        # than the request; the gate stays at the SLO dtype.
        passed = gate_passes(rel2, solve_gate_threshold(policy, n,
                                                        gate_dtype))
        span.attrs.update(rel_residual=float(rel2), passed=passed)
        recovery.append({
            "rung": rung, "rel_residual_before": float(rel),
            "rel_residual_after": float(rel2), "passed": passed, **extra,
        })
        _M_RUNGS.inc(rung=rung, outcome="passed" if passed else "failed")
        _recorder.record("recovery_rung", rung=rung, workload=workload,
                         outcome="passed" if passed else "failed",
                         rel_residual=float(rel2))
        return passed, (x2, res2, na2, nx2, nb2)

    with tel.span("recover", n=n, workload=workload,
                  rel_residual=float(rel),
                  threshold=float(threshold)) as rsp:
        # ---- rung 1: refinement through the same executable ---------
        if policy.refine_steps > 0:
            with tel.span("refine", steps=1) as sp:
                work = jnp.promote_types(in_dtype, jnp.float32)
                aw = jnp.asarray(a, work)
                xw = jnp.asarray(x, work)
                r = jnp.asarray(b, work) - jnp.matmul(
                    aw, xw, precision=jax.lax.Precision.HIGHEST)
                d, dsing = compiled(a, r.astype(dtype))
                x2 = xw + jnp.asarray(d, work)
                passed, out = _judge(x2, sp, "refine")
            if passed and not bool(dsing):
                rsp.attrs["recovered_by"] = "refine"
                x2, res2, na2, nx2, nb2 = out
                return x2, res2, na2, nx2, nb2, tuple(recovery)

        # ---- rung 2: repivot (the SPD promise may be unsound) -------
        if spd:
            with tel.span("repivot") as sp:
                x3, sing3 = jax.jit(_fresh_solve_fn(n, m, False))(a, b)
                passed, out = _judge(x3, sp, "repivot")
            if passed and not bool(sing3):
                rsp.attrs["recovered_by"] = "repivot"
                x3, res3, na3, nx3, nb3 = out
                return x3, res3, na3, nx3, nb3, tuple(recovery)

        # ---- rung 3: fp32 re-solve (sub-fp32 storage only) ----------
        if policy.escalate and in_dtype.itemsize < 4:
            with tel.span("resolve") as sp:
                x4, sing4 = jax.jit(_fresh_solve_fn(n, m, spd))(
                    a.astype(jnp.float32), b.astype(jnp.float32))
                passed, out = _judge(x4, sp, "resolve",
                                     dtype=str(x4.dtype))
            if passed and not bool(sing4):
                rsp.attrs["recovered_by"] = "resolve"
                x4, res4, na4, nx4, nb4 = out
                return x4, res4, na4, nx4, nb4, tuple(recovery)

    raise ResidualGateError(
        f"solve residual gate failed (rel {rel:.3e} > {threshold:.3e}) "
        f"and the recovery ladder exhausted "
        f"({' -> '.join(r['rung'] for r in recovery) or 'no rungs'})",
        recovery=tuple(recovery))


def lstsq(
    a,
    b,
    block_size: int | None = None,
    dtype=None,
    assume: str = "spd",
    engine: str = "auto",
    tune: bool = False,
    plan_cache: str | None = None,
    telemetry=None,
    policy=None,
    numerics: str = "off",
    verbose: bool = False,
) -> LstsqResult:
    """argmin‖A·x − b‖₂ for a full-column-rank (rows, n) A via the
    normal equations (AᴴA)x = Aᴴb, routed through :func:`solve_system`.

    The Gram matrix is Hermitian positive definite exactly when A has
    full column rank, so ``assume="spd"`` (the default) makes lstsq the
    archetypal consumer of the pivot-free fast path; pass
    ``assume="general"`` to keep condition-based pivoting on the Gram
    system.  Rank deficiency is surfaced as DATA, not garbage: a
    singular Gram elimination sets ``rank_deficient=True`` with
    ``x=None``, and ``kappa_est`` carries the Gram conditioning
    (≈ κ(A)² — the normal-equations squaring; MPAX-style LP/QP loops
    that need better should pre-scale).  Complex dtypes use the
    conjugate transpose throughout.

    The known trade-off is documented, not hidden: normal equations
    square the conditioning vs an orthogonal factorization — the eps·n
    backward-error gate runs on the GRAM system, and ``residual``
    reports the original ‖A·x − b‖∞ next to it."""
    from ..driver import UsageError

    a = jnp.asarray(a) if dtype is None else jnp.asarray(a, dtype)
    dtype = a.dtype
    if a.ndim != 2:
        raise UsageError(f"expected a (rows, n) matrix, got shape "
                         f"{tuple(a.shape)}")
    rows, n = int(a.shape[0]), int(a.shape[1])
    if rows < n:
        raise UsageError(
            f"lstsq needs rows >= n (got {rows} x {n}); the "
            f"underdetermined minimum-norm problem is not implemented")
    b2, squeezed = _as_2d_rhs(b, dtype, rows, "b")
    k = int(b2.shape[1])
    _count_workload("lstsq")

    from jax import lax as _lax

    ah = a.conj().T if jnp.issubdtype(dtype, jnp.complexfloating) \
        else a.T
    gram = jnp.matmul(ah, a, precision=_lax.Precision.HIGHEST)
    rhs = jnp.matmul(ah, b2, precision=_lax.Precision.HIGHEST)

    inner = solve_system(
        gram, rhs, block_size=block_size, assume=assume, engine=engine,
        tune=tune, plan_cache=plan_cache, telemetry=telemetry,
        policy=policy, numerics=numerics, check=False, verbose=False)

    if inner.singular:
        if verbose:
            print("rank deficient (singular normal equations)")
        return LstsqResult(
            x=None, residual=float("inf"),
            normal_residual=float("inf"), rows=rows, n=n, k=k,
            rank_deficient=True, kappa_est=None, elapsed=inner.elapsed,
            engine=inner.engine, plan=inner.plan, inner=inner)

    x = inner.x
    r = jnp.matmul(a, x, precision=_lax.Precision.HIGHEST) - b2
    residual = float(jnp.max(jnp.sum(jnp.abs(r), axis=-1)))
    if verbose:
        print(f"lstsq residual: {residual:e}")
    if squeezed:
        x = x[:, 0]
    return LstsqResult(
        x=x, residual=residual, normal_residual=inner.residual,
        rows=rows, n=n, k=k, rank_deficient=False,
        kappa_est=inner.kappa_est, elapsed=inner.elapsed,
        engine=inner.engine, plan=inner.plan, inner=inner)
