"""Streaming file scatter: host memory O(n·m), never O(n²).

The reference's root rank reads ONE block-row buffer at a time and sends
it straight to its cyclic owner (read_matrix, main.cpp:242-276), so its
host high-water mark is a single strip.  The round-2 design lost that
property (io.py parsed the whole file into one n×n host array before a
full-matrix device_put); these functions restore it TPU-natively:

  * ``MatrixStripReader`` (io.py) pulls one m-row strip per call through
    the native chunked strtod stream;
  * each strip is padded/permuted host-side (O(m·N) work) and
    ``jax.device_put`` straight onto its owner device(s);
  * per-device shards are assembled ON DEVICE (``jnp.stack`` over
    committed per-strip arrays), and the global sharded array is formed
    with ``jax.make_array_from_single_device_arrays`` — no host n×n
    array ever exists.

The output formats match the host-array scatters exactly
(ring_gemm._to_identity_padded_blocks / sharded_jordan.scatter_augmented
for 1D, jordan2d.scatter_matrix_2d / scatter_augmented_2d for 2D), so
the compiled engines cannot tell the difference — asserted by tests.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..io import MatrixStripReader
from .layout import CyclicLayout, CyclicLayout2D
from .mesh import AXIS, AXIS_C, AXIS_R


def _padded_strip(reader, r: int, lay, dtype, augmented: bool,
                  storage_dtype=None) -> np.ndarray:
    """Global block-row ``r`` as a host (m, W) strip: file data in the
    top-left, identity on the padding diagonal, and (augmented only) the
    B half's identity block — the streaming unit of the scatter.

    ``storage_dtype``: sub-fp32 storage dtypes quantize A itself (the
    single-device path's semantics: the matrix being inverted IS the
    rounded one) before the fp32 upcast for computation."""
    n, m, N = lay.n, lay.m, lay.N
    W = 2 * N if augmented else N
    out = np.zeros((m, W), dtype)
    g0 = r * m
    rows = max(0, min(m, n - g0))        # file rows in this block
    if rows:
        strip = reader.read_rows(rows)
        if storage_dtype is not None:
            strip = np.asarray(jnp.asarray(strip, storage_dtype))
        out[:rows, :n] = strip
    # Identity padding rows (pad_with_identity semantics): global rows
    # g >= n carry a 1 at column g.
    for i in range(rows, m):
        out[i, g0 + i] = 1
    if augmented:
        # B half starts as I: row g carries a 1 at column N + g.
        for i in range(m):
            out[i, N + g0 + i] = 1
    return out


def _skip_strip(reader, r: int, lay) -> None:
    """Advance the stream past block-row ``r`` without building the padded
    strip (multi-process: strips owned by other processes still consume
    file tokens, but need no host buffer or identity fill)."""
    rows = max(0, min(lay.m, lay.n - r * lay.m))
    if rows:
        reader.read_rows(rows)


def stream_scatter_1d(path: str, lay: CyclicLayout, mesh: Mesh,
                      dtype=jnp.float32, augmented: bool = False,
                      storage_dtype=None):
    """File -> (Nr, m, W) cyclic-order blocks sharded over the 1D mesh,
    one strip of host memory at a time."""
    dtype = jnp.dtype(dtype)
    p, bpw = lay.p, lay.blocks_per_worker
    devices = list(mesh.devices.flat)
    # Multi-process: every process parses the whole file (the reference's
    # root rank does too, main.cpp:242-276) but places only the strips
    # owned by ITS devices; make_array assembles the global array from
    # each process's addressable shards.
    pidx = jax.process_index()
    per_dev: list[list] = [[] for _ in range(p)]
    with MatrixStripReader(path, lay.n, dtype) as reader:
        # File order is global block order; owner of block r is r % p at
        # slot r // p — appending in r-order fills slots in order.
        for r in range(lay.Nr):
            owner = lay.owner(r)
            if devices[owner].process_index != pidx:
                _skip_strip(reader, r, lay)
                continue
            strip = _padded_strip(reader, r, lay, dtype, augmented,
                                  storage_dtype)
            per_dev[owner].append(jax.device_put(strip, devices[owner]))
            del strip
    shards = [jnp.stack(strips) for strips in per_dev
              if strips]                                 # (bpw, m, W) each
    W = (2 if augmented else 1) * lay.N
    return jax.make_array_from_single_device_arrays(
        (lay.Nr, lay.m, W),
        NamedSharding(mesh, PartitionSpec(AXIS, None, None)),
        shards,
    )


def stream_scatter_2d(path: str, lay: CyclicLayout2D, mesh: Mesh,
                      dtype=jnp.float32, augmented: bool = False,
                      storage_dtype=None):
    """File -> (Nr, m, W) blocks, both axes in cyclic storage order,
    sharded over the (pr, pc) mesh, one strip of host memory at a time."""
    dtype = jnp.dtype(dtype)
    pr, pc, m = lay.pr, lay.pc, lay.m
    ncb = 2 * lay.Nr if augmented else lay.Nr
    colp = lay.col_perm(ncb)             # storage order of column blocks
    dev = mesh.devices                   # (pr, pc) array of devices
    bpr = lay.Nr // pr
    pidx = jax.process_index()           # multi-process: see stream_scatter_1d
    per_dev: list[list[list]] = [[[] for _ in range(pc)] for _ in range(pr)]
    with MatrixStripReader(path, lay.n, dtype) as reader:
        for r in range(lay.Nr):
            kr = r % pr
            if all(dev[kr][kc].process_index != pidx for kc in range(pc)):
                _skip_strip(reader, r, lay)
                continue
            strip = _padded_strip(reader, r, lay, dtype, augmented,
                                  storage_dtype)
            # Column blocks to storage order, then split into pc chunks.
            chunks = strip.reshape(m, ncb, m)[:, colp, :]
            bc = ncb // pc
            for kc in range(pc):
                if dev[kr][kc].process_index != pidx:
                    continue
                piece = np.ascontiguousarray(
                    chunks[:, kc * bc:(kc + 1) * bc, :].reshape(m, bc * m))
                per_dev[kr][kc].append(jax.device_put(piece, dev[kr][kc]))
            del strip, chunks
    shards = []
    for kr in range(pr):
        for kc in range(pc):
            if per_dev[kr][kc]:
                shards.append(jnp.stack(per_dev[kr][kc]))  # (bpr, m, W/pc)
    W = ncb * m
    return jax.make_array_from_single_device_arrays(
        (lay.Nr, lay.m, W),
        NamedSharding(mesh, PartitionSpec(AXIS_R, None, AXIS_C)),
        shards,
    )
