"""Distributed block Gauss–Jordan inversion on a 2D block-cyclic mesh.

The north-star upgrade over the reference's 1D decomposition: the
reference shards only rows and replicates every column on every rank
(len = RpP*m*n strips, main.cpp:366-370), so per-rank memory is
O(N·2N / p) regardless of p — the wall that makes 32768²+ unreachable.
Here the augmented matrix [A | B] is sharded over BOTH axes of a
(pr, pc) mesh in ScaLAPACK-style block-cyclic order: per-worker memory is
O(N·2N / (pr·pc)).

Communication per super-step t (cf. the reference's
allreduce + bcast + P2P, SURVEY.md §3.2):

  pivot probe        COLUMN-PARALLEL (round 4): the t-panel broadcast
                     along "pc" doubles as the eliminate's E, and every
                     mesh column probes the 1/pc slice of live slots
  pivot reduction    composite-key `lax.pmin` over BOTH axes
                     (replaces MPI_Op_create/PivotMin, main.cpp:1000-1074)
  pivot-row bcast    one-hot `lax.psum` along "pr" — each mesh column
                     broadcasts its own slice of the row (main.cpp:1097)
  row swap           one-hot psum of row t along "pr" + masked local write
                     (swap-by-copy, main.cpp:1100-1131)
  multiplier fix-up  one (m, m) psum along "pc" (the t-panel broadcast
                     above doubles as the eliminate's E; the fix-up
                     patches the swapped slot — no second panel psum)
  eliminate          one local (bpr·m, m) x (m, Wc) MXU matmul

Local storage on worker (kr, kc): ``(bpr, m, Wc)`` — row blocks cyclic on
axis 0 (global block gr = slot*pr + kr), columns stored as bc2 chunks of m
in cyclic column-block order on axis 2 (global column block of chunk u is
u*pc + kc).  The global storage array is (Nr, m, 2N) with both axes in
worker-major cyclic storage order, so a plain NamedSharding
P("pr", None, "pc") realises the 2D block-cyclic distribution.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from .compat import pcast, pmax, pmin, psum, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import eps_for
from ..ops.block_inverse import (
    probe_blocks as _probe,
    probe_blocks_half_masked,
)
from ..ops.norms import block_inf_norms
from .layout import CyclicLayout2D
from .mesh import AXIS_C, AXIS_R
from .upcast import upcast_sub_fp32

BOTH = (AXIS_R, AXIS_C)
_SPEC_W = PartitionSpec(AXIS_R, None, AXIS_C)


def _local_step2d(t, Wloc, singular, *, lay: CyclicLayout2D, eps, precision,
                  use_pallas: bool):
    """One super-step on one worker's (bpr, m, Wc) shard."""
    pr, pc, m = lay.pr, lay.pc, lay.m
    bpr = lay.bpr
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    gr = jnp.arange(bpr) * pr + kr          # global block row of each slot

    # --- CHUNK BROADCAST along "pc" (pre-swap): the t-column panel is
    # what the eliminate needs as E anyway, so broadcasting it BEFORE
    # the probe adds no collective bytes — and lets every mesh column
    # probe the 1/pc slice of slots ``kc, kc+pc, ...`` instead of pc−1
    # columns idling through a cond skip (the round-4 column-parallel
    # probe, same design as jordan2d_inplace._step2d).
    own_c = kc == (t % pc)
    u_t = t // pc
    chunk = lax.dynamic_slice(Wloc, (0, 0, u_t * m), (bpr, m, m))
    chunk_all = psum(
        jnp.where(own_c, chunk, jnp.asarray(0, dtype)), AXIS_C)

    probe_dtype = jnp.float32 if jnp.dtype(dtype).itemsize < 4 else dtype
    wnd = -(-bpr // pc)                         # static slice length
    idx = kc + jnp.arange(wnd) * pc             # local slots probed here
    cands = jnp.take(chunk_all, jnp.clip(idx, 0, bpr - 1),
                     axis=0).astype(probe_dtype)
    gidx = idx * pr + kr                        # global block rows probed

    # Half-window cut via the shared traced-t helper (safety condition
    # pinned by test_jordan2d_inplace.py::test_fori_half_cut_condition_is_safe).
    invs, sing = probe_blocks_half_masked(
        cands, t >= (wnd // 2) * pc * pr, eps, use_pallas)

    inv_norms = block_inf_norms(invs)
    valid = (idx < bpr) & (gidx >= t) & ~sing
    big = jnp.asarray(jnp.inf, probe_dtype)
    key = jnp.where(valid, inv_norms.astype(probe_dtype), big)
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]
    g_cand = gidx[slot_best]

    # --- PIVOT REDUCTION over the whole mesh; ties to lowest global row
    # (same rule as the 1D and single-device paths).
    kmin = pmin(my_key, BOTH)
    win_g = pmin(
        jnp.where(my_key == kmin, g_cand, lay.Nr), BOTH
    )
    singular = singular | ~jnp.isfinite(kmin)   # all-singular agreement
    i_won = (my_key == kmin) & (g_cand == win_g)
    g_piv = psum(jnp.where(i_won, g_cand, 0), BOTH)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0), BOTH
    ).astype(dtype)

    # --- ROW BROADCASTS along "pr": each mesh column shares its slice of
    # the pivot row and of row t (one-hot psums riding ICI).
    own_piv = kr == (g_piv % pr)
    slot_piv = jnp.where(own_piv, g_piv // pr, 0)
    row_piv = psum(
        jnp.where(own_piv,
                  lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False), 0.0),
        AXIS_R,
    )                                           # (m, Wc)
    own_t = kr == (t % pr)
    slot_t = t // pr
    row_t = psum(
        jnp.where(own_t,
                  lax.dynamic_index_in_dim(Wloc, slot_t, 0, False), 0.0),
        AXIS_R,
    )                                           # (m, Wc)

    # --- SWAP-BY-COPY (main.cpp:1093-1131): pivot owner's slot receives
    # the old row t; slot t is rewritten from the normalized pivot row.
    W_swap = lax.dynamic_update_index_in_dim(Wloc, row_t, slot_piv, 0)
    Wloc = jnp.where(own_piv, W_swap, Wloc)

    # --- NORMALIZE: one (m, m) x (m, Wc) matmul per worker.
    prow = jnp.matmul(H, row_piv, precision=precision)

    # --- MULTIPLIERS from the pre-swap broadcast + swap fix-up (see
    # jordan2d_inplace._step2d): the slot that received old row t in the
    # swap gets row_t's t-chunk via one extra (m, m) psum; the slot now
    # holding global row t is zeroed (its multiplier is the prow write).
    row_t_chunk = psum(
        jnp.where(own_c,
                  lax.dynamic_slice(row_t, (0, u_t * m), (m, m)), 0.0),
        AXIS_C,
    ).astype(dtype)                             # (m, m)
    cur_Epiv = lax.dynamic_index_in_dim(chunk_all, slot_piv, 0, False)
    E = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_piv, row_t_chunk, cur_Epiv), slot_piv, 0
    )
    E = jnp.where((gr == t)[:, None, None], jnp.asarray(0, dtype), E)

    # --- ELIMINATE: one local MXU matmul over the whole shard.
    update = jnp.matmul(E.reshape(bpr * m, m), prow, precision=precision)
    Wloc = Wloc - update.reshape(Wloc.shape)

    # Row t becomes the normalized pivot row (owning mesh row only).
    W_set = lax.dynamic_update_index_in_dim(Wloc, prow, slot_t, 0)
    Wloc = jnp.where(own_t, W_set, Wloc)
    return Wloc, singular


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas"))
def _sharded_jordan2d(W, mesh, lay: CyclicLayout2D, eps, precision,
                      use_pallas):
    def worker(Wloc):
        def body(t, carry):
            Wl, sing = carry
            return _local_step2d(t, Wl, sing, lay=lay, eps=eps,
                                 precision=precision, use_pallas=use_pallas)

        sing0 = pcast(jnp.zeros((1, 1), jnp.bool_), BOTH, to='varying')
        Wl, sing = lax.fori_loop(0, lay.Nr, body, (Wloc, sing0))
        return Wl, sing

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=_SPEC_W,
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C)),
    )(W)


# --- front ends -----------------------------------------------------------


def _perms(lay: CyclicLayout2D, ncb: int):
    rowp = jnp.asarray(lay.row_perm(), jnp.int32)
    colp = jnp.asarray(lay.col_perm(ncb), jnp.int32)
    return rowp, colp


def _inv_perm(p):
    inv = jnp.zeros_like(p)
    return inv.at[p].set(jnp.arange(p.shape[0], dtype=p.dtype))


def scatter_augmented_2d(a: jnp.ndarray, lay: CyclicLayout2D, mesh: Mesh):
    """Host path: build padded [A | I], reorder both axes to cyclic storage
    order, shard over the 2D mesh."""
    from ..ops.padding import pad_with_identity

    N = lay.N
    A = pad_with_identity(a, N)
    W = jnp.concatenate([A, jnp.eye(N, dtype=a.dtype)], axis=1)  # (N, 2N)
    blocks = W.reshape(lay.Nr, lay.m, 2 * lay.Nr, lay.m)
    rowp, colp = _perms(lay, 2 * lay.Nr)
    blocks = jnp.take(jnp.take(blocks, rowp, axis=0), colp, axis=2)
    W2 = blocks.reshape(lay.Nr, lay.m, 2 * N)
    return jax.device_put(W2, NamedSharding(mesh, _SPEC_W))


def scatter_matrix_2d(a: jnp.ndarray, lay: CyclicLayout2D, mesh: Mesh):
    """Host path for an unaugmented N-wide operand (e.g. the residual's A):
    identity-pad, reorder both axes to cyclic storage, shard."""
    from ..ops.padding import pad_with_identity

    blocks = pad_with_identity(a, lay.N).reshape(
        lay.Nr, lay.m, lay.Nr, lay.m
    )
    rowp, colp = _perms(lay, lay.Nr)
    blocks = jnp.take(jnp.take(blocks, rowp, axis=0), colp, axis=2)
    return jax.device_put(
        blocks.reshape(lay.Nr, lay.m, lay.N), NamedSharding(mesh, _SPEC_W)
    )


def gather_inverse_2d(out: jnp.ndarray, lay: CyclicLayout2D, n: int):
    """Cyclic storage order (both axes) -> natural order; slice out A⁻¹."""
    from ..ops.padding import unpad

    blocks = out.reshape(lay.Nr, lay.m, 2 * lay.Nr, lay.m)
    rowp, colp = _perms(lay, 2 * lay.Nr)
    blocks = jnp.take(jnp.take(blocks, _inv_perm(rowp), axis=0),
                      _inv_perm(colp), axis=2)
    W = blocks.reshape(lay.N, 2 * lay.N)
    return unpad(W[:, lay.N:], n)


@partial(jax.jit, static_argnames=("fn_name", "lay", "mesh", "dtype",
                                   "augmented"))
def sharded_generate_2d(fn_name: str, lay: CyclicLayout2D, mesh: Mesh,
                        dtype=jnp.float32, augmented: bool = True):
    """Each worker generates its own 2D-cyclic shard of padded A (or of
    [A | I]) from global indices — init_matrix parity (main.cpp:128-149)
    with zero host memory and zero communication."""
    from ..ops.generators import GENERATORS

    fn = GENERATORS[fn_name]
    n, m, N = lay.n, lay.m, lay.N
    ncb = 2 * lay.Nr if augmented else lay.Nr
    bc = ncb // lay.pc

    def worker():
        kr = lax.axis_index(AXIS_R)
        kc = lax.axis_index(AXIS_C)
        gi = ((jnp.arange(lay.bpr) * lay.pr + kr)[:, None] * m
              + jnp.arange(m)[None, :])[:, :, None, None]   # (bpr, m, 1, 1)
        gcb = jnp.arange(bc) * lay.pc + kc                  # global col blocks
        gj = (gcb[:, None] * m + jnp.arange(m)[None, :])[None, None, :, :]
        eye_a = (gi == gj).astype(dtype)
        vals = jnp.broadcast_to(fn(gi, gj), eye_a.shape).astype(dtype)
        a_part = jnp.where((gi < n) & (gj < n), vals, eye_a)
        if augmented:
            eye_b = (gi == (gj - N)).astype(dtype)
            a_part = jnp.where(gj < N, a_part, eye_b)
        return a_part.reshape(lay.bpr, m, bc * m)

    return shard_map(
        worker, mesh=mesh, in_specs=(), out_specs=_SPEC_W,
    )()


@partial(jax.jit, static_argnames=("lay", "mesh"))
def split_inverse_blocks_2d(out: jnp.ndarray, lay: CyclicLayout2D,
                            mesh: Mesh):
    """The B half of the augmented result, still 2D-sharded.

    Nr is a multiple of pc, so every worker's B-part chunks are exactly the
    last bc1 chunks of its local storage — a local slice, no resharding.
    """
    def worker(Wloc):
        return Wloc[:, :, lay.bc1 * lay.m:]

    return shard_map(
        worker, mesh=mesh, in_specs=_SPEC_W, out_specs=_SPEC_W,
    )(out)


# --- SUMMA residual -------------------------------------------------------


def _summa_residual_worker(a_loc, b_loc, *, lay: CyclicLayout2D, precision):
    """Local part of ‖A·B − I‖∞ on the 2D layout via SUMMA: at step k the
    owner mesh column broadcasts A's k-panel along "pc" and the owner mesh
    row broadcasts B's k-panel along "pr"; one local matmul accumulates.
    Row sums are psum'd along "pc" (rows are split across mesh columns),
    then max-reduced — only a scalar leaves the mesh."""
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    wc = b_loc.shape[-1]

    def body(kb, d):
        own_ac = kc == (kb % pc)
        u = kb // pc
        a_panel = lax.dynamic_slice(a_loc, (0, 0, u * m), (bpr, m, m))
        a_panel = psum(jnp.where(own_ac, a_panel, 0.0), AXIS_C)
        own_br = kr == (kb % pr)
        s = kb // pr
        b_panel = psum(
            jnp.where(own_br,
                      lax.dynamic_index_in_dim(b_loc, s, 0, False), 0.0),
            AXIS_R,
        )                                               # (m, wc)
        upd = jnp.matmul(a_panel.reshape(bpr * m, m), b_panel,
                         precision=precision)
        return d + upd.reshape(bpr, m, wc)

    d0 = pcast(jnp.zeros((bpr, m, wc), a_loc.dtype), BOTH, to='varying')
    d = lax.fori_loop(0, lay.Nr, body, d0)
    # minus_i on the 2D-cyclic local indices.
    gi = ((jnp.arange(bpr) * pr + kr)[:, None] * m
          + jnp.arange(m)[None, :])[:, :, None]          # (bpr, m, 1)
    gcb = jnp.arange(wc // m) * pc + kc
    gj = (gcb[:, None] * m + jnp.arange(m)[None, :]).reshape(-1)[None, None, :]
    d = d - (gi == gj).astype(d.dtype)
    rowsum = psum(jnp.sum(jnp.abs(d), axis=2), AXIS_C)   # full row sums
    return pmax(jnp.max(rowsum), BOTH)[None, None]


@partial(jax.jit, static_argnames=("mesh", "lay", "precision"))
def distributed_residual_2d(a_blocks, inv_blocks, mesh, lay: CyclicLayout2D,
                            precision=lax.Precision.HIGHEST):
    """‖A·A⁻¹ − I‖∞ from 2D-cyclic block operands (identity-padded), fully
    distributed (SUMMA + pmax; reference analog main.cpp:490-513)."""
    out = shard_map(
        partial(_summa_residual_worker, lay=lay, precision=precision),
        mesh=mesh,
        in_specs=(_SPEC_W, _SPEC_W),
        out_specs=PartitionSpec(AXIS_R, AXIS_C),
    )(a_blocks, inv_blocks)
    return jnp.max(out)


# --- public API -----------------------------------------------------------


def resolve_use_pallas_2d(dtype, block_size: int) -> bool:
    from .sharded_jordan import resolve_use_pallas

    return resolve_use_pallas(dtype, block_size)


def compile_sharded_jordan_2d(
    W: jnp.ndarray,
    mesh: Mesh,
    lay: CyclicLayout2D,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
):
    """AOT-compile the 2D elimination; ``run(W) -> (out, singular_grid)``."""
    dtype = W.dtype
    if eps is None:
        probe_dt = jnp.float32 if jnp.dtype(dtype).itemsize < 4 else dtype
        eps = eps_for(probe_dt)
    if use_pallas is None:
        use_pallas = resolve_use_pallas_2d(dtype, lay.m)
    return _sharded_jordan2d.lower(
        W, mesh, lay, eps, precision, use_pallas
    ).compile()


@upcast_sub_fp32
def sharded_jordan_invert_2d(
    a: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
):
    """Invert (n, n) ``a`` over a 2D (pr, pc) mesh; returns (inv, singular).

    The 2D counterpart of ``sharded_jordan_invert``; same semantics
    (condition-based pivoting, collective singularity agreement), but both
    matrix axes are sharded so per-worker memory scales with 1/(pr·pc).
    """
    n = a.shape[-1]
    pr, pc = mesh.devices.shape
    lay = CyclicLayout2D.create(n, min(block_size, n), pr, pc)
    W = scatter_augmented_2d(a, lay, mesh)
    run = compile_sharded_jordan_2d(W, mesh, lay, eps, precision, use_pallas)
    out, singular = run(W)
    return gather_inverse_2d(out, lay, n), singular.any()
