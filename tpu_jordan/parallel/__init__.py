from .layout import (
    CyclicLayout,
    cyclic_gather_perm,
    cyclic_scatter_perm,
    find_sender,
    global_block_owner,
    global_to_local_block,
    last_block_height,
    local_to_global,
    num_block_rows,
    padded_num_blocks,
    rows_per_worker,
)

__all__ = [
    "CyclicLayout",
    "cyclic_gather_perm",
    "cyclic_scatter_perm",
    "find_sender",
    "global_block_owner",
    "global_to_local_block",
    "last_block_height",
    "local_to_global",
    "num_block_rows",
    "padded_num_blocks",
    "rows_per_worker",
]
