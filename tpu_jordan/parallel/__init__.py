from .generate import sharded_generate
from .mesh import (
    AXIS,
    MeshSizeError,
    block_sharding,
    distributed_init,
    make_mesh,
    replicated,
)
from .ring_gemm import (
    distributed_residual,
    distributed_residual_blocks,
    ring_matmul,
)
from .sharded_jordan import sharded_jordan_invert
from .layout import (
    CyclicLayout,
    cyclic_gather_perm,
    cyclic_scatter_perm,
    find_sender,
    global_block_owner,
    global_to_local_block,
    last_block_height,
    local_to_global,
    num_block_rows,
    padded_num_blocks,
    rows_per_worker,
)

__all__ = [
    "AXIS",
    "CyclicLayout",
    "MeshSizeError",
    "block_sharding",
    "distributed_init",
    "distributed_residual",
    "distributed_residual_blocks",
    "make_mesh",
    "replicated",
    "ring_matmul",
    "sharded_generate",
    "sharded_jordan_invert",
    "cyclic_gather_perm",
    "cyclic_scatter_perm",
    "find_sender",
    "global_block_owner",
    "global_to_local_block",
    "last_block_height",
    "local_to_global",
    "num_block_rows",
    "padded_num_blocks",
    "rows_per_worker",
]
