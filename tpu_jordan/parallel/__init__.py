from .generate import sharded_generate
from .jordan2d import (
    distributed_residual_2d,
    sharded_generate_2d,
    sharded_jordan_invert_2d,
)
from .mesh import (
    AXIS,
    AXIS_C,
    AXIS_R,
    MeshSizeError,
    block_sharding,
    distributed_init,
    make_mesh,
    make_mesh_2d,
    replicated,
)
from .ring_gemm import (
    distributed_residual,
    distributed_residual_blocks,
    ring_matmul,
)
from .jordan2d_inplace import sharded_jordan_invert_inplace_2d
from .sharded_inplace import sharded_jordan_invert_inplace
from .sharded_jordan import sharded_jordan_invert
from .layout import (
    CyclicLayout,
    CyclicLayout2D,
    cyclic_gather_perm,
    cyclic_scatter_perm,
    find_sender,
    global_block_owner,
    global_to_local_block,
    last_block_height,
    local_to_global,
    num_block_rows,
    padded_num_blocks,
    rows_per_worker,
)

__all__ = [
    "AXIS",
    "AXIS_C",
    "AXIS_R",
    "CyclicLayout",
    "CyclicLayout2D",
    "MeshSizeError",
    "block_sharding",
    "distributed_init",
    "distributed_residual",
    "distributed_residual_2d",
    "distributed_residual_blocks",
    "make_mesh",
    "make_mesh_2d",
    "replicated",
    "ring_matmul",
    "sharded_generate",
    "sharded_generate_2d",
    "sharded_jordan_invert",
    "sharded_jordan_invert_2d",
    "sharded_jordan_invert_inplace",
    "sharded_jordan_invert_inplace_2d",
    "cyclic_gather_perm",
    "cyclic_scatter_perm",
    "find_sender",
    "global_block_owner",
    "global_to_local_block",
    "last_block_height",
    "local_to_global",
    "num_block_rows",
    "padded_num_blocks",
    "rows_per_worker",
]
