"""Shared sub-fp32 storage-dtype policy for the sharded front ends.

Sub-fp32 (bf16/fp16) elimination state is measured divergent
(benchmarks/PHASES.md), so every public invert entry computes in fp32 and
rounds ONCE at the end — the same policy as the single-device kernels
(ops/jordan.py).  This decorator applies it uniformly so the four sharded
front ends cannot drift apart.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp


def upcast_sub_fp32(fn):
    """Wrap an ``(a, ...) -> (inv, singular)`` invert entry: sub-fp32
    inputs are upcast to fp32 for the elimination and the result rounded
    back to the storage dtype."""

    @functools.wraps(fn)
    def wrapper(a, *args, **kwargs):
        in_dtype = a.dtype
        if jnp.dtype(in_dtype).itemsize < 4:
            inv, singular = fn(a.astype(jnp.float32), *args, **kwargs)
            return inv.astype(in_dtype), singular
        return fn(a, *args, **kwargs)

    return wrapper
