"""Systolic ring GEMM over the mesh: d = a @ b with row-sharded operands.

TPU-native rebuild of `matrix_mult_matrix` (main.cpp:534-641): the
reference rotates the B row-panel through all p ranks in p steps
(`MPI_Sendrecv_replace`, main.cpp:639), each step multiplying the local A
columns that correspond to the currently-held panel's global rows
(cyclic column pick, main.cpp:583).  Here the rotation is `lax.ppermute`
over the ICI ring — structurally the same rotate-and-accumulate pattern as
ring attention — and the per-step product is one MXU matmul.

Kept as an *independent* code path from the inversion so the residual check
never shares kernels with what it verifies (the reference's design,
main.cpp:490-513).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .layout import CyclicLayout, cyclic_gather_perm, cyclic_scatter_perm
from .mesh import AXIS


def _ring_worker(a_loc, b_loc, *, lay: CyclicLayout, precision):
    """a_loc, b_loc: (bpw, m, N) local cyclic blocks; returns d_loc."""
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    k = lax.axis_index(AXIS)
    rows = bpw * m
    a2 = a_loc.reshape(rows, N)

    def body(step, carry):
        d, buf = carry
        whose = (k + step) % p
        # Columns of A that multiply the held panel: global rows of worker
        # `whose` under the cyclic layout = blocks {s*p + whose}
        # (the reference's bl_ind_a pick, main.cpp:583).
        col_blocks = jnp.arange(bpw) * p + whose            # (bpw,)
        cols = (col_blocks[:, None] * m + jnp.arange(m)[None, :]).reshape(-1)
        a_cols = jnp.take(a2, cols, axis=1)                 # (rows, bpw*m)
        d = d + jnp.matmul(
            a_cols, buf.reshape(bpw * m, N), precision=precision
        )
        # Ring rotate: receive from (k+1)%p, send to (k-1+p)%p
        # (main.cpp:564-565, 639).
        perm = [(i, (i - 1 + p) % p) for i in range(p)]
        buf = lax.ppermute(buf, AXIS, perm)
        return d, buf

    # pcast-to-varying: the accumulator is device-varying from step one (it mixes the
    # local shard), so its initial value must carry the same vma type.
    d0 = lax.pcast(jnp.zeros((rows, N), a_loc.dtype), AXIS, to='varying')
    d, _ = lax.fori_loop(0, lay.p, body, (d0, b_loc))
    return d.reshape(bpw, m, N)


@partial(jax.jit, static_argnames=("mesh", "lay", "precision"))
def _ring_gemm_blocks(a_blocks, b_blocks, mesh, lay, precision):
    spec = PartitionSpec(AXIS, None, None)
    return shard_map(
        partial(_ring_worker, lay=lay, precision=precision),
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=spec,
    )(a_blocks, b_blocks)


def _to_cyclic_blocks(x, lay: CyclicLayout, mesh: Mesh):
    N = lay.N
    xp = x
    if x.shape[-1] != N:
        xp = jnp.zeros((N, N), x.dtype).at[: x.shape[0], : x.shape[1]].set(x)
    blocks = xp.reshape(lay.Nr, lay.m, N)
    blocks = jnp.take(blocks, cyclic_gather_perm(lay), axis=0)
    return jax.device_put(
        blocks, NamedSharding(mesh, PartitionSpec(AXIS, None, None))
    )


def ring_matmul(
    a: jnp.ndarray,
    b: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """d = a @ b via the distributed systolic ring (main.cpp:534-641)."""
    n = a.shape[0]
    lay = CyclicLayout.create(n, block_size, mesh.devices.size)
    a_b = _to_cyclic_blocks(a, lay, mesh)
    b_b = _to_cyclic_blocks(b, lay, mesh)
    d = _ring_gemm_blocks(a_b, b_b, mesh, lay, precision)
    d = jnp.take(d, cyclic_scatter_perm(lay), axis=0)
    return d.reshape(lay.N, lay.N)[:n, :n]


def distributed_residual(
    a: jnp.ndarray,
    a_inv: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """‖A·A⁻¹ − I‖∞ with the ring GEMM + minus_i + max-reduce
    (main.cpp:490-513, minus_i main.cpp:1206-1224, norm main.cpp:643-667)."""
    from ..ops.norms import inf_norm

    n = a.shape[-1]
    d = ring_matmul(a, a_inv, mesh, block_size, precision)
    return inf_norm(d - jnp.eye(n, dtype=d.dtype))
