"""Systolic ring GEMM over the mesh: d = a @ b with row-sharded operands.

TPU-native rebuild of `matrix_mult_matrix` (main.cpp:534-641): the
reference rotates the B row-panel through all p ranks in p steps
(`MPI_Sendrecv_replace`, main.cpp:639), each step multiplying the local A
columns that correspond to the currently-held panel's global rows
(cyclic column pick, main.cpp:583).  Here the rotation is `lax.ppermute`
over the ICI ring — structurally the same rotate-and-accumulate pattern as
ring attention — and the per-step product is one MXU matmul.

Kept as an *independent* code path from the inversion so the residual check
never shares kernels with what it verifies (the reference's design,
main.cpp:490-513).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from .compat import pcast, pmax, ppermute, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .layout import CyclicLayout, cyclic_gather_perm, cyclic_scatter_perm
from .mesh import AXIS


def _ring_worker(a_loc, b_loc, *, lay: CyclicLayout, precision):
    """a_loc, b_loc: (bpw, m, N) local cyclic blocks; returns d_loc."""
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    k = lax.axis_index(AXIS)
    rows = bpw * m
    a2 = a_loc.reshape(rows, N)

    def body(step, carry):
        d, buf = carry
        whose = (k + step) % p
        # Columns of A that multiply the held panel: global rows of worker
        # `whose` under the cyclic layout = blocks {s*p + whose}
        # (the reference's bl_ind_a pick, main.cpp:583).
        col_blocks = jnp.arange(bpw) * p + whose            # (bpw,)
        cols = (col_blocks[:, None] * m + jnp.arange(m)[None, :]).reshape(-1)
        a_cols = jnp.take(a2, cols, axis=1)                 # (rows, bpw*m)
        d = d + jnp.matmul(
            a_cols, buf.reshape(bpw * m, N), precision=precision
        )
        # Ring rotate: receive from (k+1)%p, send to (k-1+p)%p
        # (main.cpp:564-565, 639).
        perm = [(i, (i - 1 + p) % p) for i in range(p)]
        buf = ppermute(buf, AXIS, perm)
        return d, buf

    # pcast-to-varying: the accumulator is device-varying from step one (it mixes the
    # local shard), so its initial value must carry the same vma type.
    d0 = pcast(jnp.zeros((rows, N), a_loc.dtype), AXIS, to='varying')
    d, _ = lax.fori_loop(0, lay.p, body, (d0, b_loc))
    return d.reshape(bpw, m, N)


@partial(jax.jit, static_argnames=("mesh", "lay", "precision"))
def _ring_gemm_blocks(a_blocks, b_blocks, mesh, lay, precision):
    spec = PartitionSpec(AXIS, None, None)
    return shard_map(
        partial(_ring_worker, lay=lay, precision=precision),
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=spec,
    )(a_blocks, b_blocks)


def _ring_residual_worker(a_loc, b_loc, *, lay: CyclicLayout, precision):
    """Local part of ‖A·B − I‖∞: ring-GEMM rows, subtract I, row-sum max.

    The reference keeps the residual local and MAX-allreduces one scalar
    (main.cpp:504-505); same here — nothing n×n is ever replicated.
    """
    p, m, bpw = lay.p, lay.m, lay.blocks_per_worker
    k = lax.axis_index(AXIS)
    d = _ring_worker(a_loc, b_loc, lay=lay, precision=precision)
    # minus_i with cyclic-aware indexing (main.cpp:1206-1224): this
    # worker's local row (slot, r) is global row (slot*p + k)*m + r.
    gi = ((jnp.arange(bpw) * p + k)[:, None] * m
          + jnp.arange(m)[None, :])[:, :, None]          # (bpw, m, 1)
    gj = jnp.arange(lay.N)[None, None, :]
    d = d - (gi == gj).astype(d.dtype)
    local = jnp.max(jnp.sum(jnp.abs(d), axis=2))          # local ∞-norm part
    return pmax(local, AXIS)[None]                    # (1,) per worker


@partial(jax.jit, static_argnames=("mesh", "lay", "precision"))
def _residual_blocks(a_blocks, b_blocks, mesh, lay, precision):
    spec = PartitionSpec(AXIS, None, None)
    out = shard_map(
        partial(_ring_residual_worker, lay=lay, precision=precision),
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=PartitionSpec(AXIS),
    )(a_blocks, b_blocks)
    return jnp.max(out)


def distributed_residual_blocks(
    a_blocks: jnp.ndarray,
    inv_blocks: jnp.ndarray,
    mesh: Mesh,
    lay: CyclicLayout,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """‖A·A⁻¹ − I‖∞ from cyclic block operands, fully distributed.

    Both operands must be identity-padded (the solve/generate convention:
    the padded tail of A and of A⁻¹ is I, so the padded product's tail is
    exactly I and contributes zero residual).  Output is a scalar — the
    only thing that ever leaves the mesh.
    """
    return _residual_blocks(a_blocks, inv_blocks, mesh, lay, precision)


def _shard_cyclic(xp, lay: CyclicLayout, mesh: Mesh):
    """(N, N) padded array -> cyclic-order blocks sharded over the mesh."""
    blocks = xp.reshape(lay.Nr, lay.m, lay.N)
    blocks = jnp.take(blocks, cyclic_gather_perm(lay), axis=0)
    return jax.device_put(
        blocks, NamedSharding(mesh, PartitionSpec(AXIS, None, None))
    )


def _to_cyclic_blocks(x, lay: CyclicLayout, mesh: Mesh):
    N = lay.N
    xp = x
    if x.shape[-1] != N:
        xp = jnp.zeros((N, N), x.dtype).at[: x.shape[0], : x.shape[1]].set(x)
    return _shard_cyclic(xp, lay, mesh)


def ring_matmul(
    a: jnp.ndarray,
    b: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """d = a @ b via the distributed systolic ring (main.cpp:534-641)."""
    n = a.shape[0]
    lay = CyclicLayout.create(n, block_size, mesh.devices.size)
    a_b = _to_cyclic_blocks(a, lay, mesh)
    b_b = _to_cyclic_blocks(b, lay, mesh)
    d = _ring_gemm_blocks(a_b, b_b, mesh, lay, precision)
    d = jnp.take(d, cyclic_scatter_perm(lay), axis=0)
    return d.reshape(lay.N, lay.N)[:n, :n]


def _to_identity_padded_blocks(x, lay: CyclicLayout, mesh: Mesh):
    """Host-array front end for the residual: identity-pad to N, reorder to
    cyclic storage, shard."""
    from ..ops.padding import pad_with_identity

    return _shard_cyclic(pad_with_identity(x, lay.N), lay, mesh)


def distributed_residual(
    a: jnp.ndarray,
    a_inv: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """‖A·A⁻¹ − I‖∞ with the ring GEMM + minus_i + max-reduce
    (main.cpp:490-513, minus_i main.cpp:1206-1224, norm main.cpp:643-667).

    Convenience wrapper over ``distributed_residual_blocks`` for host-side
    operands; the residual itself never materializes anything n×n."""
    lay = CyclicLayout.create(a.shape[-1], block_size, mesh.devices.size)
    a_b = _to_identity_padded_blocks(a, lay, mesh)
    inv_b = _to_identity_padded_blocks(a_inv, lay, mesh)
    return distributed_residual_blocks(a_b, inv_b, mesh, lay, precision)
