"""1D row-block-cyclic layout math.

Pure index arithmetic reproducing the reference's data decomposition
(rows_p_process main.cpp:95-116, local_to_global main.cpp:118-123,
num_block_rows main.cpp:124-127, find_sender main.cpp:521-532): global block
row ``r`` lives on worker ``r % p`` at local slot ``r // p``; columns are
fully replicated per worker.

Everything here is host-side Python (shapes/sharding are static under jit),
plus a few jnp helpers usable inside traced code.

The ragged last block of the reference (height ``l = n - m*(Nr-1)``,
main.cpp:133-137) is handled in this framework by *padding*: we extend A to
``N = Nr_pad * m`` with an identity tail, which inverts to an identity tail
(see pad_with_identity in ops/padding.py), so no ragged index math survives
into the device code.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp


def num_block_rows(n: int, m: int) -> int:
    """ceil(n / m) — number of block rows (num_block_rows, main.cpp:124-127)."""
    return -(-n // m)


def rows_per_worker(Nr: int, p: int, k: int) -> int:
    """Block rows owned by worker ``k`` of ``p`` under the cyclic layout.

    Parity with rows_p_process (main.cpp:95-116): worker k owns global block
    rows {k, k+p, k+2p, ...} below Nr.
    """
    if not 0 <= k < p:
        raise ValueError(f"worker {k} out of range for p={p}")
    return (Nr - k + p - 1) // p if Nr > k else 0


def local_to_global(i: int, m: int, p: int, k: int) -> int:
    """Local row index -> global row index (local_to_global, main.cpp:118-123).

    ``gi = ((i // m) * p + k) * m + i % m``: local block ``i // m`` on worker
    ``k`` is global block ``(i // m) * p + k``.
    """
    return ((i // m) * p + k) * m + i % m


def global_block_owner(r: int, p: int) -> int:
    """Worker owning global block row ``r`` (main.cpp:244: ``i % p``)."""
    return r % p


def global_to_local_block(r: int, p: int) -> int:
    """Local slot of global block row ``r`` on its owner (main.cpp:245)."""
    return r // p


def find_sender(Nr: int, p: int) -> int:
    """Worker owning the last block row; doubles as the file-I/O root
    (find_sender, main.cpp:521-532): ``(Nr - 1) % p``."""
    return (Nr - 1) % p


def last_block_height(n: int, m: int) -> int:
    """Height of the ragged last block row, ``l = n - m*(Nr-1)``
    (main.cpp:133-137)."""
    return n - m * (num_block_rows(n, m) - 1)


def padded_num_blocks(n: int, m: int, p: int = 1) -> int:
    """Smallest block count >= ceil(n/m) that is a multiple of ``p``.

    Padding both the ragged tail and the worker count means every worker owns
    exactly ``Nr_pad // p`` full m-row blocks — the device code never sees a
    ragged shape.
    """
    Nr = num_block_rows(n, m)
    return -(-Nr // p) * p


@dataclass(frozen=True)
class CyclicLayout:
    """Static description of one padded row-block-cyclic distribution."""

    n: int          # original matrix dimension
    m: int          # block size
    p: int          # number of workers (mesh axis size)
    Nr: int         # padded block-row count (multiple of p)

    @classmethod
    def create(cls, n: int, m: int, p: int = 1) -> "CyclicLayout":
        return cls(n=n, m=m, p=p, Nr=padded_num_blocks(n, m, p))

    @property
    def N(self) -> int:
        """Padded matrix dimension."""
        return self.Nr * self.m

    @property
    def blocks_per_worker(self) -> int:
        return self.Nr // self.p

    def owner(self, r: int) -> int:
        return global_block_owner(r, self.p)

    def local_slot(self, r: int) -> int:
        return global_to_local_block(r, self.p)

    def global_block(self, k: int, slot: int) -> int:
        """Inverse of (owner, local_slot): worker k's slot -> global block."""
        return slot * self.p + k

    def cyclic_block_order(self):
        """Global block indices in storage order (worker-major, slot-minor).

        Storing blocks in this order makes the cyclic layout a *contiguous*
        shard per worker, so a plain NamedSharding over axis 0 realises the
        reference's cyclic distribution.
        """
        return [self.global_block(k, s)
                for k in range(self.p)
                for s in range(self.blocks_per_worker)]


@dataclass(frozen=True)
class CyclicLayout2D:
    """2D block-cyclic distribution over a (pr, pc) mesh — the ScaLAPACK
    layout the 1D design can't reach: rows AND columns of the augmented
    matrix are sharded, so per-worker memory is O(N·2N/(pr·pc)) instead of
    the reference's full-width strips (main.cpp:366-370, the memory wall).

    Block (i, j) lives on worker (i % pr, j % pc) at local slot
    (i // pr, j // pc).  Local storage is (bpr, m, Wc): row blocks
    worker-cyclic on axis 0, columns stored as bc2 chunks of m in cyclic
    column-block order on axis 2 (local chunk u ↔ global column block
    u*pc + kc).
    """

    n: int           # original matrix dimension
    m: int           # block size
    pr: int          # mesh rows
    pc: int          # mesh cols
    Nr: int          # padded block-row count (multiple of lcm(pr, pc))

    @classmethod
    def create(cls, n: int, m: int, pr: int, pc: int) -> "CyclicLayout2D":
        Nr = num_block_rows(n, m)
        g = math.lcm(pr, pc)
        return cls(n=n, m=m, pr=pr, pc=pc, Nr=-(-Nr // g) * g)

    @property
    def N(self) -> int:
        return self.Nr * self.m

    @property
    def bpr(self) -> int:
        """Row blocks per worker."""
        return self.Nr // self.pr

    @property
    def bc2(self) -> int:
        """Augmented ([A|B]) column-block chunks per worker."""
        return 2 * self.Nr // self.pc

    @property
    def bc1(self) -> int:
        """Column-block chunks per worker for an unaugmented N-wide matrix."""
        return self.Nr // self.pc

    def col_perm(self, nblocks: int):
        """Storage order of column blocks: worker-major, slot-minor."""
        bpw = nblocks // self.pc
        return [s * self.pc + kc for kc in range(self.pc) for s in range(bpw)]

    def row_perm(self):
        bpw = self.Nr // self.pr
        return [s * self.pr + kr for kr in range(self.pr) for s in range(bpw)]


def cyclic_gather_perm(layout: CyclicLayout) -> jnp.ndarray:
    """Permutation taking natural block order -> cyclic storage order."""
    return jnp.asarray(layout.cyclic_block_order(), dtype=jnp.int32)


def cyclic_scatter_perm(layout: CyclicLayout) -> jnp.ndarray:
    """Inverse permutation: cyclic storage order -> natural block order."""
    order = layout.cyclic_block_order()
    inv = [0] * len(order)
    for pos, r in enumerate(order):
        inv[r] = pos
    return jnp.asarray(inv, dtype=jnp.int32)
