"""Bucketed ``ppermute`` permutation over a cyclic mesh axis.

The swap-free engines defer the pivot row permutation to ONE exchange
after the elimination loop.  Implementing that exchange as a
data-dependent ``jnp.take`` over the sharded axis makes XLA all-gather
the whole operand — a transient full-N² buffer per worker (4 GB at
n=32768 fp32), which is exactly the memory contract ``gather=False``
exists to guarantee away.  The reference never materializes anything
global either: its pivot-row exchange is pure point-to-point
(main.cpp:1100-1131).

This module is the point-to-point equivalent under XLA's static-shape
rules, the pattern of arxiv 2112.09017 (gathers replaced by ring
``ppermute`` exchanges) with JAXMg-style per-destination bucketing:

  * the permutation is REPLICATED on every worker after the loop (the
    ``pos`` carry), so routing needs no communication at all — each
    round's "bucket" (which incoming rows belong here, and at which
    slot) is computed locally from ``pos``;
  * the exchange runs as **p − 1 single-hop ``ppermute`` rounds** on the
    bidirectional ring: one buffer rotates forward one hop per round,
    one backward, and at round d each worker extracts the rows of the
    bucket addressed to it from the worker d hops away (forward rounds
    serve distances 1..p//2, backward rounds p//2+1..p−1 — disjoint and
    complete, so every row is delivered exactly once).  Single-hop
    rounds are deliberate: a direct shift-by-d ``ppermute`` costs
    min(d, p−d) link hops on the torus, so p−1 direct rounds sum to
    ~p²/4 hop·buffers, while the rotation pipeline keeps every link busy
    every round and finishes in ceil(p/2) round-trips;
  * buckets are PADDED to the static worst case — ``ceil(Nr/p)`` rows,
    i.e. the full shard, since an adversarial pivot history can route
    every row of one worker to one destination — with validity implied
    by the replicated ``pos`` (no mask bytes on the wire).  Wire bytes
    are therefore bounded by (p−1)·N²/p per worker worst-case, N²/p of
    which is payload; RESIDENCY is the contract this buys: no buffer
    ever exceeds one shard (N²/p elements), vs the take/all-gather's
    transient N².

Used by both swap-free engines: the 1D row permutation (one call), and
the 2D row + column permutations (one call per mesh axis — data moves
only along the axis that shards it, never across the whole mesh).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from .compat import ppermute


def ppermute_bucketed(items, dest, axis_name, p: int):
    """Deliver cyclically-stored ``items`` to their ``dest`` positions in
    p − 1 single-hop ``ppermute`` rounds (see module docstring).

    ``items``: (B, ...) — this worker's slots along the cyclic axis
    ``axis_name`` of size ``p``; slot ``s`` on worker ``k`` holds the
    item with physical cyclic index ``s·p + k`` (worker-major cyclic
    storage, layout.py).  ``dest``: (B·p,) replicated int32 permutation —
    the item at physical index ``x`` belongs at natural index
    ``dest[x]``, which is stored at slot ``dest[x] // p`` of worker
    ``dest[x] % p``.  Returns the (B, ...) permuted shard.  No buffer
    larger than one shard is created, and data moves only along
    ``axis_name``.
    """
    k = lax.axis_index(axis_name)
    B = items.shape[0]
    slots = jnp.arange(B, dtype=jnp.int32)

    def extract(out, buf, src):
        # Which rows of the buffer launched by worker ``src`` land here,
        # and at which local slot — all from the replicated ``dest``.
        d = jnp.take(dest, slots * p + src)     # natural index per slot
        idx = jnp.where(d % p == k, d // p, B)  # B = dropped
        return out.at[idx].set(buf, mode="drop")

    out = extract(jnp.zeros_like(items), items, k)      # distance 0
    fwd = bwd = items
    fperm = [(i, (i + 1) % p) for i in range(p)]
    bperm = [(i, (i - 1) % p) for i in range(p)]
    for d in range(1, p // 2 + 1):
        fwd = ppermute(fwd, axis_name, fperm)       # from k - d
        out = extract(out, fwd, (k - d) % p)
        if d <= (p - 1) // 2:
            bwd = ppermute(bwd, axis_name, bperm)   # from k + d
            out = extract(out, bwd, (k + d) % p)
    return out
