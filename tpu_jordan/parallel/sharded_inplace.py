"""Distributed IN-PLACE block Gauss–Jordan over a 1D mesh: the fast path.

Port of the single-chip in-place redesign (ops/jordan_inplace.py) to the
row-block-cyclic distribution of ``sharded_jordan.py``: the working set is
the (Nr, m, N) cyclic block tensor of A alone — no augmented ``[A | B]``
half — so relative to the augmented distributed path every step does

  * half the flops: the eliminate matmul is (bpw·m, m) x (m, N) instead of
    (m, 2N) → ~2N³ total instead of ~4N³ (the reference's own algorithm is
    the augmented ~4N³ one, main.cpp:1136-1193; this is a redesign, not a
    parity loss — pivot choices and the result are identical);
  * half the collective bytes: two (m, N) one-hot psum row broadcasts
    instead of two (m, 2N) ones (reference analogs: MPI_Bcast
    main.cpp:1097 and the Send/Recv swap main.cpp:1122-1129);
  * half the HBM traffic: the shard read-modify-written each step is
    (bpw, m, N), not (bpw, m, 2N).

The loop over block-columns is UNROLLED (one jit trace, static offsets) —
the same trade as the single-chip engine: compile cost grows with Nr, so
this path is for Nr ≲ 64, which covers every north-star configuration
(8192² at m=512 is Nr=16).  Unrolling also buys the shrinking-window
probe *in SPMD form*: at step t the smallest possibly-valid local slot on
ANY worker is exactly ``t // p`` (worker k's slot s holds global block row
s·p + k, so s·p + k ≥ t ⟺ s ≥ ceil((t−k)/p), minimized over k < p at
floor(t/p)), a static bound — each worker probes only its ``bpw − t//p``
live candidates instead of masking all ``bpw`` (the reference probes the
same window, main.cpp:1039; the augmented fori_loop path can't shrink a
traced-shape batch).

In-place bookkeeping: at step t the eliminated column is replaced by the
inverse-building column (V[:,t] ← −E·H, pivot row ← H·row_piv with H in
the t-chunk), and the row-swap history is replayed as *column* swaps in
reverse after the loop.  Columns are fully replicated per worker in the 1D
layout, so the replay is worker-local — zero communication.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from .compat import pcast, pmin, psum, shard_map
from jax.sharding import Mesh, PartitionSpec

from ..config import eps_for
from ..ops.block_inverse import (probe_blocks,
                                 probe_blocks_quarter_masked)
from ..ops.norms import block_inf_norms
from .layout import CyclicLayout
from .mesh import AXIS
from .upcast import upcast_sub_fp32

# Unrolled-trace budget (same bar as the single-chip engine,
# driver.single_device_invert): beyond this, the fori_loop in-place
# engine below takes over (same 2N³ algorithm, traced offsets, compile
# cost independent of Nr) — the augmented ~4N³ path is no longer the
# large-Nr fallback.
MAX_UNROLL_NR = 64


def _step(t: int, Wloc, singular, *, lay: CyclicLayout, eps, precision,
          use_pallas: bool):
    """One super-step (static ``t``) on one worker's (bpw, m, N) shard."""
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype

    # --- PIVOT PROBE over the live window only: slots [t//p, bpw).
    s0 = t // p
    gidx = jnp.arange(s0, bpw) * p + k          # global block rows probed
    cands = lax.slice(Wloc, (s0, 0, t * m), (bpw, m, (t + 1) * m))
    invs, sing = probe_blocks(cands, eps, use_pallas)
    valid = (gidx >= t) & ~sing                 # at most one stale slot/worker
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]

    # --- PIVOT REDUCTION: two-stage composite-key pmin, ties to the lowest
    # global block row (replaces the custom MPI op, main.cpp:729-744,
    # 1000-1024, 1074).
    kmin = pmin(my_key, AXIS)
    g_cand = gidx[slot_best]
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), AXIS)
    singular = singular | ~jnp.isfinite(kmin)   # all-singular (main.cpp:1075-83)
    i_won = (my_key == kmin) & (g_cand == win_g)

    g_piv = psum(jnp.where(i_won, g_cand, 0), AXIS)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0).astype(dtype),
        AXIS,
    )

    # --- ROW BROADCASTS (m, N): pivot row and current row t as one-hot
    # psums (main.cpp:1097 / 1122-1129) — half the bytes of the augmented
    # path's (m, 2N) rows.
    safe_best = jnp.where(i_won, slot_best + s0, 0)
    row_piv = psum(
        jnp.where(i_won, lax.dynamic_index_in_dim(Wloc, safe_best, 0, False),
                  0.0),
        AXIS,
    )                                           # (m, N)
    own_t = k == (t % p)
    slot_t = t // p                             # static (== s0)
    row_t = psum(
        jnp.where(own_t, Wloc[slot_t], 0.0), AXIS
    )                                           # (m, N)

    # --- SWAP-BY-COPY (main.cpp:1093-1131): pivot owner's slot receives
    # the old row t; slot t is rewritten below from the normalized pivot.
    # The select is row-granular (one (m, N) slot), not a full-shard
    # where — each step touches O(m·N) beyond the eliminate matmul.
    own_piv = k == (g_piv % p)
    slot_piv = jnp.where(own_piv, g_piv // p, 0)
    cur_piv = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, row_t, cur_piv), slot_piv, 0
    )

    # --- NORMALIZE; the t-chunk becomes H (in-place column replacement:
    # same fold as ops/jordan_inplace.py — V[:,t] is zeroed so the one
    # eliminate matmul writes −E·H there).
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, N)
    prow = prow.at[:, t * m:(t + 1) * m].set(H)

    # --- ELIMINATE: every local row (above AND below the pivot — Jordan).
    E = Wloc[:, :, t * m:(t + 1) * m]                       # (bpw, m, m)
    loc_g = jnp.arange(bpw) * p + k
    E = jnp.where((loc_g == t)[:, None, None], jnp.asarray(0, dtype), E)
    Wloc = Wloc.at[:, :, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
    update = jnp.matmul(E.reshape(bpw * m, m), prow, precision=precision)
    Wloc = Wloc - update.reshape(bpw, m, N)

    # Row t becomes the normalized pivot row (owner only); row-granular
    # select, same reasoning as the swap above.
    Wloc = Wloc.at[slot_t].set(jnp.where(own_t, prow, Wloc[slot_t]))
    return Wloc, singular, g_piv


def _step_fori(t, Wloc, singular, swaps, *, lay: CyclicLayout, eps,
               precision, use_pallas: bool):
    """One super-step with a TRACED ``t`` on one worker's (bpw, m, N)
    shard — the fori_loop body behind ``_sharded_jordan_inplace_fori``.
    Same arithmetic as ``_step`` (identical pivot choices and updates);
    the probe runs on the masked slot window shrunk by the
    quarter-window ladder (probe_blocks_quarter_masked, stride p —
    deadness pinned by tests/test_jordan2d_inplace.py::
    test_quarter_ladder_skipped_slots_are_dead)."""
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype
    gidx = jnp.arange(bpw) * p + k              # global block row per slot

    # --- PIVOT PROBE: masked slot window, quarter ladder
    # (main.cpp:1039).
    cands = lax.dynamic_slice(Wloc, (0, 0, t * m), (bpw, m, m))
    invs, sing = probe_blocks_quarter_masked(cands, t, p, eps, use_pallas)
    valid = (gidx >= t) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]

    # --- PIVOT REDUCTION (identical to _step).
    kmin = pmin(my_key, AXIS)
    g_cand = gidx[slot_best]
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), AXIS)
    singular = singular | ~jnp.isfinite(kmin)
    i_won = (my_key == kmin) & (g_cand == win_g)

    g_piv = psum(jnp.where(i_won, g_cand, 0), AXIS)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0).astype(dtype),
        AXIS,
    )

    # --- ROW BROADCASTS (m, N), one-hot psums (main.cpp:1097/1122-1129).
    safe_best = jnp.where(i_won, slot_best, 0)
    row_piv = psum(
        jnp.where(i_won, lax.dynamic_index_in_dim(Wloc, safe_best, 0, False),
                  0.0),
        AXIS,
    )                                           # (m, N)
    own_t = k == (t % p)
    slot_t = t // p
    row_t = psum(
        jnp.where(own_t, lax.dynamic_index_in_dim(Wloc, slot_t, 0, False),
                  0.0),
        AXIS,
    )                                           # (m, N)

    # --- SWAP-BY-COPY (main.cpp:1093-1131), row-granular.
    own_piv = k == (g_piv % p)
    slot_piv = jnp.where(own_piv, g_piv // p, 0)
    cur_piv = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, row_t, cur_piv), slot_piv, 0
    )

    # --- NORMALIZE; the t-chunk becomes H.
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, N)
    prow = lax.dynamic_update_slice(prow, H, (0, t * m))

    # --- ELIMINATE.
    E = lax.dynamic_slice(Wloc, (0, 0, t * m), (bpw, m, m))
    E = jnp.where((gidx == t)[:, None, None], jnp.asarray(0, dtype), E)
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.zeros((bpw, m, m), dtype), (0, 0, t * m))
    update = jnp.matmul(E.reshape(bpw * m, m), prow, precision=precision)
    Wloc = Wloc - update.reshape(bpw, m, N)

    # Row t becomes the normalized pivot row (owner only); row-granular.
    cur_t = lax.dynamic_index_in_dim(Wloc, slot_t, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_t, prow, cur_t), slot_t, 0
    )
    return Wloc, singular, swaps.at[t].set(g_piv.astype(jnp.int32))


def _step_swapfree(t, Wloc, alive, singular, pos, ipos, swaps, *,
                   lay: CyclicLayout, eps, precision, use_pallas: bool):
    """One super-step of the SWAP-FREE engine on one worker's
    (bpw, m, N) shard: rows never move — the pivot permutation is
    tracked implicitly — so the ``row t`` broadcast of the swap-by-copy
    engines (main.cpp:1122-1129's exchange) DOES NOT EXIST.  Per step
    the collective bill is ONE (m, N) pivot-row psum + the pivot
    reduction: HALF the row-broadcast bytes of ``_step_fori``, which is
    the term benchmarks/comm_model.py says dominates every projected
    north-star mesh (e.g. v5p 1D p=32 @ 32768: 94 ms of 138 is comm,
    all of it row psums).  The deferred price is ONE bucketed-ppermute
    row permutation after the loop (permute.py): p−1 single-hop rounds,
    N²/p payload bytes per worker ((p−1)·N²/p worst-case padded), and
    per-worker residency capped at one shard — so the engine holds the
    ``gather=False`` memory contract at any scale.

    Pivot PARITY is exact, ties included: the live candidate set equals
    the swap engines' shrinking window (same values — eliminations are
    position-independent), and ties resolve by the pivot's SWAP
    COORDINATE (``pos``, the position the row would occupy in the
    swap-by-copy engine), reproducing the reference's
    lowest-current-row rule (main.cpp:1051-1064) — so results bit-match
    the swap engines after the final permutation on NONSINGULAR inputs,
    pinned by tests (all-singular inputs pin different benign targets
    per engine — both flag singular, the arrays diverge bitwise).

    Carries beyond the swap engines: ``alive`` (bpw,) per-worker live
    mask; ``pos``/``ipos`` (Nr,) replicated permutation bookkeeping
    (pos[x] = swap coordinate of physical row x, ipos = inverse);
    ``swaps`` records the swap-coordinate pivot sequence, feeding the
    same composed column unscramble as every in-place engine.
    """
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype
    z = jnp.int32(0)
    t = jnp.asarray(t, jnp.int32)
    gidx = jnp.arange(bpw) * p + k              # global block row per slot

    # --- PIVOT PROBE: the full slot window, validity from the alive
    # mask (dead physical rows are scattered, so no static shrink or
    # quarter ladder applies — the structural trade of this engine).
    cands = lax.dynamic_slice(Wloc, (z, z, t * m), (bpw, m, m))
    invs, sing = probe_blocks(cands, eps, use_pallas)
    valid = alive & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    # Local then global argmin, ties by SWAP COORDINATE (see docstring).
    posl = jnp.take(pos, gidx)                  # (bpw,) swap coords
    lmin = jnp.min(key)
    slot_best = jnp.argmin(jnp.where(key == lmin, posl, lay.Nr))
    my_key = lmin
    my_pos = posl[slot_best]

    kmin = pmin(my_key, AXIS)
    finite = jnp.isfinite(kmin)
    win_pos = pmin(jnp.where(my_key == kmin, my_pos, lay.Nr), AXIS)
    singular = singular | ~finite
    i_won = (my_key == kmin) & (my_pos == win_pos) & finite
    g_piv = psum(jnp.where(i_won, gidx[slot_best], 0), AXIS)
    # All-singular pin: the physical row at swap position t (the swap
    # engines' benign self-swap target), H := 0 — deterministic.
    g_piv = jnp.where(finite, g_piv, ipos[t])
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0).astype(dtype),
        AXIS,
    )

    # --- THE one row broadcast (m, N): the pivot's physical row.
    safe_best = jnp.where(i_won, slot_best, 0)
    row_piv = psum(
        jnp.where(i_won, lax.dynamic_index_in_dim(Wloc, safe_best, 0, False),
                  0.0),
        AXIS,
    )                                           # (m, N)

    # --- NORMALIZE; the t-chunk becomes H.
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, N)
    prow = lax.dynamic_update_slice(prow, H, (z, t * m))

    # --- ELIMINATE every row except the pivot's PHYSICAL row (which
    # receives prow — rows stay put).
    E = lax.dynamic_slice(Wloc, (z, z, t * m), (bpw, m, m))
    E = jnp.where((gidx == g_piv)[:, None, None], jnp.asarray(0, dtype), E)
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.zeros((bpw, m, m), dtype), (z, z, t * m))
    update = jnp.matmul(E.reshape(bpw * m, m), prow, precision=precision)
    Wloc = Wloc - update.reshape(bpw, m, N)
    own_piv = k == (g_piv % p)
    slot_piv = jnp.where(own_piv, g_piv // p, 0)
    cur = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, prow, cur), slot_piv, 0)

    # --- BOOKKEEPING: retire the pivot's physical row; replay what the
    # swap engine would have done to positions t <-> pos[g_piv] on the
    # replicated permutation carries (O(1) scalar work; int32 throughout
    # — x64 would promote the psum'd g_piv).
    alive = alive & (gidx != g_piv)
    g32 = g_piv.astype(jnp.int32)
    piv_pos = pos[g32]
    x = ipos[t]                                 # content at swap pos t
    pos = pos.at[x].set(piv_pos).at[g32].set(t)
    ipos = ipos.at[t].set(g32).at[piv_pos].set(x)
    swaps = swaps.at[t].set(piv_pos)
    return Wloc, alive, singular, pos, ipos, swaps


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas"))
def _sharded_jordan_inplace_swapfree(W, mesh, lay: CyclicLayout, eps,
                                     precision, use_pallas):
    """The swap-free 1D engine (fori_loop; any Nr): half the per-step
    collective row bytes of the swap engines, one bucketed ``ppermute``
    row permutation at the end (permute.py).  Bit-matches the swap
    engines on NONSINGULAR inputs (after the permutation) — same pivot
    rule including ties; on all-singular inputs both flag ``singular``
    but the returned arrays diverge bitwise (different benign pin
    targets — pinned by tests).  Output contract is identical:
    (inverse blocks in cyclic NATURAL row order, singular per worker).

    The deferred row permutation runs INSIDE shard_map: the permutation
    is fully replicated (``pos``), so each worker buckets its rows by
    destination and p−1 single-hop ppermute rounds deliver them —
    per-worker residency never exceeds one (bpw, m, N) shard (N²/p
    elements), vs the transient full-N² buffer a sharded ``jnp.take``
    would all-gather.  This is what makes ``gather=False`` (the
    pod-scale memory mode) legal for this engine."""
    def worker(Wloc):
        def body(t, carry):
            Wl, alive, sing, pos, ipos, swaps = carry
            return _step_swapfree(t, Wl, alive, sing, pos, ipos, swaps,
                                  lay=lay, eps=eps, precision=precision,
                                  use_pallas=use_pallas)

        bpw = lay.blocks_per_worker
        vary = lambda v: pcast(v, AXIS, to='varying')  # noqa: E731
        alive0 = vary(jnp.ones((bpw,), bool))
        sing0 = vary(jnp.asarray(False))
        pos0 = vary(jnp.arange(lay.Nr, dtype=jnp.int32))
        ipos0 = vary(jnp.arange(lay.Nr, dtype=jnp.int32))
        swaps0 = vary(jnp.zeros((lay.Nr,), jnp.int32))
        Wloc, alive, singular, pos, ipos, swaps = lax.fori_loop(
            0, lay.Nr, body, (Wloc, alive0, sing0, pos0, ipos0, swaps0))

        from ..ops.jordan_inplace import apply_col_perm, compose_swap_perm

        Wloc = apply_col_perm(Wloc, compose_swap_perm(swaps, lay.Nr),
                              lay.m)
        # --- THE deferred row permutation, point-to-point: physical row
        # x (slot x // p on worker x % p) belongs at natural row pos[x].
        from .permute import ppermute_bucketed

        Wloc = ppermute_bucketed(Wloc, pos, AXIS, lay.p)
        return Wloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=PartitionSpec(AXIS, None, None),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W)


def _gstep(t, j: int, Wloc, Uloc, P, singular, *, lay: CyclicLayout, eps,
           precision, use_pallas: bool):
    """One inner step of a delayed-group-update group on one worker's
    (bpw, m, N) shard (the 1D port of ops/jordan_inplace.py::
    _grouped_step; reference hot loop main.cpp:1136-1194).

    ``t`` may be a Python int (the unrolled engine: static shrinking
    probe window) or a traced int32 (the fori engine: masked full-window
    probe with the half cut) — every other op is identical, so the two
    flavors bit-match.  ``j`` (position within the group) is static.

    State beyond the plain step: ``Uloc`` (bpw, m, kg·m) holds the local
    rows of the pending panel multipliers (swapped together with W rows
    — pending contributions follow the physical row), ``P`` (kg·m, N)
    the finalized pivot rows, replicated per worker (computed
    redundantly from the same psum'd broadcasts, the SPMD analog of the
    single-chip engine's P).

    Collective accounting (the grouped comm win): ONE stacked
    (2m, N + kg·m + m) psum carries the pivot row + its U row, row t +
    its U row, and the eager column's t-block — where the plain step
    pays two separate (m, N) psum rounds (+ H) — so per step the
    grouped engine does 3 pmin/psum scalar rounds + 1 H psum + 1 fat
    row psum instead of the plain engine's 2 thin ones; the trailing
    update needs no communication at all (U rows local, P replicated).
    """
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    static_t = isinstance(t, int)
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype
    Uw = Uloc.shape[-1]
    z = jnp.int32(0)
    tt = jnp.asarray(t, jnp.int32)

    # --- EAGER CANDIDATE COLUMN on all slots: W[:, t] minus pending
    # panels (finalized rows included — Jordan eliminates above the
    # pivot too, so U's column j needs every row's eager value).
    col = lax.dynamic_slice(Wloc, (z, z, tt * m), (bpw, m, m))
    if j:
        Ptc = lax.dynamic_slice(P, (z, tt * m), (j * m, m))
        col = col - jnp.matmul(
            Uloc[:, :, :j * m].reshape(bpw * m, j * m), Ptc,
            precision=precision).reshape(bpw, m, m)

    # --- PROBE (main.cpp:1039): static shrinking window [t//p, bpw) for
    # the unrolled flavor, masked full window + half cut for the fori one.
    if static_t:
        s0 = t // p
        invs, sing = probe_blocks(col[s0:], eps, use_pallas)
        gidx = jnp.arange(s0, bpw) * p + k
    else:
        s0 = 0
        invs, sing = probe_blocks_quarter_masked(col, tt, p, eps,
                                                 use_pallas)
        gidx = jnp.arange(bpw) * p + k
    valid = (gidx >= tt) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]

    # --- PIVOT REDUCTION (identical to _step), plus the all-singular
    # pin: when no candidate anywhere is invertible, H := 0 and
    # g_piv := t (a benign self-swap), so both flavors stay bit-equal
    # even on singular inputs (the flags make the output invalid anyway).
    kmin = pmin(my_key, AXIS)
    finite = jnp.isfinite(kmin)
    g_cand = gidx[slot_best]
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), AXIS)
    singular = singular | ~finite
    i_won = (my_key == kmin) & (g_cand == win_g) & finite
    g_piv = psum(jnp.where(i_won, g_cand, 0), AXIS)
    g_piv = jnp.where(finite, g_piv, tt.astype(g_piv.dtype))
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0).astype(dtype),
        AXIS,
    )

    # --- STACKED ROW BROADCAST: one (2m, N + Uw + m) psum carrying
    # [pivot stale row | its U row | 0] and [row t | its U row | eager
    # col t-block] (main.cpp:1097 / 1122-1129 analogs, fused).
    own_t = k == (tt % p)
    slot_t = tt // p
    safe_best = jnp.where(i_won, slot_best + s0, 0)
    row1 = jnp.concatenate([
        lax.dynamic_index_in_dim(Wloc, safe_best, 0, False),
        lax.dynamic_index_in_dim(Uloc, safe_best, 0, False),
        jnp.zeros((m, m), dtype),
    ], axis=1)
    row2 = jnp.concatenate([
        lax.dynamic_index_in_dim(Wloc, slot_t, 0, False),
        lax.dynamic_index_in_dim(Uloc, slot_t, 0, False),
        lax.dynamic_index_in_dim(col, slot_t, 0, False),
    ], axis=1)
    stacked = psum(jnp.concatenate([
        jnp.where(i_won, row1, 0.0),
        jnp.where(own_t, row2, 0.0),
    ], axis=0), AXIS)                            # (2m, N + Uw + m)
    row_piv = stacked[:m, :N]
    u_p = stacked[:m, N:N + Uw]
    row_t = stacked[m:, :N]
    u_t = stacked[m:, N:N + Uw]
    col_t_blk = stacked[m:, N + Uw:]

    # --- SWAP-BY-COPY (main.cpp:1093-1131): pivot owner's slot receives
    # old row t in W, U, and the eager column; row t's slot is rewritten
    # below from the normalized pivot.  Row-granular selects throughout.
    own_piv = k == (g_piv % p)
    slot_piv = jnp.where(own_piv, g_piv // p, 0)
    cur = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, row_t, cur), slot_piv, 0)
    cur = lax.dynamic_index_in_dim(Uloc, slot_piv, 0, False)
    Uloc = lax.dynamic_update_index_in_dim(
        Uloc, jnp.where(own_piv, u_t, cur), slot_piv, 0)
    cur = lax.dynamic_index_in_dim(col, slot_piv, 0, False)
    col = lax.dynamic_update_index_in_dim(
        col, jnp.where(own_piv, col_t_blk, cur), slot_piv, 0)
    # Zero the eager column's row t (its multiplier is the prow write).
    cur = lax.dynamic_index_in_dim(col, slot_t, 0, False)
    col = lax.dynamic_update_index_in_dim(
        col, jnp.where(own_t, jnp.zeros_like(cur), cur), slot_t, 0)

    # --- EAGER PIVOT ROW + NORMALIZE; the t-chunk becomes H.
    if j:
        row_piv = row_piv - jnp.matmul(u_p[:, :j * m], P[:j * m],
                                       precision=precision)
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, N)
    prow = lax.dynamic_update_slice(prow, H, (z, tt * m))

    # --- BOOKKEEPING (the grouped engine's invariants,
    # ops/jordan_inplace.py): zero W's t-column and P's pending rows'
    # t-chunk, finalize row t, record the panel.
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.zeros((bpw, m, m), dtype), (z, z, tt * m))
    if j:
        P = lax.dynamic_update_slice(
            P, jnp.zeros((j * m, m), dtype), (z, tt * m))
    cur = lax.dynamic_index_in_dim(Wloc, slot_t, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_t, prow, cur), slot_t, 0)
    cur = lax.dynamic_index_in_dim(Uloc, slot_t, 0, False)
    Uloc = lax.dynamic_update_index_in_dim(
        Uloc, jnp.where(own_t, jnp.zeros_like(cur), cur), slot_t, 0)
    Uloc = Uloc.at[:, :, j * m:(j + 1) * m].set(col)
    P = P.at[j * m:(j + 1) * m].set(prow)
    return Wloc, Uloc, P, singular, g_piv


def _group_end(Wloc, Uloc, P, precision):
    """The one fat trailing update per group: (bpw·m, kg·m) x (kg·m, N)
    local MXU matmul — no communication (U rows are local, P is
    replicated)."""
    bpw, m, N = Wloc.shape
    upd = jnp.matmul(Uloc.reshape(bpw * m, -1), P, precision=precision)
    return Wloc - upd.reshape(bpw, m, N)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "group"))
def _sharded_jordan_inplace_grouped(W, mesh, lay: CyclicLayout, eps,
                                    precision, use_pallas, group):
    """The 1D in-place engine with delayed group updates, unrolled trace
    (static shrinking probe windows).  Same pivot rule and contract as
    ``_sharded_jordan_inplace``; per-group it applies ONE fat trailing
    matmul instead of ``group`` thin ones and fuses the per-step row
    broadcasts into one stacked psum (see ``_gstep``)."""
    kgrp = max(1, min(group, lay.Nr))

    def worker(Wloc):
        bpw, m, N = lay.blocks_per_worker, lay.m, lay.N
        singular = pcast(jnp.asarray(False), AXIS, to='varying')
        swaps = []
        for t0 in range(0, lay.Nr, kgrp):
            kg = min(kgrp, lay.Nr - t0)
            Uloc = pcast(jnp.zeros((bpw, m, kg * m), Wloc.dtype),
                             AXIS, to='varying')
            P = pcast(jnp.zeros((kg * m, N), Wloc.dtype),
                          AXIS, to='varying')
            for j in range(kg):
                Wloc, Uloc, P, singular, g_piv = _gstep(
                    t0 + j, j, Wloc, Uloc, P, singular, lay=lay, eps=eps,
                    precision=precision, use_pallas=use_pallas)
                swaps.append(g_piv)
            Wloc = _group_end(Wloc, Uloc, P, precision)

        from ..ops.jordan_inplace import apply_col_perm, compose_swap_perm

        Wloc = apply_col_perm(
            Wloc, compose_swap_perm(jnp.stack(swaps), lay.Nr), lay.m)
        return Wloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=PartitionSpec(AXIS, None, None),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "group"))
def _sharded_jordan_inplace_grouped_fori(W, mesh, lay: CyclicLayout, eps,
                                         precision, use_pallas, group):
    """The grouped 1D engine with the group loop as a ``lax.fori_loop``
    (compile cost flat in Nr; the inner ``group`` steps are the only
    unrolled region) — the distributed twin of
    ops/jordan_inplace.py::block_jordan_invert_inplace_grouped_fori.
    A trailing partial group runs unrolled after the loop."""
    kgrp = max(1, min(group, lay.Nr))
    G, tail = divmod(lay.Nr, kgrp)

    def worker(Wloc):
        bpw, m, N = lay.blocks_per_worker, lay.m, lay.N
        dtype = Wloc.dtype
        step = partial(_gstep, lay=lay, eps=eps, precision=precision,
                       use_pallas=use_pallas)

        def body(g, carry):
            Wl, sing, swaps = carry
            t0 = (g * kgrp).astype(jnp.int32)
            Ul = pcast(jnp.zeros((bpw, m, kgrp * m), dtype),
                           AXIS, to='varying')
            P = pcast(jnp.zeros((kgrp * m, N), dtype),
                          AXIS, to='varying')
            for j in range(kgrp):
                Wl, Ul, P, sing, g_piv = step(t0 + j, j, Wl, Ul, P, sing)
                swaps = swaps.at[t0 + j].set(g_piv.astype(jnp.int32))
            return _group_end(Wl, Ul, P, precision), sing, swaps

        sing0 = pcast(jnp.asarray(False), AXIS, to='varying')
        swaps0 = pcast(jnp.zeros((lay.Nr,), jnp.int32), AXIS,
                           to='varying')
        Wloc, singular, swaps = lax.fori_loop(
            0, G, body, (Wloc, sing0, swaps0))

        if tail:
            Ul = pcast(jnp.zeros((bpw, m, tail * m), dtype),
                           AXIS, to='varying')
            P = pcast(jnp.zeros((tail * m, N), dtype),
                          AXIS, to='varying')
            for j in range(tail):
                Wloc, Ul, P, singular, g_piv = step(
                    jnp.int32(G * kgrp + j), j, Wloc, Ul, P, singular)
                swaps = swaps.at[G * kgrp + j].set(g_piv.astype(jnp.int32))
            Wloc = _group_end(Wloc, Ul, P, precision)

        from ..ops.jordan_inplace import apply_col_perm, compose_swap_perm

        Wloc = apply_col_perm(Wloc, compose_swap_perm(swaps, lay.Nr),
                              lay.m)
        return Wloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=PartitionSpec(AXIS, None, None),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas"))
def _sharded_jordan_inplace_fori(W, mesh, lay: CyclicLayout, eps, precision,
                                 use_pallas):
    """The in-place 1D engine with both loops as ``lax.fori_loop``s:
    identical pivot choices and results to ``_sharded_jordan_inplace``,
    compile cost independent of Nr — this is what removes the
    ``MAX_UNROLL_NR`` ceiling from the 2N³ path (n=16384 at m=128 is
    Nr=128; 32768²/65536² distributed are Nr >= 64 at every useful m)."""
    def worker(Wloc):
        def body(t, carry):
            Wl, sing, swaps = carry
            return _step_fori(t, Wl, sing, swaps, lay=lay, eps=eps,
                              precision=precision, use_pallas=use_pallas)

        sing0 = pcast(jnp.asarray(False), AXIS, to='varying')
        swaps0 = pcast(jnp.zeros((lay.Nr,), jnp.int32), AXIS,
                           to='varying')
        Wloc, singular, swaps = lax.fori_loop(
            0, lay.Nr, body, (Wloc, sing0, swaps0))

        # --- UNSCRAMBLE: the composed swap permutation applied as ONE
        # blocked gather (worker-local — columns are replicated in the
        # 1D layout).  The literal column-swap replay costs a whole-shard
        # XLA copy per step (ops/jordan_inplace.py::compose_swap_perm).
        from ..ops.jordan_inplace import apply_col_perm, compose_swap_perm

        Wloc = apply_col_perm(Wloc, compose_swap_perm(swaps, lay.Nr),
                              lay.m)
        return Wloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=PartitionSpec(AXIS, None, None),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas"))
def _sharded_jordan_inplace(W, mesh, lay: CyclicLayout, eps, precision,
                            use_pallas):
    def worker(Wloc):
        singular = pcast(jnp.asarray(False), AXIS, to='varying')
        swaps = []
        for t in range(lay.Nr):
            Wloc, singular, g_piv = _step(
                t, Wloc, singular, lay=lay, eps=eps, precision=precision,
                use_pallas=use_pallas,
            )
            swaps.append(g_piv)

        # --- UNSCRAMBLE: the composed swap permutation applied as ONE
        # blocked gather (worker-local — columns are replicated in the
        # 1D layout; the literal replay costs a whole-shard copy per
        # step, ops/jordan_inplace.py::compose_swap_perm).
        from ..ops.jordan_inplace import apply_col_perm, compose_swap_perm

        Wloc = apply_col_perm(
            Wloc, compose_swap_perm(jnp.stack(swaps), lay.Nr), lay.m)
        return Wloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=PartitionSpec(AXIS, None, None),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W)


def _probe_reduce_1d(cands, t: int, k, *, lay: CyclicLayout, eps,
                     use_pallas: bool, dtype):
    """Step ``t``'s pivot probe + cross-worker reduction, factored out of
    ``_step`` VERBATIM (same ops, same collective multiset: two scalar
    pmins, the scalar g_piv psum, the (m, m) H psum) so the lookahead
    engines can issue it EARLY — right after the critical panel of step
    t−1's eliminate, before the trailing update.

    ``cands`` is the (bpw − t//p, m, m) live candidate stack for step
    ``t`` (static).  Returns the step's full pivot decision as a carry:
    ``(H, g_piv, safe_best, i_won, step_sing)``.  Note the base engine's
    ``i_won`` carries NO finite guard — on an all-singular window every
    worker "wins" and the H psum sums dead-candidate inverses; the
    lookahead panel computes those dead values with the same arithmetic,
    so even that degenerate path stays bit-equal."""
    p, bpw = lay.p, lay.blocks_per_worker
    s0 = t // p
    gidx = jnp.arange(s0, bpw) * p + k          # global block rows probed
    invs, sing = probe_blocks(cands, eps, use_pallas)
    valid = (gidx >= t) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]

    kmin = pmin(my_key, AXIS)
    g_cand = gidx[slot_best]
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), AXIS)
    step_sing = ~jnp.isfinite(kmin)
    i_won = (my_key == kmin) & (g_cand == win_g)
    g_piv = psum(jnp.where(i_won, g_cand, 0), AXIS)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0).astype(dtype),
        AXIS,
    )
    safe_best = jnp.where(i_won, slot_best + s0, 0)
    return H, g_piv, safe_best, i_won, step_sing


def _step_lookahead(t: int, Wloc, singular, probe, *, lay: CyclicLayout,
                    eps, precision, use_pallas: bool):
    """One super-step of the PROBE-AHEAD 1D engine (ISSUE 16).

    ``probe`` is step ``t``'s pivot decision, computed AHEAD of time (at
    the end of step t−1, overlapping its trailing eliminate).  The
    eliminate sweep is split: the CRITICAL PANEL (column block t+1 —
    step t+1's candidate column) is updated first, step t+1's probe +
    reduction launch immediately after it, and only then does the
    TRAILING eliminate (all other columns) run.  The panel is the column
    slice of the very matmul ``_step`` computes
    (``matmul(Ef, prow)[:, cols] == matmul(Ef, prow[:, cols])``
    element-for-element at HIGHEST), so pivot choices, the comm
    multiset, and the result bits are pinned IDENTICAL to the plain
    engine — the collectives MOVE earlier in the schedule, none are
    added (tests/test_comm.py reconciles the inventory multiset-exact).

    Returns ``(Wloc, singular, g_piv, next_probe)`` where ``next_probe``
    is step t+1's decision carry (None at the last step)."""
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype
    H, g_piv, safe_best, i_won, step_sing = probe
    singular = singular | step_sing

    # --- ROW BROADCASTS (m, N): same one-hot psums as _step, from the
    # carried decision (Wloc here equals the plain engine's state at the
    # top of step t, by induction).
    row_piv = psum(
        jnp.where(i_won, lax.dynamic_index_in_dim(Wloc, safe_best, 0, False),
                  0.0),
        AXIS,
    )                                           # (m, N)
    own_t = k == (t % p)
    slot_t = t // p
    row_t = psum(
        jnp.where(own_t, Wloc[slot_t], 0.0), AXIS
    )                                           # (m, N)

    # --- SWAP-BY-COPY (identical to _step).
    own_piv = k == (g_piv % p)
    slot_piv = jnp.where(own_piv, g_piv // p, 0)
    cur_piv = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, row_t, cur_piv), slot_piv, 0
    )

    # --- NORMALIZE; the t-chunk becomes H.
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, N)
    prow = prow.at[:, t * m:(t + 1) * m].set(H)

    # --- MULTIPLIERS (identical to _step).
    E = Wloc[:, :, t * m:(t + 1) * m]                       # (bpw, m, m)
    loc_g = jnp.arange(bpw) * p + k
    E = jnp.where((loc_g == t)[:, None, None], jnp.asarray(0, dtype), E)
    Wloc = Wloc.at[:, :, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
    Ef = E.reshape(bpw * m, m)

    next_probe = None
    if t < lay.Nr - 1:
        # --- CRITICAL PANEL first: column block t+1's rank-m update.
        c0 = (t + 1) * m
        panel = (Wloc[:, :, c0:c0 + m]
                 - jnp.matmul(Ef, prow[:, c0:c0 + m],
                              precision=precision).reshape(bpw, m, m))
        # The plain engine probes AFTER its slot_t prow write, and
        # slot_t (= t//p) can still sit inside step t+1's window on the
        # worker owning row t — apply the same overwrite to the
        # CANDIDATE view (the panel that re-enters Wloc stays unfixed;
        # the final slot_t write below covers it).
        panel_cand = panel.at[slot_t].set(
            jnp.where(own_t, prow[:, c0:c0 + m], panel[slot_t]))
        # --- PROBE-AHEAD: step t+1's decision, issued before the
        # trailing eliminate so the pmin/psum reduction overlaps it.
        s1 = (t + 1) // p
        next_probe = _probe_reduce_1d(
            panel_cand[s1:], t + 1, k, lay=lay, eps=eps,
            use_pallas=use_pallas, dtype=dtype)
        # --- TRAILING ELIMINATE: the remaining columns (same sliced
        # contractions; concat restores _step's Wloc bits).
        left = (Wloc[:, :, :c0]
                - jnp.matmul(Ef, prow[:, :c0],
                             precision=precision).reshape(bpw, m, c0))
        right = (Wloc[:, :, c0 + m:]
                 - jnp.matmul(Ef, prow[:, c0 + m:],
                              precision=precision).reshape(
                                  bpw, m, N - c0 - m))
        Wloc = jnp.concatenate([left, panel, right], axis=2)
    else:
        update = jnp.matmul(Ef, prow, precision=precision)
        Wloc = Wloc - update.reshape(bpw, m, N)

    # Row t becomes the normalized pivot row (owner only).
    Wloc = Wloc.at[slot_t].set(jnp.where(own_t, prow, Wloc[slot_t]))
    return Wloc, singular, g_piv, next_probe


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas"))
def _sharded_jordan_inplace_lookahead(W, mesh, lay: CyclicLayout, eps,
                                      precision, use_pallas):
    """The 1D in-place engine with PROBE-AHEAD scheduling (ISSUE 16):
    step t+1's probe + pmin reduction are issued right after step t's
    critical-panel update, BEFORE the trailing eliminate — the probe
    collective comes off the superstep critical path and can overlap
    the bulk rank-m GEMM under a latency-hiding scheduler.  Unrolled
    only (the panel split needs static offsets).  Results, pivot
    choices, and the collective MULTISET are bit-identical to
    ``_sharded_jordan_inplace`` — the schedule moves, the traffic
    doesn't."""
    def worker(Wloc):
        k = lax.axis_index(AXIS)
        singular = pcast(jnp.asarray(False), AXIS, to='varying')
        # --- PROLOGUE: step 0's probe on the untouched first column.
        probe = _probe_reduce_1d(
            lax.slice(Wloc, (0, 0, 0),
                      (lay.blocks_per_worker, lay.m, lay.m)),
            0, k, lay=lay, eps=eps, use_pallas=use_pallas,
            dtype=Wloc.dtype)
        swaps = []
        for t in range(lay.Nr):
            Wloc, singular, g_piv, probe = _step_lookahead(
                t, Wloc, singular, probe, lay=lay, eps=eps,
                precision=precision, use_pallas=use_pallas,
            )
            swaps.append(g_piv)

        from ..ops.jordan_inplace import apply_col_perm, compose_swap_perm

        Wloc = apply_col_perm(
            Wloc, compose_swap_perm(jnp.stack(swaps), lay.Nr), lay.m)
        return Wloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=PartitionSpec(AXIS, None, None),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W)


def compile_sharded_jordan_inplace(
    blocks: jnp.ndarray,
    mesh: Mesh,
    lay: CyclicLayout,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
    unroll: bool | None = None,
    group: int = 0,
    swapfree: bool = False,
    lookahead: bool = False,
):
    """AOT-compile the in-place sharded elimination for a (Nr, m, N)
    identity-padded cyclic block tensor.  ``run(blocks) ->
    (inverse_blocks, singular_per_worker)`` — the output IS the inverse in
    cyclic row order (no B half to slice).

    ``unroll=None`` picks the unrolled trace (static shrinking probe
    window) for Nr <= MAX_UNROLL_NR and the fori_loop engine beyond —
    identical results either way.  ``group=k > 1`` takes the delayed-
    group-update engines instead (one fat trailing matmul and one
    stacked row psum per step — the measured single-chip winner at
    large n, ported; parity with the plain engines is to rounding).
    ``swapfree=True`` takes the implicit-permutation engine instead:
    half the per-step collective row bytes, one bucketed-ppermute row
    permutation at the end (residency capped at one shard — legal under
    gather=False) — the pod-scale comm design (benchmarks/comm_model.py);
    bit-identical results on nonsingular inputs.  ``lookahead=True``
    takes the probe-ahead engine (ISSUE 16): step t+1's probe +
    reduction issued after step t's critical panel, before its trailing
    eliminate — unrolled only, bit- and inventory-identical to the
    plain engine."""
    from .sharded_jordan import resolve_use_pallas

    if eps is None:
        eps = eps_for(blocks.dtype)
    if use_pallas is None:
        use_pallas = resolve_use_pallas(blocks.dtype, lay.m)
    if unroll is None:
        unroll = lay.Nr <= MAX_UNROLL_NR
    if lookahead:
        from ..driver import UsageError

        if swapfree or (group and group > 1):
            raise UsageError(
                "lookahead=True composes only with the plain 1D engine "
                "(the panel/trailing split is defined on its per-step "
                "schedule); drop swapfree/group or drop lookahead")
        if not unroll:
            raise UsageError(
                f"the lookahead engine is unrolled-only (the critical-"
                f"panel split needs static column offsets) and Nr="
                f"{lay.Nr} exceeds MAX_UNROLL_NR={MAX_UNROLL_NR}; use "
                f"engine='inplace' (its fori twin) or a larger "
                f"block_size")
        return _sharded_jordan_inplace_lookahead.lower(
            blocks, mesh, lay, eps, precision, use_pallas
        ).compile()
    if swapfree:
        return _sharded_jordan_inplace_swapfree.lower(
            blocks, mesh, lay, eps, precision, use_pallas
        ).compile()
    if group and group > 1:
        engine = (_sharded_jordan_inplace_grouped if unroll
                  else _sharded_jordan_inplace_grouped_fori)
        return engine.lower(
            blocks, mesh, lay, eps, precision, use_pallas, group
        ).compile()
    engine = (_sharded_jordan_inplace if unroll
              else _sharded_jordan_inplace_fori)
    return engine.lower(
        blocks, mesh, lay, eps, precision, use_pallas
    ).compile()


def gather_inverse_inplace(out: jnp.ndarray, lay: CyclicLayout, n: int):
    """Cyclic row order -> natural order; columns are already natural."""
    from ..ops.padding import unpad
    from .layout import cyclic_scatter_perm

    out = jnp.take(out, cyclic_scatter_perm(lay), axis=0)
    return unpad(out.reshape(lay.N, lay.N), n)


def inverse_corner_1d(blocks: jnp.ndarray, lay: CyclicLayout, n: int,
                      max_p: int = 10):
    """Top-left min(n, max_p) corner of the inverse from its cyclic row
    blocks — WITHOUT a global gather (the ``gather=False`` verbose print,
    main.cpp:459-461: the reference always shows the corner even though
    the full inverse stays distributed).

    Global block row ``r`` sits at storage slot ``(r % p)·bpw + r // p``
    (worker-major cyclic order, layout.py); only the first
    ceil(corner/m) blocks' leading columns move — O(corner·m) bytes, so
    the O(n²/p) per-worker memory contract holds at any scale.
    """
    from .layout import global_block_owner, global_to_local_block

    c = min(n, max_p)
    nb = -(-c // lay.m)
    parts = [
        blocks[global_block_owner(r, lay.p) * lay.blocks_per_worker
               + global_to_local_block(r, lay.p), :, :c]
        for r in range(nb)
    ]
    return jnp.concatenate(parts, axis=0)[:c]


# ---------------------------------------------------------------------
# Distributed SOLVE (ISSUE 15): the [A | B] elimination sharded over the
# 1D row-cyclic mesh — X = A⁻¹B with no inverse ever formed.
# ---------------------------------------------------------------------


def _solve_step(t, Wloc, Xloc, singular, *, lay: CyclicLayout, nrhs: int,
                eps, precision, use_pallas: bool):
    """One solve super-step on one worker's (bpw, m, N) A shard plus its
    (bpw, m, nrhs) RHS rows — the distributed twin of
    ``linalg.engine.block_jordan_solve``'s loop body.

    ``t`` may be a Python int (the unrolled engine: the live-column
    window [t·m, N) shrinks STATICALLY — per-device FLOPs land ~1/p of
    the single-device solve's, which is where the n³(1+k/n)-vs-2n³
    saving survives distribution) or a traced int32 (the fori engine:
    full-width updates whose dead-column work is exact zeros — the
    probe still shrinks via the quarter ladder).  Pivot choices and X
    are BIT-IDENTICAL to the single-device engine on nonsingular
    inputs: the probe runs the same ``batched_block_inverse`` per
    candidate, the composite-key pmin reproduces argmin's
    lowest-global-row tie rule, and the one-hot psum broadcasts deliver
    exact row copies (adding zeros is exact).

    Unlike the invert steps there is NO in-place column replacement and
    NO unscramble: the A half is driven to (approximately) identity and
    discarded — X alone is the product.

    Collectives per step (the comm inventory, obs/comm.py): 2 pivot
    pmins + the g_piv psum + the (m, m) H psum + TWO stacked
    [A_live | X] row psums — (m, N − t·m + k) unrolled,
    (m, N + k) fori."""
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    static_t = isinstance(t, int)
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype
    z = jnp.int32(0)
    tt = jnp.asarray(t, jnp.int32)

    # --- PIVOT PROBE (main.cpp:1039): static shrinking window for the
    # unrolled flavor, masked full window + quarter ladder for fori.
    if static_t:
        lo = t * m
        s0 = t // p
        cands = lax.slice(Wloc, (s0, 0, lo), (bpw, m, lo + m))
        invs, sing = probe_blocks(cands, eps, use_pallas)
        gidx = jnp.arange(s0, bpw) * p + k
        live = N - lo
    else:
        s0 = 0
        cands = lax.dynamic_slice(Wloc, (z, z, tt * m), (bpw, m, m))
        invs, sing = probe_blocks_quarter_masked(cands, tt, p, eps,
                                                 use_pallas)
        gidx = jnp.arange(bpw) * p + k
        live = N
    valid = (gidx >= tt) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]

    # --- PIVOT REDUCTION (identical to _step: ties to lowest global
    # block row — the single-device argmin-first rule).
    kmin = pmin(my_key, AXIS)
    g_cand = gidx[slot_best]
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), AXIS)
    singular = singular | ~jnp.isfinite(kmin)
    i_won = (my_key == kmin) & (g_cand == win_g)
    g_piv = psum(jnp.where(i_won, g_cand, 0), AXIS)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0).astype(dtype),
        AXIS,
    )

    # --- STACKED ROW BROADCASTS: [A_live | X] of the pivot row and of
    # row t, one psum each (main.cpp:1097 / 1122-1129 with the RHS
    # columns riding along).
    def rowcat(slot):
        # int32 indices throughout: x64 would make the argmin/psum-
        # derived slots int64 against dynamic_slice's int32 offsets.
        slot = jnp.asarray(slot, jnp.int32)
        if static_t:
            a_row = lax.dynamic_slice(Wloc, (slot, z, jnp.int32(lo)),
                                      (1, m, live))[0]
        else:
            a_row = lax.dynamic_index_in_dim(Wloc, slot, 0, False)
        return jnp.concatenate(
            [a_row, lax.dynamic_index_in_dim(Xloc, slot, 0, False)],
            axis=1)

    safe_best = jnp.where(i_won, slot_best + s0, 0)
    row_piv = psum(jnp.where(i_won, rowcat(safe_best), 0.0), AXIS)
    own_t = k == (tt % p)
    slot_t = tt // p
    row_t = psum(jnp.where(own_t, rowcat(slot_t), 0.0), AXIS)

    # --- SWAP-BY-COPY (main.cpp:1093-1131): pivot owner's slot
    # receives old row t in A's live columns and in X; slot t is
    # rewritten from the normalized pivot below.
    own_piv = k == (g_piv % p)
    slot_piv = jnp.asarray(jnp.where(own_piv, g_piv // p, 0), jnp.int32)
    if static_t:
        cur_A = lax.dynamic_slice(Wloc, (slot_piv, z, jnp.int32(lo)),
                                  (1, m, live))
        Wloc = lax.dynamic_update_slice(
            Wloc, jnp.where(own_piv, row_t[None, :, :live], cur_A),
            (slot_piv, z, jnp.int32(lo)))
    else:
        cur_A = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
        Wloc = lax.dynamic_update_index_in_dim(
            Wloc, jnp.where(own_piv, row_t[:, :live], cur_A), slot_piv, 0)
    cur_X = lax.dynamic_index_in_dim(Xloc, slot_piv, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_piv, row_t[:, live:], cur_X), slot_piv, 0)

    # --- NORMALIZE: prow = H @ pivot row — A and X as SEPARATE matmuls
    # (the single-device engine's exact op structure, the bit-match
    # contract).
    prow_A = jnp.matmul(H, row_piv[:, :live], precision=precision)
    prow_X = jnp.matmul(H, row_piv[:, live:], precision=precision)

    # --- ELIMINATE (main.cpp:1165-1193): local multipliers from the
    # post-swap t-chunk, row t excluded; one MXU matmul pair over the
    # live columns + the RHS.
    if static_t:
        E = lax.slice(Wloc, (0, 0, lo), (bpw, m, lo + m))
    else:
        E = lax.dynamic_slice(Wloc, (z, z, tt * m), (bpw, m, m))
    loc_g = jnp.arange(bpw) * p + k
    E = jnp.where((loc_g == tt)[:, None, None], jnp.asarray(0, dtype), E)
    Ef = E.reshape(bpw * m, m)
    upd_A = jnp.matmul(Ef, prow_A, precision=precision)
    upd_X = jnp.matmul(Ef, prow_X, precision=precision)
    if static_t:
        Wloc = Wloc.at[:, :, lo:].add(-upd_A.reshape(bpw, m, live))
    else:
        Wloc = Wloc - upd_A.reshape(bpw, m, N)
    Xloc = Xloc - upd_X.reshape(bpw, m, nrhs)

    # Row t becomes the normalized pivot row (owner only).
    if static_t:
        cur_t = lax.dynamic_slice(Wloc, (slot_t, z, jnp.int32(lo)),
                                  (1, m, live))
        Wloc = lax.dynamic_update_slice(
            Wloc, jnp.where(own_t, prow_A[None], cur_t),
            (slot_t, z, jnp.int32(lo)))
    else:
        cur_t = lax.dynamic_index_in_dim(Wloc, slot_t, 0, False)
        Wloc = lax.dynamic_update_index_in_dim(
            Wloc, jnp.where(own_t, prow_A, cur_t), slot_t, 0)
    cur_tx = lax.dynamic_index_in_dim(Xloc, slot_t, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_t, prow_X, cur_tx), slot_t, 0)
    return Wloc, Xloc, singular


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "eps", "precision",
                          "use_pallas"))
def _sharded_jordan_solve(W, X, mesh, lay: CyclicLayout, nrhs, eps,
                          precision, use_pallas):
    """The unrolled 1D solve engine: Python-level loop, static offsets,
    the statically shrinking live-column window per shard (Nr <=
    MAX_UNROLL_NR).  Returns (X blocks in cyclic row order, singular
    per worker); X bit-matches ``block_jordan_solve`` on shared
    nonsingular fixtures."""
    def worker(Wloc, Xloc):
        singular = pcast(jnp.asarray(False), AXIS, to='varying')
        for t in range(lay.Nr):
            Wloc, Xloc, singular = _solve_step(
                t, Wloc, Xloc, singular, lay=lay, nrhs=nrhs, eps=eps,
                precision=precision, use_pallas=use_pallas)
        return Xloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(PartitionSpec(AXIS, None, None),
                  PartitionSpec(AXIS, None, None)),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W, X)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "eps", "precision",
                          "use_pallas"))
def _sharded_jordan_solve_fori(W, X, mesh, lay: CyclicLayout, nrhs, eps,
                               precision, use_pallas):
    """The fori_loop 1D solve engine: compile cost independent of Nr —
    what lifts the MAX_UNROLL_NR ceiling off the distributed solve.
    Identical pivot choices and X bits to the unrolled flavor (the
    full-width updates touch dead columns with exact zeros)."""
    def worker(Wloc, Xloc):
        def body(t, carry):
            Wl, Xl, sing = carry
            return _solve_step(t, Wl, Xl, sing, lay=lay, nrhs=nrhs,
                               eps=eps, precision=precision,
                               use_pallas=use_pallas)

        sing0 = pcast(jnp.asarray(False), AXIS, to='varying')
        Wloc, Xloc, singular = lax.fori_loop(
            0, lay.Nr, body, (Wloc, Xloc, sing0))
        return Xloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(PartitionSpec(AXIS, None, None),
                  PartitionSpec(AXIS, None, None)),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W, X)


def _solve_step_lookahead(t: int, Wloc, Xloc, singular, probe, *,
                          lay: CyclicLayout, nrhs: int, eps, precision,
                          use_pallas: bool):
    """One PROBE-AHEAD solve super-step (ISSUE 16): ``probe`` is step
    ``t``'s pivot decision, issued at the end of step t−1 right after
    its critical panel.  The A-half eliminate splits into the t+1
    candidate panel (first), step t+1's probe + reduction, then the
    trailing A columns and the full X update — column slices of the
    same HIGHEST-precision contractions, so X bits, pivot choices, and
    the collective multiset pin identical to ``_solve_step``.  Unrolled
    only (static shrinking window + static panel offsets)."""
    p, m, bpw, N = lay.p, lay.m, lay.blocks_per_worker, lay.N
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype
    z = jnp.int32(0)
    lo = t * m
    live = N - lo
    H, g_piv, safe_best, i_won, step_sing = probe
    singular = singular | step_sing

    # --- STACKED ROW BROADCASTS [A_live | X] from the carried decision.
    def rowcat(slot):
        slot = jnp.asarray(slot, jnp.int32)
        a_row = lax.dynamic_slice(Wloc, (slot, z, jnp.int32(lo)),
                                  (1, m, live))[0]
        return jnp.concatenate(
            [a_row, lax.dynamic_index_in_dim(Xloc, slot, 0, False)],
            axis=1)

    row_piv = psum(jnp.where(i_won, rowcat(safe_best), 0.0), AXIS)
    own_t = k == (t % p)
    slot_t = t // p
    row_t = psum(jnp.where(own_t, rowcat(slot_t), 0.0), AXIS)

    # --- SWAP-BY-COPY (identical to _solve_step's static path).
    own_piv = k == (g_piv % p)
    slot_piv = jnp.asarray(jnp.where(own_piv, g_piv // p, 0), jnp.int32)
    cur_A = lax.dynamic_slice(Wloc, (slot_piv, z, jnp.int32(lo)),
                              (1, m, live))
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_piv, row_t[None, :, :live], cur_A),
        (slot_piv, z, jnp.int32(lo)))
    cur_X = lax.dynamic_index_in_dim(Xloc, slot_piv, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_piv, row_t[:, live:], cur_X), slot_piv, 0)

    # --- NORMALIZE (A and X as separate matmuls — the bit contract).
    prow_A = jnp.matmul(H, row_piv[:, :live], precision=precision)
    prow_X = jnp.matmul(H, row_piv[:, live:], precision=precision)

    # --- MULTIPLIERS from the post-swap t-chunk, row t excluded.
    E = lax.slice(Wloc, (0, 0, lo), (bpw, m, lo + m))
    loc_g = jnp.arange(bpw) * p + k
    E = jnp.where((loc_g == t)[:, None, None], jnp.asarray(0, dtype), E)
    Ef = E.reshape(bpw * m, m)

    next_probe = None
    if t < lay.Nr - 1:
        # --- CRITICAL PANEL: column block t+1 sits at offset m inside
        # the live window.
        lo2 = (t + 1) * m
        panel = (Wloc[:, :, lo2:lo2 + m]
                 - jnp.matmul(Ef, prow_A[:, m:2 * m],
                              precision=precision).reshape(bpw, m, m))
        panel_cand = panel.at[slot_t].set(
            jnp.where(own_t, prow_A[:, m:2 * m], panel[slot_t]))
        # --- PROBE-AHEAD for step t+1.
        s1 = (t + 1) // p
        next_probe = _probe_reduce_1d(
            panel_cand[s1:], t + 1, k, lay=lay, eps=eps,
            use_pallas=use_pallas, dtype=dtype)
        # --- TRAILING: pivot column, the rest of A, and all of X.
        left = (Wloc[:, :, lo:lo2]
                - jnp.matmul(Ef, prow_A[:, :m],
                             precision=precision).reshape(bpw, m, m))
        right = (Wloc[:, :, lo2 + m:]
                 - jnp.matmul(Ef, prow_A[:, 2 * m:],
                              precision=precision).reshape(
                                  bpw, m, live - 2 * m))
        Wloc = Wloc.at[:, :, lo:].set(
            jnp.concatenate([left, panel, right], axis=2))
    else:
        upd_A = jnp.matmul(Ef, prow_A, precision=precision)
        Wloc = Wloc.at[:, :, lo:].add(-upd_A.reshape(bpw, m, live))
    upd_X = jnp.matmul(Ef, prow_X, precision=precision)
    Xloc = Xloc - upd_X.reshape(bpw, m, nrhs)

    # Row t becomes the normalized pivot row (owner only).  int32
    # indices: x64 would canonicalize the static slot to int64 against
    # dynamic_slice's int32 offsets (the base _solve_step discipline).
    st = jnp.int32(slot_t)
    cur_t = lax.dynamic_slice(Wloc, (st, z, jnp.int32(lo)),
                              (1, m, live))
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_t, prow_A[None], cur_t),
        (st, z, jnp.int32(lo)))
    cur_tx = lax.dynamic_index_in_dim(Xloc, slot_t, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_t, prow_X, cur_tx), slot_t, 0)
    return Wloc, Xloc, singular, next_probe


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "eps", "precision",
                          "use_pallas"))
def _sharded_jordan_solve_lookahead(W, X, mesh, lay: CyclicLayout, nrhs,
                                    eps, precision, use_pallas):
    """The PROBE-AHEAD 1D solve engine: same prologue-probe + panel/
    trailing split as ``_sharded_jordan_inplace_lookahead``, on the
    [A | B] elimination.  X bits, pivot sequence, and the collective
    multiset match ``_sharded_jordan_solve`` exactly."""
    def worker(Wloc, Xloc):
        k = lax.axis_index(AXIS)
        singular = pcast(jnp.asarray(False), AXIS, to='varying')
        probe = _probe_reduce_1d(
            lax.slice(Wloc, (0, 0, 0),
                      (lay.blocks_per_worker, lay.m, lay.m)),
            0, k, lay=lay, eps=eps, use_pallas=use_pallas,
            dtype=Wloc.dtype)
        for t in range(lay.Nr):
            Wloc, Xloc, singular, probe = _solve_step_lookahead(
                t, Wloc, Xloc, singular, probe, lay=lay, nrhs=nrhs,
                eps=eps, precision=precision, use_pallas=use_pallas)
        return Xloc, singular[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(PartitionSpec(AXIS, None, None),
                  PartitionSpec(AXIS, None, None)),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W, X)


def scatter_rhs_1d(b: jnp.ndarray, lay: CyclicLayout, mesh: Mesh):
    """(n, k) RHS -> (Nr, m, k) zero-padded row blocks in cyclic storage
    order, sharded over the 1D mesh (pad rows of X stay exactly zero
    through the elimination — ops/padding.py semantics)."""
    from jax.sharding import NamedSharding

    from .layout import cyclic_gather_perm

    n, k = b.shape
    bp = jnp.zeros((lay.N, k), b.dtype).at[:n].set(b)
    blocks = jnp.take(bp.reshape(lay.Nr, lay.m, k),
                      cyclic_gather_perm(lay), axis=0)
    return jax.device_put(
        blocks, NamedSharding(mesh, PartitionSpec(AXIS, None, None)))


def gather_solution_1d(xb: jnp.ndarray, lay: CyclicLayout, n: int):
    """Cyclic row order -> natural order; strip the zero pad rows."""
    from .layout import cyclic_scatter_perm

    xb = jnp.take(xb, cyclic_scatter_perm(lay), axis=0)
    return xb.reshape(lay.N, -1)[:n]


def compile_sharded_jordan_solve(
    Wblocks: jnp.ndarray,
    Xblocks: jnp.ndarray,
    mesh: Mesh,
    lay: CyclicLayout,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
    unroll: bool | None = None,
    lookahead: bool = False,
):
    """AOT-compile the 1D distributed solve for an identity-padded
    (Nr, m, N) A block tensor and a zero-padded (Nr, m, k) RHS tensor.
    ``run(W, X) -> (x_blocks, singular_per_worker)``.

    ``unroll=None`` picks the unrolled trace (static shrinking
    live-column window — the FLOP-saving flavor) for Nr <=
    MAX_UNROLL_NR and the fori_loop engine beyond (identical X bits;
    full-width updates, compile cost flat in Nr).  ``lookahead=True``
    takes the probe-ahead schedule (unrolled only; identical X bits and
    comm inventory)."""
    from .sharded_jordan import resolve_use_pallas

    if eps is None:
        eps = eps_for(Wblocks.dtype)
    if use_pallas is None:
        use_pallas = resolve_use_pallas(Wblocks.dtype, lay.m)
    if unroll is None:
        unroll = lay.Nr <= MAX_UNROLL_NR
    nrhs = int(Xblocks.shape[-1])
    if lookahead:
        if not unroll:
            from ..driver import UsageError

            raise UsageError(
                f"engine='solve_lookahead' is unrolled-only (the "
                f"critical-panel split needs static column offsets) and "
                f"Nr={lay.Nr} exceeds MAX_UNROLL_NR={MAX_UNROLL_NR}; "
                f"use engine='solve_sharded' (its fori twin covers any "
                f"Nr) or a larger block_size")
        return _sharded_jordan_solve_lookahead.lower(
            Wblocks, Xblocks, mesh, lay, nrhs, eps, precision, use_pallas
        ).compile()
    engine = (_sharded_jordan_solve if unroll
              else _sharded_jordan_solve_fori)
    return engine.lower(
        Wblocks, Xblocks, mesh, lay, nrhs, eps, precision, use_pallas
    ).compile()


@upcast_sub_fp32
def sharded_jordan_invert_inplace(
    a: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
    unroll: bool | None = None,
    group: int = 0,
    swapfree: bool = False,
    lookahead: bool = False,
):
    """Invert (n, n) ``a`` over the 1D mesh with the in-place engine.

    Drop-in for ``sharded_jordan_invert`` (same pivot rule, same
    (inv, singular) contract) at ~half the flops, memory, and collective
    bytes.  Any Nr: the unrolled trace below MAX_UNROLL_NR, the
    fori_loop engine above (``unroll`` forces a choice).  ``group=k > 1``
    selects the delayed-group-update engines (k panels per trailing
    matmul; rounding-level parity with the plain engines).
    """
    from .ring_gemm import _to_identity_padded_blocks

    n = a.shape[-1]
    lay = CyclicLayout.create(n, min(block_size, n), mesh.devices.size)
    blocks = _to_identity_padded_blocks(a, lay, mesh)
    run = compile_sharded_jordan_inplace(blocks, mesh, lay, eps, precision,
                                         use_pallas, unroll, group, swapfree,
                                         lookahead)
    out, singular = run(blocks)
    return gather_inverse_inplace(out, lay, n), singular.any()


# ---------------------------------------------------------------------
# Checkpointed segment executables (ISSUE 20, resilience/checkpoint.py).
# A checkpointed distributed run executes supersteps [t0, t1) as ONE
# shard_map executable per segment; between segments the sharded
# elimination state — the (Nr, m, N) W blocks, the (Nr, m, k) X blocks
# or the (p, Nr) swap record, and the per-worker singular flags —
# round-trips to host byte-exactly (np.asarray gathers, device_put
# re-scatters).  Each segment runs the SAME ``_step``/``_solve_step``
# arithmetic and the SAME collective schedule as the monolithic
# engines, so the segment concatenation bit-matches the uninterrupted
# run (pinned by tests/test_checkpoint.py — the ISSUE 16 lookahead
# discipline: arithmetic may move between executables, none may
# change).
# ---------------------------------------------------------------------


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "t0", "t1", "eps",
                          "precision", "use_pallas", "unroll"))
def _sharded_jordan_solve_segment(W, X, singular, mesh,
                                  lay: CyclicLayout, nrhs: int, t0: int,
                                  t1: int, eps, precision, use_pallas,
                                  unroll: bool):
    """Supersteps [t0, t1) of the 1D distributed solve.  ``unroll=True``
    replays ``_solve_step`` with static offsets (the shrinking
    live-column window — eliminated columns of W are dead and carried
    stale, exactly as the monolithic unrolled engine leaves them);
    ``unroll=False`` runs the fori body over the same range.  The
    carried ``singular`` is the (p,) per-worker flag vector the
    monolithic engines emit — in and out through the same spec."""
    def worker(Wloc, Xloc, sloc):
        sing = sloc[0]
        if unroll:
            for t in range(t0, t1):
                Wloc, Xloc, sing = _solve_step(
                    t, Wloc, Xloc, sing, lay=lay, nrhs=nrhs, eps=eps,
                    precision=precision, use_pallas=use_pallas)
        else:
            def body(t, carry):
                Wl, Xl, s = carry
                return _solve_step(t, Wl, Xl, s, lay=lay, nrhs=nrhs,
                                   eps=eps, precision=precision,
                                   use_pallas=use_pallas)

            Wloc, Xloc, sing = lax.fori_loop(
                t0, t1, body, (Wloc, Xloc, sing))
        return Wloc, Xloc, sing[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(PartitionSpec(AXIS, None, None),
                  PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
        out_specs=(PartitionSpec(AXIS, None, None),
                   PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W, X, singular)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "t0", "t1", "eps", "precision",
                          "use_pallas", "unroll"))
def _sharded_jordan_inplace_segment(W, singular, swaps, mesh,
                                    lay: CyclicLayout, t0: int, t1: int,
                                    eps, precision, use_pallas,
                                    unroll: bool):
    """Supersteps [t0, t1) of the 1D in-place invert.  The swap record
    rides as a (p, Nr) int32 tensor (each worker's row is the same
    psum-broadcast pivot history — the fori engine's own carry, made
    shardable); the unscramble does NOT run here — it moves to
    :func:`_sharded_inplace_finalize`, applied once after the last
    segment exactly where the monolithic engines apply it."""
    def worker(Wloc, sloc, swloc):
        sing = sloc[0]
        sw = swloc[0]
        if unroll:
            for t in range(t0, t1):
                Wloc, sing, g_piv = _step(
                    t, Wloc, sing, lay=lay, eps=eps,
                    precision=precision, use_pallas=use_pallas)
                sw = sw.at[t].set(g_piv.astype(jnp.int32))
        else:
            def body(t, carry):
                Wl, s, sws = carry
                return _step_fori(t, Wl, s, sws, lay=lay, eps=eps,
                                  precision=precision,
                                  use_pallas=use_pallas)

            Wloc, sing, sw = lax.fori_loop(t0, t1, body,
                                           (Wloc, sing, sw))
        return Wloc, sing[None], sw[None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS),
                  PartitionSpec(AXIS, None)),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS),
                   PartitionSpec(AXIS, None)),
    )(W, singular, swaps)


@partial(jax.jit, static_argnames=("mesh", "lay"))
def _sharded_inplace_finalize(W, swaps, mesh, lay: CyclicLayout):
    """The 1D invert epilogue as its own executable: compose the swap
    history into one block-column permutation and apply it worker-local
    (columns are replicated in the 1D layout) — the exact unscramble
    the monolithic workers run after their loops."""
    def worker(Wloc, swloc):
        from ..ops.jordan_inplace import apply_col_perm, compose_swap_perm

        return apply_col_perm(
            Wloc, compose_swap_perm(swloc[0], lay.Nr), lay.m)

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(PartitionSpec(AXIS, None, None),
                  PartitionSpec(AXIS, None)),
        out_specs=PartitionSpec(AXIS, None, None),
    )(W, swaps)
