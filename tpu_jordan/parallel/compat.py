"""jax API compatibility: ``shard_map`` and ``pcast`` across versions.

The engines are written against the current jax surface — top-level
``jax.shard_map`` with the varying-type system and ``lax.pcast`` to
stamp carries with a mesh-axis varying tag.  Stock jax 0.4.x ships
shard_map at ``jax.experimental.shard_map`` and has no varying types;
its older ``check_rep`` replication checker predates several of the
patterns the engines rely on (one-hot psum broadcasts feeding scatter
updates, replicated fori_loop carries against varying outputs), so on
that lineage we run with ``check_rep=False`` — the same programs, the
same collectives, just without the newer static type layer.  ``pcast``
degrades to identity there: with no varying types, there is nothing to
cast.  Every sharded module imports these two names from here instead
of from jax, so the version split lives in exactly one file.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map          # jax >= 0.7 surface
except ImportError:                                  # jax 0.4.x lineage
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_CHECK_REP = "check_rep" in inspect.signature(_shard_map).parameters


def shard_map(f, *, mesh, in_specs, out_specs):
    if _HAS_CHECK_REP:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


from jax import lax as _lax  # noqa: E402

if hasattr(_lax, "pcast"):
    pcast = _lax.pcast
else:
    def pcast(x, axis_name, *, to):
        """No varying-type system in this jax: nothing to cast."""
        del axis_name, to
        return x
