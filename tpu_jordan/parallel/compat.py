"""jax API compatibility: ``shard_map`` and ``pcast`` across versions —
plus the ONE place every explicit collective the engines issue passes
through (ISSUE 14).

The engines are written against the current jax surface — top-level
``jax.shard_map`` with the varying-type system and ``lax.pcast`` to
stamp carries with a mesh-axis varying tag.  Stock jax 0.4.x ships
shard_map at ``jax.experimental.shard_map`` and has no varying types;
its older ``check_rep`` replication checker predates several of the
patterns the engines rely on (one-hot psum broadcasts feeding scatter
updates, replicated fori_loop carries against varying outputs), so on
that lineage we run with ``check_rep=False`` — the same programs, the
same collectives, just without the newer static type layer.  ``pcast``
degrades to identity there: with no varying types, there is nothing to
cast.  Every sharded module imports these two names from here instead
of from jax, so the version split lives in exactly one file.

Collective accounting (ISSUE 14, the communication observatory): the
``psum``/``pmin``/``pmax``/``ppermute`` wrappers below are what every
engine module imports instead of the ``lax`` originals.  With no
recorder registered they ARE the originals up to one list-truthiness
check that runs only at TRACE time (a cached executable never
re-enters Python, so the warm path — and its zero-compile pins — pays
nothing).  With a recorder registered (``obs/comm.py``'s
``CollectiveRecorder``), each wrapper notes the collective's kind,
mesh axis, operand shape and dtype as the tracer passes through — the
host-side "what did this program actually issue" half of the
``observed == analytical`` reconciliation invariant.  The recording
changes NOTHING about the traced program: the note happens beside the
``lax`` call, not inside it.
"""

from __future__ import annotations

import inspect
import threading

try:
    from jax import shard_map as _shard_map          # jax >= 0.7 surface
except ImportError:                                  # jax 0.4.x lineage
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_CHECK_REP = "check_rep" in inspect.signature(_shard_map).parameters


def shard_map(f, *, mesh, in_specs, out_specs):
    if _HAS_CHECK_REP:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


from jax import lax as _lax  # noqa: E402

if hasattr(_lax, "pcast"):
    pcast = _lax.pcast
else:
    def pcast(x, axis_name, *, to):
        """No varying-type system in this jax: nothing to cast."""
        del axis_name, to
        return x


# ---------------------------------------------------------------------
# Collective recording (ISSUE 14): the opt-in trace-time observer.
# ---------------------------------------------------------------------

#: Active collective sinks (obs/comm.CollectiveRecorder instances).
#: Registration is rare (a reconciliation window); the hot check in the
#: wrappers is one list-truthiness test per traced collective.  The
#: list is shared across threads deliberately: a recorder wants every
#: collective traced anywhere in its window (jit tracing happens on the
#: registering thread in practice; the lock only guards mutation).
_RECORDERS: list = []
_REC_LOCK = threading.Lock()


def add_collective_recorder(sink) -> None:
    """Register a sink whose ``note(kind, axis, shape, dtype)`` is
    called for every explicit collective issued at trace time while it
    is registered (``obs/comm.record_collectives`` is the public way)."""
    with _REC_LOCK:
        _RECORDERS.append(sink)


def remove_collective_recorder(sink) -> None:
    with _REC_LOCK:
        try:
            _RECORDERS.remove(sink)
        except ValueError:
            pass


def recorders_active() -> bool:
    return bool(_RECORDERS)


def _axis_label(axis_name) -> str:
    if isinstance(axis_name, (tuple, list)):
        return ",".join(str(a) for a in axis_name)
    return str(axis_name)


def _note(kind: str, x, axis_name) -> None:
    if not _RECORDERS:
        return
    shape = tuple(getattr(x, "shape", ()))
    dtype = str(getattr(x, "dtype", ""))
    label = _axis_label(axis_name)
    with _REC_LOCK:
        sinks = list(_RECORDERS)
    for s in sinks:
        s.note(kind, label, shape, dtype)


def psum(x, axis_name):
    """``lax.psum`` with trace-time accounting (see module docstring)."""
    _note("psum", x, axis_name)
    return _lax.psum(x, axis_name)


def pmin(x, axis_name):
    """``lax.pmin`` with trace-time accounting."""
    _note("pmin", x, axis_name)
    return _lax.pmin(x, axis_name)


def pmax(x, axis_name):
    """``lax.pmax`` with trace-time accounting."""
    _note("pmax", x, axis_name)
    return _lax.pmax(x, axis_name)


def ppermute(x, axis_name, perm):
    """``lax.ppermute`` with trace-time accounting."""
    _note("ppermute", x, axis_name)
    return _lax.ppermute(x, axis_name, perm)
