"""Shard-local matrix generation: each worker builds its own blocks.

Parity with ``init_matrix`` (main.cpp:128-149): the reference fills each
rank's strip from the generator formula with zero communication, using the
local→global index walk.  Here every worker of the mesh materializes its
cyclic block rows of the (padded) global matrix — or of the augmented
``[A | I]`` tensor — directly on device inside shard_map, so a
generator-driven solve never materializes an n×n array on the host.  This
is the front end that makes the 65536-class sizes reachable: host memory
stays O(1), device memory is the sharded tensor itself.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from .compat import shard_map
from jax.sharding import PartitionSpec

from ..ops.generators import GENERATORS
from .layout import CyclicLayout
from .mesh import AXIS


def _local_blocks(k, *, lay: CyclicLayout, fn, dtype, augmented: bool):
    """Worker ``k``'s (bpw, m, N) blocks of padded A — or (bpw, m, 2N) of
    [A | I] — generated from global indices (local_to_global semantics,
    main.cpp:118-123/128-149)."""
    p, m, bpw, N, n = lay.p, lay.m, lay.blocks_per_worker, lay.N, lay.n
    gidx = jnp.arange(bpw) * p + k                     # global block rows
    gi = (gidx[:, None] * m + jnp.arange(m)[None, :])[:, :, None]  # (bpw,m,1)
    gj = jnp.arange(N)[None, None, :]                  # (1, 1, N)
    eye = (gi == gj).astype(dtype)                     # (bpw, m, N)
    vals = jnp.broadcast_to(fn(gi, gj), eye.shape).astype(dtype)
    # Identity padding (ops/padding.py semantics): outside the n×n window
    # A continues as I, which inverts to I — no ragged math on device.
    a_part = jnp.where((gi < n) & (gj < n), vals, eye)
    if not augmented:
        return a_part
    return jnp.concatenate([a_part, eye], axis=2)      # [A | I]


@partial(jax.jit, static_argnames=("fn_name", "lay", "mesh", "dtype",
                                   "augmented"))
def sharded_generate(fn_name: str, lay: CyclicLayout, mesh,
                     dtype=jnp.float32, augmented: bool = False):
    """Generate the cyclic block tensor for ``fn_name`` over ``mesh``.

    Returns a (Nr, m, N) — or (Nr, m, 2N) when ``augmented`` — block tensor
    in cyclic storage order, sharded over axis 0, built with zero host
    memory and zero communication.
    """
    fn = GENERATORS[fn_name]

    def worker():
        k = lax.axis_index(AXIS)
        return _local_blocks(k, lay=lay, fn=fn, dtype=dtype,
                             augmented=augmented)

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(),
        out_specs=PartitionSpec(AXIS, None, None),
    )()
