"""Distributed IN-PLACE block Gauss–Jordan on the 2D block-cyclic mesh.

The 2D counterpart of ``sharded_inplace.py``: the working set is the
(Nr, m, N) 2D-cyclic block tensor of A alone — per-worker memory
O(N²/(pr·pc)), HALF the augmented 2D path's O(N·2N/(pr·pc)) — and every
step does half the flops (the eliminate matmul spans Wc = N/pc columns,
not 2N/pc).  Pivot choices and the result are identical to the augmented
engines (reference algorithm: main.cpp:953-1204).

The pivot probe is COLUMN-PARALLEL (round 4): the t-chunk panel is
broadcast along "pc" once per step — the same (bpr, m, m) panel the
eliminate needs as its multipliers, so the broadcast is not an extra
collective — and every mesh column probes the 1/pc slice of live slots
``s0+kc, s0+kc+pc, ...`` (the unrolled loop also shrinks the window to
slots [t//pr, bpr); the reference probes the same window,
main.cpp:1039).  Probe time therefore scales with pr·pc.  Earlier
rounds probed on the owning mesh column only (pr-fold), which was
already a fix over the augmented 2D path's all-columns-probe-everything
waste (VERDICT r2 weak #3) but left pc−1 columns idle in the probe.

In-place bookkeeping on a column-sharded layout: the row-swap history must
be replayed as *column* swaps in reverse after the loop, and a column
block may live on a different mesh column than its swap partner — each
replay step exchanges the two (bpr, m, m) panels with one-hot psums along
"pc" (the only communication the unscramble needs).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from .compat import pcast, pmin, psum, shard_map
from jax.sharding import Mesh, PartitionSpec

from ..config import eps_for
from ..ops.block_inverse import probe_blocks
from ..ops.norms import block_inf_norms
from .layout import CyclicLayout2D
from .mesh import AXIS_C, AXIS_R
from .sharded_inplace import MAX_UNROLL_NR
from .upcast import upcast_sub_fp32

BOTH = (AXIS_R, AXIS_C)
_SPEC_W = PartitionSpec(AXIS_R, None, AXIS_C)

PROBE_LAYOUTS = ("auto", "column", "owner")


def resolve_probe_layout(probe_layout: str, mesh: Mesh | None = None) -> bool:
    """Per-backend probe layout switch (VERDICT r4 weak #6) -> probe_cols.

    "column" (True): the round-4 column-parallel probe — every mesh
    column probes a 1/pc slice of the broadcast t-chunk panel.  Right
    for REAL chips, where probe cost is candidate-proportional (the
    measured TPU regime): probe time scales with pr·pc.

    "owner" (False): the round-3 owner-column probe — only the mesh
    column owning chunk t probes (a ``lax.cond`` skips the rest).
    Right for the shared-core virtual CPU mesh, where the probe's
    sequential small-block loop is batch-INSENSITIVE: pc probe
    invocations on shared silicon cost ~pc× more wall time than one
    (measured ~27% on the 2×4 mesh — benchmarks/PHASES.md round-4
    footnote), the exact opposite of real hardware.

    "auto": column on TPU, owner elsewhere.  Pivot choices are bitwise
    identical either way — every candidate is probed by exactly one
    device from the same broadcast values (pinned by the 2D parity
    suite's cross-layout test).
    """
    if probe_layout not in PROBE_LAYOUTS:
        raise ValueError(f"probe_layout {probe_layout!r}: choose from "
                         f"{'/'.join(PROBE_LAYOUTS)}")
    if probe_layout == "auto":
        # Decide per MESH, not per process: a CPU mesh on a TPU-attached
        # host is still the shared-silicon regime the owner layout is
        # for (and vice versa).
        if mesh is not None:
            return mesh.devices.flat[0].platform == "tpu"
        return jax.default_backend() == "tpu"
    return probe_layout == "column"


def _probe_candidates(chunk_all, tt, *, lay: CyclicLayout2D, eps,
                      use_pallas: bool, probe_cols: bool,
                      static_s0: int | None, full_window: bool = False):
    """The 2D pivot probe under either layout.

    Returns ``(invs, sing, idx)`` where ``idx`` are the local slots of
    ``chunk_all`` THIS worker probed (clipped; callers mask
    ``idx < bpr``).  ``static_s0`` is the unrolled engines' static live
    window start (t // pr), or None for the traced engines (full window
    + the quarter ladder).  ``full_window=True`` disables the ladder —
    required by the SWAP-FREE engine, whose dead rows are scattered (the
    ladder's "slots below t//stride are dead" invariant only holds when
    rows are physically swapped into place).  With ``probe_cols=False``
    non-owner mesh columns skip the batched inverse entirely (identity
    blocks flagged singular, masked out by the caller's validity
    test)."""
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    kc = lax.axis_index(AXIS_C)
    if probe_cols:
        if static_s0 is not None:
            wnd = -(-(bpr - static_s0) // pc)
            idx = static_s0 + kc + jnp.arange(wnd) * pc
            cands = jnp.take(chunk_all, jnp.clip(idx, 0, bpr - 1), axis=0)
            invs, sing = probe_blocks(cands, eps, use_pallas)
        else:
            from ..ops.block_inverse import probe_blocks_quarter_masked

            wnd = -(-bpr // pc)
            idx = kc + jnp.arange(wnd) * pc
            cands = jnp.take(chunk_all, jnp.clip(idx, 0, bpr - 1), axis=0)
            if full_window:
                invs, sing = probe_blocks(cands, eps, use_pallas)
            else:
                invs, sing = probe_blocks_quarter_masked(
                    cands, tt, pc * pr, eps, use_pallas)
        return invs, sing, idx

    own_c = kc == (tt % pc)
    # ``+ 0 * kc`` stamps the slot vector with the "pc" varying tag the
    # downstream whole-mesh collectives require (every worker's value is
    # numerically identical).
    if static_s0 is not None:
        idx = static_s0 + jnp.arange(bpr - static_s0) + 0 * kc
        cands = chunk_all[static_s0:]
        probe = partial(probe_blocks, eps=eps, use_pallas=use_pallas)
    elif full_window:
        idx = jnp.arange(bpr) + 0 * kc
        cands = chunk_all
        probe = partial(probe_blocks, eps=eps, use_pallas=use_pallas)
    else:
        from ..ops.block_inverse import probe_blocks_quarter_masked

        idx = jnp.arange(bpr) + 0 * kc
        cands = chunk_all
        probe = partial(probe_blocks_quarter_masked, t=tt, stride=pr,
                        eps=eps, use_pallas=use_pallas)

    def skip(c):
        # Identity blocks flagged singular; the never-taken where joins
        # the constants with c's device-varying type so both cond
        # branches agree under shard_map's varying-type check.
        w = c.shape[0]
        eye = jnp.broadcast_to(jnp.eye(m, dtype=c.dtype), (w, m, m))
        f = jnp.zeros((), bool)
        return (jnp.where(f, c, eye),
                jnp.where(f, c[:, 0, 0] == 0, True))

    invs, sing = lax.cond(own_c, probe, skip, cands)
    return invs, sing, idx


def _step2d(t: int, Wloc, singular, *, lay: CyclicLayout2D, eps, precision,
            use_pallas: bool, probe_cols: bool = True):
    """One super-step (static ``t``) on one worker's (bpr, m, Wc) shard.

    COLUMN-PARALLEL PROBE (round 4): the t-chunk panel is broadcast along
    "pc" once, BEFORE the probe (it is the same (bpr, m, m) panel the
    eliminate needs as E — one psum serves both, so per-step collective
    bytes are unchanged up to one tiny (m, m) swap fix-up), and every
    mesh column probes the 1/pc slice of live slots ``s0+kc, s0+kc+pc,
    ...``.  This removes the idle-columns waste the round-3 engine had
    (probe on the owner column only, pc−1 columns in a lax.cond skip):
    probe time scales with pr·pc instead of pr.  Pivot selection is
    bitwise unchanged — every candidate is probed by exactly one device
    from the identical broadcast values, and the composite-key pmin
    already reduces over the whole mesh."""
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    u_t = t // pc                               # owner column's local chunk
    own_c = kc == (t % pc)
    s0 = t // pr                                # min live slot on any mesh row

    # --- CHUNK BROADCAST along "pc" (pre-swap): candidates AND (after
    # the swap fix-up below) the eliminate multipliers.
    chunk = Wloc[:, :, u_t * m:(u_t + 1) * m]   # (bpr, m, m)
    chunk_all = psum(
        jnp.where(own_c, chunk, jnp.asarray(0, dtype)), AXIS_C)

    # --- PIVOT PROBE (layout per resolve_probe_layout).
    invs, sing, idx = _probe_candidates(
        chunk_all, jnp.int32(t), lay=lay, eps=eps, use_pallas=use_pallas,
        probe_cols=probe_cols, static_s0=s0)
    gidx = idx * pr + kr                        # global block rows probed
    valid = (idx < bpr) & (gidx >= t) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]
    g_cand = gidx[slot_best]

    # --- PIVOT REDUCTION over the whole mesh; ties to lowest global row.
    kmin = pmin(my_key, BOTH)
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), BOTH)
    singular = singular | ~jnp.isfinite(kmin)
    i_won = (my_key == kmin) & (g_cand == win_g)
    g_piv = psum(jnp.where(i_won, g_cand, 0), BOTH)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0), BOTH
    ).astype(dtype)

    # --- ROW BROADCASTS along "pr": (m, Wc) slices — half the augmented
    # path's bytes (main.cpp:1097 / 1122-1129).
    own_piv = kr == (g_piv % pr)
    slot_piv = jnp.where(own_piv, g_piv // pr, 0)
    row_piv = psum(
        jnp.where(own_piv,
                  lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False), 0.0),
        AXIS_R,
    )                                           # (m, Wc)
    own_t = kr == (t % pr)
    slot_t = t // pr                            # static (== s0)
    row_t = psum(
        jnp.where(own_t, Wloc[slot_t], 0.0), AXIS_R
    )                                           # (m, Wc)

    # --- SWAP-BY-COPY (main.cpp:1093-1131); row-granular select (one
    # (m, Wc) slot), not a full-shard where.
    cur_piv = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, row_t, cur_piv), slot_piv, 0
    )

    # --- NORMALIZE; the owner column's t-chunk of the pivot row becomes H
    # (in-place column replacement, ops/jordan_inplace.py semantics).
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, Wc)
    prow = jnp.where(own_c, prow.at[:, u_t * m:(u_t + 1) * m].set(H), prow)

    # --- MULTIPLIERS from the pre-swap broadcast + swap fix-up: the slot
    # that received old row t in the swap (slot_piv on piv's mesh row)
    # needs old row t's t-chunk — broadcast along "pc" as one (m, m)
    # psum (the only collective this step adds vs round 3); the slot now
    # holding global row t is zeroed (its multiplier is the prow write).
    row_t_chunk = psum(
        jnp.where(own_c, row_t[:, u_t * m:(u_t + 1) * m], 0.0), AXIS_C
    ).astype(dtype)                             # (m, m)
    cur_Epiv = lax.dynamic_index_in_dim(chunk_all, slot_piv, 0, False)
    E = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_piv, row_t_chunk, cur_Epiv), slot_piv, 0
    )
    gr = jnp.arange(bpr) * pr + kr
    E = jnp.where((gr == t)[:, None, None], jnp.asarray(0, dtype), E)
    # Chunk-granular zero of the owner column's t-chunk.
    cur_chunk = Wloc[:, :, u_t * m:(u_t + 1) * m]
    Wloc = Wloc.at[:, :, u_t * m:(u_t + 1) * m].set(
        jnp.where(own_c, jnp.zeros_like(cur_chunk), cur_chunk)
    )

    # --- ELIMINATE: one local MXU matmul over the whole shard.
    update = jnp.matmul(E.reshape(bpr * m, m), prow, precision=precision)
    Wloc = Wloc - update.reshape(Wloc.shape)

    # Row t becomes the normalized pivot row (owning mesh row only);
    # row-granular select.
    Wloc = Wloc.at[slot_t].set(jnp.where(own_t, prow, Wloc[slot_t]))
    return Wloc, singular, g_piv


def _step2d_swapfree(t, Wloc, alive, singular, pos, ipos, swaps, *,
                     lay: CyclicLayout2D, eps, precision,
                     use_pallas: bool, probe_cols: bool):
    """One super-step of the SWAP-FREE engine on one worker's
    (bpr, m, Wc) 2D shard — the 2D twin of
    sharded_inplace.py::_step_swapfree.

    Deleted relative to ``_step2d_fori``: the row_t broadcast along
    "pr", the (m, m) swap fix-up psum along "pc", AND the entire
    per-step psum unscramble after the loop (2 x (bpr, m, m) along "pc"
    per step) — rows and columns are repaired once at the end by
    bucketed ppermute permutations along their own mesh axes
    (permute.py; residency capped at one shard, so gather=False holds).
    Per step the collective bill is: chunk psum along "pc" (still
    needed — candidates and multipliers), the pivot reduction, and ONE
    (m, Wc) pivot-row psum along "pr".  Pivot parity is exact, ties
    included (swap-coordinate tie rule via the replicated pos carry).
    """
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    z = jnp.int32(0)
    tt = jnp.asarray(t, jnp.int32)
    u_t = tt // pc
    own_c = kc == (tt % pc)

    # --- CHUNK BROADCAST along "pc": candidates + multipliers.
    chunk = lax.dynamic_slice(Wloc, (z, z, u_t * m), (bpr, m, m))
    chunk_all = psum(
        jnp.where(own_c, chunk, jnp.asarray(0, dtype)), AXIS_C)

    # --- PROBE over all slots (alive-masked; the scattered dead rows
    # admit no static shrink), under either probe layout.
    invs, sing, idx = _probe_candidates(
        chunk_all, tt, lay=lay, eps=eps, use_pallas=use_pallas,
        probe_cols=probe_cols, static_s0=None, full_window=True)
    safe_idx = jnp.clip(idx, 0, bpr - 1)
    gidx = idx * pr + kr
    alive_i = jnp.take(alive, safe_idx)
    valid = (idx < bpr) & alive_i & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    posg = jnp.take(pos, jnp.clip(gidx, 0, lay.Nr - 1))
    lmin = jnp.min(key)
    slot_best = jnp.argmin(jnp.where(key == lmin, posg, lay.Nr))
    my_key = lmin
    my_pos = posg[slot_best]

    # --- PIVOT REDUCTION over the whole mesh, ties by swap coordinate.
    kmin = pmin(my_key, BOTH)
    finite = jnp.isfinite(kmin)
    win_pos = pmin(jnp.where(my_key == kmin, my_pos, lay.Nr), BOTH)
    singular = singular | ~finite
    i_won = (my_key == kmin) & (my_pos == win_pos) & finite
    g_piv = psum(jnp.where(i_won, gidx[slot_best], 0), BOTH)
    g_piv = jnp.where(finite, g_piv, ipos[tt])
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0), BOTH
    ).astype(dtype)

    # --- THE one row broadcast along "pr": the pivot's physical row.
    own_piv_r = kr == (g_piv % pr)
    slot_piv = jnp.where(own_piv_r, g_piv // pr, 0)
    row_piv = psum(
        jnp.where(own_piv_r,
                  lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False), 0.0),
        AXIS_R,
    )                                           # (m, Wc)

    # --- NORMALIZE; the owner column's t-chunk becomes H.
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, Wc)
    prow_H = lax.dynamic_update_slice(prow, H, (z, u_t * m))
    prow = jnp.where(own_c, prow_H, prow)

    # --- ELIMINATE every row except the pivot's PHYSICAL row.
    gr = jnp.arange(bpr) * pr + kr
    E = jnp.where((gr == g_piv)[:, None, None], jnp.asarray(0, dtype),
                  chunk_all)
    # ``chunk`` (sliced at the top) is still current — Wloc has not been
    # written yet in this step, unlike the swap engines.
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_c, jnp.zeros_like(chunk), chunk),
        (z, z, u_t * m))
    update = jnp.matmul(E.reshape(bpr * m, m), prow, precision=precision)
    Wloc = Wloc - update.reshape(Wloc.shape)
    cur = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv_r, prow, cur), slot_piv, 0)

    # --- BOOKKEEPING (identical to the 1D twin; int32 throughout).
    alive = alive & (gr != g_piv)
    g32 = g_piv.astype(jnp.int32)
    piv_pos = pos[g32]
    x = ipos[tt]
    pos = pos.at[x].set(piv_pos).at[g32].set(tt)
    ipos = ipos.at[tt].set(g32).at[piv_pos].set(x)
    swaps = swaps.at[tt].set(piv_pos)
    return Wloc, alive, singular, pos, ipos, swaps


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "probe_cols"))
def _sharded_jordan2d_inplace_swapfree(W, mesh, lay: CyclicLayout2D, eps,
                                       precision, use_pallas,
                                       probe_cols=True):
    """The swap-free 2D engine (fori_loop; any Nr): per step it drops
    the row_t psum, the swap fix-up, and the entire per-step psum
    unscramble relative to the swap engines; rows AND columns are
    repaired ONCE at the end by bucketed ``ppermute`` permutations
    (permute.py) — the row permutation moves data only along the "pr"
    axis, the column permutation only along "pc", each in axis−1
    single-hop rounds with residency capped at one shard (N²/(pr·pc)
    elements), so the engine holds the ``gather=False`` memory contract
    like its 1D twin.  Bit-matches the swap engines on NONSINGULAR
    inputs, ties included (all-singular inputs pin different benign
    targets — both flag singular, the arrays diverge bitwise)."""
    def worker(Wloc):
        def body(t, carry):
            Wl, alive, sing, pos, ipos, swaps = carry
            return _step2d_swapfree(t, Wl, alive, sing, pos, ipos, swaps,
                                    lay=lay, eps=eps, precision=precision,
                                    use_pallas=use_pallas,
                                    probe_cols=probe_cols)

        vary = lambda v: pcast(v, BOTH, to='varying')  # noqa: E731
        alive0 = vary(jnp.ones((lay.bpr,), bool))
        sing0 = vary(jnp.asarray(False))
        pos0 = vary(jnp.arange(lay.Nr, dtype=jnp.int32))
        ipos0 = vary(jnp.arange(lay.Nr, dtype=jnp.int32))
        swaps0 = vary(jnp.zeros((lay.Nr,), jnp.int32))
        Wloc, alive, singular, pos, ipos, swaps = lax.fori_loop(
            0, lay.Nr, body, (Wloc, alive0, sing0, pos0, ipos0, swaps0))

        from ..ops.jordan_inplace import compose_swap_perm

        from .permute import ppermute_bucketed

        # --- COLUMN permutation along "pc" alone: natural column block
        # j is input column cols[j]; invert so each stored chunk knows
        # its destination (input chunk c belongs at natural icols[c]).
        cols = compose_swap_perm(swaps, lay.Nr)
        icols = jnp.zeros_like(cols).at[cols].set(
            jnp.arange(lay.Nr, dtype=jnp.int32) + 0 * cols)
        chunks = Wloc.reshape(lay.bpr, lay.m, lay.bc1, lay.m)
        chunks = jnp.moveaxis(chunks, 2, 0)     # (bc1, bpr, m, m)
        chunks = ppermute_bucketed(chunks, icols, AXIS_C, lay.pc)
        Wloc = jnp.moveaxis(chunks, 0, 2).reshape(
            lay.bpr, lay.m, lay.bc1 * lay.m)
        # --- ROW permutation along "pr" alone: physical row x (slot
        # x // pr on mesh row x % pr) belongs at natural row pos[x].
        Wloc = ppermute_bucketed(Wloc, pos, AXIS_R, lay.pr)
        return Wloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=_SPEC_W,
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C)),
    )(W)


def _unscramble_step(t: int, piv, Wloc, *, lay: CyclicLayout2D):
    """Swap global column blocks ``t`` (static) and ``piv`` (traced) across
    the column-sharded layout: one-hot psum exchange along "pc"."""
    pc, m, bpr = lay.pc, lay.m, lay.bpr
    kc = lax.axis_index(AXIS_C)
    u_t = t // pc
    own_ct = kc == (t % pc)
    own_cp = kc == (piv % pc)
    up = jnp.where(own_cp, piv // pc, 0)

    col_t = psum(
        jnp.where(own_ct, Wloc[:, :, u_t * m:(u_t + 1) * m], 0.0), AXIS_C
    )
    loc_p = lax.dynamic_slice(Wloc, (0, 0, up * m), (bpr, m, m))
    col_p = psum(jnp.where(own_cp, loc_p, 0.0), AXIS_C)
    # Chunk-granular writes: col_t into piv's chunk first, then col_p into
    # t's chunk — when t == piv both land on the same chunk with the same
    # value.
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_cp, col_t, loc_p), (0, 0, up * m)
    )
    cur_t = Wloc[:, :, u_t * m:(u_t + 1) * m]
    return Wloc.at[:, :, u_t * m:(u_t + 1) * m].set(
        jnp.where(own_ct, col_p, cur_t)
    )


def _step2d_fori(t, Wloc, singular, swaps, *, lay: CyclicLayout2D, eps,
                 precision, use_pallas: bool, probe_cols: bool = True):
    """One super-step with a TRACED ``t`` — the fori_loop body behind
    ``_sharded_jordan2d_inplace_fori``.  Same arithmetic and pivot
    choices as ``_step2d``; the probe covers this worker's slot slice
    with dead slots masked, shrunk by the quarter-window ladder
    (probe_blocks_quarter_masked; deadness pinned by
    tests/test_jordan2d_inplace.py::
    test_quarter_ladder_skipped_slots_are_dead), and all chunk offsets
    go through ``lax.dynamic_slice``."""
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    u_t = t // pc                               # owner column's local chunk
    own_c = kc == (t % pc)

    # --- CHUNK BROADCAST along "pc" (pre-swap): candidates + (after the
    # swap fix-up) the eliminate multipliers — see _step2d.
    chunk = lax.dynamic_slice(Wloc, (0, 0, u_t * m), (bpr, m, m))
    chunk_all = psum(
        jnp.where(own_c, chunk, jnp.asarray(0, dtype)), AXIS_C)

    # --- PIVOT PROBE (layout per resolve_probe_layout; traced t ->
    # masked full window with the half cut).
    invs, sing, idx = _probe_candidates(
        chunk_all, t, lay=lay, eps=eps, use_pallas=use_pallas,
        probe_cols=probe_cols, static_s0=None)
    gidx = idx * pr + kr                        # global block rows probed
    valid = (idx < bpr) & (gidx >= t) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]
    g_cand = gidx[slot_best]

    # --- PIVOT REDUCTION over the whole mesh (identical to _step2d).
    kmin = pmin(my_key, BOTH)
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), BOTH)
    singular = singular | ~jnp.isfinite(kmin)
    i_won = (my_key == kmin) & (g_cand == win_g)
    g_piv = psum(jnp.where(i_won, g_cand, 0), BOTH)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0), BOTH
    ).astype(dtype)

    # --- ROW BROADCASTS along "pr": (m, Wc) slices.
    own_piv = kr == (g_piv % pr)
    slot_piv = jnp.where(own_piv, g_piv // pr, 0)
    row_piv = psum(
        jnp.where(own_piv,
                  lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False), 0.0),
        AXIS_R,
    )                                           # (m, Wc)
    own_t = kr == (t % pr)
    slot_t = t // pr
    row_t = psum(
        jnp.where(own_t,
                  lax.dynamic_index_in_dim(Wloc, slot_t, 0, False), 0.0),
        AXIS_R,
    )                                           # (m, Wc)

    # --- SWAP-BY-COPY, row-granular.
    cur_piv = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, row_t, cur_piv), slot_piv, 0
    )

    # --- NORMALIZE; the owner column's t-chunk of the pivot row becomes H.
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, Wc)
    prow_H = lax.dynamic_update_slice(prow, H, (0, u_t * m))
    prow = jnp.where(own_c, prow_H, prow)

    # --- MULTIPLIERS from the pre-swap broadcast + swap fix-up (see
    # _step2d): one extra (m, m) psum, no second panel broadcast.
    row_t_chunk = psum(
        jnp.where(own_c,
                  lax.dynamic_slice(row_t, (0, u_t * m), (m, m)), 0.0),
        AXIS_C,
    ).astype(dtype)                             # (m, m)
    cur_Epiv = lax.dynamic_index_in_dim(chunk_all, slot_piv, 0, False)
    E = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_piv, row_t_chunk, cur_Epiv), slot_piv, 0
    )
    gr = jnp.arange(bpr) * pr + kr
    E = jnp.where((gr == t)[:, None, None], jnp.asarray(0, dtype), E)
    cur_chunk = lax.dynamic_slice(Wloc, (0, 0, u_t * m), (bpr, m, m))
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_c, jnp.zeros_like(cur_chunk), cur_chunk),
        (0, 0, u_t * m))

    # --- ELIMINATE: one local MXU matmul over the whole shard.
    update = jnp.matmul(E.reshape(bpr * m, m), prow, precision=precision)
    Wloc = Wloc - update.reshape(Wloc.shape)

    # Row t becomes the normalized pivot row (owning mesh row only).
    cur_t = lax.dynamic_index_in_dim(Wloc, slot_t, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_t, prow, cur_t), slot_t, 0
    )
    return Wloc, singular, swaps.at[t].set(g_piv.astype(jnp.int32))


def _unscramble_step_fori(t, piv, Wloc, *, lay: CyclicLayout2D):
    """``_unscramble_step`` with a TRACED ``t``: swap global column
    blocks ``t`` and ``piv`` across the column-sharded layout.  Indices
    are int32 throughout, incl. literal zeros (x64 would make bare 0
    int64 against the int32 swap history)."""
    pc, m, bpr = lay.pc, lay.m, lay.bpr
    kc = lax.axis_index(AXIS_C)
    z = jnp.int32(0)
    t = jnp.asarray(t, jnp.int32)
    u_t = t // pc
    own_ct = kc == (t % pc)
    own_cp = kc == (piv % pc)
    up = jnp.where(own_cp, piv // pc, z)

    loc_t = lax.dynamic_slice(Wloc, (z, z, u_t * m), (bpr, m, m))
    col_t = psum(jnp.where(own_ct, loc_t, 0.0), AXIS_C)
    loc_p = lax.dynamic_slice(Wloc, (z, z, up * m), (bpr, m, m))
    col_p = psum(jnp.where(own_cp, loc_p, 0.0), AXIS_C)
    # Chunk-granular writes, same order as the static version: col_t into
    # piv's chunk first, then col_p into t's chunk.
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_cp, col_t, loc_p), (z, z, up * m)
    )
    cur_t = lax.dynamic_slice(Wloc, (z, z, u_t * m), (bpr, m, m))
    return lax.dynamic_update_slice(
        Wloc, jnp.where(own_ct, col_p, cur_t), (z, z, u_t * m)
    )


def _gstep2d(t, j: int, Wloc, Uloc, Ploc, singular, *, lay: CyclicLayout2D,
             eps, precision, use_pallas: bool, probe_cols: bool = True):
    """One inner step of a delayed-group-update group on one worker's
    (bpr, m, Wc) 2D shard — the 2D port of sharded_inplace.py::_gstep
    (reference hot loop main.cpp:1136-1194).

    ``t`` may be a Python int (unrolled: static probe window) or a
    traced int32 (fori: masked window + half cut); ``j`` is static.

    Grouped state on the 2D layout: ``Uloc`` (bpr, m, kg·m) pending
    panel multipliers, row-sharded along "pr" and REPLICATED along "pc"
    (it is exactly the E-panel the plain step already broadcasts along
    "pc" every step — the grouped engine keeps it for the whole group);
    ``Ploc`` (kg·m, Wc) finalized pivot rows, column-sharded like W and
    replicated along "pr".  The group-end trailing update is therefore
    one LOCAL (bpr·m, kg·m) x (kg·m, Wc) matmul — zero communication.

    Collective accounting vs the plain ``_step2d``: the two (m, Wc) row
    psums along "pr" and the (m, m) swap fix-up fuse into ONE stacked
    (2m, Wc + kg·m + m) psum (carrying both rows, their U rows, and the
    eager chunk's t-block); the chunk psum along "pc" and the pivot
    reduction stay as-is.
    """
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    static_t = isinstance(t, int)
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    Wc = Wloc.shape[-1]
    Uw = Uloc.shape[-1]
    z = jnp.int32(0)
    tt = jnp.asarray(t, jnp.int32)
    u_t = tt // pc                              # owner column's local chunk
    own_c = kc == (tt % pc)

    # --- EAGER CHUNK (owner column) + BROADCAST along "pc": W's t-chunk
    # minus pending panels, on all rows (Jordan updates finalized rows
    # too, so U's column j needs every row's eager value).
    chunk = lax.dynamic_slice(Wloc, (z, z, u_t * m), (bpr, m, m))
    if j:
        Ptc = lax.dynamic_slice(Ploc, (z, u_t * m), (j * m, m))
        chunk = chunk - jnp.matmul(
            Uloc[:, :, :j * m].reshape(bpr * m, j * m), Ptc,
            precision=precision).reshape(bpr, m, m)
    chunk_all = psum(
        jnp.where(own_c, chunk, jnp.asarray(0, dtype)), AXIS_C)

    # --- PIVOT PROBE (layout per resolve_probe_layout; main.cpp:1039).
    invs, sing, idx = _probe_candidates(
        chunk_all, tt, lay=lay, eps=eps, use_pallas=use_pallas,
        probe_cols=probe_cols, static_s0=(t // pr if static_t else None))
    gidx = idx * pr + kr
    valid = (idx < bpr) & (gidx >= tt) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]
    g_cand = gidx[slot_best]

    # --- PIVOT REDUCTION over the whole mesh + the all-singular pin
    # (H := 0, g_piv := t — both flavors stay bit-equal on singular
    # inputs; the flags make the output invalid anyway).
    kmin = pmin(my_key, BOTH)
    finite = jnp.isfinite(kmin)
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), BOTH)
    singular = singular | ~finite
    i_won = (my_key == kmin) & (g_cand == win_g) & finite
    g_piv = psum(jnp.where(i_won, g_cand, 0), BOTH)
    g_piv = jnp.where(finite, g_piv, tt.astype(g_piv.dtype))
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0), BOTH
    ).astype(dtype)

    # --- STACKED ROW BROADCAST along "pr": one (2m, Wc + Uw + m) psum
    # carrying [pivot stale row | its U row | 0] and [row t | its U row
    # | eager chunk t-block] (main.cpp:1097 / 1122-1129, fused).  The
    # rows are COLUMN-SHARDED, so each mesh column's row-owner (kr ==
    # row % pr) contributes its own column slice and the psum runs along
    # "pr" only; U rows and the chunk t-block are replicated along "pc",
    # so the same masking delivers them to every column without double
    # counting.
    own_piv_r = kr == (g_piv % pr)
    slot_piv = jnp.where(own_piv_r, g_piv // pr, 0)
    own_t_r = kr == (tt % pr)
    slot_t = tt // pr
    row1 = jnp.concatenate([
        lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False),
        lax.dynamic_index_in_dim(Uloc, slot_piv, 0, False),
        jnp.zeros((m, m), dtype),
    ], axis=1)
    row2 = jnp.concatenate([
        lax.dynamic_index_in_dim(Wloc, slot_t, 0, False),
        lax.dynamic_index_in_dim(Uloc, slot_t, 0, False),
        lax.dynamic_index_in_dim(chunk_all, slot_t, 0, False),
    ], axis=1)
    stacked = psum(jnp.concatenate([
        jnp.where(own_piv_r, row1, 0.0),
        jnp.where(own_t_r, row2, 0.0),
    ], axis=0), AXIS_R)                         # (2m, Wc + Uw + m)
    row_piv = stacked[:m, :Wc]
    u_p = stacked[:m, Wc:Wc + Uw]
    row_t = stacked[m:, :Wc]
    u_t_row = stacked[m:, Wc:Wc + Uw]
    col_t_blk = stacked[m:, Wc + Uw:]

    # --- SWAP-BY-COPY: piv's mesh row receives old row t in W, U, and
    # the eager chunk; the eager chunk's row t is zeroed (its multiplier
    # is the prow write).  Row-granular selects.
    cur = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv_r, row_t, cur), slot_piv, 0)
    cur = lax.dynamic_index_in_dim(Uloc, slot_piv, 0, False)
    Uloc = lax.dynamic_update_index_in_dim(
        Uloc, jnp.where(own_piv_r, u_t_row, cur), slot_piv, 0)
    cur = lax.dynamic_index_in_dim(chunk_all, slot_piv, 0, False)
    chunk_all = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_piv_r, col_t_blk, cur), slot_piv, 0)
    cur = lax.dynamic_index_in_dim(chunk_all, slot_t, 0, False)
    chunk_all = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_t_r, jnp.zeros_like(cur), cur), slot_t, 0)

    # --- EAGER PIVOT ROW + NORMALIZE; owner column's t-chunk becomes H.
    if j:
        row_piv = row_piv - jnp.matmul(u_p[:, :j * m], Ploc[:j * m],
                                       precision=precision)
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, Wc)
    prow_H = lax.dynamic_update_slice(prow, H, (z, u_t * m))
    prow = jnp.where(own_c, prow_H, prow)

    # --- BOOKKEEPING (grouped invariants): zero W's t-chunk and Ploc's
    # pending rows' t-chunk (owner column), finalize row t, record the
    # panel.
    cur_chunk = lax.dynamic_slice(Wloc, (z, z, u_t * m), (bpr, m, m))
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_c, jnp.zeros_like(cur_chunk), cur_chunk),
        (z, z, u_t * m))
    if j:
        cur_p = lax.dynamic_slice(Ploc, (z, u_t * m), (j * m, m))
        Ploc = lax.dynamic_update_slice(
            Ploc, jnp.where(own_c, jnp.zeros_like(cur_p), cur_p),
            (z, u_t * m))
    cur = lax.dynamic_index_in_dim(Wloc, slot_t, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_t_r, prow, cur), slot_t, 0)
    cur = lax.dynamic_index_in_dim(Uloc, slot_t, 0, False)
    Uloc = lax.dynamic_update_index_in_dim(
        Uloc, jnp.where(own_t_r, jnp.zeros_like(cur), cur), slot_t, 0)
    Uloc = Uloc.at[:, :, j * m:(j + 1) * m].set(chunk_all)
    Ploc = Ploc.at[j * m:(j + 1) * m].set(prow)
    return Wloc, Uloc, Ploc, singular, g_piv


def _group_end_2d(Wloc, Uloc, Ploc, precision):
    """One fat LOCAL trailing matmul per group: U is replicated along
    "pc", P column-sharded — no collective."""
    bpr, m, Wc = Wloc.shape
    upd = jnp.matmul(Uloc.reshape(bpr * m, -1), Ploc, precision=precision)
    return Wloc - upd.reshape(bpr, m, Wc)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "group", "probe_cols"))
def _sharded_jordan2d_inplace_grouped(W, mesh, lay: CyclicLayout2D, eps,
                                      precision, use_pallas, group,
                                      probe_cols=True):
    """The 2D in-place engine with delayed group updates, unrolled trace.
    Same pivot rule and contract as ``_sharded_jordan2d_inplace``;
    parity with the plain engines is to rounding (grouped summation
    order)."""
    kgrp = max(1, min(group, lay.Nr))

    def worker(Wloc):
        bpr, m, Wc = lay.bpr, lay.m, lay.N // lay.pc
        singular = pcast(jnp.asarray(False), BOTH, to='varying')
        swaps = []
        for t0 in range(0, lay.Nr, kgrp):
            kg = min(kgrp, lay.Nr - t0)
            Uloc = pcast(jnp.zeros((bpr, m, kg * m), Wloc.dtype),
                             BOTH, to='varying')
            Ploc = pcast(jnp.zeros((kg * m, Wc), Wloc.dtype),
                             BOTH, to='varying')
            for j in range(kg):
                Wloc, Uloc, Ploc, singular, g_piv = _gstep2d(
                    t0 + j, j, Wloc, Uloc, Ploc, singular, lay=lay,
                    eps=eps, precision=precision, use_pallas=use_pallas,
                    probe_cols=probe_cols)
                swaps.append(g_piv)
            Wloc = _group_end_2d(Wloc, Uloc, Ploc, precision)
        for t in reversed(range(lay.Nr)):
            Wloc = _unscramble_step(t, swaps[t], Wloc, lay=lay)
        return Wloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=_SPEC_W,
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C)),
    )(W)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "group", "probe_cols"))
def _sharded_jordan2d_inplace_grouped_fori(W, mesh, lay: CyclicLayout2D,
                                           eps, precision, use_pallas,
                                           group, probe_cols=True):
    """The grouped 2D engine with the group loop as a ``lax.fori_loop``
    (compile cost flat in Nr; the inner ``group`` steps are the only
    unrolled region).  A trailing partial group runs unrolled after the
    loop."""
    kgrp = max(1, min(group, lay.Nr))
    G, tail = divmod(lay.Nr, kgrp)

    def worker(Wloc):
        bpr, m, Wc = lay.bpr, lay.m, lay.N // lay.pc
        dtype = Wloc.dtype
        step = partial(_gstep2d, lay=lay, eps=eps, precision=precision,
                       use_pallas=use_pallas, probe_cols=probe_cols)

        def body(g, carry):
            Wl, sing, swaps = carry
            t0 = (g * kgrp).astype(jnp.int32)
            Ul = pcast(jnp.zeros((bpr, m, kgrp * m), dtype),
                           BOTH, to='varying')
            Pl = pcast(jnp.zeros((kgrp * m, Wc), dtype),
                           BOTH, to='varying')
            for j in range(kgrp):
                Wl, Ul, Pl, sing, g_piv = step(t0 + j, j, Wl, Ul, Pl, sing)
                swaps = swaps.at[t0 + j].set(g_piv.astype(jnp.int32))
            return _group_end_2d(Wl, Ul, Pl, precision), sing, swaps

        sing0 = pcast(jnp.asarray(False), BOTH, to='varying')
        swaps0 = pcast(jnp.zeros((lay.Nr,), jnp.int32), BOTH,
                           to='varying')
        Wloc, singular, swaps = lax.fori_loop(
            0, G, body, (Wloc, sing0, swaps0))

        if tail:
            Ul = pcast(jnp.zeros((bpr, m, tail * m), dtype),
                           BOTH, to='varying')
            Pl = pcast(jnp.zeros((tail * m, Wc), dtype),
                           BOTH, to='varying')
            for j in range(tail):
                Wloc, Ul, Pl, singular, g_piv = step(
                    jnp.int32(G * kgrp + j), j, Wloc, Ul, Pl, singular)
                swaps = swaps.at[G * kgrp + j].set(g_piv.astype(jnp.int32))
            Wloc = _group_end_2d(Wloc, Ul, Pl, precision)

        def unscramble(i, Wl):
            t = jnp.asarray(lay.Nr - 1 - i, jnp.int32)
            return _unscramble_step_fori(t, swaps[t], Wl, lay=lay)

        Wloc = lax.fori_loop(0, lay.Nr, unscramble, Wloc)
        return Wloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=_SPEC_W,
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C)),
    )(W)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "probe_cols"))
def _sharded_jordan2d_inplace_fori(W, mesh, lay: CyclicLayout2D, eps,
                                   precision, use_pallas, probe_cols=True):
    """The 2D in-place engine with both loops as ``lax.fori_loop``s —
    identical results to ``_sharded_jordan2d_inplace``, compile cost
    independent of Nr (the MAX_UNROLL_NR ceiling removed)."""
    def worker(Wloc):
        def body(t, carry):
            Wl, sing, swaps = carry
            return _step2d_fori(t, Wl, sing, swaps, lay=lay, eps=eps,
                                precision=precision, use_pallas=use_pallas,
                                probe_cols=probe_cols)

        sing0 = pcast(jnp.asarray(False), BOTH, to='varying')
        swaps0 = pcast(jnp.zeros((lay.Nr,), jnp.int32), BOTH,
                           to='varying')
        Wloc, singular, swaps = lax.fori_loop(
            0, lay.Nr, body, (Wloc, sing0, swaps0))

        def unscramble(i, Wl):
            # int32 throughout (x64 loop counters are int64; the swap
            # history is int32 and dynamic_slice rejects mixing).
            t = jnp.asarray(lay.Nr - 1 - i, jnp.int32)
            return _unscramble_step_fori(t, swaps[t], Wl, lay=lay)

        Wloc = lax.fori_loop(0, lay.Nr, unscramble, Wloc)
        return Wloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=_SPEC_W,
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C)),
    )(W)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "probe_cols"))
def _sharded_jordan2d_inplace(W, mesh, lay: CyclicLayout2D, eps, precision,
                              use_pallas, probe_cols=True):
    def worker(Wloc):
        singular = pcast(jnp.asarray(False), BOTH, to='varying')
        swaps = []
        for t in range(lay.Nr):
            Wloc, singular, g_piv = _step2d(
                t, Wloc, singular, lay=lay, eps=eps, precision=precision,
                use_pallas=use_pallas, probe_cols=probe_cols,
            )
            swaps.append(g_piv)
        for t in reversed(range(lay.Nr)):
            Wloc = _unscramble_step(t, swaps[t], Wloc, lay=lay)
        return Wloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=_SPEC_W,
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C)),
    )(W)


def _probe_reduce_2d(chunk_all, t: int, kr, *, lay: CyclicLayout2D, eps,
                     use_pallas: bool, probe_cols: bool, dtype):
    """Step ``t``'s pivot probe + whole-mesh reduction, factored out of
    ``_step2d`` VERBATIM (same _probe_candidates call, same collective
    multiset: two whole-mesh pmins + the g_piv psum + the (m, m) H
    psum) so the 2D lookahead engines can issue it EARLY — right after
    step t−1's critical panel, before its trailing eliminate.

    ``chunk_all`` is step ``t``'s broadcast t-chunk panel, which the
    caller already psummed along "pc" (one step ahead of schedule —
    the SAME (bpr, m, m) payload ``_step2d`` broadcasts, because the
    panel doubles as the eliminate multipliers E).  Returns the carry
    ``(chunk_all, H, g_piv, step_sing)``; ``chunk_all`` rides along
    because step ``t``'s E is built from it."""
    pr, bpr = lay.pr, lay.bpr
    invs, sing, idx = _probe_candidates(
        chunk_all, jnp.int32(t), lay=lay, eps=eps, use_pallas=use_pallas,
        probe_cols=probe_cols, static_s0=t // pr)
    gidx = idx * pr + kr
    valid = (idx < bpr) & (gidx >= t) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]
    g_cand = gidx[slot_best]

    kmin = pmin(my_key, BOTH)
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), BOTH)
    step_sing = ~jnp.isfinite(kmin)
    i_won = (my_key == kmin) & (g_cand == win_g)
    g_piv = psum(jnp.where(i_won, g_cand, 0), BOTH)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0), BOTH
    ).astype(dtype)
    return chunk_all, H, g_piv, step_sing


def _step2d_lookahead(t: int, Wloc, singular, probe, *,
                      lay: CyclicLayout2D, eps, precision,
                      use_pallas: bool, probe_cols: bool = True):
    """One super-step of the PROBE-AHEAD 2D engine (ISSUE 16).

    ``probe`` carries step ``t``'s pivot decision AND its broadcast
    t-chunk panel (``chunk_all`` — the eliminate multipliers), both
    issued at the end of step t−1.  The eliminate splits: the CRITICAL
    PANEL — the local chunk holding step t+1's pivot column on its
    owner mesh column (every column updates that chunk slot; for
    non-owners it is just that chunk's trailing update done early) —
    goes first, then step t+1's chunk broadcast + probe + reduction,
    then the TRAILING chunks.  Panel and trailing are column slices of
    ``_step2d``'s one HIGHEST-precision update matmul, so pivot
    choices, result bits, and the collective MULTISET pin identical —
    the chunk psum and the probe reduction each move one step earlier
    in the schedule; none are added."""
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    u_t = t // pc                               # owner column's local chunk
    own_c = kc == (t % pc)
    chunk_all, H, g_piv, step_sing = probe
    singular = singular | step_sing

    # --- ROW BROADCASTS along "pr" (identical to _step2d).
    own_piv = kr == (g_piv % pr)
    slot_piv = jnp.where(own_piv, g_piv // pr, 0)
    row_piv = psum(
        jnp.where(own_piv,
                  lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False), 0.0),
        AXIS_R,
    )                                           # (m, Wc)
    own_t = kr == (t % pr)
    slot_t = t // pr
    row_t = psum(
        jnp.where(own_t, Wloc[slot_t], 0.0), AXIS_R
    )                                           # (m, Wc)

    # --- SWAP-BY-COPY, row-granular (identical to _step2d).
    cur_piv = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
    Wloc = lax.dynamic_update_index_in_dim(
        Wloc, jnp.where(own_piv, row_t, cur_piv), slot_piv, 0
    )

    # --- NORMALIZE; owner column's t-chunk of the pivot row becomes H.
    prow = jnp.matmul(H, row_piv, precision=precision)      # (m, Wc)
    prow = jnp.where(own_c, prow.at[:, u_t * m:(u_t + 1) * m].set(H), prow)

    # --- MULTIPLIERS from the CARRIED broadcast + swap fix-up.
    row_t_chunk = psum(
        jnp.where(own_c, row_t[:, u_t * m:(u_t + 1) * m], 0.0), AXIS_C
    ).astype(dtype)                             # (m, m)
    cur_Epiv = lax.dynamic_index_in_dim(chunk_all, slot_piv, 0, False)
    E = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_piv, row_t_chunk, cur_Epiv), slot_piv, 0
    )
    gr = jnp.arange(bpr) * pr + kr
    E = jnp.where((gr == t)[:, None, None], jnp.asarray(0, dtype), E)
    cur_chunk = Wloc[:, :, u_t * m:(u_t + 1) * m]
    Wloc = Wloc.at[:, :, u_t * m:(u_t + 1) * m].set(
        jnp.where(own_c, jnp.zeros_like(cur_chunk), cur_chunk)
    )
    Ef = E.reshape(bpr * m, m)

    next_probe = None
    if t < lay.Nr - 1:
        # --- CRITICAL PANEL: the local chunk where global column t+1
        # lives on its owner mesh column ((t+1) % pc); the same chunk
        # slot on other columns holds a different global column and
        # simply takes its trailing update early — identical values.
        u2 = (t + 1) // pc
        c0 = u2 * m
        panel = (Wloc[:, :, c0:c0 + m]
                 - jnp.matmul(Ef, prow[:, c0:c0 + m],
                              precision=precision).reshape(bpr, m, m))
        # _step2d broadcasts the t+1 chunk AFTER its slot_t prow write —
        # apply the same overwrite to the broadcast view only (the
        # panel that re-enters Wloc stays unfixed; the final slot_t
        # write below covers it).
        panel_cand = panel.at[slot_t].set(
            jnp.where(own_t, prow[:, c0:c0 + m], panel[slot_t]))
        # --- CHUNK BROADCAST for step t+1, one step early: the SAME
        # (bpr, m, m) "pc" psum _step2d opens step t+1 with.
        own_c2 = kc == ((t + 1) % pc)
        chunk_all_next = psum(
            jnp.where(own_c2, panel_cand, jnp.asarray(0, dtype)), AXIS_C)
        # --- PROBE-AHEAD: step t+1's probe + whole-mesh reduction,
        # before the trailing eliminate.
        next_probe = _probe_reduce_2d(
            chunk_all_next, t + 1, kr, lay=lay, eps=eps,
            use_pallas=use_pallas, probe_cols=probe_cols, dtype=dtype)
        # --- TRAILING ELIMINATE: the remaining chunks.
        left = (Wloc[:, :, :c0]
                - jnp.matmul(Ef, prow[:, :c0],
                             precision=precision).reshape(bpr, m, c0))
        right = (Wloc[:, :, c0 + m:]
                 - jnp.matmul(Ef, prow[:, c0 + m:],
                              precision=precision).reshape(
                                  bpr, m, Wloc.shape[-1] - c0 - m))
        Wloc = jnp.concatenate([left, panel, right], axis=2)
    else:
        update = jnp.matmul(Ef, prow, precision=precision)
        Wloc = Wloc - update.reshape(Wloc.shape)

    # Row t becomes the normalized pivot row (owning mesh row only).
    Wloc = Wloc.at[slot_t].set(jnp.where(own_t, prow, Wloc[slot_t]))
    return Wloc, singular, g_piv, next_probe


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas",
                          "probe_cols"))
def _sharded_jordan2d_inplace_lookahead(W, mesh, lay: CyclicLayout2D, eps,
                                        precision, use_pallas,
                                        probe_cols=True):
    """The 2D in-place engine with PROBE-AHEAD scheduling (ISSUE 16):
    step t+1's chunk broadcast, probe, and pivot reduction are issued
    right after step t's critical-panel update, BEFORE its trailing
    eliminate — both collectives come off the superstep critical path.
    Unrolled only.  Results, pivot choices, and the collective multiset
    are identical to ``_sharded_jordan2d_inplace``."""
    def worker(Wloc):
        kr = lax.axis_index(AXIS_R)
        kc = lax.axis_index(AXIS_C)
        singular = pcast(jnp.asarray(False), BOTH, to='varying')
        # --- PROLOGUE: step 0's chunk broadcast + probe.
        chunk0 = psum(
            jnp.where(kc == 0, Wloc[:, :, :lay.m],
                      jnp.asarray(0, Wloc.dtype)), AXIS_C)
        probe = _probe_reduce_2d(
            chunk0, 0, kr, lay=lay, eps=eps, use_pallas=use_pallas,
            probe_cols=probe_cols, dtype=Wloc.dtype)
        swaps = []
        for t in range(lay.Nr):
            Wloc, singular, g_piv, probe = _step2d_lookahead(
                t, Wloc, singular, probe, lay=lay, eps=eps,
                precision=precision, use_pallas=use_pallas,
                probe_cols=probe_cols,
            )
            swaps.append(g_piv)
        for t in reversed(range(lay.Nr)):
            Wloc = _unscramble_step(t, swaps[t], Wloc, lay=lay)
        return Wloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=_SPEC_W,
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C)),
    )(W)


# ---------------------------------------------------------------------
# Distributed SOLVE (ISSUE 15): the [A | B] elimination on the 2D
# block-cyclic mesh — the 2D twin of sharded_inplace._solve_step.
# ---------------------------------------------------------------------


def _solve_step_2d(t, Wloc, Xloc, singular, *, lay: CyclicLayout2D,
                   nrhs: int, eps, precision, use_pallas: bool,
                   probe_cols: bool):
    """One solve super-step on one worker's (bpr, m, Wc) A shard plus
    the (bpr, m, nrhs) RHS rows — X is row-sharded along "pr" and
    REPLICATED along "pc" (the k RHS columns are tiny next to Wc; every
    mesh column applies the same X update from the same replicated
    E/prow operands, so the replicas stay bit-identical).

    ``t`` static (unrolled: the live chunk window [t//pc, bc1) shrinks
    statically per worker — per-device FLOPs ~1/(pr·pc) of the
    single-device solve's) or traced (fori: full-width updates, dead
    columns exact zeros).  Pivot choices and X bits match the
    single-device engine (same probe arithmetic per candidate off the
    one panel broadcast, same composite-key tie rule).

    Like the 1D solve there is NO in-place column replacement and NO
    unscramble: A is driven to identity and discarded.

    Collectives per step: the (bpr, m, m) panel psum along "pc", the
    whole-mesh pivot reduction, TWO stacked [A_live | X] row psums
    along "pr" — (m, (bc1 − t//pc)·m + k) unrolled, (m, Wc + k) fori —
    and the (m, m) swap fix-up psum along "pc"."""
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    static_t = isinstance(t, int)
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    Wc = Wloc.shape[-1]
    z = jnp.int32(0)
    tt = jnp.asarray(t, jnp.int32)
    u_t = tt // pc                              # owner column's local chunk
    own_c = kc == (tt % pc)

    # --- CHUNK BROADCAST along "pc": candidates + eliminate multipliers
    # (one psum serves both, the _step2d discipline).
    chunk = lax.dynamic_slice(Wloc, (z, z, u_t * m), (bpr, m, m))
    chunk_all = psum(
        jnp.where(own_c, chunk, jnp.asarray(0, dtype)), AXIS_C)

    # --- PIVOT PROBE (layout per resolve_probe_layout).
    invs, sing, idx = _probe_candidates(
        chunk_all, tt, lay=lay, eps=eps, use_pallas=use_pallas,
        probe_cols=probe_cols,
        static_s0=(t // pr if static_t else None))
    gidx = idx * pr + kr
    valid = (idx < bpr) & (gidx >= tt) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]
    g_cand = gidx[slot_best]

    # --- PIVOT REDUCTION over the whole mesh (identical to _step2d).
    kmin = pmin(my_key, BOTH)
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), BOTH)
    singular = singular | ~jnp.isfinite(kmin)
    i_won = (my_key == kmin) & (g_cand == win_g)
    g_piv = psum(jnp.where(i_won, g_cand, 0), BOTH)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0), BOTH
    ).astype(dtype)

    # --- STACKED ROW BROADCASTS along "pr": [A_live | X] of the pivot
    # row and of row t (X is replicated along "pc", so the same one-hot
    # masking delivers it to every column without double counting).
    if static_t:
        loW = (t // pc) * m                     # min live chunk offset
        live = Wc - loW
    else:
        loW = 0
        live = Wc

    def rowcat(slot):
        slot = jnp.asarray(slot, jnp.int32)
        if static_t:
            a_row = lax.dynamic_slice(Wloc, (slot, z, jnp.int32(loW)),
                                      (1, m, live))[0]
        else:
            a_row = lax.dynamic_index_in_dim(Wloc, slot, 0, False)
        return jnp.concatenate(
            [a_row, lax.dynamic_index_in_dim(Xloc, slot, 0, False)],
            axis=1)

    own_piv_r = kr == (g_piv % pr)
    slot_piv = jnp.asarray(jnp.where(own_piv_r, g_piv // pr, 0),
                           jnp.int32)
    row_piv = psum(jnp.where(own_piv_r, rowcat(slot_piv), 0.0), AXIS_R)
    own_t_r = kr == (tt % pr)
    slot_t = tt // pr
    row_t = psum(jnp.where(own_t_r, rowcat(slot_t), 0.0), AXIS_R)

    # --- SWAP-BY-COPY: pivot owner's slot receives old row t in A's
    # live columns and in X; slot t is rewritten from prow below.
    if static_t:
        cur_A = lax.dynamic_slice(Wloc, (slot_piv, z, jnp.int32(loW)),
                                  (1, m, live))
        Wloc = lax.dynamic_update_slice(
            Wloc, jnp.where(own_piv_r, row_t[None, :, :live], cur_A),
            (slot_piv, z, jnp.int32(loW)))
    else:
        cur_A = lax.dynamic_index_in_dim(Wloc, slot_piv, 0, False)
        Wloc = lax.dynamic_update_index_in_dim(
            Wloc, jnp.where(own_piv_r, row_t[:, :live], cur_A),
            slot_piv, 0)
    cur_X = lax.dynamic_index_in_dim(Xloc, slot_piv, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_piv_r, row_t[:, live:], cur_X), slot_piv, 0)

    # --- NORMALIZE: separate A/X matmuls (the single-device op
    # structure, the bit-match contract).
    prow_A = jnp.matmul(H, row_piv[:, :live], precision=precision)
    prow_X = jnp.matmul(H, row_piv[:, live:], precision=precision)

    # --- MULTIPLIERS from the pre-swap panel + the swap fix-up: the
    # slot that received old row t needs old row t's t-chunk — one
    # (m, m) psum along "pc"; the slot holding global row t is zeroed
    # (its multiplier is the prow write below).
    if static_t:
        # Owner column's t-chunk sits at the HEAD of its live slice
        # (u_t == t // pc == loW / m there).
        row_t_chunk_loc = row_t[:, :m]
    else:
        row_t_chunk_loc = lax.dynamic_slice(row_t, (z, u_t * m), (m, m))
    row_t_chunk = psum(
        jnp.where(own_c, row_t_chunk_loc, 0.0), AXIS_C).astype(dtype)
    cur_Epiv = lax.dynamic_index_in_dim(chunk_all, slot_piv, 0, False)
    E = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_piv_r, row_t_chunk, cur_Epiv),
        slot_piv, 0)
    gr = jnp.arange(bpr) * pr + kr
    E = jnp.where((gr == tt)[:, None, None], jnp.asarray(0, dtype), E)

    # --- ELIMINATE: one local MXU matmul pair over the live columns
    # and the replicated RHS.
    Ef = E.reshape(bpr * m, m)
    upd_A = jnp.matmul(Ef, prow_A, precision=precision)
    upd_X = jnp.matmul(Ef, prow_X, precision=precision)
    if static_t:
        Wloc = Wloc.at[:, :, loW:].add(-upd_A.reshape(bpr, m, live))
    else:
        Wloc = Wloc - upd_A.reshape(bpr, m, Wc)
    Xloc = Xloc - upd_X.reshape(bpr, m, nrhs)

    # Row t becomes the normalized pivot row (owning mesh row only).
    if static_t:
        cur_t = lax.dynamic_slice(Wloc, (slot_t, z, jnp.int32(loW)),
                                  (1, m, live))
        Wloc = lax.dynamic_update_slice(
            Wloc, jnp.where(own_t_r, prow_A[None], cur_t),
            (slot_t, z, jnp.int32(loW)))
    else:
        cur_t = lax.dynamic_index_in_dim(Wloc, slot_t, 0, False)
        Wloc = lax.dynamic_update_index_in_dim(
            Wloc, jnp.where(own_t_r, prow_A, cur_t), slot_t, 0)
    cur_tx = lax.dynamic_index_in_dim(Xloc, slot_t, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_t_r, prow_X, cur_tx), slot_t, 0)
    return Wloc, Xloc, singular


_SPEC_X2 = PartitionSpec(AXIS_R, None, None)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "eps", "precision",
                          "use_pallas", "probe_cols"))
def _sharded_jordan_solve_2d(W, X, mesh, lay: CyclicLayout2D, nrhs, eps,
                             precision, use_pallas, probe_cols=True):
    """The unrolled 2D solve engine (static shrinking live-chunk
    window; Nr <= MAX_UNROLL_NR)."""
    def worker(Wloc, Xloc):
        singular = pcast(jnp.asarray(False), BOTH, to='varying')
        for t in range(lay.Nr):
            Wloc, Xloc, singular = _solve_step_2d(
                t, Wloc, Xloc, singular, lay=lay, nrhs=nrhs, eps=eps,
                precision=precision, use_pallas=use_pallas,
                probe_cols=probe_cols)
        return Xloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(_SPEC_W, _SPEC_X2),
        out_specs=(_SPEC_X2, PartitionSpec(AXIS_R, AXIS_C)),
    )(W, X)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "eps", "precision",
                          "use_pallas", "probe_cols"))
def _sharded_jordan_solve_2d_fori(W, X, mesh, lay: CyclicLayout2D, nrhs,
                                  eps, precision, use_pallas,
                                  probe_cols=True):
    """The fori_loop 2D solve engine: compile cost flat in Nr —
    identical pivot choices and X bits to the unrolled flavor."""
    def worker(Wloc, Xloc):
        def body(t, carry):
            Wl, Xl, sing = carry
            return _solve_step_2d(t, Wl, Xl, sing, lay=lay, nrhs=nrhs,
                                  eps=eps, precision=precision,
                                  use_pallas=use_pallas,
                                  probe_cols=probe_cols)

        sing0 = pcast(jnp.asarray(False), BOTH, to='varying')
        Wloc, Xloc, singular = lax.fori_loop(
            0, lay.Nr, body, (Wloc, Xloc, sing0))
        return Xloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(_SPEC_W, _SPEC_X2),
        out_specs=(_SPEC_X2, PartitionSpec(AXIS_R, AXIS_C)),
    )(W, X)


def _solve_step_2d_lookahead(t: int, Wloc, Xloc, singular, probe, *,
                             lay: CyclicLayout2D, nrhs: int, eps,
                             precision, use_pallas: bool,
                             probe_cols: bool):
    """One PROBE-AHEAD 2D solve super-step (ISSUE 16): the carry holds
    step ``t``'s broadcast t-chunk panel + pivot decision, issued at
    the end of step t−1 after its critical panel.  The A eliminate
    splits panel-first / trailing-after; the X update (replicated along
    "pc") stays entirely in the trailing phase.  X bits, pivot
    sequence, and the collective multiset pin identical to
    ``_solve_step_2d``.  Unrolled only (static shrinking window)."""
    pr, pc, m, bpr = lay.pr, lay.pc, lay.m, lay.bpr
    kr = lax.axis_index(AXIS_R)
    kc = lax.axis_index(AXIS_C)
    dtype = Wloc.dtype
    Wc = Wloc.shape[-1]
    z = jnp.int32(0)
    own_c = kc == (t % pc)
    loW = (t // pc) * m                         # min live chunk offset
    live = Wc - loW
    chunk_all, H, g_piv, step_sing = probe
    singular = singular | step_sing

    # --- STACKED ROW BROADCASTS along "pr" (identical to the static
    # path of _solve_step_2d).
    def rowcat(slot):
        slot = jnp.asarray(slot, jnp.int32)
        a_row = lax.dynamic_slice(Wloc, (slot, z, jnp.int32(loW)),
                                  (1, m, live))[0]
        return jnp.concatenate(
            [a_row, lax.dynamic_index_in_dim(Xloc, slot, 0, False)],
            axis=1)

    own_piv_r = kr == (g_piv % pr)
    slot_piv = jnp.asarray(jnp.where(own_piv_r, g_piv // pr, 0),
                           jnp.int32)
    row_piv = psum(jnp.where(own_piv_r, rowcat(slot_piv), 0.0), AXIS_R)
    own_t_r = kr == (t % pr)
    slot_t = t // pr
    row_t = psum(jnp.where(own_t_r, rowcat(slot_t), 0.0), AXIS_R)

    # --- SWAP-BY-COPY (identical to _solve_step_2d).
    cur_A = lax.dynamic_slice(Wloc, (slot_piv, z, jnp.int32(loW)),
                              (1, m, live))
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_piv_r, row_t[None, :, :live], cur_A),
        (slot_piv, z, jnp.int32(loW)))
    cur_X = lax.dynamic_index_in_dim(Xloc, slot_piv, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_piv_r, row_t[:, live:], cur_X), slot_piv, 0)

    # --- NORMALIZE: separate A/X matmuls (the bit contract).
    prow_A = jnp.matmul(H, row_piv[:, :live], precision=precision)
    prow_X = jnp.matmul(H, row_piv[:, live:], precision=precision)

    # --- MULTIPLIERS from the CARRIED panel + swap fix-up (owner
    # column's t-chunk sits at the HEAD of its live slice).
    row_t_chunk = psum(
        jnp.where(own_c, row_t[:, :m], 0.0), AXIS_C).astype(dtype)
    cur_Epiv = lax.dynamic_index_in_dim(chunk_all, slot_piv, 0, False)
    E = lax.dynamic_update_index_in_dim(
        chunk_all, jnp.where(own_piv_r, row_t_chunk, cur_Epiv),
        slot_piv, 0)
    gr = jnp.arange(bpr) * pr + kr
    E = jnp.where((gr == t)[:, None, None], jnp.asarray(0, dtype), E)
    Ef = E.reshape(bpr * m, m)

    next_probe = None
    if t < lay.Nr - 1:
        # --- CRITICAL PANEL: the chunk where global column t+1 lives on
        # its owner mesh column; offset inside the live window is
        # static.
        u2 = (t + 1) // pc
        offA = u2 * m - loW
        panel = (Wloc[:, :, u2 * m:(u2 + 1) * m]
                 - jnp.matmul(Ef, prow_A[:, offA:offA + m],
                              precision=precision).reshape(bpr, m, m))
        panel_cand = panel.at[slot_t].set(
            jnp.where(own_t_r, prow_A[:, offA:offA + m], panel[slot_t]))
        # --- CHUNK BROADCAST for step t+1, one step early.
        own_c2 = kc == ((t + 1) % pc)
        chunk_all_next = psum(
            jnp.where(own_c2, panel_cand, jnp.asarray(0, dtype)), AXIS_C)
        # --- PROBE-AHEAD for step t+1.
        next_probe = _probe_reduce_2d(
            chunk_all_next, t + 1, kr, lay=lay, eps=eps,
            use_pallas=use_pallas, probe_cols=probe_cols, dtype=dtype)
        # --- TRAILING: the remaining live chunks + all of X.
        left = (Wloc[:, :, loW:u2 * m]
                - jnp.matmul(Ef, prow_A[:, :offA],
                             precision=precision).reshape(bpr, m, offA))
        right = (Wloc[:, :, (u2 + 1) * m:]
                 - jnp.matmul(Ef, prow_A[:, offA + m:],
                              precision=precision).reshape(
                                  bpr, m, live - offA - m))
        Wloc = Wloc.at[:, :, loW:].set(
            jnp.concatenate([left, panel, right], axis=2))
    else:
        upd_A = jnp.matmul(Ef, prow_A, precision=precision)
        Wloc = Wloc.at[:, :, loW:].add(-upd_A.reshape(bpr, m, live))
    upd_X = jnp.matmul(Ef, prow_X, precision=precision)
    Xloc = Xloc - upd_X.reshape(bpr, m, nrhs)

    # Row t becomes the normalized pivot row (owning mesh row only).
    # int32 indices: x64 would canonicalize the static slot to int64
    # against dynamic_slice's int32 offsets (the base-step discipline).
    st = jnp.int32(slot_t)
    cur_t = lax.dynamic_slice(Wloc, (st, z, jnp.int32(loW)),
                              (1, m, live))
    Wloc = lax.dynamic_update_slice(
        Wloc, jnp.where(own_t_r, prow_A[None], cur_t),
        (st, z, jnp.int32(loW)))
    cur_tx = lax.dynamic_index_in_dim(Xloc, slot_t, 0, False)
    Xloc = lax.dynamic_update_index_in_dim(
        Xloc, jnp.where(own_t_r, prow_X, cur_tx), slot_t, 0)
    return Wloc, Xloc, singular, next_probe


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "eps", "precision",
                          "use_pallas", "probe_cols"))
def _sharded_jordan_solve_2d_lookahead(W, X, mesh, lay: CyclicLayout2D,
                                       nrhs, eps, precision, use_pallas,
                                       probe_cols=True):
    """The PROBE-AHEAD 2D solve engine: prologue chunk broadcast +
    probe, panel/trailing split per step.  X bits, pivot sequence, and
    the collective multiset match ``_sharded_jordan_solve_2d``."""
    def worker(Wloc, Xloc):
        kr = lax.axis_index(AXIS_R)
        kc = lax.axis_index(AXIS_C)
        singular = pcast(jnp.asarray(False), BOTH, to='varying')
        chunk0 = psum(
            jnp.where(kc == 0, Wloc[:, :, :lay.m],
                      jnp.asarray(0, Wloc.dtype)), AXIS_C)
        probe = _probe_reduce_2d(
            chunk0, 0, kr, lay=lay, eps=eps, use_pallas=use_pallas,
            probe_cols=probe_cols, dtype=Wloc.dtype)
        for t in range(lay.Nr):
            Wloc, Xloc, singular, probe = _solve_step_2d_lookahead(
                t, Wloc, Xloc, singular, probe, lay=lay, nrhs=nrhs,
                eps=eps, precision=precision, use_pallas=use_pallas,
                probe_cols=probe_cols)
        return Xloc, singular[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(_SPEC_W, _SPEC_X2),
        out_specs=(_SPEC_X2, PartitionSpec(AXIS_R, AXIS_C)),
    )(W, X)


def scatter_rhs_2d(b: jnp.ndarray, lay: CyclicLayout2D, mesh: Mesh):
    """(n, k) RHS -> (Nr, m, k) zero-padded row blocks in cyclic row
    storage order, sharded along "pr" and replicated along "pc"."""
    from jax.sharding import NamedSharding

    n, k = b.shape
    bp = jnp.zeros((lay.N, k), b.dtype).at[:n].set(b)
    blocks = jnp.take(bp.reshape(lay.Nr, lay.m, k),
                      jnp.asarray(lay.row_perm(), jnp.int32), axis=0)
    return jax.device_put(blocks, NamedSharding(mesh, _SPEC_X2))


def gather_solution_2d(xb: jnp.ndarray, lay: CyclicLayout2D, n: int):
    """Cyclic row storage order -> natural order; strip the pad rows."""
    from .jordan2d import _inv_perm

    xb = jnp.take(xb, _inv_perm(jnp.asarray(lay.row_perm(), jnp.int32)),
                  axis=0)
    return xb.reshape(lay.N, -1)[:n]


def compile_sharded_jordan_solve_2d(
    Wblocks: jnp.ndarray,
    Xblocks: jnp.ndarray,
    mesh: Mesh,
    lay: CyclicLayout2D,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
    unroll: bool | None = None,
    probe_layout: str = "auto",
    lookahead: bool = False,
):
    """AOT-compile the 2D distributed solve.  ``run(W, X) ->
    (x_blocks, singular_grid)``; ``unroll=None`` picks the unrolled
    trace for Nr <= MAX_UNROLL_NR and the fori engine beyond.
    ``lookahead=True`` takes the probe-ahead schedule (unrolled only;
    identical X bits and comm inventory)."""
    from .jordan2d import resolve_use_pallas_2d

    if eps is None:
        eps = eps_for(Wblocks.dtype)
    if use_pallas is None:
        use_pallas = resolve_use_pallas_2d(Wblocks.dtype, lay.m)
    if unroll is None:
        unroll = lay.Nr <= MAX_UNROLL_NR
    probe_cols = resolve_probe_layout(probe_layout, mesh)
    nrhs = int(Xblocks.shape[-1])
    if lookahead:
        if not unroll:
            from ..driver import UsageError

            raise UsageError(
                f"engine='solve_lookahead' is unrolled-only (the "
                f"critical-panel split needs static chunk offsets) and "
                f"Nr={lay.Nr} exceeds MAX_UNROLL_NR={MAX_UNROLL_NR}; "
                f"use engine='solve_sharded' (its fori twin covers any "
                f"Nr) or a larger block_size")
        return _sharded_jordan_solve_2d_lookahead.lower(
            Wblocks, Xblocks, mesh, lay, nrhs, eps, precision,
            use_pallas, probe_cols
        ).compile()
    engine = (_sharded_jordan_solve_2d if unroll
              else _sharded_jordan_solve_2d_fori)
    return engine.lower(
        Wblocks, Xblocks, mesh, lay, nrhs, eps, precision, use_pallas,
        probe_cols
    ).compile()


def gather_inverse_inplace_2d(out: jnp.ndarray, lay: CyclicLayout2D, n: int):
    """2D-cyclic storage (both axes) -> natural order; unpad."""
    from ..ops.padding import unpad
    from .jordan2d import _inv_perm, _perms

    blocks = out.reshape(lay.Nr, lay.m, lay.Nr, lay.m)
    rowp, colp = _perms(lay, lay.Nr)
    blocks = jnp.take(jnp.take(blocks, _inv_perm(rowp), axis=0),
                      _inv_perm(colp), axis=2)
    return unpad(blocks.reshape(lay.N, lay.N), n)


def inverse_corner_2d(blocks: jnp.ndarray, lay: CyclicLayout2D, n: int,
                      max_p: int = 10):
    """Top-left min(n, max_p) corner of the inverse from its 2D-cyclic
    blocks — WITHOUT a global gather (the ``gather=False`` verbose print,
    main.cpp:459-461).

    Global row block ``i`` sits at storage slot ``(i % pr)·bpr + i // pr``
    and global column block ``j`` at chunk ``(j % pc)·(Nr // pc) + j // pc``
    (worker-major cyclic order on both axes, layout.py); only the
    ceil(corner/m)² owning blocks move — O(corner·m²·…) bytes bounded by
    the corner itself, so O(n²/(pr·pc)) per-worker memory holds.
    """
    from .layout import global_block_owner, global_to_local_block

    c = min(n, max_p)
    nb = -(-c // lay.m)
    bc = lay.Nr // lay.pc
    rows = []
    for i in range(nb):
        rpos = (global_block_owner(i, lay.pr) * lay.bpr
                + global_to_local_block(i, lay.pr))
        rows.append(jnp.concatenate([
            blocks[rpos, :, cpos * lay.m:(cpos + 1) * lay.m]
            for j in range(nb)
            for cpos in (global_block_owner(j, lay.pc) * bc
                         + global_to_local_block(j, lay.pc),)
        ], axis=1))
    return jnp.concatenate(rows, axis=0)[:c, :c]


def compile_sharded_jordan_inplace_2d(
    W: jnp.ndarray,
    mesh: Mesh,
    lay: CyclicLayout2D,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
    unroll: bool | None = None,
    group: int = 0,
    probe_layout: str = "auto",
    swapfree: bool = False,
    lookahead: bool = False,
):
    """AOT-compile the 2D in-place elimination for a (Nr, m, N) 2D-cyclic
    identity-padded block tensor.  ``run(W) -> (inverse_blocks,
    singular_grid)`` — the output IS the inverse in 2D-cyclic order.

    ``unroll=None`` picks the unrolled trace for Nr <= MAX_UNROLL_NR and
    the fori_loop engine beyond — identical results either way.
    ``group=k > 1`` takes the delayed-group-update engines (one fat
    local trailing matmul per group, fused stacked row psum per step;
    parity with the plain engines is to rounding).  ``lookahead=True``
    takes the probe-ahead engine (unrolled only; identical bits and
    comm inventory)."""
    from .jordan2d import resolve_use_pallas_2d

    if eps is None:
        eps = eps_for(W.dtype)
    if use_pallas is None:
        use_pallas = resolve_use_pallas_2d(W.dtype, lay.m)
    if unroll is None:
        unroll = lay.Nr <= MAX_UNROLL_NR
    probe_cols = resolve_probe_layout(probe_layout, mesh)
    if lookahead:
        from ..driver import UsageError

        if swapfree or (group and group > 1):
            raise UsageError(
                "lookahead=True composes only with the plain 2D engine "
                "(the panel/trailing split is defined on its per-step "
                "schedule); drop swapfree/group or drop lookahead")
        if not unroll:
            raise UsageError(
                f"the lookahead engine is unrolled-only (the critical-"
                f"panel split needs static chunk offsets) and Nr="
                f"{lay.Nr} exceeds MAX_UNROLL_NR={MAX_UNROLL_NR}; use "
                f"engine='inplace' (its fori twin) or a larger "
                f"block_size")
        return _sharded_jordan2d_inplace_lookahead.lower(
            W, mesh, lay, eps, precision, use_pallas, probe_cols
        ).compile()
    if swapfree:
        return _sharded_jordan2d_inplace_swapfree.lower(
            W, mesh, lay, eps, precision, use_pallas, probe_cols
        ).compile()
    if group and group > 1:
        engine = (_sharded_jordan2d_inplace_grouped if unroll
                  else _sharded_jordan2d_inplace_grouped_fori)
        return engine.lower(
            W, mesh, lay, eps, precision, use_pallas, group, probe_cols
        ).compile()
    engine = (_sharded_jordan2d_inplace if unroll
              else _sharded_jordan2d_inplace_fori)
    return engine.lower(
        W, mesh, lay, eps, precision, use_pallas, probe_cols
    ).compile()


@upcast_sub_fp32
def sharded_jordan_invert_inplace_2d(
    a: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
    unroll: bool | None = None,
    group: int = 0,
    probe_layout: str = "auto",
    swapfree: bool = False,
    lookahead: bool = False,
):
    """Invert (n, n) ``a`` over a 2D (pr, pc) mesh with the in-place
    engine: drop-in for ``sharded_jordan_invert_2d`` at ~half the flops,
    per-worker memory, and collective bytes.  Any Nr: the unrolled trace
    below MAX_UNROLL_NR, the fori_loop engine above (``unroll`` forces a
    choice).  ``group=k > 1`` selects the delayed-group-update engines
    (rounding-level parity with the plain engines)."""
    from .jordan2d import scatter_matrix_2d

    n = a.shape[-1]
    pr, pc = mesh.devices.shape
    lay = CyclicLayout2D.create(n, min(block_size, n), pr, pc)
    W = scatter_matrix_2d(a, lay, mesh)
    run = compile_sharded_jordan_inplace_2d(W, mesh, lay, eps, precision,
                                            use_pallas, unroll, group,
                                            probe_layout, swapfree,
                                            lookahead)
    out, singular = run(W)
    return gather_inverse_inplace_2d(out, lay, n), singular.any()


# ---------------------------------------------------------------------
# SEGMENT ENTRIES (ISSUE 20): supersteps [t0, t1) of the 2D engines as
# their own jitted executables, carry in / carry out, so a checkpointed
# runner can round-trip the carry through the host between segments.
# Same discipline as the 1D entries in sharded_inplace.py: each segment
# replays the monolithic per-step arithmetic and collective schedule
# verbatim (``_solve_step_2d`` / ``_step2d`` / ``_step2d_fori``), the
# unscramble epilogue moves to its own finalize executable, and the
# swap record rides as a (pr, pc, Nr) int32 tensor — every worker's
# slice is the same psum-broadcast pivot history, made shardable.
# ---------------------------------------------------------------------


@partial(jax.jit,
         static_argnames=("mesh", "lay", "nrhs", "t0", "t1", "eps",
                          "precision", "use_pallas", "unroll",
                          "probe_cols"))
def _sharded_jordan_solve_2d_segment(W, X, singular, mesh,
                                     lay: CyclicLayout2D, nrhs: int,
                                     t0: int, t1: int, eps, precision,
                                     use_pallas, unroll: bool,
                                     probe_cols: bool = True):
    """Supersteps [t0, t1) of the 2D distributed solve.  Unlike the
    monolithic entries this returns the A shard too — it is live carry
    between segments.  ``singular`` is the (pr, pc) per-worker flag
    grid the monolithic engines emit, in and out through the same
    spec."""
    def worker(Wloc, Xloc, sloc):
        sing = sloc[0, 0]
        if unroll:
            for t in range(t0, t1):
                Wloc, Xloc, sing = _solve_step_2d(
                    t, Wloc, Xloc, sing, lay=lay, nrhs=nrhs, eps=eps,
                    precision=precision, use_pallas=use_pallas,
                    probe_cols=probe_cols)
        else:
            def body(t, carry):
                Wl, Xl, s = carry
                return _solve_step_2d(t, Wl, Xl, s, lay=lay, nrhs=nrhs,
                                      eps=eps, precision=precision,
                                      use_pallas=use_pallas,
                                      probe_cols=probe_cols)

            Wloc, Xloc, sing = lax.fori_loop(
                t0, t1, body, (Wloc, Xloc, sing))
        return Wloc, Xloc, sing[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(_SPEC_W, _SPEC_X2, PartitionSpec(AXIS_R, AXIS_C)),
        out_specs=(_SPEC_W, _SPEC_X2, PartitionSpec(AXIS_R, AXIS_C)),
    )(W, X, singular)


@partial(jax.jit,
         static_argnames=("mesh", "lay", "t0", "t1", "eps", "precision",
                          "use_pallas", "unroll", "probe_cols"))
def _sharded_jordan2d_inplace_segment(W, singular, swaps, mesh,
                                      lay: CyclicLayout2D, t0: int,
                                      t1: int, eps, precision,
                                      use_pallas, unroll: bool,
                                      probe_cols: bool = True):
    """Supersteps [t0, t1) of the 2D in-place invert.  The unscramble
    does NOT run here — it moves to
    :func:`_sharded_jordan2d_inplace_finalize`, applied once after the
    last segment exactly where the monolithic workers apply it."""
    def worker(Wloc, sloc, swloc):
        sing = sloc[0, 0]
        sw = swloc[0, 0]
        if unroll:
            for t in range(t0, t1):
                Wloc, sing, g_piv = _step2d(
                    t, Wloc, sing, lay=lay, eps=eps, precision=precision,
                    use_pallas=use_pallas, probe_cols=probe_cols)
                sw = sw.at[t].set(g_piv.astype(jnp.int32))
        else:
            def body(t, carry):
                Wl, s, sws = carry
                return _step2d_fori(t, Wl, s, sws, lay=lay, eps=eps,
                                    precision=precision,
                                    use_pallas=use_pallas,
                                    probe_cols=probe_cols)

            Wloc, sing, sw = lax.fori_loop(t0, t1, body,
                                           (Wloc, sing, sw))
        return Wloc, sing[None, None], sw[None, None]

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C),
                  PartitionSpec(AXIS_R, AXIS_C, None)),
        out_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C),
                   PartitionSpec(AXIS_R, AXIS_C, None)),
    )(W, singular, swaps)


@partial(jax.jit, static_argnames=("mesh", "lay"))
def _sharded_jordan2d_inplace_finalize(W, swaps, mesh,
                                       lay: CyclicLayout2D):
    """The 2D invert epilogue as its own executable: replay the swap
    history in reverse through ``_unscramble_step_fori`` — pure data
    movement across the column-sharded layout, the exact loop the
    monolithic fori worker runs after its elimination sweep."""
    def worker(Wloc, swloc):
        sw = swloc[0, 0]

        def unscramble(i, Wl):
            t = jnp.asarray(lay.Nr - 1 - i, jnp.int32)
            return _unscramble_step_fori(t, sw[t], Wl, lay=lay)

        return lax.fori_loop(0, lay.Nr, unscramble, Wloc)

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=(_SPEC_W, PartitionSpec(AXIS_R, AXIS_C, None)),
        out_specs=_SPEC_W,
    )(W, swaps)
