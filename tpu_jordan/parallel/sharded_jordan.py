"""Distributed block Gauss–Jordan inversion: shard_map over a 1D mesh.

TPU-native rebuild of the reference's distributed `Jordan`
(main.cpp:953-1204) with its exact communication structure per super-step
(SURVEY.md §3.2) — but expressed as XLA collectives over a mesh instead of
MPI:

  reference (per step t)                      this file
  -------------------------------------       ----------------------------
  local pivot probe (serial loop,             batched pallas/XLA inverse of
    main.cpp:1039-1066)                         the worker's candidate blocks
  MPI_Allreduce custom PivotMin op            two-stage `lax.pmin` on a
    (main.cpp:729-744, 1000-1024, 1074)         composite (norm, worker) key
  MPI_Bcast pivot row (main.cpp:1097)         one-hot `lax.psum` of the row
  MPI_Send/Recv row swap (main.cpp:1100-31)   one-hot `lax.psum` + masked
                                                dynamic_update_slice
  local normalize + eliminate                 (bpw*m, m) @ (m, 2N) local
    (main.cpp:1133-1193)                        MXU matmul

Data layout: the augmented matrix [A | B] lives as a (Nr, m, 2N) block
tensor in *cyclic storage order* (parallel/layout.py) so that the 1D
row-block-cyclic distribution of the reference (main.cpp:118-123) is a
plain contiguous NamedSharding over axis 0.  Worker k's local slot s holds
global block row s*p + k.

Singularity is the same collective agreement as the reference
(main.cpp:1075-1083): the flag comes out of the pmin itself, so every
worker takes the same exit path with zero extra communication.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from .compat import pcast, pmin, psum, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import eps_for
from ..ops.block_inverse import probe_blocks
from ..ops.norms import block_inf_norms
from .layout import CyclicLayout, cyclic_gather_perm, cyclic_scatter_perm
from .mesh import AXIS
from .upcast import upcast_sub_fp32


def _local_step(t, Wloc, singular, *, lay: CyclicLayout, eps, precision,
                use_pallas: bool):
    """One super-step on one worker's (bpw, m, 2N) shard."""
    p, m, bpw = lay.p, lay.m, lay.blocks_per_worker
    N = lay.N
    k = lax.axis_index(AXIS)
    dtype = Wloc.dtype
    gidx = jnp.arange(bpw) * p + k          # global block row of each slot

    # --- PIVOT PROBE: batch-invert every local candidate block of column t.
    # Runs in fp32 for sub-fp32 working dtypes (same policy as
    # ops/jordan.py): a bf16 probe destroys the condition estimate.
    probe_dtype = jnp.float32 if jnp.dtype(dtype).itemsize < 4 else dtype
    cands = lax.dynamic_slice(Wloc, (0, 0, t * m), (bpw, m, m))
    cands = cands.astype(probe_dtype)
    half = bpw // 2
    if half:
        # Probe-window cut (VERDICT r2 #6, 1D): once every slot of the
        # lower half is dead (its global rows are all < t, which happens
        # exactly when t >= half*p), probe only the upper half — the
        # reference probes exactly the live window too (main.cpp:1039).
        # Dead slots get identity/True dummies; the gidx >= t mask below
        # excludes them regardless.  ~Halves average probe flops; the
        # in-place engines (the Nr <= 64 default) already shrink their
        # window statically — this covers the large-Nr fallback.
        def _upper(c):
            invs_u, sing_u = probe_blocks(c[half:], eps, use_pallas)
            eye = jnp.broadcast_to(
                jnp.eye(m, dtype=probe_dtype), (half, m, m))
            return (jnp.concatenate([eye, invs_u]),
                    jnp.concatenate([jnp.ones((half,), bool), sing_u]))

        def _full(c):
            return probe_blocks(c, eps, use_pallas)

        invs, sing = lax.cond(t >= half * p, _upper, _full, cands)
    else:
        invs, sing = probe_blocks(cands, eps, use_pallas)
    inv_norms = block_inf_norms(invs)
    valid = (gidx >= t) & ~sing
    big = jnp.asarray(jnp.inf, probe_dtype)
    key = jnp.where(valid, inv_norms.astype(probe_dtype), big)
    slot_best = jnp.argmin(key)
    my_key = key[slot_best]

    # --- PIVOT REDUCTION: argmin over workers on a composite key — replaces
    # the custom MPI op (pivot_op main.cpp:729-744, MPI_Op_create
    # main.cpp:1000-1024, Allreduce main.cpp:1074).  Stage 1: best norm;
    # stage 2: lowest *global block row* holding it, so ties resolve exactly
    # like the single-device argmin (not lowest worker id, which can own a
    # higher global row).  g_cand values are distinct across workers
    # (gidx ≡ k mod p), so the winner is unique even when every key is inf.
    kmin = pmin(my_key, AXIS)
    g_cand = gidx[slot_best]
    win_g = pmin(jnp.where(my_key == kmin, g_cand, lay.Nr), AXIS)
    singular = singular | ~jnp.isfinite(kmin)   # all-singular (main.cpp:1075-83)
    i_won = (my_key == kmin) & (g_cand == win_g)

    # Pivot's global block row and its inverse, shared one-hot (the scalar
    # payload of the reference's custom reduction).
    g_piv = psum(jnp.where(i_won, g_cand, 0), AXIS)
    H = psum(
        jnp.where(i_won, jnp.take(invs, slot_best, axis=0), 0.0).astype(dtype),
        AXIS,
    )

    # --- ROW BROADCASTS: pivot row (Bcast, main.cpp:1097) and current row t
    # (the Send/Recv half of the swap, main.cpp:1122-1129), both as one-hot
    # psums riding ICI.
    safe_best = jnp.where(i_won, slot_best, 0)
    row_piv = psum(
        jnp.where(i_won, lax.dynamic_index_in_dim(Wloc, safe_best, 0, False), 0.0),
        AXIS,
    )                                          # (m, 2N)
    own_t = k == (t % p)
    slot_t = t // p
    row_t = psum(
        jnp.where(own_t, lax.dynamic_index_in_dim(Wloc, slot_t, 0, False), 0.0),
        AXIS,
    )                                          # (m, 2N)

    # --- SWAP-BY-COPY (main.cpp:1093-1131): pivot owner's slot receives the
    # old row t; slot t is rewritten below from the normalized pivot row.
    own_piv = k == (g_piv % p)
    slot_piv = jnp.where(own_piv, g_piv // p, 0)
    W_swap = lax.dynamic_update_index_in_dim(Wloc, row_t, slot_piv, 0)
    Wloc = jnp.where(own_piv, W_swap, Wloc)

    # --- NORMALIZE (all workers, replicated like the reference's work on
    # the bcast buffer c, main.cpp:1133-1159).
    prow = jnp.matmul(H, row_piv, precision=precision)    # (m, 2N)

    # --- ELIMINATE (hot loop, main.cpp:1165-1193): one local MXU matmul.
    E = lax.dynamic_slice(Wloc, (0, 0, t * m), (bpw, m, m))
    E = jnp.where((gidx == t)[:, None, None], jnp.asarray(0, dtype), E)
    flatE = E.reshape(bpw * m, m)
    update = jnp.matmul(flatE, prow, precision=precision)
    Wloc = Wloc - update.reshape(bpw, m, 2 * N)

    # Row t becomes the normalized pivot row (owner only).
    W_set = lax.dynamic_update_index_in_dim(Wloc, prow, slot_t, 0)
    Wloc = jnp.where(own_t, W_set, Wloc)
    return Wloc, singular


@partial(jax.jit,
         static_argnames=("mesh", "lay", "eps", "precision", "use_pallas"))
def _sharded_jordan(W, mesh, lay: CyclicLayout, eps, precision, use_pallas):
    def worker(Wloc):
        def body(t, carry):
            Wl, sing = carry
            return _local_step(t, Wl, sing, lay=lay, eps=eps,
                               precision=precision, use_pallas=use_pallas)

        # The singular flag mixes in pmin results, which shard_map's
        # varying-axis typing marks as device-varying — the carry must start
        # out varying too, and the flag is returned per-worker (any() on the
        # host gives the collective verdict, identical on every worker).
        sing0 = pcast(jnp.zeros((1,), jnp.bool_), AXIS, to='varying')
        Wl, sing = lax.fori_loop(0, lay.Nr, body, (Wloc, sing0))
        return Wl, sing

    return shard_map(
        worker,
        mesh=mesh,
        in_specs=PartitionSpec(AXIS, None, None),
        out_specs=(PartitionSpec(AXIS, None, None), PartitionSpec(AXIS)),
    )(W)


def resolve_use_pallas(dtype, block_size: int) -> bool:
    from ..ops.jordan import _use_pallas_default

    return (
        _use_pallas_default(dtype)
        and block_size % 8 == 0 and block_size >= 32
    )


def scatter_augmented(a: jnp.ndarray, lay: CyclicLayout, mesh: Mesh):
    """Build [A | I], pad, reorder to cyclic storage, shard over the mesh.

    The TPU-native scatter (replaces read_matrix's per-row MPI_Send loop,
    main.cpp:244-274: the scatter IS the sharding)."""
    from ..ops.padding import pad_with_identity

    N = lay.N
    A = pad_with_identity(a, N)
    W = jnp.concatenate([A, jnp.eye(N, dtype=a.dtype)], axis=1)
    blocks = W.reshape(lay.Nr, lay.m, 2 * N)
    blocks = jnp.take(blocks, cyclic_gather_perm(lay), axis=0)
    return jax.device_put(
        blocks, NamedSharding(mesh, PartitionSpec(AXIS, None, None))
    )


def gather_inverse(out: jnp.ndarray, lay: CyclicLayout, n: int):
    """Cyclic storage order -> natural order; slice out B = A^-1."""
    from ..ops.padding import unpad

    N = lay.N
    out = jnp.take(out, cyclic_scatter_perm(lay), axis=0)
    B = out.reshape(N, 2 * N)[:, N:]
    return unpad(B, n)


def compile_sharded_jordan(
    blocks: jnp.ndarray,
    mesh: Mesh,
    lay: CyclicLayout,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
):
    """AOT-compile the sharded elimination for an already-sharded (Nr, m, 2N)
    block tensor.  Returns ``run`` with ``run(blocks) ->
    (out_blocks, singular_per_worker)``."""
    dtype = blocks.dtype
    if eps is None:
        # Match the single-device policy (ops/jordan.py): the probe runs in
        # fp32 for sub-fp32 working dtypes.
        probe_dt = jnp.float32 if jnp.dtype(dtype).itemsize < 4 else dtype
        eps = eps_for(probe_dt)
    if use_pallas is None:
        use_pallas = resolve_use_pallas(dtype, lay.m)
    return _sharded_jordan.lower(
        blocks, mesh, lay, eps, precision, use_pallas
    ).compile()


def prepare_sharded_invert(
    a: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
):
    """Resolve defaults, build the layout, scatter: the host-array front end
    shared by sharded_jordan_invert and the timing driver.

    Returns (blocks, lay, run) where ``run(blocks)`` is the AOT-compiled
    sharded elimination returning (out_blocks, singular_per_worker).
    """
    n = a.shape[-1]
    lay = CyclicLayout.create(n, min(block_size, n), mesh.devices.size)
    blocks = scatter_augmented(a, lay, mesh)
    run = compile_sharded_jordan(blocks, mesh, lay, eps, precision,
                                 use_pallas)
    return blocks, lay, run


@upcast_sub_fp32
def sharded_jordan_invert(
    a: jnp.ndarray,
    mesh: Mesh,
    block_size: int,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool | None = None,
):
    """Invert (n, n) ``a`` distributed over ``mesh`` axis "p".

    The distributed front end of the framework (reference `solve`+`Jordan`,
    main.cpp:343-519/953-1204): pads, builds the cyclic block layout,
    scatters via device_put, runs the sharded elimination, and gathers the
    inverse back to natural order.

    Returns (inv, singular) like ops.block_jordan_invert.
    """
    blocks, lay, run = prepare_sharded_invert(
        a, mesh, block_size, eps, precision, use_pallas
    )
    out, singular = run(blocks)
    return gather_inverse(out, lay, a.shape[-1]), singular.any()
