"""Mesh construction and distributed initialization.

TPU-native replacement for the reference's MPI setup (MPI_Init /
Comm_size / Comm_rank, main.cpp:69-91): a 1D `jax.sharding.Mesh` over all
devices is the communicator; the worker axis is named "p" to match the
reference's `p` rank count.  Multi-host TPU-VM slices go through
`jax.distributed.initialize` (the analog of mpirun wiring up ranks), after
which `jax.devices()` spans the whole slice and the same mesh code works
unchanged — ICI carries the per-step collectives, DCN only the host-level
setup.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS = "p"


class MeshSizeError(ValueError):
    """Requested more workers than devices exist — the analog of
    ``mpirun -np 8`` on 1 slot failing to launch."""


def distributed_init(**kwargs) -> None:
    """Initialize multi-host JAX (no-op on a single host).

    The analog of MPI_Init (main.cpp:69) for TPU-VM slices: call once per
    host process before any device use; coordinator/process wiring comes
    from the TPU environment.
    """
    try:
        jax.distributed.initialize(**kwargs)
    except (RuntimeError, ValueError):
        # Already initialized or single-process environment.
        pass


def make_mesh(num_workers: int | None = None, devices=None) -> Mesh:
    """A 1D mesh over ``num_workers`` devices, axis "p".

    Replaces MPI_Comm_size/Comm_rank (main.cpp:81-82): the axis size is the
    worker count; the per-worker index is `lax.axis_index("p")` inside
    shard_map.
    """
    if devices is None:
        devices = jax.devices()
    if num_workers is None:
        num_workers = len(devices)
    if num_workers > len(devices):
        # Never a silent degrade to fewer workers.
        raise MeshSizeError(
            f"requested {num_workers} workers but only {len(devices)} "
            f"device(s) exist (backend={jax.default_backend()!r}); run under "
            f"a larger slice or pass workers<={len(devices)}"
        )
    return Mesh(np.asarray(devices[:num_workers]), (AXIS,))


AXIS_R = "pr"
AXIS_C = "pc"


def make_mesh_2d(pr: int, pc: int, devices=None) -> Mesh:
    """A (pr, pc) mesh with axes ("pr", "pc") for the 2D block-cyclic
    layout (ScaLAPACK-style; the north-star upgrade over the reference's
    1D rows-only decomposition, main.cpp:118-123)."""
    if pr <= 0 or pc <= 0:
        raise MeshSizeError(f"mesh dims must be positive, got {pr}x{pc}")
    if devices is None:
        devices = jax.devices()
    if pr * pc > len(devices):
        raise MeshSizeError(
            f"requested a {pr}x{pc} mesh ({pr * pc} workers) but only "
            f"{len(devices)} device(s) exist "
            f"(backend={jax.default_backend()!r})"
        )
    return Mesh(
        np.asarray(devices[: pr * pc]).reshape(pr, pc), (AXIS_R, AXIS_C)
    )


def block_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a (Nr, m, cols) block tensor in cyclic storage order:
    axis 0 split over workers = each worker holds its cyclic blocks
    contiguously (see parallel/layout.py::CyclicLayout.cyclic_block_order)."""
    return NamedSharding(mesh, PartitionSpec(AXIS, None, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
