"""The autotuner (ISSUE 2 tentpole part 3): legality + cost pruning over
the registry, robust measurement of the survivors, measured-vs-projected
drift recording, and the plan-cache read/write path.

Selection ladder (``Tuner.select``), cheapest evidence first:

  1. **Plan cache hit** — a cached plan whose registry config is still
     present and legal at the point wins outright: ZERO measurements
     (the warm-pod steady state, pinned by a counter in
     tests/test_tuning.py).  Stale plans (renamed config, legality
     change) fall through instead of being honored.
  2. **Cost-model ranking** — without ``measure=True`` the cheapest
     projected candidate is the plan (``registry.select_by_cost``).
     This is what plain ``solve(engine="auto")`` runs: deterministic,
     measurement-free, and already enough to route gather=False pod
     meshes to the swap-free engine and 16384²+ single-chip solves to
     the grouped engine.
  3. **Measured tuning** — with ``measure=True`` the top ``survivors``
     candidates by projected cost are each measured with the robust core
     (``measure.measure_direct``: warmup, median-of-k, IQR rejection,
     transient retry) and the fastest median wins.  Every trial records
     measured/projected so comm_model drift is observable in the plan
     itself (VERDICT r5: projections were never validated against
     measurements).

Whatever ladder rung produced the plan, it is written back to the cache
(if one is attached and writable — a read-only shared pre-tuned cache
is never written, ISSUE 7) so the NEXT solve at the same key is rung 1.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from . import registry as _registry
from .measure import Measurement, measure_direct
from .plan_cache import Plan, PlanCache, plan_key
from .registry import EngineConfig, TunePoint
from ..obs import metrics as _obs_metrics

# Registry surface (ISSUE 4): the tuner's private counters become
# scrapeable — plan-cache hits/misses and real (or injected) engine
# measurements land in the process-wide registry next to the serve and
# driver metrics; ``Tuner.measurements`` stays the per-session pin.
_M_HITS = _obs_metrics.counter(
    "tpu_jordan_plan_cache_hits_total",
    "tuner selections satisfied by a cached plan (zero measurements)")
_M_MISSES = _obs_metrics.counter(
    "tpu_jordan_plan_cache_misses_total",
    "tuner selections that fell through to cost ranking or measurement")
_M_MEASUREMENTS = _obs_metrics.counter(
    "tpu_jordan_tuner_measurements_total",
    "engine measurements performed by tune=True selection")


def measure_config(point: TunePoint, cfg: EngineConfig,
                   samples: int = 5) -> Measurement:
    """Measure one engine configuration at a point: full engine
    executions through the driver's own compile paths (the same
    executables a solve would run), warmed once so compile never lands
    in a timed sample.

    Measurement buffers are NOT donated (unlike ``driver.solve``'s timed
    call) so one input serves every repetition; the 'rand' fixture keeps
    the matrix well-conditioned at any n so no knife-edge singularity
    aborts a tuning session.  Real-measurement tests are ``slow``-marked
    (tier-1 runs the tuner on injected fake timings only)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..driver import make_distributed_backend, single_device_invert
    from ..ops import generate

    dtype = jnp.dtype(point.dtype)
    n, m = point.n, point.block_size
    if getattr(point, "workload", "invert") == "update":
        # The update workload is cost-only by construction (ISSUE 12):
        # smw_update is its ONE registered engine, so there is no
        # ranking to measure — and silently timing a different kernel
        # under the '|wupdate' key would be exactly the bogus-plan
        # class the typed-refusal discipline exists for.
        from ..driver import UsageError

        raise UsageError(
            "tune=True has nothing to measure for the update workload "
            "(smw_update is its one engine; the serve update lanes "
            "resolve cost-only)")
    if getattr(point, "workload", "invert") != "invert":
        # Solve-workload measurement (ISSUE 11): the [A | B] engine at a
        # representative single-RHS point — engine ranking is measured
        # to depend on n/dtype, not on the RHS width, which the point
        # deliberately does not carry (docs/WORKLOADS.md).
        from ..linalg.engine import (block_jordan_solve,
                                     block_jordan_solve_fori)

        a = generate("kms" if cfg.workload == "solve_spd" else "rand",
                     (n, n), dtype)
        b = generate("crand" if point.dtype.startswith("complex")
                     else "rand", (n, 1), dtype)
        if cfg.engine in ("solve_sharded", "solve_lookahead"):
            # The distributed [A | B] elimination (ISSUE 15): measure
            # the REAL sharded executable on the point's mesh — timing
            # the single-device engine under a distributed key would be
            # exactly the bogus-plan class the typed refusals exist
            # for.  ONE mesh dispatch (linalg.api.solve_mesh_backend)
            # shared with solve_system, so the measured executable can
            # never diverge from the shipped one.
            from ..linalg.api import solve_mesh_backend

            mesh, lay, scatter_a, scatter_b, compile_fn, _ = \
                solve_mesh_backend(point.workers, n, m)
            W = scatter_a(a, lay, mesh)
            X = scatter_b(b, lay, mesh)
            run = compile_fn(W, X, mesh, lay,
                             lookahead=cfg.engine == "solve_lookahead")

            def call():
                jax.block_until_ready(run(W, X)[0])

            return measure_direct(call, samples=samples)
        if cfg.engine == "solve_fori":
            compiled = jax.jit(
                lambda aa, bb: block_jordan_solve_fori(aa, bb,
                                                       block_size=m)
            ).lower(a, b).compile()
        else:
            spd = cfg.engine == "solve_spd"
            compiled = jax.jit(
                lambda aa, bb: block_jordan_solve(aa, bb, block_size=m,
                                                  spd=spd)
            ).lower(a, b).compile()

        def call():
            jax.block_until_ready(compiled(a, b)[0])

        return measure_direct(call, samples=samples)
    if point.distributed:
        be = make_distributed_backend(point.workers, n, m, cfg.engine,
                                      cfg.group)
        W = be.generate_W("rand", dtype)
        run = be.compile(W)

        def call():
            jax.block_until_ready(run(W)[0])
    else:
        a = generate("rand", (n, n), dtype)
        compiled = jax.jit(
            single_device_invert(n, m, cfg.engine, cfg.group),
            static_argnames=("block_size", "refine", "precision"),
        ).lower(
            a, block_size=m, refine=0, precision=lax.Precision.HIGHEST
        ).compile()

        def call():
            jax.block_until_ready(compiled(a)[0])

    return measure_direct(call, samples=samples)


@dataclass
class Tuner:
    """One tuning session.  ``measurements`` counts real (or injected)
    engine measurements — the warm-cache acceptance contract is
    "second solve at the same key: counter unchanged"."""

    cache: PlanCache | None = None
    measure: bool = False
    measure_fn: object = None          # (point, cfg) -> Measurement
    survivors: int = 3                 # candidates measured per point
    samples: int = 5                   # robust-core k per candidate
    measurements: int = 0
    last_source: str | None = field(default=None, repr=False)

    def select(self, point: TunePoint) -> Plan:
        key = plan_key(point)
        if self.cache is not None:
            cached = self.cache.get(key)
            # A measuring tuner is only satisfied by measured evidence:
            # a cost_model-sourced cache entry must not short-circuit an
            # explicit tune=True request (it would pin the unmeasured
            # guess forever); it IS good enough when measurement wasn't
            # asked for.
            if (cached is not None and self._still_valid(cached, point)
                    and (not self.measure or cached.source == "measured")):
                self.last_source = "cache"
                _M_HITS.inc()
                return cached
        _M_MISSES.inc()
        plan = (self._tune(point) if self.measure
                else self._rank(point))
        self.last_source = plan.source
        # Write-back skipped for a read-only cache (the fleet's shared
        # pre-tuned plans, ISSUE 7 satellite): a replica must never
        # scribble over the pod-pretuned file, and put/save would raise
        # the typed UsageError if attempted.
        if self.cache is not None and not self.cache.read_only:
            self.cache.put(key, plan)
            self.cache.save()
        return plan

    @staticmethod
    def _still_valid(plan: Plan, point: TunePoint) -> bool:
        """Staleness gate for cached plans: the config must still exist
        in the live registry, resolve to the same (engine, group), and
        be legal at the point — otherwise the cache entry is from
        another era and falls through to fresh selection."""
        cfg = _registry.REGISTRY.get(plan.config)
        return (cfg is not None
                and cfg.engine == plan.engine
                and cfg.group == plan.group
                and cfg.workload == getattr(point, "workload", "invert")
                and cfg.legal(point))

    def _rank(self, point: TunePoint) -> Plan:
        cfg = _registry.select_by_cost(point)
        proj = cfg.cost(point)
        return Plan(config=cfg.name, engine=cfg.engine, group=cfg.group,
                    source="cost_model",
                    projected=None if math.isinf(proj) else proj)

    def _tune(self, point: TunePoint) -> Plan:
        cands = _registry.candidates(point)
        if not cands:
            raise ValueError(f"no legal engine at {point}")
        # Prune: only the top `survivors` by projected cost are worth
        # paying a measurement for; infinite-cost candidates (measured
        # dispatch priors) never make the cut.
        survivors = [c for c in cands if not math.isinf(c.cost(point))]
        survivors = survivors[:max(1, self.survivors)] or cands[:1]
        fn = self.measure_fn or measure_config
        trials = []
        best = None                       # (seconds, trial, cfg)
        for cfg in survivors:
            proj = cfg.cost(point)
            meas = fn(point, cfg, samples=self.samples)
            self.measurements += 1
            _M_MEASUREMENTS.inc()
            drift = (None if math.isinf(proj) or proj <= 0.0
                     else meas.seconds / proj)
            trial = {
                "config": cfg.name,
                "projected": None if math.isinf(proj) else proj,
                "measured": meas.seconds,
                "drift": drift,
                "spread_pct": meas.spread_pct,
                "rejected_samples": len(meas.rejected),
            }
            if meas.variance_flag:
                trial["variance_flag"] = meas.variance_flag
            trials.append(trial)
            if best is None or meas.seconds < best[0]:
                best = (meas.seconds, trial, cfg, meas)
        seconds, trial, cfg, meas = best
        return Plan(config=cfg.name, engine=cfg.engine, group=cfg.group,
                    source="measured", seconds=seconds,
                    projected=trial["projected"], drift=trial["drift"],
                    variance_flag=meas.variance_flag,
                    trials=tuple(trials))


def auto_select(n: int, block_size: int | None, dtype, workers,
                gather: bool, tune: bool = False,
                plan_cache: str | None = None,
                telemetry=None,
                workload: str = "invert") -> tuple[str, int, Plan]:
    """The driver's ``engine="auto"`` hook: build the tuning point from
    the solve arguments, run the selection ladder, return the resolved
    ``(engine, group, plan)``.  ``plan_cache`` is a JSON path (consulted
    always, updated whenever selection ran); ``tune=True`` turns on real
    measurement of the cost-pruned survivors.  ``telemetry`` records
    the ladder walk as a ``select`` span (attrs: resolved engine +
    ladder rung — obs/spans.py).  ``workload`` (ISSUE 11) scopes the
    ladder to that workload's engine zoo and plan-cache key segment
    ("invert" keys stay byte-identical)."""
    from ..obs.spans import NULL

    tel = telemetry if telemetry is not None else NULL
    with tel.span("select", n=n, tune=tune, workload=workload) as sp:
        point = TunePoint.create(n, block_size, dtype, workers, gather,
                                 workload=workload)
        cache = PlanCache.load(plan_cache) if plan_cache else None
        tuner = Tuner(cache=cache, measure=tune)
        plan = tuner.select(point)
        sp.attrs["engine"] = plan.engine
        sp.attrs["source"] = tuner.last_source
    return plan.engine, plan.group, plan
