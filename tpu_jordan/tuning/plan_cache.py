"""Versioned, persistent JSON plan cache (ISSUE 2 tentpole part 4).

A *plan* is one resolved engine choice for one plan key; the cache is a
flat ``{key: plan}`` JSON document with a format version.  Keys are
``backend|topology|n-bucket|dtype|memory-mode`` (``plan_key``): the five
coordinates engine choice is measured to depend on.  ``n`` is bucketed
to the next power of two — engine crossover points move slowly with n
(the measured grouped/plain crossover sits between 4096 and 8192), so a
plan tuned at 10000 legitimately serves 16384-bucket neighbors while the
cache stays small enough to pre-tune a pod in minutes (docs/TUNING.md).

Failure policy (all covered by tests/test_tuning.py): a missing file is
an empty cache; a corrupt file (bad JSON, wrong structure, bad plan
fields) or a version mismatch is ALSO an empty cache with
``fallback_reason`` set — the tuner then falls back to cost-model
ranking instead of crashing the solve, and the next ``save`` rewrites
the file cleanly.  Saves are atomic (tmp + ``os.replace``) so a crashed
writer can never leave a half-written cache for the next reader.

Read-only mode (ISSUE 7 satellite): ``load(path, read_only=True)``
freezes the cache — every fleet replica opens the shared pre-tuned
plans this way, so N replicas can read one pod-pretuned file with ZERO
write traffic and zero lock contention (``get`` is a plain dict read on
a dict that never mutates again; there is no lock to contend on).  A
write attempt (``put`` or ``save``) on a read-only cache is a typed
``UsageError`` — a replica must never scribble over the shared
pre-tuned plans — and the tuner skips its write-back for read-only
caches instead of tripping it (``tuning/tuner.py``).
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass, field, asdict

from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from ..resilience import faults as _faults
from .registry import TunePoint

CACHE_VERSION = 1

_M_WRITE_FAILS = _obs_metrics.counter(
    "tpu_jordan_plan_cache_write_failures_total",
    "plan-cache saves that failed (disk full / read-only dir) and "
    "degraded to in-memory plans — a warning, never an exception out "
    "of a successful solve")


def n_bucket(n: int) -> int:
    """Round ``n`` up to the next power of two (the cache-key bucket)."""
    return 1 << max(0, int(n - 1).bit_length())


def plan_key(point: TunePoint) -> str:
    """``backend|topology|n-bucket|dtype|memory-mode[|bB]`` — e.g.
    ``tpu-v5p|4x8|n32768|float32|sharded`` or, for a batched point,
    ``tpu-v5e|single|n512|float32|gathered|b64``.

    The backend segment carries the sniffed chip generation when known
    (``tpu-v5p`` vs bare ``tpu``): a plans.json measured on a v5e pod
    must not be honored verbatim on a v5p pod — the v5p link/HBM ratios
    are exactly what flips the engine ranking at pod meshes.

    The batch segment (ISSUE 3) appears only when ``point.batch > 1`` —
    the serving executors key plans per (bucket, batch_cap) because
    per-launch overheads amortize differently across a batch — so every
    pre-existing unbatched key is byte-identical and old caches stay
    valid without a version bump.

    The workload segment (ISSUE 11) follows the same discipline: it
    appears only when ``point.workload != "invert"`` (e.g.
    ``tpu-v5e|single|n4096|float32|gathered|wsolve``), so every
    pre-existing invert key — batched or not — is byte-identical and
    existing caches stay valid.

    The topology segment is also what makes the mesh-backed serve
    lanes (ISSUE 18, ``serve/meshlanes.py``) warm-cacheable with NO
    key change: a ``p8``/``2x4`` lane's plan resolves under the same
    key a direct ``solve(workers=...)`` tuned — one plans.json serves
    both the library path and the serving topology lanes."""
    backend = (f"{point.backend}-{point.chip}" if point.chip
               else point.backend)
    mem = "gathered" if point.gather else "sharded"
    key = (f"{backend}|{point.topology}|n{n_bucket(point.n)}|"
           f"{point.dtype}|{mem}")
    if getattr(point, "batch", 1) > 1:
        key += f"|b{point.batch}"
    if getattr(point, "workload", "invert") != "invert":
        key += f"|w{point.workload}"
    return key


@dataclass(frozen=True)
class Plan:
    """One resolved engine choice.  ``config`` is the registry name;
    ``engine``/``group`` are denormalized so a cached plan can drive the
    driver even if the registry entry is later renamed (staleness is
    still caught: the tuner re-validates legality against the live
    registry before honoring a cached plan).  ``projected`` vs
    ``seconds`` makes comm_model drift observable; ``trials`` carries
    the per-candidate measured-vs-projected records of the tuning run
    that produced the plan."""

    config: str
    engine: str
    group: int = 0
    source: str = "cost_model"       # "cost_model" | "measured"
    seconds: float | None = None     # measured median (None: cost-only)
    projected: float | None = None   # comm_model seconds for the pick
    drift: float | None = None       # seconds / projected
    variance_flag: str | None = None
    trials: tuple = field(default=())

    def to_json(self) -> dict:
        d = asdict(self)
        d["trials"] = list(self.trials)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "Plan":
        return cls(
            config=str(d["config"]),
            engine=str(d["engine"]),
            group=int(d.get("group", 0)),
            source=str(d.get("source", "cost_model")),
            seconds=d.get("seconds"),
            projected=d.get("projected"),
            drift=d.get("drift"),
            variance_flag=d.get("variance_flag"),
            trials=tuple(d.get("trials", ())),
        )


class PlanCache:
    """The cache object the tuner holds: ``get``/``put`` in memory,
    ``load``/``save`` against the versioned JSON file."""

    def __init__(self, path: str | None = None,
                 plans: dict[str, Plan] | None = None,
                 fallback_reason: str | None = None,
                 read_only: bool = False,
                 resident_nbytes: int | None = None):
        self.path = path
        self.plans = dict(plans or {})
        #: frozen cache (the fleet's shared pre-tuned plans): ``put`` /
        #: ``save`` raise the typed UsageError instead of mutating.
        self.read_only = bool(read_only)
        #: why a load produced an empty cache (corruption/version skew);
        #: None on a clean load.  Surfaced so operators can see that a
        #: cache was ignored rather than silently empty.
        self.fallback_reason = fallback_reason
        #: the last save failure (OSError string); None while writes
        #: succeed.  In-memory plans keep serving either way (ISSUE 5
        #: satellite: a full disk degrades, it does not crash a solve).
        self.last_write_error: str | None = None
        # ``resident_nbytes`` lets load() pass the on-disk document's
        # size it just read (no re-serialization on the startup path).
        self._meter(resident_nbytes)

    def _meter(self, nbytes: int | None = None) -> None:
        """Capacity accounting (ISSUE 13): the serialized plan document
        is resident process state — one ``plan_cache`` ledger entry per
        cache instance, re-registered (replace semantics) whenever a
        save rewrites the document.  ``save`` passes the length of the
        document it just wrote (no second serialization); construction
        serializes once itself."""
        from ..obs import capacity as _capacity

        if nbytes is None:
            doc = {"version": CACHE_VERSION,
                   "plans": {k: p.to_json()
                             for k, p in sorted(self.plans.items())}}
            nbytes = len(json.dumps(doc, indent=1, sort_keys=True)) + 1
        _capacity.register("plan_cache", (id(self),), nbytes,
                           detail=self.path or "<memory>")

    @classmethod
    def load(cls, path: str, read_only: bool = False) -> "PlanCache":
        """Load ``path``; NEVER raises for bad cache contents — the
        documented fallback is an empty cache + ``fallback_reason`` (the
        tuner then ranks by cost model).  ``read_only=True`` freezes the
        result (the fleet's shared pre-tuned cache mode) — and, alone
        among the fallbacks, a MISSING file is then a typed
        ``UsageError``: read-only's whole contract is serving an
        existing pre-tuned file, so a typoed path must not silently
        become an empty cache serving off cost ranking."""
        if not os.path.exists(path):
            if read_only:
                from ..driver import UsageError
                raise UsageError(
                    f"plan cache {path!r} does not exist — read-only "
                    f"mode serves a pre-tuned file; check the path or "
                    f"pretune first")
            return cls(path=path, read_only=read_only)
        try:
            with open(path, "r") as f:
                doc = json.load(f)
            version = doc.get("version")
            if version != CACHE_VERSION:
                return cls(path=path, read_only=read_only,
                           fallback_reason=(
                               f"plan cache version {version!r} != "
                               f"{CACHE_VERSION} — ignoring stale cache"))
            plans = {str(k): Plan.from_json(v)
                     for k, v in doc["plans"].items()}
            return cls(path=path, plans=plans, read_only=read_only,
                       resident_nbytes=os.path.getsize(path))
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError) as e:
            # ValueError covers json.JSONDecodeError; Key/Type/Attribute
            # cover structurally-wrong documents (plans not a dict, plan
            # entries missing fields, scalars where objects belong).
            return cls(path=path, read_only=read_only, fallback_reason=(
                f"corrupt plan cache ({type(e).__name__}: {e}) — "
                f"falling back to cost-model ranking"))

    def _refuse_write(self, what: str):
        from ..driver import UsageError

        raise UsageError(
            f"plan cache {self.path or '<memory>'} is read-only (the "
            f"fleet's shared pre-tuned plans); {what} is a write — "
            f"pre-tune with a writable cache (docs/TUNING.md), then "
            f"serve it read-only")

    def get(self, key: str) -> Plan | None:
        return self.plans.get(key)

    def put(self, key: str, plan: Plan) -> None:
        if self.read_only:
            self._refuse_write(f"put({key!r})")
        self.plans[key] = plan

    def save(self, path: str | None = None) -> None:
        """Atomic write (tmp file + ``os.replace`` in the destination
        directory) of the versioned document.

        A write failure (disk full, read-only dir — simulated by the
        ``plan_cache_write`` fault point) DEGRADES instead of raising:
        the in-memory plans keep serving every subsequent selection,
        ``tpu_jordan_plan_cache_write_failures_total`` counts the
        warning, and ``last_write_error`` carries the diagnostic.  A
        failed persistence must never fail the successful solve that
        triggered it (ISSUE 5 satellite); later saves retry — transient
        disk pressure may clear.  A read-only cache refuses with the
        typed UsageError instead (ISSUE 7 satellite — that is a caller
        bug, not disk weather)."""
        if self.read_only:
            self._refuse_write("save()")
        path = path or self.path
        if path is None:
            return
        doc = {"version": CACHE_VERSION,
               "plans": {k: p.to_json() for k, p in
                         sorted(self.plans.items())}}
        text = json.dumps(doc, indent=1, sort_keys=True) + "\n"
        try:
            _faults.fire("plan_cache_write")
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".plan.tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(text)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError as e:
            self.last_write_error = str(e)
            _M_WRITE_FAILS.inc()
            # Black box (ISSUE 8): the degradation is a recorded event,
            # so check_chaos can tie a plan_cache_write fault to the
            # in-memory fallback it caused instead of only counting.
            _recorder.record("plan_cache_write_failure", error=str(e))
            return
        self.last_write_error = None
        self._meter(len(text))   # re-register: the document grew
