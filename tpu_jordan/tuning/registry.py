"""The declarative engine registry: every solve configuration the driver
can run, in ONE place (ISSUE 2 tentpole part 1).

Before this module the engine zoo lived as string dispatch scattered
across ``driver.py`` (``ENGINES``, ``resolve_engine``, backend flags),
``models/jordan_solver.py``, and ``__main__.py`` (``--engine`` choices).
Now each engine is an :class:`EngineConfig` — name, the driver-level
``(engine, group)`` pair it resolves to, a *legality predicate* over the
tuning point (n / dtype / mesh / gather), and a *cost hook* backed by the
analytic model in ``benchmarks/comm_model.py`` (its ``topology_params``
API is the single source of the chip constants).  The driver's
``ENGINES`` tuple and the CLI's ``--engine`` choices are derived from
this registry, and ``tests/test_tuning.py`` lints that every engine
reachable from ``driver.solve`` is registered exactly once — adding an
engine without registering it is a test failure, not a silent gap.

Cost hooks are *rankings*, not wall-clock truth: on non-TPU backends the
calibrated v5e model still orders the engines correctly by collective
bytes and HBM passes (``topology_params()["backend_chip"]``), and
measured-vs-projected drift is recorded by the tuner whenever it
measures (``tuner.py``), so model rot is observable rather than silent.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable

# Measured single-chip dispatch prior (driver.resolve_engine's docstring,
# benchmarks/PHASES.md round 4): the delayed-group-update engine wins at
# n >= 8192 on well-conditioned fixtures; below that its per-launch probe
# overheads (which the analytic model does not carry) make the plain
# engine the right choice.  The cost hook encodes the prior as an
# infinite cost so cost-only ranking reproduces the measured policy; the
# measuring tuner prunes it the same way (an infinite-cost candidate
# never makes the survivor cut, which IS the prior doing its job).
GROUPED_MIN_SINGLE_CHIP_N = 8192

# The workload vocabulary (ISSUE 11): every tuning point carries the
# WORKLOAD it selects an engine for.  "invert" is the historical default
# (every pre-ISSUE-11 point; its plan-cache keys are byte-identical),
# "solve" the augmented-[A | B] X = A⁻¹B path (no inverse ever formed),
# "solve_spd" its pivot-free fast path (the caller's assume="spd"
# promise skips the condition-based probe — the paper's most expensive
# non-GEMM phase, main.cpp:1026-1074), "update" the Sherman–Morrison–
# Woodbury rank-k resident-inverse update (ISSUE 12,
# tpu_jordan/linalg/update.py).  lstsq is not a registry workload: it
# routes through solve_system on the normal equations
# (tpu_jordan/linalg/api.py), so its engine choice IS a solve choice.
WORKLOADS: tuple[str, ...] = ("invert", "solve", "solve_spd", "update")

# The comm model's calibration floor: its compute terms are calibrated
# on the measured 8192-class phase model and its smallest validated
# contract point is 2048 (tests/test_scale_demo.py).  Below this, the
# per-step margins between the distributed engines (a few µs of modeled
# latency) are smaller than the un-modeled dispatch/launch overheads,
# so cost-ONLY selection keeps the conservative in-place engine rather
# than trusting sub-noise rankings — the distributed analog of the
# grouped single-chip prior above.  Measured tuning (tune=True) ignores
# this floor: evidence beats priors.
COST_MODEL_FLOOR_N = 2048


@dataclass(frozen=True)
class TunePoint:
    """One autotuning problem point — everything engine choice may
    legally depend on.  ``dtype`` is the canonical jnp dtype name and
    ``workers`` the driver's workers spec (1, p, or (pr, pc)) so the
    point round-trips exactly through plan-cache keys."""

    n: int
    block_size: int
    dtype: str
    workers: Any = 1
    gather: bool = True
    backend: str = "cpu"
    #: chip-model override for the cost hooks ("v5e"/"v4"/"v5p"); None
    #: ranks with topology_params()["backend_chip"][backend].  Set by
    #: ``create`` from the real device kind on TPU backends — the v5p
    #: link/HBM ratios are what route pod meshes to the swap-free engine.
    chip: str | None = None
    #: batch size of the point (ISSUE 3: the serving executors tune and
    #: cache plans per (bucket, batch_cap) — a plan measured for one
    #: matrix must not be honored verbatim for a 64-element batch, where
    #: per-launch overheads amortize differently).  1 = the unbatched
    #: solve; plan keys only grow a ``bN`` segment when batch > 1, so
    #: every pre-existing cache key is unchanged.
    batch: int = 1
    #: the workload this point selects an engine for (ISSUE 11): plan
    #: keys only grow a ``|w<workload>`` segment when != "invert", so
    #: every pre-existing invert key is byte-identical and old caches
    #: stay valid without a version bump.
    workload: str = "invert"

    @classmethod
    def create(cls, n: int, block_size: int | None = None, dtype="float32",
               workers: Any = 1, gather: bool = True,
               backend: str | None = None,
               chip: str | None = None, batch: int = 1,
               workload: str = "invert") -> "TunePoint":
        import jax
        import jax.numpy as jnp

        from ..config import default_block_size

        if block_size is None:
            block_size = default_block_size(n)
        if isinstance(workers, tuple):
            workers = (int(workers[0]), int(workers[1]))
        else:
            workers = int(workers)
        if backend is None:
            backend = jax.default_backend()
        if chip is None and backend == "tpu":
            chip = _sniff_chip()
        if workload not in WORKLOADS:
            raise ValueError(f"unknown workload {workload!r}; choose "
                             f"from {'/'.join(WORKLOADS)}")
        return cls(n=int(n), block_size=int(min(block_size, n)),
                   dtype=jnp.dtype(dtype).name, workers=workers,
                   gather=bool(gather), backend=backend, chip=chip,
                   batch=int(batch), workload=str(workload))

    @property
    def distributed(self) -> bool:
        return isinstance(self.workers, tuple) or self.workers > 1

    @property
    def mesh_shape(self) -> tuple[int, int]:
        """(pr, pc) as the comm model counts it (1D p -> (p, 1))."""
        if isinstance(self.workers, tuple):
            return self.workers
        return (self.workers, 1)

    @property
    def topology(self) -> str:
        """Cache-key mesh label: 'single', 'p8' (1D), or '2x4' (2D)."""
        if isinstance(self.workers, tuple):
            return f"{self.workers[0]}x{self.workers[1]}"
        return "single" if self.workers == 1 else f"p{self.workers}"


@dataclass(frozen=True)
class EngineConfig:
    """One registered engine configuration.

    ``engine``/``group`` are exactly what ``driver.solve`` /
    ``JordanSolver`` accept; ``legal`` gates candidacy at a point;
    ``cost`` is the comm-model projected wall seconds (``math.inf``
    encodes a measured-dispatch prior: legal, but never cost-preferred
    and pruned from the measuring tuner's survivor set)."""

    name: str
    engine: str
    group: int
    legal: Callable[[TunePoint], bool]
    cost: Callable[[TunePoint], float]
    note: str
    #: which workload this configuration serves (ISSUE 11): candidacy is
    #: an exact match against the point's workload, so the invert and
    #: solve engine zoos can never leak into each other's rankings.  The
    #: (engine, workload) pair is linted unique by tests/test_tuning.py.
    workload: str = "invert"


_COMM_MODEL = None


def comm_model():
    """``benchmarks.comm_model``, imported once — as a package when the
    repo root is importable, by file path next to this package
    otherwise (the repo checkout layout)."""
    global _COMM_MODEL
    if _COMM_MODEL is None:
        try:
            from benchmarks import comm_model as cm
        except ImportError:
            import importlib.util
            import pathlib

            path = (pathlib.Path(__file__).resolve().parents[2]
                    / "benchmarks" / "comm_model.py")
            spec = importlib.util.spec_from_file_location(
                "_tpu_jordan_comm_model", path)
            cm = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(cm)
        _COMM_MODEL = cm
    return _COMM_MODEL


def _sniff_chip() -> str | None:
    """Best-effort chip-model name from the real TPU device kind
    (e.g. device_kind 'TPU v5p' -> 'v5p'); None when unrecognized."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:                            # noqa: BLE001
        return None
    for name in comm_model().topology_params()["chips"]:
        if name in kind.replace(" ", ""):
            return name
    return None


def _chip_for(point: TunePoint):
    params = comm_model().topology_params()
    name = point.chip or params["backend_chip"].get(point.backend, "v5e")
    return params["chips"][name]


def projected_seconds(point: TunePoint, group: int = 1,
                      swapfree: bool = False) -> float:
    """comm_model's projected total wall seconds for one engine at a
    point — the shared backing of every cost hook below.

    ISSUE 14 (ROADMAP item 5's self-pricing loop, first rung): the
    comm TERM of the projection is scaled by the communication
    observatory's measured calibration
    (``obs/comm.cost_comm_scale`` — the EWMA of judged
    measured/projected comm ratios).  Feedback is OPT-IN
    (``obs.comm.set_cost_feedback(True)``) and the scale is exactly
    1.0 otherwise, so default cost rankings are byte-identical to the
    pre-ISSUE-14 behavior; with it on, a chip whose measured
    interconnect runs slower/faster than the model's constants
    re-prices every comm-dominated engine from evidence instead of
    hand-edited constants."""
    pr, pc = point.mesh_shape
    r = comm_model().predict(
        point.n, point.block_size, pr, pc, _chip_for(point),
        group=group, swapfree=swapfree,
    )
    from ..obs.comm import cost_comm_scale

    return r["total"] + (cost_comm_scale() - 1.0) * r["comm"]


def _cost_inplace(pt: TunePoint) -> float:
    return projected_seconds(pt)


def _cost_grouped(pt: TunePoint) -> float:
    if not pt.distributed and pt.n < GROUPED_MIN_SINGLE_CHIP_N:
        return math.inf                      # measured dispatch prior
    return projected_seconds(pt, group=2)


def _cost_augmented(pt: TunePoint) -> float:
    # The reference-parity path runs the augmented [A | B] working set:
    # ~4N^3 flops and double the HBM/collective bytes of the in-place
    # engines.  2x the in-place projection is the honest first-order
    # model — it is registered for completeness (and so the tuner can
    # MEASURE it when asked), never cost-preferred.
    return 2.0 * projected_seconds(pt)


def _cost_swapfree(pt: TunePoint) -> float:
    return projected_seconds(pt, swapfree=True)


def _cost_grouped_pallas(pt: TunePoint) -> float:
    # The fused-kernel engine is a TPU perf path (off-TPU it runs the
    # Pallas interpreter — a correctness/debug route, never
    # cost-preferred) and its Mosaic-proven lane geometry is
    # m % 128 == 0 (the probe kernels' measured compile envelope).
    # Until a measured TPU session validates the new kernel at scale it
    # is priced just ABOVE the grouped engine: a brand-new kernel must
    # not displace the measured champion by model fiat, but the finite
    # cost keeps it inside tune=True's survivor cut, so measured
    # evidence (and the plan cache) can promote it — the same
    # evidence-beats-priors ladder as everywhere else in this module.
    if (pt.backend not in ("tpu", "axon")   # axon: the TPU tunnel backend
            or pt.block_size % 128 != 0
            or pt.n < GROUPED_MIN_SINGLE_CHIP_N):
        return math.inf
    return 1.02 * projected_seconds(pt, group=2)


def _cost_grouped_pallas_bf16(pt: TunePoint) -> float:
    # Legal only at sub-fp32 storage points (the caller already
    # accepted bf16-grade numbers); there the bf16-compute kernel is
    # modeled at ~0.75x the fp32 fused path — the v5p-class bf16:fp32
    # MXU advantage the 2112.09017 recipe banks on.  On v5e fp32-HIGHEST
    # already runs as bf16 passes (BASELINE.md re-scope), so the
    # measured tuner is expected to refute this prior there — which is
    # exactly what drift recording is for.
    base = _cost_grouped_pallas(pt)
    return math.inf if math.isinf(base) else 0.75 * base


def _legal_grouped_pallas(pt: TunePoint) -> bool:
    # Single-device UNBATCHED solves only (the serve executors build
    # vmapped batch engines, which the fused-kernel engines have no
    # variant of — a batched plan naming them would be unbuildable),
    # <= 4-byte float storage, probe-legal block size, and
    # unrolled-reach Nr (the kernel's mask geometry is static).
    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    m = min(pt.block_size, pt.n)
    Nr = -(-pt.n // m)
    return (not pt.distributed
            and getattr(pt, "batch", 1) == 1
            and pt.dtype in ("float32", "bfloat16", "float16")
            and m % 8 == 0 and m >= 32
            and Nr <= MAX_UNROLL_NR)


def _legal_grouped_pallas_bf16(pt: TunePoint) -> bool:
    # bf16 COMPUTE is only auto-candidate when the point's own storage
    # dtype is sub-fp32: an fp32 request must never be silently served
    # by rounded-operand dots.  (An EXPLICIT engine="grouped_pallas_bf16"
    # bypasses registry legality and is guarded by the auto-attached
    # residual-gate ladder instead — driver.py.)
    return (_legal_grouped_pallas(pt)
            and pt.dtype in ("bfloat16", "float16"))


def _always(pt: TunePoint) -> bool:
    return True


def _real_dtype(pt: TunePoint) -> bool:
    # Complex dtypes (ISSUE 11) run on the augmented-family engines only
    # (the [A | B] elimination is dtype-generic; the in-place/grouped/
    # fused engines' layout tricks are validated for real dtypes only) —
    # an auto point at complex64 must never be routed to an engine that
    # would crash or silently mis-handle it.
    return not pt.dtype.startswith("complex")


def _distributed_only(pt: TunePoint) -> bool:
    return pt.distributed and _real_dtype(pt)


def _legal_solve(pt: TunePoint) -> bool:
    # The augmented-[A | B] solve engine (tpu_jordan/linalg/engine.py):
    # single-device, unrolled-only (the live-column window shrinks
    # STATICALLY per superstep — that is where the ~half-the-invert-FLOPs
    # saving lives), any storage dtype including complex (sub-fp32
    # computes at fp32 and rounds once, the invert engines' policy).
    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    m = min(pt.block_size, pt.n)
    Nr = -(-pt.n // m)
    return not pt.distributed and Nr <= MAX_UNROLL_NR


def _cost_solve(pt: TunePoint) -> float:
    # Gauss–Jordan on [A | B] never forms A⁻¹: ~n³(1 + k/n) FLOPs vs the
    # in-place inversion's 2n³ (obs/hwcost.baseline_workload_flops).
    # 0.55x the in-place projection is the honest first-order ranking —
    # strictly below every invert engine at the same point, with margin
    # for the k-column RHS the point does not carry.
    return 0.55 * projected_seconds(pt)


def _cost_solve_spd(pt: TunePoint) -> float:
    # assume="spd" skips the condition-based pivot probe — the paper's
    # most expensive non-GEMM phase (main.cpp:1026-1074): one diagonal
    # block inverse per superstep instead of Nr-t candidates.
    return 0.45 * projected_seconds(pt)


def _legal_solve_fori(pt: TunePoint) -> bool:
    # The fori-compiled solve engine (linalg/engine.py::
    # block_jordan_solve_fori, ISSUE 15): single-device, ANY Nr (the
    # compile cost is flat in Nr — what makes Nr > MAX_UNROLL_NR legal),
    # dtype-generic incl. complex.
    return not pt.distributed


def _cost_solve_fori(pt: TunePoint) -> float:
    # Full-width updates (traced offsets cannot slice a shrinking
    # static window): ~2n³ + 2n²k vs the unrolled engine's n³(1+k/n) —
    # ranked strictly above both unrolled solve flavors wherever those
    # are legal, so it is only auto-picked beyond MAX_UNROLL_NR (or by
    # measured evidence).
    return 1.1 * projected_seconds(pt)


def _legal_solve_sharded(pt: TunePoint) -> bool:
    # The distributed [A | B] elimination (ISSUE 15 tentpole):
    # parallel/sharded_inplace.py (1D) and jordan2d_inplace.py (2D),
    # legal at any mesh shape and EITHER gather mode (X is O(n·k) and
    # always assembled; A stays sharded end to end), any Nr (unrolled
    # vs fori resolved inside by Nr), real dtypes only (the scatter/
    # collective paths follow the invert engines' real-dtype contract).
    return pt.distributed and _real_dtype(pt)


def _cost_solve_sharded(pt: TunePoint) -> float:
    # Same n³(1+k/n)-vs-2n³ discount as the single-device solve,
    # applied to the distributed projection (per-device FLOPs land
    # ~1/p of the single-device solve's — the comm terms are the
    # invert model's: same pivot/row-psum superstep structure).
    return 0.55 * projected_seconds(pt)


def _lookahead_hidden_seconds(pt: TunePoint) -> float:
    """The probe seconds the lookahead schedule can hide under the
    trailing eliminate: per superstep the probe (candidate block
    inverses + the pmin reduction) runs concurrently with the trailing
    GEMM, so the hidden time is bounded by BOTH terms —
    min(probe, elim) of the comm-model projection."""
    pr, pc = pt.mesh_shape
    r = comm_model().predict(pt.n, pt.block_size, pr, pc, _chip_for(pt))
    return min(r["probe"], r["elim"])


def probe_overlap_headroom(point: TunePoint) -> float:
    """Projected fraction of total wall time the probe-ahead schedule
    can hide — min(probe, elim)/total from the comm model.  Recorded by
    bench.py's lookahead rows as an ACCOUNTING field (the `_overlap_frac`
    suffix: context for the rate numbers, never regression-compared)
    and attached to execute spans as scheduling evidence
    (obs/hwcost.attach_execute_cost)."""
    pr, pc = point.mesh_shape
    r = comm_model().predict(
        point.n, point.block_size, pr, pc, _chip_for(point))
    return min(r["probe"], r["elim"]) / r["total"]


def _legal_lookahead(pt: TunePoint) -> bool:
    # The probe-ahead engine (ISSUE 16): pivoting flavors only (the SPD
    # pivot-free path has no probe to move), real dtypes (in-place
    # family contract), unrolled-reach Nr only — the critical-panel /
    # trailing split needs static column offsets.
    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    m = min(pt.block_size, pt.n)
    Nr = -(-pt.n // m)
    return _real_dtype(pt) and Nr <= MAX_UNROLL_NR


def _cost_lookahead(pt: TunePoint) -> float:
    # Distributed: the probe's candidate inverses AND its cross-worker
    # pmin reduction come off the superstep critical path — discount
    # the projection by the overlappable term (bounded by the trailing
    # eliminate it hides under).  Single-device: the probe is on-chip
    # compute with no reduction latency to hide; until a measured TPU
    # session validates the reordered schedule it is priced just ABOVE
    # the plain engine (the grouped_pallas discipline: a new schedule
    # must not displace the measured champion by model fiat, but stays
    # inside tune=True's survivor cut for evidence to promote it).
    if pt.distributed:
        return projected_seconds(pt) - _lookahead_hidden_seconds(pt)
    return 1.01 * projected_seconds(pt)


def _legal_solve_lookahead(pt: TunePoint) -> bool:
    # The distributed probe-ahead solve: solve_sharded's legality
    # narrowed to unrolled-reach Nr (static panel offsets).
    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    m = min(pt.block_size, pt.n)
    Nr = -(-pt.n // m)
    return _legal_solve_sharded(pt) and Nr <= MAX_UNROLL_NR


def _cost_solve_lookahead(pt: TunePoint) -> float:
    # The solve_sharded n³(1+k/n) discount on the overlap-discounted
    # projection: same supersteps, probe off the critical path —
    # strictly below solve_sharded wherever legal, so the cost model
    # routes unrolled-reach distributed solves through the lookahead
    # schedule (identical X bits; the fori twin covers Nr beyond).
    return 0.55 * (projected_seconds(pt) - _lookahead_hidden_seconds(pt))


def _legal_update(pt: TunePoint) -> bool:
    # The SMW update (linalg/update.py): three GEMMs, a k×k capacitance
    # solve, and the in-launch verification matmul — single-device
    # (the resident state it mutates lives on one chip; the tuning
    # point's k rides the serve executor key, not the plan key).
    return not pt.distributed


def _cost_update(pt: TunePoint) -> float:
    # O(n²k) correction + the one deliberate O(n³) verification matmul
    # vs the fresh elimination's ~(8/3)n³ + its own verification: ~0.45x
    # of the invert projection is the honest first-order ranking at
    # serve-relevant k ≤ n/8 (the point does not carry k; the serve key
    # does).  It is also the ONLY update-workload engine — the ranking
    # exists so the ladder, plan keys, and drift recording work exactly
    # like every other lane, not to arbitrate a zoo.
    return 0.45 * projected_seconds(pt)


CONFIGS: tuple[EngineConfig, ...] = (
    EngineConfig(
        "inplace", "inplace", 0, _real_dtype, _cost_inplace,
        "in-place 2N^3 elimination — the conservative default; unrolled "
        "trace vs fori picked by Nr inside the engine"),
    EngineConfig(
        "grouped2", "grouped", 2, _real_dtype, _cost_grouped,
        "delayed group updates, k=2 (the measured single-chip winner at "
        "n >= 8192 well-conditioned; fused stacked psums distributed)"),
    EngineConfig(
        "augmented", "augmented", 0, _always, _cost_augmented,
        "~4N^3 reference-parity path (global-scale singularity rule); "
        "the one complex-capable invert engine (dtype-generic sweeps)"),
    EngineConfig(
        "swapfree", "swapfree", 0, _distributed_only, _cost_swapfree,
        "implicit-permutation engine: no row-swap broadcast, bucketed "
        "ppermute deferred repairs — the pod-scale comm design, legal "
        "under either gather mode"),
    EngineConfig(
        "grouped_pallas", "grouped_pallas", 2, _legal_grouped_pallas,
        _cost_grouped_pallas,
        "delayed group updates with the group-closing superstep "
        "(normalize + eliminate sweep + bookkeeping) fused into one "
        "Pallas kernel (ops/pallas_update.py); fp32 bit-matches the "
        "grouped engine"),
    EngineConfig(
        "grouped_pallas_bf16", "grouped_pallas_bf16", 2,
        _legal_grouped_pallas_bf16, _cost_grouped_pallas_bf16,
        "the fused kernel with bf16-compute/fp32-accumulate dots "
        "(arXiv:2112.09017); auto-candidate only at sub-fp32 storage "
        "points, always guarded by the residual-gate ladder"),
    EngineConfig(
        "lookahead", "lookahead", 0, _legal_lookahead, _cost_lookahead,
        "probe-ahead in-place elimination (ISSUE 16): step t+1's pivot "
        "probe + reduction issued after step t's critical panel, before "
        "its trailing eliminate — the probe comes off the superstep "
        "critical path; bit-identical results and comm inventory, "
        "unrolled-reach Nr only"),
    # ---- solve workloads (ISSUE 11, tpu_jordan/linalg/) --------------
    EngineConfig(
        "solve_aug", "solve_aug", 0, _legal_solve, _cost_solve,
        "Gauss–Jordan on [A | B] with the condition-based pivot probe: "
        "X = A⁻¹B at ~n³(1+k/n) FLOPs, no inverse ever formed "
        "(linalg/engine.py); any dtype incl. complex",
        workload="solve"),
    EngineConfig(
        "solve_spd", "solve_spd", 0, _legal_solve, _cost_solve_spd,
        "pivot-free SPD fast path: the caller's assume='spd' promise "
        "makes every diagonal block invertible (PD principal "
        "submatrices), so the probe — the most expensive non-GEMM "
        "phase — is skipped outright",
        workload="solve_spd"),
    EngineConfig(
        "solve_aug_spd", "solve_aug", 0, _legal_solve, _cost_solve,
        "the pivoting solve engine at SPD points: the cross-check and "
        "recovery fallback (never cost-preferred over the pivot-free "
        "path, but a legal candidate the measuring tuner can promote)",
        workload="solve_spd"),
    EngineConfig(
        "solve_sharded", "solve_sharded", 0, _legal_solve_sharded,
        _cost_solve_sharded,
        "the [A | B] elimination sharded over the 1D/2D meshes "
        "(ISSUE 15): the k RHS columns ride the pivot/row-broadcast/"
        "eliminate supersteps, live-column window statically shrinking "
        "per shard (unrolled) or fori beyond MAX_UNROLL_NR; X "
        "bit-matches the single-device engine",
        workload="solve"),
    EngineConfig(
        "solve_lookahead_sharded", "solve_lookahead", 0,
        _legal_solve_lookahead, _cost_solve_lookahead,
        "the distributed [A | B] elimination with the probe-ahead "
        "schedule (ISSUE 16): panel-first eliminate, step t+1's probe + "
        "reduction overlapping the trailing update; X bit-matches "
        "solve_sharded, comm inventory multiset-identical, "
        "unrolled-reach Nr only",
        workload="solve"),
    EngineConfig(
        "solve_fori", "solve_fori", 0, _legal_solve_fori,
        _cost_solve_fori,
        "fori-compiled [A | B] solve: traced supersteps, compile cost "
        "flat in Nr — the engine that makes Nr > MAX_UNROLL_NR legal "
        "single-device; full-width updates (~2n³), X bit-matches the "
        "unrolled engine",
        workload="solve"),
    EngineConfig(
        "solve_fori_spd", "solve_fori", 0, _legal_solve_fori,
        _cost_solve_fori,
        "the pivoting fori solve engine at SPD points: the large-Nr "
        "fallback under the assume='spd' promise (condition-based "
        "pivoting stays sound there; never cost-preferred over the "
        "unrolled pivot-free path where that is legal)",
        workload="solve_spd"),
    # ---- resident-inverse updates (ISSUE 12, tpu_jordan/linalg) ------
    EngineConfig(
        "smw_update", "smw_update", 0, _legal_update, _cost_update,
        "Sherman–Morrison–Woodbury rank-k resident-inverse update: "
        "(A+UVᵀ)⁻¹ = A⁻¹ − A⁻¹U(I+VᵀA⁻¹U)⁻¹VᵀA⁻¹ at ~4n²k + O(nk²) "
        "plus the in-launch re-verification against the mutated matrix "
        "(linalg/update.py); the serve 'update' lanes' one engine",
        workload="update"),
)

REGISTRY: dict[str, EngineConfig] = {c.name: c for c in CONFIGS}
assert len(REGISTRY) == len(CONFIGS), "duplicate registry names"

# The product's engine vocabulary, derived from the registry (driver and
# CLI import this instead of keeping their own string lists).  dict.fromkeys
# dedups while preserving registration order; "auto" is the tuner.
# ENGINES stays the INVERT vocabulary (what driver.solve / the CLI
# --engine flag accept — byte-identical to pre-ISSUE-11); the solve
# workloads get their own derived tuple.
ENGINES: tuple[str, ...] = ("auto",) + tuple(
    dict.fromkeys(c.engine for c in CONFIGS if c.workload == "invert"))

#: The solve-workload engine vocabulary (linalg.solve_system's engine=
#: flag): derived the same way, "auto" = the tuner ladder per workload.
#: The update workload is deliberately excluded — smw_update is not a
#: solve engine (linalg.solve_update has no engine= knob to leak into).
SOLVE_ENGINES: tuple[str, ...] = ("auto",) + tuple(
    dict.fromkeys(c.engine for c in CONFIGS
                  if c.workload in ("solve", "solve_spd")))

#: The single-device fused-kernel engines (ops/pallas_update.py): the
#: driver gates them off distributed meshes, dispatches their grouped
#: Pallas implementation, and gives their execute spans MEASURED phase
#: children (the kernels are separately launchable, so the host has a
#: real bracket — obs/spans.attribute_phases_measured).
PALLAS_ENGINES: tuple[str, ...] = ("grouped_pallas", "grouped_pallas_bf16")


def get(name: str) -> EngineConfig:
    return REGISTRY[name]


def candidates(point: TunePoint) -> list[EngineConfig]:
    """Legal engine configurations at ``point``, cheapest projected
    first (name tie-break keeps the order deterministic).  Candidacy
    matches the point's WORKLOAD exactly (ISSUE 11): an invert point
    ranks the invert zoo, a solve point the solve engines — neither can
    leak into the other's cost ranking."""
    wl = getattr(point, "workload", "invert")
    legal = [c for c in CONFIGS if c.workload == wl and c.legal(point)]
    return sorted(legal, key=lambda c: (c.cost(point), c.name))


def select_by_cost(point: TunePoint) -> EngineConfig:
    """The cost-model pick — what ``engine='auto'`` runs when no plan
    cache entry exists and measurement wasn't requested.  Below the
    model's calibration floor (``COST_MODEL_FLOOR_N``) distributed
    points keep the conservative in-place engine; see the constant's
    comment for why sub-noise rankings are not trusted."""
    cands = candidates(point)
    if not cands:
        raise ValueError(f"no legal engine at {point}")
    if point.distributed and point.n < COST_MODEL_FLOOR_N:
        for c in cands:
            if c.name == "inplace":
                return c
    return cands[0]
