"""Autotuner + persistent plan cache (ISSUE 2 tentpole): measured engine
selection with a variance-damped measurement core.

Four parts (docs/TUNING.md is the operator guide):

  * ``registry``   — the single declarative registry of every engine
    configuration (legality predicates + comm_model cost hooks); the
    driver's ``ENGINES`` vocabulary derives from it.
  * ``measure``    — the robust measurement core (warmup, median-of-k
    with IQR outlier rejection, variance flags, typed transient retry),
    shared with bench.py.
  * ``tuner``      — cache -> cost ranking -> measured tuning ladder;
    records measured-vs-projected drift.
  * ``plan_cache`` — the versioned JSON plan store keyed by
    (backend, topology, n-bucket, dtype, memory mode) with
    corruption/staleness fallback.

Product surface: ``solve(engine="auto", tune=..., plan_cache=...)``,
``JordanSolver(engine="auto", ...)``, CLI ``--engine auto --tune
--plan-cache PATH``.
"""

from .measure import (Measurement, is_transient, measure_direct,
                      measure_slope, retry_transient, robust_stats)
from .plan_cache import CACHE_VERSION, Plan, PlanCache, n_bucket, plan_key
from .registry import (CONFIGS, ENGINES, PALLAS_ENGINES, REGISTRY,
                       SOLVE_ENGINES, WORKLOADS, EngineConfig,
                       TunePoint, candidates, select_by_cost)
from .tuner import Tuner, auto_select, measure_config

__all__ = [
    "Measurement", "is_transient", "measure_direct", "measure_slope",
    "retry_transient", "robust_stats",
    "CACHE_VERSION", "Plan", "PlanCache", "n_bucket", "plan_key",
    "CONFIGS", "ENGINES", "PALLAS_ENGINES", "REGISTRY", "EngineConfig",
    "SOLVE_ENGINES", "TunePoint", "WORKLOADS", "candidates",
    "select_by_cost",
    "Tuner", "auto_select", "measure_config",
]
