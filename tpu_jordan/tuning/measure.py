"""The variance-damped measurement core (ISSUE 2 tentpole part 2) —
shared by the autotuner (``tuner.py``) and the headline benchmark
(``bench.py``), so neither can drift its own weaker methodology.

What "robust" means here, in order of the failure modes it closes:

  * **Warmup discipline** — the first call of any measured callable is
    never timed (it absorbs compile/dispatch caches); ``measure_direct``
    runs explicit warmup calls, ``measure_slope`` inherits the warmup
    built into ``utils/benchmarking.slope_time``.
  * **Median-of-k with IQR outlier rejection** — VERDICT r5 weak #1: a
    single sample silently regressed the 4096 headline 15% on session
    noise.  ``robust_stats`` takes k samples, rejects points outside
    [q1 − 1.5·IQR, q3 + 1.5·IQR] (the standard Tukey fence), and reports
    the median of the survivors.  The fence needs k >= 5 to actually
    reject a lone wild sample (for k <= 4 the interpolated quartiles
    stretch with the outlier and the fence provably never excludes it);
    at bench.py's k = 3 the MEDIAN is the damper — it ignores one wild
    sample for the point estimate by construction — and the polluted
    spread then trips the variance flag, which is the honest signal.
    The tuner defaults to k = 5, where the fence is live.
  * **Spread/variance flags** — the accepted samples' (max − min)/median
    rides every measurement; above ``VARIANCE_FLAG_PCT`` an explicit
    ``variance_flag`` string is set so a noisy session can never
    masquerade as a code regression (or improvement).
  * **Transient retry via a typed classifier** — ``is_transient`` /
    ``retry_transient`` now live in ``resilience/policy.py`` (ISSUE 5
    satellite: ONE classifier, ONE backoff implementation, retries
    counted in ``tpu_jordan_retries_total``) and are re-exported here
    for compatibility: one retry on the documented-transient
    remote-compile/transport failure class, and ONLY when the exception
    TYPE is a runtime or transport error — substring matching alone
    once let an accuracy AssertionError that merely quoted "INTERNAL"
    trigger a full n=16384 re-run (ADVICE r5).  The ``measure`` fault
    point (``resilience/faults.py``) fires inside every timed call, so
    the retry path is deterministically testable.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..resilience import faults as _faults
from ..resilience.policy import (RetryPolicy, is_transient,  # noqa: F401
                                 retry_transient)

VARIANCE_FLAG_PCT = 10.0     # accepted-sample spread above this is noisy

# The measurement core's own retry discipline, expressed as the shared
# policy object (bench.py and the tuner both ride this): one retry, no
# backoff, strict transient classification.
MEASURE_RETRY = RetryPolicy(max_retries=1, backoff_s=0.0,
                            classify=is_transient)


@dataclass(frozen=True)
class Measurement:
    """One robust timing: ``seconds`` is the median of the IQR-accepted
    samples; the raw/accepted/rejected sample lists and the spread ride
    along so consumers (bench rows, tuner plans) can publish them."""

    seconds: float
    samples: tuple[float, ...]
    accepted: tuple[float, ...]
    rejected: tuple[float, ...] = ()
    spread_pct: float = 0.0
    variance_flag: str | None = field(default=None)


def robust_stats(samples, flag_pct: float = VARIANCE_FLAG_PCT
                 ) -> Measurement:
    """Median-of-k with Tukey-fence (1.5×IQR) outlier rejection over raw
    timing ``samples`` (seconds).  The fence is computed on the raw set;
    the median, spread, and variance flag on the survivors.  Note the
    fence only has teeth from k >= 5 (see module docstring); below that
    the median itself is the outlier damping.  Degenerate inputs (k <= 2,
    or a fence that would reject everything) fall back to the raw
    median — a measurement is always produced."""
    raw = tuple(float(s) for s in samples)
    if not raw:
        raise ValueError("no samples")
    accepted, rejected = raw, ()
    if len(raw) >= 3:
        q1, q3 = np.percentile(raw, [25.0, 75.0])
        iqr = q3 - q1
        lo, hi = q1 - 1.5 * iqr, q3 + 1.5 * iqr
        accepted = tuple(s for s in raw if lo <= s <= hi)
        rejected = tuple(s for s in raw if not (lo <= s <= hi))
        if not accepted:                         # pathological: keep raw
            accepted, rejected = raw, ()
    med = float(np.median(accepted))
    # abs(): slope measurements of noise-floor ops can go (harmlessly)
    # negative; the spread must stay a magnitude either way.
    spread = (0.0 if med == 0.0
              else 100.0 * (max(accepted) - min(accepted)) / abs(med))
    flag = None
    if spread > flag_pct:
        flag = (f"session spread {spread:.1f}% > {flag_pct:.0f}% — treat "
                f"the median as noisy")
    return Measurement(seconds=med, samples=raw, accepted=accepted,
                       rejected=rejected, spread_pct=round(spread, 1),
                       variance_flag=flag)


def measure_direct(fn, samples: int = 5, warmup: int = 1) -> Measurement:
    """Time ``fn()`` (which must block until its work is done) ``samples``
    times after ``warmup`` untimed calls; each call gets the one-shot
    transient retry (``MEASURE_RETRY``) and crosses the ``measure``
    fault point.  The tuner's measurement primitive for full engine
    executions."""
    def call():
        _faults.fire("measure")
        return fn()

    for _ in range(warmup):
        MEASURE_RETRY.call(call, component="measure")
    ts = []
    for _ in range(samples):
        t0 = time.perf_counter()
        MEASURE_RETRY.call(call, component="measure")
        ts.append(time.perf_counter() - t0)
    return robust_stats(ts)


def measure_slope(fn, args, r1: int, r2: int, samples: int = 3,
                  **slope_kw) -> Measurement:
    """Tunnel-safe slope timing (``utils/benchmarking.slope_time``: the
    op repeats inside one jitted fori_loop and constant offsets cancel in
    the two-trip-count slope) with the robust core applied across the
    ``samples`` per-executable slope measurements.  bench.py's capture
    ladder runs on this instead of its former private median-of-3."""
    from ..utils.benchmarking import slope_time

    slopes = retry_transient(
        lambda: slope_time(fn, args, r1=r1, r2=r2, samples=samples,
                           **slope_kw))
    if samples == 1:
        slopes = [slopes]
    return robust_stats(slopes)
