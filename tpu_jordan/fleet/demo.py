"""``fleet_demo`` — the ``--fleet-demo`` CLI mode's engine (ISSUE 7
acceptance).

One self-contained run proves the fleet contract end to end, in four
phases sharing ONE :class:`~..serve.executors.ExecutorStore` and ONE
pre-tuned read-only plan cache (so compile accounting spans the whole
demo):

  0. **pretune** — a throwaway writable service warms every bucket,
     compiling each (bucket, batch_cap) executable exactly once into
     the shared store and writing the engine plans to the plan-cache
     file.  Every later phase opens that file ``read_only=True`` (the
     fleet contract: N readers, zero writes — a write attempt would be
     the typed ``UsageError``).
  1. **baseline** — the deterministic mixed request stream (the
     chaos-demo builder: sizes {n, n/2}, seeded fixtures, rank-1
     singulars at fixed indices) through a 1-replica fleet: the
     single-replica throughput + latency reference.
  2. **fleet, fault-free** — the same stream through an N-replica
     fleet: throughput scaling + the bit-exact replay baseline (shared
     executables make every replica's answer for a given element
     byte-identical).
  3. **fleet, chaos** — the same stream again, staged (queued before
     dispatch — so a killed replica provably holds queued work), under
     a seeded :class:`~..resilience.faults.FaultPlan` whose
     ``replica_kill`` schedule crashes replicas mid-stream.  The
     supervisor warm-replaces each victim against the shared store
     (``tpu_jordan_compiles_total`` delta == 0 after warmup — the
     acceptance pin); the router re-queues the victim's queued
     requests.  Every response must bit-match phase 2 or carry a typed
     error — zero silent errors, and the ledger must add up
     (``tools/check_fleet.py`` validates; exit 2 = silent loss).

Honest-scaling note: the in-process worker backend shares one Python
interpreter (GIL) and one device between replicas, so wall-clock
throughput scaling is hardware-conditional — near 1x on a small shared
CPU host, approaching Nx only where replicas map to real parallel
devices.  The report records the measured ``scaling_x`` against an
explicit ``scaling_floor`` (default 0.6: a fleet must never cost
material throughput versus one replica; operators on parallel hardware
pass a demanding floor, e.g. ``--scaling-floor 2.5`` for the 3-replica
~3x claim).  The bound is explicit in the report — never a silent
pass (docs/FLEET.md; the BASELINE.md v5e-negative discipline).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

import jax.numpy as jnp
import numpy as np

from ..obs.journey import outcome_ledger
from ..obs.metrics import REGISTRY, percentiles
from ..obs.recorder import RECORDER
from ..obs.slo import SLOMonitor, bucket_specs
from ..resilience import FaultPlan, ResiliencePolicy
from ..resilience import activate as _activate
from ..resilience.policy import RetryPolicy
from ..serve.executors import ExecutorStore, bucket_for
from ..serve.service import (JordanService, _chaos_requests,
                             _classify_response, compare_outcomes)
from .pool import JordanFleet
from .replica import READY

#: Default scaling floor for the shared-interpreter worker backend: the
#: fleet must not cost material single-replica throughput (measured
#: 2-core-host spread is ~0.7-1.1x with median-of-3 laps, so the floor
#: leaves noise margin without going vacuous).  The ~Nx linear claim is
#: a parallel-hardware claim — pass an explicit floor there
#: (docs/FLEET.md).
DEFAULT_SCALING_FLOOR = 0.6


def _run_fleet_stream(fleet: JordanFleet, mats, staged: bool,
                      timeout: float = 300.0):
    """Run the stream; classify every response; return
    (outcomes, elapsed_s, latencies_ms).  ``staged=True`` queues
    everything before starting the dispatchers (deterministic queue
    depth at a mid-stream kill); latencies are then measured from
    dispatch start, not submit."""
    futs = []
    t_submit = []
    t0 = time.perf_counter()
    for a in mats:
        t_submit.append(time.perf_counter())
        try:
            futs.append(fleet.submit(a))
        except Exception as e:                        # noqa: BLE001
            futs.append(e)
    if staged:
        fleet.start()
        t_start = time.perf_counter()
        t_submit = [t_start] * len(futs)
    out, lat_ms = [], []
    for ts, f in zip(t_submit, futs):
        out.append(_classify_response(f, timeout))
        if not isinstance(f, Exception):
            lat_ms.append((time.perf_counter() - ts) * 1e3)
    return out, time.perf_counter() - t0, lat_ms


def _counters():
    c = REGISTRY.counter
    return {
        "compiles": c("tpu_jordan_compiles_total").total(),
        "deaths": c("tpu_jordan_fleet_replica_deaths_total").total(),
        "restarts": c("tpu_jordan_fleet_restarts_total").total(),
        "restart_failures":
            c("tpu_jordan_fleet_restart_failures_total").total(),
        "measurements": c("tpu_jordan_tuner_measurements_total").total(),
        "reroutes": c("tpu_jordan_fleet_reroutes_total").total(),
        "shed_dead": c("tpu_jordan_fleet_shed_total").value(reason="dead"),
        "shed_breaker":
            c("tpu_jordan_fleet_shed_total").value(reason="breaker"),
        "shed_overload":
            c("tpu_jordan_fleet_shed_total").value(reason="overload"),
        "faults_injected": c("tpu_jordan_faults_injected_total").total(),
    }


def fleet_demo(n: int = 96, replicas: int = 3, requests: int = 60,
               batch_cap: int = 4, max_wait_ms: float = 2.0,
               kills: int = 2, seed: int = 0, block_size: int | None = None,
               dtype=jnp.float32, plan_cache: str | None = None,
               scaling_floor: float | None = None,
               p99_bound_ms: float | None = None,
               telemetry=None, slo_report: bool = False) -> dict:
    """Run the four-phase fleet acceptance demo; returns the one-line
    JSON report ``tools/check_fleet.py`` validates.  ``plan_cache``
    None = a temp pre-tuned cache built by phase 0 and deleted after."""
    t_all = time.perf_counter()
    if replicas < 2:
        raise ValueError("fleet_demo needs replicas >= 2 (the scaling "
                         "and kill phases are fleet properties)")
    mats = _chaos_requests(n, requests, seed, jnp.dtype(dtype))
    shapes = sorted({a.shape[0] for a in mats})
    store = ExecutorStore()
    # Reroute/retry budget sized like the chaos demo: each kill can
    # re-queue a victim's whole backlog, and a request may be re-queued
    # once per kill it is unlucky enough to chase.
    policy = ResiliencePolicy(
        retry=RetryPolicy(max_retries=max(4, kills + 2), backoff_s=0.0))
    scaling_floor = (DEFAULT_SCALING_FLOOR if scaling_floor is None
                     else float(scaling_floor))

    cache_dir = None
    if plan_cache is None:
        cache_dir = tempfile.mkdtemp(prefix="tpu_jordan_fleet_")
        plan_cache = os.path.join(cache_dir, "plans.json")
    try:
        # ---- phase 0: pretune (the only writer, ever) ---------------
        with JordanService(engine="auto", plan_cache=plan_cache,
                           dtype=dtype, batch_cap=batch_cap,
                           max_wait_ms=max_wait_ms, autostart=False,
                           block_size=block_size, policy=policy,
                           shared_executors=store,
                           telemetry=telemetry) as svc:
            svc.warmup(shapes=shapes)
            pretuned_keys = len(store)
        counters_pretune = _counters()
        compiles_pretune = counters_pretune["compiles"]

        fleet_kw = dict(
            engine="auto", plan_cache=plan_cache,
            plan_cache_read_only=True, dtype=dtype, batch_cap=batch_cap,
            max_wait_ms=max_wait_ms, max_queue=max(requests * 2, 64),
            block_size=block_size, policy=policy, telemetry=telemetry,
            executor_store=store, stable_after_s=0.2,
            liveness_deadline_s=5.0)

        # ---- phase 1: single-replica baseline -----------------------
        # One untimed warm lap first: the demo's first real executions
        # pay one-time process costs (jax dispatch caches, allocator)
        # that would deflate the single-replica reference and INFLATE
        # scaling_x — the throughput comparison must be steady state
        # vs steady state.  Then median-of-3 timed laps (the
        # tuning/measure variance discipline): a single lap's wall
        # clock on a small shared host is too noisy to bound against.
        with JordanFleet(replicas=1, **fleet_kw) as one:
            one.warmup(shapes)
            _run_fleet_stream(one, mats, staged=False)
            laps1 = [_run_fleet_stream(one, mats, staged=False)
                     for _ in range(3)]
        _, el1, lat1 = sorted(laps1, key=lambda r: r[1])[1]
        single_rps = requests / el1

        # ---- the SLO monitor (ISSUE 8, --slo-report) ----------------
        # Brackets the FLEET phases (2 + 3): one sample before the
        # fault-free fleet pass, one after it, one after the chaos
        # pass — demo-scaled window pairs (a demo lives seconds, not
        # the SRE workbook's hours; the pairs truncate honestly and
        # the report says so).  Availability 0.95: the seeded chaos
        # dose of typed errors must spend budget VISIBLY (non-zero
        # burn) without paging a healthy fleet.
        monitor = None
        if slo_report:
            monitor = SLOMonitor(
                bucket_specs((bucket_for(s) for s in shapes),
                             availability=0.95),
                windows=((60.0, 10.0, 14.4), (300.0, 60.0, 6.0)))
            monitor.sample()

        # ---- phase 2: N-replica fleet, fault-free -------------------
        with JordanFleet(replicas=replicas, **fleet_kw) as flt:
            flt.warmup(shapes)
            _run_fleet_stream(flt, mats, staged=False)
            laps2 = [_run_fleet_stream(flt, mats, staged=False)
                     for _ in range(3)]
        baseline, el2, lat2 = sorted(laps2, key=lambda r: r[1])[1]
        fleet_rps = requests / el2
        scaling_x = fleet_rps / single_rps
        if monitor is not None:
            monitor.sample()

        # ---- the seeded kill schedule -------------------------------
        # Horizon = the routed-call window the kills land in: past the
        # first few calls (so the victim provably holds queued work in
        # the staged run) but well inside the stream.
        horizon = max(4, requests // 2)
        plan = FaultPlan.seeded(seed,
                                points={"replica_kill": (kills, horizon)})

        # ---- phase 3: N-replica fleet under seeded replica_kill -----
        before = _counters()
        chaos_fleet = JordanFleet(replicas=replicas, autostart=False,
                                  **fleet_kw)
        try:
            chaos_fleet.warmup(shapes)
            after_warm = _counters()
            # Black-box window (ISSUE 8): bracket the chaos pass in
            # the always-on flight recorder — every journey hop, kill,
            # restart, reroute, and fault of THIS pass lands in the
            # embedded slice, so the checker reconstructs each
            # request's causal chain from the report alone.
            bb_mark = RECORDER.total
            with _activate(plan):
                chaos, el3, lat3 = _run_fleet_stream(chaos_fleet, mats,
                                                     staged=True)
            chaos_stats = chaos_fleet.stats()
        finally:
            chaos_fleet.close()
        blackbox = RECORDER.dump(events=RECORDER.since(bb_mark))
        journey_ledger = outcome_ledger(blackbox["events"])
        after = _counters()
        if monitor is not None:
            monitor.sample()
    finally:
        if cache_dir is not None:
            shutil.rmtree(cache_dir, ignore_errors=True)

    delta = {k: after[k] - before[k] for k in before}
    compiles_after_warmup = after["compiles"] - after_warm["compiles"]

    # ---- compare chaos vs the fault-free replay ---------------------
    # ONE shared comparator with the chaos demo (ISSUE 8 satellite):
    # what "matched" means can never drift between the two checkers.
    matched, singular, typed_errors, mismatches = compare_outcomes(
        baseline, chaos)

    ledger = chaos_stats["ledger"]
    typed_total = sum(typed_errors.values())
    # A journey GAP — a request the black box saw submitted but never
    # saw resolve — is silent loss by definition, whatever the
    # response-side ledger claims (ISSUE 8 acceptance).
    silent_loss = (bool(mismatches)
                   or ledger["outstanding"] != 0
                   or matched + typed_total + len(mismatches) != requests
                   or bool(journey_ledger["gaps"]))
    # Process-wide delta over EVERY serving phase (not a sum over the
    # surviving replicas' tuners — a killed replica's counter would be
    # discarded with it and hide a measurement from the pin).
    measurements = after["measurements"] - counters_pretune["measurements"]
    # Deaths an OPEN restart breaker deliberately left unfilled at
    # stats time: the checker's restart-coverage ledger must count the
    # designed degraded state, not flag it as an abandoned slot.
    stranded_by_breaker = sum(
        1 for s in chaos_stats["slots"]
        if s["restart_breaker"] == "open"
        and (s["replica"] is None or s["replica"]["state"] != READY))

    def p99(xs):
        v = percentiles(xs)["p99"]
        return 0.0 if v is None else float(v)

    # ---- hwcost block (ISSUE 10): the shared store's per-executable
    # XLA accounting — what the fleet's compiled programs actually cost
    # per launch — plus the runtime environment fingerprint and the
    # device live-bytes watermark where the backend reports one.
    from ..obs import hwcost as _hwcost

    executables = {}
    for key, ex in store.entries():
        cost = getattr(ex, "cost", None)
        if cost is not None and cost.available:
            executables[f"{key.bucket_n}x{key.batch_cap}"
                        f"@{key.engine}"] = cost.to_json()
    hwcost_block = {
        "env": _hwcost.runtime_env(),
        "executables": executables,
        "device_memory": _hwcost.device_memory_stats(),
    }

    fleet_p99_ms = p99(lat2)
    if p99_bound_ms is None:
        # Generous runaway guard, not a perf SLO: the closed-loop p99
        # is ~the whole stream's drain time, so bound it by a multiple
        # of the measured single-replica drain + slack.
        p99_bound_ms = max(2000.0, 5e3 * el1)

    return {
        "metric": "fleet_demo",
        "n": n,
        "requests": requests,
        "request_sizes": shapes,
        "replicas": replicas,
        "batch_cap": batch_cap,
        "seed": seed,
        "worker_backend": "in-process-threads",
        "plan_cache": {
            "pretuned_keys": pretuned_keys,
            "read_only": True,
            "measurements": measurements,
            "compiles_pretune": compiles_pretune,
        },
        "throughput": {
            "single_rps": round(single_rps, 1),
            "fleet_rps": round(fleet_rps, 1),
            "scaling_x": round(scaling_x, 3),
            "scaling_floor": scaling_floor,
            "scaling_note": (
                "in-process worker backend: replicas share one "
                "interpreter and one device — ~Nx wall-clock scaling "
                "is a parallel-hardware claim (docs/FLEET.md); the "
                "floor pins 'a fleet never costs material throughput'"),
            "single_p99_ms": round(p99(lat1), 1),
            "fleet_p99_ms": round(fleet_p99_ms, 1),
            "chaos_p99_ms": round(p99(lat3), 1),
            "p99_bound_ms": round(p99_bound_ms, 1),
        },
        "chaos": {
            "faults": plan.report(),
            "kills_injected": int(delta["faults_injected"]),
            "deaths": delta["deaths"],
            "restarts": delta["restarts"],
            "restart_failures": delta["restart_failures"],
            "stranded_by_breaker": stranded_by_breaker,
            "reroutes": delta["reroutes"],
            "shed": {"dead": delta["shed_dead"],
                     "breaker": delta["shed_breaker"],
                     "overload": delta["shed_overload"]},
            "compiles_delta_after_warmup": compiles_after_warmup,
            "lineage": {str(s["slot"]): s["lineage"]
                        for s in chaos_stats["slots"]},
            "elapsed_s": round(el3, 3),
        },
        "hwcost": hwcost_block,
        "ledger": ledger,
        # The journey-derived ledger of the SAME chaos pass (ISSUE 8:
        # the one shared outcome_ledger helper over the embedded
        # black-box slice) — the checker reconciles it against the
        # response ledger and walks every request's causal chain.
        "journey_ledger": journey_ledger,
        "blackbox": blackbox,
        "matched_bitwise": matched,
        "singular_flagged": singular,
        "typed_errors": typed_errors,
        "mismatches": mismatches,
        "silent_loss": silent_loss,
        **({"slo": monitor.evaluate()} if monitor is not None else {}),
        "elapsed_s": round(time.perf_counter() - t_all, 3),
    }
