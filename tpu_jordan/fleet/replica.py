"""One supervised fleet replica (ISSUE 7 tentpole part 1).

A :class:`Replica` is a worker wrapping its own
:class:`~..serve.service.JordanService` — its own dispatcher thread,
its own bounded queue, its own per-bucket circuit breakers — while the
compiled bucket executables live in the fleet-shared
:class:`~..serve.executors.ExecutorStore` and the engine plans come
from the shared read-only pre-tuned plan cache.  That split is the
whole design: everything *stateful about health* is per replica (so one
sick replica sheds without judging its peers), everything *expensive
and immutable* is shared (so replacing a replica costs zero compiles
and zero measurements).

Lifecycle: ``ready`` → (``draining`` →) ``closed`` on a clean
shutdown, or ``ready`` → ``dead`` on a kill.  A kill is the crash
simulation (and the supervisor's wedge remedy): the replica stops
accepting work, its QUEUED requests are failed with the typed
:class:`ReplicaKilledError` (the router re-queues each one through the
PR 5 retry/deadline machinery — never lost, never silent), the batch
already on the device completes and delivers normally (the
deterministic kill boundary of the in-process worker backend), and the
supervisor is notified so a warm replacement can take the slot.

The ``replica_kill`` fault point (``resilience/faults.py``) fires on
the replica's dispatch path — the k-th routed request of a seeded
:class:`~..resilience.faults.FaultPlan` crashes whichever replica it
was routed to, byte-identically run after run (the PR 5 chaos
discipline).

Liveness: a heartbeat thread stamps ``last_beat`` every
``heartbeat_interval_s`` — but only when the DISPATCHER proves
liveness (``MicroBatcher.progress()``): idle-parked or advancing its
tick counter.  A dispatcher stuck mid-execute (the real production
wedge — a hung device call) keeps ``busy=True`` with a frozen tick
count, the stamp goes stale, and the supervisor's liveness deadline
kills and replaces the replica; that kill joins the wedged dispatcher
with a BOUNDED timeout (``kill_join_timeout_s``) so the supervising
thread abandons the stuck daemon instead of freezing fleet supervision
on it.  ``wedge()`` freezes the stamp directly (the deterministic
wedge fixture for tests — no in-process test should hang a real
dispatcher on purpose).
"""

from __future__ import annotations

import threading
import time

from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from ..resilience import faults as _faults
from ..resilience.faults import InjectedFaultError, InjectedTransientError

#: Replica lifecycle states.
READY, DRAINING, DEAD, CLOSED = "ready", "draining", "dead", "closed"

_M_DEATHS = _obs_metrics.counter(
    "tpu_jordan_fleet_replica_deaths_total",
    "unclean replica deaths (killed/injected/wedged), labeled by reason "
    "and slot — every one triggers a supervisor replacement attempt")


class ReplicaKilledError(RuntimeError):
    """A replica died (crash, injected ``replica_kill``, or supervisor
    wedge remedy) while this request was queued at it or being routed
    to it.  The fleet router treats this as re-queueable: the request
    is re-dispatched to a healthy replica within its deadline/retry
    budget — the caller only ever sees it when the budget is exhausted
    or the whole fleet is gone (typed, never silent)."""


class Replica:
    """One worker in the pool: a :class:`JordanService` plus lifecycle
    state, a heartbeat, and the kill/drain hooks the supervisor and
    router drive.  ``service`` is built by the pool (shared executor
    store, read-only plan cache, per-replica metric labels)."""

    def __init__(self, slot: int, generation: int, service,
                 heartbeat_interval_s: float = 0.05, clock=None,
                 on_death=None, kill_join_timeout_s: float = 1.0):
        self.slot = int(slot)
        self.generation = int(generation)
        self.name = f"r{slot}g{generation}"
        self.service = service
        self.clock = clock if clock is not None else time.monotonic
        self._on_death = on_death
        self._kill_join_timeout_s = float(kill_join_timeout_s)
        self._lock = threading.Lock()
        self.state = READY
        self.started_at = self.clock()
        self.last_beat = self.clock()
        self._wedged = False
        self._hb_stop = threading.Event()
        self._hb = threading.Thread(
            target=self._beat_loop, args=(float(heartbeat_interval_s),),
            name=f"tpu-jordan-fleet-hb-{self.name}", daemon=True)
        self._hb.start()

    # ---- liveness ----------------------------------------------------

    def _beat_loop(self, interval: float) -> None:
        # The stamp proves DISPATCHER liveness, not this thread's own:
        # stamping unconditionally from a dedicated thread would keep a
        # replica whose dispatcher is stuck mid-execute looking healthy
        # forever.  Idle (busy=False, parked in the condition wait) is
        # responsive; busy with an advancing tick count is working;
        # busy with a frozen tick count is the wedge — no stamp, and
        # the supervisor's staleness deadline fires.  The liveness
        # deadline must therefore exceed the longest legitimate batch
        # execution (docs/FLEET.md).
        last_ticks = None
        while not self._hb_stop.wait(interval):
            ticks, busy = self.service._batcher.progress()
            if not self._wedged and (not busy or ticks != last_ticks):
                self.last_beat = self.clock()
            last_ticks = ticks

    def wedge(self) -> None:
        """Freeze the heartbeat (test fixture): the replica keeps its
        thread but stops proving liveness — the supervisor's staleness
        deadline must catch it and kill/replace."""
        self._wedged = True

    # ---- request path ------------------------------------------------

    def _admit(self, ctx) -> None:
        """The shared dispatch guard every request kind passes: refuse
        when not serving, and fire the seeded ``replica_kill`` point —
        THIS call may be the one the schedule crashes (the request
        never entered a queue; the router re-dispatches it)."""
        if self.state != READY:
            raise ReplicaKilledError(
                f"replica {self.name} is {self.state}, not serving")
        try:
            _faults.fire("replica_kill")
        except (InjectedFaultError, InjectedTransientError) as e:
            if ctx is not None:
                # The request that pulled the trigger journeys the
                # crash it caused (it never entered a queue; the
                # router's shed/requeue hops follow).
                ctx.event("fault", point="replica_kill",
                          replica=self.name)
            self.kill(reason="injected")
            raise ReplicaKilledError(
                f"replica {self.name} crashed at dispatch "
                f"(injected replica_kill)") from e

    def submit(self, a, deadline_ms: float | None = None, ctx=None):
        """Route one request into this replica's service.  Raises
        :class:`ReplicaKilledError` when the replica is not serving —
        including the case where THIS call is the one the seeded
        ``replica_kill`` schedule crashes.  ``ctx`` is the fleet-level
        journey context (ISSUE 8), threaded through so one request
        keeps ONE journey across replicas."""
        self._admit(ctx)
        return self.service.submit(a, deadline_ms=deadline_ms, _ctx=ctx)

    def submit_update(self, handle, u, v,
                      deadline_ms: float | None = None, ctx=None):
        """Route one resident-inverse update into this replica's
        service (ISSUE 12) — same admission guard, same kill
        semantics: the handle's committed state lives in the
        fleet-shared store, so a crash here loses nothing (the router
        re-queues and the retry re-reads committed state)."""
        self._admit(ctx)
        return self.service.submit_update(handle, u, v,
                                          deadline_ms=deadline_ms,
                                          _ctx=ctx)

    def submit_solve(self, a, b, deadline_ms: float | None = None,
                     ctx=None):
        """Route one solve request (X = A⁻¹B, ISSUE 17) into this
        replica's service — same admission guard and kill semantics as
        ``submit``; the service's solve lanes never form an inverse."""
        self._admit(ctx)
        return self.service.submit(a, b, deadline_ms=deadline_ms,
                                   _ctx=ctx)

    def submit_solve_ckpt(self, a, b, ckpt, resume_from=None, ctx=None):
        """Route one CHECKPOINTED distributed solve (ISSUE 20) onto
        this replica.  Unlike the batched lanes, the superstep sweep is
        a long-lived multi-segment job, so it runs on a dedicated
        per-request thread OUTSIDE the micro-batcher, with the runner's
        ``abort=`` hook watching THIS replica's lifecycle: a kill
        mid-sweep surfaces :class:`ReplicaKilledError` at the next
        segment boundary — AFTER that boundary's checkpoint is durable
        — so the router re-queues the request and the next replica
        resumes from the store instead of recomputing (lost work is
        bounded by the cadence).  ``ckpt`` is the fleet checkpoint spec
        dict: ``store``, ``run_id``, ``cadence``, and optionally
        ``engine`` / ``mesh`` / ``block_size``."""
        self._admit(ctx)
        from concurrent.futures import Future

        import numpy as np

        from ..resilience.checkpoint import checkpointed_solve

        fut = Future()
        fut.set_running_or_notify_cancel()

        def abort():
            if self.state != READY:
                return ReplicaKilledError(
                    f"replica {self.name} is {self.state}: died under "
                    f"a checkpointed solve — resume from the last "
                    f"durable superstep")
            return None

        def run():
            try:
                from ..serve.batcher import InvertResult

                t0 = time.monotonic()
                x, singular, info = checkpointed_solve(
                    np.asarray(a), np.asarray(b),
                    ckpt.get("block_size"),
                    store=ckpt["store"], run_id=ckpt["run_id"],
                    cadence=int(ckpt["cadence"]),
                    engine=ckpt.get("engine", "unrolled"),
                    mesh=ckpt.get("mesh"),
                    resume_from=resume_from, abort=abort)
                xh = np.asarray(x)
                ah = np.asarray(a, xh.dtype)
                bh = np.asarray(b, xh.dtype)
                if bh.ndim == 1:
                    bh = bh[:, None]
                denom = float(np.linalg.norm(bh)) or 1.0
                res = InvertResult(
                    inverse=None, n=int(ah.shape[0]),
                    bucket_n=int(ah.shape[0]),
                    singular=bool(singular), kappa=float("nan"),
                    rel_residual=float(
                        np.linalg.norm(ah @ xh - bh)) / denom,
                    queue_seconds=0.0,
                    execute_seconds=time.monotonic() - t0,
                    batch_occupancy=1, workload="solve", solution=x)
                res.ckpt_info = info
                fut.set_result(res)
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)

        threading.Thread(
            target=run, daemon=True,
            name=f"tpu-jordan-ckpt-{self.name}").start()
        return fut

    def warmup(self, shapes, update_shapes=(), solve_shapes=()) -> dict:
        return self.service.warmup(shapes, update_shapes=update_shapes,
                                   solve_shapes=solve_shapes)

    def breaker_allows(self, bucket_n: int) -> bool:
        """Router shedding hook: False while this replica's per-bucket
        breaker is open (it receives no traffic for that bucket; an
        elapsed cooldown admits the half-open probe here, exactly as at
        submit)."""
        br = self.service.executors.breaker(bucket_n)
        return br is None or br.allow()

    # ---- lifecycle ---------------------------------------------------

    def kill(self, reason: str = "killed") -> bool:
        """Crash semantics (idempotent; False when already down): mark
        DEAD, stop the heartbeat, fail every QUEUED request with the
        typed :class:`ReplicaKilledError` (the in-flight batch on the
        device completes and delivers — the in-process worker's kill
        boundary), and notify the supervisor."""
        with self._lock:
            if self.state in (DEAD, CLOSED):
                return False
            self.state = DEAD
        self._hb_stop.set()
        _M_DEATHS.inc(reason=reason, replica=str(self.slot))
        _recorder.record("replica_death", replica=self.name,
                         slot=self.slot, reason=reason)
        name = self.name
        # Bounded join: a kill's whole purpose may be abandoning an
        # unresponsive worker (the wedge remedy) — joining its stuck
        # dispatcher unbounded would freeze the supervising thread and
        # with it all future replacements.
        self.service.close(
            drain=False,
            error=lambda: ReplicaKilledError(
                f"replica {name} died ({reason}) before this request "
                f"ran — re-queued by the fleet router"),
            join_timeout_s=self._kill_join_timeout_s)
        if self._on_death is not None:
            self._on_death(self, reason)
        return True

    def close(self, drain: bool = True) -> None:
        """Clean shutdown (idempotent): drain in-flight and queued work
        (``drain=True``), stop the heartbeat, mark CLOSED.  A closed
        replica is not a death — the supervisor does not replace it."""
        with self._lock:
            if self.state in (DEAD, CLOSED):
                return
            self.state = DRAINING
        self._hb_stop.set()
        self.service.close(drain=drain)
        with self._lock:
            self.state = CLOSED

    # ---- observability ----------------------------------------------

    @property
    def queued(self) -> int:
        return self.service._batcher.queued

    def snapshot(self) -> dict:
        """The per-replica slice of ``JordanFleet.stats()``."""
        return {
            "name": self.name,
            "slot": self.slot,
            "generation": self.generation,
            "state": self.state,
            "queued": (self.queued if self.state == READY else 0),
            "breakers": {str(b): s for b, s in
                         self.service.executors.breaker_states().items()},
        }
