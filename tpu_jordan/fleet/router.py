"""Bucket-affinity router with breaker-aware load shedding (ISSUE 7
tentpole part 2).

Placement: each shape bucket has a *home slot* —
``bucket.bit_length() % slots`` — so consecutive power-of-two buckets
home on different replicas and a heterogeneous bucket mix spreads
across the pool (the MPMD placement idea of arXiv:2412.14374: assign
heterogeneous stage traffic to workers, don't round-robin blindly).
A request tries its bucket's home replica first, then the others in
slot order, skipping:

  * a replica that is not READY (dead/draining — the supervisor is on
    it), counted as ``shed{reason="dead"}``;
  * a replica whose per-bucket circuit breaker is open (it receives NO
    traffic for that bucket until its cooldown admits a half-open
    probe), counted as ``shed{reason="breaker"}``;
  * a replica whose bounded queue is full (typed
    ``ServiceOverloadedError`` from admission), counted as
    ``shed{reason="overload"}``.

Nothing acceptable anywhere = typed backpressure to the caller —
:class:`~..serve.batcher.ServiceOverloadedError` when saturation/death
was the blocker, :class:`~..resilience.policy.CircuitOpenError` when
every live replica's breaker for the bucket is open.  NEVER a silent
drop (the PR 3/5 contract, now fleet-wide).

Re-queue on replica death: the router resolves its own *outer* future
per request from the replica's *inner* future.  When the inner future
fails with a death-class error (:class:`~.replica.ReplicaKilledError`,
or ``ServiceClosedError`` from a worker torn down mid-flight), the
request is re-dispatched to a healthy replica — bounded by the PR 5
retry budget (``policy.retry.max_retries``), honoring the request's
ABSOLUTE deadline (the remaining-time window shrinks with each hop;
``DeadlineExceededError`` stays typed), and counted in
``tpu_jordan_fleet_reroutes_total``.  Exhausted budget = the typed
death error to the caller.  Every other failure (deadline, corruption,
terminal batch error, per-element singularity) propagates typed,
untouched — a reroute must never retry a REAL answer away.
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from ..obs import metrics as _obs_metrics
from ..resilience.policy import CircuitOpenError
from ..serve.batcher import ServiceClosedError, ServiceOverloadedError
from ..serve.executors import bucket_for
from .replica import ReplicaKilledError

_M_REROUTES = _obs_metrics.counter(
    "tpu_jordan_fleet_reroutes_total",
    "in-flight requests re-queued to another replica after a replica "
    "death (the supervisor/retry re-queue path), labeled by the dead "
    "replica's slot")
_M_SHED = _obs_metrics.counter(
    "tpu_jordan_fleet_shed_total",
    "routing decisions that skipped a replica or shed a request, "
    "labeled by reason (breaker|overload|dead|pre_shed)")


@dataclass
class _FleetRequest:
    """One routed request: the raw matrix (re-padded by whichever
    replica serves it), the caller's ABSOLUTE deadline, the reroute
    budget spent so far, the outer future the caller holds, and the
    fleet-level journey context (ISSUE 8 — ONE journey per request,
    however many replicas it visits).

    ``kind="update"`` (ISSUE 12) routes a resident-inverse update
    instead: ``handle``/``u``/``v`` replace ``a``; the re-queue path is
    identical — the handle's committed state lives in the fleet-shared
    store, so a retried update re-reads it (exactly-once application
    across any number of reroute hops).

    ``kind="solve"`` (ISSUE 17) routes X = A⁻¹B through the replicas'
    solve lanes: ``b`` carries the RHS block, ``rhs`` its lane's
    k-bucket — the LP/QP driver's per-iteration verification solves
    ride this, so sustained correlated invert + update + solve traffic
    shares one front door."""

    a: np.ndarray
    n: int
    bucket: int
    outer: Future
    t_deadline: float | None = None      # absolute monotonic deadline
    attempts: int = 0
    t_submit: float = field(default=0.0)
    ctx: object = None                   # obs.journey.RequestContext
    kind: str = "invert"                 # "invert" | "update" | "solve"
    handle: object = None                # HandleRef (update kind)
    u: np.ndarray = None                 # (n, k) update factors
    v: np.ndarray = None
    b: np.ndarray = None                 # (n, k) RHS block (solve kind)
    rhs: int = 0                         # solve lane k-bucket
    #: ISSUE 20 — checkpoint spec for ``kind="ckpt_solve"``: a dict
    #: with ``store`` (:class:`~..resilience.checkpoint.CheckpointStore`),
    #: ``run_id``, ``cadence``, and optional ``engine``/``mesh``/
    #: ``block_size``.  A death/preemption re-queue hop probes the
    #: store: a live token means the next replica RESUMES from the
    #: last durable superstep (``ckpt_resume`` journey hop) instead of
    #: recomputing — lost work bounded by the cadence.
    ckpt: object = None

    def remaining_ms(self, now: float) -> float | None:
        if self.t_deadline is None:
            return None
        return (self.t_deadline - now) * 1e3

    @property
    def breaker_key(self):
        """The per-replica breaker this request's lane trips: invert
        lanes keep the historical bare bucket int; update lanes use
        their serve lane label, so the router sheds exactly what the
        replica's admission would fast-fail."""
        if self.kind == "update":
            from ..serve.executors import k_bucket_for

            return f"update:{self.bucket}:k{k_bucket_for(self.u.shape[1])}"
        if self.kind == "solve":
            return f"solve:{self.bucket}:k{self.rhs}"
        if self.kind == "ckpt_solve":
            # Checkpointed solves bypass the batched lanes (no lane
            # breaker exists for them); the distinct key means an
            # unknown breaker, which always allows.
            return f"ckpt:{self.bucket}"
        return self.bucket

    @property
    def rid(self) -> str | None:
        return None if self.ctx is None else self.ctx.request_id

    def hop(self, event: str, **attrs) -> None:
        if self.ctx is not None:
            self.ctx.event(event, **attrs)


class Router:
    """The fleet's front door.  Holds no replica state of its own —
    it reads the pool's slot table on every dispatch, so a supervisor
    replacement is picked up on the very next request."""

    def __init__(self, pool, max_reroutes: int = 2):
        self.pool = pool
        self.max_reroutes = max(1, int(max_reroutes))
        #: Pre-shed flag (ISSUE 18): set by the
        #: :class:`~.autoscaler.FleetAutoscaler` when the SLO burn/p99
        #: evidence says the fleet is approaching its objective — NEW
        #: submissions are shed typed at the front door (counted
        #: ``shed{reason="pre_shed"}``, journey-hopped) while in-flight
        #: work and death re-queues finish untouched.
        self.pre_shed = False

    def _check_pre_shed(self, req: "_FleetRequest") -> None:
        """Typed pre-shed at the front door: a shed request is an
        ANSWER (``ServiceOverloadedError`` — retry after backoff), with
        the shed counted and the journey explaining why; never a
        silent drop.  Applied to NEW submissions only — re-queue hops
        dispatch directly, so pre-shed can't drop accepted work."""
        if not self.pre_shed:
            return
        _M_SHED.inc(reason="pre_shed", exemplar=req.rid)
        req.hop("shed", reason="pre_shed")
        req.hop("reject", reason="pre_shed")
        raise ServiceOverloadedError(
            f"pre-shedding bucket {req.bucket}: the autoscaler flagged "
            f"the fleet as approaching its SLO objective (sustained "
            f"burn / p99 risk) — retry after backoff (typed "
            f"backpressure, nothing dropped)")

    # ---- caller side -------------------------------------------------

    def submit(self, a, dtype, deadline_ms: float | None = None,
               _ctx=None) -> Future:
        """``_ctx`` (internal, ISSUE 13): an existing fleet journey
        context to thread through — ``JordanFleet.invert(resident=)``
        mints it BEFORE budget admission so a ``capacity_evict`` hop
        lands on the admitting request's own journey; None (every
        other caller) mints here as before."""
        a = np.asarray(a, dtype)
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"expected a square (n, n) matrix, "
                             f"got shape {a.shape}")
        n = a.shape[0]
        now = time.monotonic()
        outer = Future()
        # Claim immediately (the stdlib executor protocol): the outer
        # future may be resolved from another thread's callback at any
        # point after dispatch; a caller cancel() racing that would be
        # an InvalidStateError crash inside a dispatcher.
        outer.set_running_or_notify_cancel()
        bucket = bucket_for(n)
        req = _FleetRequest(
            a=a, n=n, bucket=bucket, outer=outer,
            t_deadline=(None if deadline_ms is None
                        else now + float(deadline_ms) / 1e3),
            t_submit=now,
            ctx=(_ctx if _ctx is not None
                 else self.pool.journey.new(n, bucket)))
        self.pool._record_bucket(req.bucket)
        self.pool._account_submitted()
        try:
            self._check_pre_shed(req)
            self._dispatch(req)
        except Exception as e:
            self.pool._account_resolved(ok=False)
            req.ctx.close("error", error=type(e).__name__)
            raise
        return outer

    def submit_update(self, handle, u, v, dtype,
                      deadline_ms: float | None = None) -> Future:
        """Route one rank-k resident-inverse update (ISSUE 12): the
        same front door as ``submit`` — one fleet-level journey
        (``workload="update"``), bucket-affinity candidate order off
        the HANDLE's bucket, typed backpressure, death re-queue."""
        from ..linalg.update import as_update_factors

        n = int(handle.n)
        u, v, _ = as_update_factors(u, v, n, dtype)
        now = time.monotonic()
        outer = Future()
        outer.set_running_or_notify_cancel()
        req = _FleetRequest(
            a=None, n=n, bucket=int(handle.bucket_n), outer=outer,
            t_deadline=(None if deadline_ms is None
                        else now + float(deadline_ms) / 1e3),
            t_submit=now,
            ctx=self.pool.journey.new(n, int(handle.bucket_n),
                                      workload="update"),
            kind="update", handle=handle, u=u, v=v)
        self.pool._account_submitted()
        try:
            self._check_pre_shed(req)
            self._dispatch(req)
        except Exception as e:
            self.pool._account_resolved(ok=False)
            req.ctx.close("error", error=type(e).__name__)
            raise
        return outer

    def submit_solve(self, a, b, dtype,
                     deadline_ms: float | None = None,
                     ckpt=None) -> Future:
        """Route one solve request X = A⁻¹B (ISSUE 17): the same front
        door as ``submit`` — one fleet-level journey
        (``workload="solve"``), bucket-affinity candidate order, typed
        backpressure, death re-queue.  The replicas' solve lanes never
        form an inverse (the ISSUE 11 contract).

        ``ckpt`` (ISSUE 20) switches the request to the CHECKPOINTED
        superstep path: the serving replica runs the sweep with
        cadence-boundary checkpoints into ``ckpt["store"]``, and a
        replica death (or seeded preemption) mid-sweep re-queues here
        with a RESUME — the next replica re-enters at the last durable
        superstep (``ckpt_resume`` journey hop), never recomputing from
        scratch."""
        from ..serve.executors import rhs_bucket_for

        a = np.asarray(a, dtype)
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"expected a square (n, n) matrix, "
                             f"got shape {a.shape}")
        b = np.asarray(b, dtype)
        if b.ndim == 1:
            b = b[:, None]
        if b.ndim != 2 or b.shape[0] != a.shape[0]:
            raise ValueError(f"expected a ({a.shape[0]}, k) RHS block, "
                             f"got shape {b.shape}")
        n = a.shape[0]
        now = time.monotonic()
        outer = Future()
        outer.set_running_or_notify_cancel()
        bucket = bucket_for(n)
        req = _FleetRequest(
            a=a, n=n, bucket=bucket, outer=outer,
            t_deadline=(None if deadline_ms is None
                        else now + float(deadline_ms) / 1e3),
            t_submit=now,
            ctx=self.pool.journey.new(n, bucket, workload="solve"),
            kind=("ckpt_solve" if ckpt is not None else "solve"),
            b=b, rhs=rhs_bucket_for(b.shape[1]), ckpt=ckpt)
        self.pool._record_bucket(req.bucket)
        self.pool._account_submitted()
        try:
            self._check_pre_shed(req)
            self._dispatch(req)
        except Exception as e:
            self.pool._account_resolved(ok=False)
            req.ctx.close("error", error=type(e).__name__)
            raise
        return outer

    # ---- dispatch / re-queue ----------------------------------------

    def _candidates(self, bucket: int):
        """Replicas in affinity order: the bucket's home slot first,
        then the rest in slot order.  Reads the live slot table — a
        replacement replica is visible immediately."""
        replicas = self.pool.live_replicas()
        if not replicas:
            return []
        nslots = self.pool.slots
        home = bucket.bit_length() % nslots
        return sorted(replicas,
                      key=lambda r: (r.slot - home) % nslots)

    def _dispatch(self, req: _FleetRequest) -> None:
        """Try every candidate once; on acceptance, chain the inner
        future to the outer.  Raises typed backpressure when nobody
        accepts (the caller's thread on first submit; resolved onto the
        outer future on a re-queue hop).

        Total-loss grace: finding ZERO live replicas (every slot dead
        mid rolling-restart — distinct from saturation, which stays
        immediate typed backpressure) waits once, bounded by
        ``pool.restart_grace_s`` and the request's own deadline, for
        the supervisor's warm replacement, then rescans."""
        shed_breaker = shed_overload = shed_dead = 0
        waited = False
        while True:
            candidates = self._candidates(req.bucket)
            down = self.pool.slots - len(candidates)
            if down:
                # Routine routing-around: a dead/draining replica (or
                # an unfilled slot mid rolling-restart) sheds this
                # request's traffic — the docs/FLEET.md "dead" row, not
                # just the died-between-scan-and-submit race below.
                _M_SHED.inc(down, reason="dead", exemplar=req.rid)
                shed_dead += down
                req.hop("shed", reason="dead", slots_down=down)
            for replica in candidates:
                if not replica.breaker_allows(req.breaker_key):
                    _M_SHED.inc(reason="breaker", exemplar=req.rid)
                    shed_breaker += 1
                    req.hop("shed", reason="breaker",
                            replica=replica.name)
                    continue
                # The route decision journeys BEFORE the replica sees
                # the request — WHICH replica, on WHICH attempt (0 =
                # first dispatch, >0 = a post-death re-queue hop) — so
                # a failed hand-off reads causally: route -> shed ->
                # route elsewhere.
                req.hop("route", replica=replica.name,
                        slot=replica.slot, attempt=req.attempts)
                try:
                    if req.kind == "update":
                        inner = replica.submit_update(
                            req.handle, req.u, req.v,
                            deadline_ms=req.remaining_ms(
                                time.monotonic()),
                            ctx=req.ctx)
                    elif req.kind == "solve":
                        inner = replica.submit_solve(
                            req.a, req.b,
                            deadline_ms=req.remaining_ms(
                                time.monotonic()),
                            ctx=req.ctx)
                    elif req.kind == "ckpt_solve":
                        # Resume probe (ISSUE 20): a live token in the
                        # store means an earlier hop wrote a durable
                        # checkpoint before dying — this replica
                        # RESUMES it.  The hop is recorded before the
                        # replica sees the request, so the journey
                        # reads route -> ckpt_resume -> (segments).
                        resume = None
                        if req.ckpt["store"].has_live(
                                req.ckpt["run_id"]):
                            resume = req.ckpt["run_id"]
                            req.hop("ckpt_resume",
                                    replica=replica.name,
                                    run_id=resume,
                                    attempt=req.attempts)
                        inner = replica.submit_solve_ckpt(
                            req.a, req.b, req.ckpt,
                            resume_from=resume, ctx=req.ctx)
                    else:
                        inner = replica.submit(
                            req.a,
                            deadline_ms=req.remaining_ms(
                                time.monotonic()),
                            ctx=req.ctx)
                except (ReplicaKilledError, ServiceClosedError):
                    # Died between the candidate scan and the submit
                    # (or THIS submit triggered the seeded kill): not
                    # this request's problem — next candidate.
                    _M_SHED.inc(reason="dead", exemplar=req.rid)
                    shed_dead += 1
                    req.hop("shed", reason="dead", replica=replica.name)
                    self.pool._kick_supervisor()
                    continue
                except ServiceOverloadedError:
                    _M_SHED.inc(reason="overload", exemplar=req.rid)
                    shed_overload += 1
                    req.hop("shed", reason="overload",
                            replica=replica.name)
                    continue
                except CircuitOpenError:
                    # Breaker flipped between breaker_allows and
                    # admission.
                    _M_SHED.inc(reason="breaker", exemplar=req.rid)
                    shed_breaker += 1
                    req.hop("shed", reason="breaker",
                            replica=replica.name)
                    continue
                inner.add_done_callback(
                    lambda f, req=req, replica=replica:
                        self._on_inner_done(req, replica, f))
                return
            if (not waited and not self.pool.closing
                    and not self.pool.live_replicas()
                    # Never grace-wait ON the supervising thread: a
                    # kill's doomed-future callbacks re-dispatch here
                    # synchronously, and blocking would starve the one
                    # thread that can install the replacement.
                    and not self.pool.supervisor.is_supervising_thread()):
                waited = True
                grace = self.pool.restart_grace_s
                rem = req.remaining_ms(time.monotonic())
                if rem is not None:
                    grace = min(grace, max(0.0, rem / 1e3))
                self.pool._kick_supervisor()
                if self.pool.wait_for_live_replica(grace):
                    continue
            break
        # Nobody accepted: typed backpressure, never a drop.  The
        # reject hop explains WHY before the journey closes (the
        # submit/-requeue-failure paths close with the error type).
        if shed_overload:
            req.hop("reject", reason="saturated")
            raise ServiceOverloadedError(
                f"fleet saturated for bucket {req.bucket}: every live "
                f"replica's queue is full — retry later (typed "
                f"backpressure, nothing dropped)")
        if shed_breaker:
            req.hop("reject", reason="breaker")
            raise CircuitOpenError(
                f"every live replica's circuit for bucket {req.bucket} "
                f"is open — retry after the cooldown")
        req.hop("reject", reason="no_live_replica")
        raise ServiceOverloadedError(
            "no live replica (fleet restarting or closed) — retry "
            "later (typed backpressure, nothing dropped)")

    def _on_inner_done(self, req: _FleetRequest, replica, inner) -> None:
        """Resolve the outer future, or re-queue after a replica death.
        Runs on whichever thread resolved the inner future (a replica
        dispatcher, or a killer failing queued work) — by the batcher's
        close contract, never under a queue lock."""
        exc = inner.exception()
        if exc is None:
            self.pool._account_resolved(ok=True)
            res = inner.result()
            if req.ctx is not None:
                req.ctx.close("ok", singular=bool(
                    getattr(res, "singular", False)))
            req.outer.set_result(res)
            return
        if req.kind == "ckpt_solve":
            # A seeded preemption mid checkpointed sweep is re-queue
            # class too (ISSUE 20): the chip went away but the replica
            # did not — the re-dispatch finds the live token and
            # resumes from the last durable superstep.
            from ..resilience.checkpoint import PreemptedError

            death = (ReplicaKilledError, ServiceClosedError,
                     PreemptedError)
        else:
            death = (ReplicaKilledError, ServiceClosedError)
        if (isinstance(exc, death)
                and not self.pool.closing
                and req.attempts < self.max_reroutes):
            req.attempts += 1
            _M_REROUTES.inc(replica=str(replica.slot), exemplar=req.rid)
            req.hop("requeue", from_replica=replica.name,
                    attempt=req.attempts, error=type(exc).__name__)
            self.pool._kick_supervisor()
            try:
                self._dispatch(req)
            except Exception as e:           # noqa: BLE001 — typed out
                self.pool._account_resolved(ok=False)
                if req.ctx is not None:
                    req.ctx.close("error", error=type(e).__name__)
                req.outer.set_exception(e)
            return
        if isinstance(exc, death):
            # A death-class failure the router did NOT re-queue: the
            # journey must still explain why (the checker's no-causal-
            # gap rule) — budget spent, or the fleet is closing.
            req.hop("reject",
                    reason=("closing" if self.pool.closing
                            else "reroute_budget_exhausted"),
                    attempt=req.attempts)
        self.pool._account_resolved(ok=False)
        if req.ctx is not None:
            req.ctx.close("error", error=type(exc).__name__)
        req.outer.set_exception(exc)
