"""The replica supervisor (ISSUE 7 tentpole part 3).

One background thread health-checks the pool and keeps it at strength:

  * **Death detection** — a replica that crashed (the seeded
    ``replica_kill``, or a real exception path calling ``kill()``)
    kicks the supervisor immediately via the pool's death callback; no
    polling latency on the common path.
  * **Wedge detection** — a replica whose heartbeat stamp is stale past
    ``liveness_deadline_s`` is declared wedged and killed (its queued
    work fails typed and is re-queued by the router), then replaced.
  * **Warm rolling restart** — the replacement replica is built against
    the fleet-shared :class:`~..serve.executors.ExecutorStore` and the
    shared read-only pre-tuned plan cache, then ``warmup()`` is run on
    every bucket the fleet has ever served BEFORE the replica enters
    the slot table — so the router never routes to a cold worker and
    the replacement performs ZERO compiles and ZERO measurements
    (``tpu_jordan_compiles_total`` delta == 0, the acceptance pin).
  * **Restart breaker** (the supervisor-level breaker wiring) — each
    slot carries a :class:`~..resilience.policy.CircuitBreaker`: a slot
    whose replicas keep dying without ever reaching ``stable_after_s``
    of uptime stops being restarted (open breaker = the fleet runs
    degraded rather than burning CPU on a crash loop), until the
    cooldown admits a half-open restart probe.  A replacement that
    survives ``stable_after_s`` records the success that closes it.
"""

from __future__ import annotations

import threading

from ..obs import capacity as _capacity
from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from .replica import DEAD, READY

_M_RESTARTS = _obs_metrics.counter(
    "tpu_jordan_fleet_restarts_total",
    "warm rolling restarts performed by the supervisor (replacement "
    "replica entered the slot), labeled by slot")
_M_RESTART_FAILURES = _obs_metrics.counter(
    "tpu_jordan_fleet_restart_failures_total",
    "replacement replicas that failed to build/warm up (counted "
    "against the slot's restart breaker)")


class Supervisor:
    """The pool's health-check/restart loop.  ``check()`` is the whole
    policy and is callable inline (tests drive it deterministically
    with ``autostart_supervisor=False``); the thread just runs it every
    ``check_interval_s`` or immediately when kicked."""

    def __init__(self, pool, check_interval_s: float = 0.05,
                 liveness_deadline_s: float = 1.0,
                 stable_after_s: float = 2.0):
        self.pool = pool
        self.check_interval_s = float(check_interval_s)
        self.liveness_deadline_s = float(liveness_deadline_s)
        self.stable_after_s = float(stable_after_s)
        self._kick = threading.Event()
        self._stop = False
        self._thread: threading.Thread | None = None
        # Serializes start/stop: racing closers (both fleet.close
        # branches call stop()) must each return only after the loop
        # thread is joined, not crash on a _thread turned None.
        self._lifecycle = threading.Lock()
        # The thread currently inside check() (loop thread or an
        # inline test drive): the router must never grace-wait for a
        # replacement on the one thread that could install it.
        self._supervising: threading.Thread | None = None
        # Slots whose withheld-restart was already black-box-recorded
        # this episode: the poll loop re-visits an open breaker every
        # check_interval_s, and re-recording each pass would flood the
        # bounded ring with "still degraded" (cleared on restart).
        self._withheld_recorded: set[int] = set()

    # ---- lifecycle ---------------------------------------------------

    def start(self) -> None:
        with self._lifecycle:
            if self._thread is None and not self._stop:
                self._thread = threading.Thread(
                    target=self._loop, name="tpu-jordan-fleet-supervisor",
                    daemon=True)
                self._thread.start()

    def kick(self) -> None:
        """Wake the loop now (a death just happened — don't wait out
        the poll interval)."""
        self._kick.set()

    def stop(self) -> None:
        self._stop = True
        self._kick.set()
        with self._lifecycle:
            if self._thread is not None:
                self._thread.join()
                self._thread = None

    def _loop(self) -> None:
        while True:
            self._kick.wait(self.check_interval_s)
            self._kick.clear()
            if self._stop:
                return
            self.check()

    # ---- the health-check policy ------------------------------------

    def is_supervising_thread(self) -> bool:
        """True when the calling thread is inside ``check()`` — the
        router's total-loss grace must not block this thread (it is
        the only one that can install the replacement it would be
        waiting for)."""
        return threading.current_thread() is self._supervising

    def check(self) -> None:
        """One supervision pass over every slot: wedge detection, slot
        refill (breaker permitting), stability credit."""
        pool = self.pool
        if pool.closing:
            return
        self._supervising = threading.current_thread()
        try:
            self._check()
        finally:
            self._supervising = None

    def _check(self) -> None:
        pool = self.pool
        now = pool.clock()
        for slot in pool.slot_table():
            if slot.parked:
                # Autoscaler-drained capacity (ISSUE 18): an empty
                # parked slot is DESIGNED reduction, not a death —
                # refilling it would fight the control loop.
                continue
            replica = slot.replica
            if replica is not None and replica.state == READY:
                # Wedge: READY but the heartbeat went stale.
                if now - replica.last_beat > self.liveness_deadline_s:
                    _recorder.record(
                        "heartbeat_stale", replica=replica.name,
                        slot=slot.index,
                        stale_s=round(now - replica.last_beat, 6),
                        deadline_s=self.liveness_deadline_s)
                    self._replace_wedged(slot, replica)
                elif (not slot.credited
                      and now - slot.installed_at >= self.stable_after_s):
                    # Survived the stability window: the success that
                    # closes the slot's restart breaker.
                    slot.breaker.record_success()
                    slot.credited = True
            replica = slot.replica
            if replica is None or replica.state == DEAD:
                self._try_restart(slot)
        pool._export_ready_gauge()

    def _replace_wedged(self, slot, victim) -> None:
        """Kill a wedged replica — staging its warm replacement FIRST
        (breaker permitting).  ``kill()`` fails the victim's queued
        futures synchronously on THIS thread and their done-callbacks
        re-dispatch through the router, so the replacement must already
        be in the slot table when they run: otherwise a momentarily
        empty pool would grace-wait on the one thread able to install
        it (a self-deadlock).  When the breaker withholds the
        replacement, kill anyway — running degraded is the designed
        crash-loop answer."""
        pool = self.pool
        replacement = None
        if slot.breaker.allow():
            try:
                replacement = pool._spawn_replica(slot.index)
                replacement.warmup(pool.warm_shapes(),
                                   update_shapes=pool.warm_update_shapes(),
                                   solve_shapes=pool.warm_solve_shapes())
            except Exception as e:      # noqa: BLE001 — counted, retried
                _M_RESTART_FAILURES.inc(replica=str(slot.index))
                _recorder.record("restart_failure", slot=slot.index,
                                 error=type(e).__name__)
                slot.breaker.record_failure()
                if replacement is not None:
                    replacement.close(drain=False)
                replacement = None
        else:
            # Same per-episode dedup as _try_restart: the poll loop
            # will revisit this DEAD slot every pass while the breaker
            # stays open, and must not record a second withholding for
            # the same episode.
            if slot.index not in self._withheld_recorded:
                self._withheld_recorded.add(slot.index)
                _recorder.record("restart_withheld", slot=slot.index,
                                 breaker=slot.breaker.state)
        if replacement is not None:
            pool._install(slot, replacement)
            _M_RESTARTS.inc(replica=str(slot.index))
            self._withheld_recorded.discard(slot.index)
            # Capacity context (ISSUE 13): the compiled-lane residency
            # the replacement warmed against — on the shared store a
            # warm restart adds ZERO new lane bytes, and this field is
            # how a post-mortem sees that (or sees the growth a
            # private-store restart paid).
            _recorder.record(
                "restart", slot=slot.index, replica=replacement.name,
                cause="wedged",
                executor_lane_bytes=_capacity.live_bytes(
                    "executor_lanes"))
        victim.kill(reason="wedged")

    def _try_restart(self, slot) -> None:
        """Refill one slot with a warm replacement, breaker permitting.
        The replacement warms EVERY bucket the fleet has served before
        entering the slot table (zero compiles — shared store)."""
        pool = self.pool
        if not slot.breaker.allow():
            # Crash loop: stay degraded.  Recorded ONCE per episode so
            # the black box can prove the unfilled slot is DESIGNED
            # degradation, not an abandoned death (check_fleet's
            # stranded accounting) — without flooding the ring on
            # every poll pass.
            if slot.index not in self._withheld_recorded:
                self._withheld_recorded.add(slot.index)
                _recorder.record("restart_withheld", slot=slot.index,
                                 breaker=slot.breaker.state)
            return
        replica = None
        try:
            replica = pool._spawn_replica(slot.index)
            replica.warmup(pool.warm_shapes(),
                           update_shapes=pool.warm_update_shapes(),
                           solve_shapes=pool.warm_solve_shapes())
        except Exception as e:          # noqa: BLE001 — counted, retried
            _M_RESTART_FAILURES.inc(replica=str(slot.index))
            _recorder.record("restart_failure", slot=slot.index,
                             error=type(e).__name__)
            slot.breaker.record_failure()
            if replica is not None:
                replica.close(drain=False)   # reap the half-built worker
            return
        pool._install(slot, replica)
        _M_RESTARTS.inc(replica=str(slot.index))
        self._withheld_recorded.discard(slot.index)
        _recorder.record("restart", slot=slot.index,
                         replica=replica.name, cause="death",
                         executor_lane_bytes=_capacity.live_bytes(
                             "executor_lanes"))
