""":class:`JordanFleet` — the supervised replica pool (ISSUE 7
tentpole).

One ``JordanService`` is a throughput ceiling and a single point of
failure (ROADMAP open item 2).  The fleet runs N of them as supervised
worker replicas behind a bucket-affinity router:

  * **shared, immutable**: the compiled bucket executables
    (:class:`~..serve.executors.ExecutorStore` — one compile per key
    across the whole pool) and the read-only pre-tuned plan cache
    (``tuning/plan_cache.py`` — N readers, zero writes, zero lock
    contention);
  * **per replica, stateful**: the dispatcher thread, the bounded
    queue, the per-bucket circuit breakers, the serving stats (mirrored
    into the process registry with a ``replica`` label);
  * **supervision**: heartbeat + liveness deadline, warm rolling
    restarts (a replacement performs zero compiles and zero
    measurements), a per-slot restart breaker against crash loops, and
    router-side re-queue of a dead replica's queued requests through
    the PR 5 retry/deadline budget.

Typed failure surface, fleet-wide: ``ServiceOverloadedError`` when
every live replica's queue is full (backpressure, never a drop),
``CircuitOpenError`` when every live replica's breaker for a bucket is
open, ``DeadlineExceededError``/``ReplicaKilledError`` per request when
budgets exhaust.  The chaos acceptance (``fleet/demo.py`` +
``tools/check_fleet.py``) pins: every response under a seeded
``replica_kill`` bit-matches a fault-free replay or carries a typed
error — zero silent errors.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import jax.numpy as jnp

from ..obs import capacity as _obs_capacity
from ..obs import metrics as _obs_metrics
from ..obs.journey import JourneyLog
from ..resilience.policy import DEFAULT_POLICY, CircuitBreaker
from ..serve.executors import ExecutorStore
from ..serve.handles import HandleStore
from ..serve.service import JordanService
from ..serve.stats import cross_replica_spread as _cross_replica_spread
from ..tuning.plan_cache import PlanCache
from .replica import READY, Replica
from .router import Router
from .supervisor import Supervisor

_M_READY = _obs_metrics.gauge(
    "tpu_jordan_fleet_replicas_ready",
    "replicas currently READY and receiving traffic")
_M_REQUESTS = _obs_metrics.counter(
    "tpu_jordan_fleet_requests_total",
    "requests accepted by the fleet router")


@dataclass
class _Slot:
    """One replica slot: the live replica (swapped by the supervisor),
    its generation counter, install timestamp, stability credit, and
    the restart breaker (supervisor-level breaker wiring)."""

    index: int
    breaker: CircuitBreaker
    replica: Replica | None = None
    generation: int = 0
    installed_at: float = 0.0
    credited: bool = False
    lineage: tuple = field(default=())
    #: Parked = deliberately emptied by the autoscaler's drain
    #: (ISSUE 18): the supervisor skips it (no restart — an empty
    #: parked slot is DESIGNED capacity reduction, not a death) until
    #: ``grow()`` un-parks it.
    parked: bool = False


class JordanFleet:
    """A pool of supervised :class:`JordanService` replicas behind a
    breaker-aware bucket-affinity router.

    Args mirror :class:`JordanService` where they configure each
    replica (engine, plan_cache, dtype, batch_cap, max_wait_ms,
    max_queue — PER REPLICA, block_size, policy, default_deadline_ms,
    telemetry).  Fleet-specific:

      replicas: slot count (>= 1).
      plan_cache_read_only: default True — the fleet contract is N
        replicas reading one shared pre-tuned cache; pass False only
        for a deliberately writable single-tenant setup.
      executor_store: a pre-warmed :class:`ExecutorStore` to share
        (e.g. across demo phases); None builds a fresh one.
      heartbeat_interval_s / liveness_deadline_s / check_interval_s /
        stable_after_s: the supervision clock (docs/FLEET.md).
      restart_failures / restart_cooldown_s: the per-slot restart
        breaker (a slot in a crash loop stops restarting until the
        cooldown's half-open probe).
      autostart: False leaves every replica's dispatcher unstarted
        (tests stage queues deterministically, then ``start()``).
      autostart_supervisor: False keeps supervision manual —
        ``supervisor.check()`` runs one pass inline.
    """

    def __init__(self, replicas: int = 3, engine: str = "auto",
                 plan_cache: str | None = None,
                 plan_cache_read_only: bool = True,
                 dtype=jnp.float32, batch_cap: int = 8,
                 max_wait_ms: float = 2.0, max_queue: int = 256,
                 block_size: int | None = None, policy="default",
                 default_deadline_ms: float | None = None,
                 telemetry=None,
                 executor_store: ExecutorStore | None = None,
                 handle_store: HandleStore | None = None,
                 handle_budget_bytes: int | None = None,
                 update_drift_budget_factor: float | None = None,
                 heartbeat_interval_s: float = 0.05,
                 liveness_deadline_s: float = 1.0,
                 check_interval_s: float = 0.05,
                 stable_after_s: float = 2.0,
                 restart_failures: int = 3,
                 restart_cooldown_s: float = 5.0,
                 restart_grace_s: float = 2.0,
                 autostart: bool = True,
                 autostart_supervisor: bool = True, clock=None):
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self.slots = int(replicas)
        self.clock = clock if clock is not None else time.monotonic
        self.store = (executor_store if executor_store is not None
                      else ExecutorStore())
        # Resident-handle store (ISSUE 12): like the executor store,
        # ONE instance shared by every replica — and every warm
        # replacement — so a replica_kill never loses resident state
        # and updates write through fleet-wide (docs/FLEET.md).
        # ``handle_budget_bytes`` (ISSUE 13) attaches ONE fleet-wide
        # resident-bytes budget to it — admission is a pool property,
        # not a replica's (the store is the one shared-mutable thing);
        # the shared-store-vs-budget wiring rule lives in
        # ``serve.handles.build_handle_store``.
        from ..serve.handles import build_handle_store

        self.handles = build_handle_store(handle_store,
                                          handle_budget_bytes,
                                          "the fleet")
        self._handle_seq = 0
        self.policy = DEFAULT_POLICY if policy == "default" else policy
        if plan_cache is not None and plan_cache_read_only:
            # Load the shared pre-tuned file ONCE: every replica — and
            # every warm replacement the supervisor ever spawns —
            # shares this frozen instance.  No per-spawn re-parse, and
            # no divergence window if the file is re-pretuned
            # mid-flight (the bit-exact replay contract assumes all
            # pool-mates serve identical plans).
            plan_cache = PlanCache.load(plan_cache, read_only=True)
        self._svc_kw = dict(
            engine=engine, plan_cache=plan_cache,
            plan_cache_read_only=plan_cache_read_only, dtype=dtype,
            batch_cap=batch_cap, max_wait_ms=max_wait_ms,
            max_queue=max_queue, block_size=block_size,
            telemetry=telemetry, policy=self.policy,
            default_deadline_ms=default_deadline_ms,
            shared_executors=self.store, shared_handles=self.handles,
            update_drift_budget_factor=update_drift_budget_factor)
        self._hb_interval = float(heartbeat_interval_s)
        self.restart_grace_s = float(restart_grace_s)
        # A Condition, not a bare Lock: router threads that find ZERO
        # live replicas (a total-loss instant mid rolling-restart) wait
        # on it for the supervisor's replacement instead of typed-
        # failing work a warm worker could serve milliseconds later.
        self._lock = threading.Condition()
        #: update-lane (n, k) pairs the fleet has warmed — replacement
        #: replicas re-warm these too (a store lookup: zero compiles).
        self._warm_updates: set[tuple[int, int]] = set()
        self._warm_solves: set[tuple[int, int]] = set()
        # Close teardown serializes here (the Condition above must stay
        # free for grace-waiting routers): a racing second close()
        # blocks until the first has drained every replica, exactly
        # like JordanService._close_lock.
        self._close_lock = threading.Lock()
        self._close_complete = False
        self._warm_shapes: set[int] = set()
        self._submitted = 0
        self._resolved_ok = 0
        self._resolved_error = 0
        self.closing = False
        self._restart_failures = int(restart_failures)
        self._restart_cooldown_s = float(restart_cooldown_s)
        self._slots = [
            _Slot(index=i, breaker=self._slot_breaker(i))
            for i in range(self.slots)
        ]
        # Fleet-level journey log (ISSUE 8): the router mints ONE
        # context per request at the fleet front door and threads it
        # through every replica the request visits — a replica's own
        # service never mints a second id for fleet traffic.
        self.journey = JourneyLog(prefix="fleet")
        self._autostart = bool(autostart)
        #: once True, every replica installed from then on has its
        #: dispatcher started at install time — a warm replacement
        #: entering a RUNNING fleet must never sit with a dead
        #: dispatcher (requests routed to it would hang).  Staged runs
        #: (autostart=False) flip it in ``start()``.
        self._started = self._autostart
        for slot in self._slots:
            self._install(slot, self._spawn_replica(slot.index))
        self.router = Router(
            self,
            max_reroutes=(self.policy.retry.max_retries
                          if self.policy is not None else 1))
        self.supervisor = Supervisor(
            self, check_interval_s=check_interval_s,
            liveness_deadline_s=liveness_deadline_s,
            stable_after_s=stable_after_s)
        if autostart_supervisor:
            self.supervisor.start()

    # ---- replica lifecycle plumbing ---------------------------------

    def _slot_breaker(self, index: int) -> CircuitBreaker:
        return CircuitBreaker(
            failures=self._restart_failures,
            cooldown_s=self._restart_cooldown_s,
            clock=self.clock, name=f"fleet_slot_{index}")

    def _spawn_replica(self, slot_index: int) -> Replica:
        with self._lock:
            self._slots[slot_index].generation += 1
            gen = self._slots[slot_index].generation
        service = JordanService(
            autostart=self._autostart,
            metric_labels={"replica": str(slot_index)}, **self._svc_kw)
        return Replica(slot_index, gen, service,
                       heartbeat_interval_s=self._hb_interval,
                       clock=self.clock, on_death=self._on_death)

    def _install(self, slot: _Slot, replica: Replica) -> None:
        with self._lock:
            slot.replica = replica
            slot.installed_at = self.clock()
            slot.credited = False
            slot.lineage = slot.lineage + (replica.name,)
            started = self._started
            self._lock.notify_all()     # wake routers awaiting a replica
        if started:
            # Covers the replacement-into-a-running-staged-fleet case
            # (spawned with autostart=False after start() was called):
            # service.start() is an idempotent no-op when already live.
            replica.service.start()
        self._export_ready_gauge()

    def _on_death(self, replica: Replica, reason: str) -> None:
        """Replica death callback (any thread): count it against the
        slot's restart breaker and wake the supervisor."""
        self._slots[replica.slot].breaker.record_failure()
        self._export_ready_gauge()
        self._kick_supervisor()

    def _kick_supervisor(self) -> None:
        self.supervisor.kick()

    def _export_ready_gauge(self) -> None:
        _M_READY.set(float(sum(
            1 for s in self._slots
            if s.replica is not None and s.replica.state == READY)))

    # ---- autoscaling (ISSUE 18) -------------------------------------

    def ready_count(self) -> int:
        """Replicas currently READY (the autoscaler's capacity view)."""
        return len(self.live_replicas())

    def grow(self) -> int | None:
        """Add one replica (autoscaler scale-up): un-park the
        lowest-index parked slot, or append a brand-new slot.  The
        replacement warms every lane the fleet has served BEFORE
        entering the slot table (shared store — zero compiles, the
        supervisor's rolling-restart discipline), so scaled-up capacity
        never serves cold.  Returns the slot index, or None while the
        fleet is closing."""
        with self._lock:
            if self.closing:
                return None
            parked = [s for s in self._slots if s.parked]
            if parked:
                slot = parked[0]
                slot.parked = False
            else:
                slot = _Slot(index=len(self._slots),
                             breaker=self._slot_breaker(len(self._slots)))
                self._slots.append(slot)
                self.slots += 1
        replica = self._spawn_replica(slot.index)
        replica.warmup(self.warm_shapes(),
                       update_shapes=self.warm_update_shapes(),
                       solve_shapes=self.warm_solve_shapes())
        self._install(slot, replica)
        return slot.index

    def drain_slot(self) -> int | None:
        """Remove one replica (autoscaler drain): the highest-index
        live slot drains its queue (every in-flight/queued request
        completes — a drain never drops work), then parks empty.  The
        supervisor skips parked slots; ``grow()`` un-parks them first.
        Refuses (returns None) rather than drain the last live
        replica — the FLOOR is the autoscaler's policy, but a
        zero-replica pool is never this method's outcome."""
        with self._lock:
            live = [s for s in self._slots
                    if not s.parked and s.replica is not None]
            if len(live) <= 1 or self.closing:
                return None
            slot = live[-1]
            slot.parked = True
            replica = slot.replica
        if replica is not None:
            replica.close(drain=True)
            with self._lock:
                slot.replica = None
                self._lock.notify_all()
        self._export_ready_gauge()
        return slot.index

    # ---- router plumbing --------------------------------------------

    def slot_table(self):
        with self._lock:
            return list(self._slots)

    def live_replicas(self):
        with self._lock:
            return [s.replica for s in self._slots
                    if s.replica is not None
                    and s.replica.state == READY]

    def wait_for_live_replica(self, timeout_s: float) -> bool:
        """Block (real time, bounded) until some slot holds a READY
        replica or the fleet is closing.  The router's total-loss
        grace: a rolling restart that momentarily empties the pool must
        absorb re-queued work, not type-fail it (docs/FLEET.md)."""
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        with self._lock:
            while not self.closing:
                if any(s.replica is not None
                       and s.replica.state == READY
                       for s in self._slots):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._lock.wait(remaining)
            return False

    def warm_shapes(self):
        with self._lock:
            return sorted(self._warm_shapes)

    def warm_update_shapes(self):
        with self._lock:
            return sorted(self._warm_updates)

    def warm_solve_shapes(self):
        with self._lock:
            return sorted(self._warm_solves)

    def _record_bucket(self, bucket: int) -> None:
        # Buckets only in _warm_shapes: warmup() normalizes raw request
        # sizes through bucket_for too, so the set never conflates the
        # two and replacement warmups resolve each bucket exactly once.
        with self._lock:
            self._warm_shapes.add(int(bucket))

    def _account_submitted(self) -> None:
        with self._lock:
            self._submitted += 1
        _M_REQUESTS.inc()

    def _account_resolved(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self._resolved_ok += 1
            else:
                self._resolved_error += 1

    # ---- request path (the JordanService surface, fleet-wide) -------

    def submit(self, a, deadline_ms: float | None = None):
        """Route one (n, n) matrix through the fleet; returns a future
        resolving to :class:`~..serve.batcher.InvertResult`.  Typed
        rejections: ``ServiceOverloadedError`` (fleet saturated),
        ``CircuitOpenError`` (every live replica's breaker open for the
        bucket)."""
        if deadline_ms is None:
            deadline_ms = self._svc_kw["default_deadline_ms"]
        return self.router.submit(a, self._svc_kw["dtype"],
                                  deadline_ms=deadline_ms)

    def invert(self, a, timeout: float | None = None,
               deadline_ms: float | None = None, resident: bool = False,
               handle_id: str | None = None):
        """Synchronous fleet invert.  ``resident=True`` (ISSUE 12)
        installs the result as a resident handle in the FLEET-SHARED
        handle store and returns the :class:`~..serve.handles.HandleRef`
        — any replica (including every future warm replacement) can
        serve ``update(ref, u, v)`` against it.  With a store budget
        (ISSUE 13) the new handle's bytes are admitted BEFORE the
        invert is routed: LRU unpinned handles evicted fleet-wide to
        make room — each eviction a ``capacity_evict`` hop on THIS
        request's fleet journey — or the typed
        ``CapacityExceededError`` at submit, never an OOM mid-launch
        on some replica."""
        if resident:
            import numpy as _np

            from ..serve.executors import bucket_for
            from ..serve.handles import resident_handle_bytes

            n = _np.asarray(a).shape[0]
            bucket = bucket_for(n)
            # The journey is minted BEFORE admission so every budget
            # eviction is attributable to the request that forced it
            # (the service-path discipline); the router threads it
            # through instead of minting a second id.
            ctx = self.journey.new(n, bucket)
            try:
                self.handles.ensure_capacity(
                    resident_handle_bytes(bucket,
                                          self._svc_kw["dtype"]),
                    hop=ctx.event, replacing=handle_id)
            except Exception as e:
                ctx.close("error", error=type(e).__name__)
                raise
            if deadline_ms is None:
                deadline_ms = self._svc_kw["default_deadline_ms"]
            res = self.router.submit(a, self._svc_kw["dtype"],
                                     deadline_ms=deadline_ms,
                                     _ctx=ctx).result(timeout)
        else:
            res = self.submit(a,
                              deadline_ms=deadline_ms).result(timeout)
        if res.singular:
            from ..driver import SingularMatrixError

            raise SingularMatrixError("singular matrix")
        if not resident:
            return res
        from ..serve.handles import create_resident_handle

        if handle_id is None:
            with self._lock:
                self._handle_seq += 1
                handle_id = f"fh{self._handle_seq}"
        import jax.numpy as jnp

        return create_resident_handle(
            self.handles, jnp.dtype(self._svc_kw["dtype"]), a, res,
            handle_id)

    def submit_update(self, handle, u, v,
                      deadline_ms: float | None = None):
        """Route one rank-k resident-inverse update through the fleet
        (ISSUE 12): the router picks a READY replica (bucket affinity,
        breaker-aware), the replica's update lane mutates the handle's
        committed state in the shared store, and a mid-flight replica
        death re-queues the request — the retry re-reads committed
        state, so an update is applied exactly once."""
        if deadline_ms is None:
            deadline_ms = self._svc_kw["default_deadline_ms"]
        return self.router.submit_update(handle, u, v,
                                         self._svc_kw["dtype"],
                                         deadline_ms=deadline_ms)

    def update(self, handle, u, v, timeout: float | None = None,
               deadline_ms: float | None = None):
        """Synchronous ``submit_update`` + wait; raises
        ``SingularMatrixError`` when the mutation destroyed rank
        (typed — the committed resident state is untouched)."""
        res = self.submit_update(handle, u, v,
                                 deadline_ms=deadline_ms).result(timeout)
        if res.singular:
            from ..driver import SingularMatrixError

            raise SingularMatrixError(
                "singular matrix (rank-k update destroyed rank; "
                "resident state unchanged)")
        return res

    def submit_solve(self, a, b, deadline_ms: float | None = None,
                     ckpt=None):
        """Route one solve request X = A⁻¹B through the fleet
        (ISSUE 17): same router front door as ``submit`` — bucket
        affinity, breaker shedding, death re-queue — resolving to an
        ``InvertResult`` with ``workload="solve"`` and ``solution`` =
        the (n, k) X (no inverse is ever formed).  This is the lane the
        LP/QP driver's per-iteration verification solves ride, so the
        fleet sees the full correlated invert + update + solve mix.

        ``ckpt`` (ISSUE 20): a checkpoint spec dict (``store``,
        ``run_id``, ``cadence``, optional ``engine``/``mesh``/
        ``block_size``) routing the request down the CHECKPOINTED
        superstep path — a replica killed mid-sweep loses at most one
        cadence window of supersteps; the re-queued hop resumes from
        the last durable checkpoint (``ckpt_resume`` journey hop) and
        bit-matches the uninterrupted run."""
        if deadline_ms is None:
            deadline_ms = self._svc_kw["default_deadline_ms"]
        return self.router.submit_solve(a, b, self._svc_kw["dtype"],
                                        deadline_ms=deadline_ms,
                                        ckpt=ckpt)

    def solve_system(self, a, b, timeout: float | None = None,
                     deadline_ms: float | None = None, ckpt=None):
        """Synchronous ``submit_solve`` + wait; raises
        ``SingularMatrixError`` on a singular A (typed — the solve
        lanes' per-element flag).  ``ckpt`` routes the checkpointed
        superstep path (see :meth:`submit_solve`)."""
        res = self.submit_solve(a, b, deadline_ms=deadline_ms,
                                ckpt=ckpt).result(timeout)
        if res.singular:
            from ..driver import SingularMatrixError

            raise SingularMatrixError("singular matrix")
        return res

    # ---- lifecycle ---------------------------------------------------

    def warmup(self, shapes, update_shapes=(), solve_shapes=()) -> dict:
        """Warm every replica against the shared store: the FIRST
        replica to reach each bucket compiles it (once, fleet-wide);
        every other replica — and every future replacement — finds it
        built.  Returns {bucket: engine} from the last replica.

        ``update_shapes`` (ISSUE 12): (n, k) pairs warming the
        resident-update lanes (and each n's invert lane — handle
        creation and the re_invert rung ride it); replacements re-warm
        these too.

        ``solve_shapes`` (ISSUE 17): (n, k) pairs warming the solve
        lanes the fleet's ``solve_system`` traffic lands in — the LP/QP
        driver's verification solves stay zero-compile warm like every
        other lane."""
        from ..serve.executors import (bucket_for, k_bucket_for,
                                       rhs_bucket_for)

        shapes = [int(s) for s in shapes]
        update_shapes = [(int(n), int(k)) for n, k in update_shapes]
        solve_shapes = [(int(n), int(k)) for n, k in solve_shapes]
        with self._lock:
            # Normalized to buckets — the same coordinates
            # _record_bucket stores — so stats()["warm_shapes"] reports
            # what the fleet actually serves and a replacement's warmup
            # never re-resolves duplicate sizes of one bucket.  The
            # update/solve sets follow the same invariant with their
            # lane coordinates: (bucket_n, k_bucket) / (bucket_n, rhs).
            self._warm_shapes.update(bucket_for(s) for s in shapes)
            self._warm_updates.update(
                (bucket_for(n), k_bucket_for(k))
                for n, k in update_shapes)
            self._warm_solves.update(
                (bucket_for(n), rhs_bucket_for(k))
                for n, k in solve_shapes)
        out = {}
        for replica in self.live_replicas():
            out = replica.warmup(shapes, update_shapes=update_shapes,
                                 solve_shapes=solve_shapes)
        return out

    def start(self) -> None:
        """Start every replica's dispatcher (no-op when
        ``autostart=True``).  From here on, replacements installed by
        the supervisor start their dispatcher immediately."""
        with self._lock:
            self._started = True
        for replica in self.live_replicas():
            replica.service.start()

    def close(self, drain: bool = True) -> None:
        """Stop supervision (no restarts during shutdown), then close
        every replica; ``drain=True`` completes all queued and
        in-flight work first.  Idempotent and thread-safe, like
        ``JordanService.close``."""
        with self._lock:
            self.closing = True
            self._lock.notify_all()     # release grace-waiting routers
        with self._close_lock:          # a racing closer blocks here
            if self._close_complete:    # ... and returns only after the
                return                  # first has drained everything
            self.supervisor.stop()
            for slot in self.slot_table():
                if slot.replica is not None:
                    slot.replica.close(drain=drain)
            self._export_ready_gauge()
            self._close_complete = True

    def __enter__(self) -> "JordanFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- observability ----------------------------------------------

    def stats(self) -> dict:
        """Fleet-level aggregation: the request ledger (submitted ==
        ok + typed errors once drained — the zero-silent-loss
        invariant), per-slot replica snapshots with lineage, restart
        breaker states, and each live replica's full serving stats."""
        with self._lock:
            ledger = {"submitted": self._submitted,
                      "resolved_ok": self._resolved_ok,
                      "resolved_error": self._resolved_error,
                      "outstanding": (self._submitted - self._resolved_ok
                                      - self._resolved_error)}
            slots = list(self._slots)
        per_slot = []
        ready = 0
        for s in slots:
            entry = {"slot": s.index,
                     "restart_breaker": s.breaker.state,
                     "lineage": list(s.lineage),
                     "parked": s.parked,
                     "replica": None}
            if s.replica is not None:
                entry["replica"] = s.replica.snapshot()
                if s.replica.state == READY:
                    ready += 1
                    entry["service"] = s.replica.service.stats()
            per_slot.append(entry)
        return {
            "replicas": self.slots,
            "ready": ready,
            "ledger": ledger,
            # The journey-derived view of the same ledger (ISSUE 8):
            # derived purely from per-request journey events through
            # the ONE shared helper, so it can never drift from what
            # the black-box dump can prove.  ``gaps`` non-empty while
            # drained = silent loss.
            "journey_ledger": self.journey.ledger(),
            "warm_shapes": self.warm_shapes(),
            "warm_update_shapes": [list(p) for p
                                   in self.warm_update_shapes()],
            "warm_solve_shapes": [list(p) for p
                                  in self.warm_solve_shapes()],
            "executors_compiled": len(self.store),
            "handles": self.handles.snapshot(),
            "handle_budget": self.handles.budget_snapshot(),
            # The fleet-level capacity rollup (ISSUE 13): every byte
            # class the process holds — resident handles, compiled
            # lanes, the plan cache, the flight-recorder ring, and the
            # device watermark (re-probed here on backends that report
            # it) — with high-water marks and the created == live +
            # evicted reconciliation per metered class.
            "capacity": _obs_capacity.snapshot(),
            # Cross-replica execute-latency spread (ISSUE 19): the
            # measured-skew rollup over the READY replicas' own
            # ServeStats — the FleetSkewJudge's evidence input
            # (docs/OBSERVABILITY.md "was it the layout or the
            # replica?").
            "exec_spread": _cross_replica_spread(
                [e["service"] for e in per_slot if e.get("service")]),
            "slots": per_slot,
        }
