""":class:`FleetAutoscaler` — the SLO-driven replica control loop
(ISSUE 18 tentpole part 2).

PR 8 built the burn-rate :class:`~..obs.slo.SLOMonitor` as a REPORT;
this module closes the loop: the monitor DRIVES the supervisor-side
capacity of the pool.  One ``tick()`` is the whole policy (inline-
drivable, fake-clock deterministic in tests; the optional background
thread just runs it on an interval):

  * **Scale up on sustained burn** — any objective paging (the
    multi-window AND: long window proves material, short window proves
    ongoing) grows the pool by one replica per cooldown, up to
    ``ceiling``.  The replacement warms every fleet-served lane against
    the shared store BEFORE entering the slot table (zero compiles —
    the supervisor's rolling-restart discipline).
  * **Capacity veto** — the process-wide byte ledger
    (``obs/capacity.py``) is the what-fits check on every scale-up:
    with ``scale_budget_bytes`` set, a grow that would run over it is
    WITHHELD — a typed non-action, recorded with the same evidence as
    an action (``autoscale{action="scale_withheld"}``).
  * **Pre-shed before breach** — when burn or the p99 trend says the
    objective is at risk (p99 ≥ ``preshed_p99_frac`` × target, or any
    paging pair), the router's ``pre_shed`` flag sheds NEW submissions
    typed at the front door (``shed{reason="pre_shed"}``,
    journey-hopped ``ServiceOverloadedError``) — load is turned away
    while the pool scales, instead of queueing into a p99 breach.
  * **Drain to the floor when idle** — ``idle_after_s`` with zero new
    request outcomes (and no risk signals) parks one replica per
    cooldown down to ``floor``; parked slots drain their queues first
    (nothing dropped) and the supervisor skips them (designed
    reduction, not a death).

Every action AND withheld action is a flight-recorder ``autoscale``
event carrying the burn evidence it was derived from (paging
objectives with their window burn rates, p99 vs target, idle seconds,
ledger bytes) — ``tools/check_autoscale.py`` re-derives every decision
from that evidence and exits 2 on a silent p99 breach or an
unexplained scale action.
"""

from __future__ import annotations

import threading
import time

from ..obs import capacity as _capacity
from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from ..obs.slo import _outcome_counts

_M_ACTIONS = _obs_metrics.counter(
    "tpu_jordan_autoscale_actions_total",
    "autoscaler decisions, labeled by action (scale_up|drain|"
    "pre_shed_on|pre_shed_off|scale_withheld)")


class FleetAutoscaler:
    """The control loop over one :class:`~.pool.JordanFleet` and one
    :class:`~..obs.slo.SLOMonitor`.

    Args:
      pool: the fleet (needs ``ready_count``/``grow``/``drain_slot``
        and ``router.pre_shed`` — a test fake implementing those four
        is a full harness).
      monitor: the burn-rate monitor; ``tick()`` samples it and
        evaluates, so the caller never manages sampling separately.
      floor / ceiling: replica bounds.  Drain never goes below
        ``floor``; scale-up never above ``ceiling``.
      idle_after_s: zero new request outcomes for this long (with no
        risk signals) triggers a drain step.
      scale_cooldown_s: minimum spacing between capacity actions (both
        directions) — one step per window, never a thundering resize.
      preshed_p99_frac: the pre-breach trigger — pre-shed turns on
        when any objective's observed p99 reaches this fraction of its
        target (or any pair pages), and off when neither holds.
      scale_budget_bytes: optional ledger ceiling for the capacity
        veto; None = no veto.
      skew_judge: optional :class:`~..obs.work.FleetSkewJudge` — when
        its live verdict suspects a straggler, p99-risk-driven
        pre-shed is VETOED (one sick replica explains the p99 risk;
        shedding the whole fleet's front door is the wrong actuator —
        route/drain that replica instead).  Paging-driven pre-shed is
        never vetoed: burn is fleet-wide evidence.  None = no veto.
      clock: injectable monotonic clock (defaults to the pool's —
        fake-clock tests drive both from one source).
    """

    def __init__(self, pool, monitor, floor: int = 1, ceiling: int = 4,
                 idle_after_s: float = 30.0,
                 scale_cooldown_s: float = 5.0,
                 preshed_p99_frac: float = 0.8,
                 scale_budget_bytes: int | None = None,
                 skew_judge=None, clock=None):
        if floor < 1:
            raise ValueError("floor must be >= 1")
        if ceiling < floor:
            raise ValueError("ceiling must be >= floor")
        self.pool = pool
        self.monitor = monitor
        self.floor = int(floor)
        self.ceiling = int(ceiling)
        self.idle_after_s = float(idle_after_s)
        self.scale_cooldown_s = float(scale_cooldown_s)
        self.preshed_p99_frac = float(preshed_p99_frac)
        self.scale_budget_bytes = (None if scale_budget_bytes is None
                                   else int(scale_budget_bytes))
        self.skew_judge = skew_judge
        self._last_vetoed = False
        self.clock = (clock if clock is not None
                      else getattr(pool, "clock", time.monotonic))
        self._last_action_t: float | None = None
        self._last_activity_t = self.clock()
        self._last_outcome_total: int | None = None
        #: In-memory mirror of every recorded ``autoscale`` event, in
        #: order — the demo report embeds it next to the recorder
        #: slice so the checker can cross-validate the two.
        self.actions: list[dict] = []
        self.ticks = 0
        self._stop = False
        self._thread: threading.Thread | None = None

    # ---- the control policy -----------------------------------------

    def _record(self, action: str, ready_before: int,
                evidence: dict) -> dict:
        ev = {"action": action, "ready_before": ready_before,
              "ready_after": self.pool.ready_count(),
              "floor": self.floor, "ceiling": self.ceiling,
              "evidence": evidence}
        _M_ACTIONS.inc(action=action)
        _recorder.record("autoscale", **ev)
        self.actions.append(ev)
        return ev

    def _cooldown_ok(self, now: float) -> bool:
        return (self._last_action_t is None
                or now - self._last_action_t >= self.scale_cooldown_s)

    @staticmethod
    def _paging_evidence(report: dict) -> list[dict]:
        """The burn evidence of every paging objective — the window
        pairs whose long AND short burn exceeded the threshold, copied
        verbatim from the monitor's report (the checker re-derives the
        page decision from exactly these numbers)."""
        out = []
        for obj in report["objectives"]:
            if not obj["paging"]:
                continue
            out.append({"name": obj["name"], "bucket": obj["bucket"],
                        "error_budget": obj["error_budget"],
                        "windows": [w for w in obj["windows"]
                                    if w["page"]]})
        return out

    def _p99_risk(self, report: dict) -> list[dict]:
        """Objectives whose observed p99 reached the pre-breach
        fraction of their target."""
        out = []
        for obj in report["objectives"]:
            target, p99 = obj["p99_target_ms"], obj["p99_ms"]
            if (target is not None and p99 is not None
                    and p99 >= self.preshed_p99_frac * target):
                out.append({"name": obj["name"], "p99_ms": p99,
                            "p99_target_ms": target,
                            "frac": self.preshed_p99_frac})
        return out

    def tick(self) -> dict:
        """One control pass: sample + evaluate the monitor, then apply
        at most ONE capacity action (scale/drain, cooldown-spaced) and
        reconcile the pre-shed flag.  Returns the tick summary the
        demo report embeds."""
        now = self.clock()
        self.ticks += 1
        self.monitor.sample()
        report = self.monitor.evaluate()
        paging = self._paging_evidence(report)
        p99_risk = self._p99_risk(report)
        ready = self.pool.ready_count()

        # Activity tracking: any movement of the fleet-wide outcome
        # total (the journey-terminal series — the same numbers the
        # burn windows integrate) resets the idle clock.
        snap = self.monitor.registry.snapshot()
        ok, err = _outcome_counts(snap, None)
        total = ok + err
        if self._last_outcome_total is None \
                or total != self._last_outcome_total:
            self._last_activity_t = now
        self._last_outcome_total = total
        idle_s = now - self._last_activity_t

        action = None
        if paging and ready < self.ceiling and self._cooldown_ok(now):
            live = _capacity.live_bytes()
            if (self.scale_budget_bytes is not None
                    and live >= self.scale_budget_bytes):
                # The capacity veto: a withheld action leaves the same
                # evidence trail as a taken one.
                action = self._record("scale_withheld", ready, {
                    "paging": paging, "live_bytes": live,
                    "scale_budget_bytes": self.scale_budget_bytes})
                self._last_action_t = now
            else:
                slot = self.pool.grow()
                if slot is not None:
                    action = self._record("scale_up", ready, {
                        "paging": paging, "slot": slot,
                        "live_bytes": live,
                        "scale_budget_bytes": self.scale_budget_bytes})
                    self._last_action_t = now
        elif (not paging and not p99_risk and ready > self.floor
                and idle_s >= self.idle_after_s
                and self._cooldown_ok(now)):
            slot = self.pool.drain_slot()
            if slot is not None:
                action = self._record("drain", ready, {
                    "idle_s": round(idle_s, 6),
                    "idle_after_s": self.idle_after_s, "slot": slot})
                self._last_action_t = now

        # Pre-shed reconciliation (flag, not a step — no cooldown:
        # shedding must engage the tick the risk appears and release
        # the tick it clears).  The skew-judge veto (ISSUE 19) applies
        # ONLY to p99-risk-driven shedding: when the judge's live
        # verdict attributes the p99 spread to one suspected straggler
        # replica, shedding the whole fleet is the wrong actuator —
        # the evidence rides in the tick (and, transition-only, the
        # action trail) so a withheld shed is as reconstructible as a
        # taken one.  Paging (fleet-wide burn) is never vetoed.
        want_shed = bool(paging or p99_risk)
        skew_veto = None
        if p99_risk and not paging and self.skew_judge is not None:
            v = self.skew_judge.veto()
            if v is not None:
                skew_veto = {"replica": v.get("replica"),
                             "spread": v.get("spread"),
                             "threshold": v.get("threshold")}
                want_shed = False
        if skew_veto is not None and not self._last_vetoed:
            self._record("pre_shed_vetoed", ready, {
                "p99_risk": p99_risk, "skew_veto": skew_veto})
        self._last_vetoed = skew_veto is not None
        if want_shed != self.pool.router.pre_shed:
            self.pool.router.pre_shed = want_shed
            self._record("pre_shed_on" if want_shed else "pre_shed_off",
                         ready, {"paging": paging, "p99_risk": p99_risk})

        tick = {
            "t": round(now, 6),
            "ready": self.pool.ready_count(),
            "paging": [p["name"] for p in paging],
            "p99_risk": [p["name"] for p in p99_risk],
            "pre_shed": self.pool.router.pre_shed,
            "idle_s": round(idle_s, 6),
            "action": None if action is None else action["action"],
            "healthy": report["healthy"],
        }
        if skew_veto is not None:
            tick["skew_veto"] = skew_veto
        return tick

    # ---- optional background loop -----------------------------------

    def start(self, interval_s: float = 1.0) -> None:
        """Run ``tick()`` on a daemon thread every ``interval_s`` (the
        production wiring; tests and the demo drive ``tick()``
        inline)."""
        if self._thread is not None:
            return
        self._stop = False

        def loop():
            while not self._stop:
                time.sleep(interval_s)
                if self._stop:
                    return
                self.tick()

        self._thread = threading.Thread(
            target=loop, name="tpu-jordan-fleet-autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop = True
        if self._thread is not None:
            self._thread.join()
            self._thread = None


def autoscale_demo(n: int = 64, requests: int = 48, floor: int = 1,
                   ceiling: int = 3, batch_cap: int = 4,
                   max_wait_ms: float = 1.0, seed: int = 0,
                   block_size: int | None = None, dtype=None,
                   telemetry=None) -> dict:
    """The ``--autoscale-demo`` CLI mode's engine (ISSUE 18
    acceptance): one seeded burst→idle→recovery traffic trace through
    a floor-sized fleet under the :class:`FleetAutoscaler`, showing
    scale-up on sustained burn, typed pre-shed before breach, drain on
    idle, and a healthy recovery — every decision carried in the
    report with the burn evidence it was derived from
    (``tools/check_autoscale.py`` re-derives each one; exit 2 = a
    silent p99 breach or an unexplained scale action).

    The burn source is deterministic by construction: the burst waves
    mix clean requests with requests whose ``deadline_ms`` is already
    unpayable (sub-millisecond) — each resolves with the typed
    ``DeadlineExceededError``, an error outcome on the journey series
    the burn windows integrate.  No fault injection, no flaky timing
    assertions: the SLO math sees a sustained error rate, and the
    control loop must answer it."""
    import jax.numpy as jnp
    import numpy as np

    from ..obs.journey import outcome_ledger
    from ..obs.metrics import REGISTRY
    from ..obs.recorder import RECORDER
    from ..obs.slo import SLOMonitor, bucket_specs
    from ..serve.executors import bucket_for
    from .pool import JordanFleet

    dtype = jnp.dtype(jnp.float32 if dtype is None else dtype)
    t0 = time.monotonic()
    bucket = bucket_for(n)
    # Demo-scaled SLO: availability 0.7 (budget 0.3) with one
    # (2s, 0.4s, 1.2x) window pair — a ~50%-error burst burns ~1.67x,
    # decisively over threshold in BOTH windows within one wave, and a
    # quiet fleet decisively under (zero traffic burns zero).  The p99
    # objective is a generous runaway bound; the demo's pre-shed
    # trigger is the burn signal.
    windows = ((2.0, 0.4, 1.2),)
    availability, p99_target_ms = 0.7, 60000.0
    idle_after_s, preshed_frac = 0.6, 0.8
    monitor = SLOMonitor(
        bucket_specs([bucket], availability=availability,
                     p99_latency_ms=p99_target_ms),
        windows=windows)

    def shed_pre() -> int:
        return int(REGISTRY.counter("tpu_jordan_fleet_shed_total")
                   .value(reason="pre_shed"))

    waves, per_wave = 4, max(4, requests // 4)
    rng = np.random.default_rng(seed)
    bb_mark = RECORDER.total
    shed0 = shed_pre()
    ticks, trajectory = [], []
    phase_stats = {}

    with JordanFleet(replicas=floor, dtype=dtype, batch_cap=batch_cap,
                     max_wait_ms=max_wait_ms,
                     max_queue=max(requests * 2, 64),
                     block_size=block_size, telemetry=telemetry,
                     stable_after_s=0.05) as fleet:
        scaler = FleetAutoscaler(fleet, monitor, floor=floor,
                                 ceiling=ceiling,
                                 idle_after_s=idle_after_s,
                                 scale_cooldown_s=0.0,
                                 preshed_p99_frac=preshed_frac)
        fleet.warmup([n])
        monitor.sample()                     # the pre-burst baseline

        def run_wave(n_ok: int, n_bad: int) -> dict:
            futs = []
            for i in range(n_ok + n_bad):
                a = rng.standard_normal((n, n)).astype(dtype)
                # The bad half's deadline is unpayable by construction
                # (queue wait alone exceeds it): a deterministic typed
                # DeadlineExceededError, the demo's burn source.
                dl = None if i < n_ok else 0.01
                try:
                    futs.append(fleet.submit(a, deadline_ms=dl))
                except Exception as e:       # noqa: BLE001 — typed shed
                    futs.append(e)
            out = {"ok": 0, "typed_errors": {}}
            for f in futs:
                try:
                    if isinstance(f, Exception):
                        raise f
                    f.result(120)
                    out["ok"] += 1
                except Exception as e:       # noqa: BLE001 — typed
                    name = type(e).__name__
                    out["typed_errors"][name] = (
                        out["typed_errors"].get(name, 0) + 1)
            return out

        # ---- phase 1: burst (sustained two-window burn) -------------
        burst = []
        for _ in range(waves):
            burst.append(run_wave(per_wave // 2,
                                  per_wave - per_wave // 2))
            ticks.append(scaler.tick())
            trajectory.append(ticks[-1]["ready"])
            time.sleep(0.15)
        phase_stats["burst"] = {"waves": burst,
                                "ready_after": fleet.ready_count(),
                                "pre_shed": fleet.router.pre_shed}

        # ---- phase 2: idle (burn clears, fleet drains to floor) -----
        for _ in range(24):
            time.sleep(0.3)
            ticks.append(scaler.tick())
            trajectory.append(ticks[-1]["ready"])
            if (fleet.ready_count() <= floor
                    and not fleet.router.pre_shed):
                break
        phase_stats["idle"] = {"ready_after": fleet.ready_count(),
                               "pre_shed": fleet.router.pre_shed,
                               "ticks": len(ticks)}

        # ---- phase 3: recovery (clean traffic serves again) ---------
        recovery = run_wave(max(4, per_wave // 2), 0)
        ticks.append(scaler.tick())
        trajectory.append(ticks[-1]["ready"])
        phase_stats["recovery"] = recovery

        final_slo = monitor.evaluate()
        actions = list(scaler.actions)
        fleet_stats = fleet.stats()

    blackbox = RECORDER.dump(events=RECORDER.since(bb_mark))
    journey_ledger = outcome_ledger(blackbox["events"])
    by_action: dict[str, int] = {}
    for a in actions:
        by_action[a["action"]] = by_action.get(a["action"], 0) + 1
    # A tick that saw risk (paging or p99) and left pre-shed OFF with
    # no capacity action is the silent-breach class — the breach the
    # checker pages on.  A skew-vetoed tick is the one sanctioned
    # exception (ISSUE 19): the judge attributed the p99 risk to a
    # suspected straggler replica, and the veto evidence rides in the
    # tick itself.
    silent_p99_breach = any(
        (t["paging"] or t["p99_risk"]) and not t["pre_shed"]
        and t["action"] not in ("scale_up", "scale_withheld")
        and not t.get("skew_veto")
        for t in ticks)
    return {
        "metric": "autoscale_demo",
        "n": n, "seed": seed,
        "floor": floor, "ceiling": ceiling,
        "requests_per_wave": per_wave, "waves": waves,
        "config": {
            "windows": [list(w) for w in windows],
            "availability": availability,
            "p99_target_ms": p99_target_ms,
            "idle_after_s": idle_after_s,
            "scale_cooldown_s": 0.0,
            "preshed_p99_frac": preshed_frac,
        },
        "phases": phase_stats,
        "ticks": ticks,
        "actions": actions,
        "actions_by_kind": by_action,
        "ready_trajectory": trajectory,
        "pre_shed_count": shed_pre() - shed0,
        "slo_final": final_slo,
        "ledger": fleet_stats["ledger"],
        "journey_ledger": journey_ledger,
        "blackbox": blackbox,
        "silent_p99_breach": silent_p99_breach,
        "elapsed_s": round(time.monotonic() - t0, 3),
    }
