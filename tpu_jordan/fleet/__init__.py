"""tpu_jordan.fleet — the supervised serving replica pool (ISSUE 7
tentpole; docs/FLEET.md is the operator guide).

Five parts:

  * ``replica`` — one worker wrapping its own
    :class:`~..serve.service.JordanService` (dispatcher, bounded queue,
    per-bucket breakers, heartbeat) with kill/drain hooks and the
    seeded ``replica_kill`` fault point on its dispatch path.
  * ``router`` — bucket-affinity dispatch with breaker-aware load
    shedding: an open per-bucket breaker means no traffic for that
    bucket on that replica; fleet-wide saturation is typed
    :class:`~..serve.batcher.ServiceOverloadedError` backpressure —
    never a silent drop.  Death-class failures re-queue to a healthy
    replica within the PR 5 retry/deadline budget.
  * ``supervisor`` — heartbeat liveness + wedge detection, warm rolling
    restarts against the fleet-shared executor store and the read-only
    pre-tuned plan cache (a replacement performs zero compiles and
    zero measurements), and a per-slot restart breaker against crash
    loops.
  * ``pool`` — :class:`JordanFleet`: the ``JordanService`` surface
    (``submit``/``invert``/``warmup``/``close``) fleet-wide, plus the
    request ledger and per-slot lineage in ``stats()``.
  * ``demo`` — ``fleet_demo``: the ``--fleet-demo`` CLI engine; its
    report is validated by ``tools/check_fleet.py`` (exit 2 = silent
    loss).
"""

from .demo import fleet_demo
from .pool import JordanFleet
from .replica import Replica, ReplicaKilledError
from .router import Router
from .supervisor import Supervisor

__all__ = [
    "JordanFleet", "Replica", "ReplicaKilledError", "Router",
    "Supervisor", "fleet_demo",
]
