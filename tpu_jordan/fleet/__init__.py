"""tpu_jordan.fleet — the supervised serving replica pool (ISSUE 7
tentpole; docs/FLEET.md is the operator guide).

Five parts:

  * ``replica`` — one worker wrapping its own
    :class:`~..serve.service.JordanService` (dispatcher, bounded queue,
    per-bucket breakers, heartbeat) with kill/drain hooks and the
    seeded ``replica_kill`` fault point on its dispatch path.
  * ``router`` — bucket-affinity dispatch with breaker-aware load
    shedding: an open per-bucket breaker means no traffic for that
    bucket on that replica; fleet-wide saturation is typed
    :class:`~..serve.batcher.ServiceOverloadedError` backpressure —
    never a silent drop.  Death-class failures re-queue to a healthy
    replica within the PR 5 retry/deadline budget.
  * ``supervisor`` — heartbeat liveness + wedge detection, warm rolling
    restarts against the fleet-shared executor store and the read-only
    pre-tuned plan cache (a replacement performs zero compiles and
    zero measurements), and a per-slot restart breaker against crash
    loops.
  * ``pool`` — :class:`JordanFleet`: the ``JordanService`` surface
    (``submit``/``invert``/``warmup``/``close``) fleet-wide, plus the
    request ledger and per-slot lineage in ``stats()``.
  * ``demo`` — ``fleet_demo``: the ``--fleet-demo`` CLI engine; its
    report is validated by ``tools/check_fleet.py`` (exit 2 = silent
    loss).
  * ``autoscaler`` — :class:`FleetAutoscaler` (ISSUE 18): the
    burn-rate :class:`~..obs.slo.SLOMonitor` DRIVES the pool — scale
    up on sustained two-window burn (capacity-ledger veto), typed
    pre-shed at the router before a p99 breach, drain parked slots to
    the floor when idle; every action a flight-recorder event carrying
    its burn evidence.  ``autoscale_demo`` is the ``--autoscale-demo``
    CLI engine (``tools/check_autoscale.py`` re-derives every
    decision; exit 2 = silent p99 breach).
"""

from .autoscaler import FleetAutoscaler, autoscale_demo
from .demo import fleet_demo
from .pool import JordanFleet
from .replica import Replica, ReplicaKilledError
from .router import Router
from .supervisor import Supervisor

__all__ = [
    "FleetAutoscaler", "JordanFleet", "Replica", "ReplicaKilledError",
    "Router", "Supervisor", "autoscale_demo", "fleet_demo",
]
