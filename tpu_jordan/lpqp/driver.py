"""LP/QP optimization drivers over a :class:`~..fleet.pool.JordanFleet`
(ISSUE 17 tentpole, module 2 of the lpqp subsystem).

Both drivers exercise the EXACT traffic pattern the resident-handle
machinery was built for — one ``invert(resident=True)`` to establish
the working inverse, then a long correlated stream where every
iteration's KKT system differs from the last by a rank-k mutation:

  * :func:`solve_lp` — revised simplex (Bland's rule, so no cycling and
    a deterministic pivot sequence).  The slack basis starts at B = I;
    every pivot swaps ONE basis column, i.e. a rank-1 update
    ``B += u·e_pᵀ`` riding ``fleet.update`` — the resident inverse IS
    the simplex's basis-inverse representation.
  * :func:`solve_qp` — primal active-set on a box QP.  The working
    matrix ``M = E·Q·E + (I − E)`` (E = diag of the free mask) changes
    only in row/column *i* when coordinate *i* toggles between free and
    active — a rank-2 update ``U = [e_i, ΔM·e_i − ΔM_ii·e_i]``,
    ``V = [ΔMᵀ·e_i, e_i]`` riding the same lane.

Every update's answer carries the serving layer's own judgment
(``refreshed`` | ``re_inverted`` | ``gated``), folded into the report's
ledger; a drift-budget crossing falls through the ``re_invert`` rung
transparently and the driver keeps iterating on the recovered inverse.
Periodic verification solves (``fleet.solve_system``, every
``solve_every`` iterations) cross-check the updated inverse against a
fresh sharded elimination of the SAME system, judged by the solve
lane's κ-free backward-error gate plus a κ-scaled agreement test —
the forward-error model the repo's own gates encode, never a looser
twin (see :func:`~.problem.kkt_gate`).

Determinism: given the same instance, fleet dtype and fault plan, the
pivot/toggle sequence, every iterate, and the final fingerprint are
bit-identical run to run — a mid-flight ``replica_kill`` re-queues
through the router and the retry re-reads committed state, so the
chaos leg of the demo can bit-compare against a fault-free replay.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

import numpy as np

from ..resilience.degrade import (gate_passes, gate_threshold,
                                  solve_gate_threshold)
from ..resilience.policy import ResidualGateError
from .problem import (LPInstance, QPInstance, kkt_converged, kkt_gate,
                      lp_kkt_residual, qp_kkt_residual)

__all__ = ["OptimizeError", "OptimizeReport", "solve_lp", "solve_qp"]

#: every fleet.update outcome the drivers account (plus "error" for
#: typed gate exhaustion) — the checker proves the ledger sums to the
#: update count, so nothing the fleet judged can go unreported.
OUTCOMES = ("refreshed", "re_inverted", "gated")

_RATIO_EPS = 1e-10          # simplex ratio-test / QP step denominators


class OptimizeError(RuntimeError):
    """Typed driver failure: an unbounded/infeasible instance, an
    iteration cap hit, or a fleet-side typed numerics refusal
    (``ResidualGateError`` — the re_invert rung could not recover) the
    driver will not paper over.  ``report`` carries the iterate trail
    up to the failure for post-mortem."""

    def __init__(self, msg: str, report: "OptimizeReport" = None):
        super().__init__(msg)
        self.report = report


@dataclass
class OptimizeReport:
    """One driver run's full account — everything the ``--lp-demo``
    checker re-derives convergence from.  ``iterates`` holds one dict
    per iteration (kkt residual + threshold, the update outcome the
    fleet judged, drift, committed handle version, and — on
    verification iterations — the solve-lane residual/threshold and
    the κ-scaled agreement between the updated inverse and the fresh
    solve).  ``fingerprint`` hashes the final x bytes + objective bits,
    the chaos leg's bit-compare token."""

    kind: str                 # "lp" | "qp"
    name: str                 # instance name (seeded, self-describing)
    converged: bool
    iterations: int
    objective: float
    objective_ref: float      # the instance's constructed optimum
    kkt_rel_final: float
    kkt_threshold: float      # the solver-gate threshold at the end
    kappa: float              # last verified κ of the working matrix
    updates: int              # fleet.update calls issued
    solves: int               # fleet.solve_system verifications issued
    ledger: dict = field(default_factory=dict)   # outcome -> count
    iterates: list = field(default_factory=list)
    handle_id: str = ""
    fingerprint: str = ""
    x: np.ndarray = None

    def to_dict(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "kind", "name", "converged", "iterations", "objective",
            "objective_ref", "kkt_rel_final", "kkt_threshold", "kappa",
            "updates", "solves", "ledger", "iterates", "handle_id",
            "fingerprint")}
        d["obj_rel_err"] = (
            abs(self.objective - self.objective_ref)
            / (1.0 + abs(self.objective_ref)))
        return d


def _fingerprint(x: np.ndarray, objective: float) -> str:
    h = hashlib.sha256(np.ascontiguousarray(x).tobytes())
    h.update(float(objective).hex().encode())
    return h.hexdigest()


class _FleetLane:
    """The drivers' shared fleet adapter: one resident handle, the
    per-update outcome ledger, and the periodic verification solve —
    so the LP and QP loops stay pure algorithm."""

    def __init__(self, fleet, a0: np.ndarray, policy):
        self.fleet = fleet
        self.policy = (policy if policy is not None
                       else getattr(fleet, "policy", None))
        self.handle = fleet.invert(np.asarray(a0), resident=True)
        self.dtype = np.dtype(self.handle.dtype)
        self.inv = np.asarray(self.handle.result.inverse, np.float64)
        self.kappa = max(float(self.handle.result.kappa), 1.0)
        self.updates = 0
        self.solves = 0
        self.ledger = {o: 0 for o in OUTCOMES}
        self.ledger["error"] = 0

    def update(self, u: np.ndarray, v: np.ndarray, report) -> dict:
        """One rank-k mutation through the fleet; returns the iterate
        fields the loop folds into its record.  Typed fleet refusals
        become :class:`OptimizeError` carrying the partial report."""
        from ..driver import SingularMatrixError

        self.updates += 1
        try:
            res = self.fleet.update(self.handle, np.asarray(u),
                                    np.asarray(v))
        except ResidualGateError as e:
            self.ledger["error"] += 1
            raise OptimizeError(
                f"fleet update refused typed (re_invert rung "
                f"exhausted): {e}", report) from e
        except SingularMatrixError as e:
            # The fleet's typed singularity answer — the committed
            # resident state is untouched, but the driver's pivot
            # choice produced a rank-destroying mutation: a driver
            # bug or a degenerate instance, surfaced typed.
            self.ledger["gated"] += 1
            raise OptimizeError(
                f"update would destroy rank (fleet gated it): {e}",
                report) from e
        self.ledger[res.update_outcome] += 1
        self.inv = np.asarray(res.inverse, np.float64)
        self.kappa = max(float(res.kappa), 1.0)
        return {"outcome": res.update_outcome,
                "drift": float(res.drift),
                "version": int(res.handle_version),
                "kappa": float(res.kappa)}

    def verify(self, a: np.ndarray, rhs: np.ndarray,
               x_inv: np.ndarray) -> dict:
        """Cross-check the updated resident inverse against a FRESH
        fleet solve of the same system: the solve lane's κ-free
        backward-error gate judges the fresh solve, and a κ-scaled
        forward-error gate (eps·n·κ — the invert gate's own model)
        judges the agreement ‖x_solve − x_inv‖ between the two
        routes.  Disagreement beyond what κ explains means the
        resident inverse silently rotted — exactly what the drift
        budget exists to prevent, so the demo checker treats a failed
        agreement as the silent-divergence class."""
        n = a.shape[0]
        self.solves += 1
        res = self.fleet.solve_system(np.asarray(a), rhs[:, None])
        x_solve = np.asarray(res.solution, np.float64)[:, 0]
        solve_thr = solve_gate_threshold(self.policy, n, self.dtype)
        agree_rel = (np.max(np.abs(x_solve - x_inv))
                     / (1.0 + np.max(np.abs(x_solve))))
        # The agreement ceiling is the solver's own drift model: a
        # resident inverse is ALLOWED to carry up to drift_budget
        # gate-widths of accumulated error before re_invert fires, and
        # the fresh solve contributes one more gate-width of its own —
        # so the two routes may legitimately disagree by (budget + 1)
        # κ-scaled gate-widths, and no more.
        from ..linalg.update import drift_budget

        gate_w = gate_threshold(self.policy, n, self.kappa, self.dtype)
        agree_thr = drift_budget(gate_w) + gate_w
        return {"solve_rel": float(res.rel_residual),
                "solve_threshold": float(solve_thr),
                "solve_pass": gate_passes(float(res.rel_residual),
                                          solve_thr),
                "agree_rel": float(agree_rel),
                "agree_threshold": float(agree_thr),
                "agree": gate_passes(float(agree_rel), agree_thr)}

    def gate(self, n: int) -> float:
        return kkt_gate(self.policy, n, self.kappa, self.dtype)

    # ---- checkpoint/resume (ISSUE 20) -------------------------------

    def ckpt_arrays(self) -> tuple:
        """The lane's exact resident-handle bytes + counters, the
        stream-checkpoint payload: restoring these and replaying from
        the same iteration reproduces every later iterate bit for bit
        (the driver loops are pure functions of (instance, lane
        state))."""
        st = self.fleet.handles.get(self.handle.handle_id)
        with st.lock:
            arrays = {"handle_a": np.asarray(st.a).copy(),
                      "handle_inverse": np.asarray(st.inverse).copy()}
            meta = {"handle_id": st.handle_id, "n": st.n,
                    "bucket_n": st.bucket_n, "dtype": st.dtype,
                    "version": st.version, "drift": float(st.drift),
                    "updates_applied": st.updates_applied,
                    "reinverts": st.reinverts,
                    "kappa": float(st.kappa),
                    "rel_residual": float(st.rel_residual),
                    "nbytes": int(st.nbytes),
                    "pinned": bool(st.pinned)}
        meta.update(updates=self.updates, solves=self.solves,
                    ledger=dict(self.ledger))
        return arrays, meta

    @classmethod
    def restore(cls, fleet, policy, arrays: dict, meta: dict):
        """Re-install the checkpointed resident handle (same
        handle_id — ``HandleStore.create`` replaces any survivor, so a
        post-kill stale resident can never leak into the replay) and
        rebuild the lane counters exactly as written."""
        from ..serve.handles import HandleState

        lane = cls.__new__(cls)
        lane.fleet = fleet
        lane.policy = (policy if policy is not None
                       else getattr(fleet, "policy", None))
        state = HandleState(
            handle_id=meta["handle_id"], n=meta["n"],
            bucket_n=meta["bucket_n"], dtype=meta["dtype"],
            a=np.asarray(arrays["handle_a"]),
            inverse=np.asarray(arrays["handle_inverse"]),
            version=meta["version"], drift=meta["drift"],
            updates_applied=meta["updates_applied"],
            reinverts=meta["reinverts"], kappa=meta["kappa"],
            rel_residual=meta["rel_residual"], nbytes=meta["nbytes"],
            pinned=meta.get("pinned", False))
        lane.handle = fleet.handles.create(state)
        lane.dtype = np.dtype(meta["dtype"])
        n = meta["n"]
        lane.inv = np.asarray(arrays["handle_inverse"],
                              np.float64)[:n, :n]
        lane.kappa = max(float(meta["kappa"]), 1.0)
        lane.updates = int(meta["updates"])
        lane.solves = int(meta["solves"])
        lane.ledger = {k: int(v) for k, v in meta["ledger"].items()}
        return lane


# ---------------------------------------------------------------------
# Stream checkpointing (ISSUE 20): the optimizer loops persist the
# resident-handle bytes + the iterate audit every ``ckpt_every``
# iterations; ``resume=True`` re-enters at the stored iteration and
# replays to an IDENTICAL kkt_hex trail and final fingerprint (the
# loops are deterministic functions of (instance, lane state), so the
# restored exact bytes pin everything downstream).
# ---------------------------------------------------------------------


def _opt_ckpt_key(kind: str, prob, run_id: str, lane_dtype,
                  max_iters: int, cadence: int):
    from ..resilience.checkpoint import CheckpointKey

    n = getattr(prob, "n", 0)
    m = getattr(prob, "m", n)
    return CheckpointKey(
        run_id=run_id, workload=kind,
        engine="simplex" if kind == "lp" else "active-set",
        topology="fleet", n=int(n), m=int(m), Nr=int(max_iters),
        dtype=np.dtype(lane_dtype).name, nrhs=0, cadence=int(cadence))


def _opt_ckpt_write(store, key, it: int, lane, report,
                    extra: dict) -> None:
    arrays, handle_meta = lane.ckpt_arrays()
    meta = {"it": int(it), "handle": handle_meta,
            "iterates": report.iterates,
            "iterations": report.iterations}
    meta.update(extra)
    arrays["meta"] = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), np.uint8).copy()
    store.write(key, it, arrays)


def _opt_ckpt_resume(store, key, fleet, policy):
    step, arrays = store.resume(key)
    meta = json.loads(bytes(arrays["meta"].tobytes()).decode())
    if int(meta["it"]) != step:
        from ..resilience.checkpoint import CheckpointMismatchError

        raise CheckpointMismatchError(
            f"stream checkpoint step {step} disagrees with its own "
            f"audit ({meta['it']}); refused")
    lane = _FleetLane.restore(fleet, policy, arrays, meta["handle"])
    return step, meta, lane


def _opt_preempt(run_id: str, durable: int | None) -> None:
    from ..resilience.checkpoint import _fire_preempt

    _fire_preempt(run_id, durable)


def solve_lp(prob: LPInstance, fleet, policy=None,
             max_iters: int | None = None,
             solve_every: int = 1, ckpt_store=None,
             ckpt_every: int = 0, run_id: str | None = None,
             resume: bool = False) -> OptimizeReport:
    """Revised simplex over the fleet (see module docstring).  The
    basis inverse lives in a resident fleet handle seeded from the
    slack basis (B = I); each Bland pivot is one rank-1
    ``fleet.update``; every ``solve_every``-th iteration cross-checks
    x_B = B⁻¹b against a fresh ``fleet.solve_system(B, b)``.
    Converged means: no entering column remains AND the (x, y) pair's
    KKT residual passes the solver's own eps·n·κ gate.

    ``ckpt_store``/``ckpt_every`` persist the resident-handle bytes +
    iterate audit every k iterations (ISSUE 20); ``resume=True``
    re-enters at the stored iteration and replays to an identical
    ``kkt_hex`` trail and fingerprint.  The ``preempt`` fault point
    fires at every iteration top when a plan is active."""
    m, a, b, c = prob.m, np.asarray(prob.a, np.float64), prob.b, prob.c
    b = np.asarray(b, np.float64)
    c = np.asarray(c, np.float64)
    if max_iters is None:
        max_iters = 6 * m
    if run_id is None:
        run_id = f"lp:{prob.name}"
    basis = list(prob.basis0)
    start_it, durable = 0, None
    ckpt_key = None
    stored_iterates: list = []
    if resume:
        if ckpt_store is None:
            raise ValueError("resume=True needs ckpt_store")
        dt = (np.dtype(fleet._svc_kw["dtype"])
              if hasattr(fleet, "_svc_kw") else np.dtype(np.float32))
        ckpt_key = _opt_ckpt_key("lp", prob, run_id, dt, max_iters,
                                 max(1, ckpt_every))
        start_it, meta, lane = _opt_ckpt_resume(ckpt_store, ckpt_key,
                                                fleet, policy)
        durable = start_it
        basis = [int(i) for i in meta["basis"]]
        stored_iterates = meta["iterates"]
    else:
        lane = _FleetLane(fleet, np.eye(m, dtype=a.dtype), policy)
    if ckpt_store is not None and ckpt_key is None:
        ckpt_key = _opt_ckpt_key("lp", prob, run_id, lane.dtype,
                                 max_iters, max(1, ckpt_every))
    report = OptimizeReport(
        kind="lp", name=prob.name, converged=False,
        iterations=start_it, objective=float("nan"),
        objective_ref=prob.obj_star, kkt_rel_final=float("nan"),
        kkt_threshold=float("nan"), kappa=lane.kappa,
        updates=lane.updates, solves=lane.solves, ledger=lane.ledger,
        handle_id=lane.handle.handle_id)
    report.iterates = list(stored_iterates)
    # Dtype/κ-aware pricing tolerance: reduced costs computed through
    # the fleet inverse carry ~eps·m·κ relative noise, so Bland's
    # entering test must not chase signs below that floor.
    eps = float(np.finfo(lane.dtype).eps)
    c_inf = float(np.max(np.abs(c)))
    x = np.zeros(prob.n)
    kkt_rel, thr, optimal = float("nan"), float("nan"), False
    for it in range(start_it, max_iters):
        if (ckpt_store is not None and ckpt_every > 0 and it > start_it
                and (it % ckpt_every) == 0):
            _opt_ckpt_write(ckpt_store, ckpt_key, it, lane, report,
                            {"basis": [int(i) for i in basis]})
            durable = it
        _opt_preempt(run_id, durable)
        red_tol = (1.0 + c_inf) * max(1e-9, 10.0 * eps * m * lane.kappa)
        report.iterations = it + 1
        x_b = lane.inv @ b
        x[:] = 0.0
        x[basis] = x_b
        y = lane.inv.T @ c[basis]
        kkt_rel = lp_kkt_residual(prob, x, y)
        thr = lane.gate(m)
        rec = {"i": it, "kkt_rel": kkt_rel, "kkt_threshold": thr,
               "kkt_hex": float(kkt_rel).hex()}
        reduced = c - a.T @ y
        reduced[basis] = 0.0
        entering = np.flatnonzero(reduced < -red_tol)
        if entering.size == 0:
            report.iterates.append(rec)
            optimal = True
            break
        q = int(entering[0])                      # Bland: smallest index
        d = lane.inv @ a[:, q]
        pos = np.flatnonzero(d > _RATIO_EPS)
        if pos.size == 0:
            report.iterates.append(rec)
            _finalize(report, x, c, kkt_rel, thr, lane)
            raise OptimizeError(
                f"LP unbounded below at iteration {it} "
                f"(entering column {q} has no blocking row)", report)
        ratios = x_b[pos] / d[pos]
        best = ratios.min()
        ties = pos[ratios <= best * (1.0 + 1e-12) + 1e-300]
        # Bland's leaving rule: among the minimum-ratio rows, evict
        # the smallest basis INDEX — with the entering rule above this
        # provably never cycles, and the pivot sequence is a pure
        # function of the instance (the chaos bit-match relies on it).
        p = int(ties[np.argmin(np.asarray(basis)[ties])])
        u = a[:, q] - a[:, basis[p]]
        v = np.zeros(m)
        v[p] = 1.0
        rec.update(lane.update(u[:, None], v[:, None], report))
        basis[p] = q
        if (it + 1) % max(1, solve_every) == 0:
            b_mat = a[:, basis]
            rec.update(lane.verify(b_mat, b, lane.inv @ b))
        report.iterates.append(rec)
    x[:] = 0.0
    x[basis] = lane.inv @ b
    _finalize(report, x, c, kkt_rel, thr, lane)
    report.converged = bool(optimal
                            and kkt_converged(kkt_rel, thr))
    if not optimal:
        raise OptimizeError(
            f"LP did not reach an optimal basis in {max_iters} "
            f"iterations", report)
    if ckpt_store is not None:
        ckpt_store.discard(run_id, reason="complete")
    return report


def _finalize(report: OptimizeReport, x, c_or_none, kkt_rel, thr,
              lane) -> None:
    report.kkt_rel_final = float(kkt_rel)
    report.kkt_threshold = float(thr)
    report.kappa = lane.kappa
    report.updates = lane.updates
    report.solves = lane.solves
    report.x = x.copy()
    if c_or_none is not None:
        report.objective = float(c_or_none @ x)
    report.fingerprint = _fingerprint(report.x, report.objective)


def _qp_working_matrix(q: np.ndarray, free: np.ndarray) -> np.ndarray:
    """M = E·Q·E + (I − E): the free block is Q_FF, active rows/cols
    are identity — so M·z = rhs solves the equality-constrained
    subproblem AND M stays symmetric positive definite for every
    active set (Q_FF is a principal submatrix of an SPD Q)."""
    mat = np.where(np.outer(free, free), q, 0.0)
    mat[~free, ~free] = 1.0
    return mat


def _qp_toggle_factors(m_old: np.ndarray, m_new: np.ndarray,
                       i: int) -> tuple:
    """ΔM = M_new − M_old is confined to row/column *i* when one
    coordinate toggles, so it factors exactly as the rank-2
    ``U·Vᵀ = e_i·ΔM[i,:] + (ΔM[:,i] − ΔM[i,i]·e_i)·e_iᵀ`` (the diag
    entry assigned to the first term only, never double-counted)."""
    n = m_old.shape[0]
    delta = m_new - m_old
    e_i = np.zeros(n)
    e_i[i] = 1.0
    row = delta[i, :].copy()
    col = delta[:, i].copy()
    col[i] = 0.0
    u = np.stack([e_i, col], axis=1)
    v = np.stack([row, e_i], axis=1)
    return u, v


def solve_qp(prob: QPInstance, fleet, policy=None,
             max_iters: int | None = None,
             solve_every: int = 2, ckpt_store=None,
             ckpt_every: int = 0, run_id: str | None = None,
             resume: bool = False) -> OptimizeReport:
    """Primal active-set over the fleet (see module docstring).  The
    working-matrix inverse is a resident handle seeded from M = Q
    (empty active set, feasible start x = lo); every bound
    addition/release is one rank-2 ``fleet.update``; converged means
    the projected-gradient KKT residual passes the solver's own
    eps·n·κ gate.  ``ckpt_store``/``ckpt_every``/``resume`` follow the
    :func:`solve_lp` checkpoint contract (the extra state is the
    iterate ``x`` and the free mask; ``m_work`` is re-derived from
    them, so the restored stream replays bit-identically)."""
    n = prob.n
    q = np.asarray(prob.q, np.float64)
    c = np.asarray(prob.c, np.float64)
    lo = np.asarray(prob.lo, np.float64)
    hi = np.asarray(prob.hi, np.float64)
    if max_iters is None:
        max_iters = 6 * n
    if run_id is None:
        run_id = f"qp:{prob.name}"
    start_it, durable = 0, None
    ckpt_key = None
    stored_iterates: list = []
    if resume:
        if ckpt_store is None:
            raise ValueError("resume=True needs ckpt_store")
        dt = (np.dtype(fleet._svc_kw["dtype"])
              if hasattr(fleet, "_svc_kw") else np.dtype(np.float32))
        ckpt_key = _opt_ckpt_key("qp", prob, run_id, dt, max_iters,
                                 max(1, ckpt_every))
        start_it, meta, lane = _opt_ckpt_resume(ckpt_store, ckpt_key,
                                                fleet, policy)
        durable = start_it
        free = np.asarray(meta["free"], dtype=bool)
        x = np.asarray(meta["x"], np.float64)
        m_work = _qp_working_matrix(q, free)
        stored_iterates = meta["iterates"]
    else:
        free = np.ones(n, dtype=bool)
        m_work = _qp_working_matrix(q, free)
        lane = _FleetLane(fleet, m_work.astype(prob.q.dtype), policy)
        x = lo.copy()
    if ckpt_store is not None and ckpt_key is None:
        ckpt_key = _opt_ckpt_key("qp", prob, run_id, lane.dtype,
                                 max_iters, max(1, ckpt_every))
    report = OptimizeReport(
        kind="qp", name=prob.name, converged=False,
        iterations=start_it, objective=float("nan"),
        objective_ref=prob.obj_star, kkt_rel_final=float("nan"),
        kkt_threshold=float("nan"), kappa=lane.kappa,
        updates=lane.updates, solves=lane.solves, ledger=lane.ledger,
        handle_id=lane.handle.handle_id)
    report.iterates = list(stored_iterates)
    eps = float(np.finfo(lane.dtype).eps)
    c_inf = float(np.max(np.abs(c)))
    kkt_rel, thr = float("nan"), float("nan")

    def toggle(i: int, now_free: bool, rec: dict) -> None:
        nonlocal m_work
        free[i] = now_free
        m_new = _qp_working_matrix(q, free)
        u, v = _qp_toggle_factors(m_work, m_new, i)
        rec.update(lane.update(u, v, report))
        m_work = m_new

    for it in range(start_it, max_iters):
        if (ckpt_store is not None and ckpt_every > 0 and it > start_it
                and (it % ckpt_every) == 0):
            _opt_ckpt_write(ckpt_store, ckpt_key, it, lane, report,
                            {"free": [bool(f) for f in free],
                             "x": [float(v) for v in x]})
            durable = it
        _opt_preempt(run_id, durable)
        report.iterations = it + 1
        mul_tol = (1.0 + c_inf) * max(1e-9,
                                      10.0 * eps * n * lane.kappa)
        kkt_rel = qp_kkt_residual(prob, x)
        thr = lane.gate(n)
        rec = {"i": it, "kkt_rel": kkt_rel, "kkt_threshold": thr,
               "kkt_hex": float(kkt_rel).hex()}
        # rhs of M·z = rhs: free rows ask Q_FF·z_F = −c_F − Q_FA·x_A,
        # active rows pin z to the bound value.
        x_bnd = np.where(free, 0.0, x)
        rhs = np.where(free, -(c + q @ x_bnd), x)
        z = lane.inv @ rhs
        z[~free] = x[~free]           # active coords exact by contract
        if (it + 1) % max(1, solve_every) == 0:
            # Cross-check BEFORE any toggle mutates M — the fresh
            # solve must target the same system z came from.
            rec.update(lane.verify(m_work, rhs, z))
        p = z - x
        step = float(np.max(np.abs(p)))
        if step <= 1e-12 * (1.0 + np.max(np.abs(x))):
            # At the equality-constrained optimum for this active set:
            # release the worst bound whose multiplier says the
            # objective still improves by leaving it, or stop.
            g = q @ x + c
            lam = np.where(free, 0.0, np.where(x <= lo, g, -g))
            viol = np.flatnonzero((~free) & (lam < -mul_tol))
            if viol.size == 0:
                report.iterates.append(rec)
                break
            j = int(viol[np.argmin(lam[viol])])   # most negative
            rec["release"] = j
            toggle(j, True, rec)
        else:
            alpha, blocker, side = 1.0, -1, 0.0
            for i in np.flatnonzero(free):
                if p[i] > _RATIO_EPS:
                    r, bnd = (hi[i] - x[i]) / p[i], hi[i]
                elif p[i] < -_RATIO_EPS:
                    r, bnd = (lo[i] - x[i]) / p[i], lo[i]
                else:
                    continue
                if r < alpha - 1e-15:
                    alpha, blocker, side = r, i, bnd
            x = x + max(0.0, min(1.0, alpha)) * p
            if blocker >= 0:
                blocker = int(blocker)
                x[blocker] = side
                rec["add"] = blocker
                toggle(blocker, False, rec)
        report.iterates.append(rec)
    else:
        _finalize(report, x, None, kkt_rel, thr, lane)
        report.objective = float(0.5 * x @ q @ x + c @ x)
        report.fingerprint = _fingerprint(x, report.objective)
        raise OptimizeError(
            f"QP active-set did not terminate in {max_iters} "
            f"iterations", report)
    _finalize(report, x, None, kkt_rel, thr, lane)
    report.objective = float(0.5 * x @ q @ x + c @ x)
    report.fingerprint = _fingerprint(x, report.objective)
    report.converged = bool(kkt_converged(kkt_rel, thr))
    if ckpt_store is not None:
        ckpt_store.discard(run_id, reason="complete")
    return report
