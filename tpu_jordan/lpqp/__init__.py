"""LP/QP optimization driver (ISSUE 17, ROADMAP item 4): the
downstream workload the invert → verify → update machinery was built
for.  ``problem`` generates seeded, certificate-carrying LP/QP
instances; ``driver`` runs the optimization inner loops through a
:class:`~..fleet.pool.JordanFleet` as sustained correlated
invert + update + solve traffic; ``demo`` is the ``--lp-demo`` /
``make lp-demo`` acceptance engine."""

from .driver import OptimizeError, OptimizeReport, solve_lp, solve_qp
from .problem import (LPInstance, QPInstance, kkt_converged, kkt_gate,
                      lp_instance, lp_kkt_residual, qp_instance,
                      qp_kkt_residual)

__all__ = [
    "LPInstance", "QPInstance", "lp_instance", "qp_instance",
    "lp_kkt_residual", "qp_kkt_residual", "kkt_gate", "kkt_converged",
    "solve_lp", "solve_qp", "OptimizeReport", "OptimizeError",
]
