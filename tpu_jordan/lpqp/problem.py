"""Seeded LP/QP instance generators + the KKT convergence judge
(ISSUE 17 tentpole, module 1 of the lpqp subsystem).

Every instance is DETERMINISTIC (seeded like every other fixture in
this repo) and carries its own optimality certificate, constructed so
the generated problem's exact solution is known in closed form:

  * **LP** (standard form, ``min cᵀx  s.t.  Ax = b, x ≥ 0``):
    ``A = [G | I]`` with G ≥ 0 and diagonally boosted, so the slack
    basis is the feasible simplex start (``x_slack = b > 0``) and the
    G-columns form the optimal basis.  ``c`` is built from a dual
    certificate (``c_G = Gᵀy``, ``c_slack = y + s`` with ``s > 0``), so
    complementary slackness holds EXACTLY at the constructed vertex —
    ``obj_star`` is the true optimum, not an estimate.
  * **QP** (box-constrained, ``min ½xᵀQx + cᵀx  s.t.  lo ≤ x ≤ hi``):
    Q is SPD (Gram + identity for the well family; geometric column
    scaling before the Gram product for the ill family), and ``c`` is
    reverse-engineered from a chosen ``x_star`` with a chosen active
    set so the KKT conditions hold exactly (free gradient = 0, bound
    multipliers strictly positive).

The convergence judge REUSES the solver's own backward-error gates
(:func:`~..resilience.degrade.gate_threshold` /
:func:`~..resilience.degrade.gate_passes`) — never a looser twin: an
LP/QP iterate "converged" by exactly the expected-error model
(eps·n·κ, NaN-hostile, 0.5-capped) that judges every inverse this
repo serves.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = [
    "LPInstance", "QPInstance", "lp_instance", "qp_instance",
    "lp_kkt_residual", "qp_kkt_residual", "kkt_gate", "kkt_converged",
]

_CONDS = ("well", "ill")


def _check_cond(cond: str) -> str:
    if cond not in _CONDS:
        raise ValueError(f"cond must be one of {_CONDS}, got {cond!r}")
    return cond


@dataclass(frozen=True)
class LPInstance:
    """One standard-form LP: ``min cᵀx  s.t.  Ax = b, x ≥ 0`` with a
    known optimal vertex.  ``basis0`` is the slack basis (the feasible
    simplex start, B = I); ``x_star``/``obj_star`` the constructed
    optimum the driver's result is checked against."""

    name: str
    cond: str
    a: np.ndarray            # (m, n) constraint matrix [G | I]
    b: np.ndarray            # (m,) RHS, strictly positive
    c: np.ndarray            # (n,) objective
    basis0: tuple            # m slack column indices (B = I start)
    x_star: np.ndarray       # (n,) the constructed optimal vertex
    obj_star: float
    m: int
    n: int


@dataclass(frozen=True)
class QPInstance:
    """One box-constrained QP: ``min ½xᵀQx + cᵀx  s.t. lo ≤ x ≤ hi``
    with Q SPD and a known optimum ``x_star`` (active set chosen at
    construction, multiplier signs exact)."""

    name: str
    cond: str
    q: np.ndarray            # (n, n) SPD Hessian
    c: np.ndarray            # (n,)
    lo: np.ndarray           # (n,)
    hi: np.ndarray           # (n,)
    x_star: np.ndarray
    obj_star: float
    n: int


def lp_instance(m: int = 24, seed: int = 0, cond: str = "well",
                dtype=np.float64, ill_decades: float = 4.0) -> LPInstance:
    """Generate one seeded LP (see module docstring for the
    construction).  ``n = 2m`` (m structural + m slack columns).  The
    ill family geometrically scales G's columns over ``ill_decades``
    orders of magnitude, driving the basis matrices the simplex visits
    toward large κ — the drift budget's natural prey."""
    _check_cond(cond)
    if m < 2:
        raise ValueError("m must be >= 2")
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    g = np.abs(rng.standard_normal((m, m))) + m * np.eye(m)
    if cond == "ill":
        g = g * np.power(10.0, -np.linspace(0.0, ill_decades, m))[None, :]
    a = np.concatenate([g, np.eye(m)], axis=1).astype(dtype)
    x_g = 0.5 + rng.random(m)                 # optimal basic values > 0
    b = (g @ x_g).astype(dtype)               # > 0: slack start feasible
    y = rng.standard_normal(m)
    c_g = g.T @ y                             # s_G = 0 (complementarity)
    c_s = y + 0.1 + rng.random(m)             # s_slack > 0 strictly
    c = np.concatenate([c_g, c_s]).astype(dtype)
    x_star = np.concatenate([x_g, np.zeros(m)]).astype(dtype)
    return LPInstance(
        name=f"lp_{cond}_m{m}_s{seed}", cond=cond, a=a, b=b, c=c,
        basis0=tuple(range(m, 2 * m)), x_star=x_star,
        obj_star=float(c @ x_star), m=m, n=2 * m)


def qp_instance(n: int = 24, seed: int = 0, cond: str = "well",
                dtype=np.float64, ill_decades: float = 3.0,
                frac_active: float = 0.4) -> QPInstance:
    """Generate one seeded box QP (see module docstring).  A
    ``frac_active`` fraction of coordinates sits at a bound in the
    constructed optimum (half lo, half hi), the rest strictly
    interior; multipliers are strictly positive so the active set is
    nondegenerate and the driver's termination test is clean."""
    _check_cond(cond)
    if n < 2:
        raise ValueError("n must be >= 2")
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    mfac = rng.standard_normal((n, n))
    if cond == "ill":
        mfac = mfac * np.power(
            10.0, -np.linspace(0.0, ill_decades, n))[None, :]
    q = (mfac.T @ mfac)
    q = q + (1e-6 * np.trace(q) / n + (1.0 if cond == "well" else 0.0)
             ) * np.eye(n)
    q = q.astype(dtype)
    lo = np.zeros(n, dtype)
    hi = np.ones(n, dtype)
    n_act = int(round(frac_active * n))
    idx = rng.permutation(n)
    at_lo = idx[: n_act // 2]
    at_hi = idx[n_act // 2: n_act]
    free = idx[n_act:]
    x_star = np.empty(n, dtype)
    x_star[at_lo] = lo[at_lo]
    x_star[at_hi] = hi[at_hi]
    x_star[free] = 0.2 + 0.6 * rng.random(free.size)
    # Reverse-engineer c from the KKT conditions at x_star: g = Qx + c
    # must vanish on the free set, be strictly positive at lo-active
    # coordinates and strictly negative at hi-active ones.
    g = np.zeros(n, dtype)
    g[at_lo] = 0.1 + rng.random(at_lo.size)
    g[at_hi] = -(0.1 + rng.random(at_hi.size))
    c = (g - q @ x_star).astype(dtype)
    return QPInstance(
        name=f"qp_{cond}_n{n}_s{seed}", cond=cond, q=q, c=c, lo=lo,
        hi=hi, x_star=x_star,
        obj_star=float(0.5 * x_star @ q @ x_star + c @ x_star), n=n)


def lp_kkt_residual(prob: LPInstance, x: np.ndarray,
                    y: np.ndarray) -> float:
    """The scaled KKT residual of an LP iterate (x, y): the max of
    relative primal infeasibility, bound violation, dual infeasibility
    and the duality gap — one number, 0 at an exact optimal pair.
    NaN-propagating on corrupt iterates (the judge is NaN-hostile)."""
    a, b, c = prob.a, prob.b, prob.c
    primal = np.max(np.abs(a @ x - b)) / (1.0 + np.max(np.abs(b)))
    bound = max(0.0, float(-np.min(x))) / (1.0 + np.max(np.abs(x)))
    s = c - a.T @ y
    dual = max(0.0, float(-np.min(s))) / (1.0 + np.max(np.abs(c)))
    cx, by = float(c @ x), float(b @ y)
    gap = abs(cx - by) / (1.0 + abs(cx) + abs(by))
    return float(max(primal, bound, dual, gap))


def qp_kkt_residual(prob: QPInstance, x: np.ndarray,
                    atol: float = 1e-9) -> float:
    """The scaled projected-gradient KKT residual of a QP iterate:
    |g_i| on free coordinates, the one-sided multiplier violation at
    coordinates within ``atol`` of a bound, plus any box violation —
    ∞-norm, scaled by (1 + ‖g‖∞)."""
    g = prob.q @ x + prob.c
    r = np.abs(g)
    at_lo = x <= prob.lo + atol
    at_hi = x >= prob.hi - atol
    r[at_lo] = np.maximum(0.0, -g[at_lo])
    r[at_hi] = np.maximum(0.0, g[at_hi])
    box = max(0.0, float(np.max(prob.lo - x)),
              float(np.max(x - prob.hi)))
    return float((np.max(r) + box) / (1.0 + np.max(np.abs(g))))


def kkt_gate(policy, n: int, kappa: float, dtype) -> float:
    """The LP/QP convergence threshold IS the solver's own residual
    gate — :func:`~..resilience.degrade.gate_threshold`'s eps·n·κ
    expected-error model (gate_tol-widened, 0.5-capped), evaluated at
    the KKT system's size and the driver's latest verified κ.  Reusing
    the gate (never a looser twin) means "converged" and "this inverse
    is trustworthy" are judged by one model."""
    from ..resilience.degrade import gate_threshold

    return gate_threshold(policy, n, kappa, dtype)


def kkt_converged(kkt_rel: float, threshold: float) -> bool:
    """NaN-hostile convergence test — literally the solver's
    :func:`~..resilience.degrade.gate_passes`."""
    from ..resilience.degrade import gate_passes

    return gate_passes(kkt_rel, threshold)
