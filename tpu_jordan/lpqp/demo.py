"""``lp_demo`` — the ``--lp-demo`` CLI mode's engine (ISSUE 17
acceptance).

One self-contained run proves the LP/QP optimization driver contract
end to end, in four legs sharing ONE fleet-shared executor store:

  1. **driver convergence** — a warmed :class:`~..fleet.JordanFleet`
     serves four driver runs (LP well/ill, QP well/ill): each is one
     ``invert(resident=True)`` plus a sustained correlated stream of
     rank-k ``update`` + verification ``solve`` requests.  Pins: ZERO
     compiles and ZERO plan-cache measurements after warmup, every
     update accounted ``refreshed | re_inverted | gated``, and
     convergence judged by the solver's OWN eps·n·κ gate
     (``tools/check_lp.py`` re-derives it from the report's iterate
     residuals — exit 2 = silent divergence).
  2. **drift-budget probe** — the same LP through a fleet with a ZERO
     drift budget: every update trips the ``re_invert`` rung
     deterministically and the driver must still converge on the
     recovered inverses (the degradation ladder under optimization
     traffic).
  3. **fleet chaos** — the same LP twice through an N-replica fleet:
     fault-free (the replay baseline), then under a seeded
     ``replica_kill`` schedule.  Resident handles live in the
     fleet-shared store, so every per-iteration outcome tuple AND the
     final solution fingerprint must bit-match the fault-free replay.
  4. **batched update lanes** — ``batch_cap`` distinct resident
     handles stream updates through the vmapped batched update lane
     (ISSUE 17 tentpole): warm per-update latency at measured
     occupancy > 1 must beat the one-per-launch path, with the same
     zero-compile pin held across the measurement.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from ..obs.metrics import REGISTRY
from ..resilience import FaultPlan, ResiliencePolicy
from ..resilience import activate as _activate
from ..resilience.policy import RetryPolicy
from ..serve.executors import ExecutorStore, bucket_for, k_bucket_for
from .driver import OptimizeError, solve_lp, solve_qp
from .problem import lp_instance, qp_instance


def _counters():
    c = REGISTRY.counter
    return {
        "compiles": c("tpu_jordan_compiles_total").total(),
        "measurements": c("tpu_jordan_tuner_measurements_total").total(),
        "rungs": c("tpu_jordan_recovery_rungs_total").total(),
        "deaths": c("tpu_jordan_fleet_replica_deaths_total").total(),
        "restarts": c("tpu_jordan_fleet_restarts_total").total(),
        "reroutes": c("tpu_jordan_fleet_reroutes_total").total(),
        "faults": c("tpu_jordan_faults_injected_total").total(),
    }


def _median(samples):
    s = sorted(samples)
    return s[len(s) // 2] if s else None


def _iterate_trace(report: dict) -> list:
    """The chaos bit-compare token stream: one tuple per iteration —
    the fleet-judged outcome, the committed handle version, and the
    EXACT bits of the KKT residual (float hex)."""
    return [[r.get("outcome"), r.get("version"), r["kkt_hex"]]
            for r in report["iterates"]]


def _run_leg(fleet, prob, kind):
    """One driver run folded to its report dict; a typed driver
    failure becomes a non-converged report carrying the error (the
    checker treats it as divergence, never a crash)."""
    solver = solve_lp if kind == "lp" else solve_qp
    try:
        return solver(prob, fleet).to_dict(), None
    except OptimizeError as e:
        rep = (e.report.to_dict() if e.report is not None
               else {"converged": False, "iterates": [], "ledger": {},
                     "updates": 0, "solves": 0})
        return rep, f"{type(e).__name__}: {e}"


def lp_demo(n: int = 16, block_size: int | None = None, seed: int = 0,
            replicas: int = 3, kills: int = 1, batch_cap: int = 4,
            dtype=jnp.float64, telemetry=None) -> dict:
    """Run the four-leg LP/QP driver acceptance demo; returns the
    one-line JSON report ``tools/check_lp.py`` validates (exit 2 =
    silent divergence)."""
    t0 = time.perf_counter()
    dtype = jnp.dtype(dtype)
    if n < 4:
        raise ValueError("lp_demo needs n >= 4")
    if batch_cap < 2:
        raise ValueError("lp_demo needs batch_cap >= 2 (the batched "
                         "amortization leg measures occupancy > 1)")
    store = ExecutorStore()
    policy = ResiliencePolicy(
        retry=RetryPolicy(max_retries=max(4, kills + 2), backoff_s=0.0))
    np_dtype = np.dtype(dtype.name)
    fleet_kw = dict(engine="auto", dtype=dtype, batch_cap=1,
                    max_wait_ms=0.5, block_size=block_size,
                    policy=policy, executor_store=store,
                    stable_after_s=0.2, liveness_deadline_s=5.0,
                    telemetry=telemetry)
    probs = {
        "lp_well": ("lp", lp_instance(m=n, seed=seed, cond="well",
                                      dtype=np_dtype)),
        "lp_ill": ("lp", lp_instance(m=n, seed=seed, cond="ill",
                                     dtype=np_dtype)),
        "qp_well": ("qp", qp_instance(n=n, seed=seed, cond="well",
                                      dtype=np_dtype)),
        "qp_ill": ("qp", qp_instance(n=n, seed=seed, cond="ill",
                                     dtype=np_dtype)),
    }
    warm_kw = dict(update_shapes=[(n, 1), (n, 2)],
                   solve_shapes=[(n, 1)])
    errors: list[str] = []

    # ---- leg 1: the four driver runs through one warmed fleet -------
    from ..fleet import JordanFleet

    legs = {}
    with JordanFleet(replicas=replicas, **fleet_kw) as fleet:
        fleet.warmup([n], **warm_kw)
        after_warm = _counters()
        for name, (kind, prob) in probs.items():
            legs[name], err = _run_leg(fleet, prob, kind)
            if err:
                errors.append(f"{name}: {err}")
        fleet_stats = fleet.stats()
    leg1 = _counters()

    # ---- leg 2: zero drift budget -> every update rides re_invert ---
    with JordanFleet(replicas=min(2, replicas),
                     update_drift_budget_factor=0.0,
                     **fleet_kw) as dfleet:
        dfleet.warmup([n], **warm_kw)
        drift_rep, err = _run_leg(dfleet, probs["lp_well"][1], "lp")
        if err:
            errors.append(f"drift_probe: {err}")
    leg2 = _counters()

    # ---- leg 3: chaos vs fault-free replay --------------------------
    chaos_prob = probs["lp_ill"][1]
    before_base = leg2
    with JordanFleet(replicas=replicas, **fleet_kw) as bfleet:
        bfleet.warmup([n], **warm_kw)
        base_rep, err = _run_leg(bfleet, chaos_prob, "lp")
        if err:
            errors.append(f"chaos_baseline: {err}")
    after_base = _counters()
    horizon = max(3, 2 * n)
    plan = FaultPlan.seeded(seed,
                            points={"replica_kill": (kills, horizon)})
    with JordanFleet(replicas=replicas, **fleet_kw) as cfleet:
        cfleet.warmup([n], **warm_kw)
        chaos_warm = _counters()
        with _activate(plan):
            chaos_rep, err = _run_leg(cfleet, chaos_prob, "lp")
        if err:
            errors.append(f"chaos: {err}")
    after_chaos = _counters()

    base_trace = _iterate_trace(base_rep)
    chaos_trace = _iterate_trace(chaos_rep)
    mismatches = []
    matched = 0
    for i, (bt, ct) in enumerate(zip(base_trace, chaos_trace)):
        if bt == ct:
            matched += 1
        else:
            mismatches.append({"iterate": i, "why": (
                f"outcome diverged from the fault-free replay: "
                f"{bt} vs {ct}")})
    if len(base_trace) != len(chaos_trace):
        mismatches.append({"iterate": "length", "why": (
            f"iteration counts diverged: {len(base_trace)} fault-free "
            f"vs {len(chaos_trace)} under chaos")})
    fp_match = (base_rep.get("fingerprint") == chaos_rep.get("fingerprint")
                and bool(base_rep.get("fingerprint")))
    if not fp_match:
        mismatches.append({"iterate": "final", "why": (
            "final solution fingerprint diverged from the fault-free "
            "replay")})

    # ---- leg 4: batched update lanes (the tentpole measurement) -----
    from ..serve.service import JordanService

    rng = np.random.default_rng(seed + 1)
    scale = 1.0 / np.sqrt(float(n))
    seq_lat, batched_lat, occs = [], [], []
    rounds = 5
    with JordanService(engine="auto", dtype=dtype, batch_cap=batch_cap,
                       max_wait_ms=25.0, block_size=block_size,
                       policy=policy, shared_executors=store,
                       telemetry=telemetry) as svc:
        svc.warmup(update_shapes=[(n, 1)])
        refs = []
        for i in range(batch_cap):
            a_i = (rng.standard_normal((n, n))
                   + n * np.eye(n)).astype(np_dtype)
            refs.append(svc.invert(a_i, resident=True,
                                   handle_id=f"amort-{i}", timeout=600))
        muts = [(rng.standard_normal((n, 1)).astype(np_dtype) * scale,
                 rng.standard_normal((n, 1)).astype(np_dtype) * scale)
                for _ in range(batch_cap)]
        amort_before = _counters()
        for _ in range(rounds):
            # One-per-launch baseline: strictly sequential, each
            # update is its own cap-1 launch (occupancy 1).
            for ref, (u, v) in zip(refs, muts):
                res = svc.update(ref, u, v, timeout=600)
                seq_lat.append(res.execute_seconds)
            # Batched: one update per DISTINCT handle submitted
            # together — the batcher fuses them into one vmapped
            # launch; per-update cost is the launch amortized over
            # the measured occupancy.
            futs = [svc.submit_update(ref, u, v)
                    for ref, (u, v) in zip(refs, muts)]
            results = [f.result(600) for f in futs]
            occs.append(max(r.batch_occupancy for r in results))
            batched_lat.extend(r.execute_seconds / r.batch_occupancy
                               for r in results)
        amort_after = _counters()
    occupancy = max(occs) if occs else 0
    seq_ms = _median(seq_lat) * 1e3 if seq_lat else None
    amort_ms = _median(batched_lat) * 1e3 if batched_lat else None
    speedup = (round(seq_ms / amort_ms, 3)
               if seq_ms and amort_ms else None)

    # ---- the silent-divergence verdict ------------------------------
    def _accounted(rep):
        led = rep.get("ledger", {})
        return sum(led.values()) == rep.get("updates", -1)

    pins_ok = (leg1["compiles"] - after_warm["compiles"] == 0
               and leg1["measurements"] - after_warm["measurements"] == 0
               and after_chaos["compiles"] - chaos_warm["compiles"] == 0
               and amort_after["compiles"] - amort_before["compiles"] == 0)
    drift_rungs = leg2["rungs"] - leg1["rungs"]
    drift_ok = (drift_rep.get("converged", False)
                and drift_rep.get("ledger", {}).get("re_inverted", 0)
                == drift_rep.get("updates", -1))
    silent = (bool(errors) or bool(mismatches)
              or not all(r.get("converged") for r in legs.values())
              or not all(_accounted(r) for r in legs.values())
              or not _accounted(drift_rep) or not _accounted(chaos_rep)
              or not drift_ok or not pins_ok
              or occupancy <= 1
              or not (speedup is not None and speedup > 1.0)
              or fleet_stats["ledger"]["outstanding"] != 0)

    report = {
        "metric": "lp_demo",
        "n": n, "seed": seed, "replicas": replicas, "kills": kills,
        "batch_cap": batch_cap, "dtype": dtype.name,
        "bucket_n": bucket_for(n),
        "k_buckets": [k_bucket_for(1), k_bucket_for(2)],
        "legs": legs,
        "compiles_after_warmup": leg1["compiles"] - after_warm["compiles"],
        "measurements_after_warmup": (leg1["measurements"]
                                      - after_warm["measurements"]),
        "drift_probe": {
            "forced_budget_factor": 0.0,
            "converged": bool(drift_rep.get("converged", False)),
            "ledger": drift_rep.get("ledger", {}),
            "updates": drift_rep.get("updates", 0),
            "rungs_fired": drift_rungs,
            "kkt_rel_final": drift_rep.get("kkt_rel_final"),
            "kkt_threshold": drift_rep.get("kkt_threshold"),
        },
        "chaos": {
            "faults": plan.report(),
            "kills_injected": int(after_chaos["faults"]
                                  - after_base["faults"]),
            "deaths": after_chaos["deaths"] - after_base["deaths"],
            "restarts": after_chaos["restarts"] - after_base["restarts"],
            "reroutes": after_chaos["reroutes"] - after_base["reroutes"],
            "compiles_delta_after_warmup": (after_chaos["compiles"]
                                            - chaos_warm["compiles"]),
            "ledger": chaos_rep.get("ledger", {}),
            "converged": bool(chaos_rep.get("converged", False)),
            "baseline_fingerprint": base_rep.get("fingerprint", ""),
            "chaos_fingerprint": chaos_rep.get("fingerprint", ""),
            "fingerprint_bitmatch": bool(fp_match),
            "iterates_matched": matched,
            "iterates_total": len(base_trace),
        },
        "batched": {
            "batch_cap": batch_cap,
            "rounds": rounds,
            "occupancy": int(occupancy),
            "warm_one_per_launch_ms": (round(seq_ms, 4)
                                       if seq_ms else None),
            "warm_batched_amortized_ms": (round(amort_ms, 4)
                                          if amort_ms else None),
            "speedup_x": speedup,
            "amortized_beats_one_per_launch": bool(
                speedup is not None and speedup > 1.0),
            "compiles_delta": (amort_after["compiles"]
                               - amort_before["compiles"]),
        },
        "errors": errors,
        "mismatches": mismatches,
        "fleet_ledger": fleet_stats["ledger"],
        "silent_divergence": bool(silent),
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
    return report
