"""CPU (interpret-mode) parity tests for the pallas pivot-probe kernel.

The kernel (ops/pallas_block_inverse.py) is the production probe on TPU;
these tests pin its semantics against the reference XLA implementation
(ops/block_inverse.py::batched_block_inverse with per-block scaling) so a
Mosaic regression can't silently change pivot choices on hardware.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_jordan.ops.block_inverse import batched_block_inverse
from tpu_jordan.ops import pallas_block_inverse as pbi
from tpu_jordan.ops.pallas_block_inverse import pallas_batched_block_inverse


# All kernels must keep identical pivot/singularity/poison semantics:
# "dispatch" resolves to the production kernel (the fused in-place panel
# for m % 128 == 0 within budget, rank-1 otherwise — pinned by
# test_dispatch_policy), "rank1"/"fused" force those two, "panel" and
# "inplace" are the recorded v2/v3 experiments.
KERNELS = {
    "dispatch": pallas_batched_block_inverse,
    "rank1": pbi.pallas_batched_block_inverse_rank1,
    "fused": pbi.pallas_batched_block_inverse_fused,
    "panel": pbi.pallas_batched_block_inverse_panel,
    "inplace": pbi.pallas_batched_block_inverse_inplace,
}

# tier-1 budget: the "panel" v2 experiment is the costliest interpreted
# kernel and runs nightly; the production "dispatch"/"rank1"/"fused"
# variants (and the "inplace" v3 experiment) keep the fast-run parity.
KERNEL_PARAMS = [
    pytest.param(k, marks=pytest.mark.slow) if k == "panel" else k
    for k in KERNELS
]


def _check_parity(blocks_np, eps=None, atol=2e-5, kernel="dispatch",
                  rtol=None):
    # The rank-1 kernel replays the XLA reference's arithmetic order
    # exactly, so their rounding errors correlate and the diff stays tiny
    # even for ill-conditioned blocks.  The fused/panel kernels sum the
    # same updates in a different (MXU-deferred) order: each inverse is
    # equally accurate (verified by per-block residuals), but the errors
    # decorrelate, so the cross-kernel diff scales with eps*cond and the
    # tolerance must be looser.
    if rtol is None:
        rtol = 2e-3 if kernel in ("fused", "panel", "dispatch") else 2e-4
        atol = max(atol, 1e-3) if rtol == 2e-3 else atol
    blocks = jnp.asarray(blocks_np, jnp.float32)
    inv_p, sing_p = KERNELS[kernel](blocks, eps, interpret=True)
    inv_x, sing_x = batched_block_inverse(blocks, None, eps)
    np.testing.assert_array_equal(np.asarray(sing_p), np.asarray(sing_x))
    ok = ~np.asarray(sing_x)
    if ok.any():
        np.testing.assert_allclose(
            np.asarray(inv_p)[ok], np.asarray(inv_x)[ok],
            rtol=rtol, atol=atol,
        )
    return np.asarray(sing_p)


@pytest.mark.parametrize("kernel", KERNEL_PARAMS)
def test_random_stack_matches_xla(rng, kernel):
    blocks = rng.standard_normal((6, 32, 32))
    sing = _check_parity(blocks, kernel=kernel)
    assert not sing.any()


@pytest.mark.parametrize("kernel", KERNEL_PARAMS)
def test_singular_and_zero_diagonal_blocks(rng, kernel):
    m = 32
    blocks = rng.standard_normal((5, m, m))
    # Exactly singular: duplicate row.
    blocks[1, 3] = blocks[1, 7]
    # Rank-1 block.
    u = rng.standard_normal(m)
    blocks[2] = np.outer(u, u)
    # Zero diagonal but invertible (the |i-j| fixture's structure): needs
    # the inner partial pivoting to work at all.
    i = np.arange(m)
    blocks[3] = np.abs(i[:, None] - i[None, :]).astype(float)
    # All-zero block: degenerate scale.
    blocks[4] = 0.0
    sing = _check_parity(blocks, kernel=kernel)
    assert not sing[0] and not sing[3]
    assert sing[1] and sing[2] and sing[4]


@pytest.mark.parametrize("kernel", KERNEL_PARAMS)
def test_poison_path_flags_do_not_leak(rng, kernel):
    # A singular block next to healthy ones: the non-finite poison must be
    # confined to its own block.
    blocks = rng.standard_normal((4, 32, 32))
    blocks[2] = 1.0  # rank 1
    blocks_j = jnp.asarray(blocks, jnp.float32)
    inv, sing = KERNELS[kernel](blocks_j, interpret=True)
    assert list(np.asarray(sing)) == [False, False, True, False]
    assert np.isfinite(np.asarray(inv)[[0, 1, 3]]).all()


@pytest.mark.parametrize("kernel", KERNEL_PARAMS)
def test_chunked_grid(monkeypatch, rng, kernel):
    # Shrink the VMEM budgets (both: the dispatch path resolves to the
    # panel kernel and its budget, the forced path to the rank-1 budget)
    # so the grid must split the stack into chunks (cg < num_blocks),
    # exercising _chunk_candidates' divisor logic and the per-chunk
    # BlockSpec indexing.
    monkeypatch.setattr(pbi, "_W_BUDGET", 2 * 32 * 64 * 4)   # 2 cands/chunk
    monkeypatch.setattr(pbi, "_W_BUDGET_PANEL", 2 * 32 * 64 * 4)
    # The budgets are read at trace time: drop any executable cached by an
    # earlier test with the same shapes or the patch is a no-op.
    jax.clear_caches()
    assert pbi._chunk_candidates(6, 32) == 2
    blocks = rng.standard_normal((6, 32, 32))
    blocks[4, 0] = blocks[4, 1]          # one singular block mid-stack
    sing = _check_parity(blocks, kernel=kernel)
    assert list(sing) == [False, False, False, False, True, False]


def test_chunk_candidates_divisor_property():
    for nb in (1, 2, 3, 5, 7, 12, 16, 48):
        for m in (8, 32, 128, 256):
            cg = pbi._chunk_candidates(nb, m)
            assert 1 <= cg <= nb and nb % cg == 0
            assert cg * m * 2 * m * 4 <= pbi._W_BUDGET or cg == 1


# The production-size parity tier re-lists the kernels with the panel
# (v2) and inplace (v3) experiments slow-marked: both are recorded
# NON-dispatched experiments (measured slower everywhere, module
# docstring) — the production-size duplicates are nightly-only (the
# 870 s rule, ISSUE 6 budget pass).  The m=32 tier above keeps the
# inplace experiment fast-run; panel (the costliest interpreted
# kernel) is nightly at every size.
KERNELS_PROD = ["dispatch", "rank1", "fused",
                pytest.param("panel", marks=pytest.mark.slow),
                pytest.param("inplace", marks=pytest.mark.slow)]


class TestProductionSizeParity:
    """Parity of every kernel with the XLA reference at production block
    sizes (m=64/128); the small-m tests above use m=32."""

    @pytest.mark.parametrize("m", [
        # tier-1 headroom (ISSUE 3): m=64 is below the production
        # fused-panel sizes (128/256/384) — nightly only.
        pytest.param(64, marks=pytest.mark.slow), 128])
    @pytest.mark.parametrize("kernel", KERNELS_PROD)
    def test_matches_xla(self, rng, m, kernel):
        blocks = rng.standard_normal((4, m, m))
        sing = _check_parity(blocks, kernel=kernel)
        assert not sing.any()

    @pytest.mark.parametrize("kernel", [
        "rank1", pytest.param("panel", marks=pytest.mark.slow),
        pytest.param("inplace", marks=pytest.mark.slow), "fused"])
    def test_matches_dispatch_kernel(self, rng, kernel):
        m = 64
        blocks = jnp.asarray(rng.standard_normal((4, m, m)), jnp.float32)
        inv_p, sing_p = pallas_batched_block_inverse(
            blocks, interpret=True
        )
        inv_r, sing_r = KERNELS[kernel](blocks, interpret=True)
        np.testing.assert_array_equal(np.asarray(sing_p),
                                      np.asarray(sing_r))
        # Decorrelated rounding between summation orders (see
        # _check_parity) — flags exact, values within eps*cond.
        np.testing.assert_allclose(np.asarray(inv_p), np.asarray(inv_r),
                                   rtol=2e-3, atol=1e-3)

    @pytest.mark.parametrize("kernel", KERNELS_PROD)
    def test_singular_flags_and_zero_diag(self, rng, kernel):
        m = 64
        blocks = rng.standard_normal((4, m, m))
        blocks[1, 5] = blocks[1, 9]          # duplicate row -> singular
        i = np.arange(m)
        blocks[2] = np.abs(i[:, None] - i[None, :]).astype(float)
        blocks[3] = 0.0
        # The panel kernel's deferred update sums in a different order
        # than the sequential paths; O(m)-magnitude entries cancel to
        # near zero, so the absolute floor is a little higher at m=64.
        sing = _check_parity(blocks, atol=1e-4, kernel=kernel)
        assert list(sing) == [False, True, False, True]

    def test_panel_width_selection(self):
        assert pbi._panel_width(256) == 32
        assert pbi._panel_width(48) == 16
        assert pbi._panel_width(40) == 8
        assert pbi._panel_width(8) is None    # m == b: no split possible
        assert pbi._panel_width(12) is None
        with pytest.raises(ValueError, match="panel width"):
            pbi.pallas_batched_block_inverse_panel(
                jnp.eye(12, dtype=jnp.float32)[None], interpret=True)


def test_probe_pivot_ordering_matches(rng):
    # The pivot *choice* downstream depends on the inverse norms; equal
    # norms must come out close enough that argmin ordering is stable.
    blocks = rng.standard_normal((8, 32, 32))
    blocks_j = jnp.asarray(blocks, jnp.float32)
    inv_p, _ = pallas_batched_block_inverse(blocks_j, interpret=True)
    inv_x, _ = batched_block_inverse(blocks_j, None, None)
    norms_p = np.max(np.sum(np.abs(np.asarray(inv_p)), axis=2), axis=1)
    norms_x = np.max(np.sum(np.abs(np.asarray(inv_x)), axis=2), axis=1)
    assert np.argmin(norms_p) == np.argmin(norms_x)


def test_max_grid_launch_split_matches_single_launch(monkeypatch, rng):
    # Oversized stacks are split into a lax.map over <= cg*_MAX_GRID
    # candidate chunks (ADVICE r4: the split path had no regression
    # test).  Shrinking BOTH the budget (cg=8 per chunk) and _MAX_GRID
    # (1 chunk per launch) forces a genuine 3-launch split on a 24-stack;
    # the result must be bitwise identical to the unsplit launch.
    m = 32
    blocks = jnp.asarray(rng.standard_normal((24, m, m)), jnp.float32)
    inv_one, sing_one = pallas_batched_block_inverse(blocks, interpret=True)
    try:
        monkeypatch.setattr(pbi, "_W_BUDGET", 8 * m * 2 * m * 4)  # cg=8
        monkeypatch.setattr(pbi, "_MAX_GRID", 1)
        jax.clear_caches()
        # The split must actually engage: per-launch capacity < stack.
        assert pbi._chunk_candidates(24, m) * pbi._MAX_GRID < 24
        inv_split, sing_split = pallas_batched_block_inverse(
            blocks, interpret=True)
        np.testing.assert_array_equal(np.asarray(sing_one),
                                      np.asarray(sing_split))
        np.testing.assert_array_equal(np.asarray(inv_one),
                                      np.asarray(inv_split))
    finally:
        # Executables traced with the patched constants must not leak
        # into later same-signature calls.
        jax.clear_caches()


def test_fused_kernel_hc2_matches_reference(monkeypatch, rng):
    # The hc>1 chunked deferred-stage path of the fused kernel only
    # engages at m >= 512 in production (_fused_hc), where the fused
    # kernel doesn't currently compile — so nothing exercised it (ADVICE
    # r4).  Force hc=2 at m=128 and pin parity with the XLA reference.
    try:
        monkeypatch.setattr(pbi, "_fused_hc", lambda m: 2)
        jax.clear_caches()
        blocks = rng.standard_normal((4, 128, 128))
        blocks[2, 3] = blocks[2, 11]     # one singular block mid-stack
        sing = _check_parity(blocks, kernel="fused")
        assert list(sing) == [False, False, True, False]
    finally:
        jax.clear_caches()


@pytest.mark.slow  # tier-1 budget: the per-kernel dispatch/parity siblings stay
def test_dispatch_policy(monkeypatch):
    # Pin WHICH kernel each block size dispatches to, so a future budget
    # or gate change is deliberate: fused needs a panel width, m % 128
    # == 0, and >= 2 candidates in the stack budget (PHASES.md).
    seen = {}
    orig = pbi._run_probe_kernel

    def spy(blocks, kernel, m, interpret, budget=None, width_factor=2):
        seen[m] = kernel.func.__name__
        return orig(blocks, kernel, m, interpret, budget, width_factor)

    monkeypatch.setattr(pbi, "_run_probe_kernel", spy)
    jax.clear_caches()
    for m in (32, 64, 128, 256, 384, 512):
        blocks = jnp.eye(m, dtype=jnp.float32)[None]
        pallas_batched_block_inverse(blocks, interpret=True)
    assert seen[32] == "_gj_probe_kernel"      # m % 128 != 0
    assert seen[64] == "_gj_probe_kernel"
    assert seen[128] == "_gj_fused_panel_kernel"
    assert seen[256] == "_gj_fused_panel_kernel"
    assert seen[384] == "_gj_fused_panel_kernel"
    assert seen[512] == "_gj_probe_kernel"     # only cg=1 fits VMEM
