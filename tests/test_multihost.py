"""Multi-process execution of the distributed solve (VERDICT r2 #7).

``jax.distributed.initialize`` (parallel/mesh.py::distributed_init — the
MPI_Init analog, main.cpp:69) has to be exercised for real, not just
wired: two OS processes with 4 virtual CPU devices each form one
8-device mesh, and both the 1D and 2D sharded solves run end-to-end with
collectives crossing the process boundary — the TPU-native equivalent of
``mpirun -np 2``.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_solve(tmp_path):
    import numpy as np

    from tpu_jordan.io import write_matrix_file

    rng = np.random.default_rng(3)
    mat_path = str(tmp_path / "m64.txt")
    write_matrix_file(mat_path, rng.standard_normal((64, 64)))

    port = _free_port()
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = _REPO
    nproc = 2
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(nproc), str(port),
             mat_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=_REPO,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        # Stock jax 0.4.x CPU backend cannot run cross-process
        # collectives (the jax_graft toolchain's jax can); the mesh
        # formed and the program compiled — the capability gap is the
        # backend's, not the solver's.  Gate, don't fail: any OTHER
        # worker error still fails below.
        pytest.skip("CPU backend lacks multiprocess collectives "
                    "(stock jax 0.4.x)")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out}"
        assert "MULTIHOST-OK" in out, f"rank {i} output:\n{out}"
