"""Mesh-backed serve lanes (ISSUE 18 tentpole part 1): the topology
vocabulary (one spelling shared with the tuner's ``TunePoint``), the
typed-refusal contract (complex/SPD/update/resident are single-device
promises — a mesh lane refuses them naming the legal alternative,
never a silent single-device fallback), the byte-projected admission
walk (single if it fits, else the smallest mesh whose PER-DEVICE share
fits, else a typed ``CapacityExceededError`` AT SUBMIT), capacity
projection without compiling, and the smoke-tier warm round-trip: a
request over the single-device budget serves through the 2-device lane
with ZERO compiles and ZERO plan-cache measurements after warmup,
journey-hopped ``mesh_admitted`` with the projection that admitted
it."""

import types

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.driver import UsageError
from tpu_jordan.obs.recorder import RECORDER
from tpu_jordan.resilience.policy import CapacityExceededError
from tpu_jordan.serve import JordanService, bucket_for
from tpu_jordan.serve.executors import (ExecutorKey, lane_label,
                                        projected_lane_bytes,
                                        rhs_bucket_for)
from tpu_jordan.serve.meshlanes import (MESH_SINGLE, MeshLaneExecutor,
                                        mesh_devices, mesh_label,
                                        normalize_mesh, parse_mesh)

F32 = jnp.float32


def _mesh_key(**kw):
    """An ExecutorKey on the 2-device mesh with the refusal under test
    overriding one coordinate (refusals fire before any compile)."""
    base = dict(bucket_n=64, batch_cap=1, dtype="float32",
                engine="inplace", block_size=16, workload="invert",
                rhs=0, mesh="p2")
    base.update(kw)
    return ExecutorKey(**base)


class TestMeshVocabulary:
    def test_one_spelling_label_roundtrip(self):
        assert mesh_label(8) == "p8"
        assert mesh_label((2, 4)) == "2x4"
        assert mesh_label(1) == MESH_SINGLE
        assert parse_mesh("p8") == 8
        assert parse_mesh("2x4") == (2, 4)
        assert parse_mesh(MESH_SINGLE) == 1
        assert mesh_devices((2, 4)) == 8

    def test_malformed_label_is_typed(self):
        with pytest.raises(UsageError, match="not a topology label"):
            parse_mesh("8x")
        with pytest.raises(UsageError, match="not a topology label"):
            parse_mesh("fast")

    def test_unformable_mesh_is_typed_at_configure_time(self):
        # conftest pins exactly 8 host devices: 16 cannot form.
        with pytest.raises(UsageError, match="needs 16 devices"):
            normalize_mesh(16)
        with pytest.raises(UsageError, match="needs 16 devices"):
            normalize_mesh((4, 4))
        with pytest.raises(UsageError,
                           match="single-device lane"):
            normalize_mesh(1)
        with pytest.raises(UsageError, match="positive"):
            normalize_mesh((0, 2))

    def test_per_device_projection_divides_matrix_terms_only(self):
        single = projected_lane_bytes(64, 1, F32)
        halved = projected_lane_bytes(64, 1, F32, devices=2)
        assert halved < single
        # Solve lanes: the O(n·k) RHS/solution terms stay whole (X
        # gathers), so the mesh saving is strictly the matrix share.
        s1 = projected_lane_bytes(64, 1, F32, "solve", rhs=8)
        s2 = projected_lane_bytes(64, 1, F32, "solve", rhs=8,
                                  devices=2)
        assert s1 - s2 == (projected_lane_bytes(64, 1, F32)
                           - halved) // 2


class TestTypedRefusals:
    """The single-device contracts a mesh lane must refuse BY NAME —
    never serve silently on one device (the caller asked for a
    topology) and never crash mid-launch."""

    def test_complex_dtype_refused_naming_single_lane(self):
        with pytest.raises(UsageError, match="complex dtypes run "
                                             "single-device"):
            MeshLaneExecutor(_mesh_key(dtype="complex64"), None)

    def test_spd_fast_path_refused_naming_alternatives(self):
        with pytest.raises(UsageError, match="pivot-free fast\\s+path"):
            MeshLaneExecutor(_mesh_key(workload="solve", rhs=8,
                                       engine="solve_spd"), None)

    def test_update_workload_refused_single_chip(self):
        with pytest.raises(UsageError, match="single-chip"):
            MeshLaneExecutor(_mesh_key(workload="update", rhs=4), None)

    def test_batched_mesh_lane_refused_occupancy_one(self):
        with pytest.raises(UsageError, match="occupancy 1"):
            MeshLaneExecutor(_mesh_key(batch_cap=2), None)

    def test_single_device_solve_engine_refused(self):
        with pytest.raises(UsageError, match="single-device solve\\s+"
                                             "engine"):
            MeshLaneExecutor(_mesh_key(workload="solve", rhs=8,
                                       engine="lookahead"), None)

    def test_mesh_shapes_without_budget_is_typed(self):
        with pytest.raises(UsageError,
                           match="mesh_shapes without "
                                 "lane_budget_bytes"):
            JordanService(dtype=F32, mesh_shapes=(2,))

    def test_resident_invert_refused_on_mesh_route(self):
        # Budget under the 64-bucket's single projection: a resident
        # invert would route to the mesh, where handles cannot live.
        budget = projected_lane_bytes(64, 4, F32) - 1
        with JordanService(dtype=F32, batch_cap=4, mesh_shapes=(2,),
                           lane_budget_bytes=budget,
                           autostart=False) as svc:
            with pytest.raises(UsageError,
                               match="resident=True pins"):
                svc.invert(np.eye(64, dtype=np.float32),
                           resident=True)


class _Ctx:
    """A journey-hop recorder stub for driving the admission walk."""

    def __init__(self):
        self.events = []

    def event(self, name, **fields):
        self.events.append((name, fields))


class TestCapacityAdmission:
    def test_admission_walk_single_then_mesh_then_refusal(self):
        """The submit-time walk on one service: a bucket whose single
        projection fits stays single; one that doesn't but whose
        per-device share fits goes to the smallest mesh (with the
        ``mesh_admitted`` hop carrying the projection); one no mesh
        can hold is a typed refusal AT SUBMIT."""
        cap = 4
        budget = (projected_lane_bytes(64, cap, F32)
                  + projected_lane_bytes(128, cap, F32)) // 2
        assert projected_lane_bytes(128, 1, F32, devices=2) <= budget
        with JordanService(dtype=F32, batch_cap=cap, mesh_shapes=(2,),
                           lane_budget_bytes=budget,
                           autostart=False) as svc:
            ctx = _Ctx()
            assert svc._admit_mesh(64, 64, "invert", 0,
                                   ctx) == MESH_SINGLE
            assert ctx.events == []
            assert svc._admit_mesh(128, 128, "invert", 0, ctx) == "p2"
            name, fields = ctx.events[-1]
            assert name == "mesh_admitted" and fields["mesh"] == "p2"
            assert fields["projected_bytes"] <= budget
            assert fields["single_device_bytes"] > budget
            mark = RECORDER.total
            with pytest.raises(CapacityExceededError,
                               match="refused at submit, never an "
                                     "OOM mid-launch"):
                svc._admit_mesh(2048, 2048, "invert", 0, ctx)
            assert ctx.events[-1][0] == "reject"
            assert ctx.events[-1][1]["reason"] == "capacity"
            assert any(e.get("kind") == "capacity_refused"
                       for e in RECORDER.since(mark))

    def test_over_budget_without_mesh_names_the_gap(self):
        """No mesh_shapes configured: the refusal says so (the
        operator's fix is a config line, and the error names it)."""
        with JordanService(dtype=F32, batch_cap=4,
                           lane_budget_bytes=4096,
                           autostart=False) as svc:
            with pytest.raises(CapacityExceededError,
                               match="no mesh_shapes configured"):
                svc.submit(np.eye(64, dtype=np.float32))

    def test_too_big_for_largest_mesh_names_it(self):
        budget = projected_lane_bytes(64, 1, F32, devices=2) - 1
        with JordanService(dtype=F32, batch_cap=4, mesh_shapes=(2,),
                           lane_budget_bytes=budget,
                           autostart=False) as svc:
            with pytest.raises(CapacityExceededError,
                               match="largest configured mesh "
                                     "\\('p2'\\)"):
                svc.submit(np.eye(64, dtype=np.float32))

    def test_project_capacity_mesh_entries_without_compiling(self):
        budget = projected_lane_bytes(512, 4, F32)
        with JordanService(dtype=F32, batch_cap=4,
                           mesh_shapes=(2, (2, 2)),
                           lane_budget_bytes=budget,
                           autostart=False) as svc:
            out = svc.project_capacity(shapes=(64,),
                                       mesh_shapes=[(64, 2),
                                                    (64, 8, "2x2")])
            inv_lane = lane_label("invert", 64, 1, mesh="p2")
            slv_lane = lane_label("solve", 64, 1, rhs_bucket_for(8),
                                  mesh="2x2")
            assert out[inv_lane] == projected_lane_bytes(
                64, 1, F32, devices=2)
            assert out[slv_lane] == projected_lane_bytes(
                64, 1, F32, "solve", rhs_bucket_for(8), devices=4)
            # Projection is free: nothing compiled.
            assert svc.stats()["totals"]["compiles"] == 0


@pytest.mark.smoke
def test_smoke_mesh_serve_round_trip(rng):
    """The < 1 min smoke tier's mesh-lane leg (ISSUE 18 acceptance):
    with the single-device budget under the 64 bucket, warm the
    2-device lane, then serve over-budget requests through it — ZERO
    compiles and ZERO plan-cache measurements on the request path,
    each request journey-hopped ``mesh_admitted`` with the projection
    that admitted it, results correct on the un-padded region, and the
    stats mesh axis reporting the topology as its own row (never
    aliased into the single-device bucket)."""
    cap = 4
    budget = (projected_lane_bytes(64, 1, F32, devices=2)
              + projected_lane_bytes(64, cap, F32)) // 2
    mark = RECORDER.total
    with JordanService(dtype=F32, batch_cap=cap, max_wait_ms=1.0,
                       block_size=16, mesh_shapes=(2,),
                       lane_budget_bytes=budget) as svc:
        svc.warmup(mesh_shapes=[(64, 2)])
        warm_compiles = svc.stats()["totals"]["compiles"]
        assert warm_compiles >= 1
        mats = [rng.standard_normal((n, n)).astype(np.float32)
                for n in (64, 60, 64)]
        futs = [svc.submit(a) for a in mats]
        results = [f.result(120) for f in futs]
        stats = svc.stats()
    assert stats["totals"]["compiles"] == warm_compiles
    assert stats["measurements"] == 0
    for a, r in zip(mats, results):
        assert not r.singular
        n = a.shape[0]
        assert np.asarray(r.inverse).shape == (n, n)
        assert r.rel_residual is not None and r.rel_residual < 1e-4
        assert np.allclose(np.asarray(r.inverse) @ a, np.eye(n),
                           atol=1e-3)
    hops = [e for e in RECORDER.since(mark)
            if e.get("kind") == "journey"
            and e.get("event") == "mesh_admitted"]
    assert len(hops) == len(mats)
    assert all(e.get("mesh") == "p2" for e in hops)
    mesh_rows = {b: s for b, s in stats["buckets"].items()
                 if s.get("mesh", MESH_SINGLE) != MESH_SINGLE}
    assert sum(s["requests"] for s in mesh_rows.values()) == len(mats)
    assert "64@p2" in stats["engines"]
    assert stats["engines"]["64@p2"]["mesh"] == "p2"
