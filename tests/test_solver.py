"""JordanSolver model tests: compiled-pipeline reuse, distributed path,
residual before/after invert, refinement plumbing."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.models import JordanSolver


class TestJordanSolver:
    def test_single_device(self, rng):
        s = JordanSolver(n=48, block_size=8, dtype=jnp.float64)
        a = rng.standard_normal((48, 48))
        inv, sing = s.invert(a)
        assert not bool(sing)
        assert s.residual(a, inv) < 1e-9

    def test_repeated_solves_reuse_executable(self, rng):
        s = JordanSolver(n=32, block_size=8, dtype=jnp.float64)
        for _ in range(3):
            a = rng.standard_normal((32, 32))
            inv, sing = s.invert(a)
            assert not bool(sing)
            assert s.residual(a, inv) < 1e-9
        assert s._run is not None

    def test_workers4(self, rng):
        s = JordanSolver(n=64, block_size=8, dtype=jnp.float64, workers=4)
        a = rng.standard_normal((64, 64))
        inv, sing = s.invert(a)
        assert not bool(sing)
        assert s.residual(a, inv) < 1e-9

    def test_residual_before_invert(self, rng):
        # Regression: residual() used to crash (mesh only built in
        # _compile) when called before the first invert on workers>1.
        s = JordanSolver(n=32, block_size=8, dtype=jnp.float64, workers=4)
        a = rng.standard_normal((32, 32))
        inv = np.linalg.inv(a)
        assert s.residual(a, inv) < 1e-9

    def test_refine_distributed(self, rng):
        s = JordanSolver(n=64, block_size=8, dtype=jnp.float32,
                         workers=4, refine=2)
        a = rng.standard_normal((64, 64)).astype(np.float32)
        inv, sing = s.invert(a)
        assert not bool(sing)
        assert s.residual(a, inv) < 1e-4

    def test_shape_mismatch_raises(self, rng):
        s = JordanSolver(n=16)
        with pytest.raises(ValueError, match="expected"):
            s.invert(rng.standard_normal((8, 8)))


def test_distributed_init_single_process_noop():
    # The analog of MPI_Init must tolerate a single-process environment
    # (and being called twice) instead of crashing the CLI.
    from tpu_jordan.parallel.mesh import distributed_init

    distributed_init()
    distributed_init()


def test_cli_distributed_flag():
    from tpu_jordan.__main__ import main

    assert main(["48", "8", "--distributed", "--quiet"]) == 0
