"""JordanSolver model tests: compiled-pipeline reuse, distributed path,
residual before/after invert, refinement plumbing."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.models import JordanSolver


class TestJordanSolver:
    def test_single_device(self, rng):
        s = JordanSolver(n=48, block_size=8, dtype=jnp.float64)
        a = rng.standard_normal((48, 48))
        inv, sing = s.invert(a)
        assert not bool(sing)
        assert s.residual(a, inv) < 1e-9

    def test_repeated_solves_reuse_executable(self, rng):
        s = JordanSolver(n=32, block_size=8, dtype=jnp.float64)
        for _ in range(3):
            a = rng.standard_normal((32, 32))
            inv, sing = s.invert(a)
            assert not bool(sing)
            assert s.residual(a, inv) < 1e-9
        assert s._run is not None

    def test_workers4(self, rng):
        s = JordanSolver(n=64, block_size=8, dtype=jnp.float64, workers=4)
        a = rng.standard_normal((64, 64))
        inv, sing = s.invert(a)
        assert not bool(sing)
        assert s.residual(a, inv) < 1e-9

    def test_residual_before_invert(self, rng):
        # Regression: residual() used to crash (mesh only built in
        # _compile) when called before the first invert on workers>1.
        s = JordanSolver(n=32, block_size=8, dtype=jnp.float64, workers=4)
        a = rng.standard_normal((32, 32))
        inv = np.linalg.inv(a)
        assert s.residual(a, inv) < 1e-9

    @pytest.mark.slow  # tier-1 budget: the device-resident distributed
    # refine path (test_generate_sharded) and the driver-level refine pin
    # (test_driver) keep fast-run coverage
    def test_refine_distributed(self, rng):
        s = JordanSolver(n=64, block_size=8, dtype=jnp.float32,
                         workers=4, refine=2)
        a = rng.standard_normal((64, 64)).astype(np.float32)
        inv, sing = s.invert(a)
        assert not bool(sing)
        assert s.residual(a, inv) < 1e-4

    def test_shape_mismatch_raises(self, rng):
        s = JordanSolver(n=16)
        with pytest.raises(ValueError, match="expected"):
            s.invert(rng.standard_normal((8, 8)))

    @pytest.mark.slow  # tier-1 budget: test_workers4 + the smoke 2D layout stay
    def test_workers_2d_mesh(self, rng):
        # VERDICT r2 #8: the solver must accept a (pr, pc) mesh like the
        # driver does (2D block-cyclic layout, SUMMA residual).
        s = JordanSolver(n=64, block_size=8, dtype=jnp.float32,
                         workers=(2, 4))
        a = rng.standard_normal((64, 64)).astype(np.float32)
        inv, sing = s.invert(a)
        assert not bool(sing)
        assert inv.shape == (64, 64)
        assert s.residual(a, inv) < 1e-3
        np.testing.assert_allclose(np.asarray(inv), np.linalg.inv(a),
                                   rtol=1e-2, atol=1e-3)

    @pytest.mark.parametrize("workers", [
        4,
        # tier-1 budget: the 2D no-gather leg duplicates the 2x4
        # gather=False pins in test_solve_dist/test_jordan2d_inplace.
        pytest.param((2, 2), marks=pytest.mark.slow)])
    def test_no_gather_blocks(self, rng, workers):
        # gather=False: the inverse stays as sharded cyclic blocks and the
        # residual is verified without materializing n x n per device.
        s = JordanSolver(n=64, block_size=8, dtype=jnp.float32,
                         workers=workers, gather=False)
        a = rng.standard_normal((64, 64)).astype(np.float32)
        blocks, sing = s.invert(a)
        assert not bool(sing)
        assert s.layout is not None
        assert blocks.ndim > 2 or blocks.shape != (64, 64)
        assert s.residual(a, blocks) < 1e-3

    def test_no_gather_single_device_raises(self):
        from tpu_jordan.driver import UsageError

        with pytest.raises(UsageError, match="gather=False"):
            JordanSolver(n=16, gather=False)

    def test_refine_no_gather_raises(self):
        from tpu_jordan.driver import UsageError

        with pytest.raises(UsageError, match="refine"):
            JordanSolver(n=16, workers=4, refine=2, gather=False)

    def test_mixed_precision_no_gather_raises(self):
        # Same flag contract as driver.solve (shared check_gather_flags):
        # 'mixed' implies refinement, which needs the gathered inverse.
        from tpu_jordan.driver import UsageError

        with pytest.raises(UsageError, match="mixed"):
            JordanSolver(n=16, workers=4, precision="mixed", gather=False)

    def test_sub_fp32_storage_dtype(self, rng):
        # bf16 storage computes in fp32 and rounds once at the end.
        s = JordanSolver(n=32, block_size=8, dtype=jnp.bfloat16, workers=4)
        a = rng.standard_normal((32, 32)).astype(np.float32)
        inv, sing = s.invert(a)
        assert inv.dtype == jnp.bfloat16
        assert not bool(sing)


def test_distributed_init_single_process_noop():
    # The analog of MPI_Init must tolerate a single-process environment
    # (and being called twice) instead of crashing the CLI.
    from tpu_jordan.parallel.mesh import distributed_init

    distributed_init()
    distributed_init()


def test_cli_distributed_flag():
    from tpu_jordan.__main__ import main

    assert main(["48", "8", "--distributed", "--quiet"]) == 0


def test_solver_invert_batch(rng):
    s = JordanSolver(n=24, block_size=8, dtype=jnp.float32)
    a = rng.standard_normal((5, 24, 24)).astype(np.float32)
    inv, sing = s.invert_batch(a)
    assert inv.shape == (5, 24, 24) and sing.shape == (5,)
    assert not np.asarray(sing).any()
    np.testing.assert_allclose(np.asarray(inv), np.linalg.inv(a),
                               rtol=1e-2, atol=1e-3)


def test_solver_invert_batch_distributed_raises():
    from tpu_jordan.driver import UsageError

    s = JordanSolver(n=16, block_size=8, workers=4)
    with pytest.raises(UsageError, match="invert_batch"):
        s.invert_batch(np.zeros((2, 16, 16), np.float32))
