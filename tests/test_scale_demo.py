"""Memory-scaling evidence at a non-toy size (VERDICT r2 #9).

The 2D block-cyclic + gather=False mode exists so per-worker memory is
O(n²/(pr·pc)) — the fix for the reference's replicated-column memory wall
(main.cpp:366-370).  This test runs it at n=2048 on the 8-device CPU mesh
and asserts the actual per-device shard bytes, not just the residual.

The swap-free tests below pin the round-6 reconciliation: the pod-scale
comm engine (swapfree) in the pod-scale memory mode (gather=False) —
legal since the deferred permutations run as bucketed ``ppermute``
rounds inside the engine (parallel/permute.py), so no per-worker buffer
at the permutation step exceeds one shard (N²/P elements; the old
``jnp.take`` reshuffle transiently all-gathered the full N²).  Shard
bytes are asserted on the solver OUTPUT, and the blocks must bit-match
the gathered path (ties included — the |i−j| fixture exercises exact
pivot ties)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.driver import solve


@pytest.mark.slow
def test_2048_2d_no_gather_shard_bytes():
    n, m, pr, pc = 2048, 128, 2, 4
    res = solve(n, m, workers=(pr, pc), gather=False)
    # |i−j| fixture: ‖A‖∞ ≈ n²/2; the reported residual is unnormalized.
    assert res.residual / (n * n / 2) < 1e-4

    blocks = res.inverse_blocks
    lay = res.layout
    assert lay.n == n and lay.m == m
    N = lay.N
    # Global representation is (Nr, m, N) — n² numbers total, no
    # augmented half.
    assert blocks.shape == (lay.Nr, m, N)
    shards = blocks.addressable_shards
    assert len(shards) == pr * pc
    per_worker = (lay.Nr // pr) * m * (N // pc)
    full = N * N
    for s in shards:
        assert s.data.shape == (lay.Nr // pr, m, N // pc)
        assert s.data.nbytes == per_worker * 4          # fp32
    # The point of the mode: each worker holds 1/(pr*pc) of the matrix.
    assert per_worker * pr * pc == full
    assert per_worker * 4 == full * 4 // (pr * pc)


def _assert_sharded_blocks(blocks, lay, nshards, shard_shape):
    """Every shard holds exactly 1/P of the (Nr, m, N) block tensor —
    the gather=False memory contract, asserted in bytes."""
    shards = blocks.addressable_shards
    assert len(shards) == nshards
    itemsize = blocks.dtype.itemsize
    per_worker = int(np.prod(shard_shape))
    for s in shards:
        assert s.data.shape == shard_shape
        assert s.data.nbytes == per_worker * itemsize
    assert per_worker * nshards == lay.N * lay.N


def test_swapfree_no_gather_1d_shard_bytes_and_bitmatch():
    # |i−j| fixture: exact pivot ties — the swap-coordinate tie rule
    # must reproduce the swap engines' choices through the bucketed
    # permutation too.
    n, m, p = 512, 32, 8
    r_sf = solve(n, m, workers=p, gather=False, dtype=jnp.float64,
                 engine="swapfree")
    assert r_sf.residual / (n * n / 2) < 1e-10
    lay = r_sf.layout
    _assert_sharded_blocks(r_sf.inverse_blocks, lay, p,
                           (lay.Nr // p, m, lay.N))
    # Bit-match the gathered swap-free path AND the swap engine's
    # sharded path (nonsingular fixture; invalid-singular outputs are
    # exempt from the bit-match contract).
    r_gathered = solve(n, m, workers=p, gather=True, dtype=jnp.float64,
                       engine="swapfree")
    from tpu_jordan.parallel.sharded_inplace import gather_inverse_inplace

    assembled = gather_inverse_inplace(
        jnp.asarray(r_sf.inverse_blocks), lay, n)
    assert bool(jnp.all(assembled == r_gathered.inverse))
    r_swap = solve(n, m, workers=p, gather=False, dtype=jnp.float64)
    assert bool(jnp.all(jnp.asarray(r_sf.inverse_blocks)
                        == jnp.asarray(r_swap.inverse_blocks)))


def test_swapfree_no_gather_2d_shard_bytes_and_bitmatch():
    n, m, pr, pc = 512, 32, 2, 4
    r_sf = solve(n, m, workers=(pr, pc), gather=False, dtype=jnp.float64,
                 engine="swapfree")
    assert r_sf.residual / (n * n / 2) < 1e-10
    lay = r_sf.layout
    _assert_sharded_blocks(r_sf.inverse_blocks, lay, pr * pc,
                           (lay.Nr // pr, m, lay.N // pc))
    r_gathered = solve(n, m, workers=(pr, pc), gather=True,
                       dtype=jnp.float64, engine="swapfree")
    from tpu_jordan.parallel.jordan2d_inplace import (
        gather_inverse_inplace_2d,
    )

    assembled = gather_inverse_inplace_2d(
        jnp.asarray(r_sf.inverse_blocks), lay, n)
    assert bool(jnp.all(assembled == r_gathered.inverse))
    r_swap = solve(n, m, workers=(pr, pc), gather=False,
                   dtype=jnp.float64)
    assert bool(jnp.all(jnp.asarray(r_sf.inverse_blocks)
                        == jnp.asarray(r_swap.inverse_blocks)))
