"""Memory-scaling evidence at a non-toy size (VERDICT r2 #9).

The 2D block-cyclic + gather=False mode exists so per-worker memory is
O(n²/(pr·pc)) — the fix for the reference's replicated-column memory wall
(main.cpp:366-370).  This test runs it at n=2048 on the 8-device CPU mesh
and asserts the actual per-device shard bytes, not just the residual.

The swap-free tests below pin the round-6 reconciliation: the pod-scale
comm engine (swapfree) in the pod-scale memory mode (gather=False) —
legal since the deferred permutations run as bucketed ``ppermute``
rounds inside the engine (parallel/permute.py), so no per-worker buffer
at the permutation step exceeds one shard (N²/P elements; the old
``jnp.take`` reshuffle transiently all-gathered the full N²).  Shard
bytes are asserted on the solver OUTPUT, and the blocks must bit-match
the gathered path (ties included — the |i−j| fixture exercises exact
pivot ties)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.driver import solve


@pytest.mark.slow
def test_2048_2d_no_gather_shard_bytes():
    n, m, pr, pc = 2048, 128, 2, 4
    res = solve(n, m, workers=(pr, pc), gather=False)
    # |i−j| fixture: ‖A‖∞ ≈ n²/2; the reported residual is unnormalized.
    assert res.residual / (n * n / 2) < 1e-4

    blocks = res.inverse_blocks
    lay = res.layout
    assert lay.n == n and lay.m == m
    N = lay.N
    # Global representation is (Nr, m, N) — n² numbers total, no
    # augmented half.
    assert blocks.shape == (lay.Nr, m, N)
    shards = blocks.addressable_shards
    assert len(shards) == pr * pc
    per_worker = (lay.Nr // pr) * m * (N // pc)
    full = N * N
    for s in shards:
        assert s.data.shape == (lay.Nr // pr, m, N // pc)
        assert s.data.nbytes == per_worker * 4          # fp32
    # The point of the mode: each worker holds 1/(pr*pc) of the matrix.
    assert per_worker * pr * pc == full
    assert per_worker * 4 == full * 4 // (pr * pc)


def _assert_sharded_blocks(blocks, lay, nshards, shard_shape):
    """Every shard holds exactly 1/P of the (Nr, m, N) block tensor —
    the gather=False memory contract, asserted in bytes."""
    shards = blocks.addressable_shards
    assert len(shards) == nshards
    itemsize = blocks.dtype.itemsize
    per_worker = int(np.prod(shard_shape))
    for s in shards:
        assert s.data.shape == shard_shape
        assert s.data.nbytes == per_worker * itemsize
    assert per_worker * nshards == lay.N * lay.N


@pytest.mark.slow   # tier-1 headroom (ISSUE 3): the 2D twin below stays
def test_swapfree_no_gather_1d_shard_bytes_and_bitmatch():
    # |i−j| fixture: exact pivot ties — the swap-coordinate tie rule
    # must reproduce the swap engines' choices through the bucketed
    # permutation too.
    n, m, p = 512, 32, 8
    r_sf = solve(n, m, workers=p, gather=False, dtype=jnp.float64,
                 engine="swapfree")
    assert r_sf.residual / (n * n / 2) < 1e-10
    lay = r_sf.layout
    _assert_sharded_blocks(r_sf.inverse_blocks, lay, p,
                           (lay.Nr // p, m, lay.N))
    # Bit-match the gathered swap-free path AND the swap engine's
    # sharded path (nonsingular fixture; invalid-singular outputs are
    # exempt from the bit-match contract).
    r_gathered = solve(n, m, workers=p, gather=True, dtype=jnp.float64,
                       engine="swapfree")
    from tpu_jordan.parallel.sharded_inplace import gather_inverse_inplace

    assembled = gather_inverse_inplace(
        jnp.asarray(r_sf.inverse_blocks), lay, n)
    assert bool(jnp.all(assembled == r_gathered.inverse))
    # The swap engine, pinned explicitly ("auto" routes through the
    # autotuner since ISSUE 2 and may legitimately pick another engine).
    r_swap = solve(n, m, workers=p, gather=False, dtype=jnp.float64,
                   engine="inplace")
    assert bool(jnp.all(jnp.asarray(r_sf.inverse_blocks)
                        == jnp.asarray(r_swap.inverse_blocks)))


@pytest.mark.slow  # tier-1 budget: TestAutoEngineLegs keeps the no-gather fast-run coverage
def test_swapfree_no_gather_2d_shard_bytes_and_bitmatch():
    n, m, pr, pc = 512, 32, 2, 4
    r_sf = solve(n, m, workers=(pr, pc), gather=False, dtype=jnp.float64,
                 engine="swapfree")
    assert r_sf.residual / (n * n / 2) < 1e-10
    lay = r_sf.layout
    _assert_sharded_blocks(r_sf.inverse_blocks, lay, pr * pc,
                           (lay.Nr // pr, m, lay.N // pc))
    r_gathered = solve(n, m, workers=(pr, pc), gather=True,
                       dtype=jnp.float64, engine="swapfree")
    from tpu_jordan.parallel.jordan2d_inplace import (
        gather_inverse_inplace_2d,
    )

    assembled = gather_inverse_inplace_2d(
        jnp.asarray(r_sf.inverse_blocks), lay, n)
    assert bool(jnp.all(assembled == r_gathered.inverse))
    r_swap = solve(n, m, workers=(pr, pc), gather=False,
                   dtype=jnp.float64, engine="inplace")
    assert bool(jnp.all(jnp.asarray(r_sf.inverse_blocks)
                        == jnp.asarray(r_swap.inverse_blocks)))


class TestAutoEngineLegs:
    """ISSUE 2 MULTICHIP harness legs: ``--engine auto`` on the
    8-virtual-device CPU mesh.  The autotuner must select a LEGAL
    registry engine at every dryrun leg (1D p=8 and 2D 2x4, gather=True
    and gather=False), and the result must bit-match the same engine
    requested directly (the acceptance contract; the zero-measurement
    warm-cache half is pinned by the counter tests in test_tuning.py)."""

    @pytest.mark.parametrize("workers,gather", [
        (8, True), (8, False),
        # tier-1 budget: the gathered 2D leg duplicates the gather=False
        # 2D leg through the same auto path and runs nightly.
        pytest.param((2, 4), True, marks=pytest.mark.slow),
        ((2, 4), False),
    ])
    def test_auto_selects_legal_engine_and_bitmatches(self, workers,
                                                      gather):
        from tpu_jordan.tuning.registry import REGISTRY, TunePoint

        n, m = 64, 8
        r = solve(n, m, workers=workers, gather=gather, dtype=jnp.float64,
                  engine="auto")
        cfgs = {c.engine: c for c in REGISTRY.values()}
        assert r.engine in cfgs, f"auto selected unregistered {r.engine!r}"
        pt = TunePoint.create(n, m, jnp.float64, workers, gather)
        assert cfgs[r.engine].legal(pt), \
            f"auto selected {r.engine!r}, illegal at {pt}"
        assert r.plan is not None and r.plan.source == "cost_model"
        direct = solve(n, m, workers=workers, gather=gather,
                       dtype=jnp.float64, engine=r.engine, group=r.group)
        if gather:
            assert bool(jnp.all(r.inverse == direct.inverse))
        else:
            assert bool(jnp.all(jnp.asarray(r.inverse_blocks)
                                == jnp.asarray(direct.inverse_blocks)))

    def test_auto_gather_false_swapfree_selection(self, tmp_path):
        """The gather=False auto-selection leg on the v5p pod-scale
        north-star meshes: (a) at unrolled-reach Nr the probe-ahead
        engine ranks first (ISSUE 16 — taking the condition probe off
        the superstep critical path is a bigger projected saving than
        deferring swaps), while beyond MAX_UNROLL_NR the swap-free
        engine still owns the point (the ISSUE 2 promise — the
        projections in benchmarks/PHASES.md say SF wins there), and
        (b) an executed CPU-mesh solve honoring a swap-free plan from a
        warm cache runs swapfree and bit-matches the direct request."""
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR
        from tpu_jordan.tuning import (Plan, PlanCache, TunePoint,
                                       plan_key, select_by_cost)

        pt = TunePoint.create(32768, 512, jnp.float32, (4, 8),
                              gather=False, backend="tpu", chip="v5p")
        assert -(-32768 // 512) <= MAX_UNROLL_NR
        assert select_by_cost(pt).engine == "lookahead", \
            "v5p (4, 8) @ 32768 gather=False must rank probe-ahead first"
        pt = TunePoint.create(65536, 512, jnp.float32, (8, 8),
                              gather=False, backend="tpu", chip="v5p")
        assert -(-65536 // 512) > MAX_UNROLL_NR
        assert select_by_cost(pt).engine == "swapfree", \
            "v5p (8, 8) @ 65536 gather=False must rank swap-free first"
        # Executed leg: seed a plan cache with the swap-free plan for
        # this CPU-mesh point; auto must honor it (zero measurements)
        # and bit-match engine='swapfree' requested directly.
        n, m, mesh = 64, 8, (2, 4)
        pt = TunePoint.create(n, m, jnp.float64, mesh, gather=False)
        path = str(tmp_path / "plans.json")
        cache = PlanCache(path)
        cache.put(plan_key(pt), Plan(config="swapfree", engine="swapfree",
                                     group=0, source="measured",
                                     seconds=1e-3))
        cache.save()
        r = solve(n, m, workers=mesh, gather=False, dtype=jnp.float64,
                  engine="auto", plan_cache=path)
        assert r.engine == "swapfree"
        direct = solve(n, m, workers=mesh, gather=False,
                       dtype=jnp.float64, engine="swapfree")
        assert bool(jnp.all(jnp.asarray(r.inverse_blocks)
                            == jnp.asarray(direct.inverse_blocks)))


def test_32768_fp32_aot_lowering_shape():
    """Compile-only pin of the above-16384 path (ISSUE 2 / VERDICT r5):
    AOT-lower the auto-selected single-chip engine at n=32768 fp32 — no
    execution, no 4 GiB buffers (abstract avals only) — and check the
    output shapes.  m=256 puts Nr=128 over MAX_UNROLL_NR, so this also
    pins that the auto path takes the fori twin whose trace cost is flat
    in Nr (the reason 32768 is traceable at all)."""
    from jax import lax

    from tpu_jordan.driver import single_device_invert
    from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR
    from tpu_jordan.tuning.registry import TunePoint, select_by_cost

    n, m = 32768, 256
    assert -(-n // m) > MAX_UNROLL_NR
    cfg = select_by_cost(TunePoint.create(n, m, jnp.float32, 1, True))
    # The measured single-chip dispatch policy, reproduced by the cost
    # ranking: the delayed-group-update engine owns n >= 8192.
    assert cfg.engine == "grouped"
    a = jax.ShapeDtypeStruct((n, n), jnp.float32)
    lowered = jax.jit(
        single_device_invert(n, m, cfg.engine, cfg.group),
        static_argnames=("block_size", "refine", "precision"),
    ).lower(a, block_size=m, refine=0, precision=lax.Precision.HIGHEST)
    out_inv, out_sing = lowered.out_info
    assert tuple(out_inv.shape) == (n, n)
    assert out_inv.dtype == jnp.float32
    assert tuple(out_sing.shape) == ()
