"""Memory-scaling evidence at a non-toy size (VERDICT r2 #9).

The 2D block-cyclic + gather=False mode exists so per-worker memory is
O(n²/(pr·pc)) — the fix for the reference's replicated-column memory wall
(main.cpp:366-370).  This test runs it at n=2048 on the 8-device CPU mesh
and asserts the actual per-device shard bytes, not just the residual.
"""

import numpy as np

from tpu_jordan.driver import solve


def test_2048_2d_no_gather_shard_bytes():
    n, m, pr, pc = 2048, 128, 2, 4
    res = solve(n, m, workers=(pr, pc), gather=False)
    # |i−j| fixture: ‖A‖∞ ≈ n²/2; the reported residual is unnormalized.
    assert res.residual / (n * n / 2) < 1e-4

    blocks = res.inverse_blocks
    lay = res.layout
    assert lay.n == n and lay.m == m
    N = lay.N
    # Global representation is (Nr, m, N) — n² numbers total, no
    # augmented half.
    assert blocks.shape == (lay.Nr, m, N)
    shards = blocks.addressable_shards
    assert len(shards) == pr * pc
    per_worker = (lay.Nr // pr) * m * (N // pc)
    full = N * N
    for s in shards:
        assert s.data.shape == (lay.Nr // pr, m, N // pc)
        assert s.data.nbytes == per_worker * 4          # fp32
    # The point of the mode: each worker holds 1/(pr*pc) of the matrix.
    assert per_worker * pr * pc == full
    assert per_worker * 4 == full * 4 // (pr * pc)
