"""2D block-cyclic sharded Jordan inversion: parity vs the single-device
path on 2x4, 4x2, and 2x2 virtual CPU meshes, plus SUMMA residual and
shard-local 2D generation."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.ops import block_jordan_invert, generate
from tpu_jordan.parallel import (
    CyclicLayout2D,
    distributed_residual_2d,
    make_mesh_2d,
    sharded_generate_2d,
    sharded_jordan_invert_2d,
)
from tpu_jordan.parallel.jordan2d import (
    gather_inverse_2d,
    scatter_augmented_2d,
    split_inverse_blocks_2d,
)


@pytest.fixture(params=[(2, 4), (4, 2), (2, 2)])
def mesh2d(request):
    return make_mesh_2d(*request.param)


class TestLayout2D:
    def test_padding_is_lcm_multiple(self):
        lay = CyclicLayout2D.create(100, 8, 2, 4)   # Nr=13 -> 16
        assert lay.Nr == 16 and lay.bpr == 8 and lay.bc2 == 8

    def test_perms_are_permutations(self):
        lay = CyclicLayout2D.create(64, 8, 2, 4)
        assert sorted(lay.row_perm()) == list(range(lay.Nr))
        assert sorted(lay.col_perm(2 * lay.Nr)) == list(range(2 * lay.Nr))


class TestScatterGather2D:
    def test_roundtrip(self, rng, mesh2d):
        pr, pc = mesh2d.devices.shape
        n, m = 48, 4
        lay = CyclicLayout2D.create(n, m, pr, pc)
        a = jnp.asarray(rng.standard_normal((n, n)))
        W = scatter_augmented_2d(a, lay, mesh2d)
        assert len(W.sharding.device_set) == pr * pc
        # gather of the untouched scatter returns B = I
        got = gather_inverse_2d(W, lay, n)
        np.testing.assert_array_equal(np.asarray(got), np.eye(n))


class TestSharded2DJordan:
    @pytest.mark.parametrize("n,m", [(48, 4), (64, 8), (50, 8)])
    def test_matches_single_device(self, rng, mesh2d, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv_d, s_d = sharded_jordan_invert_2d(a, mesh2d, m)
        inv_s, s_s = block_jordan_invert(a, block_size=m)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(
            np.asarray(inv_d), np.asarray(inv_s), rtol=1e-9, atol=1e-9
        )

    def test_absdiff_tied_pivots_match(self, mesh2d):
        a = generate("absdiff", (64, 64), jnp.float64)
        inv_d, s_d = sharded_jordan_invert_2d(a, mesh2d, 8)
        inv_s, s_s = block_jordan_invert(a, block_size=8)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(
            np.asarray(inv_d), np.asarray(inv_s), rtol=1e-9, atol=1e-12
        )

    def test_singular_collective_agreement(self, mesh2d):
        _, sing = sharded_jordan_invert_2d(
            jnp.ones((32, 32), jnp.float64), mesh2d, 8
        )
        assert bool(sing)

    def test_hilbert(self, mesh2d):
        a = generate("hilbert", (8, 8), jnp.float64)
        inv, sing = sharded_jordan_invert_2d(a, mesh2d, 2)
        assert not bool(sing)
        res = np.max(np.sum(np.abs(np.asarray(a) @ np.asarray(inv)
                                   - np.eye(8)), axis=1))
        assert res < 1e-3


class TestGenerate2D:
    @pytest.mark.parametrize("name", ["absdiff", "hilbert"])
    def test_matches_host_scatter(self, mesh2d, name):
        pr, pc = mesh2d.devices.shape
        n, m = 40, 4
        lay = CyclicLayout2D.create(n, m, pr, pc)
        dev = sharded_generate_2d(name, lay, mesh2d, jnp.float64)
        host = scatter_augmented_2d(
            generate(name, (n, n), jnp.float64), lay, mesh2d
        )
        np.testing.assert_array_equal(np.asarray(dev), np.asarray(host))

    def test_unaugmented_width(self, mesh2d):
        pr, pc = mesh2d.devices.shape
        lay = CyclicLayout2D.create(32, 4, pr, pc)
        dev = sharded_generate_2d("absdiff", lay, mesh2d, jnp.float64,
                                  augmented=False)
        assert dev.shape == (lay.Nr, lay.m, lay.N)


class TestDriver2D:
    @pytest.mark.slow  # tier-1 budget: the 2D solve parity + comm reconciliation siblings stay
    def test_solve_2d_generator(self):
        from tpu_jordan.driver import solve

        res = solve(n=64, block_size=8, workers=(2, 4), dtype=jnp.float64)
        assert res.residual / (64 * 64 / 2) < 1e-12
        assert res.inverse is not None

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): driver-level 2D
    #   gather=False stays tier-1 via test_scale_demo's 2D swap-free
    #   shard-bytes+bitmatch leg and the κ∞ (2,2) gather=False leg
    def test_solve_2d_gather_false(self, monkeypatch):
        import tpu_jordan.driver as drv
        from tpu_jordan.driver import solve

        def forbid(fn, shape, dtype=jnp.float32, **kw):
            raise AssertionError(f"host generate({shape}) called")

        monkeypatch.setattr(drv, "generate", forbid)
        res = solve(n=96, block_size=8, workers=(4, 2), gather=False)
        assert res.inverse is None
        assert res.inverse_blocks is not None
        assert len(res.inverse_blocks.sharding.device_set) == 8
        assert res.residual / (96 * 96 / 2) < 1e-5

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): 2D streamed-file
    #   scatter stays tier-1 in test_stream_scatter.py; the 1D file
    #   driver leg stays
    def test_solve_2d_file(self, rng, tmp_path):
        from tpu_jordan.driver import solve
        from tpu_jordan.io import write_matrix_file

        a = rng.standard_normal((48, 48))
        path = str(tmp_path / "a.txt")
        write_matrix_file(path, a)
        res = solve(n=48, block_size=8, workers=(2, 2), file=path,
                    dtype=jnp.float64)
        assert res.residual < 1e-9

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): PRxPC parsing +
    #   the 2D driver path stay tier-1 via test_solve_2d_generator and
    #   the 1D CLI legs; nightly here
    def test_cli_2d_workers(self):
        from tpu_jordan.__main__ import main

        assert main(["64", "8", "--workers", "2x4", "--quiet"]) == 0


class TestSummaResidual2D:
    def test_end_to_end_no_host_matrix(self, mesh2d):
        # generate -> invert -> split B half -> SUMMA residual, all 2D.
        pr, pc = mesh2d.devices.shape
        n, m = 64, 8
        lay = CyclicLayout2D.create(n, m, pr, pc)
        from tpu_jordan.parallel.jordan2d import compile_sharded_jordan_2d

        W = sharded_generate_2d("absdiff", lay, mesh2d, jnp.float64)
        run = compile_sharded_jordan_2d(W, mesh2d, lay)
        out, singular = run(W)
        assert not bool(singular.any())
        inv_b = split_inverse_blocks_2d(out, lay, mesh2d)
        a_b = sharded_generate_2d("absdiff", lay, mesh2d, jnp.float64,
                                  augmented=False)
        res = float(distributed_residual_2d(a_b, inv_b, mesh2d, lay))
        rel = res / (n * n / 2)
        assert rel < 1e-12

    def test_matches_dense_residual(self, rng, mesh2d):
        pr, pc = mesh2d.devices.shape
        n, m = 32, 4
        lay = CyclicLayout2D.create(n, m, pr, pc)
        a = rng.standard_normal((n, n))
        x = np.linalg.inv(a) + 1e-8 * rng.standard_normal((n, n))
        from tpu_jordan.ops.padding import pad_with_identity

        def to_blocks(h):
            hp = pad_with_identity(jnp.asarray(h), lay.N)
            blocks = hp.reshape(lay.Nr, m, lay.Nr, m)
            rowp = jnp.asarray(lay.row_perm())
            colp = jnp.asarray(lay.col_perm(lay.Nr))
            blocks = jnp.take(jnp.take(blocks, rowp, 0), colp, 2)
            return blocks.reshape(lay.Nr, m, lay.N)

        got = float(distributed_residual_2d(
            to_blocks(a), to_blocks(x), mesh2d, lay
        ))
        want = float(np.max(np.sum(np.abs(a @ x - np.eye(n)), axis=1)))
        np.testing.assert_allclose(got, want, rtol=1e-9)
