"""Autotuner subsystem tests (ISSUE 2): registry lint, plan-cache
round-trip + corruption/version fallback, deterministic selection under
injected fake timings, the zero-measurement warm-cache contract (counter
pinned, through both the Tuner and the solve() product surface), and the
robust measurement core.  Real-measurement tuner tests are ``slow``; the
tier-1 tests here run on fake timings only."""

import json
import math

import jax.numpy as jnp
import pytest

from tpu_jordan.tuning import (CACHE_VERSION, CONFIGS, ENGINES, REGISTRY,
                               Measurement, Plan, PlanCache, TunePoint,
                               Tuner, candidates, n_bucket, plan_key,
                               robust_stats, select_by_cost)


class TestRegistry:
    def test_every_solve_engine_registered_exactly_once(self):
        """The registry IS a lint: every (engine, workload) pair
        reachable from driver.solve or linalg.solve_system appears
        exactly once, and the driver/CLI/linalg vocabularies derive
        from it (no string list can drift).  ISSUE 11 extended the
        historical per-engine lint to the workload axis: the old lint
        only covered the invert workload."""
        from tpu_jordan.driver import ENGINES as DRIVER_ENGINES
        from tpu_jordan.tuning.registry import SOLVE_ENGINES, WORKLOADS

        pairs = [(c.engine, c.workload) for c in CONFIGS]
        assert sorted(pairs) == sorted(set(pairs)), \
            "an (engine, workload) pair is registered twice"
        assert all(c.workload in WORKLOADS for c in CONFIGS)
        invert = [c.engine for c in CONFIGS if c.workload == "invert"]
        assert sorted(invert) == sorted(set(invert)), \
            "an invert engine is registered twice"
        assert set(invert) == set(DRIVER_ENGINES) - {"auto"}
        assert DRIVER_ENGINES is ENGINES      # same derived object
        assert ENGINES[0] == "auto"
        # The solve vocabulary derives the same way and never leaks
        # into the driver/CLI invert vocabulary; the update workload
        # (ISSUE 12) is its own axis — smw_update is neither a solve
        # nor an invert engine.
        solve = {c.engine for c in CONFIGS
                 if c.workload in ("solve", "solve_spd")}
        assert set(SOLVE_ENGINES) - {"auto"} == solve
        assert not (solve & set(DRIVER_ENGINES))
        update = {c.engine for c in CONFIGS if c.workload == "update"}
        assert update == {"smw_update"}
        assert not (update & (set(DRIVER_ENGINES) | set(SOLVE_ENGINES)))
        names = [c.name for c in CONFIGS]
        assert sorted(names) == sorted(set(names))
        assert set(REGISTRY) == set(names)

    def test_solve_workload_candidates_and_ranking(self):
        """ISSUE 11: solve points rank the solve zoo only; SPD points
        cost-prefer the pivot-free engine with the pivoting engine as
        the registered fallback; invert candidacy is untouched."""
        slv = TunePoint.create(256, 64, jnp.float32, 1, True,
                               workload="solve")
        assert {c.name for c in candidates(slv)} == {
            "solve_aug", "solve_fori"}
        assert select_by_cost(slv).engine == "solve_aug"
        spd = TunePoint.create(256, 64, jnp.float32, 1, True,
                               workload="solve_spd")
        assert {c.name for c in candidates(spd)} == {
            "solve_spd", "solve_aug_spd", "solve_fori_spd"}
        assert select_by_cost(spd).engine == "solve_spd"
        # The UNROLLED solve engines price strictly below every invert
        # engine at the same point (the never-materializes-A⁻¹ cost
        # story); the fori engine's full-width 2n³-class cost is the
        # honest exception (it exists for Nr > MAX_UNROLL_NR, not to
        # win rankings).
        inv = TunePoint.create(256, 64, jnp.float32, 1, True)
        inv_best = min(c.cost(inv) for c in candidates(inv))
        assert all(c.cost(slv) < inv_best for c in candidates(slv)
                   if c.engine != "solve_fori")
        # ISSUE 15/16: distributed solve points rank the sharded
        # engine pair, and at unrolled-reach Nr the probe-ahead twin
        # is the cost pick (its probe term is projected off the
        # critical path); beyond MAX_UNROLL_NR single-device, the fori
        # engine is the only (and selected) candidate.
        dslv = TunePoint.create(4096, 128, jnp.float32, 8, True,
                                workload="solve")
        assert {c.name for c in candidates(dslv)} == {
            "solve_sharded", "solve_lookahead_sharded"}
        assert select_by_cost(dslv).engine == "solve_lookahead"
        big = TunePoint.create(8192, 64, jnp.float32, 1, True,
                               workload="solve")     # Nr = 128 > 64
        assert {c.name for c in candidates(big)} == {"solve_fori"}

    def test_complex_points_route_to_augmented_family(self):
        """Complex dtypes (ISSUE 11): the invert zoo's only complex
        candidate is the augmented engine; the solve engines accept
        complex outright."""
        cx = TunePoint.create(256, 64, "complex64", 1, True)
        assert {c.name for c in candidates(cx)} == {"augmented"}
        cxs = TunePoint.create(256, 64, "complex64", 1, True,
                               workload="solve")
        assert {c.name for c in candidates(cxs)} == {
            "solve_aug", "solve_fori"}
        # Distributed complex solve points have NO candidates (the
        # sharded engine is real-dtype, like the invert mesh engines) —
        # linalg/api.py types the refusal before selection.
        cxd = TunePoint.create(256, 64, "complex64", 8, True,
                               workload="solve")
        assert candidates(cxd) == []

    def test_legality(self):
        single = TunePoint.create(64, 8, jnp.float32, 1, True)
        dist = TunePoint.create(64, 8, jnp.float32, 8, False)
        assert {c.name for c in candidates(single)} == {
            "inplace", "grouped2", "augmented", "lookahead"}
        assert {c.name for c in candidates(dist)} == {
            "inplace", "grouped2", "augmented", "swapfree", "lookahead"}

    def test_candidates_sorted_by_cost(self):
        pt = TunePoint.create(2048, 128, jnp.float32, (2, 4), False)
        cands = candidates(pt)
        costs = [c.cost(pt) for c in cands]
        assert costs == sorted(costs)
        assert all(c > 0 for c in costs)

    def test_single_chip_measured_dispatch_prior(self):
        """Cost-only ranking reproduces the measured single-chip policy
        (driver.resolve_engine docstring): plain below 8192, the
        delayed-group-update engine at and above."""
        small = TunePoint.create(4096, 128, jnp.float32, 1, True)
        large = TunePoint.create(16384, 128, jnp.float32, 1, True)
        assert select_by_cost(small).engine == "inplace"
        assert math.isinf(REGISTRY["grouped2"].cost(small))
        assert select_by_cost(large).name == "grouped2"

    def test_distributed_calibration_floor_prior(self):
        """Below the comm model's calibration floor, cost-only auto
        keeps the conservative in-place engine (sub-noise rankings are
        not trusted); at and above the floor the model decides — e.g.
        the 2048 2x4 gather=False contract point ranks swap-free
        first."""
        from tpu_jordan.tuning.registry import COST_MODEL_FLOOR_N

        tiny = TunePoint.create(64, 8, jnp.float64, (2, 4), False)
        assert tiny.n < COST_MODEL_FLOOR_N
        assert candidates(tiny)[0].name != "inplace"   # model alone says so
        assert select_by_cost(tiny).name == "inplace"  # the prior wins
        at_floor = TunePoint.create(2048, 128, jnp.float32, (2, 4), False)
        assert select_by_cost(at_floor).name == "swapfree"

    def test_cost_hook_single_source_topology(self):
        """The cost hooks consume comm_model.topology_params() — the
        same chips the PHASES.md projection tables are regenerated
        from."""
        from tpu_jordan.tuning.registry import comm_model

        params = comm_model().topology_params()
        assert set(params["chips"]) == {"v5e", "v4", "v5p"}
        assert params["north_star"], "north-star projection rows moved"
        # Every projection row references a published chip.
        assert {row[4] for row in params["north_star"]} <= set(
            params["chips"])


class TestPallasRegistry:
    """ISSUE 6: the fused-kernel engine configurations — legality
    predicates, cost hooks, and the selection behavior they encode
    (plan-cache keys are point-keyed, so registering them changes no
    existing cache entry — pinned by TestPlanKey staying green)."""

    def test_legality_predicates(self):
        gp = REGISTRY["grouped_pallas"]
        gb = REGISTRY["grouped_pallas_bf16"]
        ok = TunePoint.create(4096, 128, jnp.float32, 1, True)
        assert gp.legal(ok)
        # bf16 compute is auto-candidate ONLY at sub-fp32 storage: an
        # fp32 request must never be silently served by rounded dots.
        assert not gb.legal(ok)
        assert gb.legal(TunePoint.create(4096, 128, jnp.bfloat16, 1,
                                         True))
        # Distributed, float64, sub-probe block sizes, and Nr beyond
        # the unrolled cap are all out.
        assert not gp.legal(TunePoint.create(4096, 128, jnp.float32,
                                             (2, 4), True))
        assert not gp.legal(TunePoint.create(4096, 128, jnp.float64, 1,
                                             True))
        assert not gp.legal(TunePoint.create(64, 8, jnp.float32, 1,
                                             True))
        assert not gp.legal(TunePoint.create(4096, 8, jnp.float32, 1,
                                             True))        # Nr = 512
        # Batched points (the serve executors' TunePoints) are out:
        # the fused-kernel engines have no vmapped variant, so a
        # batched plan naming them would be unbuildable by
        # serve/executors.py.
        assert not gp.legal(TunePoint.create(8192, 128, jnp.float32, 1,
                                             True, batch=16))
        batched16 = TunePoint(n=8192, block_size=128, dtype="bfloat16",
                              backend="tpu", chip="v5e", batch=16)
        assert select_by_cost(batched16).name == "grouped2"

    def test_cost_hooks(self):
        import math as _math

        gp = REGISTRY["grouped_pallas"]
        gb = REGISTRY["grouped_pallas_bf16"]
        g2 = REGISTRY["grouped2"]
        # Off-TPU the kernels run interpreted: never cost-preferred.
        cpu = TunePoint.create(8192, 128, jnp.float32, 1, True)
        assert cpu.backend == "cpu" and _math.isinf(gp.cost(cpu))
        # On a TPU point the fp32 kernel is priced just ABOVE the
        # measured grouped champion (finite -> inside tune=True's
        # survivor cut; above -> cost-only auto keeps the champion
        # until measured evidence promotes the new kernel).
        tpu = TunePoint(n=8192, block_size=128, dtype="float32",
                        backend="tpu", chip="v5e")
        assert g2.cost(tpu) < gp.cost(tpu) < _math.inf
        assert gp.cost(tpu) / g2.cost(tpu) == pytest.approx(1.02)
        # The bf16 variant undercuts fp32 (the recipe's MXU advantage);
        # below the grouped floor both stay priors.
        tpu16 = TunePoint(n=8192, block_size=128, dtype="bfloat16",
                          backend="tpu", chip="v5e")
        assert gb.cost(tpu16) < gp.cost(tpu16)
        small = TunePoint(n=4096, block_size=128, dtype="float32",
                          backend="tpu", chip="v5e")
        assert _math.isinf(gp.cost(small))

    def test_auto_selects_bf16_kernel_at_bf16_tpu_points(self):
        # A bf16-storage point on TPU at n >= 8192: the bf16 fused
        # kernel is the cost pick (the caller already accepted
        # bf16-grade numbers, and the driver still auto-attaches the
        # residual-gate ladder on that engine).
        pt = TunePoint(n=8192, block_size=128, dtype="bfloat16",
                       backend="tpu", chip="v5e")
        assert select_by_cost(pt).name == "grouped_pallas_bf16"
        # The same point at fp32 keeps the measured champion.
        pt32 = TunePoint(n=8192, block_size=128, dtype="float32",
                         backend="tpu", chip="v5e")
        assert select_by_cost(pt32).name == "grouped2"

    def test_explicit_engine_runs_without_registry_gate(self):
        # Explicit engine="grouped_pallas" bypasses legality (it is a
        # direct request, like every other explicit engine) and solves
        # correctly on CPU via the interpreter.
        from tpu_jordan.driver import solve

        r = solve(n=64, block_size=16, engine="grouped_pallas")
        assert r.engine == "grouped_pallas" and r.group == 2
        assert r.rel_residual < 1e-4


class TestPlanKey:
    def test_n_bucket(self):
        assert n_bucket(4096) == 4096
        assert n_bucket(4097) == 8192
        assert n_bucket(10000) == 16384
        assert n_bucket(1) == 1

    def test_key_coordinates(self):
        pt = TunePoint.create(10000, 512, jnp.float32, (4, 8),
                              gather=False, backend="tpu")
        assert plan_key(pt) == "tpu|4x8|n16384|float32|sharded"
        # The sniffed/forced chip generation rides the backend segment:
        # v5e-measured plans must not be honored on a v5p pod.
        ptp = TunePoint.create(10000, 512, jnp.float32, (4, 8),
                               gather=False, backend="tpu", chip="v5p")
        assert plan_key(ptp) == "tpu-v5p|4x8|n16384|float32|sharded"
        assert plan_key(ptp) != plan_key(pt)
        pt1 = TunePoint.create(64, 8, jnp.float64, 8, True, backend="cpu")
        assert plan_key(pt1) == "cpu|p8|n64|float64|gathered"
        assert plan_key(TunePoint.create(64, 8, jnp.float64, 1, True,
                                         backend="cpu")
                        ) == "cpu|single|n64|float64|gathered"

    def test_batch_segment(self):
        """ISSUE 3: batched points (the serving executors') key with a
        trailing ``bN`` segment; batch=1 keys are byte-identical to the
        PR 2 format, so pre-existing caches stay valid."""
        base = TunePoint.create(512, 128, jnp.float32, 1, True,
                                backend="cpu")
        batched = TunePoint.create(512, 128, jnp.float32, 1, True,
                                   backend="cpu", batch=32)
        assert plan_key(base) == "cpu|single|n512|float32|gathered"
        assert plan_key(batched) == "cpu|single|n512|float32|gathered|b32"
        assert base.batch == 1 and batched.batch == 32

    def test_workload_segment(self):
        """ISSUE 11: solve-workload points key with a trailing
        ``w<workload>`` segment; invert keys (the default) are
        byte-identical to the pre-ISSUE-11 format — batched or not —
        so every pre-existing cache stays valid."""
        base = TunePoint.create(512, 128, jnp.float32, 1, True,
                                backend="cpu")
        assert base.workload == "invert"
        assert plan_key(base) == "cpu|single|n512|float32|gathered"
        slv = TunePoint.create(512, 128, jnp.float32, 1, True,
                               backend="cpu", workload="solve")
        assert plan_key(slv) == "cpu|single|n512|float32|gathered|wsolve"
        spd_b = TunePoint.create(512, 128, jnp.float32, 1, True,
                                 backend="cpu", batch=8,
                                 workload="solve_spd")
        assert plan_key(spd_b) == \
            "cpu|single|n512|float32|gathered|b8|wsolve_spd"
        with pytest.raises(ValueError, match="workload"):
            TunePoint.create(512, 128, jnp.float32, 1, True,
                             workload="nope")


class TestPlanCache:
    def _plan(self):
        return Plan(config="swapfree", engine="swapfree", group=0,
                    source="measured", seconds=1.5e-3, projected=1.2e-3,
                    drift=1.25, trials=({"config": "swapfree",
                                         "measured": 1.5e-3},))

    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "plans.json")
        cache = PlanCache(path)
        cache.put("k", self._plan())
        cache.save()
        loaded = PlanCache.load(path)
        assert loaded.fallback_reason is None
        assert loaded.get("k") == self._plan()
        doc = json.loads((tmp_path / "plans.json").read_text())
        assert doc["version"] == CACHE_VERSION

    def test_missing_file_is_empty(self, tmp_path):
        cache = PlanCache.load(str(tmp_path / "nope.json"))
        assert cache.plans == {} and cache.fallback_reason is None

    def test_version_mismatch_falls_back(self, tmp_path):
        path = tmp_path / "plans.json"
        path.write_text(json.dumps({"version": CACHE_VERSION + 99,
                                    "plans": {"k": {}}}))
        cache = PlanCache.load(str(path))
        assert cache.plans == {}
        assert "version" in cache.fallback_reason

    @pytest.mark.parametrize("body", [
        "not json {{{",
        '{"plans": 3}',                       # no version, plans scalar
        json.dumps({"version": 1, "plans": {"k": 42}}),   # plan scalar
        json.dumps({"version": 1, "plans": {"k": {"engine": "x"}}}),
    ])
    def test_corrupt_file_falls_back(self, tmp_path, body):
        path = tmp_path / "plans.json"
        path.write_text(body)
        cache = PlanCache.load(str(path))
        assert cache.plans == {}
        assert cache.fallback_reason is not None
        # A save after fallback rewrites the file cleanly.
        cache.put("k", self._plan())
        cache.save()
        assert PlanCache.load(str(path)).get("k") == self._plan()


class TestPlanCacheReadOnly:
    """ISSUE 7 satellite: a fleet replica opens the shared pre-tuned
    cache read-only — reads are lock-free dict hits, any write attempt
    is the typed UsageError, and the tuner skips its write-back instead
    of tripping it."""

    def _pretuned(self, tmp_path):
        path = str(tmp_path / "plans.json")
        cache = PlanCache(path)
        cache.put("k", Plan(config="inplace", engine="inplace", group=0,
                            source="cost_model", seconds=None,
                            projected=1e-3, drift=None, trials=()))
        cache.save()
        return path

    def test_reads_work_writes_are_typed_usage_errors(self, tmp_path):
        from tpu_jordan.driver import UsageError

        path = self._pretuned(tmp_path)
        ro = PlanCache.load(path, read_only=True)
        assert ro.read_only and ro.get("k").engine == "inplace"
        with pytest.raises(UsageError, match="read-only"):
            ro.put("k2", ro.get("k"))
        with pytest.raises(UsageError, match="read-only"):
            ro.save()
        # The file is untouched by the refused writes.
        assert PlanCache.load(path).plans.keys() == {"k"}

    def test_read_only_missing_file_is_typed_usage_error(self, tmp_path):
        """Read-only mode serves a pre-tuned FILE: a typoed path must
        fail fast, not silently become an empty cache that serves the
        whole fleet off cost ranking."""
        from tpu_jordan.driver import UsageError

        missing = str(tmp_path / "plnas.json")
        with pytest.raises(UsageError, match="does not exist"):
            PlanCache.load(missing, read_only=True)
        # Writable mode keeps the documented empty-cache fallback.
        assert PlanCache.load(missing).plans == {}

    def test_tuner_skips_write_back_on_read_only_cache(self, tmp_path):
        path = self._pretuned(tmp_path)
        before = (tmp_path / "plans.json").read_text()
        t = Tuner(cache=PlanCache.load(path, read_only=True))
        point = TunePoint.create(64, 8, jnp.float64, 8, gather=False,
                                 backend="cpu")
        plan = t.select(point)              # cache miss -> cost ranking
        assert plan.source == "cost_model"
        # Selection succeeded WITHOUT writing the shared file (the
        # put/save pair a writable cache would get is skipped).
        assert (tmp_path / "plans.json").read_text() == before

    def test_concurrent_readers_share_one_pretuned_file(self, tmp_path):
        import threading

        path = self._pretuned(tmp_path)
        caches = [PlanCache.load(path, read_only=True) for _ in range(4)]
        hits, errs = [], []

        def reader(cache):
            try:
                for _ in range(200):
                    hits.append(cache.get("k").engine)
            except Exception as e:            # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=reader, args=(c,))
                   for c in caches]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == [] and len(hits) == 800


def _fake_measure(timings):
    """Injected measurement: per-config fixed fake seconds (the
    deterministic-selection satellite) shaped like the robust core's
    output."""
    def fn(point, cfg, samples=5):
        s = timings[cfg.name]
        return Measurement(seconds=s, samples=(s,) * samples,
                           accepted=(s,) * samples)
    return fn


class TestTuner:
    def point(self):
        return TunePoint.create(64, 8, jnp.float64, 8, gather=False,
                                backend="cpu")

    def test_cost_only_selection_is_deterministic_and_free(self):
        t = Tuner()
        p1, p2 = t.select(self.point()), t.select(self.point())
        assert p1 == p2
        assert p1.source == "cost_model"
        assert t.measurements == 0

    def test_fake_timings_deterministic_selection(self):
        # lookahead injected fastest: measurement must overrule the
        # cost ranking (which puts grouped2 first at this point; the
        # survivor cut here is grouped2/swapfree/lookahead).
        timings = {"lookahead": 1e-3, "grouped2": 5e-3, "swapfree": 7e-3,
                   "augmented": 9e-3}
        t = Tuner(measure=True, measure_fn=_fake_measure(timings))
        plan = t.select(self.point())
        assert plan.config == "lookahead" and plan.source == "measured"
        assert plan.seconds == 1e-3
        assert t.measurements == len(plan.trials) == 3   # survivor cut
        # Measured-vs-projected drift is recorded on every trial.
        assert all(tr["drift"] is not None and tr["drift"] > 0
                   for tr in plan.trials)
        t2 = Tuner(measure=True, measure_fn=_fake_measure(timings))
        assert t2.select(self.point()) == plan           # deterministic

    def test_warm_cache_zero_measurements(self, tmp_path):
        """The acceptance pin: a second selection at the same key with a
        warm plan cache performs ZERO measurements."""
        path = str(tmp_path / "plans.json")
        timings = {"inplace": 2e-3, "grouped2": 1e-3, "swapfree": 3e-3,
                   "lookahead": 4e-3, "augmented": 9e-3}
        t1 = Tuner(cache=PlanCache(path), measure=True,
                   measure_fn=_fake_measure(timings))
        plan1 = t1.select(self.point())
        assert t1.measurements == 3 and plan1.config == "grouped2"
        t2 = Tuner(cache=PlanCache.load(path), measure=True,
                   measure_fn=_fake_measure(timings))
        plan2 = t2.select(self.point())
        assert t2.measurements == 0, "warm cache must skip measurement"
        assert plan2 == plan1
        assert t2.last_source == "cache"

    def test_tune_not_satisfied_by_cost_model_cache_entry(self, tmp_path):
        """A cost_model-sourced cache entry (written by a plain auto
        solve) must NOT short-circuit an explicit tune=True request —
        otherwise the unmeasured guess is pinned forever.  The measured
        result then replaces it, and a later measuring tuner IS
        satisfied by the measured entry."""
        path = str(tmp_path / "plans.json")
        timings = {"inplace": 2e-3, "grouped2": 1e-3, "swapfree": 3e-3,
                   "lookahead": 4e-3, "augmented": 9e-3}
        plain = Tuner(cache=PlanCache(path))
        assert plain.select(self.point()).source == "cost_model"
        t = Tuner(cache=PlanCache.load(path), measure=True,
                  measure_fn=_fake_measure(timings))
        plan = t.select(self.point())
        assert t.measurements == 3 and plan.source == "measured"
        t2 = Tuner(cache=PlanCache.load(path), measure=True,
                   measure_fn=_fake_measure(timings))
        assert t2.select(self.point()) == plan and t2.measurements == 0

    def test_stale_cache_entry_falls_through(self, tmp_path):
        """A cached plan whose config vanished from the registry (or
        went illegal at the point) is NOT honored — selection re-runs."""
        path = str(tmp_path / "plans.json")
        cache = PlanCache(path)
        cache.put(plan_key(self.point()),
                  Plan(config="retired-engine", engine="retired", group=0))
        cache.save()
        t = Tuner(cache=PlanCache.load(path))
        plan = t.select(self.point())
        assert plan.config in REGISTRY and plan.source == "cost_model"
        # ... and the refreshed plan replaced the stale entry on disk.
        assert (PlanCache.load(path).get(plan_key(self.point())).config
                == plan.config)

    def test_illegal_at_point_falls_through(self, tmp_path):
        # swapfree cached for a distributed key must not leak into a
        # single-device point that hashes to a different key — and even
        # a hand-poisoned single-device swapfree entry is re-selected.
        single = TunePoint.create(64, 8, jnp.float64, 1, True,
                                  backend="cpu")
        path = str(tmp_path / "plans.json")
        cache = PlanCache(path)
        cache.put(plan_key(single), Plan(config="swapfree",
                                         engine="swapfree", group=0))
        cache.save()
        plan = Tuner(cache=PlanCache.load(path)).select(single)
        assert plan.config != "swapfree"


class TestSolveSurface:
    """The product surface: solve(engine='auto', tune=..., plan_cache=...)
    measured once, served from the warm cache forever after (counter
    pinned through monkeypatched measure_config — no real measurement in
    tier-1)."""

    def test_solve_tune_writes_cache_then_zero_measurements(
            self, tmp_path, monkeypatch):
        import tpu_jordan.tuning.tuner as tuner_mod
        from tpu_jordan.driver import solve

        calls = []

        def fake(point, cfg, samples=5):
            t = {"inplace": 2e-3, "grouped2": 3e-3, "swapfree": 1e-3,
                 "lookahead": 5e-3, "augmented": 9e-3}[cfg.name]
            calls.append(cfg.name)
            return Measurement(seconds=t, samples=(t,), accepted=(t,))

        monkeypatch.setattr(tuner_mod, "measure_config", fake)
        path = str(tmp_path / "plans.json")
        r1 = solve(64, 8, workers=8, gather=False, dtype=jnp.float64,
                   engine="auto", tune=True, plan_cache=path)
        assert r1.engine == "swapfree" and r1.plan.source == "measured"
        assert len(calls) == 3
        r2 = solve(64, 8, workers=8, gather=False, dtype=jnp.float64,
                   engine="auto", tune=True, plan_cache=path)
        assert len(calls) == 3, "warm plan cache must measure nothing"
        assert r2.engine == r1.engine
        assert bool(jnp.all(jnp.asarray(r1.inverse_blocks)
                            == jnp.asarray(r2.inverse_blocks)))

    def test_tune_with_explicit_engine_is_usage_error(self):
        from tpu_jordan.driver import UsageError, solve
        from tpu_jordan.models import JordanSolver

        with pytest.raises(UsageError, match="auto"):
            solve(64, 8, workers=4, engine="inplace", tune=True)
        with pytest.raises(UsageError, match="auto"):
            solve(64, 8, engine="grouped", plan_cache="/tmp/x.json")
        with pytest.raises(UsageError, match="auto"):
            JordanSolver(64, 8, engine="inplace", tune=True)

    def test_solver_auto_resolves_through_registry(self):
        from tpu_jordan.models import JordanSolver

        s = JordanSolver(64, 8, dtype=jnp.float64, workers=(2, 4))
        assert s.engine in {c.engine for c in CONFIGS}
        assert s.plan is not None

    def test_cli_tune_flags(self, tmp_path):
        from tpu_jordan.__main__ import main

        path = str(tmp_path / "plans.json")
        # --tune with an explicit engine: usage error (exit 1), before
        # any device work.
        assert main(["32", "8", "--engine", "inplace", "--tune",
                     "--quiet"]) == 1
        assert main(["32", "8", "--batch", "2", "--tune", "--quiet"]) == 1
        # Warm-start path: a seeded cache means --engine auto performs
        # zero measurements even with --tune (the pre-tuned-pod flow).
        pt = TunePoint.create(32, 8, jnp.float64, 1, True)
        cache = PlanCache(path)
        cache.put(plan_key(pt), Plan(config="inplace", engine="inplace",
                                     group=0, source="measured",
                                     seconds=1e-3))
        cache.save()
        assert main(["32", "8", "--dtype", "float64", "--engine", "auto",
                     "--tune", "--plan-cache", path, "--quiet"]) == 0
        doc = json.loads((tmp_path / "plans.json").read_text())
        assert doc["version"] == CACHE_VERSION


class TestMeasureCore:
    def test_robust_stats_median_and_spread(self):
        m = robust_stats([1.0, 1.1, 0.9])
        assert m.seconds == 1.0
        assert m.rejected == ()
        assert m.variance_flag is not None      # 20% spread > 10%
        tight = robust_stats([1.0, 1.001, 0.999, 1.002, 0.998])
        assert tight.variance_flag is None

    def test_iqr_rejects_wild_outlier(self):
        # One 10x sample (a session hiccup) must not drag the median or
        # the spread stats.
        m = robust_stats([1.0, 1.01, 0.99, 1.02, 10.0])
        assert m.seconds == pytest.approx(1.005)
        assert m.rejected == (10.0,)
        assert len(m.accepted) == 4
        assert m.variance_flag is None

    def test_k3_fence_never_rejects_median_still_damps(self):
        # At k=3 the interpolated quartiles stretch with the outlier, so
        # the Tukey fence provably cannot exclude it — the median is the
        # damper (it ignores one wild sample by construction) and the
        # spread trips the variance flag.  Documented behavior, pinned.
        m = robust_stats([1.0, 1.01, 50.0])
        assert m.rejected == ()
        assert m.seconds == 1.01
        assert m.variance_flag is not None

    def test_robust_stats_degenerate(self):
        assert robust_stats([2.0]).seconds == 2.0
        assert robust_stats([2.0, 4.0]).seconds == 3.0
        with pytest.raises(ValueError):
            robust_stats([])

    def test_is_transient_requires_type_and_marker(self):
        from tpu_jordan.tuning.measure import is_transient, retry_transient

        assert is_transient(OSError("INTERNAL: read body too short"))
        assert not is_transient(AssertionError("INTERNAL quoted"))
        assert not is_transient(OSError("disk full"))
        # retry_transient: transient retried once, others propagate.
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("DEADLINE exceeded")
            return "ok"

        assert retry_transient(flaky) == "ok"
        with pytest.raises(AssertionError):
            retry_transient(lambda: (_ for _ in ()).throw(
                AssertionError("INTERNAL")))

    def test_measure_slope_returns_measurement(self):
        """The bench.py integration point, on a trivial CPU op."""
        from tpu_jordan.tuning.measure import measure_slope

        a = jnp.ones((16, 16), jnp.float32)
        m = measure_slope(lambda v: v * 1.0000001, (a,), r1=2, r2=4,
                          samples=3)
        # A noise-floor op's slope may land either side of zero; the
        # contract here is the robust-core packaging, not the value.
        assert isinstance(m.seconds, float)
        assert len(m.samples) == 3
        assert m.spread_pct >= 0.0


@pytest.mark.slow
class TestRealMeasurement:
    """Real engine measurements (satellite: slow-marked so tier-1 stays
    inside its timeout; tier-1 covers the tuner on fake timings)."""

    def test_tuner_measures_real_engines_and_records_drift(self):
        point = TunePoint.create(64, 8, jnp.float64, 8, gather=False,
                                 backend="cpu")
        t = Tuner(measure=True, samples=3)
        plan = t.select(point)
        assert plan.source == "measured"
        assert t.measurements == len(plan.trials) >= 2
        assert plan.seconds > 0
        assert all(tr["measured"] > 0 for tr in plan.trials)
        # comm_model drift observable on every measured trial.
        assert all(tr["drift"] is not None for tr in plan.trials)

    def test_single_device_real_measurement(self):
        from tpu_jordan.tuning import measure_config

        point = TunePoint.create(48, 8, jnp.float64, 1, True,
                                 backend="cpu")
        m = measure_config(point, REGISTRY["inplace"], samples=3)
        assert m.seconds > 0 and len(m.samples) == 3
