"""2D distributed in-place (2N³) elimination: parity on the 8-device
virtual CPU mesh across mesh shapes (VERDICT r2 item #1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import block_jordan_invert_inplace, generate
from tpu_jordan.parallel import make_mesh_2d
from tpu_jordan.parallel.jordan2d_inplace import (
    sharded_jordan_invert_inplace_2d,
)


class TestSharded2DInplace:
    @pytest.mark.parametrize("shape", [
        # tier-1 headroom (ISSUE 3): all shapes nightly; tier-1 keeps
        # the numpy-oracle smoke case below + the tied-pivot and
        # fori/grouped 2D parity pins.
        pytest.param((2, 4), marks=pytest.mark.slow),
        pytest.param((4, 2), marks=pytest.mark.slow),
        pytest.param((2, 2), marks=pytest.mark.slow)])
    def test_matches_single_device_inplace(self, rng, shape):
        mesh = make_mesh_2d(*shape)
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        inv_d, s_d = sharded_jordan_invert_inplace_2d(a, mesh, 8)
        inv_s, s_s = block_jordan_invert_inplace(a, block_size=8)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(
            np.asarray(inv_d), np.asarray(inv_s), rtol=1e-9, atol=1e-9
        )

    @pytest.mark.smoke      # the 2D-layout engine case
    def test_matches_linalg_inv(self, rng):
        mesh = make_mesh_2d(2, 4)
        # n=48 still wraps the column cycle (6 blocks over pc=4) at
        # half the unrolled-trace cost of the old 96 (smoke budget).
        a = jnp.asarray(rng.standard_normal((48, 48)), jnp.float64)
        inv, sing = sharded_jordan_invert_inplace_2d(a, mesh, 8)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(np.asarray(a)), rtol=1e-7,
            atol=1e-7,
        )

    def test_tied_pivots_swaps_cross_mesh_columns(self, rng):
        # |i-j| forces repeated swaps; with pc=4 the swap partners live on
        # different mesh columns, exercising the collective unscramble.
        from tpu_jordan.parallel.jordan2d import sharded_jordan_invert_2d

        mesh = make_mesh_2d(2, 4)
        a = generate("absdiff", (96, 96), jnp.float64)
        inv_i, s_i = sharded_jordan_invert_inplace_2d(a, mesh, 8)
        inv_a, s_a = sharded_jordan_invert_2d(a, mesh, 8)
        assert bool(s_i) == bool(s_a) is False
        np.testing.assert_allclose(
            np.asarray(inv_i), np.asarray(inv_a), rtol=1e-9, atol=1e-12
        )

    @pytest.mark.slow  # tier-1 budget: test_matches_linalg_inv keeps the fast-run 2D pin
    def test_singular_collective_agreement(self):
        mesh = make_mesh_2d(2, 4)
        _, sing = sharded_jordan_invert_inplace_2d(
            jnp.ones((64, 64), jnp.float64), mesh, 8
        )
        assert bool(sing)

    def test_sub_fp32_upcast_policy(self, rng):
        mesh = make_mesh_2d(2, 2)
        a = jnp.asarray(rng.standard_normal((32, 32)), jnp.bfloat16)
        inv, sing = sharded_jordan_invert_inplace_2d(a, mesh, 8)
        assert inv.dtype == jnp.bfloat16
        assert not bool(sing)

    @pytest.mark.parametrize("pr,pc,n,m", [
        # tier-1 headroom (ISSUE 3): bit-identical twin — the
        # single-device fori parity is a smoke test and the 1D fori
        # parity stays tier-1; all 2D shapes run nightly.
        pytest.param(2, 4, 128, 16, marks=pytest.mark.slow),
        pytest.param(4, 2, 128, 16, marks=pytest.mark.slow),
        pytest.param(2, 2, 96, 8, marks=pytest.mark.slow)])
    def test_fori_bitmatches_unrolled(self, rng, pr, pc, n, m):
        # Traced-t engine vs unrolled trace: identical pivots, identical
        # bits — including the collective column-swap unscramble.
        mesh = make_mesh_2d(pr, pc)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        x_u, s_u = sharded_jordan_invert_inplace_2d(a, mesh, m, unroll=True)
        x_f, s_f = sharded_jordan_invert_inplace_2d(a, mesh, m, unroll=False)
        assert bool(s_u) == bool(s_f)
        assert bool(jnp.all(x_u == x_f)), "2D fori engine diverged bitwise"

    def test_beyond_unroll_cap(self, rng):
        # Nr = 68 > MAX_UNROLL_NR runs through the 2D fori engine
        # (used to raise ValueError; VERDICT r3 item #1).
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 544, 8
        assert -(-n // m) > MAX_UNROLL_NR
        mesh = make_mesh_2d(2, 4)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = sharded_jordan_invert_inplace_2d(a, mesh, m)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(n)))
        assert res < 1e-7

    def test_driver_2d_inplace_covers_large_nr(self):
        from tpu_jordan.driver import _Dist2D

        be = _Dist2D((2, 4), 1024, 8)   # Nr=128 > 64
        assert be.inplace


class TestSharded2DGrouped:
    """The 2D delayed-group-update engine (VERDICT r4 #1): rounding-level
    parity with the plain engines, bit-identical grouped unrolled/fori
    pair, cross-mesh-column swaps and the collective unscramble intact."""

    @pytest.mark.parametrize("shape", [
        # tier-1 headroom (ISSUE 3): the parity chain stays connected
        # in tier-1 via grouped-2D vs plain-2D (below) and plain-2D vs
        # the numpy oracle; all shapes nightly.
        pytest.param((2, 4), marks=pytest.mark.slow),
        pytest.param((4, 2), marks=pytest.mark.slow),
        pytest.param((2, 2), marks=pytest.mark.slow)])
    def test_grouped_matches_single_chip_grouped(self, rng, shape):
        from tpu_jordan.ops import block_jordan_invert_inplace_grouped

        mesh = make_mesh_2d(*shape)
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        x_d, s_d = sharded_jordan_invert_inplace_2d(a, mesh, 8, group=2)
        x_s, s_s = block_jordan_invert_inplace_grouped(a, block_size=8,
                                                       group=2)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(np.asarray(x_d), np.asarray(x_s),
                                   rtol=1e-9, atol=1e-9)

    @pytest.mark.parametrize("n,m,k", [
        # tier-1 budget: test_grouped_tied_pivots_cross_mesh_columns
        # keeps the fast-run 2D grouped pin; the size ladder is nightly.
        pytest.param(96, 8, 4, marks=pytest.mark.slow),
        pytest.param(128, 16, 4, marks=pytest.mark.slow),
        pytest.param(100, 8, 3, marks=pytest.mark.slow)])
    def test_grouped_matches_plain_to_rounding(self, rng, n, m, k):
        mesh = make_mesh_2d(2, 4)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x_p, s_p = sharded_jordan_invert_inplace_2d(a, mesh, m)
        x_g, s_g = sharded_jordan_invert_inplace_2d(a, mesh, m, group=k)
        assert bool(s_p) == bool(s_g) is False
        np.testing.assert_allclose(np.asarray(x_g), np.asarray(x_p),
                                   rtol=1e-9, atol=1e-9)

    @pytest.mark.slow  # tier-1 budget: the plain-engine cross-mesh tied-pivot
    # pin (TestSharded2DInplace::test_tied_pivots_swaps_cross_mesh_columns)
    # and the fast grouped-parity params above keep tier-1 coverage
    def test_grouped_tied_pivots_cross_mesh_columns(self):
        # |i-j|: repeated candidates + zero diagonal; pc=4 puts swap
        # partners on different mesh columns within one group.
        from tpu_jordan.ops import block_jordan_invert_inplace_grouped

        mesh = make_mesh_2d(2, 4)
        a = generate("absdiff", (96, 96), jnp.float64)
        x_d, s_d = sharded_jordan_invert_inplace_2d(a, mesh, 8, group=4)
        x_s, s_s = block_jordan_invert_inplace_grouped(a, block_size=8,
                                                       group=4)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(np.asarray(x_d), np.asarray(x_s),
                                   rtol=1e-9, atol=1e-12)

    @pytest.mark.parametrize("pr,pc,n,m,k", [
        # tier-1 headroom (ISSUE 3): bit-identical twin — grouped-fori
        # parity stays tier-1 at single-device and 1D; nightly here.
        pytest.param(2, 4, 128, 16, 2, marks=pytest.mark.slow),
        pytest.param(4, 2, 96, 8, 4, marks=pytest.mark.slow),
        pytest.param(2, 2, 100, 8, 3, marks=pytest.mark.slow)])
    def test_grouped_fori_bitmatches_unrolled(self, rng, pr, pc, n, m, k):
        mesh = make_mesh_2d(pr, pc)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        x_u, s_u = sharded_jordan_invert_inplace_2d(a, mesh, m, group=k,
                                                    unroll=True)
        x_f, s_f = sharded_jordan_invert_inplace_2d(a, mesh, m, group=k,
                                                    unroll=False)
        assert bool(s_u) == bool(s_f)
        assert bool(jnp.all(x_u == x_f)), "2D grouped fori diverged"

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): the 1D grouped and
    #   2D plain singular-agreement pins stay tier-1
    def test_grouped_singular_collective_agreement(self):
        mesh = make_mesh_2d(2, 4)
        _, s_u = sharded_jordan_invert_inplace_2d(
            jnp.ones((64, 64), jnp.float64), mesh, 8, group=4)
        assert bool(s_u)
        _, s_f = sharded_jordan_invert_inplace_2d(
            jnp.ones((64, 64), jnp.float64), mesh, 8, group=4,
            unroll=False)
        assert bool(s_f)

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): beyond-cap grouped
    #   dispatch stays tier-1 at single-device and 1D
    def test_grouped_beyond_unroll_cap(self, rng):
        # Nr = 68 > MAX_UNROLL_NR routes to the 2D grouped fori engine.
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 544, 8
        assert -(-n // m) > MAX_UNROLL_NR
        mesh = make_mesh_2d(2, 4)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = sharded_jordan_invert_inplace_2d(a, mesh, m, group=4)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(n)))
        assert res < 1e-7


class TestProbeLayoutSwitch:
    """The per-backend probe layout (VERDICT r4 weak #6): owner-column on
    CPU meshes (batch-insensitive probe cost), column-parallel on TPU —
    bitwise-identical pivot choices and results either way."""

    def test_auto_resolves_by_backend(self):
        import jax

        from tpu_jordan.parallel.jordan2d_inplace import (
            resolve_probe_layout,
        )

        assert resolve_probe_layout("column") is True
        assert resolve_probe_layout("owner") is False
        want = jax.default_backend() == "tpu"
        assert resolve_probe_layout("auto") is want
        with pytest.raises(ValueError, match="probe_layout"):
            resolve_probe_layout("sideways")

    @pytest.mark.parametrize("unroll", [
        pytest.param(True, marks=pytest.mark.slow), False])
    def test_layouts_bitmatch(self, rng, unroll):
        mesh = make_mesh_2d(2, 4)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        x_c, s_c = sharded_jordan_invert_inplace_2d(
            a, mesh, 8, unroll=unroll, probe_layout="column")
        x_o, s_o = sharded_jordan_invert_inplace_2d(
            a, mesh, 8, unroll=unroll, probe_layout="owner")
        assert bool(s_c) == bool(s_o)
        assert bool(jnp.all(x_c == x_o)), "probe layouts diverged bitwise"

    @pytest.mark.slow
    def test_layouts_bitmatch_grouped(self, rng):
        mesh = make_mesh_2d(2, 2)
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
        x_c, _ = sharded_jordan_invert_inplace_2d(
            a, mesh, 8, group=2, probe_layout="column")
        x_o, _ = sharded_jordan_invert_inplace_2d(
            a, mesh, 8, group=2, probe_layout="owner")
        assert bool(jnp.all(x_c == x_o))

    @pytest.mark.slow  # tier-1 budget: the layout-switch policy siblings stay fast
    def test_layouts_bitmatch_tied_pivots(self):
        # |i-j|: exact ties — the tie-break must not depend on which
        # device probed the candidate.
        mesh = make_mesh_2d(2, 4)
        a = generate("absdiff", (96, 96), jnp.float64)
        x_c, _ = sharded_jordan_invert_inplace_2d(a, mesh, 8,
                                                  probe_layout="column")
        x_o, _ = sharded_jordan_invert_inplace_2d(a, mesh, 8,
                                                  probe_layout="owner")
        assert bool(jnp.all(x_c == x_o))


class TestColumnParallelProbe:
    """The round-4 column-parallel probe: every mesh column probes the
    slot slice ``s0+kc, s0+kc+pc, ...`` of the broadcast t-chunk panel.
    These pin the slice-coverage invariant the engines rely on."""

    @pytest.mark.parametrize("bpr,pc", [(8, 4), (8, 3), (5, 2), (7, 4),
                                        (1, 4), (16, 8)])
    def test_column_slices_partition_live_window(self, bpr, pc):
        # Union over kc of {s0+kc+u*pc : u < wnd} ∩ [0, bpr) must cover
        # [s0, bpr) exactly once, for every live-window start s0 — each
        # candidate probed by exactly one device.
        for s0 in range(bpr):
            wnd = -(-(bpr - s0) // pc)
            seen = []
            for kc in range(pc):
                idx = [s0 + kc + u * pc for u in range(wnd)]
                seen += [i for i in idx if i < bpr]
            assert sorted(seen) == list(range(s0, bpr)), (s0, pc, seen)

    def test_quarter_ladder_skipped_slots_are_dead(self):
        # probe_blocks_quarter_masked skips the first
        # qi = clip((t // stride) // q, 0, 3) quarters (q = w // 4) of
        # the candidate window.  Safety invariant, exhaustively: every
        # skipped slot's smallest possible global row is < t, at every
        # step, for each call layout's stride (1 single-chip, p 1D,
        # pr owner-2D, pc·pr column-2D — slot i of the column slice
        # covers rows (kc + i·pc)·pr + kr >= i·pc·pr).
        for w, stride in ((128, 1), (16, 4), (8, 2), (12, 3), (16, 8)):
            if w < 8:
                continue
            q = w // 4
            for t in range(w * stride):
                qi = min(max((t // stride) // q, 0), 3)
                for i in range(qi * q):
                    # slot i's global rows are >= i*stride and the slot
                    # is skipped — it must be dead: i*stride + anything
                    # the layout adds stays < t only if i < t // stride.
                    assert i < t // stride, (w, stride, t, i)
                    assert i * stride + (stride - 1) < t, (w, stride, t, i)


class TestSwapFree2D:
    """The swap-free 2D engine (round 5): no row_t psum, no swap
    fix-up, no per-step psum unscramble — bit-identical to the swap
    engines, ties included."""

    @pytest.mark.parametrize("shape,n,m", [
        # tier-1 budget: the (4, 2) case keeps the fast-run pin.
        pytest.param((2, 4), 96, 8, marks=pytest.mark.slow),
        ((4, 2), 64, 8),
        pytest.param((2, 2), 100, 8, marks=pytest.mark.slow),
        pytest.param((2, 4), 256, 8,
                     marks=pytest.mark.slow)])  # ladder size
    def test_bitmatches_swap_engine(self, rng, shape, n, m):
        mesh = make_mesh_2d(*shape)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x_sf, s_sf = sharded_jordan_invert_inplace_2d(a, mesh, m,
                                                      swapfree=True)
        x_sw, s_sw = sharded_jordan_invert_inplace_2d(a, mesh, m)
        assert bool(s_sf) == bool(s_sw) is False
        assert bool(jnp.all(x_sf == x_sw)), "2D swap-free diverged"

    def test_tied_pivots_bitmatch(self):
        mesh = make_mesh_2d(2, 4)
        a = generate("absdiff", (96, 96), jnp.float64)
        x_sf, s_sf = sharded_jordan_invert_inplace_2d(a, mesh, 8,
                                                      swapfree=True)
        x_sw, s_sw = sharded_jordan_invert_inplace_2d(a, mesh, 8)
        assert bool(s_sf) == bool(s_sw) is False
        assert bool(jnp.all(x_sf == x_sw))

    def test_singular_collective_agreement(self):
        mesh = make_mesh_2d(2, 4)
        _, sing = sharded_jordan_invert_inplace_2d(
            jnp.ones((64, 64), jnp.float64), mesh, 8, swapfree=True)
        assert bool(sing)

    @pytest.mark.slow  # tier-1 budget: the 1D twin in test_sharded_inplace
    # keeps the fast-run all-singular-divergence pin
    def test_all_singular_flags_agree_but_arrays_diverge(self):
        # Bit-match is scoped to NONSINGULAR inputs (see the 1D twin's
        # test): on all-singular input both flag singular, the arrays
        # diverge bitwise (different benign pin targets — ADVICE r5).
        mesh = make_mesh_2d(2, 4)
        ones = jnp.ones((64, 64), jnp.float64)
        x_sf, s_sf = sharded_jordan_invert_inplace_2d(ones, mesh, 8,
                                                      swapfree=True)
        x_sw, s_sw = sharded_jordan_invert_inplace_2d(ones, mesh, 8)
        assert bool(s_sf) and bool(s_sw)
        assert not bool(jnp.all(x_sf == x_sw))

    def test_solve_engine_swapfree_2d(self):
        from tpu_jordan.driver import solve

        r = solve(96, 8, workers=(2, 4), dtype=jnp.float64,
                  engine="swapfree")
        assert r.residual < 1e-9 * 96 * 95
        assert r.kappa is not None

    def test_solve_engine_swapfree_2d_no_gather(self):
        # Legal since the bucketed-ppermute repairs (parallel/permute.py):
        # rows along "pr", columns along "pc", residency one shard.
        from tpu_jordan.driver import solve

        r = solve(96, 8, workers=(2, 4), dtype=jnp.float64,
                  engine="swapfree", gather=False)
        assert r.inverse is None
        assert r.inverse_blocks.shape == (12, 8, 96)
        assert r.residual < 1e-9 * 96 * 95


class TestLookahead2D:
    """The 2D probe-ahead engine (ISSUE 16): step t+1's chunk broadcast
    along "pc" + probe reduction over the whole mesh issue right after
    the critical panel, before the trailing eliminate.  Bits, pivot
    sequence, and the collective multiset (tests/test_comm.py) pin
    identical to the plain 2D engine."""

    @pytest.mark.smoke      # the 2D probe-ahead engine-parity case
    def test_tied_pivots_and_forced_swaps_bitmatch(self, rng):
        # absdiff forces a row swap every superstep with exact ties;
        # ragged n puts the identity-padded tail inside the carried
        # panel; (2, 4) exercises cross-mesh-column panel ownership.
        # n kept at the smallest ragged size with a swap per superstep
        # (smoke budget: the unrolled trace cost scales with Nr).
        mesh = make_mesh_2d(2, 4)
        a = generate("absdiff", (44, 44), jnp.float64)
        x_p, s_p = sharded_jordan_invert_inplace_2d(a, mesh, 8)
        x_l, s_l = sharded_jordan_invert_inplace_2d(a, mesh, 8,
                                                    lookahead=True)
        assert bool(s_p) == bool(s_l) is False
        assert bool(jnp.all(x_p == x_l)), \
            "2D probe-ahead engine diverged bitwise from inplace"

    @pytest.mark.slow  # tier-1 budget (ISSUE 16): the smoke bitmatch keeps a tier-1 sibling
    def test_bitmatches_inplace_rand(self, rng):
        mesh = make_mesh_2d(2, 2)
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        x_p, s_p = sharded_jordan_invert_inplace_2d(a, mesh, 8)
        x_l, s_l = sharded_jordan_invert_inplace_2d(a, mesh, 8,
                                                    lookahead=True)
        assert bool(s_p) == bool(s_l) is False
        assert bool(jnp.all(x_p == x_l))

    @pytest.mark.slow  # tier-1 budget: the 1D driver-routing leg in
    # test_sharded_inplace (engine="lookahead" via solve()) and the smoke
    # 2D parity case above keep tier-1 coverage
    def test_driver_engine_string_routes_and_bitmatches(self):
        from tpu_jordan.driver import solve

        r_l = solve(64, 8, workers=(2, 2), dtype=jnp.float64,
                    engine="lookahead", gather=False)
        r_p = solve(64, 8, workers=(2, 2), dtype=jnp.float64,
                    engine="inplace", gather=False)
        assert r_l.engine == "lookahead"
        assert bool(jnp.all(jnp.asarray(r_l.inverse_blocks)
                            == jnp.asarray(r_p.inverse_blocks)))

    def test_usage_gates_are_typed(self, rng):
        from tpu_jordan.driver import UsageError
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        mesh = make_mesh_2d(2, 2)
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        with pytest.raises(UsageError, match="swapfree/group"):
            sharded_jordan_invert_inplace_2d(a, mesh, 8, lookahead=True,
                                             swapfree=True)
        with pytest.raises(UsageError, match="swapfree/group"):
            sharded_jordan_invert_inplace_2d(a, mesh, 8, lookahead=True,
                                             group=2)
        n_big = 8 * (MAX_UNROLL_NR + 4)
        a_big = jnp.asarray(rng.standard_normal((n_big, n_big)),
                            jnp.float32)
        with pytest.raises(UsageError, match="unrolled-only"):
            sharded_jordan_invert_inplace_2d(a_big, mesh, 8,
                                             lookahead=True)
