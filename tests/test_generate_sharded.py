"""Shard-local generation (init_matrix parity) and the fully distributed
residual: no host-side n×n arrays anywhere in the generator-driven
distributed solve."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_jordan.driver import solve
from tpu_jordan.ops import generate
from tpu_jordan.parallel import (
    CyclicLayout,
    distributed_residual_blocks,
    make_mesh,
    sharded_generate,
)
from tpu_jordan.parallel.sharded_jordan import scatter_augmented


@pytest.fixture
def mesh8():
    return make_mesh(8)


class TestShardedGenerate:
    @pytest.mark.parametrize("name", ["absdiff", "hilbert", "identity"])
    @pytest.mark.parametrize("n,m", [(64, 8), (50, 8), (40, 4)])
    def test_matches_host_scatter(self, mesh8, name, n, m):
        # Device-side generation must produce bit-identical blocks to the
        # host materialize-then-scatter path.
        lay = CyclicLayout.create(n, m, 8)
        dev = sharded_generate(name, lay, mesh8, jnp.float64, augmented=True)
        host = scatter_augmented(
            generate(name, (n, n), jnp.float64), lay, mesh8
        )
        np.testing.assert_array_equal(np.asarray(dev), np.asarray(host))

    def test_unaugmented_matches_padded_a(self, mesh8):
        from tpu_jordan.ops.padding import pad_with_identity
        from tpu_jordan.parallel.layout import cyclic_gather_perm

        n, m = 52, 8
        lay = CyclicLayout.create(n, m, 8)
        dev = sharded_generate("absdiff", lay, mesh8, jnp.float64)
        a = pad_with_identity(generate("absdiff", (n, n), jnp.float64), lay.N)
        blocks = jnp.take(a.reshape(lay.Nr, lay.m, lay.N),
                          cyclic_gather_perm(lay), axis=0)
        np.testing.assert_array_equal(np.asarray(dev), np.asarray(blocks))

    def test_is_sharded(self, mesh8):
        lay = CyclicLayout.create(64, 8, 8)
        dev = sharded_generate("absdiff", lay, mesh8, jnp.float32)
        assert len(dev.sharding.device_set) == 8


class TestDeviceResidentSolve:
    @pytest.mark.slow  # tier-1 budget: test_gathered_matches_host_path stays
    def test_generator_solve_no_host_matrix(self, mesh8, monkeypatch):
        # The generator-driven distributed path must never call the host
        # n×n generator.
        import tpu_jordan.driver as drv

        def forbid(fn, shape, dtype=jnp.float32, **kw):
            raise AssertionError(f"host generate({shape}) called")

        monkeypatch.setattr(drv, "generate", forbid)
        res = solve(n=96, block_size=8, workers=8, gather=False)
        assert res.inverse is None
        assert res.inverse_blocks is not None
        assert len(res.inverse_blocks.sharding.device_set) == 8
        assert res.layout.n == 96
        norm = 96 * 96 / 2  # ~‖A‖∞ of |i-j|
        assert res.residual / norm < 1e-5

    def test_gathered_matches_host_path(self, rng):
        res = solve(n=64, block_size=8, workers=4, dtype=jnp.float64)
        from tpu_jordan.ops import block_jordan_invert

        a = generate("absdiff", (64, 64), jnp.float64)
        inv_s, _ = block_jordan_invert(a, block_size=8)
        np.testing.assert_allclose(
            np.asarray(res.inverse), np.asarray(inv_s), rtol=1e-9, atol=1e-11
        )

    def test_refine_requires_gather(self):
        with pytest.raises(ValueError, match="gather"):
            solve(n=32, block_size=8, workers=4, refine=1, gather=False)

    def test_refine_gathered(self):
        res = solve(n=64, block_size=8, workers=4, refine=2)
        assert res.residual / (64 * 64 / 2) < 1e-6


class TestDistributedResidualBlocks:
    def test_identity_blocks(self, mesh8):
        lay = CyclicLayout.create(64, 8, 8)
        eye = sharded_generate("identity", lay, mesh8, jnp.float64)
        res = float(distributed_residual_blocks(eye, eye, mesh8, lay))
        assert res == 0.0

    def test_matches_dense(self, rng, mesh8):
        from tpu_jordan.parallel.ring_gemm import (
            _to_identity_padded_blocks,
        )

        n, m = 48, 8
        lay = CyclicLayout.create(n, m, 8)
        a = rng.standard_normal((n, n))
        x = np.linalg.inv(a) + 1e-6 * rng.standard_normal((n, n))
        a_b = _to_identity_padded_blocks(jnp.asarray(a), lay, make_mesh(8))
        x_b = _to_identity_padded_blocks(jnp.asarray(x), lay, make_mesh(8))
        got = float(distributed_residual_blocks(a_b, x_b, make_mesh(8), lay))
        want = float(np.max(np.sum(np.abs(a @ x - np.eye(n)), axis=1)))
        np.testing.assert_allclose(got, want, rtol=1e-10)
