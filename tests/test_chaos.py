"""Chaos acceptance + serve resilience (ISSUE 5): the seeded ≥50-request
chaos run pinned against a fault-free replay (every response bit-matches
or carries a typed error; every injected fault accounted — validated by
the SAME checker ``make chaos-demo`` runs), dispatcher survival of
mid-batch executor failures, breaker open/half-open recovery, queue +
execute deadline enforcement, draining close() during in-flight
retries, and the fault-free warm-path zero-cost pin."""

import importlib.util
import pathlib
import time

import numpy as np
import pytest

from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.resilience import (FaultPlan, FaultSpec, InjectedFaultError,
                                   ResiliencePolicy, RetryPolicy, activate)
from tpu_jordan.resilience.policy import (CircuitOpenError,
                                          DeadlineExceededError)
from tpu_jordan.serve import JordanService, chaos_demo

_tool = (pathlib.Path(__file__).resolve().parent.parent / "tools"
         / "check_chaos.py")
_spec = importlib.util.spec_from_file_location("check_chaos", _tool)
check_chaos = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_chaos)


def _totals(*names):
    return {n: REGISTRY.counter(n).total() for n in names}


class TestChaosAcceptance:
    """ISSUE 5 acceptance: ≥ 50 mixed serve requests under a seeded
    FaultPlan injecting compile failures, transient execute errors, NaN
    result corruption, and plan-cache write failures — every response
    bit-matches the fault-free replay of the same request or carries a
    typed error; zero silent corruption; every fault accounted."""

    def _pin(self, report):
        assert report["silent_corruption"] is False
        assert report["mismatches"] == []
        acct = report["accounting"]
        assert acct["injected"] > 0 and acct["unaccounted"] == 0
        by_point = report["faults"]["injected_by_point"]
        for point in ("compile", "execute", "result_corrupt_nan",
                      "plan_cache_write"):
            assert by_point.get(point, 0) > 0, f"{point} never fired"
        typed = sum(report["typed_errors"].values())
        assert report["matched_bitwise"] + typed == report["requests"]
        # The deliberately singular fixtures kept their typed
        # per-element flags under chaos (batch-mates unpoisoned).
        assert report["singular_flagged"] >= 1
        # The CI gate agrees (tools/check_chaos.py — same checker the
        # Makefile target runs).
        assert check_chaos.check(report) == []

    @pytest.mark.slow  # tier-1 budget: test_seeded_chaos_more_seeds keeps the replay pin
    def test_seeded_chaos_vs_fault_free_replay(self):
        self._pin(chaos_demo(n=96, requests=50, batch_cap=4, seed=0))

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [1, 2])
    def test_seeded_chaos_more_seeds(self, seed):
        self._pin(chaos_demo(n=96, requests=80, batch_cap=4, seed=seed))

    def test_chaos_demo_cli_usage_errors(self):
        from tpu_jordan.__main__ import main

        # Usage errors (pre-device, fast): exit 1.
        assert main(["96", "32", "--chaos-demo", "--workers", "8",
                     "--quiet"]) == 1
        assert main(["96", "32", "--chaos-demo", "--serve-demo",
                     "--quiet"]) == 1
        assert main(["96", "32", "--chaos-demo", "--tune",
                     "--quiet"]) == 1

    @pytest.mark.slow      # tier-1 sibling: the function-level pin
    def test_chaos_demo_cli_clean_run_exit_0(self, capsys):
        """The exit-0 leg re-runs a full (smaller) chaos demo; the
        report contract itself is tier-1-pinned through chaos_demo() +
        check_chaos in test_seeded_chaos_vs_fault_free_replay."""
        import json

        from tpu_jordan.__main__ import main

        rc = main(["64", "32", "--chaos-demo", "--serve-requests", "12",
                   "--batch-cap", "4", "--chaos-seed", "0", "--quiet"])
        line = capsys.readouterr().out.strip().splitlines()[-1]
        report = json.loads(line)
        assert rc == 0
        assert report["metric"] == "chaos_demo"
        assert report["silent_corruption"] is False


def _mats(rng, n, count):
    return [rng.standard_normal((n, n)).astype(np.float32)
            for _ in range(count)]


def _policy(retries=0, backoff=0.0, breaker_failures=3, cooldown=30.0):
    return ResiliencePolicy(
        retry=RetryPolicy(max_retries=retries, backoff_s=backoff,
                          max_backoff_s=backoff),
        breaker_failures=breaker_failures, breaker_cooldown_s=cooldown)


class TestDispatcherSurvivesExecutorFailure:
    def test_exactly_the_riders_get_typed_errors(self, rng):
        """A mid-batch executor failure fans typed errors to exactly
        its riders; batch-mates of OTHER batches and subsequent batches
        are unaffected, and the dispatcher thread survives."""
        mats = _mats(rng, 48, 6)
        svc = JordanService(batch_cap=2, max_wait_ms=1.0,
                            autostart=False, policy=_policy(retries=0))
        svc.warmup(shapes=[48])
        futs = [svc.submit(a) for a in mats[:4]]
        # Batch 1 = requests 0,1 (batch_cap=2, FIFO): its execute call
        # (the first) fails permanently; batch 2 = requests 2,3 runs.
        plan = FaultPlan([FaultSpec("execute", (1,), "permanent")])
        with activate(plan):
            svc.start()
            for i in (0, 1):
                with pytest.raises(InjectedFaultError):
                    futs[i].result(120)
            ok = [futs[i].result(120) for i in (2, 3)]
        assert all(not r.singular for r in ok)
        # Subsequent batches after the chaos scope: still serving.
        later = [svc.submit(a) for a in mats[4:]]
        res = [f.result(120) for f in later]
        assert all(not r.singular for r in res)
        svc.close()
        assert svc.stats()["breakers"] == {"64": "closed"}

    def test_breaker_opens_fast_fails_and_half_open_recovers(self, rng):
        """K consecutive terminal failures open the bucket's breaker
        (typed fast-fail at submit, no queueing of doomed work); after
        the cooldown a half-open probe succeeds and closes it."""
        mats = _mats(rng, 32, 6)
        svc = JordanService(batch_cap=1, max_wait_ms=0.5, autostart=False,
                            policy=_policy(retries=0, breaker_failures=2,
                                           cooldown=0.05))
        svc.warmup(shapes=[32])
        opens = REGISTRY.counter("tpu_jordan_breaker_open_total").total()
        futs = [svc.submit(a) for a in mats[:2]]
        plan = FaultPlan([FaultSpec("execute", (1, 2), "permanent")])
        with activate(plan):
            svc.start()
            for f in futs:
                with pytest.raises(InjectedFaultError):
                    f.result(120)
        # K=2 consecutive terminal failures: open + fast-fail.
        assert svc.stats()["breakers"]["64"] == "open"
        assert REGISTRY.counter(
            "tpu_jordan_breaker_open_total").total() == opens + 1
        with pytest.raises(CircuitOpenError):
            svc.submit(mats[2])
        # Rejections are counted, never silently dropped.
        assert svc.stats()["totals"]["rejected"] == 1
        time.sleep(0.06)                         # cooldown elapses
        probe = svc.submit(mats[3])              # the half-open probe
        assert not probe.result(120).singular
        assert svc.stats()["breakers"]["64"] == "closed"
        res = svc.invert(mats[4], timeout=120)   # closed: serving again
        assert not res.singular
        svc.close()

    def test_transient_mid_batch_failure_is_invisible_to_riders(self, rng):
        """The same mid-batch failure, but transient and with retry
        budget: riders get bit-exact results, one retry counted.  One
        service serves both passes — same warm executable, so the
        comparison is a true replay."""
        a = _mats(rng, 48, 1)[0]
        with JordanService(batch_cap=1, max_wait_ms=0.5,
                           policy=_policy(retries=2)) as svc:
            svc.warmup(shapes=[48])
            clean = svc.invert(a, timeout=120)       # fault-free pass
            before = REGISTRY.counter("tpu_jordan_retries_total").total()
            plan = FaultPlan([FaultSpec("execute", (1,), "transient")])
            with activate(plan):
                r = svc.invert(a, timeout=120)
        assert (np.asarray(r.inverse) == np.asarray(clean.inverse)).all()
        assert REGISTRY.counter(
            "tpu_jordan_retries_total").total() == before + 1


class TestCorruptionTargeting:
    def test_corruption_on_singular_lead_element_still_detected(self, rng):
        """A corrupt injection on a batch whose element 0 is singular
        must target a DETECTABLE (non-singular) rider — the gate
        ignores singular elements' meaningless rel, so poisoning one
        would be chaos the ledger counts but nothing can see."""
        bad = np.ones((32, 32), np.float32)          # rank 1: singular
        good = _mats(rng, 32, 1)[0]
        svc = JordanService(batch_cap=2, max_wait_ms=50.0,
                            autostart=False, policy=_policy(retries=2))
        svc.warmup(shapes=[32])
        before = REGISTRY.counter("tpu_jordan_retries_total").total()
        f_bad = svc.submit(bad)                      # element 0
        f_good = svc.submit(good)                    # element 1
        plan = FaultPlan([FaultSpec("result_corrupt_nan", (1,),
                                    "corrupt")])
        with activate(plan):
            svc.start()
            rb, rg = f_bad.result(120), f_good.result(120)
        assert rb.singular and not rg.singular
        assert np.isfinite(rg.rel_residual)
        # The injection was consumed AND absorbed: one retry, ledger
        # balanced (injected == retried).
        assert plan.injected_total == 1
        assert REGISTRY.counter(
            "tpu_jordan_retries_total").total() == before + 1
        svc.close()


class TestDeadlines:
    def test_queue_deadline_fails_typed_before_dispatch(self, rng):
        """A request whose deadline lapses while queued gets the typed
        DeadlineExceededError at dispatch; a generous-deadline
        batch-mate in the same claim is served normally.  The service's
        default_deadline_ms supplies the doomed deadline (pinning the
        default-propagation path) and the per-submit override relaxes
        the healthy one."""
        mats = _mats(rng, 32, 2)
        svc = JordanService(batch_cap=2, max_wait_ms=1.0, autostart=False,
                            policy=_policy(), default_deadline_ms=5)
        svc.warmup(shapes=[32])
        doomed = svc.submit(mats[0])             # default: 5 ms
        healthy = svc.submit(mats[1], deadline_ms=60_000)
        time.sleep(0.05)                         # deadline lapses queued
        svc.start()
        with pytest.raises(DeadlineExceededError):
            doomed.result(120)
        assert not healthy.result(120).singular
        svc.close()

    def test_execute_overrun_fails_typed_after_dispatch(self, rng):
        """A deadline generous enough to pass the queue check but
        overrun by the execution (forced deterministically: one
        transient execute fault + a 0.3 s retry backoff) fails typed in
        the execute phase — the deadline covers queue wait AND
        execute."""
        a = _mats(rng, 32, 1)[0]
        before = REGISTRY.counter(
            "tpu_jordan_deadline_exceeded_total").value(phase="execute")
        svc = JordanService(batch_cap=1, max_wait_ms=0.5, autostart=False,
                            policy=_policy(retries=1, backoff=0.3))
        svc.warmup(shapes=[32])
        fut = svc.submit(a, deadline_ms=100)
        plan = FaultPlan([FaultSpec("execute", (1,), "transient")])
        with activate(plan):
            svc.start()
            with pytest.raises(DeadlineExceededError):
                fut.result(120)
        assert REGISTRY.counter(
            "tpu_jordan_deadline_exceeded_total").value(
                phase="execute") == before + 1
        svc.close()

class TestCloseDuringRetries:
    def test_close_drains_in_flight_retries_cleanly(self, rng):
        """close(drain=True) issued while the dispatcher is mid-retry
        (real 0.15 s backoff sleeps) completes every accepted request —
        the retry loop finishes, nothing hangs, nothing drops."""
        mats = _mats(rng, 32, 3)
        svc = JordanService(batch_cap=1, max_wait_ms=0.5, autostart=False,
                            policy=_policy(retries=2, backoff=0.15))
        svc.warmup(shapes=[32])
        futs = [svc.submit(a) for a in mats]
        plan = FaultPlan([FaultSpec("execute", (1, 2), "transient")])
        with activate(plan):
            svc.start()
            time.sleep(0.05)          # dispatcher is inside retry #1
            t0 = time.perf_counter()
            svc.close(drain=True)     # must wait out the retries
            drained = time.perf_counter() - t0
        res = [f.result(0) for f in futs]       # all already resolved
        assert all(not r.singular for r in res)
        assert drained < 60


class TestWarmPathPaysNothing:
    def test_fault_free_50_request_serve_all_resilience_counters_zero(
            self, rng):
        """ISSUE 5 acceptance: with no FaultPlan active, the warm-serve
        50-request scrape shows ZERO retries, ZERO injected faults,
        ZERO breaker opens, ZERO deadline failures, ZERO recovery rungs
        — and the PR 3/4 pins (zero compiles, zero plan-cache
        measurements after warmup) still hold with the resilience layer
        on by default."""
        names = ("tpu_jordan_retries_total",
                 "tpu_jordan_faults_injected_total",
                 "tpu_jordan_breaker_open_total",
                 "tpu_jordan_deadline_exceeded_total",
                 "tpu_jordan_recovery_rungs_total",
                 "tpu_jordan_plan_cache_write_failures_total")
        mats = _mats(rng, 24, 25) + _mats(rng, 48, 25)  # one 64-bucket
        svc = JordanService(batch_cap=8, max_wait_ms=5.0, max_queue=64,
                            autostart=False)   # default policy: ON
        svc.warmup(shapes=[24, 48])
        compiles = svc.stats()["totals"]["compiles"]
        before = _totals(*names)
        futs = [svc.submit(a) for a in mats]
        svc.start()
        res = [f.result(300) for f in futs]
        svc.close()
        assert len(res) == 50 and all(not r.singular for r in res)
        assert _totals(*names) == before, "warm path must pay nothing"
        stats = svc.stats()
        assert stats["totals"]["compiles"] == compiles   # PR 3 pin
        assert stats["measurements"] == 0                # PR 2 pin
        assert all(s == "closed" for s in stats["breakers"].values())
