"""In-place blocked GJ: parity with the augmented reference implementation
(same pivot rule, same results to rounding) and with numpy."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.ops import block_jordan_invert, generate
from tpu_jordan.ops.jordan_inplace import block_jordan_invert_inplace


class TestInplaceJordan:
    @pytest.mark.parametrize("n,m", [(32, 8), (64, 16), (50, 8), (48, 48)])
    def test_matches_numpy(self, rng, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = block_jordan_invert_inplace(a, block_size=m)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(np.asarray(a)),
            rtol=1e-9, atol=1e-9,
        )

    @pytest.mark.parametrize("gen", ["absdiff", "hilbert"])
    def test_matches_augmented_reference(self, gen):
        # Same pivot rule => same arithmetic path => results agree tightly.
        n, m = 64, 8
        a = generate(gen, (n, n), jnp.float64)
        if gen == "hilbert":
            a, n = generate(gen, (8, 8), jnp.float64), 8
            inv_i, s_i = block_jordan_invert_inplace(a, block_size=2)
            inv_a, s_a = block_jordan_invert(a, block_size=2)
        else:
            inv_i, s_i = block_jordan_invert_inplace(a, block_size=m)
            inv_a, s_a = block_jordan_invert(a, block_size=m)
        assert bool(s_i) == bool(s_a) is False
        np.testing.assert_allclose(
            np.asarray(inv_i), np.asarray(inv_a), rtol=1e-7, atol=1e-10
        )

    def test_pivoting_required(self):
        # |i-j|: zero diagonal, inversion impossible without row pivoting.
        a = generate("absdiff", (96, 96), jnp.float64)
        inv, sing = block_jordan_invert_inplace(a, block_size=16)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(96)))
        assert res < 1e-8

    def test_singular_flag(self):
        _, sing = block_jordan_invert_inplace(
            jnp.ones((32, 32), jnp.float64), block_size=8
        )
        assert bool(sing)

    def test_refine(self, rng):
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
        inv, sing = block_jordan_invert_inplace(a, block_size=16, refine=2)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a, np.float64)
                            @ np.asarray(inv, np.float64) - np.eye(64)))
        assert res < 1e-3

    def test_single_block(self, rng):
        a = jnp.asarray(rng.standard_normal((16, 16)), jnp.float64)
        inv, sing = block_jordan_invert_inplace(a, block_size=16)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(np.asarray(a)),
            rtol=1e-9, atol=1e-9,
        )
