"""In-place blocked GJ: parity with the augmented reference implementation
(same pivot rule, same results to rounding) and with numpy."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.ops import block_jordan_invert, generate
from tpu_jordan.ops.jordan_inplace import (
    block_jordan_invert_inplace,
    block_jordan_invert_inplace_fori,
    block_jordan_invert_inplace_grouped,
    block_jordan_invert_inplace_grouped_fori,
    block_jordan_invert_inplace_grouped_pallas,
)


class TestInplaceJordan:
    @pytest.mark.parametrize("n,m", [(32, 8), (64, 16), (50, 8), (48, 48)])
    def test_matches_numpy(self, rng, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = block_jordan_invert_inplace(a, block_size=m)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(np.asarray(a)),
            rtol=1e-9, atol=1e-9,
        )

    @pytest.mark.smoke      # the in-place/augmented family parity case
    @pytest.mark.parametrize("gen", ["absdiff", "hilbert"])
    def test_matches_augmented_reference(self, gen):
        # Same pivot rule => same arithmetic path => results agree tightly.
        n, m = 64, 8
        a = generate(gen, (n, n), jnp.float64)
        if gen == "hilbert":
            a, n = generate(gen, (8, 8), jnp.float64), 8
            inv_i, s_i = block_jordan_invert_inplace(a, block_size=2)
            inv_a, s_a = block_jordan_invert(a, block_size=2)
        else:
            inv_i, s_i = block_jordan_invert_inplace(a, block_size=m)
            inv_a, s_a = block_jordan_invert(a, block_size=m)
        assert bool(s_i) == bool(s_a) is False
        np.testing.assert_allclose(
            np.asarray(inv_i), np.asarray(inv_a), rtol=1e-7, atol=1e-10
        )

    def test_pivoting_required(self):
        # |i-j|: zero diagonal, inversion impossible without row pivoting.
        a = generate("absdiff", (96, 96), jnp.float64)
        inv, sing = block_jordan_invert_inplace(a, block_size=16)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(96)))
        assert res < 1e-8

    def test_singular_flag(self):
        _, sing = block_jordan_invert_inplace(
            jnp.ones((32, 32), jnp.float64), block_size=8
        )
        assert bool(sing)

    def test_refine(self, rng):
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
        inv, sing = block_jordan_invert_inplace(a, block_size=16, refine=2)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a, np.float64)
                            @ np.asarray(inv, np.float64) - np.eye(64)))
        assert res < 1e-3

    def test_single_block(self, rng):
        a = jnp.asarray(rng.standard_normal((16, 16)), jnp.float64)
        inv, sing = block_jordan_invert_inplace(a, block_size=16)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(np.asarray(a)),
            rtol=1e-9, atol=1e-9,
        )


class TestInplaceForiEngine:
    """The fori_loop in-place engine: bit-identical to the unrolled trace
    at every Nr (same pivot choices, same arithmetic), and working beyond
    MAX_UNROLL_NR where the unrolled trace is unaffordable."""

    @pytest.mark.parametrize("n,m", [
        (32, 8), (64, 16), (50, 8), (48, 48),
        pytest.param(96, 8, marks=pytest.mark.slow)])
    def test_bitmatch_unrolled(self, rng, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        x_u, s_u = block_jordan_invert_inplace(a, block_size=m)
        x_f, s_f = block_jordan_invert_inplace_fori(a, block_size=m)
        assert bool(s_u) == bool(s_f)
        assert bool(jnp.all(x_u == x_f)), "fori engine diverged bitwise"

    @pytest.mark.smoke      # the fori-family engine-parity case
    @pytest.mark.parametrize("gen", ["absdiff", "rand"])
    def test_bitmatch_unrolled_generators(self, gen):
        a = generate(gen, (96, 96), jnp.float32)
        x_u, s_u = block_jordan_invert_inplace(a, block_size=16)
        x_f, s_f = block_jordan_invert_inplace_fori(a, block_size=16)
        assert bool(s_u) == bool(s_f) is False
        assert bool(jnp.all(x_u == x_f))

    def test_beyond_unroll_cap(self, rng):
        # Nr = 68 > MAX_UNROLL_NR = 64: the configuration the unrolled
        # engine cannot afford (the round-3 gap this engine closes).
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 544, 8
        assert -(-n // m) > MAX_UNROLL_NR
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = block_jordan_invert_inplace_fori(a, block_size=m)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(n)))
        assert res < 1e-7

    def test_singular_flag(self):
        _, sing = block_jordan_invert_inplace_fori(
            jnp.ones((32, 32), jnp.float64), block_size=8
        )
        assert bool(sing)

    @pytest.mark.smoke      # the grouped-family engine-parity case
    def test_grouped_k1_bitmatches_plain(self, rng):
        # group=1 is the plain engine with reordered (equivalent) writes:
        # must be bit-identical.
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        x1, _ = block_jordan_invert_inplace(a, block_size=16)
        x2, _ = block_jordan_invert_inplace_grouped(a, block_size=16,
                                                    group=1)
        assert bool(jnp.all(x1 == x2))

    @pytest.mark.parametrize("n,m,k", [
        (64, 16, 2),
        pytest.param(128, 16, 4, marks=pytest.mark.slow),
        # tier-1 budget: (64, 16, 2) + the ragged (96, 16, 3) keep the
        # fast-run pins; the wide-block case runs nightly.
        pytest.param(128, 32, 4, marks=pytest.mark.slow),
        (96, 16, 3),
        pytest.param(160, 16, 4, marks=pytest.mark.slow),
        (50, 8, 4),
        # tier-1 budget: the wide-group case runs nightly.
        pytest.param(128, 16, 8, marks=pytest.mark.slow)])
    def test_grouped_matches_plain_to_rounding(self, rng, n, m, k):
        # Delayed group updates change the summation order (one U·P
        # matmul per group), so parity is to rounding, not bitwise —
        # the standard blocked-elimination trade.
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x1, s1 = block_jordan_invert_inplace(a, block_size=m)
        x2, s2 = block_jordan_invert_inplace_grouped(a, block_size=m,
                                                     group=k)
        assert bool(s1) == bool(s2) is False
        np.testing.assert_allclose(np.asarray(x1), np.asarray(x2),
                                   rtol=1e-9, atol=1e-9)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(x2) - np.eye(n)))
        assert res < 1e-9

    @pytest.mark.parametrize("gen", ["absdiff", "rand"])
    def test_grouped_generators(self, gen):
        # absdiff: zero diagonal, pivoting + swaps required in every group.
        a = generate(gen, (128, 128), jnp.float64)
        x, sing = block_jordan_invert_inplace_grouped(a, block_size=16,
                                                      group=4)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(x) - np.eye(128)))
        assert res < 1e-8

    def test_grouped_singular_flag(self):
        _, sing = block_jordan_invert_inplace_grouped(
            jnp.ones((32, 32), jnp.float64), block_size=8, group=4)
        assert bool(sing)

    @pytest.mark.parametrize("n,m,k", [
        (64, 16, 2),
        pytest.param(128, 16, 4, marks=pytest.mark.slow),
        # tier-1 headroom (ISSUE 3): the tail-group case runs nightly;
        # tier-1 keeps the ragged (50, 8, 4) case + the smoke fori
        # parity + the generators variants.
        pytest.param(96, 16, 4, marks=pytest.mark.slow),  # tail (Nr=6)
        pytest.param(160, 16, 4,
                     marks=pytest.mark.slow),  # tail group (Nr=10)
        (50, 8, 4),    # ragged n + tail
        pytest.param(128, 16, 8, marks=pytest.mark.slow)])
    def test_grouped_fori_bitmatches_grouped(self, rng, n, m, k):
        # The fori grouped engine runs the same per-step arithmetic as
        # the unrolled grouped engine (the probe's masked full window
        # computes each candidate independently), so results bit-match.
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x_u, s_u = block_jordan_invert_inplace_grouped(a, block_size=m,
                                                       group=k)
        x_f, s_f = block_jordan_invert_inplace_grouped_fori(a, block_size=m,
                                                            group=k)
        assert bool(s_u) == bool(s_f) is False
        assert bool(jnp.all(x_u == x_f)), "grouped fori diverged bitwise"

    @pytest.mark.parametrize("gen", [
        # tier-1 headroom (ISSUE 3): the swap-forcing |i−j| variant of
        # the grouped engine keeps tier-1 coverage in
        # test_grouped_generators; the fori twin's runs nightly.
        pytest.param("absdiff", marks=pytest.mark.slow), "rand"])
    def test_grouped_fori_generators(self, gen):
        # absdiff: zero diagonal — pivoting + cross-group swaps required.
        a = generate(gen, (128, 128), jnp.float64)
        x_u, s_u = block_jordan_invert_inplace_grouped(a, block_size=16,
                                                       group=4)
        x_f, s_f = block_jordan_invert_inplace_grouped_fori(
            a, block_size=16, group=4)
        assert bool(s_u) == bool(s_f) is False
        assert bool(jnp.all(x_u == x_f))

    def test_grouped_fori_beyond_unroll_cap(self, rng):
        # Nr = 68 > MAX_UNROLL_NR: the configuration whose unrolled
        # grouped trace is unaffordable (88 s at Nr=128 on TPU) — the
        # gap this engine closes (VERDICT r4 #2).
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 544, 8
        assert -(-n // m) > MAX_UNROLL_NR
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = block_jordan_invert_inplace_grouped_fori(
            a, block_size=m, group=4)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(n)))
        assert res < 1e-7

    def test_grouped_fori_singular_flag(self):
        _, sing = block_jordan_invert_inplace_grouped_fori(
            jnp.ones((32, 32), jnp.float64), block_size=8, group=4)
        assert bool(sing)

    def test_grouped_fori_bitmatches_grouped_on_singular_input(self):
        # All-singular probe windows: the masked argmin must fall back to
        # the unrolled engine's benign self-swap (piv=t), keeping the
        # engines bit-identical even where the output is invalid.
        a = jnp.ones((32, 32), jnp.float64)
        x_u, s_u = block_jordan_invert_inplace_grouped(a, block_size=8,
                                                       group=4)
        x_f, s_f = block_jordan_invert_inplace_grouped_fori(a, block_size=8,
                                                            group=4)
        assert bool(s_u) and bool(s_f)
        nz = jnp.isfinite(x_u) & jnp.isfinite(x_f)
        assert bool(jnp.all(jnp.where(nz, x_u == x_f, True)))
        assert bool(jnp.all(jnp.isfinite(x_u) == jnp.isfinite(x_f)))

    @pytest.mark.parametrize("n,m,k", [
        (64, 16, 2),     # the production group size
        # tier-1 budget: the ragged/tail case runs nightly; the
        # production k=2 case keeps the fast-run pin.
        pytest.param(50, 8, 4, marks=pytest.mark.slow),
        # tier-1 headroom (the 870 s rule): the wider-group and k=3
        # closing-step variants run nightly; tier-1 keeps the
        # production k=2 + the ragged/tail case + both generators.
        pytest.param(96, 16, 4, marks=pytest.mark.slow),
        pytest.param(64, 16, 3, marks=pytest.mark.slow)])
    def test_grouped_pallas_bitmatches_grouped(self, rng, n, m, k):
        """ISSUE 6 bit-match pin (the swap-free-pin pattern from PR 1):
        the fused-Pallas-update engine at fp32 must reproduce the XLA
        grouped engine bit for bit on nonsingular matrices — same pivot
        sequence, element-for-element identical arithmetic in the fused
        kernel's full-contraction dots."""
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        x_g, s_g = block_jordan_invert_inplace_grouped(
            a, block_size=m, group=k)
        x_p, s_p = block_jordan_invert_inplace_grouped_pallas(
            a, block_size=m, group=k, interpret=True)
        assert bool(s_g) == bool(s_p) is False
        assert bool(jnp.all(x_g == x_p)), (
            f"grouped_pallas diverged bitwise at n={n} m={m} k={k}")

    @pytest.mark.parametrize("gen", [
        "absdiff",        # zero diagonal: every group needs real swaps
        pytest.param("rand", marks=pytest.mark.slow)])
    def test_grouped_pallas_bitmatch_generators(self, gen):
        # absdiff: zero diagonal — every group needs real pivot swaps,
        # so the kernel's swap-following bookkeeping is exercised.
        a = generate(gen, (96, 96), jnp.float32)
        x_g, s_g = block_jordan_invert_inplace_grouped(a, block_size=16,
                                                       group=2)
        x_p, s_p = block_jordan_invert_inplace_grouped_pallas(
            a, block_size=16, group=2, interpret=True)
        assert bool(s_g) == bool(s_p) is False
        assert bool(jnp.all(x_g == x_p))

    def test_grouped_pallas_singular_flag(self):
        _, sing = block_jordan_invert_inplace_grouped_pallas(
            jnp.ones((32, 32), jnp.float32), block_size=8, group=2,
            interpret=True)
        assert bool(sing)

    def test_grouped_pallas_bf16_inverts(self, rng):
        # The bf16 mode is NOT bit-matched (operands are rounded by
        # design); it must still invert a bf16-well-conditioned matrix
        # to bf16-grade accuracy.  κ·eps_bf16 must stay << 1 for bf16
        # compute to have any digits, hence the dominant diagonal.
        n = 64
        a = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n),
                        jnp.float32)
        x, sing = block_jordan_invert_inplace_grouped_pallas(
            a, block_size=16, group=2, mode="bf16", interpret=True)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a, np.float64)
                            @ np.asarray(x, np.float64) - np.eye(n)))
        assert res < 0.05

    def test_driver_routes_large_nr_through_fori(self):
        # single_device_invert must hand Nr > MAX_UNROLL_NR to the 2N³
        # fori engine, not the augmented 4N³ fallback.
        from tpu_jordan.driver import single_device_invert
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        eng_small = single_device_invert(64, 16)
        assert eng_small is block_jordan_invert_inplace
        n = 8 * (MAX_UNROLL_NR + 4)
        eng_large = single_device_invert(n, 8)
        assert eng_large is block_jordan_invert_inplace_fori


class TestLookahead:
    """The probe-ahead twins (ISSUE 16): a REORDERED schedule — step
    t+1's pivot probe issued right after the critical panel, before the
    trailing eliminate — of the SAME arithmetic (panel values are column
    slices of the very HIGHEST-precision contraction the plain engine
    computes), so pivot choices, the numerics trace, and the inverse
    bits pin IDENTICAL to the non-lookahead engines."""

    @pytest.mark.parametrize("n,m", [(32, 8), (64, 16), (50, 8),
                                     (48, 48)])
    def test_bitmatch_plain(self, rng, n, m):
        from tpu_jordan.ops.jordan_inplace import (
            block_jordan_invert_inplace_lookahead,
        )

        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        x_p, s_p = block_jordan_invert_inplace(a, block_size=m)
        x_l, s_l = block_jordan_invert_inplace_lookahead(a, block_size=m)
        assert bool(s_p) == bool(s_l)
        assert bool(jnp.all(x_p == x_l)), \
            "probe-ahead schedule diverged bitwise from the plain engine"

    @pytest.mark.smoke      # the probe-ahead family engine-parity case
    @pytest.mark.parametrize("gen", ["absdiff", "rand"])
    def test_bitmatch_plain_generators(self, gen):
        # absdiff: zero diagonal forces a row swap at EVERY superstep,
        # so the carried panel's swap fix-up path is fully exercised
        # (with exact pivot ties to boot).
        from tpu_jordan.ops.jordan_inplace import (
            block_jordan_invert_inplace_lookahead,
        )

        # Smallest ragged size with a swap per superstep (smoke budget:
        # unrolled trace cost scales with Nr).
        a = generate(gen, (44, 44), jnp.float64)
        x_p, s_p = block_jordan_invert_inplace(a, block_size=8)
        x_l, s_l = block_jordan_invert_inplace_lookahead(a, block_size=8)
        assert bool(s_p) == bool(s_l) is False
        assert bool(jnp.all(x_p == x_l))

    @pytest.mark.parametrize("n,m,k", [
        # tier-1 budget: the ragged/tail case is the single fast pin.
        pytest.param(64, 8, 2, marks=pytest.mark.slow),
        (50, 8, 4)])
    def test_grouped_lookahead_bitmatches_grouped(self, rng, n, m, k):
        from tpu_jordan.ops.jordan_inplace import (
            block_jordan_invert_inplace_grouped_lookahead,
        )

        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x_g, s_g = block_jordan_invert_inplace_grouped(a, block_size=m,
                                                       group=k)
        x_l, s_l = block_jordan_invert_inplace_grouped_lookahead(
            a, block_size=m, group=k)
        assert bool(s_g) == bool(s_l) is False
        assert bool(jnp.all(x_g == x_l))

    def test_numerics_trace_pins_pivot_sequence(self):
        # The instrumented twins: the lookahead trace must report the
        # SAME pivot block at every superstep as the plain engine's
        # trace — the schedule moved, the decisions did not.
        from tpu_jordan.ops.jordan_inplace import (
            block_jordan_invert_inplace_lookahead,
        )

        # ragged n=36 (Nr=5): a swap every superstep plus the padded
        # tail, at tier-1-budget trace cost.
        a = generate("absdiff", (36, 36), jnp.float64)
        _, _, st_p = block_jordan_invert_inplace(a, block_size=8,
                                                 collect_stats=True)
        _, _, st_l = block_jordan_invert_inplace_lookahead(
            a, block_size=8, collect_stats=True)
        assert np.array_equal(np.asarray(st_p["pivot_block"]),
                              np.asarray(st_l["pivot_block"]))
        assert np.array_equal(np.asarray(st_p["pivot_inv_norm"]),
                              np.asarray(st_l["pivot_inv_norm"]))

    def test_singular_flag(self):
        from tpu_jordan.ops.jordan_inplace import (
            block_jordan_invert_inplace_lookahead,
        )

        _, sing = block_jordan_invert_inplace_lookahead(
            jnp.ones((32, 32), jnp.float64), block_size=8)
        assert bool(sing)

    def test_driver_unrolled_only_gate_is_typed(self):
        # Nr > MAX_UNROLL_NR has no lookahead twin (the critical-panel
        # split needs static column offsets): typed refusal naming the
        # remedy, never a silent fallback to a different engine.
        from tpu_jordan.driver import UsageError, single_device_invert
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n = 8 * (MAX_UNROLL_NR + 4)
        with pytest.raises(UsageError, match="unrolled-only"):
            single_device_invert(n, 8, "lookahead")
