"""Native (C++) matrix-file parser: parity with the Python fallback and the
reference's error contract (read_matrix, main.cpp:209-282)."""

import os
import subprocess

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def native():
    try:
        from tpu_jordan import native as mod
        return mod
    except ImportError:
        r = subprocess.run(["make", "native"], cwd=REPO_ROOT,
                           capture_output=True, timeout=120)
        if r.returncode != 0:
            pytest.skip("native library unavailable and make failed")
        from tpu_jordan import native as mod
        return mod


class TestNativeParser:
    def test_roundtrip(self, native, rng, tmp_path):
        a = rng.standard_normal((30, 30))
        p = str(tmp_path / "m.txt")
        native.write_matrix_text(p, a)
        b = native.parse_matrix_text(p, 900).reshape(30, 30)
        np.testing.assert_array_equal(a, b)

    def test_matches_python_parse(self, native, rng, tmp_path):
        a = rng.standard_normal(100)
        p = tmp_path / "v.txt"
        p.write_text(" ".join(repr(float(x)) for x in a))
        v = native.parse_matrix_text(str(p), 100)
        np.testing.assert_array_equal(v, a)

    def test_missing_file(self, native, tmp_path):
        with pytest.raises(FileNotFoundError):
            native.parse_matrix_text(str(tmp_path / "nope"), 4)

    def test_short_and_garbage(self, native, tmp_path):
        p = tmp_path / "s.txt"
        p.write_text("1.5 2.5 and then garbage")
        v = native.parse_matrix_text(str(p), 10)
        assert list(v) == [1.5, 2.5]

    def test_io_layer_uses_native(self, native, rng, tmp_path):
        # read_matrix_file must produce identical results whichever parser
        # is active.
        from tpu_jordan.io import read_matrix_file, write_matrix_file
        a = rng.standard_normal((12, 12))
        p = str(tmp_path / "m.txt")
        write_matrix_file(p, a)
        b = read_matrix_file(p, 12)
        np.testing.assert_allclose(b, a, rtol=1e-15)
