"""Native (C++) matrix-file parser: parity with the Python fallback and the
reference's error contract (read_matrix, main.cpp:209-282)."""

import os
import subprocess

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def native():
    try:
        from tpu_jordan import native as mod
        return mod
    except ImportError:
        r = subprocess.run(["make", "native"], cwd=REPO_ROOT,
                           capture_output=True, timeout=120)
        if r.returncode != 0:
            pytest.skip("native library unavailable and make failed")
        from tpu_jordan import native as mod
        return mod


class TestNativeParser:
    def test_roundtrip(self, native, rng, tmp_path):
        a = rng.standard_normal((30, 30))
        p = str(tmp_path / "m.txt")
        native.write_matrix_text(p, a)
        b = native.parse_matrix_text(p, 900).reshape(30, 30)
        np.testing.assert_array_equal(a, b)

    def test_matches_python_parse(self, native, rng, tmp_path):
        a = rng.standard_normal(100)
        p = tmp_path / "v.txt"
        p.write_text(" ".join(repr(float(x)) for x in a))
        v = native.parse_matrix_text(str(p), 100)
        np.testing.assert_array_equal(v, a)

    def test_missing_file(self, native, tmp_path):
        with pytest.raises(FileNotFoundError):
            native.parse_matrix_text(str(tmp_path / "nope"), 4)

    def test_short_and_garbage(self, native, tmp_path):
        p = tmp_path / "s.txt"
        p.write_text("1.5 2.5 and then garbage")
        v = native.parse_matrix_text(str(p), 10)
        assert list(v) == [1.5, 2.5]

    def test_stream_long_whitespace_at_chunk_boundary(self, native, rng,
                                                      tmp_path):
        # Regression: a >64-byte whitespace run straddling the 1 MiB chunk
        # boundary used to carry an unbounded tail into tj_refill, whose
        # unclamped fread then overflowed the 64-byte headroom (heap
        # corruption).  Build a file whose chunk boundary lands inside a
        # multi-KiB whitespace run and check native == Python fallback.
        chunk = 1 << 20
        vals = rng.standard_normal(64)
        head = " ".join("%.17g" % v for v in vals[:32])
        pad = " " * (chunk - len(head) - 100)  # boundary inside the run
        body = head + pad + " " * 4096 + " ".join(
            "%.17g" % v for v in vals[32:])
        p = tmp_path / "ws.txt"
        p.write_text(body)
        self._assert_stream_matches_fallback(native, str(p), 64)

    def test_stream_giant_whitespace_run(self, native, tmp_path):
        # A whitespace run longer than a whole chunk (1.5 MiB) between two
        # numbers: multiple refills with zero parse progress.
        p = tmp_path / "giant_ws.txt"
        p.write_text("1.25" + "\n" * ((1 << 20) + (1 << 19)) + "2.5")
        self._assert_stream_matches_fallback(native, str(p), 2)

    def test_stream_long_token_at_chunk_boundary(self, native, tmp_path):
        # A valid 200-digit number straddling the chunk boundary must be
        # re-parsed whole (carry > 64 bytes), not split or overflowed.
        chunk = 1 << 20
        long_num = "0." + "5" * 200
        head = "1 " * ((chunk - 50) // 2)  # boundary lands inside long_num
        p = tmp_path / "long_tok.txt"
        p.write_text(head + long_num + " 3.5")
        n = len(head) // 2 + 2
        self._assert_stream_matches_fallback(native, str(p), n)

    def test_stream_garbage_tail_at_chunk_boundary(self, native, tmp_path):
        # Non-numeric garbage just before the boundary: short count, no
        # crash, parity with the fallback's error behavior.
        chunk = 1 << 20
        head = "2 " * ((chunk - 20) // 2)
        p = tmp_path / "garbage.txt"
        p.write_text(head + "certainly_not_a_number " + "4 " * 100)
        n_good = len(head) // 2
        s = native.MatrixStream(str(p))
        try:
            got = s.read(n_good + 50)
        finally:
            s.close()
        assert got.size == n_good
        assert all(got == 2.0)

    def test_stream_fuzz_random_whitespace_layout(self, native, rng,
                                                  tmp_path):
        # Randomized whitespace/token layout across several chunk
        # boundaries; native and fallback must agree exactly.
        parts = []
        count = 0
        target = (1 << 20) * 3 + 12345
        size = 0
        while size < target:
            v = rng.standard_normal()
            tok = "%.17g" % v
            ws = rng.choice([" ", "\n", "\t", "  \n", " " * 500,
                             "\r\n" * 40])
            parts.append(tok + ws)
            size += len(tok) + len(ws)
            count += 1
        p = tmp_path / "fuzz.txt"
        p.write_text("".join(parts))
        self._assert_stream_matches_fallback(native, str(p), count)

    @staticmethod
    def _assert_stream_matches_fallback(native, path, count):
        from unittest import mock

        from tpu_jordan.io import MatrixStripReader
        s = native.MatrixStream(path)
        try:
            got_native = s.read(count)
        finally:
            s.close()
        # Force the pure-Python branch through the real constructor so the
        # parity test exercises exactly the production fallback path.
        with mock.patch.object(native, "MatrixStream",
                               side_effect=ImportError("forced fallback")):
            with MatrixStripReader(path, count) as fallback:
                assert fallback._native is None
                got_py = fallback._read_tokens_py(count)
        assert got_native.size == got_py.size == count
        np.testing.assert_array_equal(got_native, got_py)

    def test_io_layer_uses_native(self, native, rng, tmp_path):
        # read_matrix_file must produce identical results whichever parser
        # is active.
        from tpu_jordan.io import read_matrix_file, write_matrix_file
        a = rng.standard_normal((12, 12))
        p = str(tmp_path / "m.txt")
        write_matrix_file(p, a)
        b = read_matrix_file(p, 12)
        np.testing.assert_allclose(b, a, rtol=1e-15)
