"""Preemption-safe execution (ISSUE 20): superstep checkpoint/resume.

The acceptance pins, in test form:

  * **bit-match** — a checkpointed run (any cadence) returns the SAME
    bytes as the monolithic engine of the same flavor, and a
    preempted-then-resumed run returns the same bytes as the
    uninterrupted one (single-device, 1D p=8 mid-sweep, grouped);
  * **typed refusals** — missing / corrupt / mismatched / unsupported
    checkpoints and misapplied CLI flags each raise their own type with
    a message that names the refusal; a resume NEVER silently degrades
    to a from-scratch run;
  * **cadence edges** — cadence > Nr writes nothing (and a resume on
    that store is a typed CheckpointNotFoundError), cadence 1 works,
    ragged last blocks round-trip, grouped cadence snaps to group
    boundaries;
  * **warm resumes are free** — zero segment compiles when the segment
    grid was already compiled (the n=64 smoke pin);
  * **the ledger adds up** — written == resumed + discarded + live,
    persisted across store reopen, corruption quarantined and counted;
  * **the fleet kill path resumes** — a replica killed mid-ckpt_solve
    re-queues with ``resume_from`` (the ``ckpt_resume`` journey hop)
    and the result bit-matches;
  * **LP streams replay** — ``solve_lp(resume=True)`` re-enters at the
    stored iteration and reproduces the identical ``kkt_hex`` trail;
  * **reaped dispatchers** (satellite): a dispatcher thread the
    bounded kill-path close abandoned is joined by a later ``reap()``
    and counted in ``tpu_jordan_serve_dispatcher_reaped_total``.
"""

import importlib.util
import json
import os
import pathlib
import threading
import time

import numpy as np
import pytest

from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.obs.recorder import RECORDER
from tpu_jordan.resilience import FaultPlan, FaultSpec, activate
from tpu_jordan.resilience.checkpoint import (
    CheckpointCorruptError, CheckpointKey, CheckpointMismatchError,
    CheckpointNotFoundError, CheckpointStore,
    CheckpointUnsupportedError, PreemptedError, checkpointed_invert,
    checkpointed_solve, fingerprint)

_repo = pathlib.Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "check_ckpt", _repo / "tools" / "check_ckpt.py")
check_ckpt = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_ckpt)


def _mat(n, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, n)) + n * np.eye(n)).astype(dtype)


def _rhs(n, k=3, seed=1, dtype=np.float32):
    return np.random.default_rng(seed).standard_normal(
        (n, k)).astype(dtype)


def _key(run_id="t:key", **kw):
    base = dict(run_id=run_id, workload="invert", engine="fori",
                topology="single", n=32, m=8, Nr=4, dtype="float32",
                nrhs=0, cadence=2)
    base.update(kw)
    return CheckpointKey(**base)


def _state(seed=0):
    rng = np.random.default_rng(seed)
    return {"V": rng.standard_normal((4, 8, 8)).astype(np.float32),
            "swaps": np.arange(8, dtype=np.int32)}


def _preempt_plan(call):
    return FaultPlan([FaultSpec("preempt", (call,), "permanent")])


# ---------------------------------------------------------------------
# The store: tokens, checksums, quarantine, ledger persistence
# ---------------------------------------------------------------------


class TestStore:
    def test_write_peek_resume_roundtrip_bit_exact(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        key = _key()
        st = _state()
        nbytes = store.write(key, 2, st)
        assert nbytes > 0
        assert store.has_live("t:key")
        step, arrays = store.resume(key)
        assert step == 2
        for name in st:
            assert arrays[name].dtype == st[name].dtype
            np.testing.assert_array_equal(arrays[name], st[name])
        led = store.ledger()
        assert led["written"] == 1 and led["resumed"] == 1
        assert led["invariant_holds"]
        # A resume consumes the token: a second one is a typed miss.
        assert not store.has_live("t:key")
        with pytest.raises(CheckpointNotFoundError):
            store.resume(key)

    def test_supersede_discards_previous_token(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        key = _key()
        store.write(key, 1, _state(1))
        store.write(key, 2, _state(2))
        led = store.ledger()
        assert led["written"] == 2 and led["discarded"] == 1
        assert led["live"] == 1 and led["invariant_holds"]
        step, arrays = store.resume(key)   # only the LATEST survives
        assert step == 2
        np.testing.assert_array_equal(arrays["V"], _state(2)["V"])

    def test_corrupt_entry_quarantined_typed_and_counted(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        key = _key()
        store.write(key, 2, _state())
        path = [p for p in os.listdir(tmp_path)
                if p != "ledger.json" and not p.endswith(".corrupt")]
        assert len(path) == 1
        full = tmp_path / path[0]
        raw = bytearray(full.read_bytes())
        raw[len(raw) // 2] ^= 0xFF          # flip a payload byte
        full.write_bytes(bytes(raw))
        before = REGISTRY.counter("tpu_jordan_ckpt_corrupt_total").total()
        with pytest.raises(CheckpointCorruptError):
            store.resume(key)
        assert REGISTRY.counter(
            "tpu_jordan_ckpt_corrupt_total").total() == before + 1
        assert any(p.endswith(".corrupt") for p in os.listdir(tmp_path))
        led = store.ledger()
        assert led["corrupt"] == 1
        assert led["invariant_holds"]       # corrupt token => discarded
        assert not store.has_live("t:key")

    def test_mismatched_key_typed_refusal_names_fields(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.write(_key(), 2, _state())
        with pytest.raises(CheckpointMismatchError,
                           match="dtype.*silent corruption"):
            store.resume(_key(dtype="float64"))
        # cadence is the ONE legitimately tunable field.
        store.write(_key(), 2, _state())
        step, _ = store.resume(_key(cadence=4))
        assert step == 2

    def test_ledger_persists_across_reopen(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        key = _key()
        store.write(key, 1, _state())
        store.resume(key)
        led0 = store.ledger()
        again = CheckpointStore(str(tmp_path))
        led1 = again.ledger()
        for k in ("written", "resumed", "discarded", "corrupt", "live"):
            assert led1[k] == led0[k], k
        assert led1["invariant_holds"]

    def test_resume_unknown_run_typed(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        with pytest.raises(CheckpointNotFoundError,
                           match="never silently"):
            store.resume(_key(run_id="t:nobody"))


# ---------------------------------------------------------------------
# Single-device runners: bit-match, preempt/resume, cadence edges
# ---------------------------------------------------------------------


class TestSingleDevice:
    @pytest.mark.smoke
    def test_invert_bitmatches_monolithic_and_warm_resume_free(
            self, tmp_path):
        """The n=64 smoke pin: segmented == monolithic bytes, and the
        preempt/resume round trip re-enters at the durable superstep
        with ZERO segment compiles (everything warm)."""
        import jax

        from tpu_jordan.ops.jordan_inplace import \
            block_jordan_invert_inplace_fori

        a = _mat(64, seed=3)
        ref, sing = jax.jit(
            lambda x: block_jordan_invert_inplace_fori(x, 16))(a)
        assert not bool(sing)
        store = CheckpointStore(str(tmp_path))
        inv, sing2, info = checkpointed_invert(
            a, 16, store=store, run_id="t:s64", cadence=2,
            engine="fori")
        assert not bool(sing2)
        assert fingerprint(inv) == fingerprint(ref)
        assert info["ckpt_written"] == 1          # boundary at 2, Nr=4
        # Preempt before the second segment: durable step 2.
        with activate(_preempt_plan(2)):
            with pytest.raises(PreemptedError) as ei:
                checkpointed_invert(a, 16, store=store, run_id="t:s64p",
                                    cadence=2, engine="fori")
        assert ei.value.step == 2
        assert store.has_live("t:s64p")
        mark = RECORDER.total
        inv2, _, info2 = checkpointed_invert(
            a, 16, store=store, run_id="t:s64p", cadence=2,
            engine="fori", resume_from="t:s64p")
        assert fingerprint(inv2) == fingerprint(ref)
        assert info2["resumed"] and info2["start_step"] == 2
        assert info2["segments_run"] == [(2, 4)]
        assert info2["segment_compiles"] == 0     # the zero-compile pin
        evs = [e["kind"] for e in RECORDER.since(mark)
               if str(e.get("kind", "")).startswith("ckpt_")]
        # The resume consumed the token; no writes remained past it
        # (the next boundary IS completion), so no discard event.
        assert evs == ["ckpt_resumed"]
        assert store.ledger()["invariant_holds"]

    def test_solve_bitmatches_monolithic(self, tmp_path):
        import jax

        from tpu_jordan.linalg.engine import block_jordan_solve_fori

        a, b = _mat(48, seed=5), _rhs(48, k=2, seed=6)
        ref, sing = jax.jit(
            lambda aa, bb: block_jordan_solve_fori(aa, bb, 8))(a, b)
        assert not bool(sing)
        store = CheckpointStore(str(tmp_path))
        x, sing2, info = checkpointed_solve(
            a, b, 8, store=store, run_id="t:sv", cadence=2,
            engine="fori")
        assert not bool(sing2)
        assert fingerprint(x) == fingerprint(ref)
        assert info["Nr"] == 6 and info["ckpt_written"] == 2

    def test_cadence_over_nr_writes_nothing_resume_typed(self, tmp_path):
        """Cadence > Nr: one monolithic segment, ZERO checkpoints —
        and asking to resume from that store is a typed miss, never a
        silent from-scratch run."""
        store = CheckpointStore(str(tmp_path))
        a = _mat(32, seed=7)
        inv, _, info = checkpointed_invert(
            a, 8, store=store, run_id="t:wide", cadence=99,
            engine="fori")
        assert info["ckpt_written"] == 0
        assert info["segments_run"] == [(0, 4)]
        assert store.ledger()["written"] == 0
        with pytest.raises(CheckpointNotFoundError):
            checkpointed_invert(a, 8, store=store, run_id="t:wide",
                                cadence=99, engine="fori",
                                resume_from="t:wide")

    def test_cadence_one_and_ragged_tail_bitmatch(self, tmp_path):
        """Cadence 1 (a checkpoint at EVERY superstep) on a ragged n
        (70 = 4*16 + 6: the last block is partial) still bit-matches;
        preempt/resume crosses the ragged boundary."""
        import jax

        from tpu_jordan.ops.jordan_inplace import \
            block_jordan_invert_inplace_fori

        a = _mat(70, seed=9)
        ref, sing = jax.jit(
            lambda x: block_jordan_invert_inplace_fori(x, 16))(a)
        assert not bool(sing)
        store = CheckpointStore(str(tmp_path))
        inv, _, info = checkpointed_invert(
            a, 16, store=store, run_id="t:rag", cadence=1,
            engine="fori")
        assert fingerprint(inv) == fingerprint(ref)
        assert info["Nr"] == 5 and info["ckpt_written"] == 4
        with activate(_preempt_plan(5)):          # durable step 4
            with pytest.raises(PreemptedError) as ei:
                checkpointed_invert(a, 16, store=store, run_id="t:ragp",
                                    cadence=1, engine="fori")
        assert ei.value.step == 4
        inv2, _, info2 = checkpointed_invert(
            a, 16, store=store, run_id="t:ragp", cadence=1,
            engine="fori", resume_from="t:ragp")
        assert fingerprint(inv2) == fingerprint(ref)
        assert info2["segments_run"] == [(4, 5)]  # the ragged tail

    def test_grouped_cadence_snaps_to_group_boundary(self, tmp_path):
        """The grouped engine closes its (V, swaps, t) state only at
        group boundaries: cadence 2 with group 4 rounds UP to 4, and
        the resume re-enters exactly on the group grid."""
        import jax

        from tpu_jordan.ops.jordan_inplace import \
            block_jordan_invert_inplace_grouped

        a = _mat(64, seed=11)
        ref, sing = jax.jit(
            lambda x: block_jordan_invert_inplace_grouped(
                x, 8, group=4))(a)
        assert not bool(sing)
        store = CheckpointStore(str(tmp_path))
        inv, _, info = checkpointed_invert(
            a, 8, store=store, run_id="t:grp", cadence=2,
            engine="grouped", group=4)
        assert fingerprint(inv) == fingerprint(ref)
        assert info["cadence"] == 4               # snapped up
        assert info["ckpt_written"] == 1          # Nr=8: boundary at 4
        with activate(_preempt_plan(2)):          # durable step 4
            with pytest.raises(PreemptedError) as ei:
                checkpointed_invert(a, 8, store=store, run_id="t:grpp",
                                    cadence=2, engine="grouped", group=4)
        assert ei.value.step == 4
        inv2, _, info2 = checkpointed_invert(
            a, 8, store=store, run_id="t:grpp", cadence=2,
            engine="grouped", group=4, resume_from="t:grpp")
        assert fingerprint(inv2) == fingerprint(ref)
        assert info2["start_step"] == 4

    def test_preempt_before_first_boundary_carries_step_none(
            self, tmp_path):
        """Preempted before anything durable: the typed error says so
        (step None) — the CORRECT recovery is from scratch, and that
        is the caller's explicit choice, not the runner's."""
        store = CheckpointStore(str(tmp_path))
        with activate(_preempt_plan(1)):
            with pytest.raises(PreemptedError) as ei:
                checkpointed_invert(_mat(32), 8, store=store,
                                    run_id="t:early", cadence=2,
                                    engine="fori")
        assert ei.value.step is None
        assert not store.has_live("t:early")


# ---------------------------------------------------------------------
# Typed refusal sweep (satellite 2)
# ---------------------------------------------------------------------


class TestRefusals:
    def test_resume_key_must_name_this_run(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        with pytest.raises(CheckpointMismatchError,
                           match="exactly its own run"):
            checkpointed_invert(_mat(32), 8, store=store, run_id="t:a",
                                cadence=2, engine="fori",
                                resume_from="t:b")

    def test_mismatched_layout_refused_on_resume(self, tmp_path):
        """A checkpoint written at one (n, m, Nr) must not feed a call
        with another: block_size 16 vs 8 changes Nr and is refused by
        type, naming the mismatched fields."""
        store = CheckpointStore(str(tmp_path))
        a = _mat(64, seed=13)
        with activate(_preempt_plan(2)):
            with pytest.raises(PreemptedError):
                checkpointed_invert(a, 16, store=store, run_id="t:mm",
                                    cadence=2, engine="fori")
        with pytest.raises(CheckpointMismatchError,
                           match="does not describe"):
            checkpointed_invert(a, 8, store=store, run_id="t:mm",
                                cadence=2, engine="fori",
                                resume_from="t:mm")

    def test_spd_fast_path_unsupported(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        with pytest.raises(CheckpointUnsupportedError,
                           match="SPD fast path"):
            checkpointed_solve(_mat(32), _rhs(32), 8, store=store,
                               run_id="t:spd", cadence=2,
                               engine="fori", spd=True)

    def test_complex_distributed_unsupported(self, tmp_path):
        from tpu_jordan.parallel.mesh import make_mesh

        store = CheckpointStore(str(tmp_path))
        a = _mat(32, dtype=np.complex64)
        with pytest.raises(CheckpointUnsupportedError,
                           match="complex distributed"):
            checkpointed_invert(a, 8, store=store, run_id="t:cplx",
                                cadence=2, engine="fori",
                                mesh=make_mesh(2))

    def test_pipeline_engines_unsupported(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        with pytest.raises(CheckpointUnsupportedError,
                           match="not checkpointable"):
            checkpointed_invert(_mat(32), 8, store=store,
                                run_id="t:look", cadence=2,
                                engine="lookahead")

    def test_cadence_below_one_refused(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        with pytest.raises(ValueError, match="cadence must be >= 1"):
            checkpointed_invert(_mat(32), 8, store=store, run_id="t:c0",
                                cadence=0, engine="fori")

    def test_cli_misapplied_flags_typed(self, capsys):
        """--ckpt-dir without --ckpt-demo, and --ckpt-demo combined
        with flags it cannot honor, are UsageError (exit 1) with
        messages that name the contract — checked BEFORE any device
        work, so these are cheap."""
        from tpu_jordan.__main__ import main

        cases = [
            (["96", "16", "--ckpt-dir", "/tmp/x"],
             "--ckpt-dir applies to --ckpt-demo"),
            (["96", "16", "--ckpt-demo", "--workload", "solve"],
             "checkpoints both workloads"),
            (["96", "16", "--ckpt-demo", "--engine", "inplace"],
             "fixed engine-leg set"),
            (["96", "16", "--ckpt-demo", "--replicas", "5"],
             "kill leg is fixed"),
            (["96", "16", "--ckpt-demo", "--serve-demo"],
             "distinct modes"),
            (["96", "16", "--ckpt-demo", "--dtype", "complex64"],
             "use a real dtype"),
        ]
        for argv, fragment in cases:
            assert main(argv) == 1, argv
            assert fragment in capsys.readouterr().err, argv


# ---------------------------------------------------------------------
# Distributed: the 8-device dryrun leg (1D p=8 mid-sweep resume) + 2D
# ---------------------------------------------------------------------


class TestDistributed:
    def test_1d_p8_solve_resumes_mid_sweep_bit_exact(self, tmp_path):
        """The 8-device dryrun leg: a 1D p=8 sharded solve preempted
        mid-sweep resumes at the durable superstep and bit-matches the
        uninterrupted checkpointed run — with zero compiles on the
        warm resume."""
        from tpu_jordan.parallel.mesh import make_mesh

        mesh = make_mesh(8)
        a, b = _mat(64, seed=17), _rhs(64, k=2, seed=18)
        store = CheckpointStore(str(tmp_path))
        x0, sing, info0 = checkpointed_solve(
            a, b, 8, store=store, run_id="t:p8", cadence=2,
            engine="fori", mesh=mesh)
        assert not bool(sing)
        assert info0["topology"] == "1d:8" and info0["Nr"] == 8
        ref = fingerprint(x0)
        with activate(_preempt_plan(3)):          # durable step 4
            with pytest.raises(PreemptedError) as ei:
                checkpointed_solve(a, b, 8, store=store, run_id="t:p8p",
                                   cadence=2, engine="fori", mesh=mesh)
        assert ei.value.step == 4
        x1, _, info1 = checkpointed_solve(
            a, b, 8, store=store, run_id="t:p8p", cadence=2,
            engine="fori", mesh=mesh, resume_from="t:p8p")
        assert fingerprint(x1) == ref
        assert info1["resumed"] and info1["start_step"] == 4
        assert info1["segments_run"] == [(4, 6), (6, 8)]
        assert info1["segment_compiles"] == 0
        assert store.ledger()["invariant_holds"]

    @pytest.mark.slow
    def test_2d_invert_resumes_bit_exact(self, tmp_path):
        from tpu_jordan.parallel.mesh import make_mesh_2d

        mesh = make_mesh_2d(2, 2)
        a = _mat(48, seed=19)
        store = CheckpointStore(str(tmp_path))
        inv0, sing, _ = checkpointed_invert(
            a, 8, store=store, run_id="t:2d", cadence=2,
            engine="fori", mesh=mesh)
        assert not bool(sing)
        with activate(_preempt_plan(2)):
            with pytest.raises(PreemptedError) as ei:
                checkpointed_invert(a, 8, store=store, run_id="t:2dp",
                                    cadence=2, engine="fori", mesh=mesh)
        assert ei.value.step == 2
        inv1, _, info1 = checkpointed_invert(
            a, 8, store=store, run_id="t:2dp", cadence=2,
            engine="fori", mesh=mesh, resume_from="t:2dp")
        assert fingerprint(inv1) == fingerprint(inv0)
        assert info1["start_step"] == 2


# ---------------------------------------------------------------------
# The fleet kill path and the resumable LP stream
# ---------------------------------------------------------------------


class TestFleetAndLP:
    def test_killed_replica_resumes_on_survivor_bit_exact(self):
        """The ISSUE 20 fleet wire-through: a replica killed while
        serving a ckpt_solve dies at the next segment boundary; the
        router re-queues, probes the store, dispatches with
        ``resume_from`` (the ``ckpt_resume`` journey hop) and the
        result bit-matches the uninterrupted run — lost work bounded
        by the cadence."""
        import tempfile

        from tpu_jordan.fleet.pool import JordanFleet
        from tpu_jordan.parallel.mesh import make_mesh
        from tpu_jordan.resilience import ResiliencePolicy, RetryPolicy

        a, b = _mat(96, seed=21, dtype=np.float64), \
            _rhs(96, k=4, seed=22, dtype=np.float64)
        mesh = make_mesh(4)
        store = CheckpointStore(tempfile.mkdtemp(prefix="t_ckpt_fleet_"))
        spec = {"store": store, "cadence": 2, "engine": "fori",
                "mesh": mesh, "block_size": 16}
        mark = RECORDER.total
        with JordanFleet(replicas=2, engine="auto", dtype="float64",
                         batch_cap=1, max_wait_ms=0.5,
                         stable_after_s=0.2, liveness_deadline_s=30.0,
                         policy=ResiliencePolicy(retry=RetryPolicy(
                             max_retries=4, backoff_s=0.0))) as fleet:
            res0 = fleet.solve_system(
                a, b, timeout=300.0,
                ckpt=dict(spec, run_id="t:fleet:base"))
            ref = fingerprint(res0.solution)
            run_id = "t:fleet:killed"
            fut = fleet.submit_solve(a, b,
                                     ckpt=dict(spec, run_id=run_id))
            deadline = time.monotonic() + 120
            while not store.has_live(run_id):
                assert time.monotonic() < deadline, \
                    "no checkpoint written in time"
                time.sleep(0.005)
            serving = {t.name.split("tpu-jordan-ckpt-")[1]
                       for t in threading.enumerate()
                       if t.name.startswith("tpu-jordan-ckpt-")}
            killed = [r.name for r in fleet.live_replicas()
                      if r.name in serving and r.kill(reason="chaos")]
            assert killed, "no serving replica found to kill"
            res1 = fut.result(timeout=300.0)
        assert fingerprint(res1.solution) == ref
        assert res1.ckpt_info["resumed"]
        evs = [e for e in RECORDER.since(mark)
               if e.get("run_id") == run_id]
        kinds = [e["kind"] for e in evs]
        assert "ckpt_preempted" in kinds and "ckpt_resumed" in kinds
        # The journey explains the recovery: the re-dispatch carries
        # the ckpt_resume hop (mirrored into the flight recorder).
        assert any(e["kind"] == "journey"
                   and e.get("event") == "ckpt_resume" for e in evs)
        assert store.ledger()["invariant_holds"]

    def test_lp_stream_resumes_to_identical_kkt_trail(self):
        """``solve_lp(resume=True)`` replays the remaining iterations
        from the persisted iterate audit to the IDENTICAL ``kkt_hex``
        trail the uninterrupted stream produced."""
        import tempfile

        from tpu_jordan.fleet.pool import JordanFleet
        from tpu_jordan.lpqp.driver import solve_lp
        from tpu_jordan.lpqp.problem import lp_instance
        from tpu_jordan.resilience import ResiliencePolicy, RetryPolicy

        prob = lp_instance(m=8, seed=23, cond="well")
        store = CheckpointStore(tempfile.mkdtemp(prefix="t_ckpt_lp_"))
        with JordanFleet(replicas=2, engine="auto", dtype="float64",
                         batch_cap=1, max_wait_ms=0.5,
                         stable_after_s=0.2, liveness_deadline_s=30.0,
                         policy=ResiliencePolicy(retry=RetryPolicy(
                             max_retries=4, backoff_s=0.0))) as fleet:
            ref = solve_lp(prob, fleet)
            trail = [it["kkt_hex"] for it in ref.iterates]
            assert len(trail) >= 4, "fixture converged too fast"
            with activate(_preempt_plan(len(trail) - 1)):
                with pytest.raises(PreemptedError) as ei:
                    solve_lp(prob, fleet, ckpt_store=store,
                             ckpt_every=2, run_id="t:lp")
            assert ei.value.step is not None
            resumed = solve_lp(prob, fleet, ckpt_store=store,
                               ckpt_every=2, run_id="t:lp",
                               resume=True)
        assert [it["kkt_hex"] for it in resumed.iterates] == trail
        assert resumed.fingerprint == ref.fingerprint
        assert resumed.converged == ref.converged
        assert store.ledger()["invariant_holds"]


# ---------------------------------------------------------------------
# Dispatcher reap (satellite 1)
# ---------------------------------------------------------------------


class TestDispatcherReap:
    def test_reap_joins_abandoned_dispatcher_and_counts(self):
        """The abandoned-dispatcher epilogue: after a bounded kill-path
        close abandons a wedged dispatcher, ``reap()`` returns False
        while the wedge holds, then joins the unstuck thread, clears
        the reference, and counts the recovery exactly once."""
        from tpu_jordan.serve.batcher import MicroBatcher
        from tpu_jordan.serve.stats import ServeStats

        gate = threading.Event()

        class StuckExecutors:
            def breaker(self, bucket):
                return None

            def get_info(self, bucket, batch_cap, block_size, **kw):
                gate.wait(30)
                raise RuntimeError("released")

        mb = MicroBatcher(StuckExecutors(), ServeStats(),
                          batch_cap=1, max_wait_ms=0.1)
        fut = mb.submit(np.eye(4, dtype=np.float32), 4, 64)
        deadline = time.monotonic() + 10
        while not mb.progress()[1] and time.monotonic() < deadline:
            time.sleep(0.005)
        assert mb.progress()[1]
        mb.close(drain=False, join_timeout_s=0.2)   # abandons (counted)
        reaped = REGISTRY.counter(
            "tpu_jordan_serve_dispatcher_reaped_total")
        before = reaped.total()
        assert mb.reap() is False                   # still wedged
        assert reaped.total() == before
        gate.set()                                  # wedge clears
        with pytest.raises(RuntimeError, match="released"):
            fut.result(30)
        deadline = time.monotonic() + 10
        while not mb.reap() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert mb.reap() is True                    # idempotent
        assert reaped.total() == before + 1         # counted ONCE
        assert mb._thread is None

    def test_reap_never_touches_a_live_dispatcher(self):
        from tpu_jordan.serve.batcher import MicroBatcher
        from tpu_jordan.serve.stats import ServeStats

        class IdleExecutors:
            def breaker(self, bucket):
                return None

        mb = MicroBatcher(IdleExecutors(), ServeStats(), batch_cap=1)
        try:
            assert mb.reap() is False   # serving: nothing abandoned
        finally:
            mb.close()
        assert mb.reap() is True        # clean close left no thread

    def test_second_service_close_reaps(self):
        """JordanService.close() is the reap retry point: a second
        close on an already-closed service joins any abandoned
        dispatcher instead of silently no-opping."""
        from tpu_jordan.serve.service import JordanService

        svc = JordanService(batch_cap=1, autostart=False)
        svc.close()
        svc.close()                     # must not raise; reaps inline
        assert svc._batcher.reap() is True


# ---------------------------------------------------------------------
# check_ckpt: the doctored-report traps (no jax in the checker)
# ---------------------------------------------------------------------


def _leg(name, **kw):
    base = {"run_id": f"demo:{name}", "workload": "invert",
            "topology": "single", "engine": "fori", "n": 96,
            "block_size": 16, "Nr": 6, "cadence": 2,
            "preempt_step": 4, "baseline_fp": "aa", "resume_fp": "aa",
            "bit_match": True, "resume_start_step": 4, "resumed": True,
            "resume_segments": [[4, 6]], "resume_compiles": 0}
    base.update(kw)
    return base


def _report():
    legs = {
        "single_invert": _leg("single_invert"),
        "dist_solve": _leg("dist_solve", workload="solve",
                           topology="1d:4", Nr=8, preempt_step=4,
                           resume_start_step=4,
                           resume_segments=[[4, 6], [6, 8]]),
        "lp_stream": _leg("lp_stream", workload="lp", topology="fleet",
                          preempt_step=6, resume_start_step=6,
                          resume_segments=[], kkt_trail_match=True),
        "fleet_kill": _leg("fleet_kill", workload="solve",
                           topology="1d:4", Nr=8, preempt_step=4,
                           resume_start_step=4,
                           resume_segments=[[4, 6], [6, 8]],
                           killed_replicas=["r0g1"],
                           kill_attempts=1),
    }
    events = []
    for name, leg in legs.items():
        rid = leg["run_id"]
        events += [
            {"kind": "ckpt_written", "run_id": rid, "step": 2},
            {"kind": "ckpt_written", "run_id": rid,
             "step": leg["preempt_step"]},
            {"kind": "ckpt_preempted", "run_id": rid,
             "step": leg["preempt_step"]},
            {"kind": "ckpt_resumed", "run_id": rid,
             "step": leg["preempt_step"]},
            {"kind": "ckpt_discarded", "run_id": rid,
             "reason": "complete"},
        ]
    return {
        "metric": "ckpt_demo", "n": 96, "block_size": 16, "cadence": 2,
        "seed": 0, "workers": 4, "dtype": "float64", "legs": legs,
        "ledger": {"written": 8, "resumed": 4, "discarded": 4,
                   "corrupt": 0, "live": 0, "invariant_holds": True},
        "counters": {}, "silent_loss": False,
        "blackbox": {"events": events},
    }


class TestCheckCkpt:
    def test_accepts_clean_report(self, tmp_path):
        errs, loss = check_ckpt.check(_report())
        assert errs == [] and loss == []
        p = tmp_path / "ckpt.json"
        p.write_text(json.dumps(_report()))
        assert check_ckpt.main([str(p)]) == 0

    def _loss(self, report, fragment):
        errs, loss = check_ckpt.check(report)
        assert any(fragment in m for m in loss), (fragment, loss, errs)

    def test_rejects_divergent_resume(self):
        r = _report()
        r["legs"]["dist_solve"]["bit_match"] = False
        r["legs"]["dist_solve"]["resume_fp"] = "bb"
        self._loss(r, "diverged from the uninterrupted baseline")

    def test_rejects_silent_from_scratch(self):
        r = _report()
        r["legs"]["single_invert"]["resumed"] = False
        self._loss(r, "silent recompute-from-scratch")

    def test_rejects_resume_at_wrong_step(self):
        r = _report()
        r["legs"]["single_invert"]["resume_start_step"] = 0
        self._loss(r, "work silently lost")

    def test_rejects_segment_over_cadence(self):
        r = _report()
        r["legs"]["dist_solve"]["resume_segments"] = [[4, 8]]
        self._loss(r, "lost-work bound is broken")

    def test_rejects_recompiled_resume(self):
        r = _report()
        r["legs"]["fleet_kill"]["resume_compiles"] = 2
        self._loss(r, "zero-compile pin broke")

    def test_rejects_diverged_lp_trail(self):
        r = _report()
        r["legs"]["lp_stream"]["kkt_trail_match"] = False
        self._loss(r, "silently diverged")

    def test_rejects_stripped_resume_events(self):
        r = _report()
        r["blackbox"]["events"] = [
            e for e in r["blackbox"]["events"]
            if not (e["kind"] == "ckpt_resumed"
                    and e["run_id"] == "demo:fleet_kill")]
        self._loss(r, "no matching ckpt_resumed")

    def test_rejects_ledger_event_drift(self):
        r = _report()
        r["ledger"]["written"] = 9
        r["ledger"]["discarded"] = 5      # still adds up internally...
        self._loss(r, "drifted from its own event stream")

    def test_rejects_broken_invariant(self):
        r = _report()
        r["ledger"]["discarded"] = 3
        self._loss(r, "does not add up")

    def test_rejects_demo_self_flag(self):
        r = _report()
        r["silent_loss"] = True
        self._loss(r, "flagged by the demo itself")

    def test_structure_violations_exit_1_not_0(self, tmp_path):
        r = _report()
        del r["legs"]["fleet_kill"]
        errs, loss = check_ckpt.check(r)
        assert any("missing leg" in m for m in errs)
        p = tmp_path / "bad.json"
        p.write_text(json.dumps(r))
        assert check_ckpt.main([str(p)]) == 1
