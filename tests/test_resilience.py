"""The resilience layer (ISSUE 5): deterministic fault injection
(nth-call FaultPlans), the promoted transient classifier + RetryPolicy
(deterministic-jitter backoff, injectable sleep), the circuit-breaker
state machine (fake clock), the plan-cache write-failure degrade
satellite, and the driver-side residual-gate degradation ladder — incl.
the bf16 -> refine -> fp32-re-solve acceptance pin with every rung on
``SolveResult.recovery`` and in the span tree."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.resilience import (CircuitBreaker, FaultPlan, FaultSpec,
                                   InjectedFaultError,
                                   InjectedTransientError, ResiliencePolicy,
                                   ResultCorruptionError, RetryPolicy,
                                   activate, faults)
from tpu_jordan.resilience.policy import (DeadlineExceededError,
                                          ResidualGateError, retryable)


def _counter_total(name):
    return REGISTRY.counter(name).total()


class TestFaultPlan:
    def test_nth_call_schedule_is_exact(self):
        plan = FaultPlan([FaultSpec("execute", (2, 4), "transient")])
        with activate(plan):
            faults.fire("execute")                       # call 1: quiet
            with pytest.raises(InjectedTransientError):
                faults.fire("execute")                   # call 2: fires
            faults.fire("execute")                       # call 3: quiet
            with pytest.raises(InjectedTransientError):
                faults.fire("execute")                   # call 4: fires
            faults.fire("execute")                       # call 5: quiet
        assert [c for _, c, _ in plan.injections] == [2, 4]

    def test_modes(self):
        plan = FaultPlan([
            FaultSpec("compile", (1,), "permanent"),
            FaultSpec("plan_cache_write", (1,), "oserror"),
            FaultSpec("result_corrupt_nan", (2,), "corrupt"),
        ])
        with activate(plan):
            with pytest.raises(InjectedFaultError):
                faults.fire("compile")
            with pytest.raises(OSError):
                faults.fire("plan_cache_write")
            assert faults.corrupt("result_corrupt_nan") is False  # call 1
            assert faults.corrupt("result_corrupt_nan") is True   # call 2
            assert faults.corrupt("result_corrupt_nan") is False  # call 3

    def test_inactive_points_are_noops(self):
        # No active plan: fire/corrupt cost one global load, do nothing.
        faults.fire("execute")
        assert faults.corrupt("result_corrupt_nan") is False
        assert faults.active() is None

    def test_seeded_plans_are_reproducible(self):
        p1, p2 = FaultPlan.seeded(7), FaultPlan.seeded(7)
        assert [(s.point, s.calls, s.mode) for s in p1.specs] \
            == [(s.point, s.calls, s.mode) for s in p2.specs]
        p3 = FaultPlan.seeded(8)
        assert [(s.point, s.calls) for s in p1.specs] \
            != [(s.point, s.calls) for s in p3.specs]

    def test_unknown_point_and_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            FaultSpec("warp_core", (1,))
        with pytest.raises(ValueError, match="unknown fault mode"):
            FaultSpec("execute", (1,), "probabilistic")
        plan = FaultPlan([])
        with pytest.raises(ValueError, match="unknown fault point"):
            plan.fire("warp_core")

    def test_chaos_scopes_do_not_nest(self):
        with activate(FaultPlan([])):
            with pytest.raises(RuntimeError, match="already active"):
                with activate(FaultPlan([])):
                    pass
        # ... and the outer scope still deactivated cleanly.
        assert faults.active() is None

    def test_injections_counted_in_registry(self):
        before = _counter_total("tpu_jordan_faults_injected_total")
        plan = FaultPlan([FaultSpec("measure", (1,), "transient")])
        with activate(plan):
            with pytest.raises(InjectedTransientError):
                faults.fire("measure")
        assert _counter_total(
            "tpu_jordan_faults_injected_total") == before + 1
        rep = plan.report()
        assert rep["injected_total"] == 1
        assert rep["injected_by_point"] == {"measure": 1}
        assert rep["log"] == [{"point": "measure", "call": 1,
                               "mode": "transient"}]


class TestRetryPolicy:
    def test_classifier_promoted_and_injected_faults_typed(self):
        # The one shared classifier (formerly tuning/measure.py): the
        # compat import must serve the SAME function object.
        from tpu_jordan.resilience.policy import is_transient
        from tpu_jordan.tuning import measure

        assert measure.is_transient is is_transient
        assert is_transient(InjectedTransientError("INTERNAL: x"))
        assert not is_transient(InjectedFaultError("INTERNAL: x"))
        # Corruption is retryable by the default policy classifier but
        # is NOT transport-transient.
        assert retryable(ResultCorruptionError("NaN"))
        assert not is_transient(ResultCorruptionError("INTERNAL NaN"))

    def test_deterministic_backoff_sequence(self):
        pol = RetryPolicy(max_retries=3, backoff_s=0.1, multiplier=2.0,
                          max_backoff_s=1.0, jitter_pct=10.0)
        # The jitter is a pure function of the attempt index: two
        # policies, one sequence — byte-reproducible chaos timing.
        seq = [pol.delay_s(k) for k in range(3)]
        assert seq == [RetryPolicy(max_retries=3, backoff_s=0.1).delay_s(k)
                       for k in range(3)]
        assert 0.1 <= seq[0] <= 0.11 and 0.2 <= seq[1] <= 0.22
        slept, calls = [], []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise InjectedTransientError("INTERNAL: flaky")
            return "ok"

        pol = RetryPolicy(max_retries=3, backoff_s=0.1,
                          sleep=slept.append)
        before = _counter_total("tpu_jordan_retries_total")
        assert pol.call(fn, component="test") == "ok"
        assert slept == [pol.delay_s(0), pol.delay_s(1)]
        assert _counter_total("tpu_jordan_retries_total") == before + 2

    def test_budget_exhaustion_raises_last_error(self):
        calls = []

        def always(_=None):
            calls.append(1)
            raise InjectedTransientError("INTERNAL: down")

        pol = RetryPolicy(max_retries=2, backoff_s=0.0)
        with pytest.raises(InjectedTransientError):
            pol.call(lambda: always())
        assert len(calls) == 3                    # 1 try + 2 retries

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise AssertionError("INTERNAL quoted in an accuracy check")

        with pytest.raises(AssertionError):
            RetryPolicy(max_retries=5, backoff_s=0.0).call(fn)
        assert len(calls) == 1

    def test_measure_fault_point_rides_the_shared_retry(self):
        # tuning/measure.measure_direct crosses the `measure` point and
        # absorbs one transient via the shared policy.
        from tpu_jordan.tuning.measure import measure_direct

        before = _counter_total("tpu_jordan_retries_total")
        plan = FaultPlan([FaultSpec("measure", (1,), "transient")])
        with activate(plan):
            m = measure_direct(lambda: None, samples=2, warmup=1)
        assert len(m.samples) == 2
        assert plan.injected_total == 1
        assert _counter_total("tpu_jordan_retries_total") == before + 1


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestCircuitBreaker:
    def test_state_machine_with_fake_clock(self):
        clk = FakeClock()
        br = CircuitBreaker(failures=3, cooldown_s=5.0, clock=clk,
                            name="t1")
        opens = _counter_total("tpu_jordan_breaker_open_total")
        assert br.allow() and br.state == "closed"
        br.record_failure(); br.record_failure()
        assert br.state == "closed" and br.allow()
        br.record_failure()                      # K=3 consecutive: open
        assert br.state == "open" and not br.allow()
        assert _counter_total(
            "tpu_jordan_breaker_open_total") == opens + 1
        clk.t = 4.9
        assert not br.allow()                    # cooldown not elapsed
        clk.t = 5.0
        assert br.state == "half_open"
        assert br.allow()                        # the probe is admitted
        br.record_failure()                      # failed probe: reopen
        assert br.state == "open" and not br.allow()
        clk.t = 10.0
        assert br.allow()
        br.record_success()                      # probe success: closed
        assert br.state == "closed" and br.allow()

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(failures=2, cooldown_s=1.0, clock=FakeClock())
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == "closed"              # never 2 consecutive

    def test_typed_errors(self):
        from tpu_jordan.resilience import CircuitOpenError

        assert issubclass(DeadlineExceededError, TimeoutError)
        assert issubclass(CircuitOpenError, RuntimeError)


class TestPlanCacheWriteDegrade:
    def test_injected_write_failure_degrades_to_memory(self, tmp_path):
        """ISSUE 5 satellite: a save failure (disk full, simulated via
        the plan_cache_write fault point) warns + keeps serving from
        memory; it never raises out of the solve that triggered it."""
        from tpu_jordan.tuning.plan_cache import Plan, PlanCache

        path = str(tmp_path / "plans.json")
        cache = PlanCache(path=path)
        cache.put("k", Plan(config="inplace", engine="inplace"))
        before = _counter_total(
            "tpu_jordan_plan_cache_write_failures_total")
        plan = FaultPlan([FaultSpec("plan_cache_write", (1,), "oserror")])
        with activate(plan):
            cache.save()                          # degrades, no raise
        assert _counter_total(
            "tpu_jordan_plan_cache_write_failures_total") == before + 1
        assert cache.last_write_error is not None
        assert not (tmp_path / "plans.json").exists()
        assert cache.get("k") is not None         # in-memory plans live
        cache.save()                              # disk pressure cleared
        assert cache.last_write_error is None
        assert PlanCache.load(path).get("k").engine == "inplace"

    def test_real_readonly_destination_degrades(self, tmp_path):
        """A genuinely unwritable destination (dirname is a FILE) takes
        the same degrade path with no fault plan active."""
        from tpu_jordan.tuning.plan_cache import Plan, PlanCache

        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        cache = PlanCache(path=str(blocker / "plans.json"))
        cache.put("k", Plan(config="inplace", engine="inplace"))
        cache.save()                              # no raise
        assert cache.last_write_error is not None
        assert cache.get("k") is not None

    def test_tuner_select_survives_write_failure(self, tmp_path):
        """End to end: engine="auto" with a failing plan-cache write
        still resolves (and re-resolves) — the documented degrade."""
        from tpu_jordan.tuning.plan_cache import PlanCache
        from tpu_jordan.tuning.registry import TunePoint
        from tpu_jordan.tuning.tuner import Tuner

        cache = PlanCache(path=str(tmp_path / "x" / "plans.json"))
        t = Tuner(cache=cache)
        pt = TunePoint.create(512, 128, "float32", 1, True)
        plan = FaultPlan([FaultSpec("plan_cache_write", (1,), "oserror")])
        with activate(plan):
            p1 = t.select(pt)
        assert p1.engine == "inplace"
        assert cache.last_write_error is not None
        assert t.select(pt).engine == p1.engine   # in-memory hit


class TestDriverPolicy:
    def test_transient_compile_and_execute_faults_retried_bitmatch(self):
        """ONE solve absorbs a transient compile failure AND a
        transient execute failure (two counted retries) and still
        bit-matches the fault-free solve."""
        from tpu_jordan import solve

        clean = solve(48, 16, generator="rand", engine="inplace")
        pol = ResiliencePolicy(retry=RetryPolicy(max_retries=2,
                                                 backoff_s=0.0))
        before = _counter_total("tpu_jordan_retries_total")
        plan = FaultPlan([FaultSpec("compile", (1,), "transient"),
                          FaultSpec("execute", (1,), "transient")])
        with activate(plan):
            r = solve(48, 16, generator="rand", engine="inplace",
                      policy=pol)
        assert plan.injected_total == 2
        assert _counter_total("tpu_jordan_retries_total") == before + 2
        assert r.recovery == ()
        assert (np.asarray(r.inverse) == np.asarray(clean.inverse)).all()

    def test_nan_corruption_recovers_through_resolve_rung(self):
        """Injected NaN corruption fails the gate (NaN rel_residual),
        refine can't fix NaN, the re-solve rung returns the bit-exact
        clean inverse — zero silent corruption."""
        from tpu_jordan import solve

        clean = solve(48, 16, generator="rand", engine="inplace")
        pol = ResiliencePolicy(retry=RetryPolicy(max_retries=1,
                                                 backoff_s=0.0))
        plan = FaultPlan([FaultSpec("result_corrupt_nan", (1,),
                                    "corrupt")])
        with activate(plan):
            r = solve(48, 16, generator="rand", engine="inplace",
                      policy=pol)
        assert [x["rung"] for x in r.recovery] == ["refine", "resolve"]
        assert not r.recovery[0]["passed"] and r.recovery[1]["passed"]
        assert (np.asarray(r.inverse) == np.asarray(clean.inverse)).all()

    def test_exhausted_ladder_raises_typed_not_silent(self):
        from tpu_jordan import solve

        pol = ResiliencePolicy(gate_tol=1e-12, refine_steps=0,
                               escalate=False)
        with pytest.raises(ResidualGateError) as ei:
            solve(32, 8, generator="rand", engine="inplace", policy=pol)
        assert ei.value.recovery == ()

    def test_solver_model_policy_retries_execute(self):
        from tpu_jordan.models import JordanSolver

        pol = ResiliencePolicy(retry=RetryPolicy(max_retries=1,
                                                 backoff_s=0.0))
        sol = JordanSolver(n=32, block_size=8, engine="inplace",
                           policy=pol)
        a = np.asarray(jnp.eye(32) * 2.0)
        before = _counter_total("tpu_jordan_retries_total")
        plan = FaultPlan([FaultSpec("execute", (1,), "transient")])
        with activate(plan):
            inv, sing = sol.invert(a)
        assert not bool(sing)
        assert _counter_total("tpu_jordan_retries_total") == before + 1
        np.testing.assert_allclose(np.asarray(inv), np.eye(32) / 2.0)


# The deliberately ill-conditioned rotated-graded-diagonal fixture was
# promoted to obs/numerics.py (ISSUE 10) so the ladder-acceptance tests
# and the numerics demo exercise ONE recipe that can never drift.
from tpu_jordan.obs.numerics import ill_conditioned as _ill_conditioned  # noqa: E402,E501


class TestDegradationLadderAcceptance:
    def test_bf16_fails_gate_recovers_refine_then_fp32(self, tmp_path):
        """ISSUE 5 acceptance: an ill-conditioned matrix that fails the
        residual gate at bf16 recovers through refine -> fp32 re-solve,
        each rung visible in SolveResult.recovery AND the span tree."""
        from tpu_jordan import solve
        from tpu_jordan.io import write_matrix_file
        from tpu_jordan.obs.spans import Telemetry

        n = 16
        path = str(tmp_path / "ill.mat")
        write_matrix_file(path, _ill_conditioned(n))
        tel = Telemetry()
        pol = ResiliencePolicy(gate_dtype="float32")
        r = solve(n, 8, file=path, dtype=jnp.bfloat16, policy=pol,
                  telemetry=tel)
        # Both rungs ran: refine diverged (bf16-grade initial residual
        # > 1 kills Newton-Schulz), the fp32 re-solve passed its gate.
        assert [x["rung"] for x in r.recovery] == ["refine", "resolve"]
        assert not r.recovery[0]["passed"]
        assert r.recovery[1]["passed"]
        assert r.recovery[1]["dtype"] == "float32"
        assert r.inverse.dtype == jnp.float32
        assert r.rel_residual < r.recovery[0]["rel_residual_before"]
        # Span tree: solve -> ... -> recover -> {refine, resolve}, with
        # the re-solve's own compile/execute nested under `resolve`.
        root = tel.roots[-1]
        rec = root.find("recover")
        assert rec is not None
        assert [c.name for c in rec.children] == ["refine", "resolve"]
        assert rec.attrs["recovered_by"] == "resolve"
        resolve_span = rec.find("resolve")
        assert resolve_span.find("execute") is not None

    def test_float64_refine_rung_stays_float64(self, tmp_path):
        """A float64 solve that enters the ladder must refine at
        float64 and be judged against eps64 — never silently downgraded
        to fp32 (which would 'pass' a ~1e9x looser gate)."""
        from tpu_jordan import solve
        from tpu_jordan.io import write_matrix_file

        n = 16
        path = str(tmp_path / "ill64.mat")
        write_matrix_file(path, _ill_conditioned(n))
        # Force the ladder: corrupt the f64 result, no escalation room.
        pol = ResiliencePolicy(refine_steps=2)
        plan = FaultPlan([FaultSpec("result_corrupt_nan", (1,),
                                    "corrupt")])
        with activate(plan):
            r = solve(n, 8, file=path, dtype=jnp.float64, policy=pol)
        # NaN corruption: refine on NaN stays NaN (fails at eps64),
        # the re-solve rung recovers — and everything stays float64.
        assert [x["rung"] for x in r.recovery] == ["refine", "resolve"]
        assert r.recovery[1]["dtype"] == "float64"
        assert r.inverse.dtype == jnp.float64
        assert r.rel_residual < 1e-10        # genuinely fp64-grade

    def test_gate_passes_untouched_on_healthy_solve(self):
        """Fault-free warm path: a healthy fp32 solve under the default
        policy pays one gate comparison — no rungs, no retries, same
        bits as the policy-free solve."""
        from tpu_jordan import solve
        from tpu_jordan.resilience import DEFAULT_POLICY

        before = _counter_total("tpu_jordan_retries_total")
        clean = solve(48, 16, generator="rand", engine="inplace")
        r = solve(48, 16, generator="rand", engine="inplace",
                  policy=DEFAULT_POLICY)
        assert r.recovery == ()
        assert (np.asarray(r.inverse) == np.asarray(clean.inverse)).all()
        assert _counter_total("tpu_jordan_retries_total") == before
