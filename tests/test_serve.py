"""The serving subsystem (ISSUE 3): bucket rounding, the AOT executable
cache (one compile per key, plan-cache engine resolution), the dynamic
micro-batcher (futures, deadlines, partial batches), JordanService's
product contract (admission control, warmup, draining shutdown,
stats), the CLI --serve-demo exit codes, and the acceptance pin — the
sustained-throughput demo with every counter nailed down."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.serve import (JordanService, MIN_BUCKET_N, ServiceClosedError,
                              ServiceOverloadedError, bucket_for, serve_demo)


def _mats(rng, sizes, copies=1, dtype=np.float32):
    """Well-conditioned request fixtures, one per (size, copy)."""
    return [rng.standard_normal((s, s)).astype(dtype)
            for s in sizes for _ in range(copies)]


def _direct_padded(a, bucket, block_size=None):
    """The comparison oracle the acceptance contract names: a direct
    solve of the same padded shape — the identity-padded matrix run
    through the driver's own single-device engine (what solve() runs
    for this shape)."""
    from tpu_jordan.config import default_block_size
    from tpu_jordan.driver import single_device_invert
    from tpu_jordan.ops import pad_with_identity

    m = block_size or default_block_size(bucket)
    pad = pad_with_identity(jnp.asarray(a, jnp.float32), bucket)
    inv, sing = single_device_invert(bucket, m)(pad, block_size=m)
    return np.asarray(inv), bool(sing)


class TestBuckets:
    def test_pow2_rounding_with_floor(self):
        assert bucket_for(1) == MIN_BUCKET_N
        assert bucket_for(MIN_BUCKET_N) == MIN_BUCKET_N
        assert bucket_for(MIN_BUCKET_N + 1) == 2 * MIN_BUCKET_N
        assert bucket_for(200) == 256
        assert bucket_for(256) == 256
        with pytest.raises(ValueError):
            bucket_for(0)

    @pytest.mark.slow  # tier-1 budget: the executor-cache one-compile pin stays
    def test_block_size_is_part_of_executor_key(self):
        """A direct cache user requesting a different m must get a
        fresh executable, never a stale-m cache hit."""
        from tpu_jordan.serve import ExecutorCache

        cache = ExecutorCache(dtype=jnp.float32)
        e8 = cache.get(64, 2, block_size=8)
        e32 = cache.get(64, 2, block_size=32)
        assert e8 is not e32
        assert e8.key.block_size == 8 and e32.key.block_size == 32
        assert cache.get(64, 2, block_size=8) is e8


class TestServeStatsLabels:
    def test_reserved_label_keys_refused_typed(self):
        """A user label colliding with the keys ServeStats stamps itself
        ('bucket'/'component') — or with the metric APIs' own 'value'/
        'exemplar' parameters (the latter would silently bind instead
        of becoming a label series) — must fail fast at construction
        with the typed UsageError, not TypeError on the first request."""
        from tpu_jordan.driver import UsageError
        from tpu_jordan.serve import ServeStats

        for key in ("bucket", "component", "value", "exemplar"):
            with pytest.raises(UsageError, match="reserved metric label"):
                ServeStats(labels={key: "x"})
        # Non-reserved labels still work end to end.
        s = ServeStats(labels={"replica": "r0"})
        s.request(64)
        assert s.snapshot()["buckets"]["64"]["requests"] == 1


class TestExecutorCache:
    def test_one_compile_per_key_then_hits(self):
        from tpu_jordan.serve import ExecutorCache, ServeStats

        stats = ServeStats()
        cache = ExecutorCache(dtype=jnp.float32, stats=stats)
        e1 = cache.get(64, 4)
        e2 = cache.get(64, 4)
        assert e1 is e2
        snap = stats.snapshot()["buckets"]["64"]
        assert snap["compiles"] == 1 and snap["cache_hits"] == 1
        # A different batch_cap is a different executable (static shape).
        e3 = cache.get(64, 2)
        assert e3 is not e1
        assert stats.snapshot()["buckets"]["64"]["compiles"] == 2

    def test_engine_resolved_through_plan_cache(self, tmp_path):
        """Warm path: the resolved plan comes from the JSON plan cache
        (batched key) and the tuner performs zero measurements."""
        from tpu_jordan.serve import ExecutorCache
        from tpu_jordan.tuning import PlanCache

        path = str(tmp_path / "plans.json")
        c1 = ExecutorCache(plan_cache=path, dtype=jnp.float32)
        ex = c1.get(64, 4)
        assert ex.key.engine == "inplace"          # cost ladder, small n
        assert ex.plan is not None and ex.plan.source == "cost_model"
        assert c1.measurements == 0
        # The batched key landed on disk...
        disk = PlanCache.load(path)
        assert any(k.endswith("|b4") for k in disk.plans)
        # ... and a fresh cache over the same file serves it warm.
        c2 = ExecutorCache(plan_cache=path, dtype=jnp.float32)
        ex2 = c2.get(64, 4)
        assert ex2.key == ex.key
        assert c2.tuner.last_source == "cache"
        assert c2.measurements == 0

    def test_explicit_engine_skips_tuner(self):
        from tpu_jordan.serve import ExecutorCache

        cache = ExecutorCache(engine="augmented", dtype=jnp.float64)
        ex = cache.get(64, 2)
        assert ex.key.engine == "augmented" and ex.plan is None

    def test_distributed_engine_rejected(self):
        from tpu_jordan.driver import UsageError
        from tpu_jordan.serve import ExecutorCache

        with pytest.raises(UsageError, match="swapfree|unknown"):
            ExecutorCache(engine="swapfree").get(64, 2)

    def test_slow_build_does_not_stall_other_buckets(self, monkeypatch):
        """ISSUE 7 review hardening: the wait on the store's per-key
        build happens OUTSIDE the cache-wide lock — one bucket's slow
        (or retrying) compile must not stall this cache's dispatch and
        warmup of other, independent buckets."""
        import threading
        import time

        from tpu_jordan.serve import executors as ex_mod

        gate = threading.Event()
        building = threading.Event()
        real = ex_mod.BucketExecutor

        class Slow(real):
            def _build(self):
                if self.key.bucket_n == 64:
                    building.set()
                    gate.wait(30)      # a long in-flight compile
                return super()._build()

        monkeypatch.setattr(ex_mod, "BucketExecutor", Slow)
        cache = ex_mod.ExecutorCache(engine="inplace", dtype=jnp.float32)
        t = threading.Thread(target=lambda: cache.get(64, 1), daemon=True)
        t.start()
        try:
            assert building.wait(30)   # 64's build holds its key lock
            ex128 = cache.get(128, 1)  # ...and 128 must not wait on it
            assert ex128.key.bucket_n == 128
            assert t.is_alive()        # 64 was still building throughout
        finally:
            gate.set()
        t.join(60)
        assert cache.get(64, 1).key.bucket_n == 64


class TestServiceRoundTrip:
    @pytest.mark.smoke      # the serve round-trip case (smoke tier)
    def test_round_trip_bitmatches_direct_padded_solve(self, rng):
        a = rng.standard_normal((48, 48)).astype(np.float32)
        with JordanService(batch_cap=2, max_wait_ms=1.0) as svc:
            res = svc.invert(a, timeout=120)
        assert res.n == 48 and res.bucket_n == 64
        assert not res.singular
        direct, sing = _direct_padded(a, res.bucket_n)
        assert not sing
        assert (np.asarray(res.inverse) == direct[:48, :48]).all()
        assert res.rel_residual < 1e-4
        assert res.kappa > 0

    def test_result_metrics_match_unpadded_conventions(self, rng):
        """κ∞/rel_residual of a bucketed solve must be the UNPADDED
        matrix's numbers (row-masked batch_metrics): identity-pad rows
        abs-sum to exactly 1 and must not leak into small-norm κ."""
        from tpu_jordan.ops import condition_inf, residual_inf_norm

        a = (0.01 * rng.standard_normal((40, 40))).astype(np.float32)
        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            res = svc.invert(a, timeout=120)
        aj = jnp.asarray(a)
        xj = jnp.asarray(res.inverse)
        want_rel = float(residual_inf_norm(aj, xj)) / float(
            jnp.max(jnp.sum(jnp.abs(aj), axis=-1)))
        want_kappa = float(condition_inf(aj, xj))
        assert res.rel_residual == pytest.approx(want_rel, rel=1e-6)
        assert res.kappa == pytest.approx(want_kappa, rel=1e-6)

    def test_batch_cap_1_bitmatches_unbatched_engine(self, rng):
        """ISSUE 3 satellite: batch_cap=1 must bit-match the unbatched
        engine — a single-slot batch is exactly a direct solve."""
        a = rng.standard_normal((64, 64)).astype(np.float32)
        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            res = svc.invert(a, timeout=120)
        direct, _ = _direct_padded(a, 64)
        assert (np.asarray(res.inverse) == direct).all()

    def test_singular_request_flagged_not_poisoning(self, rng):
        """Per-element flags (solve_batch's machinery): one singular
        request resolves ITS result singular; batch-mates in the same
        launch stay healthy with passing residuals."""
        good = [rng.standard_normal((48, 48)).astype(np.float32)
                for _ in range(3)]
        bad = np.ones((48, 48), np.float32)          # rank 1, singular
        with JordanService(batch_cap=4, max_wait_ms=50.0,
                           autostart=False) as svc:
            futs = ([svc.submit(g) for g in good[:2]]
                    + [svc.submit(bad)] + [svc.submit(good[2])])
            svc.start()
            res = [f.result(120) for f in futs]
        assert [r.singular for r in res] == [False, False, True, False]
        assert all(r.rel_residual < 1e-4 for r in res if not r.singular)
        assert res[0].batch_occupancy == 4
        # The synchronous surface raises for the singular caller only.
        from tpu_jordan.driver import SingularMatrixError

        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            with pytest.raises(SingularMatrixError):
                svc.invert(bad, timeout=120)

    def test_submit_validates_shape(self):
        with JordanService(batch_cap=1) as svc:
            with pytest.raises(ValueError, match="square"):
                svc.submit(np.zeros((4, 5), np.float32))
            with pytest.raises(ValueError, match="square"):
                svc.submit(np.zeros((4,), np.float32))


class TestBackpressureAndShutdown:
    def test_full_queue_raises_overloaded_never_drops(self, rng):
        mats = _mats(rng, [32], copies=5)
        svc = JordanService(batch_cap=2, max_wait_ms=1.0, max_queue=4,
                            autostart=False)
        futs = [svc.submit(m) for m in mats[:4]]
        with pytest.raises(ServiceOverloadedError):
            svc.submit(mats[4])
        assert svc.stats()["totals"]["rejected"] == 1
        # Backpressure is not a drop: every ACCEPTED request completes
        # once the dispatcher runs.
        svc.start()
        res = [f.result(120) for f in futs]
        assert all(not r.singular for r in res)
        svc.close()

    def test_close_drains_queued_work(self, rng):
        svc = JordanService(batch_cap=4, max_wait_ms=10_000.0,
                            autostart=False)
        futs = [svc.submit(m) for m in _mats(rng, [24], copies=3)]
        # Never-started dispatcher + huge deadline: close() must still
        # complete everything (drain), not hang or cancel.
        svc.close(drain=True)
        assert all(f.done() for f in futs)
        assert all(not f.result().singular for f in futs)
        with pytest.raises(ServiceClosedError):
            svc.submit(np.eye(8, dtype=np.float32))

    def test_caller_cancel_drops_only_that_request(self, rng):
        # A caller-cancelled future must not crash the dispatcher or
        # affect batch-mates (the stdlib claim-at-dispatch protocol).
        svc = JordanService(batch_cap=4, max_wait_ms=5.0, autostart=False)
        futs = [svc.submit(m) for m in _mats(rng, [24], copies=3)]
        assert futs[1].cancel()
        svc.start()
        res = [futs[0].result(120), futs[2].result(120)]
        assert all(not r.singular for r in res)
        assert futs[1].cancelled()
        svc.close()
        assert svc.stats()["totals"]["batches"] >= 1

    def test_close_without_drain_fails_futures_explicitly(self, rng):
        svc = JordanService(batch_cap=4, max_wait_ms=10_000.0,
                            autostart=False)
        futs = [svc.submit(m) for m in _mats(rng, [24], copies=2)]
        svc.close(drain=False)
        for f in futs:
            with pytest.raises(ServiceClosedError):
                f.result(10)

    def test_close_is_idempotent_and_thread_safe(self, rng):
        """ISSUE 7 satellite: the fleet supervisor and a with-block
        __exit__ may race to close the same service — every racer must
        return cleanly (the first does the work, the rest no-op after
        it finishes), and queued work is still drained exactly once."""
        import threading

        svc = JordanService(batch_cap=4, max_wait_ms=10_000.0,
                            autostart=False)
        futs = [svc.submit(m) for m in _mats(rng, [24], copies=3)]
        errs = []

        def closer():
            try:
                svc.close(drain=True)
            except Exception as e:            # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=closer) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        svc.close()                           # and again, sequentially
        assert errs == []
        assert all(not f.result(1).singular for f in futs)

    def test_close_error_factory_types_queued_failures(self, rng):
        """ISSUE 7 satellite: ``close(drain=False, error=...)`` fails
        queued futures with the caller's typed error (the replica-kill
        path passes ReplicaKilledError so the fleet router re-queues)
        instead of the generic ServiceClosedError."""
        class WorkerGone(RuntimeError):
            pass

        svc = JordanService(batch_cap=4, max_wait_ms=10_000.0,
                            autostart=False)
        futs = [svc.submit(m) for m in _mats(rng, [24], copies=2)]
        svc.close(drain=False, error=lambda: WorkerGone("died"))
        for f in futs:
            with pytest.raises(WorkerGone):
                f.result(10)

    def test_bounded_join_abandons_wedged_dispatcher(self):
        """ISSUE 7 review hardening: killing a replica whose dispatcher
        is stuck mid-execute (the real production wedge) must not block
        the closer forever — ``close(join_timeout_s=...)`` abandons the
        daemon thread (counted) instead of joining it unbounded."""
        import threading
        import time

        from tpu_jordan.obs.metrics import REGISTRY
        from tpu_jordan.serve.batcher import MicroBatcher
        from tpu_jordan.serve.stats import ServeStats

        gate = threading.Event()

        class StuckExecutors:
            def breaker(self, bucket):
                return None

            def get_info(self, bucket, batch_cap, block_size, **kw):
                gate.wait(30)          # the hung device call
                raise RuntimeError("released")

        mb = MicroBatcher(StuckExecutors(), ServeStats(),
                          batch_cap=1, max_wait_ms=0.1)
        fut = mb.submit(np.eye(4, dtype=np.float32), 4, 64)
        deadline = time.monotonic() + 10
        while not mb.progress()[1] and time.monotonic() < deadline:
            time.sleep(0.005)
        assert mb.progress()[1]        # dispatcher is out executing
        abandoned = REGISTRY.counter(
            "tpu_jordan_serve_dispatcher_abandoned_total")
        before = abandoned.total()
        t0 = time.monotonic()
        mb.close(drain=False, join_timeout_s=0.2)
        assert time.monotonic() - t0 < 5      # returned, never froze
        assert abandoned.total() == before + 1
        # Unwedge: the abandoned daemon fans its batch and exits.
        gate.set()
        with pytest.raises(RuntimeError, match="released"):
            fut.result(30)
        if mb._thread is not None:
            mb._thread.join(30)


class TestSustainedThroughput:
    """The ISSUE 3 acceptance criterion, pinned end to end on the CPU
    backend: >= 64 mixed-size concurrent requests across >= 3 shape
    buckets; exactly one compile per (bucket, batch_cap); compile and
    plan-cache measurement counters at ZERO after warmup; mean batch
    occupancy > 1; every result bit-matching a direct solve of the same
    padded shape; backpressure typed, not dropping."""

    @pytest.mark.slow  # tier-1 budget: the smoke serve round-trip + executor-cache pins stay
    def test_acceptance_demo(self, rng, tmp_path):
        sizes = [24, 48, 96, 130, 200]      # buckets 64, 64, 128, 256, 256
        reqs = _mats(rng, sizes, copies=13)  # 65 requests
        assert len(reqs) >= 64
        buckets = {bucket_for(a.shape[0]) for a in reqs}
        assert len(buckets) >= 3

        plan_path = str(tmp_path / "plans.json")
        svc = JordanService(batch_cap=8, max_wait_ms=5.0,
                            plan_cache=plan_path, max_queue=128,
                            autostart=False)
        svc.warmup(shapes=sorted({a.shape[0] for a in reqs}))
        warm = svc.stats()
        assert warm["totals"]["compiles"] == len(buckets), \
            "exactly one compile per (bucket, batch_cap)"
        assert warm["measurements"] == 0

        # Stage everything before the dispatcher runs, so batching is
        # deterministic and occupancy has no race to win.
        futs = [(a, svc.submit(a)) for a in reqs]
        svc.start()
        results = [(a, f.result(300)) for a, f in futs]
        svc.close()
        stats = svc.stats()

        # Counter pins: ZERO compiles and ZERO plan-cache measurements
        # after warmup — the whole request stream ran on warm
        # executables and cached plans.
        assert stats["totals"]["compiles"] == len(buckets)
        assert stats["measurements"] == 0
        assert stats["totals"]["requests"] == len(reqs)
        assert stats["totals"]["rejected"] == 0
        assert stats["totals"]["singular"] == 0

        # Mean batch occupancy > 1 in every bucket (and well above 1
        # overall — the micro-batcher actually batched).
        occs = [b["mean_occupancy"] for b in stats["buckets"].values()]
        assert all(o > 1 for o in occs), stats["buckets"]
        total_batches = stats["totals"]["batches"]
        assert len(reqs) / total_batches > 1

        # Latency percentiles exist for every served bucket.
        for b in stats["buckets"].values():
            assert b["execute_ms"]["p50"] is not None
            assert b["queue_ms"]["p99"] is not None

        # Every result bit-matches a direct solve of the same padded
        # shape (the driver's own engine on the identity-padded input).
        direct_cache = {}
        for a, r in results:
            assert not r.singular
            key = r.bucket_n
            if key not in direct_cache:
                direct_cache[key] = {}
            direct, sing = _direct_padded(a, r.bucket_n)
            assert not sing
            assert (np.asarray(r.inverse)
                    == direct[:r.n, :r.n]).all(), \
                f"serve result diverged from direct solve (n={r.n})"

        # Backpressure: a bounded queue overflows with the typed error.
        svc2 = JordanService(batch_cap=2, max_queue=2, autostart=False)
        svc2.submit(reqs[0]); svc2.submit(reqs[1])
        with pytest.raises(ServiceOverloadedError):
            svc2.submit(reqs[2])
        svc2.close()


class TestServeDemoCLI:
    def test_serve_demo_exit_codes(self, tmp_path):
        """The --serve-demo mode folds into the 0/1/2 taxonomy
        (ISSUE 3 satellite): 0 = demo ran and reported, 1 = usage."""
        from tpu_jordan.__main__ import main

        # Usage errors, all pre-device: exit 1.  (--serve-demo
        # --workers W is no longer one of them: ISSUE 18 made it the
        # mesh-lane serving path — covered by tests/test_meshserve.py
        # and the dryrun mesh-serve legs.  A non-positive workers
        # value is still usage.)
        assert main(["96", "32", "--serve-demo", "--workers", "0",
                     "--quiet"]) == 1
        assert main(["96", "32", "--serve-demo", "--batch", "4",
                     "--quiet"]) == 1
        assert main(["96", "32", "--serve-demo", "--tune",
                     "--quiet"]) == 1
        assert main(["96", "32", "--serve-demo", "--engine", "swapfree",
                     "--quiet"]) == 1
        assert main(["96", "32", "--serve-demo", "--serve-requests", "0",
                     "--quiet"]) == 1
        assert main(["96", "32", "/no/such/file", "--serve-demo",
                     "--quiet"]) == 1

    def test_serve_demo_runs_and_reports(self, capsys, tmp_path):
        import json

        from tpu_jordan.__main__ import main

        path = str(tmp_path / "plans.json")
        rc = main(["96", "32", "--serve-demo", "--serve-requests", "9",
                   "--batch-cap", "3", "--plan-cache", path, "--quiet"])
        assert rc == 0
        line = capsys.readouterr().out.strip().splitlines()[-1]
        report = json.loads(line)
        assert report["metric"] == "serve_demo"
        assert report["requests"] == 9
        assert report["singular"] == 0
        assert report["compiles_on_request_path"] == 0
        assert report["plan_cache_measurements"] == 0


@pytest.mark.slow  # tier-1 budget: TestServeDemoCLI::test_serve_demo_runs_
# and_reports exercises serve_demo() end-to-end (report shape incl.) fast-run
def test_serve_demo_function_report_shape(tmp_path):
    """serve_demo() itself (the CLI engine): full report incl. nested
    stats, >= 2 buckets at n=96 (64 + 128), occupancy recorded."""
    report = serve_demo(n=96, block_size=32, requests=8, batch_cap=4,
                        max_wait_ms=20.0)
    assert report["buckets"] >= 2
    assert report["stats"]["totals"]["requests"] == 8
    assert set(report["mean_occupancy"]) == set(report["stats"]["buckets"])
    assert report["worst_rel_residual"] is not None
