"""Test config: 8 virtual CPU devices + fp64.

The TPU-native substitute for "mpirun -np 8 without a cluster" (SURVEY.md §4):
force the host platform to expose 8 fake devices so every sharded code path
runs in CI, and enable x64 so fp64 parity tests against the reference's
golden values are meaningful.

NOTE: this environment preloads jax at interpreter start (sitecustomize)
with JAX_PLATFORMS=axon, so env-var mutation alone is too late — the
platform must be forced through jax.config before any backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# f32 matmuls default to fast-low precision; accuracy assertions in the tests
# (residual checks) need true f32 accumulation.
jax.config.update("jax_default_matmul_precision", "highest")
# NOTE: the persistent XLA compilation cache
# (jax_compilation_cache_dir) was evaluated for the tier-1 budget and
# REJECTED: on this jaxlib build a warm cache intermittently returns
# corrupted executables for the ill-conditioned recovery-ladder
# programs (tests/test_numerics.py fails its residual gate with rel
# error ~1e+01 on cache hits, passes cold every time).  Wrong results
# from a cache are disqualifying for a numerics repo — keep the budget
# with `slow` demotions instead, never with this cache.

assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy engine-parity/scale cases excluded from the tier-1 "
        "fast run (ROADMAP.md's verify command deselects them under its "
        "timeout; full coverage stays in the unmarked nightly run — "
        "VERDICT r5 weak #6)")
    config.addinivalue_line(
        "markers",
        "smoke: the < 2 min fast-signal tier (`pytest -m smoke` / `make "
        "smoke`, documented next to the tier-1 line in ROADMAP.md): one "
        "engine-parity case per family + layout + entry + one serve "
        "round-trip.  Every smoke test must also be tier-1-eligible "
        "(not slow) — linted at collection (VERDICT r5 weak #6)")


def pytest_collection_modifyitems(config, items):
    # Lint (ISSUE 3 satellite): smoke is a SUBSET of tier-1 — a test
    # carrying both `smoke` and `slow` would vanish from the tier-1 run
    # while claiming fast-signal membership.  Fail collection loudly.
    bad = [item.nodeid for item in items
           if item.get_closest_marker("smoke")
           and item.get_closest_marker("slow")]
    if bad:
        raise pytest.UsageError(
            "smoke tests must be tier-1-eligible (not slow): "
            + ", ".join(bad))


@pytest.fixture(autouse=True, scope="session")
def _metric_namespace_lint():
    """ISSUE 4 satellite: after the whole suite ran (and therefore
    registered every metric any code path creates), every name in the
    process-wide registry must match ``^tpu_jordan_[a-z0-9_]+$`` so the
    Prometheus namespace stays consistent.  The registry also enforces
    this at registration time; the lint catches any bypass (e.g. a
    direct ``Metric`` construction) and documents the contract."""
    yield
    from tpu_jordan.obs.metrics import NAME_RE, REGISTRY

    bad = sorted(n for n in REGISTRY.names() if not NAME_RE.match(n))
    assert not bad, (f"metrics registered outside the tpu_jordan_ "
                     f"namespace: {bad}")


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Bound the full-suite process's live-executable footprint.

    The suite compiles hundreds of large SPMD programs (unrolled
    engines clone every super-step into the graph); with the round-5
    engines added, the single-process full run accumulated enough
    compiler state that XLA:CPU segfaulted inside backend_compile at
    ~290 compilations — reproducibly at the same spot, while every
    file passes in isolation.  Dropping the executable caches between
    modules keeps peak state at one module's worth; cross-module cache
    hits were never load-bearing (each module builds its own shapes).
    """
    yield
    jax.clear_caches()
