"""Fleet acceptance + supervision unit coverage (ISSUE 7): the seeded
3-replica ``replica_kill`` chaos run pinned against a fault-free replay
(every response bit-matches or carries a typed error, the replacement
replica performs zero compiles and zero measurements — validated by the
SAME checker ``make fleet-demo`` runs), router shedding (breaker-open
replicas receive no bucket traffic; fleet saturation is typed
backpressure), staged-kill re-queue, wedge detection, the per-slot
restart breaker against crash loops, and the warm-rolling-restart
zero-compile pin.  ISSUE 8 layers the journey-reconstruction pin onto
the same cached acceptance run: every request — every typed failure
and every rerouted success — reconstructible from the embedded
flight-recorder slice alone, with explanatory hops on every typed
terminal."""

import importlib.util
import pathlib
import threading
import time

import numpy as np
import pytest

from tpu_jordan.fleet import JordanFleet, ReplicaKilledError, fleet_demo
from tpu_jordan.fleet.replica import DEAD, READY
from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.resilience import FaultPlan, FaultSpec, activate
from tpu_jordan.resilience.policy import (CircuitOpenError,
                                          ResiliencePolicy, RetryPolicy)
from tpu_jordan.serve.batcher import ServiceOverloadedError
from tpu_jordan.serve.executors import bucket_for

_tool = (pathlib.Path(__file__).resolve().parent.parent / "tools"
         / "check_fleet.py")
_spec = importlib.util.spec_from_file_location("check_fleet", _tool)
check_fleet = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_fleet)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def _fleet(replicas=3, **kw):
    """A small, fast, manually-supervised fleet for unit tests: no plan
    cache, tiny buckets, deterministic supervision via
    ``fleet.supervisor.check()``."""
    kw.setdefault("batch_cap", 4)
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("autostart_supervisor", False)
    kw.setdefault("stable_after_s", 0.0)
    # Manual supervision means nobody will refill a dead pool: keep the
    # router's total-loss grace short so typed-raise tests stay fast.
    kw.setdefault("restart_grace_s", 0.2)
    return JordanFleet(replicas=replicas, **kw)


def _mats(count, n=16, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((n, n)).astype(np.float32)
            for _ in range(count)]


#: The tier-1 acceptance run's report, cached so the checker-rejection
#: test can doctor it instead of paying for a second fleet_demo (the
#: tier-1 budget discipline); falls back to a small run under -k.
_REPORT_CACHE: dict = {}


def _acceptance_report():
    if "report" not in _REPORT_CACHE:
        _REPORT_CACHE["report"] = fleet_demo(
            n=96, replicas=3, requests=60, batch_cap=4, kills=2, seed=0)
    return _REPORT_CACHE["report"]


class TestFleetAcceptance:
    """ISSUE 7 acceptance: 60 mixed requests across a 3-replica fleet
    under seeded ``replica_kill`` chaos — every response bit-matches
    the fault-free replay or carries a typed error, the supervisor
    warm-replaces every victim with ZERO compiles (shared executor
    store) and ZERO plan-cache measurements (read-only pre-tuned
    plans), and the ledger adds up.  Same checker as ``make
    fleet-demo``."""

    def _pin(self, report):
        assert report["silent_loss"] is False
        assert report["mismatches"] == []
        chaos = report["chaos"]
        assert chaos["kills_injected"] >= 1
        assert chaos["deaths"] >= chaos["kills_injected"]
        assert chaos["restarts"] >= 1
        # The warm-rolling-restart pin: replacement replicas found
        # every executable in the shared store and every plan in the
        # read-only pre-tuned cache.
        assert chaos["compiles_delta_after_warmup"] == 0
        assert report["plan_cache"]["measurements"] == 0
        assert report["plan_cache"]["read_only"] is True
        typed = sum(report["typed_errors"].values())
        assert report["matched_bitwise"] + typed == report["requests"]
        ledger = report["ledger"]
        assert ledger["outstanding"] == 0
        assert (ledger["resolved_ok"] + ledger["resolved_error"]
                == ledger["submitted"])
        # The deliberately singular fixtures kept their typed
        # per-element flags through kills and reroutes.
        assert report["singular_flagged"] >= 1
        # ---- journey reconstruction (ISSUE 8 acceptance) -----------
        # Every request of the chaos pass — every typed failure and
        # every rerouted success — is reconstructible from the
        # embedded flight-recorder slice ALONE.
        bb = report["blackbox"]
        assert bb["dropped"] == 0
        journeys = check_fleet._blackbox.journeys(bb["events"])
        assert len(journeys) == report["requests"]
        assert report["journey_ledger"]["gaps"] == []
        assert (report["journey_ledger"]["submitted"]
                == report["requests"])
        hops_by_rid = {rid: {e.get("event") for e in evs}
                       for rid, evs in journeys.items()}
        # Every typed failure's journey explains itself with its
        # shed/requeue/retry/... hops (no causal gaps)...
        explanatory = check_fleet._blackbox.EXPLANATORY_HOPS
        for rid, evs in journeys.items():
            terminal = evs[-1]
            assert terminal.get("event") == "result"
            if terminal.get("outcome") != "ok":
                assert hops_by_rid[rid] & explanatory, (
                    f"typed failure {rid} has no explanatory hop")
        # ...and the kills demonstrably re-routed work: at least one
        # journey carries a requeue hop that ended in a clean result
        # (the fault -> recovery chain, per request).
        requeued = [rid for rid, hops in hops_by_rid.items()
                    if "requeue" in hops]
        assert requeued, "no journey recorded a requeue hop"
        assert any(
            journeys[rid][-1].get("outcome") == "ok"
            for rid in requeued), "no rerouted request recovered"
        # The CI gate agrees (tools/check_fleet.py — same checker the
        # Makefile target runs); no violations, no silent loss.
        assert check_fleet.check(report) == ([], [])

    def test_seeded_replica_kill_vs_fault_free_replay(self):
        self._pin(_acceptance_report())

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [1, 2])
    def test_seeded_replica_kill_more_seeds(self, seed):
        self._pin(fleet_demo(n=96, replicas=3, requests=80,
                             batch_cap=4, kills=3, seed=seed))

    def test_fleet_demo_cli_usage_errors(self):
        from tpu_jordan.__main__ import main

        # Usage errors (pre-device, fast): exit 1.
        assert main(["96", "32", "--fleet-demo", "--workers", "8",
                     "--quiet"]) == 1
        assert main(["96", "32", "--fleet-demo", "--chaos-demo",
                     "--quiet"]) == 1
        assert main(["96", "32", "--fleet-demo", "--replicas", "1",
                     "--quiet"]) == 1
        assert main(["96", "32", "--fleet-demo", "--tune",
                     "--quiet"]) == 1

    def test_checker_rejects_doctored_reports(self):
        """check_fleet must fail a report claiming compiles, losing a
        request, or carrying a vacuous scaling floor — both directions
        of the gate are tested (the check_telemetry discipline)."""
        good = _acceptance_report()
        assert check_fleet.check(good) == ([], [])

        doctored = dict(good, chaos=dict(good["chaos"],
                                         compiles_delta_after_warmup=1))
        errs, silent = check_fleet.check(doctored)
        assert any("compiled" in e for e in errs) and not silent

        doctored = dict(good, ledger=dict(good["ledger"], outstanding=1))
        errs, silent = check_fleet.check(doctored)
        assert any("outstanding" in e for e in silent)

        doctored = dict(good, throughput=dict(good["throughput"],
                                              scaling_floor=0.1))
        errs, silent = check_fleet.check(doctored)
        assert any("vacuous" in e for e in errs)

        doctored = dict(good, chaos=dict(good["chaos"],
                                         kills_injected=0))
        errs, silent = check_fleet.check(doctored)
        assert any("vacuous" in e for e in errs)


@pytest.mark.smoke
def test_smoke_fleet_round_trip():
    """The < 1 min smoke tier's fleet leg: a 2-replica pool serves a
    small burst, survives a mid-stream kill with a warm replacement,
    and the ledger accounts for every request."""
    with _fleet(replicas=2, autostart_supervisor=True,
                stable_after_s=0.05) as fleet:
        fleet.warmup([16])
        compiles0 = REGISTRY.counter("tpu_jordan_compiles_total").total()
        mats = _mats(10)
        futs = [fleet.submit(a) for a in mats[:5]]
        # Kill the bucket's home replica — the slot holding the
        # queued traffic — mid-stream.
        home = bucket_for(16).bit_length() % 2
        fleet.slot_table()[home].replica.kill(reason="smoke")
        futs += [fleet.submit(a) for a in mats[5:]]
        results = [f.result(60) for f in futs]
        for a, r in zip(mats, results):
            np.testing.assert_allclose(
                np.asarray(r.inverse) @ a, np.eye(16), atol=5e-4)
        deadline = time.monotonic() + 10
        while (fleet.stats()["ready"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        stats = fleet.stats()
        assert stats["ready"] == 2, "supervisor never refilled the slot"
        assert stats["ledger"]["outstanding"] == 0
        assert stats["ledger"]["resolved_ok"] == 10
        # The journey-derived ledger (ISSUE 8) agrees with the
        # response-side one — same requests, zero gaps.
        assert stats["journey_ledger"]["ok"] == 10
        assert stats["journey_ledger"]["gaps"] == []
        # The replacement warmed from the shared store: zero compiles.
        assert REGISTRY.counter(
            "tpu_jordan_compiles_total").total() == compiles0


class TestRouterShedding:
    """Breaker-aware load shedding: an open per-bucket breaker means NO
    traffic for that bucket on that replica; nothing acceptable
    anywhere is typed backpressure — never a silent drop."""

    def _open_breaker(self, replica, bucket):
        br = replica.service.executors.breaker(bucket)
        for _ in range(replica.service.policy.breaker_failures):
            br.record_failure()
        assert not br.allow()

    def test_breaker_open_replica_gets_no_bucket_traffic(self):
        bucket = bucket_for(16)
        with _fleet(replicas=2) as fleet:
            fleet.warmup([16])
            # Open the breaker on the bucket's HOME replica — the one
            # affinity would otherwise send every request to.
            home = bucket.bit_length() % fleet.slots
            victim = fleet.slot_table()[home].replica
            self._open_breaker(victim, bucket)
            before = victim.service.stats()["totals"]["requests"]
            futs = [fleet.submit(a) for a in _mats(8)]
            assert all(not f.result(60).singular for f in futs)
            # Every request was shed away from the open breaker.
            assert (victim.service.stats()["totals"]["requests"]
                    == before)

    def test_every_breaker_open_is_typed_circuit_open(self):
        bucket = bucket_for(16)
        with _fleet(replicas=2) as fleet:
            fleet.warmup([16])
            for slot in fleet.slot_table():
                self._open_breaker(slot.replica, bucket)
            with pytest.raises(CircuitOpenError):
                fleet.submit(_mats(1)[0])
            # A different bucket's traffic is unaffected (per-bucket
            # isolation fleet-wide; n=96 rounds to the 128 bucket,
            # clear of the opened 64 bucket).
            assert not fleet.submit(
                _mats(1, n=96)[0]).result(60).singular

    def test_saturation_is_typed_backpressure(self):
        with _fleet(replicas=2, max_queue=2, batch_cap=1,
                    autostart=False) as fleet:
            fleet.warmup([16])
            mats = _mats(10)
            accepted = 0
            with pytest.raises(ServiceOverloadedError):
                for a in mats:
                    fleet.submit(a)
                    accepted += 1
            assert accepted == 4          # 2 replicas x max_queue=2
            fleet.start()                 # drain the accepted ones

    def test_no_live_replica_is_typed(self):
        with _fleet(replicas=2) as fleet:
            fleet.warmup([16])
            for slot in fleet.slot_table():
                slot.replica.kill(reason="test")
            with pytest.raises(ServiceOverloadedError):
                fleet.submit(_mats(1)[0])


class TestKillRequeue:
    """A killed replica's queued requests are re-queued through the
    retry budget — never lost, never silent."""

    def test_staged_kill_requeues_queued_work(self):
        reroutes = REGISTRY.counter("tpu_jordan_fleet_reroutes_total")
        before = reroutes.total()
        with _fleet(replicas=3, autostart=False,
                    max_queue=64) as fleet:
            fleet.warmup([16])
            futs = [fleet.submit(a) for a in _mats(12)]
            # Kill whichever replica holds the queued bucket traffic.
            victim = max(fleet.slot_table(),
                         key=lambda s: s.replica.queued).replica
            assert victim.queued > 0
            victim.kill(reason="test")
            fleet.start()
            assert all(not f.result(60).singular for f in futs)
            assert fleet.stats()["ledger"]["resolved_ok"] == 12
        assert reroutes.total() > before

    def test_total_loss_waits_for_warm_replacement(self):
        """EVERY replica killed while work is queued (the worst
        rolling-restart instant): the router's bounded grace absorbs
        the re-queued work into the supervisor's warm replacements —
        nothing typed-fails, nothing is lost."""
        with _fleet(replicas=2, autostart=False,
                    autostart_supervisor=True, stable_after_s=0.05,
                    restart_grace_s=10.0, max_queue=64) as fleet:
            fleet.warmup([16])
            futs = [fleet.submit(a) for a in _mats(8)]
            for slot in fleet.slot_table():
                slot.replica.kill(reason="test")
            fleet.start()
            assert all(not f.result(60).singular for f in futs)
            stats = fleet.stats()
            assert stats["ledger"]["resolved_ok"] == 8
            assert stats["ledger"]["outstanding"] == 0

    def test_exhausted_fleet_surfaces_typed_death(self):
        """Queued work on the LAST live replica when it dies (and the
        pool is closing, so no re-dispatch target appears): the caller
        gets the typed ReplicaKilledError, not a hang or a drop — and
        the request's journey explains the terminal (ISSUE 8: a typed
        failure with no explanatory hop is a causal gap)."""
        with _fleet(replicas=1, autostart=False) as fleet:
            fleet.warmup([16])
            fut = fleet.submit(_mats(1)[0])
            fleet.closing = True      # block re-dispatch (shutdown race)
            fleet.slot_table()[0].replica.kill(reason="test")
            with pytest.raises(ReplicaKilledError):
                fut.result(10)
            (ctx,) = fleet.journey.contexts()
            assert ctx.outcome() == ("error", "ReplicaKilledError")
            reject = next(e for e in ctx.events()
                          if e["event"] == "reject")
            assert reject["reason"] == "closing"


class _StubBatcher:
    def __init__(self):
        self.ticks = 0
        self.busy = False

    def progress(self):
        return self.ticks, self.busy


class _StubService:
    """Just enough service for a bare Replica: the dispatcher progress
    signal and a ``close()`` accepting the kill path's kwargs."""

    def __init__(self):
        self._batcher = _StubBatcher()
        self.closed = []

    def close(self, drain=True, error=None, join_timeout_s=None):
        self.closed.append((drain, join_timeout_s))


class TestHeartbeatLiveness:
    """Review hardening: the heartbeat stamp proves DISPATCHER
    liveness, not the beat thread's own.  A dispatcher stuck
    mid-execute (busy with a frozen tick count) must stop the stamp —
    otherwise wedge detection only ever catches the wedge() test
    fixture, never a real hang."""

    def _mk(self):
        from tpu_jordan.fleet.replica import Replica

        svc = _StubService()
        return svc, Replica(0, 1, svc, heartbeat_interval_s=0.01)

    @staticmethod
    def _stamped_after(replica, t, timeout=5.0):
        deadline = time.monotonic() + timeout
        while replica.last_beat <= t and time.monotonic() < deadline:
            time.sleep(0.005)
        return replica.last_beat > t

    def test_idle_dispatcher_keeps_stamping(self):
        svc, r = self._mk()
        try:
            # Idle (parked in the condition wait) is responsive.
            assert self._stamped_after(r, r.started_at)
        finally:
            r.kill(reason="test")

    def test_stuck_dispatcher_goes_stale_then_recovers(self):
        svc, r = self._mk()
        try:
            assert self._stamped_after(r, r.started_at)
            svc._batcher.busy = True   # mid-execute, ticks frozen: the
            time.sleep(0.15)           # beat loop must stop stamping
            stale_from = r.last_beat
            time.sleep(0.15)
            assert r.last_beat == stale_from
            # The batch completes (ticks advance): stamps resume.
            svc._batcher.ticks += 1
            svc._batcher.busy = False
            assert self._stamped_after(r, stale_from)
        finally:
            r.kill(reason="test")

    def test_kill_joins_bounded(self):
        """The kill path passes its bounded join through to the
        service close — abandoning a wedged dispatcher beats freezing
        the supervising thread on an unbounded join."""
        svc, r = self._mk()
        assert r.kill(reason="test")
        assert svc.closed == [(False, r._kill_join_timeout_s)]
        assert r._kill_join_timeout_s > 0


class TestSupervisor:
    """Wedge detection, warm replacement, and the per-slot restart
    breaker — driven inline (``supervisor.check()``) on a fake clock
    (the obs fake-clock discipline)."""

    def test_wedge_detected_killed_and_replaced(self):
        clock = FakeClock()
        with _fleet(replicas=2, clock=clock,
                    liveness_deadline_s=1.0) as fleet:
            fleet.warmup([16])
            victim = fleet.slot_table()[0].replica
            victim.wedge()
            clock.advance(1.5)
            # The healthy replica's heartbeat must catch up to the
            # advanced fake clock before the check, or it would be
            # declared wedged too (its beat loop runs on wall time).
            deadline = time.monotonic() + 5
            other = fleet.slot_table()[1].replica
            while (other.last_beat < clock.t
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            fleet.supervisor.check()
            assert victim.state == DEAD
            stats = fleet.stats()
            assert stats["ready"] == 2
            assert stats["slots"][0]["lineage"] == ["r0g1", "r0g2"]
            # The wedged replica's death is labeled.
            assert REGISTRY.counter(
                "tpu_jordan_fleet_replica_deaths_total").value(
                    reason="wedged", replica="0") >= 1

    def test_restart_breaker_stops_crash_loop_then_half_open(self):
        clock = FakeClock()
        # liveness_deadline_s huge: advancing the fake clock must not
        # make HEALTHY replicas (whose wall-time heartbeat threads lag
        # the jump) look wedged.
        with _fleet(replicas=2, clock=clock, restart_failures=2,
                    restart_cooldown_s=10.0, liveness_deadline_s=1e6,
                    stable_after_s=1.0) as fleet:
            fleet.warmup([16])
            slot = fleet.slot_table()[0]
            # Two deaths without ever reaching stable_after_s of
            # uptime: the slot's restart breaker opens.
            slot.replica.kill(reason="test")
            fleet.supervisor.check()          # restart #1 (breaker 1/2)
            assert slot.replica.state == READY
            slot.replica.kill(reason="test")  # failure 2/2 -> open
            fleet.supervisor.check()
            assert slot.replica.state == DEAD  # degraded, not restarted
            assert fleet.stats()["ready"] == 1
            assert slot.breaker.state == "open"
            # Requests still flow through the surviving replica.
            assert not fleet.submit(_mats(1)[0]).result(60).singular
            # Cooldown elapses: the half-open probe restart goes in...
            clock.advance(10.5)
            fleet.supervisor.check()
            assert slot.replica.state == READY
            # ...and surviving the stability window closes the breaker.
            clock.advance(1.5)
            fleet.supervisor.check()
            assert slot.breaker.state == "closed"

    @pytest.mark.slow      # tier-1 siblings: the acceptance demo's
    # compiles_delta_after_warmup == 0 pin and the smoke round-trip's
    # compile-counter pin cover the warm-replacement contract.
    def test_warm_replacement_compiles_nothing_and_serves(self):
        with _fleet(replicas=2) as fleet:
            fleet.warmup([16, 32])
            compiles = REGISTRY.counter("tpu_jordan_compiles_total")
            before = compiles.total()
            fleet.slot_table()[1].replica.kill(reason="test")
            fleet.supervisor.check()
            replacement = fleet.slot_table()[1].replica
            assert replacement.generation == 2
            assert compiles.total() == before
            # The replacement serves both warmed buckets immediately.
            for n in (16, 32):
                assert not replacement.submit(
                    _mats(1, n=n)[0]).result(60).singular

    def test_injected_replica_kill_fires_on_dispatch(self):
        """The seeded replica_kill fault point crashes the replica the
        k-th routed request lands on; the router re-dispatches that
        request elsewhere — the caller never sees the crash."""
        deaths = REGISTRY.counter("tpu_jordan_fleet_replica_deaths_total")
        before = deaths.value(reason="injected", replica="0") + \
            deaths.value(reason="injected", replica="1")
        plan = FaultPlan([FaultSpec("replica_kill", (3,), "permanent")])
        with _fleet(replicas=2) as fleet:
            fleet.warmup([16])
            with activate(plan):
                futs = [fleet.submit(a) for a in _mats(6)]
                assert all(not f.result(60).singular for f in futs)
            assert plan.injected_total == 1
        after = deaths.value(reason="injected", replica="0") + \
            deaths.value(reason="injected", replica="1")
        assert after == before + 1


class TestFleetLifecycle:
    def test_fleet_close_is_idempotent_and_concurrent(self):
        fleet = _fleet(replicas=2)
        fleet.warmup([16])
        futs = [fleet.submit(a) for a in _mats(6)]
        errs = []

        def closer():
            try:
                fleet.close()
            except Exception as e:            # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=closer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fleet.close()
        assert errs == []
        # drain=True close completed the queued work first.
        assert all(not f.result(1).singular for f in futs)
        assert all(s.replica.state == "closed"
                   for s in fleet.slot_table())

    def test_closed_fleet_rejects_typed(self):
        fleet = _fleet(replicas=2)
        fleet.warmup([16])
        fleet.close()
        with pytest.raises(ServiceOverloadedError):
            fleet.submit(_mats(1)[0])

    def test_per_replica_metric_labels(self):
        """Fleet-level Prometheus aggregation: each replica's serve
        series carries its slot label, so one scrape shows the pool
        with per-replica breakdown."""
        with _fleet(replicas=2) as fleet:
            fleet.warmup([16])
            futs = [fleet.submit(a) for a in _mats(6)]
            [f.result(60) for f in futs]
            c = REGISTRY.counter("tpu_jordan_serve_requests_total")
            bucket = str(bucket_for(16))
            per_replica = [c.value(bucket=bucket, replica="0"),
                           c.value(bucket=bucket, replica="1")]
        assert sum(per_replica) >= 6
        # Affinity homes one bucket on one replica; shedding/overflow
        # may spill, but the labeled series exist per replica.
        assert any(v > 0 for v in per_replica)
