"""ISSUE 11 acceptance: tpu_jordan/linalg/ — solve_system, lstsq, the
pivot-free SPD fast path, complex dtypes, and the serve/tuning/numerics
wiring that makes them products rather than demos.

The pins, in roughly the acceptance-criteria order:
  * solve_system via engine="auto" never materializes A⁻¹ — the
    compiled solve executable's OWN cost_analysis FLOPs are strictly
    below the invert executable's at the same n;
  * bit-stable under the plan cache — a warm serve path performs ZERO
    compiles and ZERO measurements (counter-pinned) across both
    workloads;
  * the SPD fast path bit-matches the pivoting engine on the seeded
    diagonally dominant SPD fixture (same probe arithmetic, same
    sweeps);
  * complex64 solve parity vs jnp.linalg.solve within eps·n·κ∞;
  * old invert plan-cache keys stay byte-identical (test_tuning.py's
    TestPlanKey::test_workload_segment carries the key-level pin).
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_jordan.linalg import (block_jordan_solve, lstsq,
                               solve_batch_metrics, solve_system)
from tpu_jordan.ops import generate

RNG = np.random.default_rng(11)


def _rel_backward(a, x, b):
    a, x, b = (np.asarray(v) for v in (a, x, b))
    r = a @ x - b
    na = np.abs(a).sum(axis=-1).max()
    nx = np.abs(x).sum(axis=-1).max()
    nb = np.abs(b).sum(axis=-1).max()
    return np.abs(r).sum(axis=-1).max() / (na * nx + nb)


def _rand(shape, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    if np.dtype(dtype).kind == "c":
        return (rng.standard_normal(shape)
                + 1j * rng.standard_normal(shape)).astype(dtype)
    return rng.standard_normal(shape).astype(dtype)


class TestSolveEngine:
    @pytest.mark.smoke
    def test_round_trip_vs_inverse_matmul(self):
        """The tentpole identity: GJ on [A | B] returns the same X the
        explicit route inverse @ B does, compared at fp64 against the
        true solution — no inverse formed."""
        a = _rand((48, 48), seed=1)
        b = _rand((48, 3), seed=2)
        x, sing = block_jordan_solve(jnp.asarray(a), jnp.asarray(b),
                                     block_size=16)
        assert not bool(sing)
        ref = np.linalg.solve(a.astype(np.float64),
                              b.astype(np.float64))
        kappa = (np.abs(a).sum(1).max()
                 * np.abs(np.linalg.inv(a.astype(np.float64))).sum(1
                                                                   ).max())
        tol = np.finfo(np.float32).eps * 48 * kappa
        assert np.abs(np.asarray(x) - ref).max() <= 3 * tol * \
            np.abs(ref).max()
        # and against the explicit-inverse route, at fp64-grade agreement
        via_inv = np.linalg.inv(a.astype(np.float64)) @ b
        assert np.abs(np.asarray(x) - via_inv).max() <= 3 * tol * \
            np.abs(via_inv).max()
        assert _rel_backward(a, x, b) < 1e-5

    def test_ragged_and_wide_rhs(self):
        a = _rand((20, 20), seed=3)
        b = _rand((20, 5), seed=4)
        x, sing = block_jordan_solve(jnp.asarray(a), jnp.asarray(b),
                                     block_size=8)
        assert not bool(sing) and x.shape == (20, 5)
        assert _rel_backward(a, x, b) < 1e-5

    def test_spd_bitmatches_pivoting_on_seeded_spd(self):
        """The acceptance pin: on the diagonally dominant KMS SPD
        fixture the condition-based probe picks the diagonal block at
        every superstep, so the pivot-free path follows IDENTICAL
        arithmetic — bit-equal X, not merely close."""
        g = generate("kms", (48, 48), jnp.float32)
        b = jnp.asarray(_rand((48, 4), seed=5))
        xg, sg = block_jordan_solve(g, b, block_size=16, spd=False)
        xs, ss = block_jordan_solve(g, b, block_size=16, spd=True)
        assert not bool(sg) and not bool(ss)
        assert np.array_equal(np.asarray(xg), np.asarray(xs))

    def test_spd_correct_on_random_spd(self):
        """A generic (not diagonally dominant) SPD matrix: the
        pivot-free path must still be CORRECT (PD principal blocks are
        always invertible), even where the probe might pivot."""
        s = _rand((40, 40), seed=6).astype(np.float64)
        a = (s @ s.T + 40 * np.eye(40)).astype(np.float32)
        b = _rand((40, 2), seed=7)
        x, sing = block_jordan_solve(jnp.asarray(a), jnp.asarray(b),
                                     block_size=8, spd=True)
        assert not bool(sing)
        assert _rel_backward(a, x, b) < 1e-5

    def test_complex64_parity_vs_jnp_linalg_solve(self):
        """Acceptance: complex64 solve parity vs jnp.linalg.solve
        within eps·n·κ∞."""
        n = 40
        a = _rand((n, n), np.complex64, seed=8)
        b = _rand((n, 2), np.complex64, seed=9)
        x, sing = block_jordan_solve(jnp.asarray(a), jnp.asarray(b),
                                     block_size=8)
        assert not bool(sing)
        ref = np.asarray(jnp.linalg.solve(jnp.asarray(a),
                                          jnp.asarray(b)))
        kappa = (np.abs(a).sum(1).max()
                 * np.abs(np.linalg.inv(a.astype(np.complex128))
                          ).sum(1).max())
        tol = np.finfo(np.float32).eps * n * kappa
        denom = np.abs(ref).max()
        assert np.abs(np.asarray(x) - ref).max() / denom <= 3 * tol
        # parity against the fp128-free ground truth too
        truth = np.linalg.solve(a.astype(np.complex128),
                                b.astype(np.complex128))
        assert np.abs(np.asarray(x) - truth).max() / denom <= 3 * tol

    def test_singular_flagged(self):
        a = np.ones((16, 16), np.float32)          # rank 1
        b = _rand((16, 1), seed=10)
        _, sing = block_jordan_solve(jnp.asarray(a), jnp.asarray(b),
                                     block_size=8)
        assert bool(sing)

    def test_bf16_storage_upcasts_and_rounds_back(self):
        a = _rand((24, 24), seed=11)
        b = _rand((24, 2), seed=12)
        x, sing = block_jordan_solve(jnp.asarray(a, jnp.bfloat16),
                                     jnp.asarray(b, jnp.bfloat16),
                                     block_size=8)
        assert x.dtype == jnp.bfloat16 and not bool(sing)

    def test_batch_metrics_pad_mask(self):
        """Identity-padded filler rows must not cap the norms, and an
        all-filler element reports zeros, never NaN."""
        a = np.stack([np.eye(8, dtype=np.float32)] * 2)
        a[0, :4, :4] = _rand((4, 4), seed=13) * 100
        x = np.zeros((2, 8, 2), np.float32)
        b = np.zeros((2, 8, 2), np.float32)
        x[0, :4] = _rand((4, 2), seed=14)
        b[0, :4] = np.asarray(a[0, :4, :4] @ x[0, :4])
        met = solve_batch_metrics(jnp.asarray(a), jnp.asarray(x),
                                  jnp.asarray(b),
                                  n_real=jnp.asarray([4, 0]))
        assert float(met["rel_residual"][0]) < 1e-5
        assert float(met["norm_a"][0]) > 10      # unmasked rows
        assert float(met["rel_residual"][1]) == 0.0
        assert math.isfinite(float(met["kappa_est"][1]))


class TestSolveSystemAPI:
    def test_auto_resolves_solve_engine_and_reports(self):
        a = _rand((48, 48), seed=20)
        b = _rand((48, 2), seed=21)
        res = solve_system(a, b, block_size=16)
        assert res.engine == "solve_aug" and res.workload == "solve"
        assert res.x.shape == (48, 2) and not res.singular
        assert res.rel_residual < 1e-5
        assert res.kappa_est is not None and res.kappa_est > 1
        assert res.plan is not None and res.plan.source == "cost_model"

    def test_1d_rhs_squeezes(self):
        a = _rand((32, 32), seed=22)
        b = _rand((32,), seed=23)
        res = solve_system(a, b, block_size=8)
        assert res.x.shape == (32,) and res.k == 1

    def test_never_materializes_inverse_flops_pin(self):
        """THE acceptance pin: the compiled solve executable's own
        cost_analysis FLOPs are strictly below the invert executable's
        at the same n — X = A⁻¹B never pays for A⁻¹."""
        from tpu_jordan.driver import single_device_invert
        from tpu_jordan.obs import hwcost

        n, m, k = 256, 64, 4
        a = jnp.zeros((n, n), jnp.float32)
        b = jnp.zeros((n, k), jnp.float32)
        cs = jax.jit(lambda aa, bb: block_jordan_solve(
            aa, bb, block_size=m)).lower(a, b).compile()
        ci = jax.jit(
            single_device_invert(n, m, "inplace", 0),
            static_argnames=("block_size", "refine", "precision"),
        ).lower(a, block_size=m, refine=0,
                precision=jax.lax.Precision.HIGHEST).compile()
        fs = hwcost.executable_cost(cs).flops
        fi = hwcost.executable_cost(ci).flops
        if fs is None or fi is None:
            pytest.skip("backend exposes no cost_analysis")
        assert fs < fi, (fs, fi)
        # and the analytic convention agrees on the direction
        assert hwcost.baseline_workload_flops(n, "solve", k=k) < \
            hwcost.baseline_invert_flops(n)

    def test_flag_contract(self):
        from tpu_jordan.driver import UsageError

        a = _rand((16, 16), seed=24)
        b = _rand((16, 1), seed=25)
        with pytest.raises(UsageError, match="solve engine"):
            solve_system(a, b, engine="inplace")
        with pytest.raises(UsageError, match="assume"):
            solve_system(a, b, engine="solve_spd")   # no spd promise
        with pytest.raises(UsageError, match="auto"):
            solve_system(a, b, engine="solve_aug", tune=True)
        with pytest.raises(UsageError, match="probe"):
            # trace is a PIVOTING-path mode since ISSUE 12 (the 1b
            # remainder); the pivot-free fast path stays a typed
            # refusal — no probe to trace.
            solve_system(a @ a.T + 16 * np.eye(16, dtype=np.float32),
                         b, assume="spd", numerics="trace")
        with pytest.raises(UsageError, match="square"):
            solve_system(_rand((8, 4), seed=26), b)
        # a zero-column RHS is a caller bug, never a vacuous success
        with pytest.raises(UsageError, match="k>=1"):
            solve_system(a, np.zeros((16, 0), np.float32))

    def test_singular_raises_and_check_false_reports(self):
        from tpu_jordan.driver import SingularMatrixError

        a = np.ones((16, 16), np.float32)
        b = _rand((16, 1), seed=27)
        with pytest.raises(SingularMatrixError):
            solve_system(a, b, block_size=8)
        res = solve_system(a, b, block_size=8, check=False)
        assert res.singular and res.x is None

    def test_plan_cache_workload_key_and_warm_hit(self, tmp_path):
        """engine='auto' writes the |wsolve key; the second solve at
        the same point is a cache hit (zero fresh selections)."""
        import json

        from tpu_jordan.obs.metrics import REGISTRY

        path = str(tmp_path / "plans.json")
        a = _rand((32, 32), seed=28)
        b = _rand((32, 1), seed=29)
        solve_system(a, b, block_size=8, plan_cache=path)
        doc = json.loads(open(path).read())
        keys = list(doc["plans"])
        assert len(keys) == 1 and keys[0].endswith("|wsolve")
        hits0 = REGISTRY.counter(
            "tpu_jordan_plan_cache_hits_total").total()
        solve_system(a, b, block_size=8, plan_cache=path)
        assert REGISTRY.counter(
            "tpu_jordan_plan_cache_hits_total").total() == hits0 + 1

    def test_numerics_summary_workload_tagged(self):
        a = _rand((32, 32), seed=30)
        b = _rand((32, 1), seed=31)
        res = solve_system(a, b, block_size=8, numerics="summary")
        assert res.numerics is not None
        assert res.numerics.workload == "solve"
        assert res.numerics.mode == "summary"
        assert res.numerics.to_json()["workload"] == "solve"

    def test_gate_passes_clean_no_rungs(self):
        from tpu_jordan.resilience import ResiliencePolicy

        a = _rand((32, 32), seed=32)
        b = _rand((32, 1), seed=33)
        res = solve_system(a, b, block_size=8,
                           policy=ResiliencePolicy())
        assert res.recovery == ()

    def test_bf16_gate_failure_recovers_by_refine(self):
        """The solve ladder's first rung: a bf16-rounded X fails the
        fp32-SLO gate; one refinement pass through the same compiled
        executable recovers (the numerics-demo recipe)."""
        from tpu_jordan.obs.numerics import ill_conditioned
        from tpu_jordan.resilience import ResiliencePolicy

        a = ill_conditioned(16, 4.5, 7)
        b = np.random.default_rng(8).standard_normal((16, 2))
        res = solve_system(a, b, block_size=8, dtype=jnp.bfloat16,
                           policy=ResiliencePolicy(gate_dtype="float32"))
        assert res.recovery and res.recovery[-1]["passed"]
        assert res.recovery[0]["rung"] == "refine"

    def test_broken_spd_promise_recovers_by_repivot(self):
        """assume='spd' on a non-SPD matrix with a near-singular
        leading diagonal block: the pivot-free sweep's growth fails the
        backward-error gate and the ladder's repivot rung (the
        registered pivoting fallback) recovers — a broken promise is
        never a silently wrong X."""
        from tpu_jordan.resilience import ResiliencePolicy

        s = _rand((32, 32), seed=34)
        a = (s + s.T) / 2
        a[:8, :8] = np.eye(8, dtype=np.float32) * 1e-6
        b = _rand((32, 2), seed=35)
        res = solve_system(a, b, block_size=8, assume="spd",
                           policy=ResiliencePolicy())
        assert res.recovery and res.recovery[-1]["passed"]
        assert res.recovery[-1]["rung"] == "repivot"
        assert res.rel_residual < 1e-5


class TestSolveTrace:
    """ISSUE 12 satellite (ROADMAP 1b remainder): the instrumented
    per-superstep trace twin for the solve engine — stats ride the
    SAME executable, X bits untouched, pivot sequence pinned equal to
    the invert engine's on a shared fixture."""

    def test_trace_bits_untouched_and_report_shape(self):
        a = _rand((48, 48), seed=71)
        b = _rand((48, 3), seed=72)
        traced = solve_system(a, b, block_size=8, numerics="trace")
        plain = solve_system(a, b, block_size=8)
        assert (np.asarray(traced.x) == np.asarray(plain.x)).all()
        rep = traced.numerics
        assert rep.mode == "trace" and rep.workload == "solve"
        assert rep.trace_engine == traced.engine == "solve_aug"
        Nr = 48 // 8
        assert len(rep.pivot_block) == Nr
        assert len(rep.pivot_inv_norm) == Nr
        assert len(rep.cand_norm_max) == Nr
        assert len(rep.singular_candidates) == Nr
        assert len(rep.growth) == Nr
        assert all(s == 0 for s in rep.singular_candidates)
        doc = rep.to_json()
        assert doc["modeled_fields"] == ["residual_est"]
        assert doc["workload"] == "solve"

    def test_pivot_sequence_matches_invert_engine(self):
        """The parity pin: the [A | B] elimination probes the same
        candidate blocks with the same criterion as the in-place
        invert engine — identical pivot choices on a shared fixture."""
        import os
        import tempfile

        from tpu_jordan.driver import solve

        n, m = 48, 8
        a = _rand((n, n), seed=73)
        b = _rand((n, 2), seed=74)
        traced = solve_system(a, b, block_size=m, numerics="trace")
        fd, path = tempfile.mkstemp(suffix=".mat")
        os.close(fd)
        try:
            from tpu_jordan.io import write_matrix_file

            write_matrix_file(path, a)
            inv_res = solve(n, m, file=path, numerics="trace")
        finally:
            os.unlink(path)
        assert traced.numerics.pivot_block == \
            inv_res.numerics.pivot_block

    def test_trace_spikes_precede_recovery(self):
        """The ISSUE 10 causality discipline holds on the traced solve
        path: an ill-conditioned bf16 solve records its numerics_spike
        BEFORE the gate/ladder events."""
        from tpu_jordan.obs.numerics import ill_conditioned
        from tpu_jordan.obs.recorder import RECORDER
        from tpu_jordan.resilience import ResiliencePolicy

        a = ill_conditioned(16, 4.5, seed=7)
        b = _rand((16, 2), seed=75)
        mark = RECORDER.total
        res = solve_system(a, b, block_size=8, dtype=jnp.bfloat16,
                           policy=ResiliencePolicy(gate_dtype="float32"),
                           numerics="trace")
        assert res.numerics.mode == "trace"
        assert res.recovery          # the gate fired and recovered
        events = RECORDER.since(mark)
        spikes = [e["seq"] for e in events
                  if e["kind"] == "numerics_spike"]
        rungs = [e["seq"] for e in events
                 if e["kind"] == "recovery_rung"]
        assert spikes and rungs
        assert min(spikes) < min(rungs)


class TestLstsq:
    def test_vs_numpy_lstsq(self):
        a = _rand((64, 24), seed=40)
        b = _rand((64,), seed=41)
        res = lstsq(a, b)
        assert res.engine == "solve_spd"          # gram is SPD
        ref, *_ = np.linalg.lstsq(a.astype(np.float64),
                                  b.astype(np.float64), rcond=None)
        assert np.abs(np.asarray(res.x) - ref).max() < 1e-3
        assert not res.rank_deficient
        assert res.kappa_est is not None

    def test_rank_deficient_surfaced(self):
        a = _rand((32, 8), seed=42)
        a[:, 4:] = a[:, :4]                       # rank 4 of 8
        res = lstsq(a, _rand((32,), seed=43))
        assert res.rank_deficient and res.x is None

    def test_complex_lstsq(self):
        a = _rand((48, 12), np.complex64, seed=44)
        b = _rand((48, 2), np.complex64, seed=45)
        res = lstsq(a, b)
        ref, *_ = np.linalg.lstsq(a.astype(np.complex128),
                                  b.astype(np.complex128), rcond=None)
        assert np.abs(np.asarray(res.x) - ref).max() < 1e-2
        assert not res.rank_deficient

    def test_underdetermined_typed(self):
        from tpu_jordan.driver import UsageError

        with pytest.raises(UsageError, match="rows >= n"):
            lstsq(_rand((8, 16), seed=46), _rand((8,), seed=47))


class TestServeSolve:
    @pytest.mark.smoke
    def test_serve_solve_round_trip_warm_zero_compiles(self):
        """The serve acceptance: solve requests ride their own lanes
        next to invert requests; after a warmup covering both, the
        request path performs ZERO compiles and ZERO plan-cache
        measurements (counter-pinned), and every solve result matches
        the explicit inverse @ B route at fp64 tolerance."""
        from tpu_jordan.obs.metrics import REGISTRY
        from tpu_jordan.serve import JordanService

        with JordanService(batch_cap=4, max_wait_ms=1.0) as svc:
            svc.warmup(shapes=[48], solve_shapes=[(48, 3)])
            c0 = REGISTRY.counter("tpu_jordan_compiles_total").total()
            mats = [( _rand((48, 48), seed=50 + i),
                      _rand((48, 3), seed=70 + i)) for i in range(5)]
            futs = [svc.submit(a, b) for a, b in mats]
            inv_fut = svc.submit(mats[0][0])
            results = [f.result(120) for f in futs]
            inv_res = inv_fut.result(120)
            stats = svc.stats()
            c1 = REGISTRY.counter("tpu_jordan_compiles_total").total()
        assert c1 == c0, "warm serve path recompiled"
        assert stats["measurements"] == 0
        for (a, b), r in zip(mats, results):
            assert r.workload == "solve" and r.inverse is None
            assert r.solution.shape == (48, 3)
            assert not r.singular and r.rel_residual < 1e-5
            via_inv = (np.linalg.inv(a.astype(np.float64))
                       @ b.astype(np.float64))
            assert np.abs(np.asarray(r.solution) - via_inv).max() < 1e-2
        assert inv_res.workload == "invert"
        # per-workload traffic accounting (stats rollup + lanes)
        assert stats["workloads"]["solve"]["requests"] == 5
        assert stats["workloads"]["invert"]["requests"] == 1
        assert any(k.startswith("solve:") for k in stats["engines"])
        assert stats["engines"]["solve:64:k4"]["engine"] == "solve_aug"

    def test_sync_sugar_and_singular(self):
        from tpu_jordan.driver import SingularMatrixError
        from tpu_jordan.serve import JordanService

        with JordanService(batch_cap=2, max_wait_ms=1.0) as svc:
            a = _rand((24, 24), seed=90)
            b = _rand((24, 2), seed=91)
            r = svc.solve_system(a, b, timeout=120)
            assert r.workload == "solve" and r.rel_residual < 1e-4
            with pytest.raises(SingularMatrixError):
                svc.solve_system(np.ones((24, 24), np.float32), b,
                                 timeout=120)

    def test_rhs_bucketing_slices_real_k(self):
        from tpu_jordan.serve import JordanService
        from tpu_jordan.serve.executors import rhs_bucket_for

        assert [rhs_bucket_for(k) for k in (1, 2, 3, 4, 5)] == \
            [1, 2, 4, 4, 8]
        with pytest.raises(ValueError, match="positive"):
            rhs_bucket_for(0)
        with JordanService(batch_cap=2, max_wait_ms=1.0) as svc:
            a = _rand((16, 16), seed=92)
            b = _rand((16, 3), seed=93)          # k=3 -> rhs bucket 4
            r = svc.submit(a, b).result(120)
            assert r.solution.shape == (16, 3)
            assert _rel_backward(a, r.solution, b) < 1e-4
            with pytest.raises(ValueError, match="k>=1"):
                svc.submit(a, np.zeros((16, 0), np.float32))

    @pytest.mark.slow  # tier-1 budget: the serve-solve round-trip sibling stays
    def test_journey_workload_stamped(self):
        from tpu_jordan.serve import JordanService

        with JordanService(batch_cap=2, max_wait_ms=1.0) as svc:
            a = _rand((16, 16), seed=94)
            svc.submit(a, _rand((16, 1), seed=95)).result(120)
            svc.submit(a).result(120)
            ctxs = svc.journey.contexts()
        workloads = {c.workload for c in ctxs}
        assert workloads == {"solve", "invert"}
        solve_ctx = next(c for c in ctxs if c.workload == "solve")
        assert solve_ctx.events()[0]["workload"] == "solve"


class TestCLIWorkloads:
    def _run(self, argv):
        from tpu_jordan.__main__ import main

        return main(argv)

    def test_solve_exit_0(self, capsys):
        assert self._run(["64", "16", "--workload", "solve", "--rhs",
                          "2", "--generator", "rand", "--quiet"]) == 0
        out = capsys.readouterr().out
        assert "residual" in out

    def test_spd_and_lstsq_exit_0(self):
        assert self._run(["48", "16", "--workload", "solve", "--rhs",
                          "1", "--assume", "spd", "--generator", "kms",
                          "--quiet"]) == 0
        assert self._run(["48", "16", "--workload", "lstsq", "--rhs",
                          "1", "--generator", "rand", "--quiet"]) == 0

    def test_complex64_solve_exit_0(self):
        assert self._run(["32", "8", "--workload", "solve", "--dtype",
                          "complex64", "--generator", "crand",
                          "--quiet"]) == 0

    def test_usage_errors_exit_1(self):
        # invert-engine vocabulary does not apply to solve workloads
        assert self._run(["32", "8", "--workload", "solve", "--engine",
                          "grouped"]) == 1
        # lstsq is generator-input only
        assert self._run(["32", "8", "--workload", "lstsq", "somefile",
                          ]) == 1
        # refine is an inverse concept
        assert self._run(["32", "8", "--workload", "solve", "--refine",
                          "1"]) == 1
        # demo modes stream invert requests
        assert self._run(["32", "8", "--workload", "solve",
                          "--serve-demo"]) == 1
        # workload flags on the default invert workload are never
        # silently dropped (review hardening)
        assert self._run(["32", "8", "--assume", "spd"]) == 1
        assert self._run(["32", "8", "--rhs", "5"]) == 1
        # crand with a real dtype would silently discard imag parts
        assert self._run(["32", "8", "--workload", "solve",
                          "--generator", "crand"]) == 1

    def test_crand_real_cast_is_typed(self):
        from tpu_jordan.ops import generate

        with pytest.raises(ValueError, match="imaginary"):
            generate("crand", (4, 4), jnp.float32)

    def test_singular_exit_2(self):
        # |0| is the 1x1 absdiff matrix: genuinely singular
        assert self._run(["1", "1", "--workload", "solve",
                          "--quiet"]) == 2


class TestWorkloadFlops:
    def test_conventions(self):
        from tpu_jordan.obs.hwcost import (baseline_invert_flops,
                                           baseline_workload_flops)
        from tpu_jordan.utils.profiling import workload_flops

        n, k = 1024, 8
        assert baseline_workload_flops(n, "invert") == \
            baseline_invert_flops(n)
        s = baseline_workload_flops(n, "solve", k=k)
        assert s == n ** 3 * (1 + k / n)
        assert s < baseline_invert_flops(n)
        assert baseline_workload_flops(n, "solve_spd", k=k) == s
        ls = baseline_workload_flops(n, "lstsq", k=k, rows=4 * n)
        assert ls > s            # gram + projection on top
        # the profiling shim delegates
        assert workload_flops(n, "solve", k=k) == s
        with pytest.raises(ValueError):
            baseline_workload_flops(n, "nope")


class TestCheckNumericsSolve:
    def test_solve_demo_report_validates_and_doctored_fails(self):
        """The check_numerics satellite: the solve-workload demo report
        passes; stripping its spikes turns the rung unexplained
        (exit-2 class)."""
        import copy
        import importlib.util
        import os

        from tpu_jordan.obs.numerics import numerics_demo

        spec = importlib.util.spec_from_file_location(
            "check_numerics", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "check_numerics.py"))
        cn = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cn)

        report = numerics_demo(16, 8, workload="solve")
        errs, unexplained = cn.check(report)
        assert not errs and not unexplained
        assert report["workload"] == "solve"
        assert report["recovery"] and report["recovery"][-1]["passed"]

        doctored = copy.deepcopy(report)
        doctored["blackbox"]["events"] = [
            e for e in doctored["blackbox"]["events"]
            if e.get("kind") != "numerics_spike"]
        _, unexplained2 = cn.check(doctored)
        assert unexplained2
